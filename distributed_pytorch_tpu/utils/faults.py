"""Deterministic fault injection: the chaos harness behind ISSUE 1.

The recovery paths this framework promises (training sentry rollback,
checkpoint quarantine, rendezvous backoff, elastic gang restart) are only
real if they are exercised by REAL injected faults — BAGUA's argument
(PAPERS.md): system relaxations earn their speed only when paired with
principled, *tested* failure handling.  This module is the single switch
panel: a ``FaultPlan`` names one fault class, the step/generation it
fires at, and a seed, and every layer of the stack (train step, trainer
host loop, checkpoint writer, rendezvous dial, launcher) consults it
through cheap hooks that are EXACT no-ops when no plan is installed.

Fault classes (``FaultPlan.kind``):

- ``nan_grad`` / ``inf_grad``: poison ONE gradient leaf (chosen by seed)
  at ``step`` — inside the jitted step, pre-sync, so the collective
  spreads it exactly like a real hardware NaN would;
- ``loss_spike``: multiply the loss by ``magnitude`` at ``step`` (the
  detector sees a spike; grads spike with it);
- ``crash``: hard-exit the process (``FAULT_EXIT_CODE``) after ``step``
  completes — the launcher classifies this exit as injected;
- ``ckpt_corrupt``: flip bits in / truncate the next checkpoint file
  written (also available directly as ``corrupt_file`` for tests);
- ``rendezvous``: refuse the first ``count`` rendezvous connection
  attempts (parallel/init.py retries with backoff + jitter);
- ``straggler``: sleep ``delay_s`` before each step in
  [``step``, ``step + count``) — a slow rank, not a dead one;
- ``replica_loss``: kill one serving-fleet replica (``rank`` is the
  REPLICA id here — the fleet is in-process, so there is no process
  rank to scope by) once its poll tick reaches ``step``; the router
  must detect the loss and rescue the replica's in-flight requests
  (fleet/router.py, ``maybe_kill_replica``);
- ``rpc_drop`` / ``rpc_torn`` / ``rpc_slow``: the socket-fleet transport
  faults (fleet/transport.py, ``maybe_rpc_fault``).  ``rank`` is again
  the REPLICA id; ``step`` counts the server's RPC calls; ``op``
  optionally pins the fault to one RPC op (e.g. ``"poll"``) so arming
  is immune to call-mix drift.  ``drop``
  kills the serving endpoint mid-call (a dead peer), ``torn`` truncates
  the reply frame at the boundary class named by ``mode`` (``header`` |
  ``payload`` | ``crc`` — a partial write cut by a crash), ``slow``
  sleeps ``delay_s`` before replying (a hung peer, the client's
  deadline/backoff path).  The client must detect each and quarantine
  the peer; the router rescues exactly as for ``replica_loss``.

Plans deliver either programmatically (``install``) or through the
``FAULT_PLAN`` env var as JSON — the env path crosses the launcher's
process boundary, so gang-level tests inject into workers they never
import.  ``gen`` gates a plan to one restart generation (the launcher's
``RESTART_ATTEMPT``): a crash plan fires in generation 0 and stays quiet
after the restart, so recovery can actually be observed.  ``rank``
(-1 = every process) scopes process-level faults to one gang member.

In-jit hooks (``tap_grads`` / ``tap_loss``) decide at TRACE time whether
to emit any fault logic: the clean path compiles byte-identical programs
with zero overhead.  Host hooks (``maybe_crash`` / ``maybe_delay``) are
one attribute test per dispatch when no plan is installed.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import asdict, dataclass

import jax
import jax.numpy as jnp
import numpy as np

# Exit code workers use for injected crashes; launch.py classifies it.
FAULT_EXIT_CODE = 77

ENV_VAR = "FAULT_PLAN"

RPC_KINDS = ("rpc_drop", "rpc_torn", "rpc_slow")
KINDS = ("nan_grad", "inf_grad", "loss_spike", "crash", "ckpt_corrupt",
         "rendezvous", "straggler", "replica_loss") + RPC_KINDS


@dataclass
class FaultPlan:
    """One scheduled fault.  ``step`` is the trainer's global step
    counter for step-scoped kinds; ``gen`` the restart generation the
    plan is live in (-1 = every generation); ``rank`` the process it
    fires on (-1 = all)."""

    kind: str
    step: int = 0
    seed: int = 0
    gen: int = 0
    rank: int = -1
    magnitude: float = 1e4   # loss_spike multiplier
    delay_s: float = 0.0     # straggler sleep per step
    # rendezvous refusals / straggler steps / grad-loss firings: the
    # default 1 models a TRANSIENT fault (fires once even if a sentry
    # rollback re-crosses the step); > 1 models a persistent one (the
    # escalation-ladder scenario)
    count: int = 1
    # ckpt_corrupt: 'bitflip' | 'truncate';
    # rpc_torn: 'header' | 'payload' | 'crc' (frame boundary class)
    mode: str = "bitflip"
    # rpc_* only: scope the plan to one RPC op ("poll", "submit", ...).
    # "" = any call.  An op-scoped plan fires on the first MATCHING
    # call at/past ``step``, so arming survives drift in the call mix
    # (hello probes, retries, routing) that shifts raw call indices.
    op: str = ""

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"one of {KINDS}")

    def to_env(self) -> str:
        return json.dumps(asdict(self))


_PLAN: FaultPlan | None = None
_PLAN_FROM_ENV = False


def install(plan: FaultPlan | None) -> None:
    """Install (or clear, with None) the process-wide fault plan.
    Programmatic installs shadow the env var."""
    global _PLAN, _PLAN_FROM_ENV
    _PLAN = plan
    _PLAN_FROM_ENV = False


def get_plan() -> FaultPlan | None:
    """The active plan: a programmatic install, else ``FAULT_PLAN`` from
    the environment (parsed once), else None."""
    global _PLAN, _PLAN_FROM_ENV
    if _PLAN is not None:
        return _PLAN
    if not _PLAN_FROM_ENV:
        raw = os.environ.get(ENV_VAR)
        if raw:
            _PLAN = FaultPlan(**json.loads(raw))
        _PLAN_FROM_ENV = True
    return _PLAN


def _gen_live(plan: FaultPlan) -> bool:
    if plan.gen < 0:
        return True
    return int(os.environ.get("RESTART_ATTEMPT", "0")) == plan.gen


def _rank_live(plan: FaultPlan) -> bool:
    if plan.rank < 0:
        return True
    try:
        if jax.process_count() > 1:
            return jax.process_index() == plan.rank
    except RuntimeError:  # pragma: no cover - uninitialized backend
        pass
    # Gangs of SINGLE-process-jax members (the elastic CPU simulation:
    # every worker is jax process 0 of its own world): the gang rank is
    # the launcher's env contract, not the jax process index.  Outside
    # any gang RANK is unset and this degrades to the old `rank == 0`.
    return int(os.environ.get("RANK", "0")) == plan.rank


def armed(kind: str) -> FaultPlan | None:
    """The plan, iff it matches ``kind`` and this generation/process."""
    plan = get_plan()
    if (plan is not None and plan.kind == kind and _gen_live(plan)
            and _rank_live(plan)):
        return plan
    return None


# -- in-jit taps (trace-time no-ops on the clean path) -----------------------

_STEP_FAULTS_FIRED = 0


def step_plan() -> FaultPlan | None:
    """The armed plan, if it is one of the step-keyed in-jit kinds."""
    return armed("nan_grad") or armed("inf_grad") or armed("loss_spike")


def arm_window(step0: int, k: int = 1) -> float:
    """Host-side one-shot arming for the in-jit taps: 1.0 iff a
    grad/loss plan's step falls inside the dispatch window
    [step0, step0 + k) with firings left (``plan.count``, default 1);
    marks one firing consumed.  The host gate is what gives step-keyed
    faults ONCE semantics: a sentry rollback rewinds the step counter
    across the fault step, and without the gate the re-crossed step
    would re-inject forever — the default models a transient fault (the
    class rewind-and-skip recovers from); ``count > 1`` models a
    persistent one (the escalation-ladder scenario)."""
    global _STEP_FAULTS_FIRED
    plan = step_plan()
    if plan is None or _STEP_FAULTS_FIRED >= plan.count:
        return 0.0
    if step0 <= plan.step < step0 + k:
        _STEP_FAULTS_FIRED += 1
        return 1.0
    return 0.0


def tap_grads(grads, step, fault_arm=0.0):
    """Poison one gradient leaf with NaN/Inf when ``step`` (a traced
    scalar) hits the plan's step AND the host armed this dispatch
    (``fault_arm`` from ``arm_window``).  Called inside the jitted train
    step, BEFORE the gradient sync, so the collective propagates the
    poison exactly as a real bad shard would.  No plan: returns
    ``grads`` untouched — nothing is traced into the program."""
    plan = armed("nan_grad") or armed("inf_grad")
    if plan is None:
        return grads
    bad = jnp.float32(jnp.nan if plan.kind == "nan_grad" else jnp.inf)
    leaves, treedef = jax.tree.flatten(grads)
    idx = plan.seed % len(leaves)
    hit = (step == plan.step) & (fault_arm > 0.0)
    leaves[idx] = jnp.where(hit, (leaves[idx] + bad).astype(
        leaves[idx].dtype), leaves[idx])
    return jax.tree.unflatten(treedef, leaves)


def tap_loss(loss, step, fault_arm=0.0):
    """Multiply the loss by ``magnitude`` at the plan's (host-armed)
    step (traced conditional; no plan: identity at trace time)."""
    plan = armed("loss_spike")
    if plan is None:
        return loss
    return jnp.where((step == plan.step) & (fault_arm > 0.0),
                     loss * jnp.asarray(plan.magnitude, loss.dtype), loss)


# -- host hooks --------------------------------------------------------------

def maybe_crash(step: int, window: int = 1) -> None:
    """Hard-exit (no teardown, no final checkpoint — a real crash) once
    the trainer's counter reaches/passes the plan's step.  ``step`` is
    the POST-dispatch counter and ``window`` the steps that dispatch
    executed: a K-step scan calls this once with the counter advanced by
    K, so the trigger is the (step - window, step] interval — a plan
    step inside the scan still fires at the dispatch boundary (the
    finest granularity a real crash could be observed at anyway).  The
    distinctive exit code lets the launcher classify the death as
    injected."""
    plan = armed("crash")
    if plan is not None and step - window < plan.step <= step:
        print(f"[faults] injected crash at step {plan.step} "
              f"(dispatch boundary {step})", flush=True)
        os._exit(FAULT_EXIT_CODE)


def maybe_delay(step: int, window: int = 1) -> None:
    """Straggler: sleep ``delay_s`` before any dispatch whose window
    [step, step + window) intersects the plan's [step, step + count)."""
    plan = armed("straggler")
    if plan is not None and (plan.step < step + window
                             and step < plan.step + plan.count):
        time.sleep(plan.delay_s)


def maybe_kill_replica(replica: int, tick: int) -> bool:
    """``replica_loss``: True exactly ``count`` times once the fleet's
    poll tick reaches the plan's ``step``, for the planned replica.
    ``rank`` is interpreted as the REPLICA id (-1 = any replica) — the
    serving fleet runs in ONE process, so ``_rank_live``'s process-rank
    gate does not apply; generation gating works as for every other
    kind.  The replica marks itself dead (its KV pool is lost, as a real
    process death would lose it) and the router rescues its in-flight
    requests (fleet/replica.py / fleet/router.py)."""
    plan = get_plan()
    if (plan is None or plan.kind != "replica_loss"
            or not _gen_live(plan)):
        return False
    if 0 <= plan.rank != replica:
        return False
    if tick < plan.step or plan.count <= 0:
        return False
    plan.count -= 1
    return True


def maybe_rpc_fault(replica: int, call: int,
                    op: str | None = None) -> FaultPlan | None:
    """``rpc_drop``/``rpc_torn``/``rpc_slow``: the socket-transport
    chaos hook (fleet/transport.py RpcServer consults it once per
    served call).  Returns the armed plan exactly ``count`` times once
    the server's call counter reaches the plan's ``step``, for the
    planned replica — ``rank`` is the REPLICA id (-1 = any), exactly
    as ``maybe_kill_replica`` reads it; the env path (``FAULT_PLAN``)
    crosses the daemon's process boundary the same way it crosses the
    launcher's.  A plan with ``op`` set fires only on calls of that op
    (still at/past ``step`` on the server's global counter) — index-
    only plans are brittle to call-mix drift (hello probes, retries,
    routing) silently disarming the chaos.  The caller acts on
    ``plan.kind``/``mode``/``delay_s``; this hook only decides WHETHER
    this call is the planned one."""
    plan = get_plan()
    if (plan is None or plan.kind not in RPC_KINDS
            or not _gen_live(plan)):
        return None
    if 0 <= plan.rank != replica:
        return None
    if plan.op and plan.op != op:
        return None
    if call < plan.step or plan.count <= 0:
        return None
    plan.count -= 1
    return plan


_RDZV_FAILED = 0


def maybe_refuse_rendezvous() -> None:
    """Raise ConnectionRefusedError for the first ``count`` attempts —
    the flapping-coordinator simulation parallel/init.py retries
    through."""
    global _RDZV_FAILED
    plan = armed("rendezvous")
    if plan is not None and _RDZV_FAILED < plan.count:
        _RDZV_FAILED += 1
        raise ConnectionRefusedError(
            f"[faults] injected rendezvous refusal "
            f"{_RDZV_FAILED}/{plan.count}")


def reset() -> None:
    """Clear all fault state (tests)."""
    global _RDZV_FAILED, _STEP_FAULTS_FIRED
    _RDZV_FAILED = 0
    _STEP_FAULTS_FIRED = 0
    install(None)


# -- checkpoint corruption ---------------------------------------------------

def corrupt_file(path: str, mode: str = "bitflip", seed: int = 0,
                 nbytes: int = 8) -> None:
    """Corrupt ``path`` in place: flip ``nbytes`` pseudo-random bytes
    (``bitflip``) or cut the file to half length (``truncate``) —
    deterministic given ``seed``.  The checkpoint layer must detect
    either (checksums / unreadable archive) and fall back a generation."""
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))
        return
    if mode != "bitflip":
        raise ValueError(f"unknown corruption mode {mode!r}")
    rng = np.random.default_rng(seed)
    # skip the first 512 bytes: flipping zip central-directory headers
    # tests unreadability, flipping payload bytes tests checksums — the
    # tail region exercises the checksum path more reliably
    lo = min(512, size - 1)
    offs = rng.integers(lo, size, nbytes)
    with open(path, "r+b") as f:
        for off in offs:
            f.seek(int(off))
            b = f.read(1)
            f.seek(int(off))
            f.write(bytes([b[0] ^ 0xFF]))


def maybe_corrupt_checkpoint(path: str) -> None:
    """Post-write hook: corrupt the just-published checkpoint file when a
    ``ckpt_corrupt`` plan is armed (fires ``count`` times)."""
    plan = armed("ckpt_corrupt")
    if plan is None or plan.count <= 0:
        return
    plan.count -= 1
    corrupt_file(path, mode=plan.mode, seed=plan.seed)
    print(f"[faults] corrupted checkpoint {path} ({plan.mode})",
          flush=True)
