"""Training-consistency checkers: the race/desync detection the reference lacks.

The reference has no sanitizers at all (SURVEY.md section 5): its collectives
are synchronous so ordering races are avoided by construction, but nothing
ever *verifies* the data-parallel invariants — and its manual variants do
silently violate one (per-rank BatchNorm stats drift, SURVEY.md 2.3).  On TPU
the failure modes shift (non-deterministic reduction orders, desynced
replicated state after a bad host-side update, NaN-poisoned grads); this
module makes them checkable:

- ``replica_desync(tree)``: bitwise-compare every device copy of replicated
  arrays — the DP invariant torch DDP enforces by broadcast; a mismatch means
  a desync bug (or a non-replicated sharding sneaking into training state);
- ``check_determinism(fn, *args)``: run a compiled step twice from identical
  inputs and compare results bitwise — catches nondeterministic kernels or
  host-side state leaking into a supposedly pure step;
- ``assert_finite(tree)``: NaN/Inf scan over a pytree (grad/param health).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import numpy as np

PyTree = Any


class ConsistencyError(AssertionError):
    """A data-parallel training invariant was violated."""


def _leaf_paths(tree: PyTree):
    # tree_util spelling: present on every supported runtime (the
    # jax.tree.flatten_with_path alias arrived later than 0.4.x)
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in leaves:
        yield jax.tree_util.keystr(path), leaf


def replica_desync(tree: PyTree, *, atol: float = 0.0) -> list[str]:
    """Paths of replicated leaves whose per-device copies disagree.

    Replicated training state (params, optimizer state) must be identical on
    every device — the invariant the reference maintains by same-seed
    construction plus grad sync (SURVEY.md 2.3) and torch DDP by broadcast.
    Leaves that are genuinely sharded (no device holds the full value) are
    skipped; only the replicated ones are comparable.
    """
    bad = []
    for path, leaf in _leaf_paths(tree):
        if not isinstance(leaf, jax.Array) or not hasattr(leaf, "sharding"):
            continue
        shards = leaf.addressable_shards
        if len(shards) < 2:
            continue
        if shards[0].data.shape != leaf.shape:
            continue  # sharded, not replicated: nothing to cross-check
        ref = np.asarray(shards[0].data)
        for s in shards[1:]:
            other = np.asarray(s.data)
            if atol == 0.0:
                ok = np.array_equal(ref, other, equal_nan=True)
            else:
                ok = np.allclose(ref, other, atol=atol, rtol=0.0,
                                 equal_nan=True)
            if not ok:
                bad.append(path)
                break
    return bad


def assert_replicas_in_sync(tree: PyTree, *, atol: float = 0.0,
                            what: str = "training state") -> None:
    bad = replica_desync(tree, atol=atol)
    if bad:
        raise ConsistencyError(
            f"{what} desynced across replicas at {len(bad)} leaves: "
            f"{bad[:5]}{'...' if len(bad) > 5 else ''}")


def check_determinism(fn: Callable[..., PyTree], *args,
                      runs: int = 2) -> None:
    """Run ``fn(*args)`` ``runs`` times and require bitwise-identical outputs.

    ``fn`` must be pure (a compiled step re-invoked on the SAME inputs —
    donation must be off, or pass fresh copies).  Catches nondeterministic
    reductions and host-side state leaking into the step.
    """
    outs = [jax.tree.map(np.asarray, fn(*args)) for _ in range(runs)]
    ref = outs[0]
    for i, out in enumerate(outs[1:], start=2):
        mism = []
        for (path, a), (_, b) in zip(_leaf_paths(ref), _leaf_paths(out)):
            if not np.array_equal(np.asarray(a), np.asarray(b),
                                  equal_nan=True):
                mism.append(path)
        if mism:
            raise ConsistencyError(
                f"run {i} differs from run 1 at {len(mism)} leaves: "
                f"{mism[:5]}{'...' if len(mism) > 5 else ''}")


def assert_finite(tree: PyTree, *, what: str = "pytree") -> None:
    """Raise if any leaf contains NaN/Inf (grad/param health check)."""
    bad = []
    for path, leaf in _leaf_paths(tree):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating) and not np.isfinite(
                arr).all():
            bad.append(path)
    if bad:
        raise ConsistencyError(
            f"{what} has non-finite values at {len(bad)} leaves: "
            f"{bad[:5]}{'...' if len(bad) > 5 else ''}")
