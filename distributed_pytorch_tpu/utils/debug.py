"""Training-consistency checkers: the race/desync detection the reference lacks.

The reference has no sanitizers at all (SURVEY.md section 5): its collectives
are synchronous so ordering races are avoided by construction, but nothing
ever *verifies* the data-parallel invariants — and its manual variants do
silently violate one (per-rank BatchNorm stats drift, SURVEY.md 2.3).  On TPU
the failure modes shift (non-deterministic reduction orders, desynced
replicated state after a bad host-side update, NaN-poisoned grads); this
module makes them checkable:

- ``replica_desync(tree)``: bitwise-compare every device copy of replicated
  arrays — the DP invariant torch DDP enforces by broadcast; a mismatch means
  a desync bug (or a non-replicated sharding sneaking into training state);
- ``check_determinism(fn, *args)``: run a compiled step twice from identical
  inputs and compare results bitwise — catches nondeterministic kernels or
  host-side state leaking into a supposedly pure step;
- ``assert_finite(tree)``: NaN/Inf scan over a pytree (grad/param health);
- the SCHEDULE INSPECTOR (round 8): ``op_schedule`` linearizes a compiled
  step's jaxpr into equation order — the order XLA receives the program,
  which the backward-overlap machinery (parallel/strategies.OverlapSync)
  manipulates — and ``collective_stats`` / ``assert_overlap_schedule`` /
  ``assert_post_backward_schedule`` prove whether gradient-sync
  collectives are interleaved between backward matmuls (overlap=True) or
  clustered after the backward drains (the historical post-backward
  shape).  ``hlo_collective_counts`` counts collectives in lowered
  (Stable)HLO text for the bench tables.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import numpy as np

PyTree = Any

# Compute ops a training step's forward/backward is made of (VGG steps are
# convolution-dominated, LM steps dot_general-dominated).
COMPUTE_PRIMS = frozenset({"dot_general", "conv_general_dilated"})
# Cross-device collectives (pmean lowers to psum+div, reduce-scatter to
# psum_scatter, so these cover every strategy's wire ops).
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "psum_scatter", "reduce_scatter",
})


def _eqn_axes(eqn) -> tuple:
    """The mesh axis names a collective equation runs over (normalized to
    a flat tuple; empty for non-collectives)."""
    axes = eqn.params.get("axes", None)
    if axes is None:
        axes = eqn.params.get("axis_name", None)
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    flat: list = []
    for a in axes:
        if isinstance(a, (tuple, list)):
            flat.extend(a)
        else:
            flat.append(a)
    return tuple(flat)


def _eqn_bytes(eqn) -> int:
    """Total operand payload of an equation (per device, per execution of
    its enclosing jaxpr) — the collective's wire cost proxy."""
    total = 0
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if aval is not None and hasattr(aval, "shape"):
            total += int(np.prod(aval.shape, dtype=np.int64) or 1) * \
                jax.dtypes.canonicalize_dtype(aval.dtype).itemsize
    return total


def _sub_jaxprs(eqn):
    """Nested jaxprs of call-like equations (pjit/scan/while/cond/
    shard_map/remat/custom_* ...), in parameter order."""
    for val in eqn.params.values():
        vals = val if isinstance(val, (list, tuple)) else [val]
        for s in vals:
            inner = getattr(s, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                yield inner
            elif hasattr(s, "eqns"):
                yield s


def jaxpr_schedule(jaxpr) -> list[dict]:
    """Flatten a (closed) jaxpr into equation order, recursing into nested
    jaxprs in place, and record every compute/collective op as
    ``{"kind": "compute"|"collective", "prim": name, "axes": tuple,
    "bytes": int, "trips": int}``.  Equation order is the order
    autodiff/transposition emitted the program and the order XLA receives
    it — the thing the overlap sync points exist to restructure.

    A scan body appears ONCE in the schedule (its per-iteration sequence
    is the repeating unit), but ``trips`` carries the product of the
    enclosing scan lengths, so per-execution accounting (the ring
    strategies' 2(n-1) ppermute hops live in scans) sums ``bytes *
    trips`` — see ``collective_stats``'s ``bytes_executed``.  ``while``
    bodies have no static trip count and keep the enclosing multiplier
    (an undercount; none of the train steps use while-loop collectives).
    """
    sched: list[dict] = []

    def walk(j, trips: int):
        for eqn in j.eqns:
            name = eqn.primitive.name
            if name in COMPUTE_PRIMS:
                sched.append({"kind": "compute", "prim": name,
                              "axes": (), "bytes": _eqn_bytes(eqn),
                              "trips": trips})
            elif name in COLLECTIVE_PRIMS:
                sched.append({"kind": "collective", "prim": name,
                              "axes": _eqn_axes(eqn),
                              "bytes": _eqn_bytes(eqn), "trips": trips})
            inner = trips
            if name == "scan":
                inner = trips * int(eqn.params.get("length", 1))
            for sub in _sub_jaxprs(eqn):
                walk(sub, inner)

    walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr, 1)
    return sched


def op_schedule(fn: Callable, *args, **kwargs) -> list[dict]:
    """``jaxpr_schedule`` of ``fn(*args, **kwargs)`` (fn may be jitted or
    shard_mapped; nothing is executed — args can be ShapeDtypeStructs)."""
    return jaxpr_schedule(jax.make_jaxpr(fn)(*args, **kwargs))


def collective_stats(sched: list[dict], axes=None,
                     min_bytes: int = 0) -> dict:
    """Interleaving statistics for the collectives in a schedule.

    ``axes``: restrict to collectives touching ANY of these mesh axes
    (e.g. ("data",) for the data-parallel gradient sync; None = all).
    ``min_bytes``: drop collectives below this operand payload — the
    LM steps psum scalar loss/token-count values over the batch axes
    mid-graph, and a gradient-sync interleaving pin must not count a
    4-byte loss reduction as overlapped sync traffic.

    Returns counts over the STATIC schedule: ``total`` collectives,
    ``interleaved`` (compute BOTH before and after — emitted strictly
    between matmuls), ``tail`` (no compute after — the post-backward
    cluster), ``bytes`` (summed operand payload, each scan body once) and
    ``compute`` (compute-op count); plus the PER-EXECUTION accounting
    ``executions`` / ``bytes_executed`` (scan-trip-weighted — the honest
    wire totals when collectives ride a scan, e.g. the int8 ring's
    ppermute hops)."""
    if axes is not None:
        axes = set(axes)
    compute_idx = [i for i, r in enumerate(sched) if r["kind"] == "compute"]
    first_c = compute_idx[0] if compute_idx else None
    last_c = compute_idx[-1] if compute_idx else None
    total = interleaved = tail = executions = 0
    nbytes = nbytes_exec = 0
    for i, r in enumerate(sched):
        if r["kind"] != "collective":
            continue
        if axes is not None and not (axes & set(r["axes"])):
            continue
        if r["bytes"] < min_bytes:
            continue
        total += 1
        nbytes += r["bytes"]
        trips = r.get("trips", 1)
        executions += trips
        nbytes_exec += r["bytes"] * trips
        if last_c is None or i > last_c:
            tail += 1
        elif first_c is not None and i > first_c:
            interleaved += 1
    return {"total": total, "interleaved": interleaved, "tail": tail,
            "bytes": nbytes, "compute": len(compute_idx),
            "executions": executions, "bytes_executed": nbytes_exec}


def per_axis_collective_stats(sched: list[dict],
                              min_bytes: int = 0) -> dict[str, dict]:
    """``collective_stats`` split BY MESH AXIS: one stats dict per axis
    name appearing in the schedule ({'dcn': ..., 'ici': ...} for the
    factored-mesh strategies), so wire accounting can attribute traffic
    to the link that carries it — cross-slice DCN bytes separately from
    within-slice ICI bytes (scripts/bench_strategies.py's per-axis
    columns; the measurement behind two_level_psum's |grads|/ici claim).
    A collective running over several axes at once (a flat psum over
    ('data', 'expert')) counts toward EACH of them — per-axis rows are
    attribution, not a partition, and need not sum to the total."""
    axes = sorted({a for r in sched if r["kind"] == "collective"
                   for a in r["axes"]})
    return {a: collective_stats(sched, axes=(a,), min_bytes=min_bytes)
            for a in axes}


def per_hop_collective_stats(sched: list[dict],
                             min_bytes: int = 0) -> dict[str, dict]:
    """``collective_stats`` split BY HOP — one row per (mesh-axes,
    primitive) pair, keyed ``"axis:prim"`` in the routing grammar's
    spirit (``parallel/routing``): a 3-hop routed sync traces as e.g.
    ``{"ici:psum_scatter": ..., "dcn:ppermute": ..., "wan:ppermute":
    ..., "ici:all_gather": ...}``, so each hop of a ``HopPlan`` is
    attributable separately even when two hops share a mesh axis (the
    reduce-scatter and the all-gather of the same bracket).  A
    collective spanning several axes at once keys them joined with
    ``"+"`` (``"data+expert:psum"``) — the same joint-axis spelling the
    route grammar uses for flat plans.  Stats fields match
    ``collective_stats`` (round 20, the per-hop side of
    ``plan_bytes_vs_schedule``)."""
    compute_idx = [i for i, r in enumerate(sched) if r["kind"] == "compute"]
    first_c = compute_idx[0] if compute_idx else None
    last_c = compute_idx[-1] if compute_idx else None
    out: dict[str, dict] = {}
    for i, r in enumerate(sched):
        if r["kind"] != "collective" or r["bytes"] < min_bytes:
            continue
        key = "+".join(sorted(r["axes"])) + ":" + r["prim"]
        row = out.setdefault(key, {
            "total": 0, "interleaved": 0, "tail": 0, "bytes": 0,
            "compute": len(compute_idx), "executions": 0,
            "bytes_executed": 0})
        trips = r.get("trips", 1)
        row["total"] += 1
        row["bytes"] += r["bytes"]
        row["executions"] += trips
        row["bytes_executed"] += r["bytes"] * trips
        if last_c is None or i > last_c:
            row["tail"] += 1
        elif first_c is not None and i > first_c:
            row["interleaved"] += 1
    return out


def amortized_axis_bytes(entries, steps: int,
                         min_bytes: int = 0, *,
                         by_hop: bool = False) -> dict[str, float]:
    """Per-axis wire bytes PER STEP of a multi-program step family:
    ``entries`` is an iterable of ``(sched, multiplicity)`` pairs — each
    jaxpr schedule weighted by how many times it runs over a ``steps``-
    step window — and the result sums each axis's scan-trip-weighted
    ``bytes_executed`` across them, divided by ``steps``.

    This is the round-18 measurement behind the local-SGD claim: a
    ``sync_every=H`` trainer runs the LOCAL schedule H times and the
    boundary-EXCHANGE schedule once per window, so
    ``amortized_axis_bytes([(local, H), (exchange, 1)], H)`` gives the
    honest dcn-axis bytes/step to compare against the per-step path's
    ``amortized_axis_bytes([(step, 1)], 1)`` — the ~1/H scaling pin
    (tests/test_localsgd.py, the __graft_entry__ dryrun leg).

    ``by_hop=True`` (round 20) keys the result per HOP instead of per
    axis (``per_hop_collective_stats``'s ``"axis:prim"`` keys) — the
    3-axis-mesh accounting that keeps routed ``HopPlan`` predictions
    checkable hop-by-hop against emitted programs."""
    split = per_hop_collective_stats if by_hop else per_axis_collective_stats
    totals: dict[str, float] = {}
    for sched, mult in entries:
        for axis, stats in split(sched, min_bytes=min_bytes).items():
            totals[axis] = (totals.get(axis, 0.0)
                            + float(stats["bytes_executed"]) * mult)
    return {a: b / float(steps) for a, b in totals.items()}


def assert_overlap_schedule(sched: list[dict], axes=("data",),
                            min_interleaved: int = 2,
                            min_bytes: int = 0) -> dict:
    """Assert the overlap property: at least ``min_interleaved``
    ``axes``-collectives sit STRICTLY BETWEEN compute ops (backward
    matmuls run after them — the latency-hiding scheduler has something
    to overlap).  ``min_bytes`` excludes scalar loss reductions (see
    collective_stats).  Returns the stats for reporting."""
    stats = collective_stats(sched, axes=axes, min_bytes=min_bytes)
    if stats["interleaved"] < min_interleaved:
        raise ConsistencyError(
            f"expected >= {min_interleaved} {tuple(axes)}-collectives "
            f"interleaved between compute ops, found "
            f"{stats['interleaved']} (of {stats['total']}; {stats}) — "
            f"the collectives are not overlapped with backward compute")
    return stats


def assert_post_backward_schedule(sched: list[dict],
                                  axes=("data",),
                                  min_bytes: int = 0) -> dict:
    """Assert the historical post-backward shape: every ``axes``-collective
    comes AFTER the last compute op (all-at-the-end; nothing for the
    scheduler to overlap).  ``min_bytes`` excludes the scalar loss
    reductions that legitimately sit mid-graph (see collective_stats)."""
    stats = collective_stats(sched, axes=axes, min_bytes=min_bytes)
    if stats["interleaved"] != 0 or stats["tail"] != stats["total"]:
        raise ConsistencyError(
            f"expected all {tuple(axes)}-collectives after the final "
            f"compute op, got {stats}")
    return stats


# Lowered-HLO collective opcodes (canonical name -> regex matching the op
# DEFINITION site — opcode immediately followed by its operand list — in
# both classic HLO (`all-reduce(...)`) and StableHLO
# (`"stablehlo.all_reduce"(...)` / `stablehlo.all_reduce(...)`) text;
# value references like `%all-reduce.1` never match).
_HLO_COLLECTIVES = {
    "all-reduce": r"all[-_]reduce\"?\(",
    "collective-permute": r"collective[-_]permute\"?\(",
    "all-gather": r"all[-_]gather\"?\(",
    "reduce-scatter": r"reduce[-_]scatter\"?\(",
    "all-to-all": r"all[-_]to[-_]all\"?\(",
}


def hlo_collective_counts(hlo_text: str) -> dict[str, int]:
    """Count collective ops in lowered (Stable)HLO text
    (``jit(f).lower(...).as_text()``), keyed by canonical opcode plus a
    ``"total"`` — the bench tables' HLO collective-count column
    (scripts/bench_strategies.py)."""
    import re

    counts = {canon: len(re.findall(pat, hlo_text))
              for canon, pat in _HLO_COLLECTIVES.items()}
    counts = {k: v for k, v in counts.items() if v}
    counts["total"] = sum(counts.values())
    return counts


class ConsistencyError(AssertionError):
    """A data-parallel training invariant was violated."""


# Route-grammar hop operations (parallel/routing.Hop.describe()'s part
# after the ":", bracket suffix stripped) -> the jaxpr primitives that
# hop lowers to.  "ag" lists psum too: the legacy-runtime gather
# fallback emits a masked psum instead of all_gather (strategies.py).
_HOP_OP_PRIMS = {
    "rs": ("psum_scatter", "reduce_scatter"),
    "slice": (),            # local dynamic_slice — no collective
    "ag": ("all_gather", "psum"),
    "psum": ("psum", "psum2"),
    "ring": ("ppermute",),
    # round 21: the expert dispatch/combine exchange ('expert:a2a@bits'
    # hops) lowers to all_to_all at every wire width — the quantized
    # payload+scale concat rides the same primitive
    "a2a": ("all_to_all",),
}


def plan_bytes_vs_schedule(plan, sched: list[dict], *,
                           min_bytes: int = 1024,
                           by_hop: bool = False) -> dict[str, dict]:
    """Predicted-vs-measured wire accounting for an autotuner SyncPlan
    (parallel/autotune.py) against a traced step's schedule: for each
    axis the plan predicts traffic on, pair its ``predicted_bytes``
    (operand-payload, scan-trip-weighted — the same accounting as
    ``collective_stats``'s ``bytes_executed``) with the measured
    ``bytes_executed`` of that axis's collectives (``min_bytes`` filters
    the scalar loss/health reductions, as everywhere).  Returns
    ``{axis: {"predicted": int, "measured": int, "ratio": float}}`` —
    the cost model's ground-truth check (round 11).

    ``by_hop=True`` (round 20) compares the plan's ``per_hop`` rows
    instead (route-model plans only — ``plan.per_hop`` must be
    populated): each hop label (``"dcn:ring[int4+ef]"``) is matched to
    the measured ``per_hop_collective_stats`` rows for its axis and the
    primitives that hop kind lowers to, so a 3-axis routed sync is
    checkable hop-by-hop, not just axis-by-axis.  Hops predicting no
    bytes (a ``slice`` reduce-scatter, a degraded size-1 tier) are
    skipped, same as zero-byte axes."""
    if by_hop:
        measured_hops = per_hop_collective_stats(sched, min_bytes=min_bytes)
        out: dict[str, dict] = {}
        for hp in getattr(plan, "per_hop", ()) or ():
            if hp.predicted_bytes <= 0:
                continue
            axis, _, op = hp.axis.partition(":")
            # strip both tag syntaxes: 'ring[int4+ef]' and 'a2a@int8'
            prims = _HOP_OP_PRIMS.get(
                op.split("[", 1)[0].split("@", 1)[0], ())
            measured = sum(
                measured_hops.get(f"{axis}:{p}", {}).get("bytes_executed", 0)
                for p in prims)
            out[hp.axis] = {"predicted": int(hp.predicted_bytes),
                            "measured": int(measured),
                            "ratio": measured / hp.predicted_bytes}
        return out
    per_axis = per_axis_collective_stats(sched, min_bytes=min_bytes)
    out = {}
    for ap in plan.per_axis:
        if ap.predicted_bytes <= 0:
            continue
        measured = per_axis.get(ap.axis, {}).get("bytes_executed", 0)
        out[ap.axis] = {"predicted": int(ap.predicted_bytes),
                        "measured": int(measured),
                        "ratio": measured / ap.predicted_bytes}
    return out


def assert_plan_bytes_match(plan, sched: list[dict], *, rtol: float = 0.5,
                            min_bytes: int = 1024) -> dict[str, dict]:
    """Assert every axis the plan predicts traffic on measures within
    ``rtol`` relative tolerance of the prediction — the autotuner's
    cost model is only trustworthy while its byte predictions track the
    emitted program (the measured side may run slightly over: the
    schedule also carries non-sync collectives like BN-buffer
    broadcasts above ``min_bytes``).  Returns the comparison rows."""
    rows = plan_bytes_vs_schedule(plan, sched, min_bytes=min_bytes)
    if not rows:
        raise ConsistencyError(
            f"plan {plan.strategy!r} predicts no per-axis traffic to "
            f"check (per_axis={plan.per_axis!r})")
    bad = {a: r for a, r in rows.items()
           if abs(r["ratio"] - 1.0) > rtol}
    if bad:
        raise ConsistencyError(
            f"predicted per-axis bytes diverge from the measured "
            f"schedule beyond rtol={rtol}: {bad} (all rows: {rows})")
    return rows


def pipeline_schedule_stats(clocks: list[dict], *, n_stages: int) -> dict:
    """Summary statistics of a 1F1B timetable (the ``pp_clocks`` data a
    ``make_lm_1f1b_train_step`` step carries): measured ``bubble_fraction``
    (idle (stage, clock) slots / all slots — the thing the analytic
    (pp-1)/(pp-1+M) bound bounds), total ``clocks``, per-kind unit counts,
    and ``steady_alternations`` — the number of F->B / B->F kind switches
    summed over stages, the 1F1B steady state's signature (a GPipe-shaped
    all-F-then-all-B schedule has n_stages-ish switches; 1F1B has ~2 per
    in-flight microbatch per stage)."""
    busy = sum(len(c) for c in clocks)
    slots = n_stages * len(clocks)
    f_units = sum(1 for c in clocks for op in c.values() if op[0] == "F")
    b_units = sum(1 for c in clocks for op in c.values() if op[0] == "B")
    alternations = 0
    for s in range(n_stages):
        kinds = [c[s][0] for c in clocks if s in c]
        alternations += sum(1 for a, b in zip(kinds, kinds[1:]) if a != b)
    return {"bubble_fraction": 1.0 - busy / slots if slots else 0.0,
            "clocks": len(clocks), "f_units": f_units, "b_units": b_units,
            "steady_alternations": alternations}


def assert_pipeline_schedule(clocks_or_step, *, n_stages: int,
                             n_micro: int, interleave: int = 1,
                             max_bubble: float | None = None) -> dict:
    """Assert a 1F1B timetable is well-formed and meets its bubble bound —
    the pipeline-parallel sibling of ``assert_overlap_schedule`` (round
    10): the 1F1B step EMITS its program in timetable order, so checking
    the timetable checks the emitted schedule the same way the jaxpr
    inspector checks emitted collective placement.

    Accepts the timetable (list of ``{stage: (kind, chunk, micro)}``
    clocks) or a step function carrying it (``step.pp_clocks``).  Checks:

    - completeness: every (chunk, microbatch) runs F and B exactly once;
    - dependencies: chunk c's F after chunk c-1's F (same microbatch),
      chunk c's B after its own F and after chunk c+1's B — the dataflow
      the stage-boundary transfers implement;
    - grad-accumulation order: per chunk, backwards run in ascending
      microbatch order — the property that makes 1F1B's reordering a
      pure reassociation of the accumulated sum (lm.py's bitwise pin);
    - steady-state interleaving: with n_micro > n_stages there is at
      least one clock where EVERY stage is busy and both F and B units
      run somewhere (stage-f/stage-b work genuinely interleaved, not a
      GPipe all-F-then-all-B shape);
    - bubble: measured bubble fraction <= ``max_bubble`` (default: the
      analytic 1F1B fill/drain bound (pp-1)/(pp-1+M) with M =
      ``n_micro`` — which the generated timetable meets EXACTLY at
      interleave=1 and beats at interleave>1; the idealized v-fold
      bound (pp-1)/(pp-1+M*v) rides along as ``ideal_bound`` but is not
      enforced — the greedy schedule lands between the two).

    Returns ``pipeline_schedule_stats`` + ``analytic_bound`` /
    ``ideal_bound`` for the bench tables (bench.py
    ``lm_pp_bubble_fraction``)."""
    clocks = getattr(clocks_or_step, "pp_clocks", clocks_or_step)
    n_chunks = n_stages * interleave
    done_f: dict = {}
    done_b: dict = {}
    for t, clock in enumerate(clocks):
        for s, (kind, c, m) in clock.items():
            if c % n_stages != s:
                raise ConsistencyError(
                    f"clock {t}: chunk {c} ran on stage {s}, but the "
                    f"round-robin placement puts it on {c % n_stages}")
            key = (c, m)
            book = done_f if kind == "F" else done_b
            if key in book:
                raise ConsistencyError(
                    f"clock {t}: duplicate {kind} unit for chunk {c} "
                    f"microbatch {m} (first at clock {book[key]})")
            if kind == "F":
                if c > 0 and done_f.get((c - 1, m), t) >= t:
                    raise ConsistencyError(
                        f"clock {t}: F({c},{m}) before upstream "
                        f"F({c - 1},{m}) finished")
            else:
                if done_f.get((c, m), t) >= t:
                    raise ConsistencyError(
                        f"clock {t}: B({c},{m}) before its own F")
                if c < n_chunks - 1 and done_b.get((c + 1, m), t) >= t:
                    raise ConsistencyError(
                        f"clock {t}: B({c},{m}) before downstream "
                        f"B({c + 1},{m}) — its output cotangent does "
                        f"not exist yet")
            book[key] = t
    want = {(c, m) for c in range(n_chunks) for m in range(n_micro)}
    for name, book in (("forward", done_f), ("backward", done_b)):
        if set(book) != want:
            missing = sorted(want - set(book))[:4]
            raise ConsistencyError(
                f"incomplete schedule: {len(want) - len(book)} {name} "
                f"units missing (first: {missing})")
    for c in range(n_chunks):
        ms = sorted(range(n_micro), key=lambda m: done_b[(c, m)])
        if ms != sorted(ms):
            raise ConsistencyError(
                f"chunk {c}: backwards out of microbatch order {ms} — "
                f"the grad accumulation would reassociate vs pp_size=1")
    stats = pipeline_schedule_stats(clocks, n_stages=n_stages)
    if n_micro > n_stages and n_stages > 1:
        full = [t for t, c in enumerate(clocks)
                if len(c) == n_stages
                and {op[0] for op in c.values()} == {"F", "B"}]
        if not full:
            raise ConsistencyError(
                "no steady-state clock runs F and B units on a fully "
                "busy stage set — the schedule is not interleaved 1F1B "
                f"(stats: {stats})")
        stats["steady_clocks"] = len(full)
    # the ONE definition of the analytic bound (parallel/pipeline.py) —
    # enforced at interleave=1 terms, reported also in idealized v-fold
    # terms (lazy import: debug must stay importable standalone)
    from ..parallel.pipeline import analytic_bubble_bound
    bound = analytic_bubble_bound(n_stages, n_micro)
    stats["analytic_bound"] = bound
    stats["ideal_bound"] = analytic_bubble_bound(n_stages, n_micro,
                                                 interleave)
    limit = bound if max_bubble is None else max_bubble
    if n_stages > 1 and stats["bubble_fraction"] > limit + 1e-9:
        raise ConsistencyError(
            f"measured bubble fraction {stats['bubble_fraction']:.4f} "
            f"exceeds the bound {limit:.4f} "
            f"((pp-1)/(pp-1+M) = {bound:.4f}; stats: {stats})")
    return stats


def _leaf_paths(tree: PyTree):
    # tree_util spelling: present on every supported runtime (the
    # jax.tree.flatten_with_path alias arrived later than 0.4.x)
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in leaves:
        yield jax.tree_util.keystr(path), leaf


def replica_desync(tree: PyTree, *, atol: float = 0.0) -> list[str]:
    """Paths of replicated leaves whose per-device copies disagree.

    Replicated training state (params, optimizer state) must be identical on
    every device — the invariant the reference maintains by same-seed
    construction plus grad sync (SURVEY.md 2.3) and torch DDP by broadcast.
    Leaves that are genuinely sharded (no device holds the full value) are
    skipped; only the replicated ones are comparable.
    """
    bad = []
    for path, leaf in _leaf_paths(tree):
        if not isinstance(leaf, jax.Array) or not hasattr(leaf, "sharding"):
            continue
        shards = leaf.addressable_shards
        if len(shards) < 2:
            continue
        if shards[0].data.shape != leaf.shape:
            continue  # sharded, not replicated: nothing to cross-check
        ref = np.asarray(shards[0].data)
        for s in shards[1:]:
            other = np.asarray(s.data)
            if atol == 0.0:
                ok = np.array_equal(ref, other, equal_nan=True)
            else:
                ok = np.allclose(ref, other, atol=atol, rtol=0.0,
                                 equal_nan=True)
            if not ok:
                bad.append(path)
                break
    return bad


def assert_replicas_in_sync(tree: PyTree, *, atol: float = 0.0,
                            what: str = "training state") -> None:
    bad = replica_desync(tree, atol=atol)
    if bad:
        raise ConsistencyError(
            f"{what} desynced across replicas at {len(bad)} leaves: "
            f"{bad[:5]}{'...' if len(bad) > 5 else ''}")


def check_determinism(fn: Callable[..., PyTree], *args,
                      runs: int = 2) -> None:
    """Run ``fn(*args)`` ``runs`` times and require bitwise-identical outputs.

    ``fn`` must be pure (a compiled step re-invoked on the SAME inputs —
    donation must be off, or pass fresh copies).  Catches nondeterministic
    reductions and host-side state leaking into the step.
    """
    outs = [jax.tree.map(np.asarray, fn(*args)) for _ in range(runs)]
    ref = outs[0]
    for i, out in enumerate(outs[1:], start=2):
        mism = []
        for (path, a), (_, b) in zip(_leaf_paths(ref), _leaf_paths(out)):
            if not np.array_equal(np.asarray(a), np.asarray(b),
                                  equal_nan=True):
                mism.append(path)
        if mism:
            raise ConsistencyError(
                f"run {i} differs from run 1 at {len(mism)} leaves: "
                f"{mism[:5]}{'...' if len(mism) > 5 else ''}")


def assert_finite(tree: PyTree, *, what: str = "pytree") -> None:
    """Raise if any leaf contains NaN/Inf (grad/param health check)."""
    bad = []
    for path, leaf in _leaf_paths(tree):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating) and not np.isfinite(
                arr).all():
            bad.append(path)
    if bad:
        raise ConsistencyError(
            f"{what} has non-finite values at {len(bad)} leaves: "
            f"{bad[:5]}{'...' if len(bad) > 5 else ''}")
