"""Training metrics with the reference's exact window semantics.

The reference's only observability is two printed windows (reference:
main.py:28-48, identical in every variant — SURVEY.md section 5):

- running loss, averaged and reset every 20 iterations (main.py:40-42);
- per-iteration wall time, *excluding iteration 0* as compile/warm-up,
  averaged and reset every 40 iterations — the first window therefore
  divides by 39, later windows by 40 (main.py:43-48).

These meters reproduce that metric definition exactly so benchmark numbers
are comparable, while exposing the values programmatically instead of only
printing them.
"""

from __future__ import annotations

import math
from collections import deque
from dataclasses import dataclass, field

from . import telemetry

LOSS_WINDOW = 20
TIME_WINDOW = 40


@dataclass
class WindowRecord:
    first_iter: int  # 1-based, matching the reference's printout
    last_iter: int
    value: float


def _window_gauge(name: str, rec: WindowRecord) -> None:
    """Round 13: a completed reference-semantics window also lands as a
    gauge on the unified timeline when the process registry is active —
    the SAME value the meter prints, so the reference's loss/20 and
    time/40 windows become plottable next to the per-step scalars
    instead of print-only.  Free while telemetry is off."""
    tel = telemetry.active()
    if tel is not None:
        tel.gauge(name, rec.value, phase="train",
                  first_iter=rec.first_iter, last_iter=rec.last_iter)


@dataclass
class LossMeter:
    """Running loss averaged per 20-iteration window (main.py:40-42)."""

    window: int = LOSS_WINDOW
    running: float = 0.0
    records: list[WindowRecord] = field(default_factory=list)

    def update(self, batch_idx: int, loss: float) -> WindowRecord | None:
        self.running += loss
        if batch_idx % self.window == self.window - 1:
            rec = WindowRecord(batch_idx - self.window + 2, batch_idx + 1,
                               self.running / self.window)
            self.records.append(rec)
            self.running = 0.0
            _window_gauge("window_loss", rec)
            return rec
        return None


@dataclass
class IterTimeMeter:
    """Avg s/iter per 40-iteration window, iteration 0 excluded (main.py:43-48).

    The reference's quirk is preserved: iteration 0's time is never counted,
    and the first window is divided by 39 while all later ones divide by 40.
    """

    window: int = TIME_WINDOW
    total: float = 0.0
    records: list[WindowRecord] = field(default_factory=list)

    def update(self, batch_idx: int, seconds: float) -> WindowRecord | None:
        if batch_idx != 0:
            self.total += seconds
        if batch_idx % self.window == self.window - 1:
            divisor = self.window - 1 if batch_idx == self.window - 1 else self.window
            rec = WindowRecord(batch_idx - divisor + 2, batch_idx + 1,
                               self.total / divisor)
            self.records.append(rec)
            self.total = 0.0
            _window_gauge("window_iter_seconds", rec)
            return rec
        return None


class SpikeDetector:
    """Rolling median/MAD outlier detector — the training sentry's
    loss-spike (and step-time straggler) test (utils/sentry.py).

    A value spikes when it exceeds ``median + threshold * sigma`` of the
    trailing window, with sigma the MAD scaled to a normal-consistent
    estimate (1.4826 * MAD) floored by ``min_sigma`` — the floor keeps a
    converged, near-constant loss stream (MAD -> 0) from flagging
    ordinary noise.  Median/MAD rather than mean/std because the window
    must stay honest THROUGH a spike: one huge value barely moves the
    median, while it would drag a mean-based threshold up enough to wave
    the next spike through.  Non-finite values always spike.  Spiking
    values are NOT admitted to the window (a fault must not poison the
    baseline it is judged against); the first ``min_history`` values
    train the baseline and never spike.
    """

    def __init__(self, window: int = 32, threshold: float = 10.0,
                 min_history: int = 8, min_sigma: float = 1e-3):
        if window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        self.window = window
        self.threshold = threshold
        self.min_history = max(min_history, 2)
        self.min_sigma = min_sigma
        self._hist: deque[float] = deque(maxlen=window)

    def _median(self, values: list[float]) -> float:
        s = sorted(values)
        n = len(s)
        mid = n // 2
        return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])

    def bound(self) -> float:
        """Current spike threshold (+inf while the baseline trains)."""
        if len(self._hist) < self.min_history:
            return math.inf
        vals = list(self._hist)
        med = self._median(vals)
        mad = self._median([abs(v - med) for v in vals])
        sigma = max(1.4826 * mad, self.min_sigma)
        return med + self.threshold * sigma

    def update(self, value: float) -> bool:
        """Feed one value; True = spike (value withheld from window)."""
        if not math.isfinite(value):
            return True
        if value > self.bound():
            return True
        self._hist.append(value)
        return False
