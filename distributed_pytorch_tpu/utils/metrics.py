"""Training metrics with the reference's exact window semantics.

The reference's only observability is two printed windows (reference:
main.py:28-48, identical in every variant — SURVEY.md section 5):

- running loss, averaged and reset every 20 iterations (main.py:40-42);
- per-iteration wall time, *excluding iteration 0* as compile/warm-up,
  averaged and reset every 40 iterations — the first window therefore
  divides by 39, later windows by 40 (main.py:43-48).

These meters reproduce that metric definition exactly so benchmark numbers
are comparable, while exposing the values programmatically instead of only
printing them.
"""

from __future__ import annotations

from dataclasses import dataclass, field

LOSS_WINDOW = 20
TIME_WINDOW = 40


@dataclass
class WindowRecord:
    first_iter: int  # 1-based, matching the reference's printout
    last_iter: int
    value: float


@dataclass
class LossMeter:
    """Running loss averaged per 20-iteration window (main.py:40-42)."""

    window: int = LOSS_WINDOW
    running: float = 0.0
    records: list[WindowRecord] = field(default_factory=list)

    def update(self, batch_idx: int, loss: float) -> WindowRecord | None:
        self.running += loss
        if batch_idx % self.window == self.window - 1:
            rec = WindowRecord(batch_idx - self.window + 2, batch_idx + 1,
                               self.running / self.window)
            self.records.append(rec)
            self.running = 0.0
            return rec
        return None


@dataclass
class IterTimeMeter:
    """Avg s/iter per 40-iteration window, iteration 0 excluded (main.py:43-48).

    The reference's quirk is preserved: iteration 0's time is never counted,
    and the first window is divided by 39 while all later ones divide by 40.
    """

    window: int = TIME_WINDOW
    total: float = 0.0
    records: list[WindowRecord] = field(default_factory=list)

    def update(self, batch_idx: int, seconds: float) -> WindowRecord | None:
        if batch_idx != 0:
            self.total += seconds
        if batch_idx % self.window == self.window - 1:
            divisor = self.window - 1 if batch_idx == self.window - 1 else self.window
            rec = WindowRecord(batch_idx - divisor + 2, batch_idx + 1,
                               self.total / divisor)
            self.records.append(rec)
            self.total = 0.0
            return rec
        return None
