"""Structured logging: the observability layer the reference lacks.

The reference imports ``logging`` but never configures it and reports
everything via bare ``print`` (reference main.py:10, SURVEY.md section 5).
Here one ``setup_logging`` call configures rank-aware stdlib logging; the
training loop's printed windows (loss/20 iters, time/40 iters) route through
it so output is greppable and per-process attributable on multi-host runs.

The rank is resolved LAZILY, per record, by a ``logging.Filter`` (round
13): it used to be baked into the format string at the first
``setup_logging`` call, and the idempotent early-return then kept it
stale forever — a gang worker configured before ``jax.distributed``
init logged rank 0 for its whole life, and a rank respawned into a new
generation after an elastic resize kept its old number.  ``_rank()``
prefers the launcher env contract (``RANK`` — correct before jax init
and refreshed per generation, since elastic resizes respawn the
process) and falls back to ``jax.process_index()`` only when jax is
ALREADY imported (launcher-less multi-host runs); it never imports jax
itself — the launcher agent logs through this module and must stay
jax-free.
"""

from __future__ import annotations

import logging
import os
import sys


def current_rank() -> int:
    """Current process rank, resolved at call time (never cached) — the
    ONE launcher-rank precedence, shared with telemetry's CLI bootstrap
    (utils/telemetry.enable_from_cli): env ``RANK`` first, then
    ``jax.process_index()`` iff jax is already loaded, else 0."""
    env = os.environ.get("RANK")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass
    jax = sys.modules.get("jax")  # only consult jax if someone loaded it
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:
            pass
    return 0


_rank = current_rank  # backward-friendly local alias


class RankFilter(logging.Filter):
    """Stamps ``record.rank`` on every record at emit time, so the
    format string's ``%(rank)s`` always reflects the CURRENT rank."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.rank = _rank()
        return True


def setup_logging(level: str = "INFO") -> None:
    """Configure root logging with a rank-aware format (idempotent; the
    level still updates on repeat calls — only the handler install is
    once-only).  INFO/WARNING go to stdout; ERROR and above go to
    stderr — so a supervisor capturing stderr still sees failures
    (launch.py's "gang failed" line routed there as a bare print before
    round 13, and must keep doing so through the logger)."""
    root = logging.getLogger("distributed_pytorch_tpu")
    root.setLevel(level.upper())
    if root.handlers:  # already configured (rank stays fresh via the filter)
        return
    fmt = logging.Formatter(
        "%(asctime)s rank%(rank)s %(name)s %(levelname)s: %(message)s",
        datefmt="%H:%M:%S")
    out = logging.StreamHandler(sys.stdout)
    out.addFilter(RankFilter())
    out.addFilter(lambda record: record.levelno < logging.ERROR)
    out.setFormatter(fmt)
    err = logging.StreamHandler(sys.stderr)
    err.setLevel(logging.ERROR)
    err.addFilter(RankFilter())
    err.setFormatter(fmt)
    root.addHandler(out)
    root.addHandler(err)
    root.propagate = False


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"distributed_pytorch_tpu.{name}")
