"""Structured logging: the observability layer the reference lacks.

The reference imports ``logging`` but never configures it and reports
everything via bare ``print`` (reference main.py:10, SURVEY.md section 5).
Here one ``setup_logging`` call configures rank-aware stdlib logging; the
training loop's printed windows (loss/20 iters, time/40 iters) route through
it so output is greppable and per-process attributable on multi-host runs.
"""

from __future__ import annotations

import logging
import sys


def setup_logging(level: str = "INFO") -> None:
    """Configure root logging with a rank-aware format (idempotent)."""
    try:
        import jax
        rank = jax.process_index()
    except Exception:
        rank = 0
    root = logging.getLogger("distributed_pytorch_tpu")
    root.setLevel(level.upper())
    if root.handlers:  # already configured
        return
    handler = logging.StreamHandler(sys.stdout)
    handler.setFormatter(logging.Formatter(
        f"%(asctime)s rank{rank} %(name)s %(levelname)s: %(message)s",
        datefmt="%H:%M:%S"))
    root.addHandler(handler)
    root.propagate = False


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"distributed_pytorch_tpu.{name}")
