"""Training sentry: detect bad steps, roll back, skip, escalate.

Long TPU runs die of NaN/Inf gradients and loss spikes far more often
than of hardware loss — and the reference has no answer to either
(SURVEY.md section 5).  The sentry is the host-side recovery driver over
the per-step health signals the jitted steps already compute in-scan
(train.py / lm.py: loss value + a grads-finite flag, negligible next to
the backward):

1. **Detect** — a step is bad when its in-jit finiteness flag trips or
   its loss exceeds the rolling median/MAD spike bound
   (``metrics.SpikeDetector``; median/MAD so the spike cannot poison the
   baseline it is judged against).
2. **Rewind and skip** (the PaLM recipe) — restore the last-good
   snapshot (params/opt state/step counter, host-resident) and DROP the
   data window since that snapshot: the caller simply continues with the
   next batch, so the offending window is never replayed.  Because the
   step counter rewinds with the state, the post-rollback trajectory is
   bitwise-identical to an uninjected run over the same data order with
   the skip-window excluded (tests/test_faults.py pins this).
3. **Escalate** — triggers inside one recovery horizon climb a ladder:
   skip the window (level <= ``skip_budget``); then also tighten the
   gradient clip via the trainer's ``tighten_grad_clip`` hook (LM
   trainer) by ``clip_factor`` per level; past ``max_rollbacks``, a NEW
   rung (round 12) sits between rollback-and-skip and abort: with an
   ``on_resize`` hook installed, the sentry rolls back to last-good ONCE
   more and requests a GANG RESIZE — in a gang worker the hook
   checkpoints and exits ``ELASTIC_RESIZE_EXIT_CODE`` so the elastic
   agent re-rendezvouses the gang one smaller (parallel/elastic.py); in
   a single-controller run it may rebuild the trainer on a smaller mesh
   (``trainer.rebuild``) and return True to continue.  Only past THAT —
   no hook, or the hook declined — does the sentry abort with a full
   diagnostic (``SentryAbort``).  ``checkpoint_every`` clean steps reset
   the ladder — recovery that holds is recovery.

Event accounting lives in ``self.stats`` (steps, nonfinite, spikes,
rollbacks, skipped_steps, clip_tightened, stragglers) — the train-stats
contract of ISSUE 1.  Step wall-time runs through a second SpikeDetector
purely for STRAGGLER accounting: a slow step is recorded, never rolled
back (slowness is not state corruption).

The sentry is trainer-agnostic: it snapshots whichever of
``params/state/opt_state/sync_state`` the trainer owns, plus ``_step``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import numpy as np

from . import monitor, telemetry
from .metrics import SpikeDetector


def _tel_event(name: str, **args) -> None:
    """Sentry escalations on the unified timeline (round 13): every
    detect / rollback / tighten / resize / abort lands as an event in
    the 'sentry' lane when the registry is active; free otherwise."""
    tel = telemetry.active()
    if tel is not None:
        tel.event(name, phase="sentry", **args)


@dataclass
class SentryConfig:
    checkpoint_every: int = 50   # clean steps between last-good snapshots
    spike_window: int = 32
    spike_threshold: float = 10.0
    spike_min_history: int = 8
    skip_budget: int = 1         # ladder: rollbacks at this level only skip
    max_rollbacks: int = 3       # ladder: abort past this many per horizon
    clip_factor: float = 0.5     # grad-clip multiplier per tighten
    time_threshold: float = 10.0  # straggler bound (MAD multiples)


class SentryAbort(RuntimeError):
    """The escalation ladder ran out: repeated faults survived rollback,
    skip, and grad-clip tightening.  Carries the full event accounting."""

    def __init__(self, message: str, stats: dict):
        super().__init__(f"{message}; events={stats}")
        self.stats = dict(stats)


_STATE_ATTRS = ("params", "state", "opt_state", "sync_state")


class TrainingSentry:
    """Guard one trainer's step loop.  Usage::

        sentry = TrainingSentry(trainer)
        for batch in batches:
            loss = sentry.step(*batch)   # None = batch skipped (rollback)

    ``step`` runs ``trainer.train_step``, judges the result, and either
    returns the loss (clean) or rolls the trainer back and returns None
    — the caller's only job is to keep feeding batches.
    """

    def __init__(self, trainer, cfg: SentryConfig | None = None, *,
                 on_resize=None, log=print):
        self.trainer = trainer
        self.cfg = cfg or SentryConfig()
        # the resize escalation rung (round 12): called ONCE per run,
        # after rollback/skip/clip-tightening all failed but before
        # aborting — ``on_resize(stats)`` returning truthy means the
        # resize happened in-process (e.g. trainer.rebuild onto a
        # smaller mesh) and training continues; a gang worker's hook
        # checkpoints and exits ELASTIC_RESIZE_EXIT_CODE instead (the
        # elastic agent then reshards the gang one smaller).
        self.on_resize = on_resize
        self._resize_used = False
        # every sentry log line also lands in the monitor's bounded log
        # ring, so a postmortem bundle shows the escalation trail the
        # operator saw

        def _log(msg, _inner=log):
            monitor.log_line(str(msg))
            _inner(msg)
        self.log = _log
        self.detector = SpikeDetector(
            window=self.cfg.spike_window,
            threshold=self.cfg.spike_threshold,
            min_history=self.cfg.spike_min_history)
        self.time_detector = SpikeDetector(
            window=self.cfg.spike_window,
            threshold=self.cfg.time_threshold,
            min_history=self.cfg.spike_min_history,
            min_sigma=1e-4)
        self.stats = dict(steps=0, nonfinite=0, spikes=0, rollbacks=0,
                          skipped_steps=0, clip_tightened=0, stragglers=0,
                          snapshots=0, resizes=0)
        self._ladder = 0
        self._snap = None
        self._snap_step = 0
        self.snapshot()

    # -- last-good state ---------------------------------------------------
    def snapshot(self) -> None:
        """Host-copy the trainer's full training state as last-good.

        The fetch is ``checkpoint._fetch``: it returns an OWNED copy
        (on the CPU backend a host view of a jax array can be ZERO-COPY,
        and the trainer's next step DONATES these buffers — an aliased
        snapshot would silently rot as the runtime reuses them) and
        allgathers cross-process-sharded leaves, so multi-host trainers
        snapshot collectively — every process must drive the sentry in
        step, exactly as they must for checkpoint saves."""
        from .checkpoint import _fetch

        snap = {}
        for name in _STATE_ATTRS:
            tree = getattr(self.trainer, name, None)
            if tree is not None:
                snap[name] = jax.tree.map(
                    lambda x: (_fetch(x) if isinstance(x, jax.Array)
                               else x), tree)
        self._snap = snap
        self._snap_step = self.trainer._step
        self.stats["snapshots"] += 1
        self._ladder = 0  # a full clean horizon: recovery held

    def rollback(self) -> int:
        """Restore the last-good snapshot (device placement taken from
        the trainer's live arrays, so shardings survive the round-trip;
        cross-process shardings rebuild per-shard via
        ``make_array_from_callback``); returns the steps rewound."""
        def put(s, l):
            if not isinstance(l, jax.Array):
                return s
            if l.is_fully_addressable:
                return jax.device_put(s, l.sharding)
            # multi-host: each process supplies its addressable shards
            # of the full host copy (the snapshot holds the global value)
            return jax.make_array_from_callback(
                l.shape, l.sharding, lambda idx, s=s: s[idx])

        rewound = self.trainer._step - self._snap_step
        for name, saved in self._snap.items():
            live = getattr(self.trainer, name)
            setattr(self.trainer, name, jax.tree.map(put, saved, live))
        self.trainer._step = self._snap_step
        self.stats["rollbacks"] += 1
        _tel_event("sentry_rollback", to_step=self._snap_step,
                   rewound=rewound)
        return rewound

    # -- escalation rungs --------------------------------------------------
    def request_resize(self, reason: str = "ladder") -> bool:
        """The resize rung as a public entry point: roll back to
        last-good once and hand the decision to the ``on_resize`` hook —
        exactly what the exhausted escalation ladder does, but callable
        from OUTSIDE the step loop too (monitor.sentry_breach_hook wires
        an SLO breach here, so a breached step-time objective recovers
        through the same resize machinery a loss-spike storm would).
        True iff the hook resized in-process and training continues with
        a fresh recovery horizon; False when no hook is wired, the one
        resize was already spent, or the hook declined (a gang worker's
        hook never returns — it exits ELASTIC_RESIZE_EXIT_CODE)."""
        if self.on_resize is None or self._resize_used:
            return False
        self._resize_used = True
        self.stats["resizes"] += 1
        rewound = self.rollback()
        self.stats["skipped_steps"] += rewound
        self.log(f"[sentry] requesting gang RESIZE ({reason}): rolled "
                 f"back {rewound} step(s) to last-good")
        _tel_event("sentry_resize", step=self.trainer._step,
                   rewound=rewound, reason=reason)
        if self.on_resize(dict(self.stats)):
            # resized in-process: the rebuilt trainer's state is the
            # new last-good; give recovery a fresh horizon
            self._ladder = 0
            self.snapshot()
            return True
        return False

    # -- the guarded step --------------------------------------------------
    def _trainer_ok(self) -> bool:
        ok = getattr(self.trainer, "last_ok", None)
        # the flag is a pmean over replicas: ONE poisoned replica yields
        # a fractional value (e.g. 0.875), which plain truthiness would
        # wave through — healthy means exactly 1.0 everywhere
        return True if ok is None else bool(np.all(np.asarray(ok) >= 1.0))

    def step(self, *batch):
        """One guarded optimizer step; returns the loss, or None when the
        step was judged bad and the trainer was rolled back (the batch
        window since the last snapshot is skipped — continue with the
        NEXT batch)."""
        t0 = time.perf_counter()
        loss = self.trainer.train_step(*batch)
        loss_val = float(loss)
        elapsed = time.perf_counter() - t0

        trigger = None
        if not self._trainer_ok() or not np.isfinite(loss_val):
            trigger = "nonfinite"
        elif self.detector.update(loss_val):
            trigger = "spikes"

        if trigger is None:
            self.stats["steps"] += 1
            if self.time_detector.update(elapsed):
                # slow, not wrong: account, never roll back
                self.stats["stragglers"] += 1
            if (self.trainer._step - self._snap_step
                    >= self.cfg.checkpoint_every):
                self.snapshot()
            return loss_val

        self.stats[trigger] += 1
        self._ladder += 1
        self.log(f"[sentry] step {self.trainer._step - 1}: {trigger} "
                 f"(loss={loss_val:.6g}); escalation level {self._ladder}")
        _tel_event("sentry_trigger", kind=trigger,
                   step=self.trainer._step - 1, loss=loss_val,
                   ladder=self._ladder)
        if self._ladder > self.cfg.max_rollbacks:
            # resize rung (round 12): the rollback/skip/clip ladder is
            # exhausted — before aborting, roll back to last-good once
            # more and hand the decision to the resize hook (a gang
            # worker exits ELASTIC_RESIZE_EXIT_CODE from inside it; an
            # in-process hook rebuilds the trainer and returns True)
            if self.request_resize(f"ladder:{trigger}"):
                return None
            _tel_event("sentry_abort", kind=trigger,
                       step=self.trainer._step - 1,
                       rollbacks=self.stats["rollbacks"])
            # flight recorder (round 15): snapshot the run's last
            # moments before the abort unwinds the training loop
            monitor.write_postmortem(
                "sentry_abort",
                detail={"kind": trigger,
                        "step": int(self.trainer._step - 1),
                        "loss": loss_val,
                        "stats": {k: float(v)
                                  for k, v in self.stats.items()}},
                memory=monitor.memory_watermarks(
                    **{a: getattr(self.trainer, a, None)
                       for a in _STATE_ATTRS}))
            raise SentryAbort(
                f"{trigger} at step {self.trainer._step - 1} after "
                f"{self.stats['rollbacks']} rollbacks — escalation "
                f"ladder exhausted", self.stats)
        if self._ladder > self.cfg.skip_budget:
            tighten = getattr(self.trainer, "tighten_grad_clip", None)
            if tighten is not None:
                new_clip = tighten(self.cfg.clip_factor)
                self.stats["clip_tightened"] += 1
                self.log(f"[sentry] grad clip tightened to {new_clip:g}")
                _tel_event("sentry_clip_tightened", clip=float(new_clip))
        rewound = self.rollback()
        self.stats["skipped_steps"] += rewound
        self.log(f"[sentry] rolled back {rewound} step(s) to step "
                 f"{self._snap_step}; window skipped")
        return None
