"""JAX version compatibility: run the modern API on older runtimes.

The framework targets current JAX (``jax.shard_map``, the vma/pcast
varying-axis machinery, ``ShapeDtypeStruct(vma=...)``).  Older runtimes
(< 0.6) ship ``shard_map`` under ``jax.experimental`` with ``check_rep``
instead of ``check_vma`` and have no vma tracking at all.  Robustness
policy (ISSUE 1): degrade gracefully instead of failing at import — a
worker that cannot even ``import train`` cannot run ANY recovery path.

What degrades where:

- ``shard_map``: the experimental fallback maps ``check_vma`` to
  ``check_rep=False`` (the old checker predates the vma rules the
  framework's collectives are written against; numerics are unchanged,
  only the static replication proof is off — the same trade the
  ``vma_opaque`` strategies already make deliberately).
- ``vma_of`` / ``pcast``: without vma tracking, every array reports an
  empty vma set and pcast is the identity — callers' "make varying"
  bookkeeping becomes a no-op, which is exactly the old semantics.
- ``shape_struct``: drops the ``vma=`` kwarg when unsupported.
"""

from __future__ import annotations

import os

import jax

try:  # modern: top-level shard_map with check_vma
    from jax import shard_map as _shard_map
    _MODERN_SHARD_MAP = True
except ImportError:  # pragma: no cover - exercised only on old runtimes
    from jax.experimental.shard_map import shard_map as _shard_map
    _MODERN_SHARD_MAP = False

HAS_VMA = hasattr(jax, "typeof")

# Old runtimes (<= 0.4.x) heap-corrupt EXECUTING a train-step executable
# deserialized from the persistent compilation cache when its inputs are
# DONATED ("corrupted double-linked list" aborts on the warm-cache run:
# the loaded executable's input-output aliasing frees buffers it does
# not own).  Donation and AOT execution consult these flags and degrade
# on legacy runtimes — donation off costs transient memory, jit-instead-
# of-AOT moves compile time into the first timed step; neither costs
# correctness, and the persistent cache stays on for the compile-bound
# test suite.
#
# Donation sites that consult DONATION_SAFE (via ``donate``): the train
# steps (train.py, lm.py), and serve.py's whole decode hot path — the
# lockstep block (KV cache + the device-side carry the overlapped
# dispatch chains on), the speculative block (cache + its staging dict,
# whose (slots, kv_len) stream buffer is rebuilt every dispatch), the
# suffix-prefill/chunk/insert/scatter cache writers.  Without donation,
# each of those dispatches copies the full paged pool per call.
#
# JAX_GRAFT_FORCE_DONATION=1/0 overrides the runtime detection — for
# A/B-measuring donation's effect on hardware, or re-testing the legacy
# corruption after a runtime upgrade.  When forcing ON where
# DONATION_SAFE would be False, disable the persistent compilation
# cache first (that combination IS the corruption).
AOT_EXECUTION_SAFE = _MODERN_SHARD_MAP
DONATION_SAFE = _MODERN_SHARD_MAP
_force = os.environ.get("JAX_GRAFT_FORCE_DONATION")
if _force is not None:  # pragma: no cover - operator escape hatch
    DONATION_SAFE = _force.strip().lower() not in ("0", "", "false")


def donate(*argnums: int) -> tuple:
    """``donate_argnums`` value honoring DONATION_SAFE: the given indices
    on modern runtimes, empty (no donation) on legacy ones."""
    return tuple(argnums) if DONATION_SAFE else ()


if _MODERN_SHARD_MAP:
    shard_map = _shard_map
else:  # pragma: no cover - exercised only on old runtimes
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        # check_rep=False: the legacy replication checker predates the
        # vma rules (psum-of-lists, custom_vjp sync points) and rejects
        # valid modern programs; correctness is unaffected.
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)


def vma_of(x) -> frozenset:
    """The array's varying mesh axes (empty set when untracked)."""
    if HAS_VMA:
        return jax.typeof(x).vma
    return frozenset()


def pcast(x, axes, to: str = "varying"):
    """``jax.lax.pcast`` where it exists; identity on untracked runtimes."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to=to)
    return x  # pragma: no cover - exercised only on old runtimes


def shape_struct(shape, dtype, vma=None):
    """``ShapeDtypeStruct`` carrying vma only where supported."""
    if HAS_VMA and vma is not None:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


if not hasattr(jax.lax, "axis_size"):  # pragma: no cover - old runtimes
    # Polyfill via the classic idiom (psum of a unit constant folds to
    # the axis size at trace time).  Installed onto jax.lax so the many
    # call sites need no edits; the package __init__ imports this module
    # first, so the polyfill is in place before any trace runs.
    def _axis_size(axis):
        return jax.lax.psum(1, axis)

    jax.lax.axis_size = _axis_size

if not hasattr(jax.lax, "pcast"):  # pragma: no cover - old runtimes
    # Identity: legacy runtimes have no vma tracking, so "cast to
    # varying" has nothing to record.  Collective semantics are
    # unchanged (the legacy shard_map runs check_rep=False here).
    def _pcast(x, axes, to="varying"):
        return x

    jax.lax.pcast = _pcast


# -- differentiable fusion barrier (round 10) ------------------------------
#
# ``lax.optimization_barrier`` has no autodiff rule on legacy runtimes
# (NotImplementedError under vjp on 0.4.37), and even where it does, the
# pipeline chunk body needs the barrier on BOTH passes: the cotangent
# chain must get the same compilation boundary as the primal, or the
# unrolled-backward fusion drifts exactly like the forward one.  The
# custom_vjp below is the one definition of "identity that XLA may not
# fuse across, in either direction".

@jax.custom_vjp
def opt_barrier(x):
    """Identity that blocks XLA fusion across it, differentiable: the
    forward applies ``optimization_barrier`` to the primal, the backward
    applies it to the cotangent (parallel/pipeline.py uses it to give
    layer-scan bodies the same fusion boundary at every trip count —
    XLA unrolls trip-count-1 scans and re-fuses them sub-ulp
    differently)."""
    return jax.lax.optimization_barrier(x)


def _opt_barrier_fwd(x):
    return jax.lax.optimization_barrier(x), None


def _opt_barrier_bwd(_, g):
    return (jax.lax.optimization_barrier(g),)


opt_barrier.defvjp(_opt_barrier_fwd, _opt_barrier_bwd)
