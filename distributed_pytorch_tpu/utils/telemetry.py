"""Unified run telemetry: ONE structured event stream for the whole stack.

Twelve PRs in, every subsystem had grown a private side channel —
``PhaseTimer`` in serving, the sentry's ``stats`` dict, the elastic
agent's ``resize_events``, autotune's ``SyncPlan``, the reference-
semantics metric windows — none sharing a clock, a schema, or a sink,
and the launcher still reported resizes via bare ``print``.  BAGUA
(arXiv 2107.01499) builds its autotuning and straggler relaxations ON a
unified tracing service; the ROADMAP's carried-forward items (async
relaxations, the fleet router) need the same substrate here: you cannot
route around a replica — or relax a straggler — you cannot see.

Design:

- **Registry** (``Telemetry``): counters, gauges, histogram-style
  observations, timed spans, and discrete events, all funneled into one
  record shape: ``{"type", "name", "phase", "ts", "rank", "gen", ...}``.
  ``phase`` is the subsystem lane ("train", "serve", "gang", "ckpt",
  "autotune", "sentry") — the Chrome-trace ``tid``.
- **Sink**: one rank-tagged JSONL file per process under a shared run
  directory (``events_rank<R>_gen<G>_<pid>.jsonl``).  Appends are whole
  lines written with a single ``os.write`` on an ``O_APPEND`` fd — the
  same torn-read-proof idiom as the elastic heartbeat files — and the
  default flushes every record, so even a worker that leaves via
  ``os._exit`` (the elastic drain path) loses nothing.  The first
  record of every file is an **epoch** pinning (wall clock, monotonic
  clock), which is how the exporter aligns ranks that booted at
  different times onto one timeline.
- **Bounded memory**: a ring of the most recent ``ring`` records plus
  exact running aggregates per (phase, name) — a month-long serving
  process must not accumulate one dict per block forever.
- **Exporter**: ``merge_chrome_trace(run_dir)`` merges every rank's
  files into one Chrome-trace/Perfetto JSON (``pid`` = rank, ``tid`` =
  phase, generation tagged on every event so a timeline survives an
  elastic shrink/grow), and ``run_summary(run_dir)`` is the
  machine-readable companion (``scripts/telemetry_summary.py`` prints
  both).

**Off is the default and is free**: nothing in this module touches jax,
the compiled step programs are identical with telemetry on or off (the
per-step scalars ride the health-flag output that exists regardless —
train.py/lm.py), and instrumented call sites guard on ``active()``
returning None (one attribute read).  The module must stay importable
without jax: the launcher agent (a deliberately jax-free process) logs
gang lifecycle events through it.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import socket
import threading
import time
from collections import deque

# Env contract: the launcher exports the run directory to its workers
# (and the CLIs' --telemetry-dir defaults from it), so one flag on the
# agent wires the whole gang onto one timeline.
TELEMETRY_DIR_ENV = "TELEMETRY_DIR"
RECORD_VERSION = 1
FILE_PREFIX = "events_"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def _jsonsafe(obj):
    """Map non-finite floats to strings ("NaN"/"Infinity"/"-Infinity")
    recursively: Python's json module happily WRITES bare NaN, which is
    invalid strict JSON — and a diverging run (exactly when the trace
    matters most) gauges loss=NaN, which would make the whole exported
    Chrome trace unparseable to chrome://tracing / JSON.parse."""
    if isinstance(obj, float):
        if obj != obj:
            return "NaN"
        if obj in (float("inf"), float("-inf")):
            return "Infinity" if obj > 0 else "-Infinity"
        return obj
    if isinstance(obj, dict):
        return {k: _jsonsafe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonsafe(v) for v in obj]
    return obj


class Telemetry:
    """One process's telemetry registry + JSONL sink.

    ``rank``/``gen`` default from the launcher env contract (``RANK``,
    ``RESTART_ATTEMPT``); the agent itself registers as rank -1 with
    ``label="agent"``.  All methods are thread-safe (the serving loop
    and checkpoint writer threads share the process registry).
    """

    def __init__(self, run_dir: str, *, rank: int | None = None,
                 gen: int | None = None, ring: int = 4096,
                 flush_every: int = 1, label: str | None = None,
                 tag: str = ""):
        self.run_dir = run_dir
        self.rank = rank if rank is not None else _env_int("RANK", 0)
        self.gen = (gen if gen is not None
                    else _env_int("RESTART_ATTEMPT", 0))
        self.label = label
        self.flush_every = max(1, flush_every)
        os.makedirs(run_dir, exist_ok=True)
        # ``tag`` disambiguates SEVERAL registries in one process writing
        # the same run_dir (the serving fleet: each replica + the router
        # keep their own registry so spans land under their own pid/rank
        # in the merged trace) — without it two same-rank registries
        # would interleave epochs in one O_APPEND file
        self.path = os.path.join(
            run_dir,
            f"{FILE_PREFIX}rank{self.rank}_gen{self.gen}_"
            f"{os.getpid()}{tag}.jsonl")
        self._lock = threading.Lock()
        self._fd: int | None = None
        self._pending: list[str] = []
        self._closed = False
        # bounded in-memory view: recent records for summaries/debugging,
        # exact running aggregates forever
        self.recent: deque[dict] = deque(maxlen=ring)
        self._counters: dict[tuple[str, str], float] = {}
        self._gauges: dict[tuple[str, str], float] = {}
        self._spans: dict[tuple[str, str], list] = {}  # [n, total, max]
        self._events: dict[tuple[str, str], int] = {}
        # live-record subscribers (the run doctor): called OUTSIDE the
        # lock — a subscriber is allowed to emit its own records (breach
        # events) and the lock is not reentrant
        self._subs: list = []
        # keep the ONE bound-method object: atexit.unregister matches
        # the registered callable, and `self.close` evaluates to a
        # fresh (non-matching) bound method on every access
        self._atexit_hook = self.close
        atexit.register(self._atexit_hook)

    # -- sink --------------------------------------------------------------
    def _open(self) -> int:
        """Open the sink lazily and stamp the EPOCH record first: wall +
        monotonic clock pinned at the same instant, which is what lets
        the exporter place this process's monotonic timestamps on the
        shared wall timeline."""
        fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                     0o644)
        epoch = {"type": "epoch", "version": RECORD_VERSION,
                 "rank": self.rank, "gen": self.gen, "pid": os.getpid(),
                 "host": socket.gethostname(), "label": self.label,
                 "wall": time.time(), "mono": time.perf_counter()}
        os.write(fd, (json.dumps(epoch) + "\n").encode())
        return fd

    def _record(self, rec: dict) -> None:
        rec = _jsonsafe(rec)  # strict JSON even for NaN/Inf gauges
        with self._lock:
            if self._closed:
                return
            self.recent.append(rec)
            self._pending.append(json.dumps(rec))
            if len(self._pending) >= self.flush_every:
                self._flush_locked()
            subs = self._subs if self._subs else None
        if subs:
            # snapshot taken under the lock; delivery outside it so a
            # subscriber may emit records (breach events) without
            # deadlocking on the non-reentrant lock
            for fn in subs:
                try:
                    fn(rec)
                except Exception:
                    pass  # a broken monitor must never break the run

    def subscribe(self, fn) -> None:
        """Register ``fn(record_dict)`` to see every record as it lands
        (the run doctor's live feed).  No subscribers (the default) costs
        one truthiness test per record."""
        with self._lock:
            if fn not in self._subs:
                self._subs = self._subs + [fn]

    def unsubscribe(self, fn) -> None:
        with self._lock:
            self._subs = [s for s in self._subs if s is not fn]

    def _flush_locked(self) -> None:
        if not self._pending:
            return
        if self._fd is None:
            self._fd = self._open()
        data = ("\n".join(self._pending) + "\n").encode()
        self._pending = []
        # ONE write on an O_APPEND fd: a reader (the exporter, possibly
        # racing a live run) sees whole lines or nothing — the heartbeat
        # idiom applied to an append-only log
        os.write(self._fd, data)

    def flush(self) -> None:
        with self._lock:
            if not self._closed:
                self._flush_locked()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._flush_locked()
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None
            self._closed = True
        # drop the exit hook: a process that cycles enable()/disable()
        # (the bench A/B, a server toggling telemetry) must not pin one
        # dead registry per cycle on the atexit list for its lifetime
        try:
            atexit.unregister(self._atexit_hook)
        except Exception:
            pass

    # -- instruments -------------------------------------------------------
    def _base(self, type_: str, name: str, phase: str) -> dict:
        return {"type": type_, "name": name, "phase": phase,
                "ts": time.perf_counter(), "rank": self.rank,
                "gen": self.gen}

    def counter(self, name: str, inc: float = 1, *, phase: str = "run",
                **args) -> None:
        """Monotonic accumulator; the record carries both the increment
        and the running total (so a truncated stream still reads)."""
        key = (phase, name)
        with self._lock:
            total = self._counters[key] = self._counters.get(key, 0) + inc
        rec = self._base("counter", name, phase)
        rec["inc"] = inc
        rec["total"] = total
        if args:
            rec["args"] = args
        self._record(rec)

    def gauge(self, name: str, value: float, *, phase: str = "run",
              **args) -> None:
        """Point-in-time scalar (loss, grad-norm, window average)."""
        with self._lock:
            self._gauges[(phase, name)] = value
        rec = self._base("gauge", name, phase)
        rec["value"] = value
        if args:
            rec["args"] = args
        self._record(rec)

    def observe(self, name: str, value: float, *, phase: str = "run",
                **args) -> None:
        """Histogram-style observation: aggregated like a span's
        duration (count/total/max + the recent ring for percentiles)."""
        self._span_agg((phase, name), value)
        rec = self._base("hist", name, phase)
        rec["value"] = value
        if args:
            rec["args"] = args
        self._record(rec)

    def event(self, name: str, *, phase: str = "run", **args) -> None:
        """Discrete occurrence (worker loss, resize, sentry rollback)."""
        key = (phase, name)
        with self._lock:
            self._events[key] = self._events.get(key, 0) + 1
        rec = self._base("event", name, phase)
        rec["args"] = args
        self._record(rec)

    def _span_agg(self, key: tuple, dur: float) -> None:
        with self._lock:
            agg = self._spans.get(key)
            if agg is None:
                agg = self._spans[key] = [0, 0.0, 0.0]
            agg[0] += 1
            agg[1] += dur
            agg[2] = max(agg[2], dur)

    def span_at(self, name: str, start: float, dur: float, *,
                phase: str = "run", **args) -> None:
        """Record a completed span from a caller-held ``perf_counter``
        pair — the hot-loop entry point (PhaseTimer.add's shape)."""
        self._span_agg((phase, name), dur)
        rec = {"type": "span", "name": name, "phase": phase, "ts": start,
               "dur": dur, "rank": self.rank, "gen": self.gen}
        if args:
            rec["args"] = args
        self._record(rec)

    @contextlib.contextmanager
    def span(self, name: str, *, phase: str = "run", **args):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.span_at(name, t0, time.perf_counter() - t0, phase=phase,
                         **args)

    # -- in-process view ---------------------------------------------------
    def summary(self) -> dict:
        """Exact running aggregates (counters' totals, gauges' last
        values, span/hist count-total-max, event counts), keyed
        "phase/name".  Percentile detail lives in the run files — this
        is the bounded in-memory view."""
        with self._lock:
            return {
                "rank": self.rank, "gen": self.gen,
                "counters": {f"{p}/{n}": v
                             for (p, n), v in self._counters.items()},
                "gauges": {f"{p}/{n}": v
                           for (p, n), v in self._gauges.items()},
                "spans": {f"{p}/{n}": {"count": a[0], "total_s": a[1],
                                       "max_s": a[2]}
                          for (p, n), a in self._spans.items()},
                "events": {f"{p}/{n}": v
                           for (p, n), v in self._events.items()},
            }


# ---------------------------------------------------------------------------
# process-wide registry (the no-op fast path when disabled)

_ACTIVE: Telemetry | None = None


def active() -> Telemetry | None:
    """The process registry, or None when telemetry is off (the default).
    Call sites guard on this — one module-global read on the off path."""
    return _ACTIVE


def enable(run_dir: str, **kwargs) -> Telemetry:
    """Install the process registry writing into ``run_dir``; replaces
    (and closes) a previous registry."""
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.close()
    _ACTIVE = Telemetry(run_dir, **kwargs)
    return _ACTIVE


def maybe_enable(run_dir: str | None = None, **kwargs) -> Telemetry | None:
    """Enable iff a run directory is known: the explicit argument (a
    CLI's --telemetry-dir) or the launcher-exported ``TELEMETRY_DIR``
    env; None otherwise — the off-by-default contract."""
    run_dir = run_dir or os.environ.get(TELEMETRY_DIR_ENV)
    if not run_dir:
        return None
    return enable(run_dir, **kwargs)


def child_env(tel: Telemetry | None = None) -> dict[str, str]:
    """The env contract that hands this process's run directory to a
    child process: merge into the child's environment and its
    ``maybe_enable()`` lands in the SAME run dir, so per-process event
    files (pid-suffixed) interleave into one merged Chrome trace.  The
    launcher exports ``TELEMETRY_DIR`` by hand; spawned fleet daemons
    (fleet/daemon.py ``ReplicaProcess``) ride this helper.  Empty dict
    when telemetry is off — safe to splat unconditionally."""
    tel = tel if tel is not None else active()
    run_dir = tel.run_dir if tel is not None else os.environ.get(
        TELEMETRY_DIR_ENV)
    return {TELEMETRY_DIR_ENV: run_dir} if run_dir else {}


def enable_from_cli(run_dir: str | None = None) -> Telemetry | None:
    """The ONE CLI bootstrap (cli.py / lm_cli.py): ``maybe_enable`` with
    the launcher-aware rank precedence — env ``RANK`` first (the
    launcher contract, right even for CPU-simulation gang members whose
    ``jax.process_index()`` is always 0), falling back to
    ``jax.process_index()`` only when jax is already loaded
    (launcher-less multi-host runs).  The precedence itself is
    ``utils.logging.current_rank`` — the SAME resolver that stamps log
    lines, so telemetry and logs can never disagree on a rank; neither
    ever imports jax."""
    from .logging import current_rank

    return maybe_enable(run_dir, rank=current_rank())


def disable() -> None:
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.close()
    _ACTIVE = None


def emit_train_steps(tel: Telemetry, t0: float, step0: int, k: int,
                     losses, oks, mets, *, span_name: str = "train_steps",
                     phase: str = "train") -> None:
    """The ONE train-dispatch emission both trainers share (train.py /
    lm.py): a span for the dispatch plus per-step gauges for the
    device-side scalars that ride the in-scan health-flag output —
    loss, grad global-norm, post-update param global-norm — and an
    event for any unhealthy step.  Fetches the (tiny) metric arrays to
    host; only ever called with an active registry, so telemetry-off
    pays nothing.  numpy imports lazily: this module must stay cheap
    and jax-free for the launcher agent."""
    import numpy as np

    dur = time.perf_counter() - t0
    step0, k = int(step0), int(k)
    losses = np.asarray(losses).reshape(-1)
    oks = np.asarray(oks).reshape(-1)
    mets = np.asarray(mets).reshape(-1, 2)
    tel.span_at(span_name, t0, dur, phase=phase, step0=step0, k=k)
    for i in range(k):
        s = step0 + i
        tel.gauge("loss", float(losses[i]), phase=phase, step=s)
        tel.gauge("grad_norm", float(mets[i, 0]), phase=phase, step=s)
        tel.gauge("param_norm", float(mets[i, 1]), phase=phase, step=s)
        if float(oks[i]) < 1.0:
            tel.event("unhealthy_step", phase=phase, step=s,
                      ok=float(oks[i]))
    tel.counter("steps", k, phase=phase)


def emit_sync_windows(tel: Telemetry, t0: float, step0: int, k: int,
                      sync_every: int, *, wire_bytes: int | None = None,
                      span_name: str = "sync_window",
                      phase: str = "train") -> None:
    """Window-boundary spans + per-window wire gauges for a
    communication-sparse dispatch (round 18, ``sync_every > 1``): one
    ``sync_window`` span per completed H-step window inside the
    dispatch, stamped with its step range, plus a ``window_wire_bytes``
    gauge (the trainer's static f32 estimate of ONE boundary exchange's
    payload — compression rides below it).  The dispatch is one host
    measurement, so the window spans split its duration evenly: the
    timeline shows boundary CADENCE, not per-window jitter (per-window
    device timing would need device instrumentation the zero-overhead
    pin forbids)."""
    windows = k // sync_every
    if windows <= 0:
        return
    dur = (time.perf_counter() - t0) / windows
    for w in range(windows):
        tel.span_at(span_name, t0 + w * dur, dur, phase=phase,
                    step0=int(step0) + w * sync_every, k=sync_every)
        if wire_bytes is not None:
            tel.gauge("window_wire_bytes", float(wire_bytes), phase=phase,
                      step=int(step0) + (w + 1) * sync_every - 1)
    tel.counter("sync_windows", windows, phase=phase)


def emit_window_plan(tel: Telemetry, *, step: int,
                     sync_every_per_slice=None,
                     outer_steps: int | None = None,
                     phase: str = "train") -> None:
    """Round-22 boundary gauges for the DiLoCo layer: one
    ``sync_every_slice{i}`` gauge per WAN-attached slice (so the
    RunDoctor timeline shows WHICH slice the per-slice SyncRelaxHook
    widened, and when it narrowed back) and an ``outer_opt_steps``
    gauge counting applied outer-optimizer steps.  Both are no-ops
    when the feature is off — the uniform/plain-mean path emits
    exactly what it emitted in round 18."""
    if sync_every_per_slice is not None:
        for i, h in enumerate(sync_every_per_slice):
            tel.gauge(f"sync_every_slice{i}", float(h), phase=phase,
                      step=int(step))
    if outer_steps is not None:
        tel.gauge("outer_opt_steps", float(outer_steps), phase=phase,
                  step=int(step))


# ---------------------------------------------------------------------------
# exporter: merge every rank's files -> Chrome trace + run summary


def read_run(run_dir: str) -> list[tuple[dict, list[dict]]]:
    """Parse every per-process event file in ``run_dir`` into
    ``(epoch_record, records)`` pairs.  Torn trailing lines (a reader
    racing a live writer) and unreadable files are skipped — the merge
    must work mid-run."""
    out = []
    try:
        names = sorted(os.listdir(run_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith(FILE_PREFIX) and name.endswith(".jsonl")):
            continue
        epoch, records = None, []
        try:
            with open(os.path.join(run_dir, name)) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail of a live file
                    if rec.get("type") == "epoch":
                        epoch = rec
                    else:
                        records.append(rec)
        except OSError:
            continue
        if epoch is not None:
            out.append((epoch, records))
    # chronological by each file's epoch wall clock, NOT by filename:
    # lexicographic order puts gen10 before gen2, which would make
    # "last value" summaries stale past 9 elastic restarts
    out.sort(key=lambda pair: pair[0].get("wall", 0.0))
    return out


def _align_us(epoch: dict, mono_ts: float) -> float:
    """Monotonic timestamp -> shared wall-clock microseconds, via the
    file's epoch record (wall and mono pinned at the same instant)."""
    return (epoch["wall"] + (mono_ts - epoch["mono"])) * 1e6


def merge_chrome_trace(run_dir: str) -> dict:
    """Merge all ranks' event files into one Chrome-trace/Perfetto JSON:
    ``pid`` = rank (process-named, the agent's -1 reads "agent"),
    ``tid`` = phase, spans as complete ("X") events, discrete events as
    instants, counters/gauges as counter ("C") tracks; every event's
    args carry its generation, so a timeline spanning an elastic
    shrink -> grow stays attributable."""
    events: list[dict] = []
    seen_pids: set[int] = set()
    for epoch, records in read_run(run_dir):
        pid = int(epoch["rank"])
        if pid not in seen_pids:
            seen_pids.add(pid)
            name = (epoch.get("label")
                    or ("agent" if pid < 0 else f"rank {pid}"))
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": name}})
            events.append({"ph": "M", "name": "process_sort_index",
                           "pid": pid, "tid": 0,
                           "args": {"sort_index": pid}})
        for rec in records:
            ts = _align_us(epoch, rec["ts"])
            args = dict(rec.get("args") or {})
            # a caller-supplied generation wins (the agent's registry is
            # pinned gen 0 but its events span every generation — see
            # launch.py _tel_event); the registry gen is the default
            args.setdefault("gen", rec.get("gen", epoch.get("gen", 0)))
            kind = rec.get("type")
            base = {"name": rec.get("name", "?"), "pid": pid,
                    "tid": rec.get("phase", "run"), "ts": ts}
            if kind == "span":
                events.append(dict(base, ph="X",
                                   dur=rec.get("dur", 0.0) * 1e6,
                                   args=args))
            elif kind in ("counter", "gauge", "hist"):
                value = rec.get("total", rec.get("value", 0))
                events.append(dict(base, ph="C",
                                   args={rec.get("name", "?"): value}))
            else:  # event (and any forward-compat record type)
                for k in ("inc", "total", "value"):
                    if k in rec:
                        args[k] = rec[k]
                events.append(dict(base, ph="i", s="p", args=args))
    events.sort(key=lambda e: (e.get("ts", 0), e["pid"]))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"run_dir": os.path.abspath(run_dir),
                          "record_version": RECORD_VERSION}}


def _percentiles(values: list[float]) -> dict:
    s = sorted(values)
    n = len(s)
    return {"count": n, "total_s": sum(s), "p50_s": s[n // 2],
            "p95_s": s[min(n - 1, int(n * 0.95))], "max_s": s[-1]}


def run_summary(run_dir: str) -> dict:
    """Machine-readable cross-rank rollup of a run directory:

    - ``spans``: per (rank, phase, name) duration percentiles;
    - ``counters``: per (rank, phase, name) final totals;
    - ``gauges``: per (rank, phase, name) last value + count;
    - ``events``: per (rank, phase, name) occurrence counts, with the
      per-generation breakdown (the resize story at a glance);
    - ``ranks`` / ``generations``: which processes contributed.
    """
    spans: dict[str, list[float]] = {}
    counters: dict[str, float] = {}
    gauges: dict[str, dict] = {}
    events: dict[str, dict] = {}
    ranks: set[int] = set()
    gens: set[int] = set()
    for epoch, records in read_run(run_dir):
        ranks.add(int(epoch["rank"]))
        gens.add(int(epoch.get("gen", 0)))
        for rec in records:
            key = (f"rank{rec.get('rank', epoch['rank'])}/"
                   f"{rec.get('phase', 'run')}/{rec.get('name', '?')}")
            # a caller-supplied args gen wins over the registry's (the
            # agent's events span generations its registry does not)
            rec_gen = (rec.get("args") or {}).get(
                "gen", rec.get("gen", epoch.get("gen", 0)))
            gens.add(int(rec_gen))
            kind = rec.get("type")
            if kind == "span":
                spans.setdefault(key, []).append(rec.get("dur", 0.0))
            elif kind == "counter":
                # sum the INCREMENTS: running totals restart at zero on
                # every new registry (elastic respawn = new file; a
                # re-enable even appends to the same file), so neither a
                # per-file max nor the last total is the run's count
                counters[key] = counters.get(key, 0) + rec.get("inc", 0)
            elif kind in ("gauge", "hist"):
                g = gauges.setdefault(key, {"count": 0, "last": None})
                g["count"] += 1
                g["last"] = rec.get("value")
            else:
                e = events.setdefault(key, {"count": 0, "by_gen": {}})
                e["count"] += 1
                g = str(rec_gen)
                e["by_gen"][g] = e["by_gen"].get(g, 0) + 1
    return {
        "ranks": sorted(ranks), "generations": sorted(gens),
        "spans": {k: _percentiles(v) for k, v in sorted(spans.items())},
        "counters": dict(sorted(counters.items())),
        "gauges": dict(sorted(gauges.items())),
        "events": dict(sorted(events.items())),
    }
