from . import checkpoint, logging, metrics

__all__ = ["checkpoint", "logging", "metrics"]
