"""utils subpackage.

Submodules resolve LAZILY (PEP 562): the launcher agent — a
deliberately jax-free process (see launch.py's module docstring) —
imports ``utils.telemetry`` and ``utils.logging`` for gang lifecycle
events and structured logs, and an eager ``from . import checkpoint``
here would drag jax into it.  ``from .utils import <submodule>`` keeps
working everywhere (the import system loads submodules regardless);
only attribute-style access routes through ``__getattr__``.
"""

import importlib

_SUBMODULES = ("checkpoint", "compat", "debug", "faults", "logging",
               "metrics", "sentry", "telemetry", "tracing")

__all__ = list(_SUBMODULES)


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
