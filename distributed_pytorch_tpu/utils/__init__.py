from . import metrics

__all__ = ["metrics"]
