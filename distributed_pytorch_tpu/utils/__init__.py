from . import checkpoint, compat, faults, logging, metrics, sentry

__all__ = ["checkpoint", "compat", "faults", "logging", "metrics",
           "sentry"]
