"""Profiling/tracing: the subsystem the reference hand-rolls with datetime.

The reference's only tracing is ``datetime.now()`` deltas per iteration
(reference main.py:28-48, SURVEY.md section 5).  That metric survives in
utils/metrics.py; this module adds what a real framework provides on top:

- ``trace(dir)``: capture an XLA/TPU profile (TensorBoard-loadable) around
  any region — per-op device timelines, HLO, memory viewer;
- ``annotate_step(n)``: mark one training step in the trace so device time
  groups by step (the profiler's step-boundary convention);
- ``StepTimer``: cheap wall-clock step timing with percentile summary, for
  when a full profile is overkill;
- ``PhaseTimer``: named-phase wall-clock accumulation (serve.py's
  plan / dispatch / fetch / parse attribution) — so a serving ms/token
  number decomposes into where the time actually went instead of being
  one opaque wall-clock scalar (``scripts/profile_decode.py`` prints it).
"""

from __future__ import annotations

import contextlib
import time
from collections import deque
from dataclasses import dataclass, field

import jax

from . import telemetry


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a device profile into ``log_dir`` for the enclosed region."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate_step(step: int):
    """Context manager marking one train step in an active trace."""
    return jax.profiler.StepTraceAnnotation("train", step_num=step)


@dataclass
class StepTimer:
    """Wall-clock step timer with summary stats (excludes warm-up steps,
    like the reference's iter-0 exclusion at main.py:43-48)."""

    skip_first: int = 1
    _times: list[float] = field(default_factory=list)
    _seen: int = 0
    _t0: float | None = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self._seen += 1
        if self._seen > self.skip_first:
            self._times.append(dt)
        return False

    def summary(self) -> dict[str, float]:
        if not self._times:
            return {}
        ts = sorted(self._times)
        n = len(ts)
        return {
            "steps": n,
            "mean_s": sum(ts) / n,
            "p50_s": ts[n // 2],
            "p90_s": ts[min(n - 1, int(n * 0.9))],
            "max_s": ts[-1],
        }


@dataclass
class PhaseTimer:
    """Wall-clock accumulation by NAMED PHASE.

    Two entry points — ``phase(name)`` as a context manager around a code
    region, or ``add(name, seconds)`` for callers that already hold a
    ``perf_counter`` delta (hot loops that cannot afford a context-manager
    frame per segment).  A phase may receive several segments per outer
    iteration (serve.py's ``host_plan`` spans the admission machinery in
    two pieces); ``summary`` aggregates whatever landed.

    Overhead is one ``perf_counter`` pair and a deque append per segment
    — cheap enough to stay always-on in the serving loop
    (``enabled=False`` turns even that off).  Memory is BOUNDED for
    long-lived servers: exact running aggregates (count / total / max)
    are kept per phase, while the percentile window holds only the most
    recent ``window`` segments (a month-long serving process must not
    accumulate one float per block forever).

    Round 13: when the process telemetry registry is active
    (utils/telemetry.py), every segment is ALSO re-emitted as a span on
    the unified timeline under ``component`` as its phase lane — so the
    serving loop's host_plan / dispatch / fetch / host_parse / prefill
    attribution lands on the same Chrome trace as train steps, gang
    resizes, and checkpoint writes, with no serve.py changes and zero
    cost while telemetry is off (one registry read per segment)."""

    enabled: bool = True
    window: int = 4096
    component: str = "serve"
    _recent: dict = field(default_factory=dict)   # phase -> deque[float]
    _agg: dict = field(default_factory=dict)      # phase -> [n, total, max]

    @contextlib.contextmanager
    def phase(self, name: str):
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float) -> None:
        if not self.enabled:
            return
        agg = self._agg.get(name)
        if agg is None:
            agg = self._agg[name] = [0, 0.0, 0.0]
            self._recent[name] = deque(maxlen=self.window)
        agg[0] += 1
        agg[1] += seconds
        agg[2] = max(agg[2], seconds)
        self._recent[name].append(seconds)
        tel = telemetry.active()
        if tel is not None:
            # the segment just ENDED: rebase its start so the span lands
            # where the work actually ran on the shared timeline
            tel.span_at(name, time.perf_counter() - seconds, seconds,
                        phase=self.component)

    def reset(self) -> None:
        self._recent.clear()
        self._agg.clear()

    def summary(self) -> dict[str, dict[str, float]]:
        """phase -> {segments, total_s, p50_s, p95_s, max_s}, plus a
        ``"_total_s"`` key summing every phase (the attributable wall).
        ``segments``/``total_s``/``max_s`` are exact over the full run;
        the percentiles come from the last ``window`` segments."""
        out: dict = {}
        total = 0.0
        for name, (n, tot, mx) in self._agg.items():
            s = sorted(self._recent[name])
            m = len(s)
            total += tot
            out[name] = {
                "segments": n,
                "total_s": tot,
                # m == 0 only when window=0 (percentiles disabled) — a
                # phase with aggregates but an empty recent window must
                # not IndexError a drained-replica stats read
                "p50_s": s[m // 2] if m else 0.0,
                "p95_s": s[min(m - 1, int(m * 0.95))] if m else 0.0,
                "max_s": mx,
            }
        out["_total_s"] = total
        return out
