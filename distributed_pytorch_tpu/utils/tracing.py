"""Profiling/tracing: the subsystem the reference hand-rolls with datetime.

The reference's only tracing is ``datetime.now()`` deltas per iteration
(reference main.py:28-48, SURVEY.md section 5).  That metric survives in
utils/metrics.py; this module adds what a real framework provides on top:

- ``trace(dir)``: capture an XLA/TPU profile (TensorBoard-loadable) around
  any region — per-op device timelines, HLO, memory viewer;
- ``annotate_step(n)``: mark one training step in the trace so device time
  groups by step (the profiler's step-boundary convention);
- ``StepTimer``: cheap wall-clock step timing with percentile summary, for
  when a full profile is overkill.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a device profile into ``log_dir`` for the enclosed region."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate_step(step: int):
    """Context manager marking one train step in an active trace."""
    return jax.profiler.StepTraceAnnotation("train", step_num=step)


@dataclass
class StepTimer:
    """Wall-clock step timer with summary stats (excludes warm-up steps,
    like the reference's iter-0 exclusion at main.py:43-48)."""

    skip_first: int = 1
    _times: list[float] = field(default_factory=list)
    _seen: int = 0
    _t0: float | None = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self._seen += 1
        if self._seen > self.skip_first:
            self._times.append(dt)
        return False

    def summary(self) -> dict[str, float]:
        if not self._times:
            return {}
        ts = sorted(self._times)
        n = len(ts)
        return {
            "steps": n,
            "mean_s": sum(ts) / n,
            "p50_s": ts[n // 2],
            "p90_s": ts[min(n - 1, int(n * 0.9))],
            "max_s": ts[-1],
        }
