"""Activation-memory accountant: predict + measure the LM backward's
saved-residual footprint.

Two sides, one contract:

- :func:`predict_activation_bytes` — a PURE SHAPE FUNCTION from
  (TransformerConfig, batch, seq, remat, loss_impl) to peak saved-residual
  bytes per device.  The inventory below is the jaxpr-level census of the
  repo's own stack (models/transformer.py block + ops/losses.py head),
  itemized per layer and per mode — at float32 it reproduces the census
  byte-for-byte for the dense-MLP flash stack (tests/test_memory.py pins
  <=10%).
- :func:`saved_residual_census` — the measurement: JAX's
  ``saved_residuals`` over the actual loss function (exact, CPU-friendly,
  nothing executed), with parameter/argument entries filtered out so only
  true activations count.

Third verification lane: utils/monitor.py ``record_memory`` watermarks
(``device_peak_bytes``) on a live backend, with the prediction feeding the
``default_rules`` device-memory SLO ceiling.

Why it matters (round 17 / ISSUE 14): on a real TPU, activation memory is
what caps per-device batch size, and batch size is the denominator every
gradient-sync strategy amortizes against — so the autotuner's chooser
(parallel/autotune.py) prices remat/loss_impl rungs with exactly this
predictor against a ``memory_budget_bytes``.

Known approximations (documented, not silent): GQA stacks (kv_heads <
n_heads) count the post-repeat H-sized flash residuals (slight
overcount of the pre-repeat k/v einsum outputs); MoE layers are counted
as dense-MLP layers of the same d_ff; ring attention (sp > 1) is counted
as flash over the local sequence shard.  bfloat16 compute counts the
activation-dtype items at 2 bytes and the always-f32 items (norm
statistics, flash lse/accumulators, the f32 head) at 4.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

try:  # public on modern runtimes
    from jax.ad_checkpoint import saved_residuals as _saved_residuals
except ImportError:  # 0.4.x exposes it under _src only
    from jax._src.ad_checkpoint import saved_residuals as _saved_residuals

F32 = 4  # bytes; items the stack keeps in f32 regardless of compute dtype
I32 = 4


@dataclass(frozen=True)
class Residual:
    shape: tuple
    dtype: str
    bytes: int
    src: str


def saved_residual_census(fn: Callable, *args: Any) -> dict:
    """Jaxpr saved-residual census of ``fn(*args)`` (nothing is executed;
    args may be ShapeDtypeStructs).  Entries that are function ARGUMENTS
    (params, batches — held live by the caller anyway) and zero-byte
    float0 tangent placeholders are excluded, so ``bytes`` is the
    activation residual footprint the backward adds on top of the inputs.
    """
    residuals = []
    total = 0
    for aval, why in _saved_residuals(fn, *args):
        dt = str(aval.dtype)
        if "from the argument" in why or dt.startswith("[("):
            continue
        nbytes = int(np.prod(aval.shape)) * aval.dtype.itemsize if \
            aval.shape else aval.dtype.itemsize
        residuals.append(Residual(tuple(aval.shape), dt, nbytes, why))
        total += nbytes
    return {"bytes": total, "residuals": residuals}


def find_residuals(census: dict, *, min_bytes: int = 0,
                   dtype: str | None = None, last_dim: int | None = None):
    """Filter a census's residual list (the logits-pin helper: e.g.
    ``find_residuals(c, dtype='float32', last_dim=vocab)``)."""
    out = []
    for r in census["residuals"]:
        if r.bytes < min_bytes:
            continue
        if dtype is not None and r.dtype != dtype:
            continue
        if last_dim is not None and (not r.shape or r.shape[-1] != last_dim):
            continue
        out.append(r)
    return out


def predict_activation_bytes(
    model,                      # models/transformer.TransformerConfig
    *,
    batch: int,                 # per-device batch rows
    seq: int,                   # GLOBAL sequence length
    remat: str = "none",
    loss_impl: str = "dense",
    loss_chunk: int | None = None,
    dtype_bytes: int = 4,       # compute dtype itemsize (4 = f32)
    tp: int = 1,
    sp: int = 1,
) -> int:
    """Peak saved-residual activation bytes per device for one backward
    of the LM loss — the itemized census of this repo's stack as a pure
    shape function.  See module docstring for the per-mode inventory and
    the documented approximations."""
    if remat not in ("none", "full", "selective"):
        raise ValueError(f"unknown remat {remat!r}")
    if loss_impl not in ("dense", "chunked"):
        raise ValueError(f"unknown loss_impl {loss_impl!r}")
    a = dtype_bytes
    d, hd = model.d_model, model.head_dim
    h = model.n_heads // max(tp, 1)
    f = model.ff // max(tp, 1)
    t = seq // max(sp, 1)
    v = model.vocab_size
    bt = batch * t
    n_layers = model.n_layers

    if remat == "none":
        # the full block inventory: 6 F-sized MLP residuals (gate, up,
        # silu pair, product, matmul operands), 8 D-sized stream/norm
        # residuals, 5 H*hd-sized attention projections (flash q/k/v/o
        # + the pre-reshape layout copy), the flash lse (bh, 8, t),
        # rotary cos/sin tables (4 each for q and k), and the rms_norm
        # rsqrt statistics
        per_layer = (6 * bt * f * a
                     + 8 * bt * d * a
                     + 5 * bt * h * hd * a
                     + batch * h * 8 * t * F32          # flash lse
                     + 8 * t * (hd // 2) * F32          # rotary tables
                     + 4 * bt * F32                     # rms rsqrt stats
                     + 2 * d * F32 + 4 * 16 * I32)      # misc tiny
    else:
        # jax.checkpoint: only each block's input carry (+ the tiny
        # rotary freq vectors) survives to the backward ...
        per_layer = bt * d * a + 2 * (hd // 2) * F32
        if remat == "selective":
            # ... plus the policy-saved flash (o, lse) pair
            per_layer += bt * h * hd * a + batch * h * 8 * t * F32

    # head + boundaries (fixed part): 4 D-sized residuals (embed output,
    # final stream carry, final-norm f32 input, normed h) ...
    fixed = 4 * bt * d * a + 2 * bt * F32 + d * F32 + 4
    if remat != "none":
        fixed += t * I32  # pos becomes a saved checkpoint input
    if loss_impl == "dense":
        # ... the (B, T, V) f32 softmax residual and the transposed
        # embedding, plus masked_ce's index/mask scalars
        fixed += (bt * v * F32 + d * v * F32
                  + 2 * bt + 2 * bt * I32 + 2 * bt * F32)
    else:
        # ... the streamed head keeps only its (B*T,) logsumexp + the
        # integer targets — nothing V-sized
        fixed += bt * F32 + 2 * bt * I32 + 2 * bt * F32
    return n_layers * per_layer + fixed


def predict_recompute_bytes(
    model,
    *,
    batch: int,
    seq: int,
    remat: str = "none",
    loss_impl: str = "dense",
    dtype_bytes: int = 4,
    tp: int = 1,
    sp: int = 1,
) -> int:
    """Activation bytes the backward must RE-produce under this (remat,
    loss_impl) — the compute half of the memory trade, priced by the
    autotuner at the profile's calibrated ``recompute_s_per_byte`` (the
    ``quant_s_per_byte`` precedent: wire/memory saved vs compute spent,
    both in seconds, on THIS host).

    - ``remat='full'`` re-runs each block forward: everything the
      no-remat census saved minus what full still saves.
    - ``remat='selective'`` additionally skips the flash kernel (its
      (o, lse) residuals are policy-saved, so only the projections +
      MLP re-run): subtract the flash share of the block inventory.
    - ``loss_impl='chunked'`` re-materializes the chunk logits once in
      the backward: one (B, T, V) f32 pass that dense paid for in
      memory instead.
    """
    none_b = predict_activation_bytes(
        model, batch=batch, seq=seq, remat="none", loss_impl=loss_impl,
        dtype_bytes=dtype_bytes, tp=tp, sp=sp)
    saved_b = predict_activation_bytes(
        model, batch=batch, seq=seq, remat=remat, loss_impl=loss_impl,
        dtype_bytes=dtype_bytes, tp=tp, sp=sp)
    recompute = none_b - saved_b  # 0 when remat == "none"
    if remat == "selective":
        h = model.n_heads // max(tp, 1)
        t = seq // max(sp, 1)
        bt = batch * t
        # the kernel's own work (softmax over 5 H*hd-sized operands) is
        # NOT re-run — only its already-counted (o, lse) are kept, so
        # drop the remaining flash share from the recompute bill
        recompute -= model.n_layers * (
            4 * bt * h * model.head_dim * dtype_bytes)
    if loss_impl == "chunked":
        recompute += batch * (seq // max(sp, 1)) * model.vocab_size * F32
    return max(recompute, 0)
