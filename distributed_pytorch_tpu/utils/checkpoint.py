"""Checkpoint / resume — a capability the reference lacks entirely.

The reference never saves anything: no ``state_dict``/``torch.save`` call
exists and results live only in stdout (SURVEY.md section 5).  This module
adds atomic whole-training-state checkpointing: params, per-replica
BatchNorm statistics, optimizer state (SGD momentum buffers), the step
counter and the epoch, keyed by pytree path into one ``.npz`` per epoch.

Design notes (TPU-native):
- arrays are fetched with ``jax.device_get`` (gathers replicated/sharded
  leaves to host) and restored with the same placement the Trainer uses at
  init, so a resumed run is sharding-identical to a fresh one;
- writes are atomic (tmp file + rename) so a preempted save never corrupts
  the latest checkpoint — preemption is the normal failure mode on TPU pods;
- only process 0 writes (params/opt-state are replicated across hosts);
  every process restores from the shared directory;
- ``async_write``: the device->host fetch stays synchronous (it is a
  collective and must see a settled device state), but serialization and
  disk IO run on a background thread so training resumes immediately —
  the orbax-style overlap of checkpoint writing with compute.  The writer
  thread is non-daemonic (a clean interpreter exit flushes it) and each
  save joins the previous write first (no interleaved files).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import zlib

import jax
import numpy as np

from ..parallel.mesh import replicated
from . import faults, monitor, telemetry

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.npz$")


def _tel_span(name: str, t0: float, **args) -> None:
    """Checkpoint IO on the unified timeline (round 13): every
    save/restore/reshard lands as a span in the 'ckpt' lane —
    duration + bytes — when the process registry is active; one
    registry read otherwise.  Round 15 rides a host-RSS gauge along:
    checkpoint IO is where host memory peaks (a full host copy of the
    training state is in flight), so the memory lane samples here."""
    tel = telemetry.active()
    if tel is not None:
        tel.span_at(name, t0, time.perf_counter() - t0, phase="ckpt",
                    **args)
        tel.gauge("host_rss_bytes", monitor.host_rss_bytes(),
                  phase="mem", at=name)


class CorruptCheckpointError(RuntimeError):
    """A checkpoint failed integrity verification (checksum mismatch,
    truncated archive, missing leaves).  Restore paths catch this,
    QUARANTINE the offending generation (rename to ``*.corrupt`` so it
    never lists again) and fall back to the previous one — a bad shard
    must cost one checkpoint interval, not the run."""


def _crc(arr: np.ndarray) -> int:
    """crc32 over dtype/shape/bytes — cheap (GB/s-scale) per-leaf
    integrity tag, written into the checkpoint meta at save and verified
    at load.  Not cryptographic; the threat is bit rot and truncation,
    not an adversary."""
    h = zlib.crc32(str(arr.dtype).encode())
    h = zlib.crc32(str(arr.shape).encode(), h)
    return zlib.crc32(np.ascontiguousarray(arr).tobytes(), h)


def _verify_flat(flat: dict, meta: dict, path: str) -> None:
    """Check every leaf against the meta's checksum table.  Checkpoints
    from before the table existed (no ``__checksums__``) pass — there is
    nothing to verify against."""
    sums = meta.get("__checksums__")
    if sums is None:
        return
    missing = [k for k in sums if k not in flat]
    if missing:
        raise CorruptCheckpointError(
            f"checkpoint {path} is missing {len(missing)} leaves "
            f"(e.g. {missing[:3]})")
    bad = [k for k, want in sums.items() if _crc(flat[k]) != want]
    if bad:
        raise CorruptCheckpointError(
            f"checkpoint {path} failed checksum verification at "
            f"{len(bad)} leaves (e.g. {bad[:3]})")


def _load_npz_verified(path: str) -> tuple[dict, dict]:
    """Read one whole-tree npz + embedded meta, verifying integrity;
    raises CorruptCheckpointError for unreadable/truncated archives and
    checksum mismatches."""
    try:
        with np.load(path) as z:
            flat = {k: z[k] for k in z.files}
        meta = json.loads(bytes(flat.pop("__meta__").tobytes()).decode())
    except CorruptCheckpointError:
        raise
    except Exception as e:  # zipfile/EOF/pickle/json: unreadable archive
        raise CorruptCheckpointError(
            f"checkpoint {path} is unreadable: {e}") from e
    _verify_flat(flat, meta, path)
    return flat, meta


def _quarantine(path: str, err: Exception, log=print) -> None:
    """Move a corrupt checkpoint aside (``<path>.corrupt``, uniquified on
    collision) so it stops listing as restorable; never raises (the
    fallback restore must proceed even when the rename loses a race with
    a concurrent prune).  Uniquifying matters for recurring corruption:
    a generation index gets REUSED after a rollback re-saves it, and a
    directory rename onto an existing non-empty ``*.corrupt`` would
    ENOTEMPTY-fail and leave the bad generation listed forever."""
    dest = path + ".corrupt"
    for n in range(1, 100):
        if not os.path.exists(dest):
            break
        dest = f"{path}.corrupt.{n}"
    try:
        os.replace(path, dest)
    except OSError:
        pass
    if log:
        log(f"[checkpoint] quarantined corrupt checkpoint {path}: {err}")


class _AsyncWriter:
    """At most one in-flight background write; join-before-submit.

    Shared per directory (module registry below) so EVERY checkpointer
    instance pointing at the same path serializes against the same
    in-flight write — a reader constructed after a writer still waits for
    the pending publish.  A background failure is captured and re-raised
    from the next wait()/submit(), so a failed save cannot masquerade as
    success (the synchronous path's behavior)."""

    def __init__(self):
        self._t: threading.Thread | None = None
        self._exc: BaseException | None = None
        # Writers are shared across checkpointer instances via the module
        # registry, so submit/wait can race from different threads; all
        # _t/_exc handoff happens under this lock.
        self._lock = threading.Lock()

    def submit(self, fn) -> None:
        with self._lock:
            self._wait_locked()

            def run():
                try:
                    fn()
                except BaseException as e:  # noqa: BLE001 — re-raised in wait
                    self._exc = e

            self._t = threading.Thread(target=run)  # non-daemon: exit flushes
            self._t.start()

    def wait(self) -> None:
        with self._lock:
            self._wait_locked()

    def _wait_locked(self) -> None:
        if self._t is not None:
            self._t.join()
            self._t = None
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise RuntimeError("background checkpoint write failed") from exc


_WRITERS: dict[str, _AsyncWriter] = {}
_WRITERS_LOCK = threading.Lock()


def _writer_for(directory: str) -> _AsyncWriter:
    key = os.path.abspath(directory)
    with _WRITERS_LOCK:
        return _WRITERS.setdefault(key, _AsyncWriter())


def _fetch(leaf) -> np.ndarray:
    """Materialize a leaf on host.  Replicated/single-host arrays are a plain
    device_get; multi-host sharded arrays (per-replica BN state) need a
    cross-host allgather, which every process must enter (collective).

    The result is an OWNED copy (``np.array(copy=True)``): on the CPU
    backend ``device_get`` can return a zero-copy view of the device
    buffer, and training steps DONATE those buffers — an aliased fetch
    handed to the async writer would serialize whatever the runtime
    reused the buffer for by the time the background thread runs."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.array(multihost_utils.process_allgather(
            leaf, tiled=True), copy=True)
    return np.array(jax.device_get(leaf), copy=True)


def _flatten(tree) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = jax.tree_util.keystr(path)
        flat[key] = _fetch(leaf)
    return flat


def _unflatten_like(tree, flat: dict[str, np.ndarray], prefix: str):
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(tree)
    leaves = []
    for path, leaf in leaves_with_path:
        key = prefix + jax.tree_util.keystr(path)
        if key not in flat:
            raise KeyError(f"checkpoint missing leaf {key!r}")
        arr = flat[key]
        if arr.shape != leaf.shape:
            raise ValueError(
                f"checkpoint leaf {key!r} has shape {arr.shape}, "
                f"model expects {leaf.shape}")
        leaves.append(arr.astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _place_like(like: dict, flat: dict) -> dict:
    """Unflatten ``flat`` into ``like``'s structure and re-place every leaf
    with the template leaf's sharding (restores are layout-identical to a
    fresh init)."""
    out = {}
    for name, tree in like.items():
        restored = _unflatten_like(tree, flat, name)
        out[name] = jax.tree.map(
            lambda new, old: (jax.device_put(new, old.sharding)
                              if isinstance(old, jax.Array) else new),
            restored, tree)
    return out


def _list_ckpts(directory: str) -> list[tuple[int, str]]:
    out = []
    for name in os.listdir(directory):
        m = _CKPT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def _atomic_write(directory: str, index: int, payload: dict,
                  meta: dict, keep: int) -> str:
    """Embed meta + per-leaf checksums, write ckpt_<index>.npz
    atomically, prune old ones."""
    t0 = time.perf_counter()
    nbytes = sum(v.nbytes for v in payload.values())
    payload = dict(payload)
    meta = dict(meta, __checksums__={k: _crc(v) for k, v in
                                     payload.items()})
    payload["__meta__"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    path = os.path.join(directory, f"ckpt_{index}.npz")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)  # atomic publish
    faults.maybe_corrupt_checkpoint(path)  # chaos hook (no-op unplanned)
    for _, old in _list_ckpts(directory)[:-keep]:
        os.remove(old)
    _tel_span("ckpt_save", t0, step=int(index), bytes=int(nbytes),
              fmt="npz")
    return path


class Checkpointer:
    """Epoch-granularity checkpoints in ``directory`` (ckpt_<epoch>.npz)."""

    def __init__(self, directory: str, *, keep: int = 3,
                 async_write: bool = False):
        self.directory = directory
        self.keep = keep
        self.async_write = async_write
        self._writer = _writer_for(directory)
        os.makedirs(directory, exist_ok=True)

    def wait(self) -> None:
        """Block until any in-flight background write has been published."""
        self._writer.wait()

    # -- save -------------------------------------------------------------
    def save(self, trainer, epoch: int) -> str | None:
        """Snapshot the trainer after ``epoch`` completed epochs.

        Every process must call this (the fetch of cross-host-sharded BN
        state is a collective); only process 0 writes the file."""
        payload: dict[str, np.ndarray] = {}
        for prefix, tree in (("params", trainer.params),
                             ("state", trainer.state),
                             ("opt", trainer.opt_state)):
            for k, v in _flatten(tree).items():
                payload[prefix + k] = v
        if jax.process_index() != 0:
            return None
        meta = {"epoch": epoch, "step": trainer._step,
                "model": trainer.cfg.model, "strategy": trainer.cfg.strategy,
                "n_replicas": trainer.n_replicas,
                # mesh trainers stack BN state with a leading replica axis;
                # the single-device trainer stores it bare — restore needs
                # to know which layout the saved arrays use
                "stacked_state": trainer.mesh is not None}
        path = os.path.join(self.directory, f"ckpt_{epoch}.npz")
        if self.async_write:
            self._writer.submit(lambda: _atomic_write(
                self.directory, epoch, payload, meta, self.keep))
            return path
        return _atomic_write(self.directory, epoch, payload, meta, self.keep)

    # -- restore ----------------------------------------------------------
    def list(self) -> list[tuple[int, str]]:
        self._writer.wait()  # reads must see the settled directory
        return _list_ckpts(self.directory)

    def latest(self) -> tuple[int, str] | None:
        ckpts = self.list()
        return ckpts[-1] if ckpts else None

    def maybe_restore(self, trainer) -> int:
        """Restore the latest checkpoint into ``trainer`` if one exists;
        returns the epoch to resume from (0 = fresh start).

        Cross-topology: a checkpoint written on a different mesh size (or
        the single-device trainer) restores onto this trainer's topology.
        Params/optimizer state are replicated, so only the replica-stacked
        BN state needs resharding — rank 0's running stats are taken as
        authoritative and re-stacked to the new replica count (the torch
        DDP buffer-broadcast convention; exact per-replica stats are kept
        when the topology matches).

        Integrity: each candidate verifies against its embedded per-leaf
        checksums; a corrupt/truncated generation is QUARANTINED
        (renamed ``*.corrupt``) and restore falls back to the previous
        one instead of crashing mid-resume."""
        t0 = time.perf_counter()
        got = None
        for epoch, path in reversed(self.list()):
            try:
                flat, meta = _load_npz_verified(path)
            except CorruptCheckpointError as e:
                _quarantine(path, e)
                continue
            got = (epoch, flat, meta)
            break
        if got is None:
            return 0
        epoch, flat, meta = got
        if meta["model"] != trainer.cfg.model:
            raise ValueError(
                f"checkpoint is for model {meta['model']}, "
                f"trainer is {trainer.cfg.model}")
        params = _unflatten_like(trainer.params, flat, "params")
        # Legacy checkpoints (no stacked_state key): mesh presence — and
        # hence the stacked BN layout — follows the strategy exactly
        # (Trainer keeps the mesh iff strategy.needs_mesh; only 'none'
        # doesn't), including 1-device meshes where n_replicas==1 stacks.
        saved_stacked = meta.get("stacked_state", meta["strategy"] != "none")
        if (meta["n_replicas"] != trainer.n_replicas
                or saved_stacked != (trainer.mesh is not None)):
            for k in [k for k in flat if k.startswith("state")]:
                v = flat[k]
                if saved_stacked:
                    v = v[0]  # rank 0 authoritative
                if trainer.mesh is not None:
                    v = np.broadcast_to(
                        v[None], (trainer.n_replicas,) + v.shape)
                flat[k] = v
        state = _unflatten_like(trainer.state, flat, "state")
        opt_state = _unflatten_like(trainer.opt_state, flat, "opt")
        if trainer.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep = replicated(trainer.mesh)
            # the data axis may be factored (hierarchical: ('dcn', 'ici'))
            shd = NamedSharding(trainer.mesh, P(trainer.data_axes))
            params = jax.device_put(params, rep)
            opt_state = jax.device_put(opt_state, rep)
            state = jax.device_put(state, shd)
        trainer.params, trainer.state, trainer.opt_state = (
            params, state, opt_state)
        trainer._step = meta["step"]
        _tel_span("ckpt_restore", t0, step=int(meta["step"]),
                  bytes=int(sum(v.nbytes for v in flat.values())),
                  fmt="npz")
        return meta["epoch"]


class PyTreeCheckpointer:
    """Generic step-granularity checkpoints for named pytrees (the LM-side
    sibling of ``Checkpointer``, which is wedded to the VGG trainer's
    params/BN-state/opt triple).

    ``save`` stores any dict of pytrees + JSON-able meta; ``restore`` needs
    a template dict with the same structure (e.g. a freshly initialized
    trainer's state) and re-places every leaf with the template leaf's
    sharding, so a resumed run is layout-identical to a fresh one.
    """

    def __init__(self, directory: str, *, keep: int = 3,
                 async_write: bool = False):
        self.directory = directory
        self.keep = keep
        self.async_write = async_write
        self._writer = _writer_for(directory)
        os.makedirs(directory, exist_ok=True)

    def wait(self) -> None:
        """Block until any in-flight background write has been published."""
        self._writer.wait()

    def save(self, trees: dict, step: int, meta: dict | None = None):
        payload: dict[str, np.ndarray] = {}
        for name, tree in trees.items():
            for k, v in _flatten(tree).items():
                payload[name + k] = v
        if jax.process_index() != 0:
            return None
        full_meta = dict(meta or {}, step=step)
        path = os.path.join(self.directory, f"ckpt_{step}.npz")
        if self.async_write:
            self._writer.submit(lambda: _atomic_write(
                self.directory, step, payload, full_meta, self.keep))
            return path
        return _atomic_write(self.directory, step, payload, full_meta,
                             self.keep)

    def list(self) -> list[tuple[int, str]]:
        self._writer.wait()  # reads must see the settled directory
        return _list_ckpts(self.directory)

    def restore(self, like: dict) -> tuple[dict, dict] | None:
        """Latest VERIFIED checkpoint restored into ``like``'s
        structure/shardings; returns (trees, meta) or None when none
        exists.  Corrupt generations are quarantined and skipped —
        restore falls back to the newest one that passes its
        checksums."""
        t0 = time.perf_counter()
        for _, path in reversed(self.list()):
            try:
                flat, meta = _load_npz_verified(path)
            except CorruptCheckpointError as e:
                _quarantine(path, e)
                continue
            out = _place_like(like, flat), meta
            _tel_span("ckpt_restore", t0, step=int(meta.get("step", -1)),
                      bytes=int(sum(v.nbytes for v in flat.values())),
                      fmt="npz")
            return out
        return None


# ---------------------------------------------------------------------------
# Sharded (per-process) checkpoints
# ---------------------------------------------------------------------------

def _slices_to_json(index, shape) -> list[list[int]]:
    return [[s.start or 0, s.stop if s.stop is not None else dim]
            for s, dim in zip(index, shape)]


def _overlap(target: list[tuple[int, int]],
             saved: list[tuple[int, int]]) -> tuple | None:
    """Intersection of two global index boxes as (target-local slices,
    saved-local slices), or None when they do not overlap.  The
    per-dimension arithmetic behind the cross-topology reshard: a saved
    shard's bytes land in a new-mesh shard exactly on the box overlap,
    with both sides re-based to their own origins."""
    tgt_sl, src_sl = [], []
    for (ta, tb), (sa, sb) in zip(target, saved):
        lo, hi = max(ta, sa), min(tb, sb)
        if lo >= hi:
            return None
        tgt_sl.append(slice(lo - ta, hi - ta))
        src_sl.append(slice(lo - sa, hi - sa))
    return tuple(tgt_sl), tuple(src_sl)


def _cut_target(key: str, entries: list, read,
                target: list[tuple[int, int]], dtype) -> np.ndarray:
    """Rebuild ONE target shard from the saved entries that intersect it
    — the memory-efficient redistribution step (arXiv 2112.01075): only
    overlapping chunks are read, and nothing the size of the full array
    is ever allocated.  Saved slices never overlap each other
    (replica_id-0 dedupe), so coverage is verified by element count."""
    shape = tuple(b - a for a, b in target)
    out = None
    covered = 0
    for e in entries:
        if e["slices"] is None:
            # leaf saved as one whole host value: the target region is a
            # plain cut of it
            whole = np.asarray(read(e))
            return np.ascontiguousarray(
                whole[tuple(slice(a, b) for a, b in target)]).astype(
                    dtype, copy=False)
        hit = _overlap(target, [tuple(s) for s in e["slices"]])
        if hit is None:
            continue
        tgt_sl, src_sl = hit
        chunk = read(e)
        if out is None:
            out = np.zeros(shape, chunk.dtype)
        out[tgt_sl] = chunk[src_sl]
        covered += int(np.prod([s.stop - s.start for s in tgt_sl]))
    size = int(np.prod(shape)) if shape else 1
    if out is None or covered != size:
        raise ValueError(
            f"leaf {key!r}: saved shards cover {covered} of {size} "
            f"target elements — checkpoint incomplete for this layout "
            f"(missing process files?)")
    return out


def _assemble(key: str, entries: list, read, shape: tuple) -> np.ndarray:
    """Rebuild a full array on host from its saved slice entries (the
    cross-layout restore fallback); verifies complete coverage by element
    count (saved slices never overlap: replica_id-0 dedupe keeps exactly
    one copy of each global element).  A ``slices=None`` entry is a whole
    array saved as a plain host value — full coverage by itself."""
    for e in entries:
        if e["slices"] is None:
            return np.asarray(read(e)).reshape(shape)
    first = read(entries[0])
    full = np.zeros(shape, first.dtype)
    covered = 0
    for e in entries:
        sl = tuple(slice(a, b) for a, b in e["slices"])
        chunk = read(e)
        full[sl] = chunk
        covered += chunk.size
    if covered != full.size:
        raise ValueError(
            f"leaf {key!r}: saved shards cover {covered} of {full.size} "
            f"elements — checkpoint incomplete (missing process files?)")
    return full


class ShardedCheckpointer:
    """Per-shard checkpoints: every process writes ONLY its addressable
    array shards (no cross-host allgather, no full-tree host copy), so
    checkpoint memory/IO scales with the per-host shard size — the path for
    FSDP/tensor-sharded models larger than one host's memory.

    Layout: ``directory/ckpt_<step>/`` holds one ``proc<k>.npz`` (shard
    data) + ``proc<k>.idx.json`` (per-shard global-slice index) per
    process, and ``meta.json`` (written last by process 0 — its presence
    marks the checkpoint complete).  Replicated leaves are deduplicated by
    ``shard.replica_id == 0``, so each unique byte is written exactly once
    across the job.

    Restore matches each template shard's global slice against the saved
    index — an exact hit moves only that shard's bytes (the fast path, IO
    proportional to the per-host shard size).  A template whose layout
    differs from the save (resharded mesh, or optimizer state whose
    GSPMD-propagated sharding drifted between init and post-step) falls
    back per leaf to assembling the full array from the saved slices on
    host and cutting the needed shards — correct for any layout, at the
    cost of one host-side copy of that leaf.
    """

    def __init__(self, directory: str, *, keep: int = 3):
        self.directory = directory
        self.keep = keep
        # accounting of the newest restore/load_resharded (the reshard
        # tests pin full_assemblies == 0 on the resharding path)
        self.last_reshard_stats: dict | None = None
        os.makedirs(directory, exist_ok=True)

    def wait(self) -> None:
        """No-op: shard writes are synchronous (interface symmetry with the
        async whole-tree checkpointer)."""

    # -- save -------------------------------------------------------------
    def save(self, trees: dict, step: int, meta: dict | None = None) -> str:
        t0 = time.perf_counter()
        pid = jax.process_index()
        ckpt_dir = os.path.join(self.directory, f"ckpt_{step}")
        os.makedirs(ckpt_dir, exist_ok=True)
        if pid == 0:
            # Re-saving an existing step: drop the completion marker FIRST,
            # so a crash mid-rewrite cannot leave a mixed old/new checkpoint
            # that still lists as complete.
            try:
                os.remove(os.path.join(ckpt_dir, "meta.json"))
            except FileNotFoundError:
                pass
        if jax.process_count() > 1:
            # No process may overwrite shard files until the marker is gone.
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("sharded_ckpt_unmark")
        payload: dict[str, np.ndarray] = {}
        index: dict[str, list] = {}
        for name, tree in trees.items():
            for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
                key = name + jax.tree_util.keystr(path)
                if not isinstance(leaf, jax.Array):
                    if pid == 0:
                        arr = np.asarray(leaf)
                        payload[f"{key}#0"] = arr
                        index[key] = [{"npz": f"{key}#0", "slices": None,
                                       "shape": list(arr.shape),
                                       "crc": _crc(arr)}]
                    continue
                entries = []
                for j, shard in enumerate(leaf.addressable_shards):
                    if shard.replica_id != 0:
                        continue  # dedupe replicated copies
                    npz_key = f"{key}#{j}"
                    data = np.asarray(shard.data)
                    payload[npz_key] = data
                    entries.append({
                        "npz": npz_key,
                        "slices": _slices_to_json(shard.index, leaf.shape),
                        "shape": list(leaf.shape),
                        "crc": _crc(data),
                    })
                if entries:
                    index[key] = entries
        tmp = os.path.join(ckpt_dir, f"proc{pid}.npz.tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, os.path.join(ckpt_dir, f"proc{pid}.npz"))
        with open(os.path.join(ckpt_dir, f"proc{pid}.idx.json.tmp"),
                  "w") as f:
            json.dump(index, f)
        os.replace(os.path.join(ckpt_dir, f"proc{pid}.idx.json.tmp"),
                   os.path.join(ckpt_dir, f"proc{pid}.idx.json"))
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("sharded_ckpt_save")
        if pid == 0:
            with open(os.path.join(ckpt_dir, "meta.json.tmp"), "w") as f:
                json.dump(dict(meta or {}, step=step,
                               nprocs=jax.process_count()), f)
            os.replace(os.path.join(ckpt_dir, "meta.json.tmp"),
                       os.path.join(ckpt_dir, "meta.json"))
            self._prune()
        _tel_span("ckpt_save", t0, step=int(step),
                  bytes=int(sum(v.nbytes for v in payload.values())),
                  fmt="sharded")
        return ckpt_dir

    def _prune(self) -> None:
        for step, path in self.list()[:-self.keep]:
            import shutil
            shutil.rmtree(path, ignore_errors=True)

    # -- restore ----------------------------------------------------------
    def list(self) -> list[tuple[int, str]]:
        out = []
        for name in os.listdir(self.directory):
            full = os.path.join(self.directory, name)
            if (name.startswith("ckpt_") and name[5:].isdigit()
                    and os.path.exists(os.path.join(full, "meta.json"))):
                out.append((int(name[5:]), full))
        return sorted(out)

    def restore(self, like: dict) -> tuple[dict, dict] | None:
        """Latest complete VERIFIED checkpoint restored into ``like``'s
        structure, each leaf rebuilt shard-by-shard onto the template's
        devices; returns (trees, meta) or None when no checkpoint
        exists.  A generation with a corrupt shard file (per-shard crc
        mismatch, truncated npz) is quarantined (renamed ``*.corrupt``)
        and restore falls back to the previous generation."""
        for _, ckpt_dir in reversed(self.list()):
            try:
                return self._restore_dir(ckpt_dir, like)
            except CorruptCheckpointError as e:
                _quarantine(ckpt_dir, e)
        return None

    def load_resharded(self, like: dict) -> tuple[dict, dict] | None:
        """Cross-topology restore (round 12, the elastic-resize loader):
        map the SAVED shard layout onto ``like``'s — possibly different
        — mesh per leaf, following the memory-efficient redistribution
        recipe (arXiv 2112.01075).

        Same verification/quarantine/fall-back contract as ``restore``
        and BITWISE the same values (test-pinned), but the cross-layout
        path never materializes a full array on any host: each target
        shard is cut from exactly the saved chunks that intersect it
        (``_cut_target``), chunks are dropped once their leaf is placed,
        and a layout that matches exactly still moves only its own
        shard's bytes (the fast path).  So host memory is bounded by the
        template's addressable shards plus ONE in-flight leaf's
        overlapping chunks — the property that lets a 2-host gang
        restore a checkpoint written by 4 hosts (or vice versa) without
        any host holding the 4-host model.  Accounting lands in
        ``self.last_reshard_stats`` (exact_hits / intersections /
        full_assemblies — pinned 0 here — read_bytes,
        peak_leaf_read_bytes)."""
        for _, ckpt_dir in reversed(self.list()):
            try:
                return self._restore_dir(ckpt_dir, like, reshard=True)
            except CorruptCheckpointError as e:
                _quarantine(ckpt_dir, e)
        return None

    def _restore_dir(self, ckpt_dir: str, like: dict,
                     reshard: bool = False) -> tuple[dict, dict]:
        t_restore = time.perf_counter()
        # JSON metadata is in the same bit-rot threat model as the shard
        # payloads: a corrupt meta/index must fail THIS generation (and
        # fall back), not crash the resume
        def read_json(path: str):
            try:
                with open(path) as f:
                    return json.load(f)
            except (OSError, ValueError, UnicodeDecodeError) as e:
                raise CorruptCheckpointError(
                    f"checkpoint metadata {path} is unreadable: {e}") from e

        meta = read_json(os.path.join(ckpt_dir, "meta.json"))
        # Merge every process's shard index; load npz files lazily.
        index: dict[str, list] = {}
        files: dict[int, np.lib.npyio.NpzFile] = {}
        for k in range(meta.get("nprocs", 1)):
            idx_path = os.path.join(ckpt_dir, f"proc{k}.idx.json")
            if not os.path.exists(idx_path):
                continue
            for key, entries in read_json(idx_path).items():
                for e in entries:
                    e["proc"] = k
                index.setdefault(key, []).extend(entries)
            npz_path = os.path.join(ckpt_dir, f"proc{k}.npz")
            try:
                files[k] = np.load(npz_path)
            except Exception as e:  # truncated/unreadable archive
                raise CorruptCheckpointError(
                    f"shard file {npz_path} is unreadable: {e}") from e

        def lookup(key: str):
            if key not in index:
                raise KeyError(f"checkpoint missing leaf {key!r}")
            return index[key]

        loaded: dict[tuple, np.ndarray] = {}
        stats = {"leaves": 0, "exact_hits": 0, "intersections": 0,
                 "full_assemblies": 0, "read_bytes": 0,
                 "peak_leaf_read_bytes": 0}

        def read(e) -> np.ndarray:
            """npz access decompresses on EVERY __getitem__; memoize so a
            replicated leaf is not decompressed once per template shard.
            First load verifies the entry's crc (written at save) — a
            flipped bit in any consumed shard fails THIS generation."""
            k = (e["proc"], e["npz"])
            if k not in loaded:
                try:
                    arr = files[e["proc"]][e["npz"]]
                except Exception as err:
                    raise CorruptCheckpointError(
                        f"shard {e['npz']} of proc{e['proc']} in "
                        f"{ckpt_dir} is unreadable: {err}") from err
                want = e.get("crc")
                if want is not None and _crc(arr) != want:
                    raise CorruptCheckpointError(
                        f"shard {e['npz']} of proc{e['proc']} in "
                        f"{ckpt_dir} failed checksum verification")
                loaded[k] = arr
                stats["read_bytes"] += arr.nbytes
            return loaded[k]

        try:
            out = {}
            for name, tree in like.items():
                leaves_with_path, treedef = (
                    jax.tree_util.tree_flatten_with_path(tree))
                new_leaves = []
                for path, leaf in leaves_with_path:
                    key = name + jax.tree_util.keystr(path)
                    entries = lookup(key)
                    stats["leaves"] += 1
                    leaf_read0 = stats["read_bytes"]
                    saved_shape = entries[0].get("shape")
                    if (saved_shape is not None
                            and tuple(saved_shape) != tuple(
                                np.shape(leaf))):
                        raise ValueError(
                            f"checkpoint leaf {key!r} has shape "
                            f"{tuple(saved_shape)}, template expects "
                            f"{tuple(np.shape(leaf))}")
                    if not isinstance(leaf, jax.Array):
                        new_leaves.append(_assemble(
                            key, entries, read, tuple(np.shape(leaf))))
                        continue
                    by_slices = {
                        tuple(map(tuple, e["slices"])): e
                        for e in entries if e["slices"] is not None}
                    full = None  # lazy cross-layout fallback (gather mode)
                    pieces = []
                    for shard in leaf.addressable_shards:
                        want = tuple(map(tuple, _slices_to_json(
                            shard.index, leaf.shape)))
                        e = by_slices.get(want)
                        if e is not None:
                            # exact layout hit: only this shard's bytes move
                            data = read(e)
                            stats["exact_hits"] += 1
                        elif reshard:
                            # cross-topology: cut this target shard from
                            # exactly the saved chunks intersecting it —
                            # the full array is never built
                            data = _cut_target(key, entries, read,
                                               [list(w) for w in want],
                                               leaf.dtype)
                            stats["intersections"] += 1
                        else:
                            if full is None:
                                full = _assemble(key, entries, read,
                                                 leaf.shape)
                                stats["full_assemblies"] += 1
                            data = full[shard.index]
                        pieces.append(jax.device_put(
                            data.astype(leaf.dtype), shard.device))
                    new_leaves.append(
                        jax.make_array_from_single_device_arrays(
                            leaf.shape, leaf.sharding, pieces))
                    if reshard:
                        # one-in-flight-leaf memory bound: this leaf's
                        # chunks are placed on device; drop the host copies
                        stats["peak_leaf_read_bytes"] = max(
                            stats["peak_leaf_read_bytes"],
                            stats["read_bytes"] - leaf_read0)
                        loaded.clear()
                out[name] = jax.tree_util.tree_unflatten(treedef, new_leaves)
        finally:
            for z in files.values():
                z.close()
        self.last_reshard_stats = stats
        _tel_span("ckpt_reshard" if reshard else "ckpt_restore",
                  t_restore, step=int(meta.get("step", -1)),
                  bytes=int(stats["read_bytes"]), fmt="sharded",
                  exact_hits=stats["exact_hits"],
                  intersections=stats["intersections"])
        return out, meta


# ---------------------------------------------------------------------------
# Incremental (content-hashed) checkpoints
# ---------------------------------------------------------------------------

class IncrementalCheckpointer:
    """Content-hashed incremental checkpoints: each ``save`` writes ONLY the
    leaves whose bytes changed since the previous save, plus a manifest
    mapping every leaf to the delta file that holds its current bytes.

    Layout: ``directory/inc_<step>.npz`` (changed leaves only) and
    ``directory/manifest_<step>.json`` — the manifest is written last and
    atomically, so its presence marks the step complete.  Restore reads the
    newest manifest and loads each leaf from whichever delta file the
    manifest points at.

    Honest scoping (BASELINE.md measurements): whole-training-state saves
    see NO size win — Adam moments and momentum change every step, so every
    leaf re-hashes differently.  The win is real for frozen-regime saves
    (adapter/embedding-only training: only the trained leaves are written)
    and for params-only saves of partially-frozen models.  Hashing adds one
    blake2b pass over the tree per save (~GB/s-scale, dwarfed by npz
    compression of the leaves that DO change).

    ``keep`` retains the newest N manifests; delta files still referenced
    by a retained manifest survive garbage collection regardless of age.
    """

    _MANIFEST_RE = re.compile(r"^manifest_(\d+)\.json$")

    def __init__(self, directory: str, *, keep: int = 3,
                 async_write: bool = False):
        self.directory = directory
        self.keep = keep
        self.async_write = async_write
        self._writer = _writer_for(directory)
        os.makedirs(directory, exist_ok=True)
        self._last: dict[str, dict] | None = None  # leaf -> {hash, file}

    def wait(self) -> None:
        self._writer.wait()

    # -- internals --------------------------------------------------------
    @staticmethod
    def _hash(arr: np.ndarray) -> str:
        import hashlib
        h = hashlib.blake2b(digest_size=16)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
        return h.hexdigest()

    def _manifests(self) -> list[tuple[int, str]]:
        out = []
        for name in os.listdir(self.directory):
            m = self._MANIFEST_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        return sorted(out)

    def _load_last(self) -> dict[str, dict] | None:
        ms = self._manifests()
        if not ms:
            return None
        with open(ms[-1][1]) as f:
            return json.load(f)["leaves"]

    # -- API --------------------------------------------------------------
    def save(self, trees: dict, step: int, meta: dict | None = None):
        payload: dict[str, np.ndarray] = {}
        for name, tree in trees.items():
            for k, v in _flatten(tree).items():
                payload[name + k] = v
        if jax.process_index() != 0:
            return None
        # the hash state is settled only once the previous (possibly
        # async) publish has landed — wait before reading it
        self._writer.wait()
        if self._last is None:
            self._last = self._load_last() or {}

        delta_file = f"inc_{step}.npz"
        leaves: dict[str, dict] = {}
        delta: dict[str, np.ndarray] = {}
        for key, arr in payload.items():
            digest = self._hash(arr)
            prev = self._last.get(key)
            if prev is not None and prev["hash"] == digest:
                leaves[key] = prev           # unchanged: point at old file
            else:
                leaves[key] = {"hash": digest, "file": delta_file}
                delta[key] = arr
        manifest = {"step": step, "meta": dict(meta or {}, step=step),
                    "leaves": leaves}

        def publish():
            # self._last advances only AFTER the manifest publish succeeds:
            # a failed write must not poison the hash state (the next save
            # would hash-match leaves whose delta never landed and emit a
            # manifest with dangling references).  On failure, drop the
            # cached state entirely so the next save re-reads the on-disk
            # manifest.
            try:
                if delta:
                    tmp = os.path.join(self.directory, delta_file + ".tmp")
                    with open(tmp, "wb") as f:
                        np.savez(f, **delta)
                    os.replace(tmp,
                               os.path.join(self.directory, delta_file))
                mpath = os.path.join(self.directory,
                                     f"manifest_{step}.json")
                tmp = mpath + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(manifest, f)
                os.replace(tmp, mpath)  # atomic publish marks step complete
            except BaseException:
                self._last = None
                raise
            self._last = leaves
            self._gc()
            return mpath

        if self.async_write:
            self._writer.submit(publish)
            return os.path.join(self.directory, f"manifest_{step}.json")
        return publish()

    def _gc(self) -> None:
        ms = self._manifests()
        drop, kept = ms[:-self.keep], ms[-self.keep:]
        live_files = set()
        for _, mp in kept:
            with open(mp) as f:
                for entry in json.load(f)["leaves"].values():
                    live_files.add(entry["file"])
        for _, mp in drop:
            os.remove(mp)
        for name in os.listdir(self.directory):
            if (name.startswith("inc_") and name.endswith(".npz")
                    and name not in live_files):
                os.remove(os.path.join(self.directory, name))

    def list(self) -> list[tuple[int, str]]:
        self._writer.wait()
        return self._manifests()

    def restore(self, like: dict) -> tuple[dict, dict] | None:
        """Latest VERIFIED manifest restored into ``like``'s
        structure/shardings.  The manifest's per-leaf content hashes
        double as integrity checks: a corrupt/truncated delta file fails
        verification, the manifest is quarantined, and restore falls
        back to the previous one."""
        for _, mpath in reversed(self.list()):
            try:
                return self._restore_manifest(mpath, like)
            except CorruptCheckpointError as e:
                _quarantine(mpath, e)
                self._last = None  # cached hash state may cite the bad file
        return None

    def _restore_manifest(self, mpath: str, like: dict):
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError, UnicodeDecodeError) as e:
            raise CorruptCheckpointError(
                f"manifest {mpath} is unreadable: {e}") from e
        by_file: dict[str, list[str]] = {}
        for key, entry in manifest["leaves"].items():
            by_file.setdefault(entry["file"], []).append(key)
        flat: dict[str, np.ndarray] = {}
        for fname, keys in by_file.items():
            fpath = os.path.join(self.directory, fname)
            try:
                with np.load(fpath) as z:
                    for k in keys:
                        flat[k] = z[k]
            except Exception as e:
                raise CorruptCheckpointError(
                    f"delta file {fpath} is unreadable: {e}") from e
        bad = [k for k, entry in manifest["leaves"].items()
               if self._hash(flat[k]) != entry["hash"]]
        if bad:
            raise CorruptCheckpointError(
                f"manifest {mpath}: {len(bad)} leaves failed content-hash "
                f"verification (e.g. {bad[:3]})")
        return _place_like(like, flat), manifest["meta"]
