"""Checkpoint / resume — a capability the reference lacks entirely.

The reference never saves anything: no ``state_dict``/``torch.save`` call
exists and results live only in stdout (SURVEY.md section 5).  This module
adds atomic whole-training-state checkpointing: params, per-replica
BatchNorm statistics, optimizer state (SGD momentum buffers), the step
counter and the epoch, keyed by pytree path into one ``.npz`` per epoch.

Design notes (TPU-native):
- arrays are fetched with ``jax.device_get`` (gathers replicated/sharded
  leaves to host) and restored with the same placement the Trainer uses at
  init, so a resumed run is sharding-identical to a fresh one;
- writes are atomic (tmp file + rename) so a preempted save never corrupts
  the latest checkpoint — preemption is the normal failure mode on TPU pods;
- only process 0 writes (params/opt-state are replicated across hosts);
  every process restores from the shared directory;
- ``async_write``: the device->host fetch stays synchronous (it is a
  collective and must see a settled device state), but serialization and
  disk IO run on a background thread so training resumes immediately —
  the orbax-style overlap of checkpoint writing with compute.  The writer
  thread is non-daemonic (a clean interpreter exit flushes it) and each
  save joins the previous write first (no interleaved files).
"""

from __future__ import annotations

import json
import os
import re
import threading

import jax
import numpy as np

from ..parallel.mesh import data_sharding, replicated

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.npz$")


class _AsyncWriter:
    """At most one in-flight background write; join-before-submit.

    Shared per directory (module registry below) so EVERY checkpointer
    instance pointing at the same path serializes against the same
    in-flight write — a reader constructed after a writer still waits for
    the pending publish.  A background failure is captured and re-raised
    from the next wait()/submit(), so a failed save cannot masquerade as
    success (the synchronous path's behavior)."""

    def __init__(self):
        self._t: threading.Thread | None = None
        self._exc: BaseException | None = None

    def submit(self, fn) -> None:
        self.wait()

        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — re-raised in wait()
                self._exc = e

        self._t = threading.Thread(target=run)  # non-daemon: exit flushes
        self._t.start()

    def wait(self) -> None:
        if self._t is not None:
            self._t.join()
            self._t = None
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise RuntimeError("background checkpoint write failed") from exc


_WRITERS: dict[str, _AsyncWriter] = {}
_WRITERS_LOCK = threading.Lock()


def _writer_for(directory: str) -> _AsyncWriter:
    key = os.path.abspath(directory)
    with _WRITERS_LOCK:
        return _WRITERS.setdefault(key, _AsyncWriter())


def _fetch(leaf) -> np.ndarray:
    """Materialize a leaf on host.  Replicated/single-host arrays are a plain
    device_get; multi-host sharded arrays (per-replica BN state) need a
    cross-host allgather, which every process must enter (collective)."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(
            leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _flatten(tree) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = jax.tree_util.keystr(path)
        flat[key] = _fetch(leaf)
    return flat


def _unflatten_like(tree, flat: dict[str, np.ndarray], prefix: str):
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(tree)
    leaves = []
    for path, leaf in leaves_with_path:
        key = prefix + jax.tree_util.keystr(path)
        if key not in flat:
            raise KeyError(f"checkpoint missing leaf {key!r}")
        arr = flat[key]
        if arr.shape != leaf.shape:
            raise ValueError(
                f"checkpoint leaf {key!r} has shape {arr.shape}, "
                f"model expects {leaf.shape}")
        leaves.append(arr.astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _list_ckpts(directory: str) -> list[tuple[int, str]]:
    out = []
    for name in os.listdir(directory):
        m = _CKPT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def _atomic_write(directory: str, index: int, payload: dict,
                  meta: dict, keep: int) -> str:
    """Embed meta, write ckpt_<index>.npz atomically, prune old ones."""
    payload = dict(payload)
    payload["__meta__"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    path = os.path.join(directory, f"ckpt_{index}.npz")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)  # atomic publish
    for _, old in _list_ckpts(directory)[:-keep]:
        os.remove(old)
    return path


class Checkpointer:
    """Epoch-granularity checkpoints in ``directory`` (ckpt_<epoch>.npz)."""

    def __init__(self, directory: str, *, keep: int = 3,
                 async_write: bool = False):
        self.directory = directory
        self.keep = keep
        self.async_write = async_write
        self._writer = _writer_for(directory)
        os.makedirs(directory, exist_ok=True)

    def wait(self) -> None:
        """Block until any in-flight background write has been published."""
        self._writer.wait()

    # -- save -------------------------------------------------------------
    def save(self, trainer, epoch: int) -> str | None:
        """Snapshot the trainer after ``epoch`` completed epochs.

        Every process must call this (the fetch of cross-host-sharded BN
        state is a collective); only process 0 writes the file."""
        payload: dict[str, np.ndarray] = {}
        for prefix, tree in (("params", trainer.params),
                             ("state", trainer.state),
                             ("opt", trainer.opt_state)):
            for k, v in _flatten(tree).items():
                payload[prefix + k] = v
        if jax.process_index() != 0:
            return None
        meta = {"epoch": epoch, "step": trainer._step,
                "model": trainer.cfg.model, "strategy": trainer.cfg.strategy,
                "n_replicas": trainer.n_replicas}
        path = os.path.join(self.directory, f"ckpt_{epoch}.npz")
        if self.async_write:
            self._writer.submit(lambda: _atomic_write(
                self.directory, epoch, payload, meta, self.keep))
            return path
        return _atomic_write(self.directory, epoch, payload, meta, self.keep)

    # -- restore ----------------------------------------------------------
    def list(self) -> list[tuple[int, str]]:
        self._writer.wait()  # reads must see the settled directory
        return _list_ckpts(self.directory)

    def latest(self) -> tuple[int, str] | None:
        ckpts = self.list()
        return ckpts[-1] if ckpts else None

    def maybe_restore(self, trainer) -> int:
        """Restore the latest checkpoint into ``trainer`` if one exists;
        returns the epoch to resume from (0 = fresh start)."""
        latest = self.latest()
        if latest is None:
            return 0
        epoch, path = latest
        with np.load(path) as z:
            flat = {k: z[k] for k in z.files}
        meta = json.loads(bytes(flat.pop("__meta__").tobytes()).decode())
        if meta["model"] != trainer.cfg.model:
            raise ValueError(
                f"checkpoint is for model {meta['model']}, "
                f"trainer is {trainer.cfg.model}")
        if meta["n_replicas"] != trainer.n_replicas:
            raise ValueError(
                f"checkpoint has {meta['n_replicas']} replicas (per-replica "
                f"BN state), trainer has {trainer.n_replicas}")
        params = _unflatten_like(trainer.params, flat, "params")
        state = _unflatten_like(trainer.state, flat, "state")
        opt_state = _unflatten_like(trainer.opt_state, flat, "opt")
        if trainer.mesh is not None:
            rep = replicated(trainer.mesh)
            shd = data_sharding(trainer.mesh)
            params = jax.device_put(params, rep)
            opt_state = jax.device_put(opt_state, rep)
            state = jax.device_put(state, shd)
        trainer.params, trainer.state, trainer.opt_state = (
            params, state, opt_state)
        trainer._step = meta["step"]
        return meta["epoch"]


class PyTreeCheckpointer:
    """Generic step-granularity checkpoints for named pytrees (the LM-side
    sibling of ``Checkpointer``, which is wedded to the VGG trainer's
    params/BN-state/opt triple).

    ``save`` stores any dict of pytrees + JSON-able meta; ``restore`` needs
    a template dict with the same structure (e.g. a freshly initialized
    trainer's state) and re-places every leaf with the template leaf's
    sharding, so a resumed run is layout-identical to a fresh one.
    """

    def __init__(self, directory: str, *, keep: int = 3,
                 async_write: bool = False):
        self.directory = directory
        self.keep = keep
        self.async_write = async_write
        self._writer = _writer_for(directory)
        os.makedirs(directory, exist_ok=True)

    def wait(self) -> None:
        """Block until any in-flight background write has been published."""
        self._writer.wait()

    def save(self, trees: dict, step: int, meta: dict | None = None):
        payload: dict[str, np.ndarray] = {}
        for name, tree in trees.items():
            for k, v in _flatten(tree).items():
                payload[name + k] = v
        if jax.process_index() != 0:
            return None
        full_meta = dict(meta or {}, step=step)
        path = os.path.join(self.directory, f"ckpt_{step}.npz")
        if self.async_write:
            self._writer.submit(lambda: _atomic_write(
                self.directory, step, payload, full_meta, self.keep))
            return path
        return _atomic_write(self.directory, step, payload, full_meta,
                             self.keep)

    def list(self) -> list[tuple[int, str]]:
        self._writer.wait()  # reads must see the settled directory
        return _list_ckpts(self.directory)

    def restore(self, like: dict) -> tuple[dict, dict] | None:
        """Latest checkpoint restored into ``like``'s structure/shardings;
        returns (trees, meta) or None when no checkpoint exists."""
        ckpts = self.list()
        if not ckpts:
            return None
        _, path = ckpts[-1]
        with np.load(path) as z:
            flat = {k: z[k] for k in z.files}
        meta = json.loads(bytes(flat.pop("__meta__").tobytes()).decode())
        out = {}
        for name, tree in like.items():
            restored = _unflatten_like(tree, flat, name)
            out[name] = jax.tree.map(
                lambda new, old: (jax.device_put(new, old.sharding)
                                  if isinstance(old, jax.Array) else new),
                restored, tree)
        return out, meta
