"""Run doctor: online SLO monitors, profiling lanes, and postmortems.

PR 9 gave every subsystem one rank/generation-tagged event stream; this
module is the CONSUMER layer that watches it while the run is alive —
BAGUA's argument (PAPERS.md) that a tracing service earns its keep when
diagnosis closes the loop back into scheduling decisions:

- **Profiling lanes** — ``record_memory`` (device live/peak bytes via
  ``jax.Device.memory_stats()`` with a pytree-``nbytes`` fallback on
  CPU, plus host RSS) and ``compile_span`` (per-program-hash compile
  time + cache-size gauges) ride the existing record schema, so they
  land in the same merged Chrome trace as everything else.
- **SLO monitors** — declarative ``SloRule``s (metric, window,
  threshold, severity) evaluated online by a ``RunDoctor`` either
  in-process (a ``Telemetry.subscribe`` feed) or cross-process (a
  ``RunTailer`` over the rank JSONL files, read the way
  ``merge_chrome_trace`` reads them).  Breaches emit events and fire
  hooks; two real ones ship here: ``sentry_breach_hook`` escalates
  through TrainingSentry's existing resize rung, and
  ``FleetBreachHook`` drains/readmits a breaching replica through
  FleetRouter's existing paths.
- **Flight recorder** — ``write_postmortem`` snapshots the last-N ring
  records, active SLO states, gang membership, serve stats, memory
  watermarks, and a log tail into one strict-JSON bundle at the
  existing failure-classification points (SentryAbort, FAULT_EXIT_CODE
  worker death, elastic shrink, replica loss); ``scripts/postmortem.py``
  renders it.

Like metrics.py, this module is JAX-FREE at import time (launch.py's
agent imports it); device introspection goes through
``sys.modules.get("jax")`` so a process that never imported jax (the
agent) degrades gracefully instead of paying the import.

Monitors off is the default and changes NO compiled program — pinned
bitwise + ``_cache_size`` in tests/test_monitor.py per the PR-9
methodology.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import socket
import sys
import time
from collections import deque
from dataclasses import dataclass, field

from . import telemetry
from .metrics import SpikeDetector

BUNDLE_VERSION = 1
BUNDLE_PREFIX = "postmortem_"

# the trigger classes the flight recorder covers: the four of ISSUE 12
# plus "transport" (round 19) — a socket-fleet peer quarantined by the
# RPC client (torn/corrupt frame, or deadline exhaustion after retries),
# written by fleet/daemon.py RemoteReplica before the router's
# replica_loss rescue bundle, so the socket-layer death and the
# scheduling-layer recovery each leave their own strict-JSON record
TRIGGERS = ("sentry_abort", "worker_fault", "elastic_shrink",
            "replica_loss", "transport")
SEVERITIES = ("info", "warn", "critical")
AGGS = ("last", "mean", "max", "min", "p50", "p95", "spike", "age")
OPS = ("<=", ">=")
RECORD_TYPES = ("span", "gauge", "hist", "counter", "event")

# bundle keys every postmortem must carry (load_postmortem validates)
BUNDLE_KEYS = ("version", "trigger", "written_at", "host", "pid",
               "ring", "slo", "gang", "serve", "memory", "log_tail")


# ---------------------------------------------------------------------------
# module log ring: the "recent log tail" lane of the flight recorder.
# Subsystems route their log lines here (sentry/launch pass their log
# callable through log_line) so a bundle can show what the operator saw.

_LOG_RING: deque = deque(maxlen=200)


def log_line(msg: str) -> None:
    """Append one line to the bounded module log ring (and nothing
    else — callers keep printing wherever they printed before)."""
    _LOG_RING.append(f"{time.time():.3f} {msg}")


def log_tail(n: int = 50) -> list[str]:
    return list(_LOG_RING)[-n:]


# ---------------------------------------------------------------------------
# profiling lanes

def host_rss_bytes() -> int:
    """This process's resident set size.  /proc is authoritative on
    Linux; the resource fallback covers everything else."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF)
        return int(ru.ru_maxrss) * 1024  # linux reports KiB
    except Exception:
        return 0


def tree_nbytes(tree) -> int:
    """Total ``nbytes`` across a pytree's array leaves — the accounting
    fallback when ``memory_stats()`` is unavailable (CPU).  Uses jax's
    flattener only if jax is ALREADY imported (agent stays jax-free);
    otherwise walks dict/list/tuple containers by hand."""
    jax = sys_jax()
    if jax is not None:
        try:
            leaves = jax.tree_util.tree_leaves(tree)
        except Exception:
            leaves = _manual_leaves(tree)
    else:
        leaves = _manual_leaves(tree)
    total = 0
    for leaf in leaves:
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            try:
                total += int(nb)
            except (TypeError, ValueError):
                pass
    return total


def _manual_leaves(tree) -> list:
    out: list = []
    stack = [tree]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            stack.extend(node.values())
        elif isinstance(node, (list, tuple)):
            stack.extend(node)
        elif node is not None:
            out.append(node)
    return out


def sys_jax():
    """jax, iff some OTHER module already imported it.  The launcher
    agent is jax-free by contract; importing jax here would silently
    break that, so we only ever look at sys.modules."""
    return sys.modules.get("jax")


def device_memory_stats() -> dict[str, dict]:
    """Per-device live/peak/limit bytes via ``jax.Device.memory_stats()``
    — populated on TPU/GPU, ``{}`` on CPU (the backend returns None) or
    in a process that never imported jax."""
    jax = sys_jax()
    if jax is None:
        return {}
    out: dict[str, dict] = {}
    try:
        for d in jax.devices():
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if not ms:
                continue
            out[str(d.id)] = {
                "live_bytes": ms.get("bytes_in_use", 0),
                "peak_bytes": ms.get("peak_bytes_in_use",
                                     ms.get("bytes_in_use", 0)),
                "limit_bytes": ms.get("bytes_limit", 0),
            }
    except Exception:
        return {}
    return out


def memory_watermarks(**trees) -> dict:
    """One memory snapshot: host RSS, per-device stats, and the nbytes
    of each named pytree (params/opt-state/KV pool/handoff staging)."""
    return {
        "host_rss_bytes": host_rss_bytes(),
        "devices": device_memory_stats(),
        "trees": {name: tree_nbytes(t) for name, t in trees.items()},
    }


def record_memory(tel=None, *, phase: str = "mem", **trees):
    """Emit the memory snapshot as gauges on the run's event stream
    (``host_rss_bytes``, ``<tree>_bytes``, ``device_live_bytes`` /
    ``device_peak_bytes`` summed across devices).  Returns the snapshot,
    or None when telemetry is off (the zero-overhead default: one
    registry read, nothing measured)."""
    tel = tel if tel is not None else telemetry.active()
    if tel is None:
        return None
    wm = memory_watermarks(**trees)
    tel.gauge("host_rss_bytes", wm["host_rss_bytes"], phase=phase)
    for name, nb in wm["trees"].items():
        tel.gauge(f"{name}_bytes", nb, phase=phase)
    if wm["devices"]:
        live = sum(d["live_bytes"] for d in wm["devices"].values())
        peak = sum(d["peak_bytes"] for d in wm["devices"].values())
        tel.gauge("device_live_bytes", live, phase=phase)
        tel.gauge("device_peak_bytes", peak, phase=phase)
    return wm


def program_key(key) -> str:
    """Stable short hash of a compile key (arg shapes/dtypes) — the
    per-program identity compile spans are grouped by."""
    return hashlib.sha1(repr(key).encode()).hexdigest()[:12]


@contextlib.contextmanager
def compile_span(name: str, *, key=None, cache_size=None, tel=None,
                 **args):
    """Wrap a compile point: times the block and emits a phase
    ``"compile"`` span tagged with the program hash, plus a
    ``<name>_cache_size`` gauge when ``cache_size`` (a callable,
    evaluated AFTER the build so it sees the inserted entry) is given.
    Telemetry off: one registry read, no timing, no records."""
    tel = tel if tel is not None else telemetry.active()
    if tel is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dur = time.perf_counter() - t0
        span_args = dict(args)
        if key is not None:
            span_args["program"] = program_key(key)
        tel.span_at(name, t0, dur, phase="compile", **span_args)
        if cache_size is not None:
            try:
                tel.gauge(f"{name}_cache_size", float(cache_size()),
                          phase="compile")
            except Exception:
                pass


# ---------------------------------------------------------------------------
# declarative SLO rules

@dataclass
class SloRule:
    """One service-level objective over the event stream.

    ``metric`` names the record (span/gauge/hist/counter/event name);
    ``record`` its type, which fixes how a value is extracted — spans
    contribute their duration in MILLISECONDS (``step_ms p95 <= X``
    reads naturally), gauges/hists their value, counters their
    increment, events 1.0 per occurrence.  ``agg`` folds the bounded
    window to one number (``spike`` delegates to metrics.SpikeDetector;
    ``age`` is seconds since the metric was LAST seen — the
    heartbeat-staleness shape, where silence is the breach).  ``phase``
    and ``rank`` narrow the match (rank is the replica id for fleet
    rules).  ``op``/``threshold`` judge the aggregate; ``severity`` is
    carried into breach events and hook decisions."""

    name: str
    metric: str
    threshold: float
    op: str = "<="
    window: int = 32
    agg: str = "p95"
    severity: str = "warn"
    phase: str | None = None
    rank: int | None = None
    record: str = "span"
    min_samples: int = 1
    spike_threshold: float = 10.0
    spike_min_history: int = 8

    def __post_init__(self):
        if self.op not in OPS:
            raise ValueError(f"op {self.op!r} not in {OPS}")
        if self.agg not in AGGS:
            raise ValueError(f"agg {self.agg!r} not in {AGGS}")
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"severity {self.severity!r} not in {SEVERITIES}")
        if self.record not in RECORD_TYPES:
            raise ValueError(
                f"record {self.record!r} not in {RECORD_TYPES}")
        if self.window < 1:
            raise ValueError("window must be >= 1")

    def matches(self, rec: dict) -> bool:
        if rec.get("type") != self.record:
            # spans also aggregate as hists in some emitters; keep the
            # match strict — one rule, one record type
            return False
        if rec.get("name") != self.metric:
            return False
        if self.phase is not None and rec.get("phase") != self.phase:
            return False
        if self.rank is not None and rec.get("rank") != self.rank:
            return False
        return True

    def value_of(self, rec: dict) -> float | None:
        if self.record == "span":
            dur = rec.get("dur")
            return None if dur is None else float(dur) * 1e3  # -> ms
        if self.record in ("gauge", "hist"):
            v = rec.get("value")
            return None if not isinstance(v, (int, float)) else float(v)
        if self.record == "counter":
            v = rec.get("inc")
            return None if not isinstance(v, (int, float)) else float(v)
        return 1.0  # event: each occurrence counts once

    def to_dict(self) -> dict:
        return {
            "name": self.name, "metric": self.metric,
            "threshold": self.threshold, "op": self.op,
            "window": self.window, "agg": self.agg,
            "severity": self.severity, "phase": self.phase,
            "rank": self.rank, "record": self.record,
            "min_samples": self.min_samples,
            "spike_threshold": self.spike_threshold,
            "spike_min_history": self.spike_min_history,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "SloRule":
        return cls(**{k: d[k] for k in cls.__dataclass_fields__
                      if k in d})


@dataclass
class SloState:
    """Live evaluation state for one rule."""

    rule: SloRule
    window: deque = field(default_factory=deque)
    breached: bool = False
    breaches: int = 0
    samples: int = 0
    current: float | None = None
    last_value: float | None = None
    last_seen_mono: float | None = None
    detector: SpikeDetector | None = None

    def to_dict(self) -> dict:
        return {
            "rule": self.rule.to_dict(),
            "breached": self.breached,
            "breaches": self.breaches,
            "samples": self.samples,
            "current": self.current,
            "last_value": self.last_value,
            "window": list(self.window),
        }


def _aggregate(values: list[float], agg: str) -> float | None:
    if not values:
        return None
    if agg == "last":
        return values[-1]
    if agg == "mean":
        return sum(values) / len(values)
    if agg == "max":
        return max(values)
    if agg == "min":
        return min(values)
    s = sorted(values)
    q = 0.5 if agg == "p50" else 0.95
    return s[min(int(q * len(s)), len(s) - 1)]


class RunDoctor:
    """Online SLO evaluator over the record stream.

    Feed it live (``attach()`` subscribes to the process registry) or
    cross-process (``pump(RunTailer(run_dir))``); every observed record
    updates matching rules' bounded windows and, every ``check_every``
    observations, transitions are judged: entering breach emits an
    ``slo_breach`` event (phase ``"slo"``) and fires the registered
    breach hooks; leaving it emits ``slo_clear`` and fires clear hooks.
    Records of phase ``"slo"`` are ignored on input — the doctor's own
    events must not feed back into its windows."""

    def __init__(self, rules=(), *, check_every: int = 1, log=None):
        self.states: dict[str, SloState] = {}
        self.check_every = max(1, check_every)
        self.log = log
        self._hooks_breach: list = []
        self._hooks_clear: list = []
        self._attached: list = []
        self._since_check = 0
        self._checking = False
        self._t0_mono = time.perf_counter()
        for r in rules:
            self.add_rule(r)

    # -- wiring ----------------------------------------------------------
    def add_rule(self, rule: SloRule) -> SloState:
        if rule.name in self.states:
            raise ValueError(f"duplicate rule name {rule.name!r}")
        st = SloState(rule=rule, window=deque(maxlen=rule.window))
        if rule.agg == "spike":
            st.detector = SpikeDetector(
                window=max(rule.window, 2),
                threshold=rule.spike_threshold,
                min_history=rule.spike_min_history)
        self.states[rule.name] = st
        return st

    def on_breach(self, fn) -> None:
        """Register ``fn(state)`` for breach transitions."""
        self._hooks_breach.append(fn)

    def on_clear(self, fn) -> None:
        self._hooks_clear.append(fn)

    def attach(self, tel=None) -> bool:
        """Subscribe to the live registry (default: the active one)."""
        tel = tel if tel is not None else telemetry.active()
        if tel is None:
            return False
        tel.subscribe(self.observe)
        self._attached.append(tel)
        return True

    def detach(self) -> None:
        for tel in self._attached:
            try:
                tel.unsubscribe(self.observe)
            except Exception:
                pass
        self._attached = []

    # -- evaluation ------------------------------------------------------
    def observe(self, rec: dict) -> None:
        """Feed one record; auto-checks every ``check_every`` calls."""
        if rec.get("phase") == "slo":
            return  # never eat our own breach events
        hit = False
        for st in self.states.values():
            rule = st.rule
            if not rule.matches(rec):
                continue
            v = rule.value_of(rec)
            if v is None:
                continue
            hit = True
            st.samples += 1
            st.last_value = v
            st.last_seen_mono = time.perf_counter()
            if st.detector is not None:
                # SpikeDetector owns its window; a True return = spike
                st.window.append(1.0 if st.detector.update(v) else 0.0)
            else:
                st.window.append(v)
        if hit:
            self._since_check += 1
            if self._since_check >= self.check_every:
                self.check()

    def check(self, now: float | None = None) -> list[SloState]:
        """Judge every rule; returns states that TRANSITIONED.  Safe to
        call re-entrantly (a hook emitting records that re-trigger
        observe→check is a no-op inner call)."""
        if self._checking:
            return []
        self._checking = True
        self._since_check = 0
        flipped: list[SloState] = []
        try:
            now = now if now is not None else time.perf_counter()
            for st in self.states.values():
                rule = st.rule
                if rule.agg == "age":
                    base = (st.last_seen_mono if st.last_seen_mono
                            is not None else self._t0_mono)
                    cur = now - base
                elif rule.agg == "spike":
                    if len(st.window) < rule.min_samples:
                        continue
                    cur = sum(st.window)  # spikes in window
                else:
                    if len(st.window) < rule.min_samples:
                        continue
                    cur = _aggregate(list(st.window), rule.agg)
                if cur is None:
                    continue
                st.current = cur
                ok = (cur <= rule.threshold if rule.op == "<="
                      else cur >= rule.threshold)
                if not ok and not st.breached:
                    st.breached = True
                    st.breaches += 1
                    flipped.append(st)
                    self._emit("slo_breach", st)
                    self._fire(self._hooks_breach, st)
                elif ok and st.breached:
                    st.breached = False
                    flipped.append(st)
                    self._emit("slo_clear", st)
                    self._fire(self._hooks_clear, st)
        finally:
            self._checking = False
        return flipped

    def _emit(self, name: str, st: SloState) -> None:
        r = st.rule
        msg = (f"[monitor] {name}: {r.name} ({r.metric} {r.agg}="
               f"{st.current:.4g} {'>' if r.op == '<=' else '<'} "
               f"{r.threshold:g}, severity={r.severity})")
        log_line(msg)
        if self.log is not None:
            try:
                self.log(msg)
            except Exception:
                pass
        tel = telemetry.active()
        if tel is not None:
            tel.event(name, phase="slo", rule=r.name, metric=r.metric,
                      agg=r.agg, value=st.current,
                      threshold=r.threshold, op=r.op,
                      severity=r.severity, breaches=st.breaches,
                      rule_rank=r.rank)

    def _fire(self, hooks: list, st: SloState) -> None:
        for fn in hooks:
            try:
                fn(st)
            except Exception as e:  # a hook must never kill the doctor
                log_line(f"[monitor] hook {fn!r} failed: {e!r}")

    def pump(self, tailer: "RunTailer") -> int:
        """Drain a tailer into observe(); returns records consumed."""
        recs = tailer.poll()
        for rec in recs:
            self.observe(rec)
        return len(recs)

    def summary(self) -> dict:
        """Active SLO states keyed by rule name (the bundle's ``slo``
        section and the ``--slo`` table's source)."""
        return {name: st.to_dict() for name, st in self.states.items()}


class RunTailer:
    """Incremental reader over a run dir's ``events_*.jsonl`` files —
    the cross-process feed (the doctor in the agent watching workers).
    Tracks a byte offset per file and only consumes COMPLETE lines, so
    a torn tail mid-write is re-read whole on the next poll (the same
    whole-line guarantee the single-``os.write`` flush provides)."""

    def __init__(self, run_dir: str):
        self.run_dir = run_dir
        self._offsets: dict[str, int] = {}

    def poll(self) -> list[dict]:
        out: list[dict] = []
        try:
            names = sorted(os.listdir(self.run_dir))
        except OSError:
            return out
        for name in names:
            if not (name.startswith(telemetry.FILE_PREFIX)
                    and name.endswith(".jsonl")):
                continue
            path = os.path.join(self.run_dir, name)
            off = self._offsets.get(name, 0)
            try:
                with open(path, "rb") as f:
                    f.seek(off)
                    chunk = f.read()
            except OSError:
                continue
            if not chunk:
                continue
            nl = chunk.rfind(b"\n")
            if nl < 0:
                continue  # only a torn line so far
            self._offsets[name] = off + nl + 1
            for line in chunk[:nl].split(b"\n"):
                if not line.strip():
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
        return out


# ---------------------------------------------------------------------------
# the two wired hooks

def sentry_breach_hook(sentry, *, severity: str = "critical"):
    """Breach hook escalating through TrainingSentry's resize rung: a
    breach at/above ``severity`` calls ``sentry.request_resize`` — the
    same rollback + ``on_resize`` + ladder-reset path rung 2 takes, so
    an SLO breach and a loss-spike escalation recover identically."""
    floor = SEVERITIES.index(severity)

    def hook(st: SloState) -> None:
        if SEVERITIES.index(st.rule.severity) < floor:
            return
        sentry.request_resize(f"slo:{st.rule.name}")
    return hook


class FleetBreachHook:
    """Breach/clear hooks marking a breaching replica degraded and
    draining it through FleetRouter's existing ``drain``/``readmit``:
    rules scoped with ``rank=<replica id>`` map breaches to replicas.
    ``register(doctor)`` wires both directions."""

    def __init__(self, router, *, log=None):
        self.router = router
        self.log = log
        self.degraded: set[int] = set()

    def breach(self, st: SloState) -> None:
        rid = st.rule.rank
        if rid is None or rid in self.degraded:
            return
        try:
            self.router.drain(rid)
        except (KeyError, ValueError):
            return
        self.degraded.add(rid)
        msg = (f"[monitor] replica {rid} degraded by SLO "
               f"{st.rule.name}; draining")
        log_line(msg)
        if self.log is not None:
            self.log(msg)
        tel = telemetry.active()
        if tel is not None:
            tel.event("replica_degraded", phase="slo", replica=rid,
                      rule=st.rule.name)

    def clear(self, st: SloState) -> None:
        rid = st.rule.rank
        if rid is None or rid not in self.degraded:
            return
        try:
            self.router.readmit(rid)
        except (KeyError, ValueError, RuntimeError):
            return  # dead replica: stays degraded
        self.degraded.discard(rid)
        msg = f"[monitor] replica {rid} recovered; readmitted"
        log_line(msg)
        if self.log is not None:
            self.log(msg)
        tel = telemetry.active()
        if tel is not None:
            tel.event("replica_readmitted", phase="slo", replica=rid,
                      rule=st.rule.name)

    def register(self, doctor: RunDoctor) -> "FleetBreachHook":
        doctor.on_breach(self.breach)
        doctor.on_clear(self.clear)
        return self


class SyncRelaxHook:
    """The straggler ACTUATOR (round 18): a step-time SLO breach widens
    the trainer's local-SGD window — ``rebuild(sync_every=2*current)``
    within ``cfg.max_sync_every`` — so a congested DCN hop amortizes
    over more local steps instead of stalling every boundary; the clear
    narrows back to the config's base interval.  Rule-table-not-new-
    plumbing (the round-15 monitor's promise): any ``SloRule`` name can
    drive it — the stock pairing is ``default_rules``'s ``step_time``
    p95 — and the transition rides the existing breach/clear hook bus.
    With ``max_sync_every`` at its default 1 every widen request clamps
    to a no-op: relaxation stays opt-in, exactly like passing
    ``sync_every`` by hand.

    The rebuild drops per-device optimizer divergence and any
    un-exchanged window delta (both trainers' documented carry-drop
    contract) — acceptable for an actuator that fires on the SLO
    cadence, not per step.

    Round 22 adds the PER-SLICE mode: ``slice_rules`` maps additional
    rule names to slice indices (e.g. ``{"step_time_site1": 1}`` from
    a rule scoped to one WAN site's spans).  A breach of a mapped rule
    widens ONLY that slice's entry in ``cfg.sync_every_per_slice``
    (doubling within ``max_sync_every``), so a straggling site
    amortizes its own WAN hop without staling the healthy slices; the
    clear narrows that slot back to its base.  Widen/narrow always
    move by powers of two from the base tuple, so the checker's
    min/multiple invariants hold at every transition (the gang-wide
    base interval is ``min`` of the tuple and never rises above the
    healthy slices' base)."""

    def __init__(self, trainer, *, rule: str = "step_time", log=None,
                 slice_rules: dict[str, int] | None = None):
        self.trainer = trainer
        self.rule = rule
        self.log = log
        self.base = trainer.cfg.sync_every
        self.slice_rules = dict(slice_rules or {})
        per = getattr(trainer.cfg, "sync_every_per_slice", None)
        dcn = getattr(trainer.cfg, "dcn_size", 1) or 1
        # the base tuple the clear narrows back to (uniform windows
        # expand to (H, ..., H) on first per-slice widen)
        self.base_slices = (tuple(per) if per is not None
                           else (self.base,) * dcn)
        self.had_per = per is not None  # narrow restores None when the
        # config started uniform (the bitwise build-time branch)

    def _emit(self, cur: int | tuple, target: int | tuple,
              direction: str, st: SloState,
              slice_idx: int | None = None) -> None:
        scope = "" if slice_idx is None else f" [slice {slice_idx}]"
        msg = (f"[monitor] request_sync_relax{scope}: sync_every "
               f"{cur} -> {target} ({direction}, rule {st.rule.name})")
        log_line(msg)
        if self.log is not None:
            try:
                self.log(msg)
            except Exception:
                pass
        tel = telemetry.active()
        if tel is not None:
            extra = {} if slice_idx is None else {"slice": slice_idx}
            tel.event("request_sync_relax", phase="slo",
                      rule=st.rule.name, direction=direction,
                      sync_every=(target if isinstance(target, int)
                                  else min(target)), previous=str(cur),
                      max_sync_every=self.trainer.cfg.max_sync_every,
                      **extra)

    def _retarget(self, target: int, direction: str,
                  st: SloState) -> None:
        cur = self.trainer.cfg.sync_every
        if target == cur:
            return
        try:
            self.trainer.rebuild(sync_every=target)
        except ValueError as e:
            # a config that cannot window (overlap, meshless, ...)
            # must not kill the doctor — log the refusal and stand down
            log_line(f"[monitor] sync relax refused: {e}")
            return
        self._emit(cur, target, direction, st)

    def _retarget_slice(self, idx: int, direction: str,
                        st: SloState) -> None:
        cfg = self.trainer.cfg
        per = getattr(cfg, "sync_every_per_slice", None)
        cur = list(per if per is not None else self.base_slices)
        if idx < 0 or idx >= len(cur):
            log_line(f"[monitor] sync relax refused: slice {idx} out "
                     f"of range for {len(cur)} slices")
            return
        prev = tuple(cur)
        if direction == "widen":
            cur[idx] = min(max(2 * cur[idx], 2),
                           max(cfg.max_sync_every, 1))
        else:
            cur[idx] = self.base_slices[idx]
        target = tuple(cur)
        if target == prev:
            # already at the ceiling/base (or a narrow on a trainer
            # that never widened): no rebuild, no event
            return
        install = (None if (target == self.base_slices
                            and not self.had_per) else target)
        try:
            # the base interval follows min(tuple): the checker's
            # min(per_slice) == sync_every invariant, preserved because
            # every slot moves in powers of two from a common base
            self.trainer.rebuild(sync_every=min(target),
                                 sync_every_per_slice=install)
        except (TypeError, ValueError) as e:
            log_line(f"[monitor] sync relax refused: {e}")
            return
        self._emit(prev, target, direction, st, slice_idx=idx)

    def breach(self, st: SloState) -> None:
        if st.rule.name in self.slice_rules:
            self._retarget_slice(self.slice_rules[st.rule.name],
                                 "widen", st)
            return
        if st.rule.name != self.rule:
            return
        cur = self.trainer.cfg.sync_every
        ceiling = self.trainer.cfg.max_sync_every
        self._retarget(min(max(2 * cur, 2), max(ceiling, 1)),
                       "widen", st)

    def clear(self, st: SloState) -> None:
        if st.rule.name in self.slice_rules:
            self._retarget_slice(self.slice_rules[st.rule.name],
                                 "narrow", st)
            return
        if st.rule.name != self.rule:
            return
        self._retarget(self.base, "narrow", st)

    def register(self, doctor: RunDoctor) -> "SyncRelaxHook":
        doctor.on_breach(self.breach)
        doctor.on_clear(self.clear)
        return self


# ---------------------------------------------------------------------------
# rule presets / serialization

def default_rules(*, step_ms_p95: float = 1000.0,
                  heartbeat_age_s: float = 300.0,
                  slot_utilization: float = 0.5,
                  fleet_handoff_ms: float = 5000.0,
                  device_peak_bytes: float | None = None) -> list[SloRule]:
    """The four ISSUE-12 example rules with overridable thresholds.

    ``device_peak_bytes`` (round 17, opt-in: None adds no rule, keeping
    the stock set at four) arms a device-memory watermark against the
    ``record_memory`` gauge of the same name — the live third lane of
    the activation accountant's contract (utils/memacct.py): feed it the
    accountant's predicted peak plus headroom, and a step whose measured
    watermark crosses the prediction pages the doctor instead of
    becoming tomorrow's OOM."""
    rules = [
        SloRule(name="step_time", metric="lm_train_step",
                record="span", agg="p95", op="<=",
                threshold=step_ms_p95, severity="critical"),
        SloRule(name="heartbeat_fresh", metric="heartbeat",
                record="event", agg="age", op="<=",
                threshold=heartbeat_age_s, severity="critical"),
        SloRule(name="slot_utilization", metric="slot_utilization",
                record="gauge", agg="mean", op=">=",
                threshold=slot_utilization, severity="warn"),
        SloRule(name="fleet_handoff", metric="handoff_ms",
                record="hist", agg="p95", op="<=",
                threshold=fleet_handoff_ms, severity="warn",
                phase="fleet"),
    ]
    if device_peak_bytes is not None:
        rules.append(
            SloRule(name="device_memory_watermark",
                    metric="device_peak_bytes", record="gauge",
                    agg="max", op="<=", threshold=device_peak_bytes,
                    severity="critical"))
    return rules


def rules_from_json(path: str) -> list[SloRule]:
    with open(path) as f:
        raw = json.load(f)
    return [SloRule.from_dict(d) for d in raw]


def evaluate_run(run_dir: str, rules) -> dict:
    """Offline doctor pass over a finished (or live) run dir — the
    ``telemetry_summary --slo`` path.  Replays every record in timestamp
    order through a fresh doctor; ``age`` rules are judged against the
    LAST record's timestamp, not wall-now (a long-dead run would
    otherwise always read stale)."""
    doctor = RunDoctor(rules, check_every=1)
    recs: list[tuple[float, dict]] = []
    for epoch, rows in telemetry.read_run(run_dir):
        for rec in rows:
            ts = rec.get("ts")
            if isinstance(ts, (int, float)):
                recs.append((telemetry._align_us(epoch, ts), rec))
    recs.sort(key=lambda p: p[0])
    if recs:
        # Re-baseline the never-seen fallback onto the RUN's clock: the
        # doctor's own perf_counter origin is meaningless against a
        # replayed run's timestamps (age would read negative/garbage).
        # With this, a metric never seen at all ages from the run's
        # first record — "silent for the whole run".
        first_ts = recs[0][1].get("ts")
        if isinstance(first_ts, (int, float)):
            doctor._t0_mono = first_ts
    last_mono: float | None = None
    for _, rec in recs:
        doctor.observe(rec)
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            last_mono = ts
    doctor.check(now=last_mono)
    return doctor.summary()


# ---------------------------------------------------------------------------
# flight recorder / postmortem bundles

def gang_from_env() -> dict:
    """Gang membership as the launcher env contract describes it from
    inside a worker; the agent passes its own view explicitly."""
    env = os.environ
    out = {}
    for key in ("RANK", "WORLD_SIZE", "LOCAL_RANK", "RESTART_ATTEMPT",
                "ELASTIC_MIN_WORKERS", "ELASTIC_MAX_WORKERS"):
        v = env.get(key)
        if v is not None:
            out[key.lower()] = v
    return out


def _ring_from_run_dir(run_dir: str, n: int) -> list[dict]:
    """Last-N records across the WHOLE run dir (all ranks), ordered on
    the shared wall timeline the way merge_chrome_trace orders spans."""
    recs: list[tuple[float, dict]] = []
    for epoch, rows in telemetry.read_run(run_dir):
        for rec in rows:
            ts = rec.get("ts")
            if isinstance(ts, (int, float)):
                recs.append((telemetry._align_us(epoch, ts), rec))
    recs.sort(key=lambda p: p[0])
    return [r for _, r in recs[-n:]]


def write_postmortem(trigger: str, *, run_dir: str | None = None,
                     tel=None, detail: dict | None = None,
                     doctor: RunDoctor | None = None,
                     gang: dict | None = None,
                     serve_stats: dict | None = None,
                     memory: dict | None = None,
                     log_tail_n: int = 50,
                     ring_n: int = 256) -> str | None:
    """Write one postmortem bundle; returns its path, or None.

    Runs on the FAILURE path (under SentryAbort, after a worker death,
    mid-shrink) — so it must never raise: any internal error returns
    None and the original failure handling proceeds.  The bundle is
    strict JSON (``_jsonsafe`` — a diverging run's NaN stats are the
    common case here), written atomically (tmp + rename) so a reader
    racing the crash sees a whole bundle or none.
    """
    try:
        if trigger not in TRIGGERS:
            raise ValueError(f"trigger {trigger!r} not in {TRIGGERS}")
        tel = tel if tel is not None else telemetry.active()
        if run_dir is None:
            run_dir = tel.run_dir if tel is not None else None
        if run_dir is None:
            return None
        # flush our own registry first so the dir-wide ring includes
        # this process's newest records
        if tel is not None:
            try:
                tel.flush()
            except Exception:
                pass
        ring = _ring_from_run_dir(run_dir, ring_n)
        if not ring and tel is not None:
            ring = list(tel.recent)[-ring_n:]
        # trigger kind LAST: a detail dict carrying its own "kind"
        # (launch.py forwards worker-exit classifications verbatim)
        # must not shadow the bundle's trigger class
        trig = dict(detail or {})
        trig["kind"] = trigger
        bundle = {
            "version": BUNDLE_VERSION,
            "trigger": trig,
            "written_at": time.time(),
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "ring": ring,
            "slo": doctor.summary() if doctor is not None else {},
            "gang": gang if gang is not None else gang_from_env(),
            "serve": serve_stats or {},
            "memory": memory if memory is not None else
            memory_watermarks(),
            "log_tail": log_tail(log_tail_n),
        }
        os.makedirs(run_dir, exist_ok=True)
        name = (f"{BUNDLE_PREFIX}{trigger}_{os.getpid()}_"
                f"{int(time.time() * 1000)}.json")
        path = os.path.join(run_dir, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(telemetry._jsonsafe(bundle), f)
        os.replace(tmp, path)
        log_line(f"[monitor] postmortem bundle written: {path}")
        if tel is not None:
            try:
                tel.event("postmortem", phase="slo", trigger=trigger,
                          path=path)
            except Exception:
                pass
        return path
    except Exception:
        return None


def _strict(value):  # json parse_constant hook
    raise ValueError(f"non-strict JSON constant {value!r}")


def load_postmortem(path: str) -> dict:
    """Parse + validate a bundle: STRICT json (any bare NaN/Infinity is
    a writer bug and raises), all schema keys present, a known trigger
    kind.  scripts/postmortem.py and tests share this one validator."""
    with open(path) as f:
        bundle = json.load(f, parse_constant=_strict)
    missing = [k for k in BUNDLE_KEYS if k not in bundle]
    if missing:
        raise ValueError(f"bundle {path} missing keys {missing}")
    if bundle["version"] != BUNDLE_VERSION:
        raise ValueError(f"bundle version {bundle['version']!r} != "
                         f"{BUNDLE_VERSION}")
    kind = bundle["trigger"].get("kind")
    if kind not in TRIGGERS:
        raise ValueError(f"unknown trigger kind {kind!r}")
    return bundle


def find_postmortems(run_dir: str) -> list[str]:
    try:
        names = sorted(os.listdir(run_dir))
    except OSError:
        return []
    return [os.path.join(run_dir, n) for n in names
            if n.startswith(BUNDLE_PREFIX) and n.endswith(".json")]


def format_postmortem(bundle: dict) -> str:
    """Human-readable rendering — shared by scripts/postmortem.py and
    telemetry_summary --postmortem (one schema, two consumers)."""
    trig = bundle["trigger"]
    lines = [
        f"postmortem: {trig.get('kind')}  (host {bundle['host']}, "
        f"pid {bundle['pid']})",
        f"  written_at: {time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime(bundle['written_at']))}",
    ]
    extra = {k: v for k, v in trig.items() if k != "kind"}
    if extra:
        lines.append(f"  detail: {json.dumps(extra, sort_keys=True)}")
    gang = bundle.get("gang") or {}
    if gang:
        lines.append("  gang: " + ", ".join(
            f"{k}={v}" for k, v in sorted(gang.items())))
    mem = bundle.get("memory") or {}
    if mem:
        rss = mem.get("host_rss_bytes", 0)
        lines.append(f"  memory: host_rss={rss / 1e6:.1f} MB"
                     + "".join(f", {k}={v / 1e6:.1f} MB"
                               for k, v in sorted(
                                   (mem.get("trees") or {}).items())))
        for did, d in sorted((mem.get("devices") or {}).items()):
            lines.append(f"    device {did}: live="
                         f"{d['live_bytes'] / 1e6:.1f} MB peak="
                         f"{d['peak_bytes'] / 1e6:.1f} MB")
    slo = bundle.get("slo") or {}
    if slo:
        lines.append("  slo states:")
        for name, st in sorted(slo.items()):
            mark = "BREACHED" if st.get("breached") else "ok"
            cur = st.get("current")
            cur_s = f"{cur:.4g}" if isinstance(cur, (int, float)) else "-"
            rule = st.get("rule", {})
            lines.append(
                f"    {name:<24} {mark:<9} current={cur_s} "
                f"{rule.get('op', '?')} {rule.get('threshold', '?')} "
                f"(breaches={st.get('breaches', 0)}, "
                f"samples={st.get('samples', 0)})")
    serve = bundle.get("serve") or {}
    if serve:
        lines.append("  serve: " + json.dumps(serve, sort_keys=True))
    ring = bundle.get("ring") or []
    lines.append(f"  ring: {len(ring)} records")
    for rec in ring[-10:]:
        nm = rec.get("name", rec.get("type"))
        lines.append(f"    [{rec.get('phase', '?'):<8}] "
                     f"{rec.get('type', '?'):<8} {nm} "
                     f"rank={rec.get('rank')}")
    tail = bundle.get("log_tail") or []
    if tail:
        lines.append(f"  log tail ({len(tail)} lines):")
        lines.extend(f"    {ln}" for ln in tail[-10:])
    return "\n".join(lines)
