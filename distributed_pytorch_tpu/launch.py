"""Multi-process launcher: the torchrun equivalent, with failure detection.

The reference launches DDP via ``torchrun --nproc_per_node=1 --nnodes=4
--node_rank=R --master_addr=M --master_port=6585 main_ddp.py`` (reference
start_ddp.sh:1) — torchrun's elastic agent spawns the worker and exports the
MASTER_ADDR/MASTER_PORT/WORLD_SIZE/LOCAL_WORLD_SIZE/LOCAL_RANK/RANK env-var
convention that main_ddp.py:93-100 reads.  This module is the framework's own
launcher speaking the same contract:

  python -m distributed_pytorch_tpu.launch --nnodes 4 --node-rank R \
      --master-addr M --master-port 6585 -- \
      -m distributed_pytorch_tpu.cli --rendezvous env --strategy ddp

Two deliberate upgrades over the reference's setup:

- **Failure detection.** The reference's ``timeout=None`` rendezvous
  (main_all_reduce.py:96) and unconfigured torchrun (no ``--max_restarts``,
  start_ddp.sh:1) mean a dead peer hangs the gang forever (SURVEY.md 2.3/5).
  Here the agent polls its children; when one exits non-zero, the rest are
  terminated (SIGTERM, then SIGKILL after a grace period) and the gang is
  either restarted (``--max-restarts N``, elastic-style) or the launcher
  exits with the failed worker's code.  SIGTERM to the launcher itself also
  tears the gang down (no orphaned workers holding chips).

  Multi-node restarts are COORDINATED through a generation-numbered
  rendezvous (torchrun's round concept): the node-0 agent hosts a tiny TCP
  coordinator (master_port+1); every agent passes a barrier per generation
  before spawning, reports local worker failures to the coordinator, and
  polls it so a death on ANY node tears down every node's workers within
  the monitor interval.  All agents then rejoin the barrier for generation
  g+1 and respawn together — no mixed-generation gangs.  Workers see their
  generation as ``RESTART_ATTEMPT`` (checkpoint/resume hook).
- **TPU process model.** On TPU one *process per host* owns all local chips
  (JAX single-controller-per-host), so ``--nproc-per-node`` defaults to 1 and
  values >1 are for CPU simulation/testing, where each worker is given a
  disjoint slice of fake devices.
- **Elastic resize** (``--elastic --min-nodes M --max-nodes N``, round 12).
  Restart-at-the-same-size costs the whole gang for one lost member; elastic
  mode makes a worker loss cost a RESHARD instead.  The agent gains
  heartbeat-based liveness (workers publish ``hb_rank<R>.json`` into
  ``ELASTIC_DIR`` each step — a HUNG straggler is detected by heartbeat
  staleness, not just a dead PID), and on worker loss with at least
  ``min_nodes`` survivors it drives a GENERATION BUMP instead of a restart:
  survivors are drained gracefully (SIGTERM -> they exit the step loop at a
  sync point, flush a checkpoint, and exit ``ELASTIC_DRAIN_EXIT_CODE``),
  then the gang re-rendezvouses at the smaller world size and resumes from
  the last-good checkpoint, resharded across the new topology
  (parallel/elastic.py is the worker-side half; utils/checkpoint.py
  ``load_resharded`` is the reshard).  When the lost slot becomes eligible
  again (``rejoin_delay_s``) and the shrunk gang has provably advanced
  (heartbeat steps moved >= ``grow_after_steps``), the same machinery GROWS
  the gang back at the next boundary.  Both transitions are recorded as
  ``GangResult.resize_events``; drain outcomes (how many workers flushed vs
  needed SIGKILL) land in ``GangResult.drain``.  Elastic mode currently
  drives ONE agent's workers (``--nnodes 1``, the CPU-simulation topology
  every gang test uses; one worker == one "node"); coordinated multi-agent
  membership is the carried-forward half (ROADMAP).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field

# jax-free by design (the agent must never compete with workers for
# chips): utils/__init__ resolves submodules lazily (PEP 562), and both
# utils.telemetry and utils.logging import no jax — the agent's gang
# lifecycle events and structured logs ride the same machinery as the
# workers' without breaking the process-model contract above.
from .utils import monitor, telemetry
from .utils.logging import get_logger, setup_logging


def _tel_event(name: str, **args) -> None:
    """Gang lifecycle on the unified timeline (round 13): worker
    start/exit, heartbeat staleness, drain outcomes, and resize
    generations land as events in the 'gang' lane when the agent runs
    with --telemetry-dir; free otherwise.  The agent registers as
    pid -1 ("agent") in the merged trace; its CURRENT generation rides
    in args (the registry's gen is per-process, and the agent spans
    every generation)."""
    tel = telemetry.active()
    if tel is not None:
        tel.event(name, phase="gang", **args)

# Exit code of chaos-harness-injected crashes.  Kept in sync with
# utils/faults.FAULT_EXIT_CODE rather than imported: faults.py imports
# jax, and the agent process must stay jax-free (it supervises workers;
# it must never compete with them for chips or import time).  Pinned by
# tests/test_faults.py::test_fault_exit_code_constants_agree.
FAULT_EXIT_CODE = 77

# Elastic-gang exit codes (round 12).  Workers use them to tell the agent
# HOW they left; the agent must never confuse either with a failure.
# Defined here (the jax-free side) and imported by parallel/elastic.py —
# the worker-side half — so the two can never drift.
#
# DRAIN: the worker honored an agent-initiated drain (SIGTERM) at a step
# boundary — it flushed its checkpoint and exited ready to re-rendezvous.
ELASTIC_DRAIN_EXIT_CODE = 78
# RESIZE: the worker itself REQUESTS a gang resize (the training sentry's
# escalation rung between rollback-and-skip and abort): it rolled back to
# last-good, checkpointed, and left at a sync point.  The agent treats the
# exit like a lost worker — survivors drain and the gang re-rendezvouses
# one smaller — but classifies the event as "requested".
ELASTIC_RESIZE_EXIT_CODE = 79

# Env contract the elastic agent exports to workers (beyond the torchrun
# vars): the heartbeat/run directory and the resize bounds.
ELASTIC_DIR_ENV = "ELASTIC_DIR"
ELASTIC_MIN_ENV = "ELASTIC_MIN_NODES"
ELASTIC_MAX_ENV = "ELASTIC_MAX_NODES"
HEARTBEAT_PREFIX = "hb_rank"  # hb_rank<R>.json, written atomically

DEFAULT_PORT = 6585  # reference start_ddp.sh:1 / main_all_reduce.py:96
TERM_GRACE_S = 10.0
BARRIER_TIMEOUT_S = 600.0   # max skew between agents reaching a generation
RPC_TIMEOUT_S = 5.0         # status/fail round-trip budget
CONNECT_RETRY_S = 60.0      # waiting for the node-0 coordinator to come up


# ---------------------------------------------------------------------------
# heartbeat reading + liveness verdicts: ONE copy, shared by the elastic
# agent below and the serving fleet's router (fleet/router.py).  Both
# supervise members that publish atomic hb_rank<R>.json beacons
# (parallel/elastic.Heartbeat, fleet/replica.BatcherReplica), and both
# need the same judgment call: a member that has NEVER beaten is a cold
# start (long compile) judged by PID liveness alone, never by silence.

def heartbeat_path(run_dir: str, rank: int) -> str:
    """Where member ``rank``'s beacon lands (the Heartbeat contract)."""
    return os.path.join(run_dir, f"{HEARTBEAT_PREFIX}{rank}.json")


def read_heartbeat(path: str) -> dict | None:
    """One atomically-published beacon: {rank, step, gen, time, age_s};
    None for missing/torn/half-typed files (beats are tmp+rename, so
    the next one lands whole — a missed read is late detection, not a
    death).  ``time`` is informational and optional — age is judged
    from the file's mtime, so beacons that publish only
    {rank, step, gen} stay supervisable."""
    try:
        with open(path) as f:
            hb = json.load(f)
        mtime = os.path.getmtime(path)
        return {"rank": int(hb["rank"]), "step": int(hb["step"]),
                "gen": int(hb["gen"]), "time": float(hb.get("time", mtime)),
                "age_s": time.time() - mtime}
    except (OSError, ValueError, KeyError, TypeError):
        return None


def pid_alive(pid: int | None) -> bool:
    """POSIX existence probe (signal 0).  Permission errors mean the
    process exists; no pid to probe reads as dead."""
    if not pid:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True
    return True


def heartbeat_verdict(hb: dict | None, *, stale_s: float,
                      gen: int | None = None,
                      pid: int | None = None) -> str:
    """Classify one member from its newest beat (``read_heartbeat``):

    - ``"cold"`` — never beaten (in generation ``gen``, when given):
      still compiling / still spawning.  Silence before the first beat
      must NEVER read as a hang;
    - ``"lost"`` — cold AND the given ``pid`` is gone: the process died
      before it ever beat (the only judgment PID liveness may make);
    - ``"fresh"`` — newest beat younger than ``stale_s``;
    - ``"stale"`` — beaten, then silent past ``stale_s``: a HUNG member
      (wedged collective, live PID), the case PID polling cannot see.
    """
    if hb is None or (gen is not None and hb["gen"] != gen):
        return ("lost" if pid is not None and not pid_alive(pid)
                else "cold")
    return "stale" if hb["age_s"] > stale_s else "fresh"


class _Coordinator:
    """Generation rendezvous service hosted by the node-0 agent.

    The barrier counts CHANGING membership (round 19, the carried
    elastic half): it releases a generation when every CURRENT member
    has arrived — not a fixed ``nnodes`` — so ``join``/``leave`` let
    the gang grow/shrink between generations without a fixed-size
    rendezvous.  A ``leave`` during a wait re-evaluates the barrier
    (the departed node must not wedge survivors), and barrier replies
    carry the membership the generation rendezvoused at, so arrivals
    spawn at the CURRENT world size.  With membership never touched,
    every condition degrades to the fixed-``nnodes`` behavior.

    One JSON message per TCP connection:
      {"op": "barrier", "node": R, "gen": G} -> blocks until every
          current member arrives at generation G (or abort) ->
          {"ok": bool, "abort", "world_size", "members"}
      {"op": "join", "node": R}              -> R becomes a member from
          the next barrier on -> {"ok", "world_size", "members"}
      {"op": "leave", "node": R}             -> R stops being counted
          (and stops blocking any in-flight barrier) -> same reply
      {"op": "fail", "gen": G, "code": C}    -> records G as failed
      {"op": "status", "gen": G}             -> {"failed", "code", "abort"}
      {"op": "done", "node": R}              -> node R is finished (its own
          gang result is settled): no further generations, but running
          gangs are NOT torn down
      {"op": "abort"}                        -> no further generations AND
          running workers should be terminated (fatal)
    """

    def __init__(self, nnodes: int, port: int):
        self.nnodes = nnodes
        self.members: set[int] = set(range(nnodes))
        self.cond = threading.Condition()
        self.arrived: dict[int, set[int]] = {}
        self.failed: dict[int, int] = {}
        self.abort = False
        self.done = False
        self.finished: set[int] = set()
        self.srv = socket.create_server(("0.0.0.0", port))
        threading.Thread(target=self._serve, daemon=True).start()

    def _membership(self) -> dict:
        # callers hold self.cond
        return {"world_size": len(self.members),
                "members": sorted(self.members)}

    def _serve(self) -> None:
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:  # closed
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        with conn:
            try:
                # Bound the request read: a client that connects but never
                # sends a line must not pin this handler thread (and, for
                # 'barrier', the condition path) forever.  Barrier gets the
                # long budget — its request line may lag a slow agent.
                conn.settimeout(BARRIER_TIMEOUT_S)
                msg = json.loads(conn.makefile("r").readline())
                op = msg["op"]
                if op == "barrier":
                    gen = msg["gen"]
                    with self.cond:
                        self.arrived.setdefault(gen, set()).add(msg["node"])
                        self.cond.notify_all()
                        # every CURRENT member present (membership may
                        # shrink mid-wait — re-evaluated on notify)
                        ok = self.cond.wait_for(
                            lambda: (self.members
                                     <= self.arrived.get(gen, set())
                                     or self.abort or self.done),
                            timeout=BARRIER_TIMEOUT_S)
                        reply = {"ok": (bool(ok) and not self.abort
                                        and not self.done),
                                 "abort": self.abort,
                                 **self._membership()}
                elif op in ("join", "leave"):
                    node = int(msg["node"])
                    with self.cond:
                        if op == "join":
                            self.members.add(node)
                        else:
                            self.members.discard(node)
                        self.cond.notify_all()
                        reply = {"ok": True, **self._membership()}
                elif op == "fail":
                    with self.cond:
                        self.failed.setdefault(msg["gen"],
                                               int(msg.get("code", 1)))
                        self.cond.notify_all()
                    reply = {"ok": True}
                elif op == "done":
                    with self.cond:
                        self.done = True
                        self.finished.add(int(msg.get("node", -1)))
                        self.cond.notify_all()
                    reply = {"ok": True}
                elif op == "abort":
                    with self.cond:
                        self.abort = True
                        self.cond.notify_all()
                    reply = {"ok": True}
                else:  # status
                    gen = msg["gen"]
                    with self.cond:
                        reply = {"failed": gen in self.failed,
                                 "code": self.failed.get(gen, 0),
                                 "abort": self.abort}
                conn.sendall((json.dumps(reply) + "\n").encode())
            except (OSError, ValueError, KeyError):
                pass

    def wait_all_finished(self, timeout: float) -> bool:
        """Block until every CURRENT member has reported done (so peers
        still polling never see a vanished coordinator; departed members
        owe nothing); False on timeout."""
        with self.cond:
            return self.cond.wait_for(
                lambda: self.members <= self.finished, timeout=timeout)

    def close(self) -> None:
        try:
            self.srv.close()
        except OSError:
            pass


def _rpc(addr: str, port: int, msg: dict, timeout: float) -> dict:
    with socket.create_connection((addr, port), timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall((json.dumps(msg) + "\n").encode())
        return json.loads(s.makefile("r").readline())


@dataclass
class WorkerSpec:
    """One worker process's identity within the gang (the env contract of
    reference main_ddp.py:93-100)."""

    rank: int
    local_rank: int
    node_rank: int
    world_size: int
    local_world_size: int
    master_addr: str
    master_port: int

    def env(self) -> dict[str, str]:
        env = dict(os.environ)
        env.update(
            MASTER_ADDR=self.master_addr,
            MASTER_PORT=str(self.master_port),
            WORLD_SIZE=str(self.world_size),
            LOCAL_WORLD_SIZE=str(self.local_world_size),
            RANK=str(self.rank),
            LOCAL_RANK=str(self.local_rank),
            NODE_RANK=str(self.node_rank),
        )
        return env


@dataclass
class ElasticConfig:
    """Elastic-gang policy for one agent (round 12).

    ``min_workers``/``max_workers`` bound the gang size (one worker == one
    "node" in the single-agent topology).  ``heartbeat_timeout_s`` is the
    hung-straggler bound: a worker whose newest CURRENT-GENERATION
    heartbeat is older than this is killed and treated as lost (a worker
    that never beat — e.g. still compiling — is judged by PID only, so a
    long cold compile cannot be misread as a hang).  ``drain_grace_s`` is
    how long survivors get to reach a sync point, flush their checkpoint
    and exit ``ELASTIC_DRAIN_EXIT_CODE`` before SIGKILL.  A lost slot
    becomes respawn-eligible ``rejoin_delay_s`` after the loss, and the
    gang grows back only once every live worker's heartbeat step has
    advanced >= ``grow_after_steps`` within the current generation — the
    shrunk gang must provably train (and hence checkpoint) before the
    grow-back costs another reshard."""

    min_workers: int = 1
    max_workers: int = 1
    heartbeat_timeout_s: float = 300.0
    drain_grace_s: float = 30.0
    rejoin_delay_s: float = 0.0
    grow_after_steps: int = 1
    # Resize budget: total SHRINKS the run may absorb before the gang is
    # declared failed (grow-backs are free).  Without a cap, a slot that
    # deterministically crashes (bad host, poisoned env) would drive an
    # unbounded shrink/grow oscillation; with one, the repeated loss
    # eventually surfaces as the failure it is.  ``--max-restarts`` is
    # NOT consulted in elastic mode — resizes replace restarts.
    max_resizes: int = 16
    run_dir: str | None = None  # heartbeat dir (default: mkdtemp)

    def __post_init__(self):
        if not 1 <= self.min_workers <= self.max_workers:
            raise ValueError(
                f"elastic bounds must satisfy 1 <= min <= max, got "
                f"[{self.min_workers}, {self.max_workers}]")
        if self.max_resizes < 1:
            raise ValueError(
                f"max_resizes must be >= 1, got {self.max_resizes}")


@dataclass
class GangResult:
    """Outcome of one gang attempt.

    ``injected_failures`` counts worker deaths the agent CLASSIFIED as
    fault-injected (exit code ``faults.FAULT_EXIT_CODE`` — the chaos
    harness's distinctive code, utils/faults.py) across all generations;
    they feed the same ``--max-restarts`` budget as genuine failures
    (an injected crash must exercise the REAL restart path), but the
    classification separates "the chaos test fired" from "production
    fell over" in logs and results.

    ``resize_events`` (elastic mode) records every world-size change as
    ``{"gen", "kind" ("shrink"/"grow"), "from_size", "to_size",
    "reason", "rank"}``; ``drain`` accumulates graceful-drain outcomes
    across all teardowns: how many workers exited the step loop cleanly
    on SIGTERM ("drained" = flushed-checkpoint DRAIN exits, "exited" =
    other voluntary exits) versus had to be SIGKILLed ("killed")."""

    returncode: int
    failed_rank: int | None = None
    restarts_used: int = 0
    per_rank: dict[int, int] = field(default_factory=dict)
    injected_failures: int = 0
    resize_events: list = field(default_factory=list)
    drain: dict = field(default_factory=dict)

    @property
    def injected(self) -> bool:
        """The FINAL failure (if any) was a classified injected fault."""
        return self.returncode == FAULT_EXIT_CODE


class LocalAgent:
    """Spawns and supervises this node's workers (torchrun's elastic agent).

    ``argv`` is passed to the Python interpreter verbatim, so both script
    paths (``train.py ...``) and modules (``-m pkg.cli ...``) work.
    """

    def __init__(
        self,
        argv: list[str],
        *,
        nnodes: int = 1,
        node_rank: int = 0,
        nproc_per_node: int = 1,
        master_addr: str = "127.0.0.1",
        master_port: int = DEFAULT_PORT,
        max_restarts: int = 0,
        monitor_interval_s: float = 0.1,
        agent_port: int | None = None,
        elastic: ElasticConfig | None = None,
        log=print,
    ):
        self.argv = argv
        self.nnodes = nnodes
        self.node_rank = node_rank
        self.nproc = nproc_per_node
        self.master_addr = master_addr
        self.master_port = master_port
        self.max_restarts = max_restarts
        self.monitor_interval_s = monitor_interval_s
        # coordinator endpoint (nnodes > 1): node 0 hosts, everyone dials
        self.agent_port = (agent_port if agent_port is not None
                           else master_port + 1)
        self.elastic = elastic
        if elastic is not None and nnodes > 1:
            raise ValueError(
                "elastic resize drives one agent's workers (nnodes=1, the "
                "worker-per-'node' CPU-simulation topology); coordinated "
                "multi-agent membership is the carried-forward half "
                "(ROADMAP 'Elastic gang + async relaxations')")
        # agent log lines also feed the monitor's bounded log ring so a
        # postmortem bundle carries the supervision trail

        def _log(msg, _inner=log):
            monitor.log_line(str(msg))
            _inner(msg)
        self.log = _log
        self._procs: dict[int, subprocess.Popen] = {}
        self._gen = 0  # current rendezvous generation (RESTART_ATTEMPT)
        # the membership the newest barrier rendezvoused at (None until
        # a coordinated generation has passed one) — _barrier records it
        self._barrier_world: int | None = None
        # graceful-drain accounting across every teardown of this run
        # (satellite: _terminate_all outcome rides GangResult.drain)
        self._drain_stats = {"drained": 0, "exited": 0, "killed": 0}

    def specs(self) -> list[WorkerSpec]:
        return self._specs_for(self.nproc)

    def _specs_for(self, nproc: int) -> list[WorkerSpec]:
        world = self.nnodes * nproc
        return [
            WorkerSpec(
                rank=self.node_rank * nproc + lr,
                local_rank=lr,
                node_rank=self.node_rank,
                world_size=world,
                local_world_size=nproc,
                master_addr=self.master_addr,
                master_port=self.master_port,
            )
            for lr in range(nproc)
        ]

    # -- process management ------------------------------------------------
    def _spawn(self, nproc: int | None = None,
               extra_env: dict[str, str] | None = None) -> None:
        for spec in self._specs_for(nproc if nproc is not None
                                    else self.nproc):
            cmd = [sys.executable] + self.argv
            env = spec.env()
            env["RESTART_ATTEMPT"] = str(self._gen)
            if extra_env:
                env.update(extra_env)
            self._procs[spec.rank] = subprocess.Popen(cmd, env=env)
            self.log(f"[launch] node {self.node_rank}: started rank "
                     f"{spec.rank} (pid {self._procs[spec.rank].pid})")
            _tel_event("worker_start", rank=spec.rank, gen=self._gen,
                       pid=self._procs[spec.rank].pid,
                       world_size=spec.world_size)

    def _terminate_all(self, grace_s: float = TERM_GRACE_S) -> dict:
        """Graceful drain: SIGTERM the gang first (workers may reach a
        sync point, flush their last checkpoint, and exit — the elastic
        contract exits ``ELASTIC_DRAIN_EXIT_CODE``), escalate to SIGKILL
        only after ``grace_s``.  Returns this teardown's outcome counts
        and accumulates them into the run-wide ``GangResult.drain``
        accounting: {"drained": DRAIN-code exits, "exited": other
        voluntary exits under SIGTERM, "killed": needed SIGKILL}."""
        outcome = {"drained": 0, "exited": 0, "killed": 0}
        live = [p for p in self._procs.values() if p.poll() is None]
        for p in live:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + grace_s
        for p in live:
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
                p.wait()
                outcome["killed"] += 1
            elif p.returncode == ELASTIC_DRAIN_EXIT_CODE:
                outcome["drained"] += 1
            else:
                outcome["exited"] += 1
        for k, v in outcome.items():
            self._drain_stats[k] += v
        if live:
            _tel_event("gang_drain", gen=self._gen, **outcome)
        return outcome

    def _gang_view(self, size: int | None = None) -> dict:
        """Gang membership as the agent sees it (the bundle's ``gang``
        section): topology, generation, and each rank's exit state."""
        return {
            "nnodes": self.nnodes, "node_rank": self.node_rank,
            "world_size": self.nnodes * (size if size is not None
                                         else self.nproc),
            "gen": self._gen,
            "ranks": {r: p.poll() for r, p in self._procs.items()},
        }

    def _postmortem(self, trigger: str, size: int | None = None,
                    **detail) -> str | None:
        """Flight recorder at the agent's failure-classification points
        (round 15).  Only fires when the run has a telemetry dir — the
        agent's own registry or the exported TELEMETRY_DIR the workers
        wrote to; a bare gang has nowhere to put a bundle."""
        tel = telemetry.active()
        run_dir = (tel.run_dir if tel is not None
                   else os.environ.get(telemetry.TELEMETRY_DIR_ENV))
        if not run_dir:
            return None
        return monitor.write_postmortem(
            trigger, run_dir=run_dir, tel=tel, detail=detail,
            gang=self._gang_view(size))

    def _monitor(self, watch_remote: bool = False) -> GangResult:
        """Block until the gang finishes or any worker fails.

        This is the failure *detection* the reference lacks: a non-zero or
        signal-killed worker is noticed within ``monitor_interval_s`` and
        the survivors are torn down instead of hanging in a collective.
        With ``watch_remote`` the coordinator is polled too, so a worker
        death on ANOTHER node tears this node's workers down as promptly.
        """
        last_remote_check = 0.0
        while True:
            running = False
            for rank, p in self._procs.items():
                code = p.poll()
                if code is None:
                    running = True
                elif code != 0:
                    kind = ("injected fault" if code == FAULT_EXIT_CODE
                            else "failure")
                    self.log(f"[launch] rank {rank} FAILED with exit code "
                             f"{code} ({kind}); terminating gang")
                    _tel_event("worker_exit", rank=rank, gen=self._gen,
                               code=code, kind=kind)
                    self._postmortem("worker_fault", rank=rank,
                                     code=code, classified=kind)
                    self._terminate_all()
                    return GangResult(
                        returncode=code,
                        failed_rank=rank,
                        per_rank={r: q.returncode
                                  for r, q in self._procs.items()},
                        injected_failures=int(code == FAULT_EXIT_CODE),
                    )
            if not running:
                return GangResult(
                    returncode=0,
                    per_rank={r: p.returncode
                              for r, p in self._procs.items()},
                )
            now = time.monotonic()
            if watch_remote and now - last_remote_check >= max(
                    self.monitor_interval_s, 0.2):
                last_remote_check = now
                rep = None
                for attempt in (0, 1):  # one retry: a single RST/timeout
                    try:                # must not consume a restart budget
                        rep = self._rpc_coord(
                            {"op": "status", "gen": self._gen},
                            RPC_TIMEOUT_S)
                        break
                    except (OSError, ValueError):
                        if attempt == 0:
                            time.sleep(0.5)
                if rep is None:
                    rep = {"failed": False, "abort": True, "code": 1}
                    self.log("[launch] coordinator unreachable; "
                             "terminating gang")
                if rep.get("failed") or rep.get("abort"):
                    self.log(f"[launch] remote failure in generation "
                             f"{self._gen}; terminating local workers")
                    self._terminate_all()
                    return GangResult(
                        returncode=rep.get("code") or 1,
                        per_rank={r: q.returncode
                                  for r, q in self._procs.items()},
                    )
            time.sleep(self.monitor_interval_s)

    # -- elastic resize (round 12) ----------------------------------------
    def _heartbeats(self, run_dir: str) -> dict[int, dict]:
        """Read every rank's newest heartbeat: {rank: {"step", "gen",
        "age_s"}}.  Heartbeats are single-JSON files written atomically
        by parallel/elastic.py Heartbeat; unreadable/half-written files
        are skipped (the next beat lands whole)."""
        out: dict[int, dict] = {}
        try:
            names = os.listdir(run_dir)
        except OSError:
            return out
        for name in names:
            if not (name.startswith(HEARTBEAT_PREFIX)
                    and name.endswith(".json")):
                continue
            hb = read_heartbeat(os.path.join(run_dir, name))
            if hb is not None:
                out[hb["rank"]] = hb
        return out

    def _clear_heartbeats(self, run_dir: str) -> None:
        try:
            for name in os.listdir(run_dir):
                if name.startswith(HEARTBEAT_PREFIX):
                    try:
                        os.remove(os.path.join(run_dir, name))
                    except OSError:
                        pass
        except OSError:
            pass

    def _run_elastic(self) -> GangResult:
        """Elastic supervision: worker loss (dead PID, hung heartbeat, or
        a worker-requested resize) within [min, max] costs a generation
        bump — drain survivors at a sync point, re-rendezvous smaller,
        resume from the resharded checkpoint — instead of the job; the
        gang grows back once the lost slot is eligible again and the
        shrunk gang has provably advanced."""
        cfg = self.elastic
        run_dir = cfg.run_dir or tempfile.mkdtemp(prefix="elastic_gang_")
        os.makedirs(run_dir, exist_ok=True)
        size = cfg.max_workers
        lost_at: list[float] = []   # when each currently-lost slot died
        injected = 0
        events: list[dict] = []

        def finish(code: int, failed_rank=None, per_rank=None) -> GangResult:
            return GangResult(
                returncode=code, failed_rank=failed_rank,
                restarts_used=self._gen,
                per_rank=per_rank if per_rank is not None else
                {r: p.returncode for r, p in self._procs.items()},
                injected_failures=injected, resize_events=events)

        while True:
            self._clear_heartbeats(run_dir)
            self._procs = {}
            self._spawn(size, extra_env={
                ELASTIC_DIR_ENV: run_dir,
                ELASTIC_MIN_ENV: str(cfg.min_workers),
                ELASTIC_MAX_ENV: str(cfg.max_workers),
            })
            try:
                kind, info = self._monitor_elastic(run_dir, size, lost_at)
            except BaseException:
                # Ctrl-C / SIGTERM to the agent: workers still get the
                # CONFIGURED drain window to flush their checkpoint (an
                # operator who set --drain-grace 60 for slow saves must
                # not have teardown SIGKILL them at the 10 s default)
                self._terminate_all(grace_s=cfg.drain_grace_s)
                raise
            if kind == "done":
                return finish(0, per_rank=info)
            if kind == "grow":
                n_back = info
                self.log(f"[launch] elastic: {n_back} lost slot(s) "
                         f"rejoining; draining gang of {size} to grow to "
                         f"{size + n_back}")
                self._terminate_all(grace_s=cfg.drain_grace_s)
                events.append({"gen": self._gen, "kind": "grow",
                               "from_size": size, "to_size": size + n_back,
                               "reason": "rejoin", "rank": None})
                _tel_event("gang_resize", **events[-1])
                size += n_back
                del lost_at[:n_back]
                self._gen += 1
                continue
            # kind == "lost": a worker died / hung / requested a resize.
            # Shrink by exactly the ONE lost slot: survivors may be mid-
            # collective with the dead peer and exit messily during the
            # drain (a broken psum is a symptom, not a second loss) —
            # every slot respawns fresh at the new world size anyway.
            rank, code, reason = info
            injected += int(code == FAULT_EXIT_CODE)
            new_size = size - 1
            shrinks = sum(1 for e in events if e["kind"] == "shrink")
            if shrinks >= cfg.max_resizes:
                self.log(f"[launch] elastic: rank {rank} lost ({reason}) "
                         f"after {shrinks} shrinks — resize budget "
                         f"max_resizes={cfg.max_resizes} exhausted; "
                         f"terminating gang")
                self._terminate_all(grace_s=cfg.drain_grace_s)
                return finish(code or 1, failed_rank=rank)
            if new_size < cfg.min_workers:
                self.log(f"[launch] elastic: rank {rank} lost ({reason}) "
                         f"leaves {new_size} < min_nodes="
                         f"{cfg.min_workers}; terminating gang")
                self._terminate_all(grace_s=cfg.drain_grace_s)
                return finish(code or 1, failed_rank=rank)
            self.log(f"[launch] elastic: rank {rank} lost ({reason}); "
                     f"draining survivors and resharding to world size "
                     f"{new_size}")
            self._terminate_all(grace_s=cfg.drain_grace_s)
            events.append({"gen": self._gen, "kind": "shrink",
                           "from_size": size, "to_size": new_size,
                           "reason": reason, "rank": rank})
            _tel_event("gang_resize", **events[-1])
            self._postmortem("elastic_shrink", size=new_size,
                             **{k: v for k, v in events[-1].items()
                                if k != "kind"})
            lost_at.append(time.monotonic())
            size = new_size
            self._gen += 1

    def _monitor_elastic(self, run_dir: str, size: int,
                         lost_at: list[float]):
        """Supervise one elastic generation.  Returns one of
        ("done", per_rank), ("lost", (rank, code, reason)), or
        ("grow", n_slots_rejoining)."""
        cfg = self.elastic
        gen_start_step: dict[int, int] = {}   # rank -> first hb step seen
        last_step: dict[int, int] = {}
        while True:
            per_rank: dict[int, int] = {}
            running = []
            for rank, p in self._procs.items():
                code = p.poll()
                per_rank[rank] = code
                if code is None:
                    running.append(rank)
                elif code == ELASTIC_RESIZE_EXIT_CODE:
                    self.log(f"[launch] rank {rank} requested a gang "
                             f"resize (exit {code})")
                    _tel_event("worker_exit", rank=rank, gen=self._gen,
                               code=code, kind="requested resize")
                    return "lost", (rank, 0, "requested")
                elif code not in (0,):
                    kind = ("injected fault" if code == FAULT_EXIT_CODE
                            else "failure")
                    self.log(f"[launch] rank {rank} FAILED with exit code "
                             f"{code} ({kind})")
                    _tel_event("worker_exit", rank=rank, gen=self._gen,
                               code=code, kind=kind)
                    self._postmortem("worker_fault", size=size,
                                     rank=rank, code=code,
                                     classified=kind)
                    return "lost", (rank, code, kind)
            if not running:
                return "done", per_rank
            # heartbeat staleness: one shared verdict (heartbeat_verdict
            # — the fleet router judges its replicas through the same
            # helper).  "cold" ranks (no beat this generation — still
            # compiling) are ineligible; their PID liveness is already
            # covered by the poll() loop above, so pid=None here.
            beats = self._heartbeats(run_dir)
            for rank in running:
                hb = beats.get(rank)
                verdict = heartbeat_verdict(
                    hb, stale_s=cfg.heartbeat_timeout_s, gen=self._gen)
                if verdict == "cold":
                    continue
                gen_start_step.setdefault(rank, hb["step"])
                last_step[rank] = hb["step"]
                if verdict == "stale":
                    self.log(f"[launch] rank {rank} heartbeat stale "
                             f"({hb['age_s']:.1f}s > "
                             f"{cfg.heartbeat_timeout_s}s); killing hung "
                             f"worker")
                    _tel_event("heartbeat_stale", rank=rank,
                               gen=self._gen, age_s=hb["age_s"],
                               timeout_s=cfg.heartbeat_timeout_s)
                    self._postmortem("worker_fault", size=size,
                                     rank=rank, code=None,
                                     classified="heartbeat_stale",
                                     age_s=hb["age_s"])
                    try:
                        self._procs[rank].kill()
                    except OSError:
                        pass
                    self._procs[rank].wait()
                    return "lost", (rank, 1, "heartbeat")
            # grow back: lost slots past the rejoin delay, once every
            # live rank's heartbeat advanced grow_after_steps in-gen
            if size < cfg.max_workers and lost_at:
                now = time.monotonic()
                eligible = sum(1 for t in lost_at
                               if now - t >= cfg.rejoin_delay_s)
                eligible = min(eligible, cfg.max_workers - size)
                # every still-RUNNING rank must have beaten this gen and
                # advanced enough (ranks that finished and exited 0 no
                # longer gate growth; a rank still compiling does)
                advanced = bool(running) and all(
                    r in last_step
                    and last_step[r] - gen_start_step[r]
                    >= cfg.grow_after_steps
                    for r in running)
                if eligible > 0 and advanced:
                    return "grow", eligible
            time.sleep(self.monitor_interval_s)

    # -- gang orchestration -------------------------------------------------
    def _rpc_coord(self, msg: dict, timeout: float) -> dict:
        return _rpc(self.master_addr, self.agent_port, msg, timeout)

    def _barrier(self, gen: int) -> bool:
        """Arrive at generation ``gen``; True when every current member
        is in.  The node-0 coordinator may come up after us — retry the
        dial.  The reply's membership (round 19: the barrier counts
        CHANGING membership, not a fixed nnodes) is recorded so this
        generation spawns against the world size it rendezvoused at."""
        deadline = time.monotonic() + CONNECT_RETRY_S
        while True:
            try:
                rep = self._rpc_coord(
                    {"op": "barrier", "node": self.node_rank, "gen": gen},
                    BARRIER_TIMEOUT_S + RPC_TIMEOUT_S)
                ws = rep.get("world_size")
                if ws:
                    self._barrier_world = int(ws)
                    if ws != self.nnodes:
                        self.log(f"[launch] generation {gen} rendezvoused "
                                 f"at world size {ws} (membership "
                                 f"changed from {self.nnodes})")
                return bool(rep.get("ok"))
            except (OSError, ValueError):
                if time.monotonic() > deadline:
                    return False
                time.sleep(0.2)

    def run(self) -> GangResult:
        """Run the gang, restarting up to ``max_restarts`` times on failure.

        Single node: plain supervise-and-restart.  Multi node: every
        (re)start passes a coordinator barrier per generation, so all nodes
        always run the same generation (see module docstring).  Elastic
        mode (an ``ElasticConfig``): resize instead of restart — worker
        loss within [min, max] shrinks the gang at a drain boundary; the
        lost slot growing back is the same machinery in reverse.
        """
        if self.elastic is not None:
            result = self._run_elastic()
        elif self.nnodes == 1:
            result = self._run_local()
        else:
            result = self._run_coordinated()
        result.drain = dict(self._drain_stats)
        return result

    def _run_local(self) -> GangResult:
        attempt = 0
        injected = 0
        while True:
            self._gen = attempt
            self._procs = {}
            self._spawn()
            try:
                result = self._monitor()
            except BaseException:
                # Ctrl-C, SIGTERM (via the main() handler), or any agent
                # crash: never leave workers orphaned on the chips.
                self._terminate_all()
                raise
            injected += result.injected_failures
            result.injected_failures = injected
            result.restarts_used = attempt
            if result.returncode == 0 or attempt >= self.max_restarts:
                return result
            attempt += 1
            self.log(f"[launch] restarting gang (attempt {attempt}/"
                     f"{self.max_restarts})")

    def _send(self, msg: dict) -> None:
        """Best-effort coordinator notification."""
        try:
            self._rpc_coord(msg, RPC_TIMEOUT_S)
        except (OSError, ValueError):
            pass

    def _run_coordinated(self) -> GangResult:
        coord = (_Coordinator(self.nnodes, self.agent_port)
                 if self.node_rank == 0 else None)
        try:
            gen = 0
            injected = 0
            last: GangResult | None = None
            while True:
                self._gen = gen
                if not self._barrier(gen):
                    # Denied: another node settled (done/abort) or the
                    # rendezvous timed out.  Report the real failure that
                    # got us here, not a synthetic code.
                    self.log(f"[launch] rendezvous for generation {gen} "
                             f"denied (done/abort/timeout)")
                    return last or GangResult(returncode=1)
                self._procs = {}
                self._spawn()
                try:
                    result = self._monitor(watch_remote=True)
                except BaseException:
                    self._terminate_all()
                    raise
                injected += result.injected_failures
                result.injected_failures = injected
                result.restarts_used = gen
                if result.returncode == 0:
                    # No further generations for laggards — but running
                    # peers finishing this generation are NOT torn down.
                    return result
                last = result
                self._send({"op": "fail", "gen": gen,
                            "code": result.returncode})
                if gen >= self.max_restarts:
                    self._send({"op": "abort"})
                    return result
                gen += 1
                self.log(f"[launch] restarting gang, generation {gen}/"
                         f"{self.max_restarts}")
        finally:
            # Settle this node with the coordinator no matter how we exit,
            # then (node 0) keep the coordinator alive until every node has
            # settled — a vanished coordinator reads as a remote failure to
            # peers still polling.
            self._send({"op": "done", "node": self.node_rank})
            if coord is not None:
                if not coord.wait_all_finished(BARRIER_TIMEOUT_S):
                    self.log("[launch] not all nodes settled before "
                             "coordinator shutdown")
                coord.close()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="distributed_pytorch_tpu.launch",
        description="torchrun-style launcher (reference start_ddp.sh:1) "
                    "with failure detection",
    )
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node-rank", "--node_rank", type=int, default=0)
    p.add_argument("--nproc-per-node", "--nproc_per_node", type=int,
                   default=1,
                   help="processes on this node (TPU: 1 per host owns all "
                        "local chips; >1 is for CPU simulation)")
    p.add_argument("--master-addr", "--master_addr", default="127.0.0.1")
    p.add_argument("--master-port", "--master_port", type=int,
                   default=DEFAULT_PORT)
    p.add_argument("--max-restarts", type=int, default=0,
                   help="elastic restarts of the whole gang on worker "
                        "failure (torchrun leaves this 0 too, but the "
                        "reference never sets it — start_ddp.sh:1)")
    p.add_argument("--monitor-interval", type=float, default=0.1,
                   help="seconds between worker liveness polls")
    p.add_argument("--agent-port", type=int, default=None,
                   help="coordinator port for multi-node restarts "
                        "(default master_port+1; node 0 hosts)")
    # elastic resize (round 12): detect worker loss, shrink the gang at a
    # drain boundary, reshard from checkpoint, keep training; grow back
    # when the slot rejoins.
    p.add_argument("--elastic", action="store_true",
                   help="resize instead of restart: a worker loss within "
                        "[--min-nodes, --max-nodes] drains the survivors "
                        "at a sync point and re-rendezvouses one smaller "
                        "(resuming from the resharded checkpoint); the "
                        "gang grows back when the slot rejoins")
    p.add_argument("--min-nodes", type=int, default=1,
                   help="elastic: smallest world size worth training at "
                        "(fewer survivors fails the gang)")
    p.add_argument("--max-nodes", type=int, default=None,
                   help="elastic: largest world size (default "
                        "--nproc-per-node); the gang starts here and "
                        "grows back to it")
    p.add_argument("--heartbeat-timeout", type=float, default=300.0,
                   help="elastic: a worker whose newest heartbeat is "
                        "older than this is a HUNG straggler — killed "
                        "and treated as lost (workers that never beat "
                        "are judged by PID only)")
    p.add_argument("--drain-grace", type=float, default=30.0,
                   help="elastic: seconds survivors get to reach a sync "
                        "point and flush their checkpoint on SIGTERM "
                        "before SIGKILL")
    p.add_argument("--rejoin-delay", type=float, default=0.0,
                   help="elastic: seconds after a loss before the slot "
                        "is respawn-eligible (grow-back)")
    p.add_argument("--grow-after-steps", type=int, default=1,
                   help="elastic: grow back only after every live "
                        "worker's heartbeat advanced this many steps in "
                        "the shrunk generation")
    p.add_argument("--max-resizes", type=int, default=16,
                   help="elastic: total shrinks the run may absorb "
                        "before the gang is declared failed (grow-backs "
                        "are free) — bounds the shrink/grow oscillation "
                        "a deterministically-crashing slot would "
                        "otherwise drive forever; replaces "
                        "--max-restarts, which elastic mode ignores")
    p.add_argument("--telemetry-dir", default=None,
                   help="unified run telemetry (round 13): the agent "
                        "logs gang lifecycle events (worker start/exit, "
                        "heartbeat staleness, drains, resize "
                        "generations) into this shared run directory "
                        "and exports it to the workers (TELEMETRY_DIR), "
                        "so every rank's JSONL stream merges into ONE "
                        "Chrome trace (scripts/telemetry_summary.py)")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="worker command: a script path or '-m module', "
                        "optionally preceded by '--'")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    setup_logging()
    log = get_logger("launch")
    if args.telemetry_dir:
        # the agent's own events (rank -1, "agent" in the merged trace)
        # plus the worker env contract: every rank's stream lands in the
        # same run directory, one timeline for the whole gang
        telemetry.enable(args.telemetry_dir, rank=-1, gen=0,
                         label="agent")
        os.environ[telemetry.TELEMETRY_DIR_ENV] = args.telemetry_dir
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        build_parser().error("no worker command given")
    elastic = None
    if args.elastic:
        max_workers = (args.max_nodes if args.max_nodes is not None
                       else args.nproc_per_node)
        if (args.max_nodes is not None and args.nproc_per_node != 1
                and args.nproc_per_node != args.max_nodes):
            build_parser().error(
                f"--elastic: --nproc-per-node {args.nproc_per_node} "
                f"conflicts with --max-nodes {args.max_nodes} (the gang "
                f"starts at max-nodes workers; set one, not both)")
        try:
            elastic = ElasticConfig(
                min_workers=args.min_nodes,
                max_workers=max_workers,
                heartbeat_timeout_s=args.heartbeat_timeout,
                drain_grace_s=args.drain_grace,
                rejoin_delay_s=args.rejoin_delay,
                grow_after_steps=args.grow_after_steps,
                max_resizes=args.max_resizes,
            )
        except ValueError as e:
            build_parser().error(str(e))
        args.nproc_per_node = max_workers
    elif args.max_nodes is not None or args.min_nodes != 1:
        build_parser().error(
            "--min-nodes/--max-nodes configure elastic resize; pass "
            "--elastic (or drop the bounds)")
    try:
        agent = LocalAgent(
            cmd,
            nnodes=args.nnodes,
            node_rank=args.node_rank,
            nproc_per_node=args.nproc_per_node,
            master_addr=args.master_addr,
            master_port=args.master_port,
            max_restarts=args.max_restarts,
            monitor_interval_s=args.monitor_interval,
            agent_port=args.agent_port,
            elastic=elastic,
        )
    except ValueError as e:  # e.g. --elastic with --nnodes > 1
        build_parser().error(str(e))
    # A scheduler's SIGTERM must tear down the gang, not orphan it; raising
    # SystemExit routes through run()'s BaseException cleanup.
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    result = agent.run()
    # round 13: agent reporting routes through the structured logger
    # (greppable, timestamped, rank-tagged like everything else) instead
    # of bare prints; the per-event telemetry already landed live.
    for ev in result.resize_events:
        log.info("resize: gen %d %s %d -> %d (%s)", ev["gen"], ev["kind"],
                 ev["from_size"], ev["to_size"], ev["reason"])
    if result.drain:
        log.info("drain outcome: %s", result.drain)
    if result.returncode != 0:
        log.error("gang failed: rank %s exit %d after %d restarts",
                  result.failed_rank, result.returncode,
                  result.restarts_used)
    _tel_event("gang_done", returncode=result.returncode,
               restarts_used=result.restarts_used,
               resizes=len(result.resize_events), drain=result.drain)
    telemetry.disable()  # flush before the agent exits
    return result.returncode


if __name__ == "__main__":
    sys.exit(main())
