"""Multi-process launcher: the torchrun equivalent, with failure detection.

The reference launches DDP via ``torchrun --nproc_per_node=1 --nnodes=4
--node_rank=R --master_addr=M --master_port=6585 main_ddp.py`` (reference
start_ddp.sh:1) — torchrun's elastic agent spawns the worker and exports the
MASTER_ADDR/MASTER_PORT/WORLD_SIZE/LOCAL_WORLD_SIZE/LOCAL_RANK/RANK env-var
convention that main_ddp.py:93-100 reads.  This module is the framework's own
launcher speaking the same contract:

  python -m distributed_pytorch_tpu.launch --nnodes 4 --node-rank R \
      --master-addr M --master-port 6585 -- \
      -m distributed_pytorch_tpu.cli --rendezvous env --strategy ddp

Two deliberate upgrades over the reference's setup:

- **Failure detection.** The reference's ``timeout=None`` rendezvous
  (main_all_reduce.py:96) and unconfigured torchrun (no ``--max_restarts``,
  start_ddp.sh:1) mean a dead peer hangs the gang forever (SURVEY.md 2.3/5).
  Here the agent polls its children; when one exits non-zero, the rest are
  terminated (SIGTERM, then SIGKILL after a grace period) and the gang is
  either restarted (``--max-restarts N``, elastic-style) or the launcher
  exits with the failed worker's code.  SIGTERM to the launcher itself also
  tears the gang down (no orphaned workers holding chips).

  Multi-node restarts are COORDINATED through a generation-numbered
  rendezvous (torchrun's round concept): the node-0 agent hosts a tiny TCP
  coordinator (master_port+1); every agent passes a barrier per generation
  before spawning, reports local worker failures to the coordinator, and
  polls it so a death on ANY node tears down every node's workers within
  the monitor interval.  All agents then rejoin the barrier for generation
  g+1 and respawn together — no mixed-generation gangs.  Workers see their
  generation as ``RESTART_ATTEMPT`` (checkpoint/resume hook).
- **TPU process model.** On TPU one *process per host* owns all local chips
  (JAX single-controller-per-host), so ``--nproc-per-node`` defaults to 1 and
  values >1 are for CPU simulation/testing, where each worker is given a
  disjoint slice of fake devices.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field

# Exit code of chaos-harness-injected crashes.  Kept in sync with
# utils/faults.FAULT_EXIT_CODE rather than imported: faults.py imports
# jax, and the agent process must stay jax-free (it supervises workers;
# it must never compete with them for chips or import time).  Pinned by
# tests/test_faults.py::test_fault_exit_code_constants_agree.
FAULT_EXIT_CODE = 77

DEFAULT_PORT = 6585  # reference start_ddp.sh:1 / main_all_reduce.py:96
TERM_GRACE_S = 10.0
BARRIER_TIMEOUT_S = 600.0   # max skew between agents reaching a generation
RPC_TIMEOUT_S = 5.0         # status/fail round-trip budget
CONNECT_RETRY_S = 60.0      # waiting for the node-0 coordinator to come up


class _Coordinator:
    """Generation rendezvous service hosted by the node-0 agent.

    One JSON message per TCP connection:
      {"op": "barrier", "node": R, "gen": G} -> blocks until all nnodes
          agents arrive at generation G (or abort) -> {"ok": bool, "abort"}
      {"op": "fail", "gen": G, "code": C}    -> records G as failed
      {"op": "status", "gen": G}             -> {"failed", "code", "abort"}
      {"op": "done", "node": R}              -> node R is finished (its own
          gang result is settled): no further generations, but running
          gangs are NOT torn down
      {"op": "abort"}                        -> no further generations AND
          running workers should be terminated (fatal)
    """

    def __init__(self, nnodes: int, port: int):
        self.nnodes = nnodes
        self.cond = threading.Condition()
        self.arrived: dict[int, set[int]] = {}
        self.failed: dict[int, int] = {}
        self.abort = False
        self.done = False
        self.finished: set[int] = set()
        self.srv = socket.create_server(("0.0.0.0", port))
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self) -> None:
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:  # closed
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        with conn:
            try:
                # Bound the request read: a client that connects but never
                # sends a line must not pin this handler thread (and, for
                # 'barrier', the condition path) forever.  Barrier gets the
                # long budget — its request line may lag a slow agent.
                conn.settimeout(BARRIER_TIMEOUT_S)
                msg = json.loads(conn.makefile("r").readline())
                op = msg["op"]
                if op == "barrier":
                    gen = msg["gen"]
                    with self.cond:
                        self.arrived.setdefault(gen, set()).add(msg["node"])
                        self.cond.notify_all()
                        ok = self.cond.wait_for(
                            lambda: (len(self.arrived.get(gen, ()))
                                     >= self.nnodes or self.abort
                                     or self.done),
                            timeout=BARRIER_TIMEOUT_S)
                    reply = {"ok": (bool(ok) and not self.abort
                                    and not self.done),
                             "abort": self.abort}
                elif op == "fail":
                    with self.cond:
                        self.failed.setdefault(msg["gen"],
                                               int(msg.get("code", 1)))
                        self.cond.notify_all()
                    reply = {"ok": True}
                elif op == "done":
                    with self.cond:
                        self.done = True
                        self.finished.add(int(msg.get("node", -1)))
                        self.cond.notify_all()
                    reply = {"ok": True}
                elif op == "abort":
                    with self.cond:
                        self.abort = True
                        self.cond.notify_all()
                    reply = {"ok": True}
                else:  # status
                    gen = msg["gen"]
                    with self.cond:
                        reply = {"failed": gen in self.failed,
                                 "code": self.failed.get(gen, 0),
                                 "abort": self.abort}
                conn.sendall((json.dumps(reply) + "\n").encode())
            except (OSError, ValueError, KeyError):
                pass

    def wait_all_finished(self, timeout: float) -> bool:
        """Block until every node has reported done (so peers still polling
        never see a vanished coordinator); False on timeout."""
        with self.cond:
            return self.cond.wait_for(
                lambda: len(self.finished) >= self.nnodes, timeout=timeout)

    def close(self) -> None:
        try:
            self.srv.close()
        except OSError:
            pass


def _rpc(addr: str, port: int, msg: dict, timeout: float) -> dict:
    with socket.create_connection((addr, port), timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall((json.dumps(msg) + "\n").encode())
        return json.loads(s.makefile("r").readline())


@dataclass
class WorkerSpec:
    """One worker process's identity within the gang (the env contract of
    reference main_ddp.py:93-100)."""

    rank: int
    local_rank: int
    node_rank: int
    world_size: int
    local_world_size: int
    master_addr: str
    master_port: int

    def env(self) -> dict[str, str]:
        env = dict(os.environ)
        env.update(
            MASTER_ADDR=self.master_addr,
            MASTER_PORT=str(self.master_port),
            WORLD_SIZE=str(self.world_size),
            LOCAL_WORLD_SIZE=str(self.local_world_size),
            RANK=str(self.rank),
            LOCAL_RANK=str(self.local_rank),
            NODE_RANK=str(self.node_rank),
        )
        return env


@dataclass
class GangResult:
    """Outcome of one gang attempt.

    ``injected_failures`` counts worker deaths the agent CLASSIFIED as
    fault-injected (exit code ``faults.FAULT_EXIT_CODE`` — the chaos
    harness's distinctive code, utils/faults.py) across all generations;
    they feed the same ``--max-restarts`` budget as genuine failures
    (an injected crash must exercise the REAL restart path), but the
    classification separates "the chaos test fired" from "production
    fell over" in logs and results."""

    returncode: int
    failed_rank: int | None = None
    restarts_used: int = 0
    per_rank: dict[int, int] = field(default_factory=dict)
    injected_failures: int = 0

    @property
    def injected(self) -> bool:
        """The FINAL failure (if any) was a classified injected fault."""
        return self.returncode == FAULT_EXIT_CODE


class LocalAgent:
    """Spawns and supervises this node's workers (torchrun's elastic agent).

    ``argv`` is passed to the Python interpreter verbatim, so both script
    paths (``train.py ...``) and modules (``-m pkg.cli ...``) work.
    """

    def __init__(
        self,
        argv: list[str],
        *,
        nnodes: int = 1,
        node_rank: int = 0,
        nproc_per_node: int = 1,
        master_addr: str = "127.0.0.1",
        master_port: int = DEFAULT_PORT,
        max_restarts: int = 0,
        monitor_interval_s: float = 0.1,
        agent_port: int | None = None,
        log=print,
    ):
        self.argv = argv
        self.nnodes = nnodes
        self.node_rank = node_rank
        self.nproc = nproc_per_node
        self.master_addr = master_addr
        self.master_port = master_port
        self.max_restarts = max_restarts
        self.monitor_interval_s = monitor_interval_s
        # coordinator endpoint (nnodes > 1): node 0 hosts, everyone dials
        self.agent_port = (agent_port if agent_port is not None
                           else master_port + 1)
        self.log = log
        self._procs: dict[int, subprocess.Popen] = {}
        self._gen = 0  # current rendezvous generation (RESTART_ATTEMPT)

    def specs(self) -> list[WorkerSpec]:
        world = self.nnodes * self.nproc
        return [
            WorkerSpec(
                rank=self.node_rank * self.nproc + lr,
                local_rank=lr,
                node_rank=self.node_rank,
                world_size=world,
                local_world_size=self.nproc,
                master_addr=self.master_addr,
                master_port=self.master_port,
            )
            for lr in range(self.nproc)
        ]

    # -- process management ------------------------------------------------
    def _spawn(self) -> None:
        for spec in self.specs():
            cmd = [sys.executable] + self.argv
            env = spec.env()
            env["RESTART_ATTEMPT"] = str(self._gen)
            self._procs[spec.rank] = subprocess.Popen(cmd, env=env)
            self.log(f"[launch] node {self.node_rank}: started rank "
                     f"{spec.rank} (pid {self._procs[spec.rank].pid})")

    def _terminate_all(self) -> None:
        """SIGTERM the gang, escalate to SIGKILL after a grace period."""
        live = [p for p in self._procs.values() if p.poll() is None]
        for p in live:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + TERM_GRACE_S
        for p in live:
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
                p.wait()

    def _monitor(self, watch_remote: bool = False) -> GangResult:
        """Block until the gang finishes or any worker fails.

        This is the failure *detection* the reference lacks: a non-zero or
        signal-killed worker is noticed within ``monitor_interval_s`` and
        the survivors are torn down instead of hanging in a collective.
        With ``watch_remote`` the coordinator is polled too, so a worker
        death on ANOTHER node tears this node's workers down as promptly.
        """
        last_remote_check = 0.0
        while True:
            running = False
            for rank, p in self._procs.items():
                code = p.poll()
                if code is None:
                    running = True
                elif code != 0:
                    kind = ("injected fault" if code == FAULT_EXIT_CODE
                            else "failure")
                    self.log(f"[launch] rank {rank} FAILED with exit code "
                             f"{code} ({kind}); terminating gang")
                    self._terminate_all()
                    return GangResult(
                        returncode=code,
                        failed_rank=rank,
                        per_rank={r: q.returncode
                                  for r, q in self._procs.items()},
                        injected_failures=int(code == FAULT_EXIT_CODE),
                    )
            if not running:
                return GangResult(
                    returncode=0,
                    per_rank={r: p.returncode
                              for r, p in self._procs.items()},
                )
            now = time.monotonic()
            if watch_remote and now - last_remote_check >= max(
                    self.monitor_interval_s, 0.2):
                last_remote_check = now
                rep = None
                for attempt in (0, 1):  # one retry: a single RST/timeout
                    try:                # must not consume a restart budget
                        rep = self._rpc_coord(
                            {"op": "status", "gen": self._gen},
                            RPC_TIMEOUT_S)
                        break
                    except (OSError, ValueError):
                        if attempt == 0:
                            time.sleep(0.5)
                if rep is None:
                    rep = {"failed": False, "abort": True, "code": 1}
                    self.log("[launch] coordinator unreachable; "
                             "terminating gang")
                if rep.get("failed") or rep.get("abort"):
                    self.log(f"[launch] remote failure in generation "
                             f"{self._gen}; terminating local workers")
                    self._terminate_all()
                    return GangResult(
                        returncode=rep.get("code") or 1,
                        per_rank={r: q.returncode
                                  for r, q in self._procs.items()},
                    )
            time.sleep(self.monitor_interval_s)

    # -- gang orchestration -------------------------------------------------
    def _rpc_coord(self, msg: dict, timeout: float) -> dict:
        return _rpc(self.master_addr, self.agent_port, msg, timeout)

    def _barrier(self, gen: int) -> bool:
        """Arrive at generation ``gen``; True when all nodes are in.  The
        node-0 coordinator may come up after us — retry the dial."""
        deadline = time.monotonic() + CONNECT_RETRY_S
        while True:
            try:
                rep = self._rpc_coord(
                    {"op": "barrier", "node": self.node_rank, "gen": gen},
                    BARRIER_TIMEOUT_S + RPC_TIMEOUT_S)
                return bool(rep.get("ok"))
            except (OSError, ValueError):
                if time.monotonic() > deadline:
                    return False
                time.sleep(0.2)

    def run(self) -> GangResult:
        """Run the gang, restarting up to ``max_restarts`` times on failure.

        Single node: plain supervise-and-restart.  Multi node: every
        (re)start passes a coordinator barrier per generation, so all nodes
        always run the same generation (see module docstring).
        """
        if self.nnodes == 1:
            return self._run_local()
        return self._run_coordinated()

    def _run_local(self) -> GangResult:
        attempt = 0
        injected = 0
        while True:
            self._gen = attempt
            self._procs = {}
            self._spawn()
            try:
                result = self._monitor()
            except BaseException:
                # Ctrl-C, SIGTERM (via the main() handler), or any agent
                # crash: never leave workers orphaned on the chips.
                self._terminate_all()
                raise
            injected += result.injected_failures
            result.injected_failures = injected
            result.restarts_used = attempt
            if result.returncode == 0 or attempt >= self.max_restarts:
                return result
            attempt += 1
            self.log(f"[launch] restarting gang (attempt {attempt}/"
                     f"{self.max_restarts})")

    def _send(self, msg: dict) -> None:
        """Best-effort coordinator notification."""
        try:
            self._rpc_coord(msg, RPC_TIMEOUT_S)
        except (OSError, ValueError):
            pass

    def _run_coordinated(self) -> GangResult:
        coord = (_Coordinator(self.nnodes, self.agent_port)
                 if self.node_rank == 0 else None)
        try:
            gen = 0
            injected = 0
            last: GangResult | None = None
            while True:
                self._gen = gen
                if not self._barrier(gen):
                    # Denied: another node settled (done/abort) or the
                    # rendezvous timed out.  Report the real failure that
                    # got us here, not a synthetic code.
                    self.log(f"[launch] rendezvous for generation {gen} "
                             f"denied (done/abort/timeout)")
                    return last or GangResult(returncode=1)
                self._procs = {}
                self._spawn()
                try:
                    result = self._monitor(watch_remote=True)
                except BaseException:
                    self._terminate_all()
                    raise
                injected += result.injected_failures
                result.injected_failures = injected
                result.restarts_used = gen
                if result.returncode == 0:
                    # No further generations for laggards — but running
                    # peers finishing this generation are NOT torn down.
                    return result
                last = result
                self._send({"op": "fail", "gen": gen,
                            "code": result.returncode})
                if gen >= self.max_restarts:
                    self._send({"op": "abort"})
                    return result
                gen += 1
                self.log(f"[launch] restarting gang, generation {gen}/"
                         f"{self.max_restarts}")
        finally:
            # Settle this node with the coordinator no matter how we exit,
            # then (node 0) keep the coordinator alive until every node has
            # settled — a vanished coordinator reads as a remote failure to
            # peers still polling.
            self._send({"op": "done", "node": self.node_rank})
            if coord is not None:
                if not coord.wait_all_finished(BARRIER_TIMEOUT_S):
                    self.log("[launch] not all nodes settled before "
                             "coordinator shutdown")
                coord.close()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="distributed_pytorch_tpu.launch",
        description="torchrun-style launcher (reference start_ddp.sh:1) "
                    "with failure detection",
    )
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node-rank", "--node_rank", type=int, default=0)
    p.add_argument("--nproc-per-node", "--nproc_per_node", type=int,
                   default=1,
                   help="processes on this node (TPU: 1 per host owns all "
                        "local chips; >1 is for CPU simulation)")
    p.add_argument("--master-addr", "--master_addr", default="127.0.0.1")
    p.add_argument("--master-port", "--master_port", type=int,
                   default=DEFAULT_PORT)
    p.add_argument("--max-restarts", type=int, default=0,
                   help="elastic restarts of the whole gang on worker "
                        "failure (torchrun leaves this 0 too, but the "
                        "reference never sets it — start_ddp.sh:1)")
    p.add_argument("--monitor-interval", type=float, default=0.1,
                   help="seconds between worker liveness polls")
    p.add_argument("--agent-port", type=int, default=None,
                   help="coordinator port for multi-node restarts "
                        "(default master_port+1; node 0 hosts)")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="worker command: a script path or '-m module', "
                        "optionally preceded by '--'")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        build_parser().error("no worker command given")
    agent = LocalAgent(
        cmd,
        nnodes=args.nnodes,
        node_rank=args.node_rank,
        nproc_per_node=args.nproc_per_node,
        master_addr=args.master_addr,
        master_port=args.master_port,
        max_restarts=args.max_restarts,
        monitor_interval_s=args.monitor_interval,
        agent_port=args.agent_port,
    )
    # A scheduler's SIGTERM must tear down the gang, not orphan it; raising
    # SystemExit routes through run()'s BaseException cleanup.
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    result = agent.run()
    if result.returncode != 0:
        print(f"[launch] gang failed: rank {result.failed_rank} exit "
              f"{result.returncode} after {result.restarts_used} restarts",
              file=sys.stderr)
    return result.returncode


if __name__ == "__main__":
    sys.exit(main())
