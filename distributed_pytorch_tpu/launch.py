"""Multi-process launcher: the torchrun equivalent, with failure detection.

The reference launches DDP via ``torchrun --nproc_per_node=1 --nnodes=4
--node_rank=R --master_addr=M --master_port=6585 main_ddp.py`` (reference
start_ddp.sh:1) — torchrun's elastic agent spawns the worker and exports the
MASTER_ADDR/MASTER_PORT/WORLD_SIZE/LOCAL_WORLD_SIZE/LOCAL_RANK/RANK env-var
convention that main_ddp.py:93-100 reads.  This module is the framework's own
launcher speaking the same contract:

  python -m distributed_pytorch_tpu.launch --nnodes 4 --node-rank R \
      --master-addr M --master-port 6585 -- \
      -m distributed_pytorch_tpu.cli --rendezvous env --strategy ddp

Two deliberate upgrades over the reference's setup:

- **Failure detection.** The reference's ``timeout=None`` rendezvous
  (main_all_reduce.py:96) and unconfigured torchrun (no ``--max_restarts``,
  start_ddp.sh:1) mean a dead peer hangs the gang forever (SURVEY.md 2.3/5).
  Here the agent polls its children; when one exits non-zero, the rest are
  terminated (SIGTERM, then SIGKILL after a grace period) and the gang is
  either restarted (``--max-restarts N``, elastic-style) or the launcher
  exits with the failed worker's code.  SIGTERM to the launcher itself also
  tears the gang down (no orphaned workers holding chips).

  Scope: each agent supervises ONLY its own node's workers.  A worker death
  on another node surfaces there; this node's workers then fail out of the
  collective via the rendezvous/heartbeat timeout (parallel/init.py's
  ``--rendezvous-timeout``, vs the reference's infinite hang).  Because
  restarts are per-node and uncoordinated, ``--max-restarts > 0`` with
  ``--nnodes > 1`` would produce mixed-generation gangs and is rejected.
- **TPU process model.** On TPU one *process per host* owns all local chips
  (JAX single-controller-per-host), so ``--nproc-per-node`` defaults to 1 and
  values >1 are for CPU simulation/testing, where each worker is given a
  disjoint slice of fake devices.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field

DEFAULT_PORT = 6585  # reference start_ddp.sh:1 / main_all_reduce.py:96
TERM_GRACE_S = 10.0


@dataclass
class WorkerSpec:
    """One worker process's identity within the gang (the env contract of
    reference main_ddp.py:93-100)."""

    rank: int
    local_rank: int
    node_rank: int
    world_size: int
    local_world_size: int
    master_addr: str
    master_port: int

    def env(self) -> dict[str, str]:
        env = dict(os.environ)
        env.update(
            MASTER_ADDR=self.master_addr,
            MASTER_PORT=str(self.master_port),
            WORLD_SIZE=str(self.world_size),
            LOCAL_WORLD_SIZE=str(self.local_world_size),
            RANK=str(self.rank),
            LOCAL_RANK=str(self.local_rank),
            NODE_RANK=str(self.node_rank),
        )
        return env


@dataclass
class GangResult:
    """Outcome of one gang attempt."""

    returncode: int
    failed_rank: int | None = None
    restarts_used: int = 0
    per_rank: dict[int, int] = field(default_factory=dict)


class LocalAgent:
    """Spawns and supervises this node's workers (torchrun's elastic agent).

    ``argv`` is passed to the Python interpreter verbatim, so both script
    paths (``train.py ...``) and modules (``-m pkg.cli ...``) work.
    """

    def __init__(
        self,
        argv: list[str],
        *,
        nnodes: int = 1,
        node_rank: int = 0,
        nproc_per_node: int = 1,
        master_addr: str = "127.0.0.1",
        master_port: int = DEFAULT_PORT,
        max_restarts: int = 0,
        monitor_interval_s: float = 0.1,
        log=print,
    ):
        if max_restarts > 0 and nnodes > 1:
            raise ValueError(
                "--max-restarts requires --nnodes 1: restarts are per-node "
                "and an uncoordinated restart would rejoin a gang whose "
                "other nodes still run the previous generation")
        self.argv = argv
        self.nnodes = nnodes
        self.node_rank = node_rank
        self.nproc = nproc_per_node
        self.master_addr = master_addr
        self.master_port = master_port
        self.max_restarts = max_restarts
        self.monitor_interval_s = monitor_interval_s
        self.log = log
        self._procs: dict[int, subprocess.Popen] = {}

    def specs(self) -> list[WorkerSpec]:
        world = self.nnodes * self.nproc
        return [
            WorkerSpec(
                rank=self.node_rank * self.nproc + lr,
                local_rank=lr,
                node_rank=self.node_rank,
                world_size=world,
                local_world_size=self.nproc,
                master_addr=self.master_addr,
                master_port=self.master_port,
            )
            for lr in range(self.nproc)
        ]

    # -- process management ------------------------------------------------
    def _spawn(self) -> None:
        for spec in self.specs():
            cmd = [sys.executable] + self.argv
            self._procs[spec.rank] = subprocess.Popen(cmd, env=spec.env())
            self.log(f"[launch] node {self.node_rank}: started rank "
                     f"{spec.rank} (pid {self._procs[spec.rank].pid})")

    def _terminate_all(self) -> None:
        """SIGTERM the gang, escalate to SIGKILL after a grace period."""
        live = [p for p in self._procs.values() if p.poll() is None]
        for p in live:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + TERM_GRACE_S
        for p in live:
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
                p.wait()

    def _monitor(self) -> GangResult:
        """Block until the gang finishes or any worker fails.

        This is the failure *detection* the reference lacks: a non-zero or
        signal-killed worker is noticed within ``monitor_interval_s`` and
        the survivors are torn down instead of hanging in a collective.
        """
        while True:
            running = False
            for rank, p in self._procs.items():
                code = p.poll()
                if code is None:
                    running = True
                elif code != 0:
                    self.log(f"[launch] rank {rank} FAILED with exit code "
                             f"{code}; terminating gang")
                    self._terminate_all()
                    return GangResult(
                        returncode=code,
                        failed_rank=rank,
                        per_rank={r: q.returncode
                                  for r, q in self._procs.items()},
                    )
            if not running:
                return GangResult(
                    returncode=0,
                    per_rank={r: p.returncode
                              for r, p in self._procs.items()},
                )
            time.sleep(self.monitor_interval_s)

    def run(self) -> GangResult:
        """Run the gang, restarting up to ``max_restarts`` times on failure."""
        attempt = 0
        while True:
            self._procs = {}
            self._spawn()
            try:
                result = self._monitor()
            except BaseException:
                # Ctrl-C, SIGTERM (via the main() handler), or any agent
                # crash: never leave workers orphaned on the chips.
                self._terminate_all()
                raise
            result.restarts_used = attempt
            if result.returncode == 0 or attempt >= self.max_restarts:
                return result
            attempt += 1
            self.log(f"[launch] restarting gang (attempt {attempt}/"
                     f"{self.max_restarts})")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="distributed_pytorch_tpu.launch",
        description="torchrun-style launcher (reference start_ddp.sh:1) "
                    "with failure detection",
    )
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node-rank", "--node_rank", type=int, default=0)
    p.add_argument("--nproc-per-node", "--nproc_per_node", type=int,
                   default=1,
                   help="processes on this node (TPU: 1 per host owns all "
                        "local chips; >1 is for CPU simulation)")
    p.add_argument("--master-addr", "--master_addr", default="127.0.0.1")
    p.add_argument("--master-port", "--master_port", type=int,
                   default=DEFAULT_PORT)
    p.add_argument("--max-restarts", type=int, default=0,
                   help="elastic restarts of the whole gang on worker "
                        "failure (torchrun leaves this 0 too, but the "
                        "reference never sets it — start_ddp.sh:1)")
    p.add_argument("--monitor-interval", type=float, default=0.1,
                   help="seconds between worker liveness polls")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="worker command: a script path or '-m module', "
                        "optionally preceded by '--'")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        build_parser().error("no worker command given")
    agent = LocalAgent(
        cmd,
        nnodes=args.nnodes,
        node_rank=args.node_rank,
        nproc_per_node=args.nproc_per_node,
        master_addr=args.master_addr,
        master_port=args.master_port,
        max_restarts=args.max_restarts,
        monitor_interval_s=args.monitor_interval,
    )
    # A scheduler's SIGTERM must tear down the gang, not orphan it; raising
    # SystemExit routes through run()'s BaseException cleanup.
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    result = agent.run()
    if result.returncode != 0:
        print(f"[launch] gang failed: rank {result.failed_rank} exit "
              f"{result.returncode} after {result.restarts_used} restarts",
              file=sys.stderr)
    return result.returncode


if __name__ == "__main__":
    sys.exit(main())
