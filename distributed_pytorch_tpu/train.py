"""The single trainer: one jitted train step, pluggable gradient sync.

This factors the reference's five ~80%-identical ``main_*.py`` scripts
(SURVEY.md section 0) into one training loop where the gradient-sync strategy
is a plug-in (parallel/strategies.py).  The hot path — zero_grad / forward /
loss / backward / [sync] / step (reference main_all_reduce.py:36-50) — becomes
ONE compiled XLA program per step:

- single-process (strategy 'none'): plain ``jax.jit`` (reference main.py);
- data-parallel: ``shard_map`` over the mesh's ``'data'`` axis, with the
  batch sharded, params/optimizer state replicated, and per-replica
  BatchNorm statistics carried with a leading device axis (the reference
  keeps BN stats local per rank — SURVEY.md section 2.3).

The optimizer is optax ``add_decayed_weights(wd)`` then ``sgd(lr, momentum)``
— the exact update rule of torch ``SGD(lr=0.1, momentum=0.9,
weight_decay=1e-4)`` (reference main.py:103-104: grad += wd*p, then the
momentum buffer, then the step).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from .data import augment as aug
from .models import vgg
from .ops import nn as ops
from .parallel import strategies as strat
from .parallel.mesh import DATA_AXIS, data_sharding, make_mesh, replicated
from .utils.metrics import IterTimeMeter, LossMeter

PyTree = Any


@dataclass
class TrainConfig:
    """Hyper-parameters; defaults are the reference's exact settings."""

    model: str = "VGG11"
    lr: float = 0.1               # main.py:103
    momentum: float = 0.9         # main.py:104
    weight_decay: float = 1e-4    # main.py:104
    batch_size: int = 256         # per replica (main.py:18)
    strategy: str = "ddp"
    sync_bn: bool = False         # reference never syncs BN (SURVEY.md 2.3)
    compute_dtype: str | None = None  # e.g. "bfloat16" for MXU-friendly compute
    augment: bool = True
    seed: int = 1                 # torch.manual_seed(1), main.py:70

    @property
    def dtype(self):
        return jnp.dtype(self.compute_dtype) if self.compute_dtype else None


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    return optax.chain(
        optax.add_decayed_weights(cfg.weight_decay),
        optax.sgd(cfg.lr, momentum=cfg.momentum),
    )


def _loss_fn(params, state, key, images, labels, *, cfg: TrainConfig,
             bn_axis: str | None):
    """Forward + loss on one replica's shard; images are raw uint8 NHWC."""
    if cfg.augment:
        x = aug.augment(key, images)
    else:
        x = aug.normalize(images)
    logits, new_state = vgg.apply(
        params, state, x, name=cfg.model, train=True,
        dtype=cfg.dtype, bn_axis_name=bn_axis,
    )
    loss = ops.cross_entropy_loss(logits, labels)
    return loss, new_state


def make_train_step(cfg: TrainConfig, strategy: strat.Strategy,
                    mesh: Mesh | None):
    """Build the compiled train step.

    Signature: ``step(params, state, opt_state, key, images, labels) ->
    (params, state, opt_state, loss)``.  Under a mesh, ``state`` leaves carry
    a leading device axis (per-replica BN stats) and ``loss`` is the
    cross-replica mean of the per-shard losses.

    The three training-state arguments are DONATED: the step updates them in
    place on device and the caller must use the returned pytrees (passing a
    consumed buffer again raises "Array has been deleted").
    """
    tx = make_optimizer(cfg)
    bn_axis = DATA_AXIS if (cfg.sync_bn and mesh is not None) else None
    grad_fn = jax.value_and_grad(
        partial(_loss_fn, cfg=cfg, bn_axis=bn_axis), has_aux=True)

    if mesh is None:
        if strategy.needs_mesh:
            raise ValueError(f"strategy {strategy.name!r} requires a mesh")

        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(params, state, opt_state, key, images, labels):
            (loss, new_state), grads = grad_fn(params, state, key, images, labels)
            grads = strategy(grads, None)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, new_state, opt_state, loss

        return step

    def shard_step(params, state, opt_state, key, images, labels):
        # state arrives as this replica's (1, ...) slice of the stacked
        # per-device BN stats; drop/restore the leading axis around compute.
        local_state = jax.tree.map(lambda s: s[0], state)
        key = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))
        # Differentiate w.r.t. a *device-local* (varying) view of the params
        # so each replica's grads are its own shard's grads (otherwise the
        # new shard_map autodiff inserts an implicit psum for replicated
        # inputs and the strategy's collective would double-reduce).  The
        # strategy below is then the one and only cross-replica reduction —
        # exactly the reference's structure (sync between backward and step).
        local_params = jax.lax.pcast(params, DATA_AXIS, to="varying")
        (loss, new_state), grads = grad_fn(
            local_params, local_state, key, images, labels)
        grads = strategy(grads, DATA_AXIS)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        new_state = jax.tree.map(lambda s: s[None], new_state)
        return params, new_state, opt_state, jax.lax.pmean(loss, DATA_AXIS)

    # donate_argnums: params/BN-state/opt-state are consumed and re-emitted
    # every step — donation lets XLA update them in place (no HBM copy of the
    # ~36.9 MB params + ~36.9 MB momentum buffers per step).
    return jax.jit(shard_map(
        shard_step,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(), P(), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P(DATA_AXIS), P(), P()),
    ), donate_argnums=(0, 1, 2))


def replicate_state(state: PyTree, n: int) -> PyTree:
    """Stack BN state with a leading device axis (identical initial stats on
    every replica — same-seed construction, SURVEY.md section 2.3)."""
    return jax.tree.map(lambda s: jnp.broadcast_to(s[None], (n,) + s.shape), state)


def rank0_state(state: PyTree, mesh: Mesh | None) -> PyTree:
    """Rank 0's BN stats for evaluation (torch DDP broadcasts module buffers
    from rank 0 — reference main_ddp.py:137's engine behavior).

    Always returns host copies: the live ``state`` buffers are donated into
    the next compiled step, so a held reference would otherwise be deleted.
    """
    if mesh is None:
        return jax.tree.map(np.asarray, state)
    return jax.tree.map(lambda s: np.asarray(s)[0], state)


class Trainer:
    """Owns (params, state, opt_state) and the compiled step.

    Replaces the per-script ``main()``s: build model + optimizer from one
    seed, then drive ``train_epoch`` / ``evaluate`` (reference
    main_all_reduce.py:84-135).
    """

    def __init__(self, cfg: TrainConfig, mesh: Mesh | None = None):
        self.cfg = cfg
        self.strategy = strat.get(cfg.strategy)
        if self.strategy.needs_mesh and mesh is None:
            mesh = make_mesh()
        self.mesh = mesh if self.strategy.needs_mesh else None
        self.n_replicas = self.mesh.devices.size if self.mesh else 1

        key = jax.random.key(cfg.seed)
        self.init_key, self.data_key = jax.random.split(key)
        params, state = vgg.init(self.init_key, cfg.model)
        tx = make_optimizer(cfg)
        opt_state = tx.init(params)

        if self.mesh is not None:
            rep = replicated(self.mesh)
            shd = data_sharding(self.mesh)
            params = jax.device_put(params, rep)
            opt_state = jax.device_put(opt_state, rep)
            state = jax.device_put(
                replicate_state(state, self.n_replicas), shd)
        self.params, self.state, self.opt_state = params, state, opt_state
        self.step_fn = make_train_step(cfg, self.strategy, self.mesh)
        self._step = 0

    # -- one optimizer step over a *global* batch -------------------------
    def train_step(self, images: np.ndarray, labels: np.ndarray) -> jax.Array:
        key = jax.random.fold_in(self.data_key, self._step)
        if self.mesh is not None:
            shd = data_sharding(self.mesh)
            if jax.process_count() > 1:
                # Multi-host: each process contributes its local ranks' shard
                # of the global batch (the per-host DistributedSampler split,
                # reference main_all_reduce.py:112); assemble a global array.
                images = jax.make_array_from_process_local_data(shd, images)
                labels = jax.make_array_from_process_local_data(shd, labels)
            else:
                if len(images) % self.n_replicas != 0:
                    raise ValueError(
                        f"global batch {len(images)} not divisible by the "
                        f"{self.n_replicas}-device '{DATA_AXIS}' mesh axis; "
                        f"pass per-replica batches of equal size (the sampler "
                        f"pads the epoch for exactly this reason)")
                images = jax.device_put(images, shd)
                labels = jax.device_put(labels, shd)
        self.params, self.state, self.opt_state, loss = self.step_fn(
            self.params, self.state, self.opt_state, key, images, labels)
        self._step += 1
        return loss

    def train_epoch(self, loaders, epoch: int, *, log=print):
        """One epoch over per-replica loaders, with the reference's metric
        windows (loss/20 iters, time/40 iters excl. iter 0 — SURVEY.md 2.3).

        ``loaders``: one DataLoader per replica (the global batch is their
        concatenation), or a single loader for the single-process baseline.
        """
        if not isinstance(loaders, (list, tuple)):
            loaders = [loaders]
        # One loader per *locally-fed* replica: all of them single-host, this
        # process's shard of the mesh on multi-host.
        local = max(1, self.n_replicas // max(jax.process_count(), 1))
        assert len(loaders) == local, (
            f"got {len(loaders)} loaders for {local} local replicas")
        for dl in loaders:
            dl.set_epoch(epoch)
        loss_meter, time_meter = LossMeter(), IterTimeMeter()
        loss = None
        for batch_idx, batches in enumerate(zip(*loaders)):
            begin = time.perf_counter()
            images = np.concatenate([b[0] for b in batches])
            labels = np.concatenate([b[1] for b in batches])
            loss = self.train_step(images, labels)
            loss_val = float(loss)  # sync point, like loss.item() (main.py:37)
            elapsed = time.perf_counter() - begin
            rec = loss_meter.update(batch_idx, loss_val)
            if rec and log:
                log(f"Epoch: {epoch + 1}, Iteration: {rec.first_iter}-"
                    f"{rec.last_iter}, Average Loss: {rec.value:.3f}")
            rec = time_meter.update(batch_idx, elapsed)
            if rec and log:
                log(f"Avg Time for iteration {rec.first_iter}-{rec.last_iter}: "
                    f"{rec.value} seconds.")
        return loss_meter, time_meter

    def eval_state(self) -> PyTree:
        return rank0_state(self.state, self.mesh)
