"""The single trainer: one jitted train step, pluggable gradient sync.

This factors the reference's five ~80%-identical ``main_*.py`` scripts
(SURVEY.md section 0) into one training loop where the gradient-sync strategy
is a plug-in (parallel/strategies.py).  The hot path — zero_grad / forward /
loss / backward / [sync] / step (reference main_all_reduce.py:36-50) — becomes
ONE compiled XLA program per step:

- single-process (strategy 'none'): plain ``jax.jit`` (reference main.py);
- data-parallel: ``shard_map`` over the mesh's ``'data'`` axis, with the
  batch sharded, params/optimizer state replicated, and per-replica
  BatchNorm statistics carried with a leading device axis (the reference
  keeps BN stats local per rank — SURVEY.md section 2.3).

The optimizer is optax ``add_decayed_weights(wd)`` then ``sgd(lr, momentum)``
— the exact update rule of torch ``SGD(lr=0.1, momentum=0.9,
weight_decay=1e-4)`` (reference main.py:103-104: grad += wd*p, then the
momentum buffer, then the step).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, replace
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .data import augment as aug, pipeline
from .models import vgg
from .ops import nn as ops
from .parallel import strategies as strat
from .parallel.mesh import DATA_AXIS, make_mesh, replicated
from .utils import compat, debug as dbg, faults, monitor, telemetry, tracing
from .utils.compat import pcast, shard_map, vma_of
from .utils.metrics import IterTimeMeter, LossMeter

PyTree = Any


@dataclass
class TrainConfig:
    """Hyper-parameters; defaults are the reference's exact settings."""

    model: str = "VGG11"
    lr: float = 0.1               # main.py:103
    momentum: float = 0.9         # main.py:104
    weight_decay: float = 1e-4    # main.py:104
    batch_size: int = 256         # per replica (main.py:18)
    # Gradient-sync strategy (parallel/strategies.py), or "auto" (round
    # 11): calibrate the topology's per-axis links (or take an injected
    # profile — ``autotune_profile``), census the model's grad tree, and
    # resolve to the named strategy + bucket/compression knobs that
    # minimize predicted step-sync time (parallel/autotune.py).  The
    # resolved plan routes through the existing strategies unchanged, so
    # auto under a forced profile trains bitwise-identically to the
    # named strategy it resolves to (test-pinned); the Trainer records
    # the explainable plan as ``trainer.sync_plan``.
    strategy: str = "ddp"
    # Backward-overlapped gradient sync (round 8): emit each ~25 MB
    # bucket's collective INSIDE the backward graph at the bucket's layer-
    # group boundary (custom_vjp sync points — strategies.OverlapSync), so
    # XLA's latency-hiding scheduler can run bucket N's reduction under
    # layer N-1's backward matmuls, instead of starting all collectives
    # only after the backward fully drains.  Requires a mesh and an
    # overlap-capable strategy (strategies.overlap_capable()); numerics
    # are bitwise-identical to the post-backward path (test-pinned).
    overlap: bool = False
    # Bucket size for overlap packing (and for the bucketed/ring
    # strategies' internal packing); None keeps each strategy's default
    # (torch DDP's 25 MB).  Small values force many buckets — useful for
    # schedule inspection on tiny models.
    overlap_bucket_mb: float | None = None
    # Number of slices for the 'hierarchical' strategy: the data axis
    # factors into Mesh(('dcn', 'ici')) with dcn_size slices (cross-slice
    # DCN traffic drops to payload/ici — see strategies.Hierarchical).
    # Ignored by single-axis strategies.
    dcn_size: int = 2
    # Slow-hop compression for the 'hierarchical' strategy (round 9):
    # "int8" runs the cross-slice shard exchange as an int8 ring (per-row
    # scales, error-feedback residuals through the sync-state carry)
    # while the ICI reduce-scatter/all-gather stay full-precision — see
    # strategies.Hierarchical's dcn_compress docstring.  "int4" (round
    # 16) drops one more rung: two nibbles per int8 lane on the wire,
    # ~0.51x the int8 DCN bytes, same EF carry.  None (default) keeps
    # the exact full-precision psum.  Rejected for strategies with no
    # DCN hop.
    dcn_compress: str | None = None
    # Declarative sync route (round 20, parallel/routing.py): a route
    # string in the hop grammar ("ici:rs → dcn:ring[int4+ef] → ici:ag";
    # plain "->" works too) executed by RoutedSync instead of a named
    # strategy.  Requires strategy="routed"; the route must be a 2-level
    # ('dcn', 'ici') plan — the trainer's factored-mesh topology (3-tier
    # wan routes run through the RoutedSync surface directly; the
    # trainer's mesh recipe only builds two tiers).  Compression and EF
    # live IN the route, so dcn_compress must stay None.
    sync_route: str | None = None
    # Profile source for strategy="auto" (parallel/autotune.py): None =
    # load the repo-local cached profile for this topology or calibrate
    # and cache one; a synthetic preset name ("uniform",
    # "fast_ici_slow_dcn", ...) or a profile-JSON path or a
    # TopologyProfile instance forces the chooser's inputs (CPU tests,
    # the dryrun).  Ignored unless strategy="auto".
    autotune_profile: Any = None
    steps_per_loop: int = 1       # K optimizer steps per device dispatch
    sync_bn: bool = False         # reference never syncs BN (SURVEY.md 2.3)
    # torch DDP's broadcast_buffers=True: BN running stats follow rank 0
    # (reference main_ddp.py:137 inherits this engine behavior); the manual
    # variants keep local per-replica stats.  None = strategy default
    # (True for the DDP-engine strategies 'ddp'/'bucketed', False otherwise).
    broadcast_buffers: bool | None = None
    compute_dtype: str | None = None  # e.g. "bfloat16" for MXU-friendly compute
    augment: bool = True
    seed: int = 1                 # torch.manual_seed(1), main.py:70
    # Communication-sparse sync (round 18, the BAGUA/local-SGD system
    # relaxation): run H local optimizer steps between cross-replica
    # exchanges — each replica (each SLICE under 'hierarchical', which
    # keeps its fast ICI mean every step and skips only the DCN hop)
    # steps on its own gradients while the window's accumulated update
    # delta is averaged once per H steps, so exchange wire bytes per
    # step scale ~1/H.  1 (default) is the existing per-step path,
    # UNTOUCHED at build time (bitwise + compile-count identical).
    # Requires a mesh, steps_per_loop % H == 0 (every dispatch ends on
    # a window boundary), and overlap=False — strategies.
    # require_sync_window is the one refusal site.  Momentum buffers
    # stay LOCAL per device across windows (they ride a leading device
    # axis like BN state), the standard local-momentum variant.
    sync_every: int = 1
    # Relaxation ceiling for the interval-aware autotuner
    # (strategy="auto" prices exposed sync time at H in powers of 2 up
    # to this) and the monitor's straggler actuator
    # (monitor.SyncRelaxHook widens sync_every within it on step-time
    # SLO breach).  Default 1: relaxation is OPT-IN — staleness is a
    # convergence trade the user must accept explicitly.
    max_sync_every: int = 1
    # DiLoCo outer optimizer (round 22): at each window boundary the
    # anchor moves by outer_opt(mean delta) instead of the plain mean —
    # Nesterov/heavy-ball momentum ON THE ANCHOR recovers convergence
    # lost to wide windows (the "wider window at matched quality"
    # claim, measured in tests/test_diloco.py).  The f32 momentum state
    # rides the sync_state carry as a flat tail, so the window scan's
    # signature is unchanged.  None (default) is the round-18 plain
    # mean, UNTOUCHED at build time; so is momentum==0 ∧ lr==1 (the
    # OuterOptimizer.trivial collapse) — bitwise, not approximately.
    outer_opt: str | None = None      # None | "nesterov" | "momentum"
    outer_momentum: float = 0.9
    outer_lr: float = 1.0

    @property
    def dtype(self):
        return jnp.dtype(self.compute_dtype) if self.compute_dtype else None

    @property
    def broadcast_buffers_resolved(self) -> bool:
        """torch DDP semantics by default exactly where the reference gets
        them from the DDP engine; reference-faithful local BN elsewhere."""
        if self.broadcast_buffers is not None:
            return self.broadcast_buffers
        return self.strategy in ("ddp", "bucketed")


def _as_varying(tree: PyTree, axis) -> PyTree:
    """Pcast leaves to device-varying over ``axis`` (a name or tuple of
    names); leaves already varying (e.g. a scan carry whose vma was unified
    with varying neighbors) pass through unchanged."""
    names = (axis,) if isinstance(axis, str) else tuple(axis)

    def cast(x):
        missing = tuple(a for a in names if a not in vma_of(x))
        if not missing:
            return x
        return pcast(x, missing, to="varying")
    return jax.tree.map(cast, tree)


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    return optax.chain(
        optax.add_decayed_weights(cfg.weight_decay),
        optax.sgd(cfg.lr, momentum=cfg.momentum),
    )


def _loss_fn(params, state, key, images, labels, *, cfg: TrainConfig,
             bn_axis: str | None, boundary=None):
    """Forward + loss on one replica's shard; images are raw uint8 NHWC.
    ``boundary`` threads the overlap sync hook into the model's layer-group
    boundaries (vgg.apply; None = historical graph, byte-identical)."""
    if cfg.augment:
        x = aug.augment(key, images)
    else:
        x = aug.normalize(images)
    logits, new_state = vgg.apply(
        params, state, x, name=cfg.model, train=True,
        dtype=cfg.dtype, bn_axis_name=bn_axis, boundary=boundary,
    )
    loss = ops.cross_entropy_loss(logits, labels)
    return loss, new_state


def _apply_bucket_mb(cfg: TrainConfig, strategy: strat.Strategy) -> None:
    """Propagate cfg.overlap_bucket_mb into the strategy's packing knob
    (shared by the overlap markers and the bucketed/ring post-backward
    paths, so both modes always agree on bucket membership)."""
    if cfg.overlap_bucket_mb is not None and hasattr(strategy,
                                                     "bucket_bytes"):
        strategy.bucket_bytes = int(cfg.overlap_bucket_mb * 1024 * 1024)


def _apply_dcn(cfg: TrainConfig, strategy: strat.Strategy) -> None:
    """Propagate cfg.dcn_compress / cfg.dcn_size into the strategy (the
    hierarchical slow-hop knobs); must run before the step is built AND
    before init_state (compression flips the strategy stateful and the
    EF residual layout reads dcn_size).  Strategies without a DCN hop
    reject the compress knob instead of silently ignoring it."""
    if hasattr(strategy, "set_dcn"):
        strategy.set_dcn(cfg.dcn_compress, cfg.dcn_size)
    elif cfg.dcn_compress is not None:
        raise ValueError(
            f"dcn_compress={cfg.dcn_compress!r} quantizes the cross-slice "
            f"hop of the factored-mesh 'hierarchical' strategy; strategy "
            f"{strategy.name!r} has no DCN hop to compress")


def _validate_overlap(cfg: TrainConfig, strategy: strat.Strategy,
                      mesh: Mesh | None) -> None:
    if not cfg.overlap:
        return
    if mesh is None:
        raise ValueError(
            "overlap=True requires a mesh: the data-axis collectives are "
            "the thing being overlapped with backward compute")
    # the ONE capability-check site (strategies.py, round 9): the refusal
    # lives next to the OverlapSync machinery it describes
    strat.require_overlap_capable(strategy)


def make_train_step(cfg: TrainConfig, strategy: strat.Strategy,
                    mesh: Mesh | None):
    """Build the compiled single train step — ``make_multi_step`` with K=1
    (one implementation of the optimizer-step semantics, not two).

    Signature: ``step(params, state, opt_state, sync_state, key, step0,
    images, labels) -> (params, state, opt_state, sync_state, loss)``; the
    per-step RNG is ``fold_in(key, step0)``.  Under a mesh, ``state`` (and
    ``sync_state`` — a stateful strategy's per-device residual; a dummy
    otherwise) leaves carry a leading device axis, and ``loss`` is the
    cross-replica mean of the per-shard losses.

    The three training-state arguments are DONATED: the step updates them in
    place on device and the caller must use the returned pytrees (passing a
    consumed buffer again raises "Array has been deleted").

    This convenience wrapper never arms the chaos taps (fault_sig=False):
    its fixed 8-arg signature has no fault_arm slot — use the Trainer (or
    make_multi_step directly) to drive step-keyed fault injection.
    """
    multi = make_multi_step(cfg, strategy, mesh, fault_sig=False)

    def step(params, state, opt_state, sync_state, key, step0, images,
             labels):
        params, state, opt_state, sync_state, losses, oks, mets = multi(
            params, state, opt_state, sync_state, key, step0,
            images[None], labels[None])
        return params, state, opt_state, sync_state, losses[0]

    return step


def _outer_of(cfg: TrainConfig) -> strat.OuterOptimizer | None:
    """The configured DiLoCo outer optimizer, or None for the plain-mean
    boundary — also None when trivial (momentum==0 ∧ lr==1), which is the
    build-time collapse that keeps zero-momentum bitwise ≡ round 18."""
    if cfg.sync_every > 1 and cfg.outer_opt is not None:
        outer = strat.OuterOptimizer(cfg.outer_opt, cfg.outer_momentum,
                                     cfg.outer_lr)
        if not outer.trivial:
            return outer
    return None


def make_multi_step(cfg: TrainConfig, strategy: strat.Strategy,
                    mesh: Mesh | None, fault_sig: bool | None = None):
    """Build a compiled K-step training loop (``lax.scan`` over stacked
    batches): ONE dispatch executes K optimizer steps on device.

    Signature: ``fn(params, state, opt_state, key, step0, images, labels) ->
    (params, state, opt_state, losses, oks, mets)`` with ``images``/
    ``labels`` carrying a leading scan axis of length K, ``losses`` shape
    (K,), ``oks`` (K,) f32 per-step health flags (1.0 = loss AND synced
    grads finite) — the in-scan detection signal of the training sentry
    (utils/sentry.py), one sum-of-squares pass over the gradient tree,
    negligible next to the backward — and ``mets`` (K, 2) f32 per-step
    device-side scalars [grad global-norm, post-update param
    global-norm] (round 13): they RIDE the same in-scan output channel
    as the health flag, so telemetry reads them from the step's normal
    outputs and toggling telemetry on/off changes NO compiled program
    (zero extra compiles, bitwise-identical losses — test-pinned).

    This is the TPU-native answer to per-step dispatch overhead: the
    reference's hot loop makes one eager dispatch per op (SURVEY.md 3.1);
    the single-step path here makes one per step; this makes one per K
    steps, which matters when the host link has real latency (tunneled or
    multi-host setups).  RNG per step is ``fold_in(key, step0 + i)`` —
    identical to the single-step path's stream, so loss curves match
    exactly regardless of steps_per_loop.
    """
    tx = make_optimizer(cfg)
    # Strategy knobs FIRST: dcn compression flips `stateful`/`vma_opaque`
    # on the hierarchical strategy, and the bucket cap feeds both the
    # overlap markers and the post-backward packing.
    _apply_dcn(cfg, strategy)
    _apply_bucket_mb(cfg, strategy)
    _validate_overlap(cfg, strategy, mesh)
    # Communication-sparse windows (round 18): coherence check at the ONE
    # definition site (strategies.require_sync_window).  sync_every == 1
    # never enters the windowed builder below, so the per-step path —
    # jaxpr, specs, compile count — is byte-identical to round 17 by
    # construction, not by test luck.
    windowed = cfg.sync_every > 1
    if windowed or cfg.outer_opt is not None:
        strat.require_sync_window(
            sync_every=cfg.sync_every, max_sync_every=cfg.max_sync_every,
            mesh=mesh is not None, overlap=cfg.overlap, trainer="train",
            outer_opt=cfg.outer_opt, outer_momentum=cfg.outer_momentum,
            outer_lr=cfg.outer_lr)
    # DiLoCo outer optimizer (round 22): built ONLY when configured and
    # non-trivial, so the plain-mean boundary below stays byte-identical
    # by construction (same discipline as the sync_every==1 gate).
    outer = _outer_of(cfg)
    use_outer = outer is not None
    # The data axis may be factored: hierarchical runs over ('dcn', 'ici').
    data_axes = getattr(strategy, "axes", None) or DATA_AXIS
    bn_axis = data_axes if (cfg.sync_bn and mesh is not None) else None
    bcast_buffers = cfg.broadcast_buffers_resolved and mesh is not None
    # Stateful strategies (error-feedback ring) carry a per-device residual
    # through the scan, alongside BN state; stateless ones thread a dummy.
    stateful = getattr(strategy, "stateful", False)
    grad_fn = jax.value_and_grad(
        partial(_loss_fn, cfg=cfg, bn_axis=bn_axis), has_aux=True)

    # Backward-overlapped sync (round 8): the loss traces with per-bucket
    # custom_vjp sync points at the model's layer-group boundaries, so
    # value_and_grad returns ALREADY-SYNCED grads with each bucket's
    # collective emitted inside the backward graph; the post-backward
    # strategy call is skipped.  Stateful (EF) strategies differentiate
    # w.r.t. the residual too — its "gradient" is the updated residual
    # (strategies.sync_boundary_stateful), threaded back into the scan
    # carry exactly like the post-backward path's returned state.
    overlap = cfg.overlap
    if overlap:
        group_idx = vgg.sync_group_index(cfg.model)

        def _ov_loss(params, state, key, images, labels):
            ov = strat.OverlapSync(strategy, data_axes, params, group_idx)
            return _loss_fn(params, state, key, images, labels, cfg=cfg,
                            bn_axis=bn_axis, boundary=ov.boundary)

        def _ov_loss_stateful(params, sync_state, state, key, images,
                              labels):
            ov = strat.OverlapSync(strategy, data_axes, params, group_idx,
                                   sync_state=sync_state)
            return _loss_fn(params, state, key, images, labels, cfg=cfg,
                            bn_axis=bn_axis, boundary=ov.boundary)

        grad_fn_ov = (jax.value_and_grad(_ov_loss_stateful, argnums=(0, 1),
                                         has_aux=True)
                      if stateful
                      else jax.value_and_grad(_ov_loss, has_aux=True))

    # Chaos-harness plumbing: with an installed STEP-KEYED FaultPlan
    # (nan/inf grad, loss spike) the compiled step gains ONE trailing f32
    # arg (the host's arm_window gate for the in-jit taps); the clean
    # path's signature stays byte-identical.  The Trainer passes its
    # build-time decision so caller and program can never disagree.
    if fault_sig is None:
        fault_sig = faults.step_plan() is not None

    def scan_steps(params, state, opt_state, sync_state, key, step0,
                   images, labels, fault_arm=0.0, *, axis: str | None):
        def body(carry, batch):
            params, state, opt_state, sync_state, step = carry
            imgs, lbls = batch
            k = jax.random.fold_in(key, step)
            if axis is not None:
                k = jax.random.fold_in(k, jax.lax.axis_index(axis))
                # Per-shard grads via a device-varying view (see
                # make_train_step); the strategy's collective then restores
                # cross-replica invariance before the optimizer update.
                local_params = _as_varying(params, axis)
            else:
                local_params = params
            if overlap:
                # grads arrive pre-synced (in-backward bucket collectives);
                # the chaos taps therefore land POST-sync here — an
                # injected NaN still poisons params and trips the health
                # flag, it just no longer rides the wire first
                if stateful:
                    (loss, state), (grads, sync_state) = grad_fn_ov(
                        local_params, sync_state, state, k, imgs, lbls)
                else:
                    (loss, state), grads = grad_fn_ov(
                        local_params, state, k, imgs, lbls)
            else:
                (loss, state), grads = grad_fn(local_params, state, k,
                                               imgs, lbls)
            # chaos-harness taps: trace-time no-ops unless a FaultPlan is
            # installed (utils/faults.py) — pre-sync on the post-backward
            # path, so an injected bad shard propagates through the
            # collective like a real one
            grads = faults.tap_grads(grads, step, fault_arm)
            loss = faults.tap_loss(loss, step, fault_arm)
            if bcast_buffers and axis is not None:
                # torch DDP broadcast_buffers: BN running stats follow rank
                # 0 (buffers broadcast from rank 0 every forward — reference
                # main_ddp.py:137's engine).  Broadcasting rank 0's *updated*
                # stats here, after the local update instead of before the
                # next forward, yields the identical rank-0-authoritative
                # trajectory (next forward sees rank 0's stats either way)
                # while keeping the carried state replica-identical.
                idx = jax.lax.axis_index(axis)
                state = jax.tree.map(
                    lambda s: _as_varying(
                        jax.lax.psum(
                            jnp.where(idx == 0, s, jnp.zeros_like(s)), axis),
                        axis),
                    state)
            if not overlap:
                if stateful:
                    grads, sync_state = strategy(grads, axis, sync_state)
                else:
                    grads = strategy(grads, axis)
            # per-step health flag (sentry): finite loss + finite synced
            # grads, via one global sum-of-squares over the tree
            gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                      for g in jax.tree.leaves(grads))
            ok = (jnp.isfinite(loss) & jnp.isfinite(gsq)).astype(
                jnp.float32)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            # per-step telemetry scalars (round 13) riding the SAME
            # output channel as the health flag: grad global-norm (gsq
            # is already computed for `ok`) and post-update param
            # global-norm — device-side, so telemetry-on never adds a
            # program or a compile (ops.step_metrics: the ONE
            # implementation, shared with lm.py's step finishers)
            met = ops.step_metrics(gsq, params)
            return (params, state, opt_state, sync_state, step + 1), (
                loss, ok, met)

        (params, state, opt_state, sync_state, _), (losses, oks, mets) = (
            jax.lax.scan(
                body, (params, state, opt_state, sync_state, step0),
                (images, labels)))
        return params, state, opt_state, sync_state, losses, oks, mets

    if windowed:
        # Local-SGD window loop: a nested scan — outer over K/H window
        # boundaries, inner over H local steps — so the schedule
        # inspector's trip accounting (utils/debug.py multiplies nested
        # scan lengths) can PROVE the boundary collectives run once per
        # window, which a lax.cond-gated flat loop cannot (cond bodies
        # are counted every trip).  The carry tracks the window's params
        # as anchor + delta: ``anchor`` is the last exchanged (replica-
        # identical) point, ``delta`` the locally accumulated optimizer
        # updates since — the boundary then exchanges ONLY delta, and
        # plain-SGD windows are bitwise an accumulated-gradient-averaging
        # oracle by pure reassociation (tests/test_localsgd.py).
        hier = hasattr(strategy, "window_exchange")

        def scan_steps_windowed(params, state, opt_state, sync_state, key,
                                step0, images, labels, fault_arm=0.0, *,
                                axis):
            h = cfg.sync_every
            k_total = images.shape[0]
            if k_total % h:
                raise ValueError(
                    f"dispatch of {k_total} steps is not a multiple of "
                    f"sync_every={h}: every compiled dispatch must end "
                    f"on a window boundary so params leave replicated")

            def local_body(anchor, carry, batch):
                delta, state, opt_state, step = carry
                imgs, lbls = batch
                k = jax.random.fold_in(key, step)
                k = jax.random.fold_in(k, jax.lax.axis_index(axis))
                local_params = _as_varying(
                    jax.tree.map(jnp.add, anchor, delta), axis)
                (loss, state), grads = grad_fn(local_params, state, k,
                                               imgs, lbls)
                grads = faults.tap_grads(grads, step, fault_arm)
                loss = faults.tap_loss(loss, step, fault_arm)
                if bcast_buffers:
                    idx = jax.lax.axis_index(axis)
                    state = jax.tree.map(
                        lambda s: _as_varying(
                            jax.lax.psum(
                                jnp.where(idx == 0, s, jnp.zeros_like(s)),
                                axis), axis),
                        state)
                if hier:
                    # within-slice mean every step: the per-step path's
                    # ICI ops, zero DCN ops (Hierarchical.local_sync);
                    # flat strategies step fully locally instead
                    grads = strategy.local_sync(grads, axis)
                gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                          for g in jax.tree.leaves(grads))
                ok = (jnp.isfinite(loss) & jnp.isfinite(gsq)).astype(
                    jnp.float32)
                updates, opt_state = tx.update(grads, opt_state,
                                               local_params)
                delta = jax.tree.map(jnp.add, delta, updates)
                met = ops.step_metrics(
                    gsq, jax.tree.map(jnp.add, anchor, delta))
                return (delta, state, opt_state, step + 1), (loss, ok,
                                                             met)

            def window_body(carry, batch):
                anchor, delta, state, opt_state, sync_state, step = carry
                (delta, state, opt_state, step), outs = jax.lax.scan(
                    partial(local_body, anchor),
                    (delta, state, opt_state, step), batch)
                # boundary: cross-replica mean of the accumulated update
                # — the window's ONE slow exchange (shard-sized over dcn
                # for hierarchical, incl. the int8/int4+EF ring; the
                # full strategy collective for flat strategies)
                if use_outer:
                    # the outer momentum rides sync_state as a flat f32
                    # TAIL after the strategy's residual segments —
                    # split at a trace-time-static offset, exchange on
                    # the residual part only, then move the anchor by
                    # outer_opt(mean delta) instead of the plain add
                    m_len = strat.OuterOptimizer.state_len(anchor)
                    res_len = sync_state.shape[0] - m_len
                    res = sync_state[:res_len]
                    if hier:
                        ex = (strategy.window_exchange(delta, axis, res)
                              if stateful
                              else strategy.window_exchange(delta, axis))
                    else:
                        ex = (strategy(delta, axis, res) if stateful
                              else strategy(delta, axis))
                    if stateful:
                        d_avg, res = ex
                    else:
                        d_avg = ex
                    anchor, m_flat = outer.apply_flat(
                        anchor, d_avg, sync_state[res_len:])
                    sync_state = jnp.concatenate([res, m_flat])
                else:
                    if hier:
                        ex = (strategy.window_exchange(delta, axis,
                                                       sync_state)
                              if stateful
                              else strategy.window_exchange(delta, axis))
                    else:
                        ex = (strategy(delta, axis, sync_state)
                              if stateful else strategy(delta, axis))
                    if stateful:
                        d_avg, sync_state = ex
                    else:
                        d_avg = ex
                    anchor = jax.tree.map(jnp.add, anchor, d_avg)
                delta = jax.tree.map(jnp.zeros_like, delta)
                return (anchor, delta, state, opt_state, sync_state,
                        step), outs

            w = k_total // h
            imgs = images.reshape((w, h) + images.shape[1:])
            lbls = labels.reshape((w, h) + labels.shape[1:])
            delta = jax.tree.map(jnp.zeros_like, params)
            (params, _, state, opt_state, sync_state, _), (losses, oks,
                                                           mets) = (
                jax.lax.scan(
                    window_body,
                    (params, delta, state, opt_state, sync_state, step0),
                    (imgs, lbls)))
            return (params, state, opt_state, sync_state,
                    losses.reshape(k_total), oks.reshape(k_total),
                    mets.reshape((k_total,) + mets.shape[2:]))

    if mesh is None:
        if strategy.needs_mesh:
            raise ValueError(f"strategy {strategy.name!r} requires a mesh")

        if fault_sig:
            @partial(jax.jit, donate_argnums=compat.donate(0, 1, 2, 3))
            def multi_step(params, state, opt_state, sync_state, key,
                           step0, images, labels, fault_arm):
                return scan_steps(params, state, opt_state, sync_state,
                                  key, step0, images, labels, fault_arm,
                                  axis=None)
        else:
            @partial(jax.jit, donate_argnums=compat.donate(0, 1, 2, 3))
            def multi_step(params, state, opt_state, sync_state, key,
                           step0, images, labels):
                return scan_steps(params, state, opt_state, sync_state,
                                  key, step0, images, labels, axis=None)

        return multi_step

    if windowed:
        # Per-device momentum (local-momentum local SGD): the optimizer
        # state rides a leading device axis like BN state — it never
        # crosses the wire, so the boundary exchange stays delta-only
        # (the 1/H dcn-byte claim) at the cost of replica-local buffers.
        opt_spec = P(data_axes)

        def run_shard(params, state, opt_state, sync_state, key, step0,
                      images, labels, fault_arm):
            local_state = jax.tree.map(lambda s: s[0], state)
            local_opt = jax.tree.map(lambda s: s[0], opt_state)
            local_sync = jax.tree.map(lambda s: s[0], sync_state)
            (params, new_state, new_opt, new_sync, losses, oks,
             mets) = scan_steps_windowed(
                params, local_state, local_opt, local_sync, key, step0,
                images, labels, fault_arm, axis=data_axes)
            new_state = jax.tree.map(lambda s: s[None], new_state)
            new_opt = jax.tree.map(lambda s: s[None], new_opt)
            new_sync = jax.tree.map(lambda s: s[None], new_sync)
            return (params, new_state, new_opt, new_sync,
                    jax.lax.pmean(losses, data_axes),
                    jax.lax.pmean(oks, data_axes),
                    jax.lax.pmean(_as_varying(mets, data_axes),
                                  data_axes))
    else:
        opt_spec = P()

        def run_shard(params, state, opt_state, sync_state, key, step0,
                      images, labels, fault_arm):
            local_state = jax.tree.map(lambda s: s[0], state)
            local_sync = jax.tree.map(lambda s: s[0], sync_state)
            (params, new_state, opt_state, new_sync, losses, oks,
             mets) = scan_steps(
                params, local_state, opt_state, local_sync, key, step0,
                images, labels, fault_arm, axis=data_axes)
            new_state = jax.tree.map(lambda s: s[None], new_state)
            new_sync = jax.tree.map(lambda s: s[None], new_sync)
            # oks pmean: 1.0 iff EVERY replica's step was healthy (a
            # poisoned shard pulls the mean below 1 even before its sync
            # spreads it); mets pmean: synced grads/params are
            # replica-identical, so the mean is the value — it just also
            # PROVES invariance to the vma checker (a few scalar psums,
            # excluded from the schedule pins by their min_bytes
            # filter).  mets may arrive vma-INVARIANT (derived from
            # post-psum grads and updated params), and modern runtimes
            # reject reducing an invariant value — cast varying first
            # (pass-through where already varying, no-op on legacy).
            return (params, new_state, opt_state, new_sync,
                    jax.lax.pmean(losses, data_axes),
                    jax.lax.pmean(oks, data_axes),
                    jax.lax.pmean(_as_varying(mets, data_axes),
                                  data_axes))

    if fault_sig:
        def shard_multi_step(params, state, opt_state, sync_state, key,
                             step0, images, labels, fault_arm):
            return run_shard(params, state, opt_state, sync_state, key,
                             step0, images, labels, fault_arm)
        extra_specs: tuple = (P(),)
    else:
        def shard_multi_step(params, state, opt_state, sync_state, key,
                             step0, images, labels):
            return run_shard(params, state, opt_state, sync_state, key,
                             step0, images, labels, 0.0)
        extra_specs = ()

    return jax.jit(shard_map(
        shard_multi_step,
        mesh=mesh,
        in_specs=(P(), P(data_axes), opt_spec, P(data_axes), P(), P(),
                  P(None, data_axes), P(None, data_axes)) + extra_specs,
        out_specs=(P(), P(data_axes), opt_spec, P(data_axes), P(), P(),
                   P()),
        # Ring-collective strategies assemble their result from ppermute
        # hops: bitwise replicated by construction, but not provably so to
        # the vma checker (no sanctioned varying->invariant downcast).
        check_vma=not getattr(strategy, "vma_opaque", False),
    ), donate_argnums=compat.donate(0, 1, 2, 3))


def replicate_state(state: PyTree, n: int) -> PyTree:
    """Stack BN state with a leading device axis (identical initial stats on
    every replica — same-seed construction, SURVEY.md section 2.3)."""
    return jax.tree.map(lambda s: jnp.broadcast_to(s[None], (n,) + s.shape), state)


def rank0_state(state: PyTree, mesh: Mesh | None) -> PyTree:
    """Rank 0's BN stats for evaluation (torch DDP broadcasts module buffers
    from rank 0 — reference main_ddp.py:137's engine behavior).

    Always returns host copies: the live ``state`` buffers are donated into
    the next compiled step, so a held reference would otherwise be deleted.
    Multi-host meshes: the replica-stacked state spans processes, so the
    fetch is a collective (every process must call this together).
    """
    if mesh is None:
        return jax.tree.map(np.asarray, state)

    def fetch0(s):
        if isinstance(s, jax.Array) and not s.is_fully_addressable:
            from jax.experimental import multihost_utils
            return np.asarray(
                multihost_utils.process_allgather(s, tiled=True))[0]
        return np.asarray(s)[0]

    return jax.tree.map(fetch0, state)


class Trainer:
    """Owns (params, state, opt_state) and the compiled step.

    Replaces the per-script ``main()``s: build model + optimizer from one
    seed, then drive ``train_epoch`` / ``evaluate`` (reference
    main_all_reduce.py:84-135).
    """

    def __init__(self, cfg: TrainConfig, mesh: Mesh | None = None,
                 num_devices: int | None = None):
        # strategy="auto" (round 11): resolve FIRST, to a named strategy
        # plus bucket/dcn knobs, so everything below — including the
        # bitwise-pinned step builders — runs the exact named path.  The
        # explainable plan (predicted ms + per-axis bytes) is kept on
        # the trainer; pass mesh=None so the resolved strategy's own
        # mesh recipe applies.
        self.sync_plan = None
        if cfg.strategy == "auto":
            if mesh is not None:
                # resolution decides the topology (flat vs factored) and
                # hence the mesh shape; a pre-built mesh could disagree
                # with whatever the chooser picks, which would only
                # surface as a cryptic trace-time sharding error
                raise ValueError(
                    "strategy='auto' builds its own mesh from the "
                    "resolved plan; pass mesh=None (use num_devices to "
                    "bound the fleet)")
            from .parallel import autotune
            cfg, self.sync_plan = autotune.resolve_train_auto(
                cfg, num_devices=num_devices)
        self.cfg = cfg
        if cfg.strategy == "routed" or cfg.sync_route is not None:
            # declarative routed sync (round 20): the route string IS
            # the strategy — parse it into a HopPlan and execute it with
            # RoutedSync over the trainer's factored ('dcn', 'ici') mesh
            from .parallel import routing
            if cfg.strategy != "routed" or cfg.sync_route is None:
                raise ValueError(
                    "routed sync needs BOTH strategy='routed' and a "
                    f"sync_route string (got strategy={cfg.strategy!r}, "
                    f"sync_route={cfg.sync_route!r})")
            if cfg.dcn_compress is not None:
                raise ValueError(
                    "strategy='routed' encodes compression in the route "
                    "itself (e.g. 'dcn:ring[int4+ef]'); dcn_compress "
                    "must stay None")
            route_plan = routing.parse_route(cfg.sync_route)
            if route_plan.mesh_axes() != ("dcn", "ici"):
                raise ValueError(
                    f"the trainer's mesh recipe builds two tiers "
                    f"('dcn', 'ici'); route {route_plan.describe()!r} "
                    f"spans {route_plan.mesh_axes()} — run other "
                    f"topologies through RoutedSync directly")
            self.strategy = routing.RoutedSync(
                route_plan,
                n_by_axis=None)  # bound below, from the built mesh
        else:
            self.strategy = strat.get(cfg.strategy)
        self.data_axes = getattr(self.strategy, "axes", None) or DATA_AXIS
        if self.strategy.needs_mesh and mesh is None:
            if isinstance(self.data_axes, tuple):
                n = num_devices or len(jax.devices())
                if n % cfg.dcn_size:
                    raise ValueError(
                        f"dcn_size {cfg.dcn_size} must divide the "
                        f"{n}-device fleet for strategy "
                        f"{self.strategy.name!r}")
                mesh = make_mesh(n, axis_names=self.data_axes,
                                 axis_shape=(cfg.dcn_size,
                                             n // cfg.dcn_size))
            else:
                mesh = make_mesh(num_devices)
        if (self.strategy.needs_mesh and isinstance(self.data_axes, tuple)
                and tuple(mesh.axis_names) != self.data_axes):
            raise ValueError(
                f"strategy {self.strategy.name!r} needs a mesh with axes "
                f"{self.data_axes}, got {mesh.axis_names}")
        if self.strategy.needs_mesh and isinstance(self.data_axes, tuple):
            # caller-supplied factored meshes too: the outer (dcn) extent
            # must match cfg.dcn_size — the int8 EF residual layout and
            # the bench accounting are sized from the config, and a
            # mismatch would surface as a cryptic reshape at trace time
            dcn_axis = self.data_axes[0]
            sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
            if sizes[dcn_axis] != cfg.dcn_size:
                raise ValueError(
                    f"mesh {dcn_axis!r} axis has size {sizes[dcn_axis]} "
                    f"but cfg.dcn_size is {cfg.dcn_size}; pass a mesh "
                    f"matching the config (or mesh=None to build one)")
        self.mesh = mesh if self.strategy.needs_mesh else None
        self.n_replicas = self.mesh.devices.size if self.mesh else 1
        if self.mesh is not None and hasattr(self.strategy, "n_by_axis"):
            # RoutedSync sizes its EF state from static per-axis extents
            self.strategy.n_by_axis = dict(
                zip(self.mesh.axis_names,
                    (int(s) for s in self.mesh.devices.shape)))
        # strategy knobs must land before init_state (dcn compression
        # flips statefulness and the EF residual layout follows the
        # bucket plan + dcn_size) and fail fast on incapable strategies
        _apply_dcn(cfg, self.strategy)
        _apply_bucket_mb(cfg, self.strategy)
        _validate_overlap(cfg, self.strategy, self.mesh)
        # round-18 window coherence at the ONE definition site — includes
        # the dispatch-alignment refusal (steps_per_loop % sync_every)
        # so every compiled dispatch ends on a window boundary
        strat.require_sync_window(
            sync_every=cfg.sync_every, max_sync_every=cfg.max_sync_every,
            mesh=self.mesh is not None, overlap=cfg.overlap,
            steps_per_loop=cfg.steps_per_loop, trainer="train",
            outer_opt=cfg.outer_opt, outer_momentum=cfg.outer_momentum,
            outer_lr=cfg.outer_lr)

        key = jax.random.key(cfg.seed)
        self.init_key, self.data_key = jax.random.split(key)
        params, state = vgg.init(self.init_key, cfg.model)
        tx = make_optimizer(cfg)
        opt_state = tx.init(params)

        # Stateful strategies (error-feedback ring) carry a per-device
        # residual between steps, stacked like BN state; stateless ones
        # thread a zero-size dummy through the same slot.
        if getattr(self.strategy, "stateful", False):
            sync_state = self.strategy.init_state(params, self.n_replicas)
        else:
            sync_state = jnp.zeros((0,), jnp.float32)
        if _outer_of(cfg) is not None:
            # DiLoCo outer momentum (round 22): a flat f32 tail appended
            # after the strategy's residual segments — same carry slot,
            # so the window scan's signature and specs are unchanged
            sync_state = jnp.concatenate(
                [sync_state,
                 jnp.zeros((strat.OuterOptimizer.state_len(params),),
                           jnp.float32)])
        sync_state = jnp.broadcast_to(
            sync_state[None], (self.n_replicas,) + sync_state.shape)

        if self.mesh is not None:
            rep = replicated(self.mesh)
            shd = NamedSharding(self.mesh, P(self.data_axes))
            params = jax.device_put(params, rep)
            if cfg.sync_every > 1:
                # windowed mode: per-device momentum rides a leading
                # device axis like BN state (local-momentum local SGD —
                # it never crosses the wire, keeping the boundary
                # exchange delta-only)
                opt_state = jax.device_put(
                    replicate_state(opt_state, self.n_replicas), shd)
            else:
                opt_state = jax.device_put(opt_state, rep)
            state = jax.device_put(
                replicate_state(state, self.n_replicas), shd)
            sync_state = jax.device_put(sync_state, shd)
        self.params, self.state, self.opt_state = params, state, opt_state
        self.sync_state = sync_state
        self._multi_fn = None   # jitted K-step program, built lazily
        self._compiled = {}     # (images.shape, labels.shape) -> AOT executable
        self._step = 0
        self.last_ok = None     # (K,) health flags of the last dispatch
        # (K, 2) [grad gnorm, param gnorm] of the last dispatch — the
        # round-13 telemetry scalars, fetched lazily like last_ok
        self.last_metrics = None
        # snapshot the chaos-tap signature decision NOW: the AOT
        # executables are cached, so a plan installed mid-run must not
        # change the compiled arg list (install plans before building)
        self._fault_sig = faults.step_plan() is not None
        # vma-opaque strategies (ppermute-assembled results) compile with
        # check_vma=False — the static replication proof is off, so EVERY
        # freshly compiled executable (first step, and any later
        # shape-specialized recompile) has its first real step followed by
        # a DYNAMIC verification that params/opt-state are still bitwise
        # replicated (the failure mode the static checker would have
        # caught is a missing/broken collective, which desyncs
        # immediately, not gradually).  Tracked PER EXECUTABLE (shape
        # key): _executable arms the key on cache miss, train_steps
        # verifies after the first run of each armed key — so interleaved
        # precompiles/shapes each get their own check.
        self._vma_opaque = bool(
            getattr(self.strategy, "vma_opaque", False)
            and self.mesh is not None)
        self._unverified_exes: set = set()
        self._window_wire_bytes = self._compute_window_wire_bytes()

    def _compute_window_wire_bytes(self):
        """Static f32 payload of ONE window-boundary exchange (the round-18
        per-window wire gauge): the shard-sized dcn hop for hierarchical
        (per bucket, ceil(bucket/n_ici) elements), the full tree for flat
        strategies.  Compression rides below this estimate (int8 ~1/4,
        int4 ~1/8 of it); None when not windowed."""
        if self.cfg.sync_every <= 1:
            return None
        leaves = jax.tree.leaves(self.params)
        if hasattr(self.strategy, "window_exchange"):
            n_ici = max(self.n_replicas // self.cfg.dcn_size, 1)
            return sum(
                4 * -(-sum(leaves[i].size for i in b) // n_ici)
                for b in strat.make_bucket_plan(
                    leaves, self.strategy.bucket_bytes))
        return sum(4 * leaf.size for leaf in leaves)

    # -- one optimizer step over a *global* batch -------------------------
    def train_step(self, images: np.ndarray, labels: np.ndarray) -> jax.Array:
        """One step == ``train_steps`` with K=1 (same compiled path, same
        RNG stream: per-step key is fold_in(data_key, step))."""
        return self.train_steps(images[None], labels[None])[0]

    # -- K optimizer steps in one device dispatch -------------------------
    def _stage(self, images, labels):
        """Place stacked (K, global_batch, ...) arrays onto the mesh.

        Idempotent: already-staged jax.Arrays (e.g. from the prefetch
        thread) pass through — re-staging a global multi-host array through
        make_array_from_process_local_data would fail."""
        if self.mesh is None:
            return images, labels
        shd = NamedSharding(self.mesh, P(None, self.data_axes))
        if isinstance(images, jax.Array) and images.sharding == shd:
            return images, labels
        if jax.process_count() > 1:
            # Multi-host: each process contributes its local ranks' shard
            # of the global batch (the per-host DistributedSampler split,
            # reference main_all_reduce.py:112); assemble a global array.
            return (jax.make_array_from_process_local_data(shd, images),
                    jax.make_array_from_process_local_data(shd, labels))
        if images.shape[1] % self.n_replicas != 0:
            raise ValueError(
                f"global batch {images.shape[1]} not divisible by the "
                f"{self.n_replicas}-device {self.data_axes!r} mesh axis; "
                f"pass per-replica batches of equal size (the sampler "
                f"pads the epoch for exactly this reason)")
        return jax.device_put(images, shd), jax.device_put(labels, shd)

    def _executable(self, args):
        """AOT-compile the K-step program for these batch shapes (cached).

        ``lower().compile()`` builds the executable without running it, so
        callers (train_epoch) can keep compile time out of timed windows —
        the reference's iter-0 exclusion contract (main.py:43-48) would
        otherwise be diluted to 1/K by the scan."""
        key = (args[6].shape, args[7].shape)  # (images, labels)
        exe = self._compiled.get(key)
        if exe is None:
            # compile lane (round 15): per-program-hash compile time +
            # cache size on the unified stream; telemetry off = no-op
            with monitor.compile_span(
                    "aot_compile", key=key,
                    cache_size=lambda: len(self._compiled)):
                if self._multi_fn is None:
                    self._multi_fn = make_multi_step(
                        self.cfg, self.strategy, self.mesh,
                        fault_sig=self._fault_sig)
                if compat.AOT_EXECUTION_SAFE:
                    exe = self._multi_fn.lower(*args).compile()
                else:
                    # old runtimes abort EXECUTING a cache-loaded AOT
                    # executable (utils/compat.py) — run through jit
                    # there; compile then lands inside the first timed
                    # step (a metrics skew on legacy hosts, not a
                    # correctness loss)
                    exe = self._multi_fn
                self._compiled[key] = exe
            if self._vma_opaque:
                # new executable, no static vma proof: re-verify
                # replication after ITS first real step (see __init__)
                self._unverified_exes.add(key)
        return exe

    def _args(self, images, labels, fault_arm: float = 0.0):
        step0 = jnp.asarray(self._step, jnp.int32)
        args = (self.params, self.state, self.opt_state, self.sync_state,
                self.data_key, step0, images, labels)
        if self._fault_sig:
            # the compiled step carries the chaos-tap arm scalar (traced,
            # so 0.0 vs 1.0 never recompiles); clean builds have no slot
            args += (jnp.float32(fault_arm),)
        return args

    def precompile_steps(self, images: np.ndarray, labels: np.ndarray) -> None:
        """Ensure the program for these (K, batch, ...) shapes is compiled
        WITHOUT executing a step (no state is consumed)."""
        images, labels = self._stage(images, labels)
        self._executable(self._args(images, labels))

    def train_steps(self, images: np.ndarray, labels: np.ndarray) -> jax.Array:
        """Run ``K = images.shape[0]`` steps over stacked global batches
        (K, global_batch, ...) as one compiled ``lax.scan``; returns the K
        per-step losses.  Produces the identical parameter/RNG trajectory as
        K ``train_step`` calls — just one dispatch instead of K."""
        k = images.shape[0]
        if self.cfg.sync_every > 1 and k % self.cfg.sync_every:
            raise ValueError(
                f"train_steps got {k} steps with sync_every="
                f"{self.cfg.sync_every}: dispatches must be window-"
                f"aligned (k % H == 0) so params leave the step "
                f"replicated; stack window-multiple batches (train_step's "
                f"K=1 path is likewise unavailable under windows)")
        faults.maybe_delay(self._step, k)  # chaos: straggler (no-op unplanned)
        images, labels = self._stage(images, labels)
        # one-shot host arming of step-keyed grad/loss faults (consumes a
        # firing only when the plan's step falls in this dispatch window).
        # Gated on the build-time signature snapshot: a plan installed
        # AFTER construction has no arm slot in the compiled step, and
        # arming would silently consume its firing without injecting
        # (plans must be installed before building — _fault_sig note)
        args = self._args(images, labels,
                          faults.arm_window(self._step, k)
                          if self._fault_sig else 0.0)
        key = (args[6].shape, args[7].shape)
        t0 = time.perf_counter()
        (self.params, self.state, self.opt_state, self.sync_state,
         losses, oks, mets) = self._executable(args)(*args)
        # per-step health flags for the training sentry (1.0 = loss and
        # synced grads finite on every replica); fetched lazily by readers
        self.last_ok = oks
        self.last_metrics = mets
        self._step += k
        faults.maybe_crash(self._step, k)  # chaos: injected process death
        tel = telemetry.active()
        if tel is not None:
            telemetry.emit_train_steps(tel, t0, self._step - k, k, losses,
                                       oks, mets)
            if self.cfg.sync_every > 1:
                telemetry.emit_sync_windows(
                    tel, t0, self._step - k, k, self.cfg.sync_every,
                    wire_bytes=self._window_wire_bytes)
        if key in self._unverified_exes:
            self._unverified_exes.discard(key)
            self.check_consistency()
        return losses

    def train_epoch(self, loaders, epoch: int, *, log=print, on_step=None):
        """One epoch over per-replica loaders, with the reference's metric
        windows (loss/20 iters, time/40 iters excl. iter 0 — SURVEY.md 2.3).

        ``loaders``: one DataLoader per replica (the global batch is their
        concatenation), or a single loader for the single-process baseline.
        ``on_step(step)`` fires once per device dispatch (before compile) —
        the elastic CLI's heartbeat cadence, so a long epoch cannot be
        misread as a hung worker (launch.py heartbeat staleness).
        """
        if not isinstance(loaders, (list, tuple)):
            loaders = [loaders]
        # One loader per *locally-fed* replica: all of them single-host, this
        # process's shard of the mesh on multi-host.
        local = max(1, self.n_replicas // max(jax.process_count(), 1))
        assert len(loaders) == local, (
            f"got {len(loaders)} loaders for {local} local replicas")
        for dl in loaders:
            dl.set_epoch(epoch)
        loss_meter, time_meter = LossMeter(), IterTimeMeter()

        def record(batch_idx, loss_val, elapsed):
            rec = loss_meter.update(batch_idx, loss_val)
            if rec and log:
                log(f"Epoch: {epoch + 1}, Iteration: {rec.first_iter}-"
                    f"{rec.last_iter}, Average Loss: {rec.value:.3f}")
            rec = time_meter.update(batch_idx, elapsed)
            if rec and log:
                log(f"Avg Time for iteration {rec.first_iter}-{rec.last_iter}: "
                    f"{rec.value} seconds.")

        spl = max(1, self.cfg.steps_per_loop)

        def host_chunks():
            """Stack loader batches into K-step scan chunks (a ragged final
            batch flushes early — it can't stack with full ones)."""
            chunk: list[tuple[np.ndarray, np.ndarray]] = []
            for batches in zip(*loaders):
                batch = (np.concatenate([b[0] for b in batches]),
                         np.concatenate([b[1] for b in batches]))
                if chunk and batch[0].shape != chunk[0][0].shape:
                    yield chunk
                    chunk = []
                chunk.append(batch)
                if len(chunk) == spl:
                    yield chunk
                    chunk = []
            if chunk:
                yield chunk  # tail: one smaller scan, compiled once per size

        def staged():
            """Assemble + device-stage chunks; runs on the prefetch thread
            so transfer overlaps the previous chunk's compute."""
            for chunk in host_chunks():
                images = np.stack([c[0] for c in chunk])
                labels = np.stack([c[1] for c in chunk])
                if self.mesh is not None:
                    images, labels = self._stage(images, labels)
                else:
                    images, labels = jax.device_put((images, labels))
                yield len(chunk), images, labels

        batch_idx = 0
        for k, images, labels in pipeline.prefetch(staged(), depth=2):
            if on_step is not None:
                on_step(self._step)
            # Compile outside the timed window: the reference's metric
            # excludes warm-up (iter 0, main.py:43-48); with a K-step scan
            # the compile would otherwise smear across K counted iters.
            self.precompile_steps(images, labels)
            begin = time.perf_counter()
            with tracing.annotate_step(self._step):
                losses = np.asarray(self.train_steps(images, labels))
            per_step = (time.perf_counter() - begin) / k
            for loss_val in losses:
                record(batch_idx, float(loss_val), per_step)
                batch_idx += 1
        return loss_meter, time_meter

    def eval_state(self) -> PyTree:
        return rank0_state(self.state, self.mesh)

    # -- elastic resize (round 12) ----------------------------------------
    def rebuild(self, mesh: Mesh | None = None,
                num_devices: int | None = None, **overrides) -> None:
        """Re-create the compiled step on a NEW mesh, carrying the live
        training state across — the in-process half of the elastic gang
        (parallel/elastic.py): when the fleet shrinks or grows, the step
        is re-built rather than the whole process.

        Params/optimizer state are replicated, so they re-place exactly;
        replica-stacked BN state takes rank 0's stats re-stacked to the
        new replica count (the same convention as the cross-topology
        ``Checkpointer.maybe_restore``, so a rebuilt trainer and a fresh
        one restored from the last checkpoint continue BITWISE-equal —
        test-pinned); the EF sync residual re-initializes (dropping it
        is safe — residuals re-accumulate within one step).  Compiled
        executables are discarded; the step counter survives.

        Single-controller only: a multi-process gang resizes by drain +
        re-rendezvous (the worker re-runs init at the new WORLD_SIZE),
        not by in-process rebuild."""
        if jax.process_count() > 1:
            raise ValueError(
                "in-process rebuild is single-controller; multi-process "
                "gangs resize via the elastic agent's drain + "
                "re-rendezvous (launch.py --elastic)")
        was_windowed = self.cfg.sync_every > 1
        if overrides:
            # config overrides (round 18): the monitor's straggler
            # actuator widens/narrows sync_every through here — re-tune
            # step knobs on the LIVE strategy; a strategy change needs a
            # fresh Trainer (mesh recipe and sync-state layout differ)
            cfg = replace(self.cfg, **overrides)
            if cfg.strategy != self.cfg.strategy:
                raise ValueError(
                    "rebuild(**overrides) re-tunes step knobs on the "
                    "live strategy; changing the strategy itself needs "
                    "a fresh Trainer")
            strat.require_sync_window(
                sync_every=cfg.sync_every,
                max_sync_every=cfg.max_sync_every, mesh=True,
                overlap=cfg.overlap, steps_per_loop=cfg.steps_per_loop,
                trainer="train", outer_opt=cfg.outer_opt,
                outer_momentum=cfg.outer_momentum, outer_lr=cfg.outer_lr)
            self.cfg = cfg
        if not self.strategy.needs_mesh:
            raise ValueError(
                f"strategy {self.strategy.name!r} runs without a mesh; "
                f"there is no topology to resize")
        if mesh is None:
            if isinstance(self.data_axes, tuple):
                n = num_devices or len(jax.devices())
                if n % self.cfg.dcn_size:
                    raise ValueError(
                        f"dcn_size {self.cfg.dcn_size} must divide the "
                        f"resized {n}-device fleet")
                mesh = make_mesh(n, axis_names=self.data_axes,
                                 axis_shape=(self.cfg.dcn_size,
                                             n // self.cfg.dcn_size))
            else:
                mesh = make_mesh(num_devices)
        if isinstance(self.data_axes, tuple):
            if tuple(mesh.axis_names) != self.data_axes:
                raise ValueError(
                    f"strategy {self.strategy.name!r} needs a mesh with "
                    f"axes {self.data_axes}, got {mesh.axis_names}")
            # same extent check as __init__: the EF residual layout and
            # bench accounting are sized from cfg.dcn_size, and a
            # mismatched caller-supplied mesh would only surface as a
            # cryptic reshape at trace time
            dcn_axis = self.data_axes[0]
            sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
            if sizes[dcn_axis] != self.cfg.dcn_size:
                raise ValueError(
                    f"resized mesh {dcn_axis!r} axis has size "
                    f"{sizes[dcn_axis]} but cfg.dcn_size is "
                    f"{self.cfg.dcn_size}; pass a matching mesh (or "
                    f"mesh=None to build one)")
        from .utils.checkpoint import _fetch  # owned copies (donation)

        params_host = jax.tree.map(_fetch, self.params)
        opt_host = jax.tree.map(_fetch, self.opt_state)
        if was_windowed:
            # per-device momentum rode a leading device axis; carry rank
            # 0's buffers across the resize (the BN rank-0 convention)
            opt_host = jax.tree.map(lambda s: s[0], opt_host)
        state0 = rank0_state(self.state, self.mesh)  # rank-0 authoritative

        self.mesh = mesh
        self.n_replicas = mesh.devices.size
        rep = replicated(mesh)
        shd = NamedSharding(mesh, P(self.data_axes))
        self.params = jax.device_put(params_host, rep)
        if self.cfg.sync_every > 1:
            self.opt_state = jax.device_put(
                replicate_state(jax.tree.map(jnp.asarray, opt_host),
                                self.n_replicas), shd)
        else:
            self.opt_state = jax.device_put(opt_host, rep)
        self.state = jax.device_put(
            replicate_state(jax.tree.map(jnp.asarray, state0),
                            self.n_replicas), shd)
        if getattr(self.strategy, "stateful", False):
            sync_state = self.strategy.init_state(params_host,
                                                  self.n_replicas)
        else:
            sync_state = jnp.zeros((0,), jnp.float32)
        if _outer_of(self.cfg) is not None:
            # fresh outer momentum after a resize (anchor topology
            # changed; same convention as the EF residual reset)
            sync_state = jnp.concatenate(
                [sync_state,
                 jnp.zeros((strat.OuterOptimizer.state_len(params_host),),
                           jnp.float32)])
        self.sync_state = jax.device_put(
            jnp.broadcast_to(sync_state[None],
                             (self.n_replicas,) + sync_state.shape), shd)
        self._multi_fn = None
        self._compiled = {}
        self._unverified_exes = set()
        self.last_ok = None
        self.last_metrics = None
        self._window_wire_bytes = self._compute_window_wire_bytes()

    def check_consistency(self) -> None:
        """Verify the DP invariants (utils/debug.py): params and optimizer
        state bitwise-identical on every replica, and finite.  The check the
        reference never does — torch DDP enforces it once by broadcast; the
        manual variants just trust same-seed init + sync (SURVEY.md 2.3).
        Under sync_every > 1 the optimizer state is per-device BY DESIGN
        (local momentum, a leading device axis) — only params, which every
        window boundary re-replicates, are checked there."""
        tree = {"params": self.params}
        if self.cfg.sync_every == 1:
            tree["opt_state"] = self.opt_state
        dbg.assert_replicas_in_sync(tree, what="params/opt_state")
        dbg.assert_finite(jax.tree.map(np.asarray, self.params),
                          what="params")
