"""Evaluation: the reference's ``test_model`` semantics, compiled.

Reference (main.py:51-66): model.eval(), no grad, sum per-batch mean losses,
divide by the *number of batches*, and argmax accuracy over the full test set.
The test set is NOT sharded — every rank evaluates all 10k images redundantly
(SURVEY.md section 2.1 item 10); here one evaluation runs on device with BN
running statistics (rank 0's, matching DDP's buffer-broadcast convention).

Batches are padded to a static shape with a validity mask so every batch
compiles to the same program (XLA: static shapes), instead of a second
compilation for the ragged last batch.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .data import augment as aug
from .models import vgg
from .ops import nn as ops

PyTree = Any


@partial(jax.jit, static_argnames=("model_name", "dtype"))
def _eval_batch(params, state, images, labels, mask, *, model_name, dtype):
    x = aug.normalize(images)  # test transform: ToTensor+Normalize (main.py:80-82)
    logits, _ = vgg.apply(params, state, x, name=model_name, train=False,
                          dtype=dtype)
    ce = ops.cross_entropy_per_sample(logits, labels) * mask
    correct = jnp.sum((jnp.argmax(logits, axis=-1) == labels) * mask)
    # per-batch mean over real samples == torch CrossEntropyLoss reduction
    return jnp.sum(ce) / jnp.maximum(jnp.sum(mask), 1), correct


def evaluate(params: PyTree, state: PyTree, loader, *,
             model_name: str = "VGG11",
             compute_dtype: jnp.dtype | None = None,
             log=print) -> tuple[float, float]:
    """Full-test-set eval; returns (avg_loss, accuracy).

    ``avg_loss`` is the sum of per-batch mean losses divided by the batch
    count — the reference's exact (slightly unusual) definition
    (main.py:59,63)."""
    total_loss, correct, total, n_batches = 0.0, 0, 0, 0
    batch_size = None
    for images, labels in loader:
        if batch_size is None:
            batch_size = len(labels)
        n = len(labels)
        if n < batch_size:  # pad ragged last batch to the static shape
            pad = batch_size - n
            images = np.concatenate([images, np.zeros((pad,) + images.shape[1:],
                                                      images.dtype)])
            labels = np.concatenate([labels, np.zeros(pad, labels.dtype)])
        mask = (np.arange(batch_size) < n).astype(np.float32)
        loss, corr = _eval_batch(params, state, jnp.asarray(images),
                                 jnp.asarray(labels), jnp.asarray(mask),
                                 model_name=model_name, dtype=compute_dtype)
        total_loss += float(loss)
        correct += int(corr)
        total += n
        n_batches += 1
    avg_loss = total_loss / max(n_batches, 1)
    acc = correct / max(total, 1)
    if log:
        log(f"Test set: Average loss: {avg_loss:.4f}, "
            f"Accuracy: {correct}/{total} ({100.0 * acc:.0f}%)\n")
    return avg_loss, acc


def evaluate_sharded(params: PyTree, state: PyTree, dataset, mesh, *,
                     batch_size: int = 256, model_name: str = "VGG11",
                     compute_dtype: jnp.dtype | None = None,
                     log=print) -> tuple[float, float]:
    """Mesh-sharded evaluation: the test set is split over the data axis and
    per-shard sums are psum'd — an O(devices) speedup the reference
    deliberately forgoes (every rank evaluates all 10k images redundantly,
    main_gather.py:131); ``evaluate`` above keeps that replicated semantic,
    this is the capability upgrade behind a flag.

    Loss definition matches ``evaluate``: sum of per-(global-)batch mean
    losses over real samples, divided by batch count.
    """
    from functools import partial as _partial

    from jax import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .parallel.mesh import DATA_AXIS

    if jax.process_count() > 1:
        raise NotImplementedError(
            "--shard-eval is single-process for now: the eval batches are "
            "host-local numpy and would need make_array_from_process_local_"
            "data assembly (as Trainer._stage does) for a multi-host mesh")
    n_dev = mesh.devices.size
    if batch_size % max(n_dev, 1):
        # keep batch boundaries (and therefore the per-batch-mean loss
        # definition) identical to `evaluate`
        raise ValueError(f"batch_size {batch_size} must be divisible by the "
                         f"{n_dev}-device mesh for loss parity with "
                         f"evaluate()")
    per_dev = batch_size // max(n_dev, 1)
    global_batch = per_dev * n_dev

    @_partial(jax.jit, static_argnames=("model_name", "dtype"))
    def batch_metrics(params, state, images, labels, mask, *, model_name,
                      dtype):
        def shard_fn(params, state, images, labels, mask):
            local_state = jax.tree.map(lambda s: s[0], state)
            x = aug.normalize(images)
            logits, _ = vgg.apply(params, local_state, x, name=model_name,
                                  train=False, dtype=dtype)
            ce = ops.cross_entropy_per_sample(logits, labels) * mask
            correct = jnp.sum(
                (jnp.argmax(logits, axis=-1) == labels) * mask)
            return (jax.lax.psum(jnp.sum(ce), DATA_AXIS),
                    jax.lax.psum(correct, DATA_AXIS),
                    jax.lax.psum(jnp.sum(mask), DATA_AXIS))

        return shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS)),
            out_specs=(P(), P(), P()))(params, state, images, labels, mask)

    # state arrives replicated per-device stacked (leading axis) like the
    # trainer's; eval uses rank 0's stats on every shard for parity with
    # `evaluate` (DDP buffer-broadcast convention)
    state = jax.tree.map(
        lambda s: jnp.broadcast_to(jnp.asarray(s)[None],
                                   (n_dev,) + np.asarray(s).shape), state)
    state = jax.device_put(state, NamedSharding(mesh, P(DATA_AXIS)))

    total_loss, correct, total, n_batches = 0.0, 0, 0, 0
    images_all, labels_all = dataset.images, dataset.labels
    for start in range(0, len(labels_all), global_batch):
        images = images_all[start:start + global_batch]
        labels = labels_all[start:start + global_batch]
        n = len(labels)
        if n < global_batch:
            pad = global_batch - n
            images = np.concatenate(
                [images, np.zeros((pad,) + images.shape[1:], images.dtype)])
            labels = np.concatenate([labels, np.zeros(pad, labels.dtype)])
        mask = (np.arange(global_batch) < n).astype(np.float32)
        ce_sum, corr, msum = batch_metrics(
            params, state, jnp.asarray(images), jnp.asarray(labels),
            jnp.asarray(mask), model_name=model_name, dtype=compute_dtype)
        total_loss += float(ce_sum) / max(float(msum), 1.0)
        correct += int(corr)
        total += n
        n_batches += 1
    avg_loss = total_loss / max(n_batches, 1)
    acc = correct / max(total, 1)
    if log:
        log(f"Test set (sharded x{n_dev}): Average loss: {avg_loss:.4f}, "
            f"Accuracy: {correct}/{total} ({100.0 * acc:.0f}%)\n")
    return avg_loss, acc
