"""Evaluation: the reference's ``test_model`` semantics, compiled.

Reference (main.py:51-66): model.eval(), no grad, sum per-batch mean losses,
divide by the *number of batches*, and argmax accuracy over the full test set.
The test set is NOT sharded — every rank evaluates all 10k images redundantly
(SURVEY.md section 2.1 item 10); here one evaluation runs on device with BN
running statistics (rank 0's, matching DDP's buffer-broadcast convention).

Batches are padded to a static shape with a validity mask so every batch
compiles to the same program (XLA: static shapes), instead of a second
compilation for the ragged last batch.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .data import augment as aug
from .models import vgg
from .ops import nn as ops

PyTree = Any


def _batch_metrics(params, state, images, labels, mask, *, model_name,
                   dtype, folded=False):
    """Masked (ce_sum, correct, n_real) for one padded batch — the single
    compute core behind both the replicated and the sharded eval paths.
    With ``folded``, ``params`` is a vgg.fold_bn tree (state unused)."""
    x = aug.normalize(images)  # test transform: ToTensor+Normalize (main.py:80-82)
    if folded:
        logits = vgg.apply_folded(params, x, name=model_name, dtype=dtype)
    else:
        logits, _ = vgg.apply(params, state, x, name=model_name, train=False,
                              dtype=dtype)
    ce = ops.cross_entropy_per_sample(logits, labels) * mask
    correct = jnp.sum((jnp.argmax(logits, axis=-1) == labels) * mask)
    return jnp.sum(ce), correct, jnp.sum(mask)


def _pad_batch(images, labels, batch_size):
    """Pad a ragged batch to the static shape + validity mask."""
    n = len(labels)
    if n < batch_size:
        pad = batch_size - n
        images = np.concatenate(
            [images, np.zeros((pad,) + images.shape[1:], images.dtype)])
        labels = np.concatenate([labels, np.zeros(pad, labels.dtype)])
    mask = (np.arange(batch_size) < n).astype(np.float32)
    return images, labels, mask, n


@partial(jax.jit, static_argnames=("model_name", "dtype", "folded"))
def _eval_batch(params, state, images, labels, mask, *, model_name, dtype,
                folded=False):
    ce_sum, correct, n_real = _batch_metrics(
        params, state, images, labels, mask, model_name=model_name,
        dtype=dtype, folded=folded)
    # per-batch mean over real samples == torch CrossEntropyLoss reduction
    return ce_sum / jnp.maximum(n_real, 1), correct


def evaluate(params: PyTree, state: PyTree, loader, *,
             model_name: str = "VGG11",
             compute_dtype: jnp.dtype | None = None,
             fold_bn: bool = False,
             log=print) -> tuple[float, float]:
    """Full-test-set eval; returns (avg_loss, accuracy).

    ``avg_loss`` is the sum of per-batch mean losses divided by the batch
    count — the reference's exact (slightly unusual) definition
    (main.py:59,63).  ``fold_bn`` folds the BatchNorm statistics into the
    conv weights once up front (models/vgg.fold_bn) — mathematically
    identical, one fewer normalize pass per conv layer."""
    if fold_bn:
        params = vgg.fold_bn(params, state, name=model_name)
    total_loss, correct, total, n_batches = 0.0, 0, 0, 0
    batch_size = None
    for images, labels in loader:
        if batch_size is None:
            batch_size = len(labels)
        images, labels, mask, n = _pad_batch(images, labels, batch_size)
        loss, corr = _eval_batch(params, state, jnp.asarray(images),
                                 jnp.asarray(labels), jnp.asarray(mask),
                                 model_name=model_name, dtype=compute_dtype,
                                 folded=fold_bn)
        total_loss += float(loss)
        correct += int(corr)
        total += n
        n_batches += 1
    avg_loss = total_loss / max(n_batches, 1)
    acc = correct / max(total, 1)
    if log:
        log(f"Test set: Average loss: {avg_loss:.4f}, "
            f"Accuracy: {correct}/{total} ({100.0 * acc:.0f}%)\n")
    return avg_loss, acc


@partial(jax.jit, static_argnames=("mesh", "model_name", "dtype", "folded"))
def _sharded_batch(params, state, images, labels, mask, *, mesh, model_name,
                   dtype, folded=False):
    """Mesh-sharded (ce_sum, correct, n_real) — jit-cached across epochs
    (mesh/model/dtype are hashable statics, so repeat calls reuse the
    executable instead of recompiling per evaluate_sharded call)."""
    from .utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    # The data axis may be factored (hierarchical: ('dcn', 'ici')) — shard
    # the batch and reduce over ALL mesh axes, whatever their names.
    axes = tuple(mesh.axis_names)

    def shard_fn(params, state, images, labels, mask):
        ce_sum, correct, n_real = _batch_metrics(
            params, state, images, labels, mask, model_name=model_name,
            dtype=dtype, folded=folded)
        return (jax.lax.psum(ce_sum, axes),
                jax.lax.psum(correct, axes),
                jax.lax.psum(n_real, axes))

    return shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P(), P(axes), P(axes), P(axes)),
        out_specs=(P(), P(), P()))(params, state, images, labels, mask)


def evaluate_sharded(params: PyTree, state: PyTree, dataset, mesh, *,
                     batch_size: int = 256, model_name: str = "VGG11",
                     compute_dtype: jnp.dtype | None = None,
                     fold_bn: bool = False,
                     log=print) -> tuple[float, float]:
    """Mesh-sharded evaluation: the test set is split over the data axis and
    per-shard sums are psum'd — an O(devices) speedup the reference
    deliberately forgoes (every rank evaluates all 10k images redundantly,
    main_gather.py:131); ``evaluate`` above keeps that replicated semantic,
    this is the capability upgrade behind a flag.

    Loss definition matches ``evaluate`` (sum of per-batch mean losses over
    real samples / batch count), enforced by requiring device-divisible
    batches so batch boundaries are identical.  Multi-host meshes work:
    every process loads the full test set (the reference's download-
    everywhere behavior) and each padded batch is assembled into a global
    array with ``make_array_from_process_local_data`` — its full-shape
    fast path slices each process's device rows out of the replicated
    host copy.  ``state`` is the unstacked rank-0 BN state, exactly as
    ``evaluate`` takes it (replicated onto every shard by the P() in_spec).
    """
    if fold_bn:
        params = vgg.fold_bn(params, state, name=model_name)
    n_dev = mesh.devices.size
    if batch_size % max(n_dev, 1):
        raise ValueError(f"batch_size {batch_size} must be divisible by the "
                         f"{n_dev}-device mesh for loss parity with "
                         f"evaluate()")

    from jax.sharding import NamedSharding, PartitionSpec as P

    data_shd = NamedSharding(mesh, P(tuple(mesh.axis_names)))

    def stage(arr):
        arr = np.asarray(arr)
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(
                data_shd, arr, arr.shape)
        return jnp.asarray(arr)

    total_loss, correct, total, n_batches = 0.0, 0, 0, 0
    images_all, labels_all = dataset.images, dataset.labels
    for start in range(0, len(labels_all), batch_size):
        images, labels, mask, n = _pad_batch(
            images_all[start:start + batch_size],
            labels_all[start:start + batch_size], batch_size)
        ce_sum, corr, n_real = _sharded_batch(
            params, state, stage(images), stage(labels),
            stage(mask), mesh=mesh, model_name=model_name,
            dtype=compute_dtype, folded=fold_bn)
        total_loss += float(ce_sum) / max(float(n_real), 1.0)
        correct += int(corr)
        total += n
        n_batches += 1
    avg_loss = total_loss / max(n_batches, 1)
    acc = correct / max(total, 1)
    if log:
        log(f"Test set (sharded x{n_dev}): Average loss: {avg_loss:.4f}, "
            f"Accuracy: {correct}/{total} ({100.0 * acc:.0f}%)\n")
    return avg_loss, acc
