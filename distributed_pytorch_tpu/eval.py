"""Evaluation: the reference's ``test_model`` semantics, compiled.

Reference (main.py:51-66): model.eval(), no grad, sum per-batch mean losses,
divide by the *number of batches*, and argmax accuracy over the full test set.
The test set is NOT sharded — every rank evaluates all 10k images redundantly
(SURVEY.md section 2.1 item 10); here one evaluation runs on device with BN
running statistics (rank 0's, matching DDP's buffer-broadcast convention).

Batches are padded to a static shape with a validity mask so every batch
compiles to the same program (XLA: static shapes), instead of a second
compilation for the ragged last batch.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .data import augment as aug
from .models import vgg
from .ops import nn as ops

PyTree = Any


@partial(jax.jit, static_argnames=("model_name", "dtype"))
def _eval_batch(params, state, images, labels, mask, *, model_name, dtype):
    x = aug.normalize(images)  # test transform: ToTensor+Normalize (main.py:80-82)
    logits, _ = vgg.apply(params, state, x, name=model_name, train=False,
                          dtype=dtype)
    ce = ops.cross_entropy_per_sample(logits, labels) * mask
    correct = jnp.sum((jnp.argmax(logits, axis=-1) == labels) * mask)
    # per-batch mean over real samples == torch CrossEntropyLoss reduction
    return jnp.sum(ce) / jnp.maximum(jnp.sum(mask), 1), correct


def evaluate(params: PyTree, state: PyTree, loader, *,
             model_name: str = "VGG11",
             compute_dtype: jnp.dtype | None = None,
             log=print) -> tuple[float, float]:
    """Full-test-set eval; returns (avg_loss, accuracy).

    ``avg_loss`` is the sum of per-batch mean losses divided by the batch
    count — the reference's exact (slightly unusual) definition
    (main.py:59,63)."""
    total_loss, correct, total, n_batches = 0.0, 0, 0, 0
    batch_size = None
    for images, labels in loader:
        if batch_size is None:
            batch_size = len(labels)
        n = len(labels)
        if n < batch_size:  # pad ragged last batch to the static shape
            pad = batch_size - n
            images = np.concatenate([images, np.zeros((pad,) + images.shape[1:],
                                                      images.dtype)])
            labels = np.concatenate([labels, np.zeros(pad, labels.dtype)])
        mask = (np.arange(batch_size) < n).astype(np.float32)
        loss, corr = _eval_batch(params, state, jnp.asarray(images),
                                 jnp.asarray(labels), jnp.asarray(mask),
                                 model_name=model_name, dtype=compute_dtype)
        total_loss += float(loss)
        correct += int(corr)
        total += n
        n_batches += 1
    avg_loss = total_loss / max(n_batches, 1)
    acc = correct / max(total, 1)
    if log:
        log(f"Test set: Average loss: {avg_loss:.4f}, "
            f"Accuracy: {correct}/{total} ({100.0 * acc:.0f}%)\n")
    return avg_loss, acc
