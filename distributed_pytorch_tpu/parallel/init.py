"""Multi-host rendezvous: the TPU-native ``init_process_group``.

The reference rendezvouses 4 Gloo workers over TCP in one of two ways
(SURVEY.md section 2.1 item 7):

- explicit: ``init_process_group('gloo', init_method='tcp://<master-ip>:6585',
  world_size, rank)`` from ``--master-ip/--num-nodes/--rank`` CLI args
  (reference main_all_reduce.py:86-96);
- env-var: torchrun sets MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK and
  ``init_process_group('gloo')`` reads them (reference main_ddp.py:93-104).

Both contracts are preserved here, mapped onto
``jax.distributed.initialize(coordinator_address, num_processes,
process_id)``: the coordinator (rank 0's host, the ``--master-ip`` analog)
runs the distributed KV store; XLA then compiles collectives over ICI within
a slice and DCN across slices — there is no per-collective TCP path to
configure.

Failure-detection upgrade over the reference: the reference passes
``timeout=None`` so a missing peer hangs forever (SURVEY.md section 2.3).
Here rendezvous has a real default timeout and raises a diagnosable
``RendezvousError`` naming the coordinator it could not reach.
"""

from __future__ import annotations

import os

import jax

DEFAULT_PORT = 6585  # the reference's hard-coded port (main_all_reduce.py:96)
DEFAULT_TIMEOUT_S = 300


class RendezvousError(RuntimeError):
    """Multi-host initialization failed (peer missing / coordinator down)."""


def init_distributed(
    master_ip: str | None = None,
    num_nodes: int = 1,
    rank: int = 0,
    *,
    port: int = DEFAULT_PORT,
    timeout_s: int | None = DEFAULT_TIMEOUT_S,
) -> None:
    """Explicit-rendezvous mode (reference main_all_reduce.py:96 contract).

    No-op for ``num_nodes == 1`` (single-controller JAX needs no init), so the
    same entry point serves the single-process baseline (reference main.py).
    """
    if num_nodes <= 1:
        return
    if master_ip is None:
        raise ValueError("--master-ip is required when --num-nodes > 1")
    coordinator = f"{master_ip}:{port}"
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_nodes,
            process_id=rank,
            initialization_timeout=timeout_s if timeout_s else 86_400,
        )
    except Exception as e:
        raise RendezvousError(
            f"rendezvous with coordinator {coordinator} failed for rank "
            f"{rank}/{num_nodes} after {timeout_s}s: {e}") from e


def init_from_env(*, timeout_s: int | None = DEFAULT_TIMEOUT_S) -> None:
    """Env-var rendezvous mode (the torchrun convention, main_ddp.py:93-104).

    Reads MASTER_ADDR / MASTER_PORT / WORLD_SIZE / RANK.  Missing vars mean
    single-process (matching a bare ``python main_ddp.py`` failing loudly in
    the reference — here we degrade to the single-host path instead).
    """
    world_size = int(os.environ.get("WORLD_SIZE", "1"))
    init_distributed(
        os.environ.get("MASTER_ADDR"),
        world_size,
        int(os.environ.get("RANK", "0")),
        port=int(os.environ.get("MASTER_PORT", str(DEFAULT_PORT))),
        timeout_s=timeout_s,
    )


def shutdown() -> None:
    """Tear down the distributed service (torch's destroy_process_group)."""
    if jax.process_count() > 1:
        jax.distributed.shutdown()


def process_info() -> tuple[int, int]:
    """(process_id, process_count) — the post-init (rank, world_size)."""
    return jax.process_index(), jax.process_count()
