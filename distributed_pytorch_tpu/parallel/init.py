"""Multi-host rendezvous: the TPU-native ``init_process_group``.

The reference rendezvouses 4 Gloo workers over TCP in one of two ways
(SURVEY.md section 2.1 item 7):

- explicit: ``init_process_group('gloo', init_method='tcp://<master-ip>:6585',
  world_size, rank)`` from ``--master-ip/--num-nodes/--rank`` CLI args
  (reference main_all_reduce.py:86-96);
- env-var: torchrun sets MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK and
  ``init_process_group('gloo')`` reads them (reference main_ddp.py:93-104).

Both contracts are preserved here, mapped onto
``jax.distributed.initialize(coordinator_address, num_processes,
process_id)``: the coordinator (rank 0's host, the ``--master-ip`` analog)
runs the distributed KV store; XLA then compiles collectives over ICI within
a slice and DCN across slices — there is no per-collective TCP path to
configure.

Failure-detection upgrade over the reference: the reference passes
``timeout=None`` so a missing peer hangs forever (SURVEY.md section 2.3).
Here rendezvous has a real default timeout, retries transient connection
failures with EXPONENTIAL BACKOFF + seeded JITTER (a flapping/slow-to-come-up
coordinator costs seconds, not the run; the jitter decorrelates a pod's worth
of ranks re-dialing at once), and raises a diagnosable ``RendezvousError``
naming the coordinator it could not reach and how many attempts were made.
The chaos harness (utils/faults.py ``rendezvous`` plan) injects refused
connections into exactly this path, so the backoff is tested, not assumed.
"""

from __future__ import annotations

import os
import time

import jax
import numpy as np

from ..utils import faults

DEFAULT_PORT = 6585  # the reference's hard-coded port (main_all_reduce.py:96)
DEFAULT_TIMEOUT_S = 300
CONNECT_ATTEMPTS = 5     # rendezvous dials before giving up
BACKOFF_BASE_S = 1.0     # first retry delay (doubles per attempt)
BACKOFF_CAP_S = 30.0     # ceiling on any single delay

# Env overrides (round 12): long coordinator flaps — e.g. an elastic
# re-rendezvous racing a slow teardown — need a bigger retry budget than
# the code default, and operators tuning it must not have to edit code.
# Both parse ONCE per dial and fail loudly on typos (a silently-ignored
# budget would surface as an unexplained early give-up mid-incident).
ATTEMPTS_ENV = "JAX_GRAFT_RDZV_ATTEMPTS"
BACKOFF_CAP_ENV = "JAX_GRAFT_RDZV_BACKOFF_CAP_S"


def _env_positive(name: str, default, cast):
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        val = cast(raw)
    except ValueError:
        val = None
    if val is None or val <= 0:
        raise ValueError(
            f"{name} must be a positive {cast.__name__}, got {raw!r}")
    return val


def rdzv_attempts_from_env(default: int = CONNECT_ATTEMPTS) -> int:
    """The retry budget: JAX_GRAFT_RDZV_ATTEMPTS, else ``default``."""
    return _env_positive(ATTEMPTS_ENV, default, int)


def rdzv_backoff_cap_from_env(default: float = BACKOFF_CAP_S) -> float:
    """The per-delay ceiling: JAX_GRAFT_RDZV_BACKOFF_CAP_S, else
    ``default`` — the exponential growth is CAPPED here, so a long flap
    costs a bounded, predictable wait per retry instead of runaway
    doubling."""
    return _env_positive(BACKOFF_CAP_ENV, default, float)


class RendezvousError(RuntimeError):
    """Multi-host initialization failed (peer missing / coordinator down)."""


def _backoff_delay(attempt: int, rank: int, *, base_s: float,
                   cap_s: float = BACKOFF_CAP_S) -> float:
    """Exponential backoff with deterministic per-(rank, attempt) jitter
    in [0.5x, 1.5x): reproducible (seeded — the chaos tests pin it) yet
    decorrelated across ranks, so a gang re-dialing a flapped
    coordinator does not arrive as one thundering herd."""
    delay = min(base_s * (2.0 ** attempt), cap_s)
    jitter = np.random.default_rng(7919 * rank + attempt).random()
    return delay * (0.5 + jitter)


def init_distributed(
    master_ip: str | None = None,
    num_nodes: int = 1,
    rank: int = 0,
    *,
    port: int = DEFAULT_PORT,
    timeout_s: int | None = DEFAULT_TIMEOUT_S,
    connect_attempts: int | None = None,
    backoff_base_s: float = BACKOFF_BASE_S,
    backoff_cap_s: float | None = None,
    _initialize=None,
) -> None:
    """Explicit-rendezvous mode (reference main_all_reduce.py:96 contract).

    No-op for ``num_nodes == 1`` (single-controller JAX needs no init), so the
    same entry point serves the single-process baseline (reference main.py).

    Transient connection failures retry up to ``connect_attempts`` times
    (default: ``JAX_GRAFT_RDZV_ATTEMPTS`` env, else 5) with exponential
    backoff + jitter, each delay capped at ``backoff_cap_s`` (default:
    ``JAX_GRAFT_RDZV_BACKOFF_CAP_S`` env, else 30 s — bounded growth on
    long flaps); ``_initialize`` is a test seam (defaults to
    ``jax.distributed.initialize``)."""
    if num_nodes <= 1:
        return
    if master_ip is None:
        raise ValueError("--master-ip is required when --num-nodes > 1")
    if connect_attempts is None:
        connect_attempts = rdzv_attempts_from_env()
    if backoff_cap_s is None:
        backoff_cap_s = rdzv_backoff_cap_from_env()
    coordinator = f"{master_ip}:{port}"
    initialize = _initialize if _initialize is not None else (
        jax.distributed.initialize)
    # ``timeout_s`` stays the TOTAL failure-detection budget (the old
    # single-attempt contract): retries split whatever remains of it, so
    # a genuinely-down coordinator is diagnosed in ~timeout_s + backoff,
    # not attempts x timeout_s.  Deterministic errors (double init, bad
    # world size) fail each dial fast and cost only the backoff sleeps.
    total_s = timeout_s if timeout_s else 86_400
    deadline = time.monotonic() + total_s
    last: Exception | None = None
    attempts = max(connect_attempts, 1)
    for attempt in range(attempts):
        remaining = deadline - time.monotonic()
        if remaining <= 0 and attempt > 0:
            break
        try:
            faults.maybe_refuse_rendezvous()  # chaos: injected flap
            initialize(
                coordinator_address=coordinator,
                num_processes=num_nodes,
                process_id=rank,
                initialization_timeout=max(int(remaining), 1),
            )
            # attempts-used surfaced in the ONE init log line: a gang
            # that needed retries should say so without log spelunking
            print(f"[rendezvous] rank {rank}/{num_nodes}: connected to "
                  f"{coordinator} after {attempt + 1}/{attempts} "
                  f"attempt(s)", flush=True)
            return
        except Exception as e:
            last = e
            if attempt + 1 >= attempts:
                break
            delay = _backoff_delay(attempt, rank, base_s=backoff_base_s,
                                   cap_s=backoff_cap_s)
            print(f"[rendezvous] rank {rank}: attempt {attempt + 1}/"
                  f"{attempts} to {coordinator} failed ({e}); "
                  f"retrying in {delay:.2f}s", flush=True)
            time.sleep(delay)
    raise RendezvousError(
        f"rendezvous with coordinator {coordinator} failed for rank "
        f"{rank}/{num_nodes} after {attempts} attempts "
        f"within the {total_s}s budget: {last}") from last


def init_from_env(*, timeout_s: int | None = DEFAULT_TIMEOUT_S) -> None:
    """Env-var rendezvous mode (the torchrun convention, main_ddp.py:93-104).

    Reads MASTER_ADDR / MASTER_PORT / WORLD_SIZE / RANK.  Missing vars mean
    single-process (matching a bare ``python main_ddp.py`` failing loudly in
    the reference — here we degrade to the single-host path instead).
    """
    world_size = int(os.environ.get("WORLD_SIZE", "1"))
    init_distributed(
        os.environ.get("MASTER_ADDR"),
        world_size,
        int(os.environ.get("RANK", "0")),
        port=int(os.environ.get("MASTER_PORT", str(DEFAULT_PORT))),
        timeout_s=timeout_s,
    )


def shutdown() -> None:
    """Tear down the distributed service (torch's destroy_process_group)."""
    if jax.process_count() > 1:
        jax.distributed.shutdown()


def process_info() -> tuple[int, int]:
    """(process_id, process_count) — the post-init (rank, world_size)."""
    return jax.process_index(), jax.process_count()
