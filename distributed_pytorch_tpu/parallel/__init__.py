from . import init, mesh, strategies
from .init import init_distributed, init_from_env, shutdown
from .mesh import DATA_AXIS, data_sharding, make_mesh, shard_batch

__all__ = [
    "init", "mesh", "strategies",
    "init_distributed", "init_from_env", "shutdown",
    "DATA_AXIS", "data_sharding", "make_mesh", "shard_batch",
]
