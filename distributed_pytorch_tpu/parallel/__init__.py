from . import mesh, strategies
from .mesh import DATA_AXIS, data_sharding, make_mesh, shard_batch

__all__ = [
    "mesh", "strategies",
    "DATA_AXIS", "data_sharding", "make_mesh", "shard_batch",
]
