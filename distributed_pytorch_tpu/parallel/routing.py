"""Declarative multi-hop collective routing (round 20).

Rounds 9/13/16/18 each hand-built one point on the communication
lattice — ``two_level_psum``, the int8/int4+EF DCN rings, the
hierarchical local-SGD window exchange — as separate ``_two_level_*``
code paths.  This module replaces the family with ONE compiler: a
collective is a declarative :class:`HopPlan`, an ordered graph of
topology hops, each hop independently choosing

  * **algorithm** — ``psum`` / reduce-scatter+all-gather (``rs``/``ag``
    pair) / chained-ppermute ``ring`` (the compressed exchange);
  * **bits** — ``f32`` / ``int8`` / ``int4`` on ring exchanges;
  * **EF-residual placement** — ``ef=True`` threads an error-feedback
    residual segment through a compressed hop.

``execute`` compiles a plan into exactly the op sequence the hand-built
strategies emitted, so the 2-level routes below are **bitwise ≡** the
round-9/16 implementations (same jaxpr collective census, same EF
invariant ``delivered + psum(residuals) ≡ exact sum`` at every hop
boundary — tests/test_routing.py pins both, and the existing
strategy/LM suites keep pinning the refactored callers):

  * ``hierarchical``                    → ``ici:rs → dcn:psum → ici:ag``
  * ``hierarchical + dcn_compress``     → ``ici:rs → dcn:ring[int8+ef] → ici:ag``
  * LM ``_two_level_sync`` fsdp bucket  → ``dcn:psum`` (leaf mode) or
    ``dcn:ring[bits+ef]``
  * local-SGD ``window_exchange``       → ``ici:slice → dcn:… → ici:ag``

and ≥3-level meshes route for free by nesting, e.g. the WAN plan the
autotuner's ``choose_sync_plan`` picks on the ``ici_dcn_wan`` preset::

    ici:rs → dcn:rs → wan:ring[int4+ef] → dcn:ag → ici:ag

Grammar (``HopPlan.validate``): ``rs``/``ag`` hops pair LIFO like
brackets (each ``ag`` gathers the innermost open ``rs`` axis);
``exchange`` hops act on the current shard anywhere between them; a
mesh axis appears at most once per role.  Re-quantization across hop
boundaries (a ring hop feeding another ring hop) adds one quantization
noise term per compressed hop — modeled in the autotuner's quantize
cost and curve-pinned by the routing tests.

The module is deliberately free of autotune imports (autotune imports
*us* to enumerate and price routes); it leans on
``strategies.QuantizedRing`` for the wire format so the int4 nibble
packing and per-256-row scale layout stay single-sourced.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from . import strategies as _strat

PyTree = Any

_BITS = ("f32", "int8", "int4")
_KINDS = ("rs", "exchange", "ag", "a2a")

# The expert-dispatch exchange (kind 'a2a', round 21) runs only over
# the dedicated expert tier: it permutes whole (device, expert, slot)
# token buffers, which is meaningful for exactly one mesh role.  The
# executor (`execute_a2a`) accepts any mesh axis NAME at call time —
# ops/moe.py binds whatever the caller's expert axis is called — but a
# declarative ROUTE must say 'expert' so plans stay topology-tier
# statements like every other hop.
_A2A_AXIS = "expert"


@dataclass(frozen=True)
class Hop:
    """One edge of a sync route.

    kind       'rs' (reduce-scatter over ``axis``), 'exchange'
               (all-reduce of the current shard over ``axis``), 'ag'
               (all-gather back over ``axis`` — must close the matching
               'rs'), or 'a2a' (the expert dispatch/combine all-to-all,
               round 21 — a pure permutation, not a reduction).
    axis       mesh axis name the hop runs over ('expert' for a2a hops
               in declarative routes; :func:`execute_a2a` rebinds the
               concrete mesh axis at call time).
    algorithm  rs: 'scatter' (``psum_scatter``) or 'slice' (take the
               static ``axis_index`` chunk — free when the value is
               already replicated over ``axis``, the local-SGD window
               case).  exchange: 'psum' (one XLA all-reduce) or 'ring'
               (chained-ppermute quantized ring).  ag: 'gather'.
               a2a: 'alltoall' (one ``lax.all_to_all``).
    bits       wire precision of a ring exchange or a2a hop ('f32'
               psum/rs/ag hops are always full-width).
    ef         thread an error-feedback residual through this ring hop
               (consumes/refills one residual segment in plan order).
               Never legal on a2a hops: the all-to-all compresses
               *activations*, whose error leaves the program with the
               step — there is no persistent ledger to feed back into.
    """

    kind: str
    axis: str
    algorithm: str = ""
    bits: str = "f32"
    ef: bool = False

    def __post_init__(self):
        defaults = {"rs": "scatter", "exchange": "psum", "ag": "gather",
                    "a2a": "alltoall"}
        if self.kind not in _KINDS:
            raise ValueError(f"hop kind must be one of {_KINDS}, "
                             f"got {self.kind!r}")
        if not self.algorithm:
            object.__setattr__(self, "algorithm", defaults[self.kind])
        allowed = {"rs": ("scatter", "slice"),
                   "exchange": ("psum", "ring"),
                   "ag": ("gather",),
                   "a2a": ("alltoall",)}[self.kind]
        if self.algorithm not in allowed:
            raise ValueError(
                f"{self.kind} hop over {self.axis!r}: algorithm must be "
                f"one of {allowed}, got {self.algorithm!r}")
        if self.bits not in _BITS:
            raise ValueError(f"bits must be one of {_BITS}, "
                             f"got {self.bits!r}")
        if self.bits != "f32" and not (self.kind == "a2a"
                                       or (self.kind == "exchange"
                                           and self.algorithm == "ring")):
            raise ValueError(
                f"bits={self.bits!r} requires a ring exchange or a2a "
                f"hop; {self.kind}/{self.algorithm} over {self.axis!r} "
                f"is always full-width")
        if self.kind == "a2a" and self.ef:
            raise ValueError(
                f"ef=True on the a2a hop over {self.axis!r}: the "
                f"all-to-all compresses activations, not gradient "
                f"partial sums — quantization error leaves with the "
                f"step, so there is no residual ledger to thread "
                f"(ef is a ring-exchange contract)")
        if self.ef and self.bits == "f32":
            raise ValueError(
                f"ef=True requires a compressed (int8/int4) ring hop; "
                f"the f32 hop over {self.axis!r} drops no bits")

    def describe(self) -> str:
        if self.kind == "rs":
            return (f"{self.axis}:rs" if self.algorithm == "scatter"
                    else f"{self.axis}:slice")
        if self.kind == "ag":
            return f"{self.axis}:ag"
        if self.kind == "a2a":
            return f"{self.axis}:a2a@{self.bits}"
        if self.algorithm == "psum":
            return f"{self.axis}:psum"
        tag = self.bits + ("+ef" if self.ef else "")
        return f"{self.axis}:ring[{tag}]"


@dataclass(frozen=True)
class HopPlan:
    """An ordered, validated hop graph — the declarative sync route."""

    hops: tuple = field(default_factory=tuple)

    def __post_init__(self):
        object.__setattr__(self, "hops", tuple(self.hops))
        self.validate()

    def validate(self) -> None:
        if not self.hops:
            raise ValueError("a HopPlan needs at least one hop")
        stack: list[str] = []
        seen_rs: set[str] = set()
        seen_x: set[str] = set()
        seen_a2a: set[str] = set()
        for hop in self.hops:
            if not isinstance(hop, Hop):
                raise ValueError(f"plan entries must be Hop, got {hop!r}")
            if hop.kind == "rs":
                if hop.axis in seen_rs:
                    raise ValueError(
                        f"axis {hop.axis!r} reduce-scattered twice — each "
                        f"axis gets at most one rs/ag pair")
                seen_rs.add(hop.axis)
                stack.append(hop.axis)
            elif hop.kind == "ag":
                if not stack:
                    raise ValueError(
                        f"ag over {hop.axis!r} with no open rs — rs/ag "
                        f"pair LIFO like brackets")
                if stack[-1] != hop.axis:
                    raise ValueError(
                        f"ag over {hop.axis!r} must close the innermost "
                        f"open rs ({stack[-1]!r}); rs/ag pair LIFO")
                stack.pop()
            elif hop.kind == "a2a":
                if hop.axis != _A2A_AXIS:
                    raise ValueError(
                        f"a2a over {hop.axis!r}: the all-to-all is the "
                        f"expert-dispatch exchange and routes only over "
                        f"the {_A2A_AXIS!r} tier (reduce/gather hops "
                        f"cover every other axis role)")
                if stack:
                    raise ValueError(
                        f"a2a over {hop.axis!r} inside the open rs "
                        f"bracket over {stack!r} — the dispatch "
                        f"exchange permutes whole (expert, slot) token "
                        f"buffers and cannot run on a scattered shard")
                if hop.axis in seen_a2a:
                    raise ValueError(
                        f"axis {hop.axis!r} carries two a2a hops — one "
                        f"a2a hop describes BOTH directions (dispatch "
                        f"and combine ride the same wire format)")
                seen_a2a.add(hop.axis)
            else:
                if hop.axis in seen_x:
                    raise ValueError(
                        f"axis {hop.axis!r} exchanged twice — one "
                        f"exchange hop per axis")
                if hop.axis in stack:
                    raise ValueError(
                        f"exchange over {hop.axis!r} while its rs is "
                        f"still open — an axis is either scattered or "
                        f"exchanged, not both")
                seen_x.add(hop.axis)
        if stack:
            raise ValueError(
                f"unclosed rs over {stack!r} — every rs needs a "
                f"matching ag")
        # NOTE an exchange-free plan is legal: scatter-rs + ag IS the
        # all-reduce over that axis (local_sync's within-slice route).

    # -- derived properties (the strategy-protocol flags) ------------------

    @property
    def compressed(self) -> bool:
        return any(h.bits != "f32" for h in self.hops)

    @property
    def stateful(self) -> bool:
        """Plans with an EF hop carry a residual (the round-9
        quantized_ring_ef sync-state contract)."""
        return any(h.ef for h in self.hops)

    @property
    def vma_opaque(self) -> bool:
        """Ring hops assemble their result from ppermute payloads —
        replicated by construction, not by proof (the round-9 escape
        hatch); slice-rs hops consume replication the type system can't
        see either."""
        return any(h.algorithm in ("ring", "slice") for h in self.hops)

    def axes(self) -> tuple:
        out = []
        for h in self.hops:
            if h.axis not in out:
                out.append(h.axis)
        return tuple(out)

    def describe(self) -> str:
        return " → ".join(h.describe() for h in self.hops)

    def exchange_hops(self) -> tuple:
        return tuple(h for h in self.hops if h.kind == "exchange")

    def mesh_axes(self) -> tuple:
        """The plan's mesh axis names ordered SLOWEST (outermost) first —
        the tier order a ``Mesh`` for this route is built with: exchange
        axes in reverse plan order (the last exchange is the outermost
        tier a sequential route climbs to), then reduce-scatter axes in
        reverse bracket order (the first-opened rs is the innermost
        shard axis).  ``two_level_route('ici', 'dcn')`` → ``('dcn',
        'ici')`` — the trainer's factored-mesh axis order."""
        ex: list = []
        for h in reversed(self.hops):
            if h.kind == "exchange" and h.axis not in ex:
                ex.append(h.axis)
        rs: list = []
        for h in self.hops:
            if h.kind == "rs" and h.axis not in ex and h.axis not in rs:
                rs.append(h.axis)
        # rs axes collected fastest-first (open order); flip to slow->fast
        return tuple(ex + list(reversed(rs)))


# -- route constructors ----------------------------------------------------

def flat_route(axis: str, *, bits: str = "f32", ef: bool = False) -> HopPlan:
    """Single-hop all-reduce over ``axis`` (the ddp / quantized_ring
    point of the lattice)."""
    if bits == "f32":
        return HopPlan((Hop("exchange", axis),))
    return HopPlan((Hop("exchange", axis, algorithm="ring", bits=bits,
                        ef=ef),))


def two_level_route(fast: str, slow: str | None, *,
                    compress: str | None = None,
                    rs_algorithm: str = "scatter") -> HopPlan:
    """The round-9 hierarchical route: reduce-scatter over the fast
    axis, exchange the shard over the slow one (plain psum, or a
    compressed+EF ring under ``compress``), gather back.  ``slow=None``
    degrades to the within-slice route (local_sync)."""
    hops: list[Hop] = [Hop("rs", fast, algorithm=rs_algorithm)]
    if slow is not None:
        if compress is None:
            hops.append(Hop("exchange", slow))
        else:
            hops.append(Hop("exchange", slow, algorithm="ring",
                            bits=compress, ef=True))
    hops.append(Hop("ag", fast))
    return HopPlan(tuple(hops))


def nested_route(axes: tuple, *, compress: str | None = None) -> HopPlan:
    """N-level nested route, fastest axis first: rs down every axis but
    the last, exchange the innermost shard over the slowest axis, gather
    back out.  ``nested_route(('ici','dcn','wan'), compress='int4')`` is
    the ISSUE's example ``ici:rs → dcn:rs → wan:ring[int4+ef] → dcn:ag →
    ici:ag``."""
    if len(axes) < 2:
        return flat_route(axes[0],
                          bits=compress or "f32",
                          ef=compress is not None)
    fast, slow = list(axes[:-1]), axes[-1]
    hops = [Hop("rs", a) for a in fast]
    if compress is None:
        hops.append(Hop("exchange", slow))
    else:
        hops.append(Hop("exchange", slow, algorithm="ring", bits=compress,
                        ef=True))
    hops.extend(Hop("ag", a) for a in reversed(fast))
    return HopPlan(tuple(hops))


def sequential_route(fast: str, slows: tuple,
                     bits_by_axis: dict | None = None) -> HopPlan:
    """One rs/ag pair over ``fast`` with a CHAIN of shard exchanges over
    each slow axis in order (``ici:rs → dcn:… → wan:… → ici:ag``) —
    the re-quantizing multi-hop shape: each compressed exchange
    re-quantizes the previous hop's delivered sum, so noise accumulates
    one term per compressed hop (modeled by the autotuner, curve-pinned
    by tests/test_routing.py)."""
    bits_by_axis = bits_by_axis or {}
    hops = [Hop("rs", fast)]
    for ax in slows:
        bits = bits_by_axis.get(ax, "f32")
        if bits == "f32":
            hops.append(Hop("exchange", ax))
        else:
            hops.append(Hop("exchange", ax, algorithm="ring", bits=bits,
                            ef=True))
    hops.append(Hop("ag", fast))
    return HopPlan(tuple(hops))


def parse_route(route: str) -> HopPlan:
    """Inverse of :meth:`HopPlan.describe`: parse a route string
    (``"ici:rs → dcn:ring[int4+ef] → ici:ag"``; a plain ``"->"``
    separator is accepted too — CLI flags shouldn't require typing an
    arrow glyph) back into a validated ``HopPlan``.  The grammar is
    exactly what ``describe()`` emits: per hop ``axis:op`` with op one
    of ``rs`` / ``slice`` / ``ag`` / ``psum`` /
    ``ring[int8|int4[+ef]]`` / ``a2a@f32|int8|int4`` (the expert
    dispatch exchange — ``expert:a2a@int8`` is the quantized-dispatch
    route, round 21)."""
    hops = []
    for part in route.replace("->", "→").split("→"):
        part = part.strip()
        if not part:
            raise ValueError(f"empty hop in route {route!r}")
        axis, sep, op = part.partition(":")
        if not sep or not axis or not op:
            raise ValueError(
                f"hop {part!r} in route {route!r} is not 'axis:op'")
        if op == "rs":
            hops.append(Hop("rs", axis))
        elif op == "slice":
            hops.append(Hop("rs", axis, algorithm="slice"))
        elif op == "ag":
            hops.append(Hop("ag", axis))
        elif op == "psum":
            hops.append(Hop("exchange", axis))
        elif op.startswith("ring[") and op.endswith("]"):
            tag = op[len("ring["):-1]
            bits, _, ef = tag.partition("+")
            if ef not in ("", "ef"):
                raise ValueError(f"bad ring tag {tag!r} in hop {part!r}")
            hops.append(Hop("exchange", axis, algorithm="ring",
                            bits=bits, ef=ef == "ef"))
        elif op.startswith("a2a@"):
            hops.append(Hop("a2a", axis, bits=op[len("a2a@"):]))
        else:
            raise ValueError(
                f"unknown hop op {op!r} in route {route!r} (want rs, "
                f"slice, ag, psum, ring[int8|int4[+ef]], or "
                f"a2a@f32|int8|int4)")
    return HopPlan(tuple(hops))


def enumerate_routes(axes: tuple, *,
                     compress_options: tuple = (None, "int8", "int4"),
                     ) -> list[HopPlan]:
    """Every candidate route over ``axes`` (fastest → slowest) the
    autotuner prices: the flat joint exchange, every 2-level split, and
    — at ≥3 axes — the nested and sequential 3-level shapes, each at
    every slow-hop precision.  Pure structure: pricing lives in
    autotune (``choose_sync_plan``)."""
    routes: list[HopPlan] = []
    joint = axes[0] if len(axes) == 1 else tuple(axes)
    # flat: one exchange over the joint axis tuple (a flat psum over a
    # multi-axis tuple is what strategy='ddp' emits on a factored mesh)
    if isinstance(joint, str):
        for c in compress_options:
            routes.append(flat_route(joint, bits=c or "f32",
                                     ef=c is not None))
        return routes
    routes.append(HopPlan((Hop("exchange", "+".join(axes)),)))
    # 2-level: rs/ag over a fast prefix (flattened), exchange the rest
    for split in range(1, len(axes)):
        fast = axes[:split]
        slow = axes[split:]
        fast_name = "+".join(fast)
        slow_name = "+".join(slow)
        for c in compress_options:
            routes.append(two_level_route(fast_name, slow_name,
                                          compress=c))
    if len(axes) >= 3:
        for c in compress_options:
            routes.append(nested_route(axes, compress=c))
        # sequential: compress only the slowest hop, or the two slowest
        for c in compress_options:
            if c is None:
                routes.append(sequential_route(axes[0], axes[1:]))
            else:
                routes.append(sequential_route(
                    axes[0], axes[1:], {axes[-1]: c}))
                routes.append(sequential_route(
                    axes[0], axes[1:],
                    {a: c for a in axes[1:]}))
    return routes


# -- residual sizing (the EF sync-state contract) --------------------------

def _elems_after(plan: HopPlan, upto: int, total: int,
                 sizes: dict) -> int:
    """Flat-vector length entering hop ``upto`` of ``plan`` for a
    ``total``-element bucket — each enclosing rs divides (after padding
    to a multiple), exchanges keep the length."""
    elems = total
    for h in plan.hops[:upto]:
        if h.kind == "rs":
            elems = -(-elems // sizes[h.axis])
        elif h.kind == "ag":
            elems = elems * sizes[h.axis]
    return elems


def residual_len(plan: HopPlan, total: int, sizes: dict) -> int:
    """Total EF-residual length one ``total``-element bucket needs under
    ``plan``: each EF ring hop over axis n contributes ``n * ring._chunk``
    of the shard length entering that hop — exactly the round-9
    ``Hierarchical._segments`` / lm ``_bucket_residual_len`` arithmetic
    (``_chunk`` is bits-independent, so the layout is stable across
    int8/int4)."""
    ring = _strat.QuantizedRing()
    out = 0
    for i, h in enumerate(plan.hops):
        if h.kind == "exchange" and h.ef:
            n = sizes[h.axis]
            out += n * ring._chunk(_elems_after(plan, i, total, sizes), n)
    return out


# -- the executor ----------------------------------------------------------

def execute(plan: HopPlan, tree: PyTree, *,
            scale: float | None = None,
            residuals: list | None = None,
            overrides: dict | None = None,
            concat: bool = True):
    """Compile ``plan`` into the executed sync of ``tree`` (a bucket).

    Reproduces the hand-built op sequences exactly — concatenate to one
    f32 vector, pad/scatter per rs hop, exchange, gather, slice back to
    ``total``, apply ``scale``, split to leaf shapes/dtypes — so routed
    2-level plans are bitwise ≡ ``two_level_psum`` (the strategy suites
    pin this transitively; tests/test_routing.py pins it directly).

    ``residuals``: list of EF residual segments, consumed in plan order
    by ``ef=True`` ring hops (lengths per :func:`residual_len`).
    ``overrides``: ``{axis: shard -> summed_shard}`` replaces that
    axis's exchange hop body — the hook the legacy ``dcn_reduce``
    callers (Hierarchical's n_dcn==1 degrade, LM's capture closures)
    plug into.  ``concat=False`` (single plain-psum exchange plans
    only) syncs the leaves as one multi-operand psum without
    flattening — the LM fsdp bucket's per-leaf-vma path.

    Returns ``(synced_tree, new_residuals)`` where ``new_residuals`` is
    the list of refilled EF segments (empty for stateless plans).
    """
    overrides = overrides or {}
    leaves, treedef = jax.tree.flatten(tree)
    if not concat:
        if (len(plan.hops) != 1
                or plan.hops[0].kind != "exchange"
                or plan.hops[0].algorithm != "psum"):
            raise ValueError(
                "concat=False supports only a single plain-psum "
                f"exchange plan, got {plan.describe()!r}")
        synced = lax.psum(leaves, plan.hops[0].axis)
        return jax.tree.unflatten(treedef, synced), []

    flat = jnp.concatenate(
        [g.ravel().astype(jnp.float32) for g in leaves])
    total = flat.size
    cur = flat
    stack: list[tuple[str, int, int]] = []  # (axis, padded_size, n)
    res_iter = iter(residuals or [])
    new_res: list = []
    for hop in plan.hops:
        if hop.kind == "rs":
            n = lax.axis_size(hop.axis)
            padded = jnp.pad(cur, (0, (-cur.size) % n))
            if hop.algorithm == "scatter":
                cur = lax.psum_scatter(padded, hop.axis,
                                       scatter_dimension=0, tiled=True)
            else:  # 'slice': value already replicated over hop.axis
                me = lax.axis_index(hop.axis)
                chunk = padded.size // n
                cur = lax.dynamic_slice(padded, (me * chunk,), (chunk,))
            stack.append((hop.axis, padded.size, n))
        elif hop.kind == "exchange":
            if hop.axis in overrides:
                cur = overrides[hop.axis](cur)
            elif hop.algorithm == "psum":
                cur = lax.psum(cur, hop.axis)
            else:  # quantized ring at hop.bits (+EF when hop.ef)
                n = lax.axis_size(hop.axis)
                ring = _strat.QuantizedRing(
                    bits=4 if hop.bits == "int4" else 8)
                res = next(res_iter) if hop.ef else None
                cur, err_rows = ring._ring_sum(cur, hop.axis, n,
                                               residual=res)
                if hop.ef:
                    new_res.append(err_rows.ravel())
        else:  # 'ag'
            axis, padded_size, n = stack.pop()
            assert axis == hop.axis, "validated plan cannot mismatch"
            if _strat._all_gather_inv is not None:
                cur = _strat._all_gather_inv(cur, hop.axis, axis=0,
                                             tiled=True)
            else:
                me = lax.axis_index(hop.axis)
                chunk = padded_size // n
                buf = jnp.zeros((padded_size,), cur.dtype)
                buf = lax.dynamic_update_slice(buf, cur, (me * chunk,))
                cur = lax.psum(buf, hop.axis)
    summed = cur[:total]
    if scale is not None:
        summed = summed * scale
    out, offset = [], 0
    for g in leaves:
        out.append(summed[offset:offset + g.size]
                   .reshape(g.shape).astype(g.dtype))
        offset += g.size
    return jax.tree.unflatten(treedef, out), new_res


# -- the expert all-to-all executor (round 21) -----------------------------

def _a2a_quant_exchange(x: jax.Array, axis: str, bits: str) -> jax.Array:
    """One quantized ``lax.all_to_all`` over ``axis`` of a device-major
    ``(n, ...)`` buffer: symmetric rowwise quantization over the last
    (feature) dim — int8 lanes, or the ``QuantizedRing`` nibble packing
    at int4 — with each row's f32 scale bitcast to 4 int8 lanes and
    CONCATENATED onto its payload row, so the scales ride the *same*
    exchange.  One collective either way, same census as f32; the wire
    carries ``d + 4`` (int8) or ``d/2 + 4`` (int4) bytes per d-element
    f32 row instead of ``4d``."""
    levels = 127.0 if bits == "int8" else 7.0
    d = x.shape[-1]
    if bits == "int4" and d % 2:
        raise ValueError(
            f"int4 a2a nibble-packs feature pairs; the trailing (model) "
            f"dim must be even, got {d}")
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(
        jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / levels, 1e-30)
    q = jnp.clip(jnp.round(xf / scale), -levels, levels).astype(jnp.int8)
    if bits == "int4":
        ring = _strat.QuantizedRing(bits=4)
        q = ring._pack(q).reshape(q.shape[:-1] + (d // 2,))
    srows = lax.bitcast_convert_type(scale[..., 0], jnp.int8)  # (..., 4)
    wire = lax.all_to_all(jnp.concatenate([q, srows], axis=-1), axis,
                          split_axis=0, concat_axis=0, tiled=False)
    q_out, s_out = wire[..., :-4], wire[..., -4:]
    scale_out = lax.bitcast_convert_type(s_out, jnp.float32)[..., None]
    if bits == "int4":
        ring = _strat.QuantizedRing(bits=4)
        q_out = ring._unpack(q_out, q_out.shape[:-1] + (d,))
    return (q_out.astype(jnp.float32) * scale_out).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _a2a_wire_q(x: jax.Array, axis: str, bits: str) -> jax.Array:
    return _a2a_quant_exchange(x, axis, bits)


def _a2a_wire_q_fwd(x, axis, bits):
    return _a2a_quant_exchange(x, axis, bits), None


def _a2a_wire_q_bwd(axis, bits, _res, g):
    # all_to_all(split=0, concat=0) is its own transpose — a symmetric
    # block permutation — so the cotangent rides the SAME quantized
    # wire: both directions of the dispatch move low-bit bytes, and
    # quant→dequant is straight-through (activation compression; the
    # round-16 flip-rate gate, not an EF ledger, bounds the damage).
    return (_a2a_quant_exchange(g, axis, bits),)


_a2a_wire_q.defvjp(_a2a_wire_q_fwd, _a2a_wire_q_bwd)


def execute_a2a(hop: Hop, x: jax.Array, *, direction: str,
                axis: str | None = None) -> jax.Array:
    """Execute one direction of the expert all-to-all hop on an MoE
    exchange buffer — the ONE executor both ``ops/moe.py`` directions
    route through (round 21).

    ``direction='dispatch'`` takes the router's ``(E, C, D)`` capacity
    buffer and returns the expert-major ``(E/n, n*C, D)`` buffer each
    device's local experts consume; ``direction='combine'`` is the exact
    inverse trip for the expert outputs.  At ``bits='f32'`` the emitted
    op sequence is literally the hand-built one (reshape → all_to_all →
    moveaxis → reshape), so the routed path is bitwise ≡ and census-≡
    the pre-round-21 ``ops/moe.py``; at int8/int4 the wire payload is
    rowwise-quantized with scales on the same exchange (see
    :func:`_a2a_quant_exchange`) and the backward pass compresses the
    cotangent's wire identically via a ``custom_vjp``.

    ``axis`` rebinds the concrete mesh axis at call time (plans say
    'expert'; the caller's mesh may say 'model' or anything else).
    """
    if hop.kind != "a2a":
        raise ValueError(
            f"execute_a2a wants an a2a hop, got {hop.describe()!r}")
    ax = axis or hop.axis
    n = lax.axis_size(ax)

    def wire(v):
        if hop.bits == "f32":
            return lax.all_to_all(v, ax, split_axis=0, concat_axis=0,
                                  tiled=False)
        return _a2a_wire_q(v, ax, hop.bits)

    if direction == "dispatch":
        e, cap, d = x.shape
        x = wire(x.reshape(n, e // n, cap, d))
        return jnp.moveaxis(x, 0, 1).reshape(e // n, n * cap, d)
    if direction == "combine":
        e_local, ncap, d = x.shape
        x = wire(jnp.moveaxis(x.reshape(e_local, n, ncap // n, d), 1, 0))
        return x.reshape(n * e_local, ncap // n, d)
    raise ValueError(
        f"direction must be 'dispatch' or 'combine', got {direction!r}")


# -- the routed strategy (plug-in protocol, parallel/strategies.py) --------

class RoutedSync:
    """A gradient-sync strategy that executes an arbitrary
    :class:`HopPlan` — the first-class surface for routed plans the
    autotuner's ``choose_sync_plan`` emits (2-level routes keep running
    through ``hierarchical``, whose internals now delegate here too).

    ``axis_map`` renames plan axes to mesh axes at call time (the plan
    speaks topology tiers — 'ici'/'dcn'/'wan' — the mesh speaks whatever
    the trainer named its axes).  Stateless plans drop into the plain
    strategy protocol; EF plans follow the round-9 stateful contract
    (``state_segments``/``init_state``/``(grads, state) -> (synced,
    state)``)."""

    name = "routed"
    needs_mesh = True
    supports_overlap = True

    def __init__(self, plan: HopPlan, *, scale_to_mean: bool = True,
                 bucket_mb: float = _strat.BUCKET_CAP_MB,
                 n_by_axis: dict | None = None):
        self.plan = plan
        self.scale_to_mean = scale_to_mean
        self.bucket_bytes = int(bucket_mb * 1024 * 1024)
        self.stateful = plan.stateful
        self.vma_opaque = plan.vma_opaque
        # mesh axis order the trainer's make_mesh recipe needs (slow
        # tier first — Hierarchical.axes' contract); n_by_axis is the
        # static per-axis extent map trace-free sizing (init_state /
        # state_segments with an int replica count) resolves through —
        # the Trainer binds it from the mesh it builds
        self.axes = plan.mesh_axes()
        self.n_by_axis = dict(n_by_axis) if n_by_axis else None

    def _sizes(self) -> dict:
        return {h.axis: lax.axis_size(h.axis) for h in self.plan.hops}

    def _static_sizes(self, n_by_axis) -> dict:
        if not isinstance(n_by_axis, dict):
            # the round-9 stateful-strategy contract passes the total
            # replica count; the per-axis split comes from the bound map
            if self.n_by_axis is None:
                raise ValueError(
                    "RoutedSync needs its per-axis sizes to size EF "
                    "state from a replica count: pass n_by_axis={axis: "
                    "n} at construction (or call with a dict)")
            n_by_axis = self.n_by_axis
        return {h.axis: int(n_by_axis[h.axis]) for h in self.plan.hops}

    def _scale(self, sizes: dict) -> float | None:
        if not self.scale_to_mean:
            return None
        n = 1
        for ax in self.plan.axes():
            n *= sizes[ax]
        return 1.0 / n

    # -- EF sync-state contract (round 9) ------------------------------

    def state_segments(self, leaves: list, n_by_axis) -> list[int]:
        sizes = self._static_sizes(n_by_axis)
        return [residual_len(self.plan,
                             sum(leaves[i].size for i in b), sizes)
                for b in _strat.make_bucket_plan(leaves,
                                                 self.bucket_bytes)]

    def init_state(self, params: PyTree, n_by_axis) -> jax.Array:
        if not self.stateful:
            return jnp.zeros((0,), jnp.float32)
        leaves = jax.tree.leaves(params)
        return jnp.zeros(
            (sum(self.state_segments(leaves, n_by_axis)),), jnp.float32)

    def sync_bucket(self, leaves: list, residual: jax.Array | None = None):
        sizes = self._sizes()
        res_list = None
        if self.stateful:
            # one residual segment per EF hop, split in plan order
            segs, off = [], 0
            total = sum(int(g.size) for g in leaves)
            ring = _strat.QuantizedRing()
            for i, h in enumerate(self.plan.hops):
                if h.kind == "exchange" and h.ef:
                    n = sizes[h.axis]
                    ln = n * ring._chunk(
                        _elems_after(self.plan, i, total, sizes), n)
                    segs.append(residual[off:off + ln])
                    off += ln
            res_list = segs
        synced, new_res = execute(self.plan, leaves,
                                  scale=self._scale(sizes),
                                  residuals=res_list)
        if not self.stateful:
            return synced
        return synced, (jnp.concatenate(new_res) if new_res
                        else jnp.zeros((0,), jnp.float32))

    def __call__(self, grads: PyTree, axis=None,
                 sync_state: jax.Array | None = None):
        # ``axis`` is the strategy-protocol slot (the trainer passes its
        # data axes); the plan is the authority on which axes each hop
        # runs over, so it is accepted and ignored
        del axis
        leaves, treedef = jax.tree.flatten(grads)
        out: list = [None] * len(leaves)
        if not self.stateful:
            for b in _strat.make_bucket_plan(leaves, self.bucket_bytes):
                synced = self.sync_bucket([leaves[i] for i in b])
                for i, s in zip(b, synced):
                    out[i] = s
            return jax.tree.unflatten(treedef, out)
        sizes = self._sizes()
        new_parts, offset = [], 0
        for b in _strat.make_bucket_plan(leaves, self.bucket_bytes):
            total = sum(int(leaves[i].size) for i in b)
            seg = residual_len(self.plan, total,
                               {a: sizes[a] for a in self.plan.axes()})
            synced, new_r = self.sync_bucket(
                [leaves[i] for i in b],
                sync_state[offset:offset + seg])
            offset += seg
            new_parts.append(new_r)
            for i, s in zip(b, synced):
                out[i] = s
        return (jax.tree.unflatten(treedef, out),
                jnp.concatenate(new_parts) if new_parts
                else jnp.zeros((0,), jnp.float32))
