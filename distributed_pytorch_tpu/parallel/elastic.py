"""Elastic gang, worker side: heartbeats, drain sync points, resize.

launch.py's elastic agent (round 12) turns a worker loss into a reshard
instead of a dead job; this module is the half that runs INSIDE the
workers.  Three pieces:

- **Heartbeat** — each worker publishes ``hb_rank<R>.json`` into the
  agent's ``ELASTIC_DIR`` once per step (atomic tmp+rename, so the agent
  never reads a torn file).  The agent's liveness check reads the file's
  age: a HUNG straggler (stuck collective, wedged host thread) goes
  stale and is detected even though its PID is alive — the upgrade over
  PR 1's dead-PID-only detection.

- **DrainGuard** — converts the agent's SIGTERM into "exit the step
  loop at the next SYNC POINT".  The subtlety is agreement: ranks
  observe the signal skewed by up to a step, and a rank that drains
  (its checkpoint fetch is a collective) while a peer proceeds into the
  next step's collectives deadlocks both.  ``sync()`` therefore
  all-gathers the local flag across processes every step and drains on
  the MAX — every rank leaves at the same boundary, signal skew
  notwithstanding.  After the flush the worker exits
  ``ELASTIC_DRAIN_EXIT_CODE``; the agent counts it as a graceful drain
  and re-rendezvouses the gang at the new world size.

- **reshard_from_checkpoint** — the in-process resize leg: rebuild the
  trainer's mesh/compiled step at a new parallel degree
  (``LMTrainer.rebuild``) and restore the last-good checkpoint through
  ``ShardedCheckpointer.load_resharded``, which maps the SAVED shard
  layout onto the NEW mesh per leaf without any host materializing a
  full array (the memory-efficient array-redistribution recipe of
  arXiv 2112.01075).  The gang path gets the same effect across
  processes: drained workers re-exec their init at the new WORLD_SIZE
  and restore through the same resharding loader.

What the gang may tolerate versus what must stay synchronous follows
BAGUA's system-relaxation framing (arXiv 2107.01499): membership and
data assignment may relax between sync points (this module); the
optimizer step itself stays fully synchronous — bounded-staleness
relaxations are the carried-forward half (ROADMAP).
"""

from __future__ import annotations

import json
import os
import signal
import time
from dataclasses import dataclass

import numpy as np

# The jax-free agent side owns the constants (launch.py imports nothing
# from this package's jax-importing modules); importing them here means
# the two halves can never drift.
from ..launch import (  # noqa: F401  (re-exported for workers)
    ELASTIC_DIR_ENV,
    ELASTIC_DRAIN_EXIT_CODE,
    ELASTIC_MAX_ENV,
    ELASTIC_MIN_ENV,
    ELASTIC_RESIZE_EXIT_CODE,
    HEARTBEAT_PREFIX,
)


@dataclass
class ElasticContext:
    """The elastic env contract as one object (None fields when the
    worker was not launched by an elastic agent)."""

    run_dir: str
    rank: int
    world_size: int
    generation: int
    min_nodes: int
    max_nodes: int

    @classmethod
    def from_env(cls) -> "ElasticContext | None":
        run_dir = os.environ.get(ELASTIC_DIR_ENV)
        if not run_dir:
            return None
        return cls(
            run_dir=run_dir,
            rank=int(os.environ.get("RANK", "0")),
            world_size=int(os.environ.get("WORLD_SIZE", "1")),
            generation=int(os.environ.get("RESTART_ATTEMPT", "0")),
            min_nodes=int(os.environ.get(ELASTIC_MIN_ENV, "1")),
            max_nodes=int(os.environ.get(ELASTIC_MAX_ENV, "1")),
        )


class Heartbeat:
    """Per-step liveness beacon: ``beat(step)`` atomically publishes
    {rank, step, gen, time} to ``hb_rank<R>.json``.  ``min_interval_s``
    rate-limits rewrites for fast step loops (0 = every call); the
    FIRST beat always lands (the agent keys staleness off beats of the
    current generation, so silence before the first beat reads as
    "still compiling", never as "hung")."""

    def __init__(self, run_dir: str, rank: int, generation: int,
                 *, min_interval_s: float = 0.0):
        self.run_dir = run_dir
        self.rank = rank
        self.generation = generation
        self.min_interval_s = min_interval_s
        self._last = 0.0
        os.makedirs(run_dir, exist_ok=True)
        self.path = os.path.join(run_dir,
                                 f"{HEARTBEAT_PREFIX}{rank}.json")

    def beat(self, step: int) -> None:
        now = time.time()
        if self._last and now - self._last < self.min_interval_s:
            return
        self._last = now
        tmp = self.path + f".tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump({"rank": self.rank, "step": int(step),
                           "gen": self.generation, "time": now}, f)
            os.replace(tmp, self.path)  # atomic: the agent never sees torn
        except OSError:
            pass  # a missed beat is a late detection, not a crash


class DrainGuard:
    """SIGTERM -> drain-at-next-sync-point flag, with cross-process
    agreement.

    ``install()`` chains the previous SIGTERM disposition (a worker that
    already exits on SIGTERM keeps doing so only if it installed AFTER
    us; install early).  ``sync()`` is the per-step sync point: it
    combines the local flag across all jax processes (max over an
    allgather), so every rank agrees on the SAME drain boundary even
    though the signal lands skewed — a rank draining mid-collective
    while peers run on would deadlock the gang."""

    def __init__(self):
        self._requested = False
        self._installed = False

    def install(self) -> "DrainGuard":
        signal.signal(signal.SIGTERM, self._handler)
        self._installed = True
        return self

    def _handler(self, signum, frame):
        self._requested = True

    @property
    def requested(self) -> bool:
        """The LOCAL flag (no agreement) — for single-process drivers."""
        return self._requested

    def sync(self) -> bool:
        """True when ANY process has seen the drain signal: all ranks
        receive the same answer at the same step boundary, so the whole
        gang leaves together.  One tiny allgather per step — the price
        of a deadlock-free drain, paid only in elastic mode."""
        import jax

        if jax.process_count() <= 1:
            return self._requested
        from jax.experimental import multihost_utils
        flags = multihost_utils.process_allgather(
            np.asarray([1.0 if self._requested else 0.0], np.float32))
        return bool(np.max(flags) > 0.0)


def drain_exit(save_fn, *, log=print, code: int = ELASTIC_DRAIN_EXIT_CODE):
    """Flush the last-good state and leave at this sync point: runs
    ``save_fn`` (the caller's checkpoint-and-flush closure; it may be a
    collective — every rank calls ``drain_exit`` at the same boundary,
    that is what ``DrainGuard.sync`` guarantees) and hard-exits with the
    drain code.  ``os._exit`` on purpose: the distributed teardown of a
    half-dismantled gang can hang, and the checkpoint is already on
    disk."""
    try:
        save_fn()
    except Exception as e:  # noqa: BLE001 — the agent's grace covers us
        if log:
            log(f"[elastic] drain checkpoint failed ({e}); exiting anyway")
    if log:
        log(f"[elastic] drained at sync point (exit {code})", )
    os._exit(code)


def reshard_from_checkpoint(trainer, directory: str, **rebuild_kw) -> int:
    """In-process resize: rebuild the trainer on a new topology and
    restore the latest checkpoint RESHARDED onto it.

    ``rebuild_kw`` goes to ``trainer.rebuild`` (e.g. ``dp=2`` /
    ``mesh=...``); the restore goes through the cross-topology loader
    (``ShardedCheckpointer.load_resharded`` for per-shard checkpoints —
    no host materializes more than its target shards plus one in-flight
    leaf), which ``LMTrainer.maybe_restore`` / ``Checkpointer`` already
    route.  Returns the step resumed from."""
    trainer.rebuild(**rebuild_kw)
    if hasattr(trainer, "maybe_restore"):
        return trainer.maybe_restore(directory)
    from ..utils.checkpoint import Checkpointer

    return Checkpointer(directory).maybe_restore(trainer)
