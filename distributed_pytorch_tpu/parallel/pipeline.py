"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

The reference's parallelism inventory is data-parallel only (SURVEY.md
section 5); this module adds the pipeline axis for models whose layer stack
does not fit one chip.  Design (the JAX SPMD formulation, not a scheduler
thread per stage):

- the transformer's L identical blocks are split into ``n = axis_size(pipe)``
  contiguous stages; each stage's layer parameters are stacked with a leading
  stage dim and sharded ``P('pipe')``, so each device holds L/n layers;
- a ``lax.scan`` runs the GPipe schedule: at tick t, stage s processes
  microbatch ``t - s`` (when valid); activations hop stage s -> s+1 with one
  ``lax.ppermute`` per tick (ICI neighbor exchange);
- every device executes the same program every tick (SPMD lockstep); ticks
  outside a stage's valid window compute on zeros and are masked out of the
  loss — the classic (n-1)/(M+n-1) pipeline bubble;
- the backward schedule is NOT hand-written: ``jax.grad`` through the scan
  and ppermute yields the reverse pipeline (ppermute's transpose reverses
  the ring), with ``jax.checkpoint`` on the stage body for activation remat;
- pp composes with tensor parallelism: stage layer weights additionally
  carry the Megatron head/FFN sharding over ``tp_axis`` and the block's two
  psums run inside every stage (mesh (data, pipe, model)).

Embedding/unembedding weights are replicated to every stage (cheap at these
scales) so first/last-stage special-casing is a mask, not a branch.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from ..models import transformer as tfm

PyTree = Any


def split_layer_params(params: PyTree, cfg: tfm.TransformerConfig,
                       n_stages: int):
    """Re-pack per-layer params into stage-stacked leaves.

    Returns ``(stage_params, shared)`` where each ``stage_params`` leaf has
    shape (n_stages, layers_per_stage, *leaf) — shard its leading dim over
    'pipe' — and ``shared`` holds embed/final_norm (replicated everywhere).
    """
    if cfg.n_experts:
        raise ValueError(
            "pipeline parallelism requires a dense layer stack (layer "
            "params must stack homogeneously); MoE models (n_experts > 0) "
            "are not supported with pp > 1")
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"{cfg.n_layers} layers do not split into {n_stages} stages")
    per = cfg.n_layers // n_stages
    layers = [params[f"layer{i}"] for i in range(cfg.n_layers)]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *layers)
    stage_params = jax.tree.map(
        lambda x: x.reshape((n_stages, per) + x.shape[1:]), stacked)
    shared = {"embed": params["embed"], "final_norm": params["final_norm"]}
    return stage_params, shared


def merge_layer_params(stage_params: PyTree, shared: PyTree,
                       cfg: tfm.TransformerConfig) -> PyTree:
    """Inverse of split_layer_params (for checkpoint export/tests)."""
    flat = jax.tree.map(
        lambda x: x.reshape((-1,) + x.shape[2:]), stage_params)
    params = {"embed": shared["embed"], "final_norm": shared["final_norm"]}
    for i in range(cfg.n_layers):
        params[f"layer{i}"] = jax.tree.map(lambda x: x[i], flat)
    return params


def stage_specs(cfg: tfm.TransformerConfig, n_stages: int,
                tp_axis: str | None = None) -> PyTree:
    """The spec tree matching split_layer_params' stage output: leading
    stage dim over 'pipe'; with ``tp_axis``, each leaf's trailing dims also
    carry the Megatron head/FFN sharding (models/transformer.shard_specs),
    shifted right by the two stacking dims (stage, layer-in-stage)."""
    from jax.sharding import PartitionSpec as P

    stages_shape = jax.eval_shape(
        lambda k: split_layer_params(tfm.init(k, cfg), cfg, n_stages)[0],
        jax.random.key(0))
    if tp_axis is None:
        return jax.tree.map(lambda _: P("pipe"), stages_shape)
    layer_tp = tfm.shard_specs(cfg, tp_axis=tp_axis)["layer0"]
    return jax.tree.map(lambda spec, _: P("pipe", None, *spec),
                        layer_tp, stages_shape)


def _stage(stage_layers: PyTree, x: jax.Array,
           cfg: tfm.TransformerConfig, attn_impl: str,
           tp_axis: str | None = None) -> jax.Array:
    """Run this device's layers_per_stage blocks (a homogeneous layer scan
    over the shared models/transformer.py:block body)."""
    pos = jnp.arange(x.shape[1])

    def body(x, lp):
        x, _ = tfm.block(lp, x, cfg=cfg, is_moe=False, pos=pos,
                         attn_impl=attn_impl, tp_axis=tp_axis)
        return x, None

    x, _ = lax.scan(body, x, stage_layers)
    return x


def pipeline_loss(
    stage_params: PyTree,
    shared: PyTree,
    tokens: jax.Array,     # (M, mb, S) microbatched token ids
    targets: jax.Array,    # (M, mb, S) next-token targets (IGNORE = pad)
    *,
    cfg: tfm.TransformerConfig,
    axis: str = "pipe",
    dtype: jnp.dtype | None = None,
    attn_impl: str = "flash",
    tp_axis: str | None = None,
) -> jax.Array:
    """Mean masked CE over all microbatches, computed through the pipeline.

    Runs inside shard_map with ``stage_params`` leaves carrying this stage's
    (1, layers_per_stage, ...) slice.  Returns the loss summed over this
    shard's tokens plus the valid-token count (both to be psum'd by the
    caller across data/pipe axes).
    """
    from ..ops.nn import masked_ce

    me = lax.axis_index(axis)
    n = lax.axis_size(axis)
    local_layers = jax.tree.map(lambda x: x[0], stage_params)  # (per, ...)
    m_micro, mb, s = tokens.shape

    # Embed all microbatches (replicated embed; masked-out stages feed zeros).
    x_all = shared["embed"][tokens]  # (M, mb, S, D)
    if dtype is not None:
        x_all = x_all.astype(dtype)

    stage_fn = jax.checkpoint(partial(_stage, cfg=cfg, attn_impl=attn_impl,
                                      tp_axis=tp_axis))
    perm = [(i, i + 1) for i in range(n - 1)]  # stage s -> s+1

    # Scan carries must be varying over every axis their updates vary over:
    # the pipe axis (stage params) plus whatever the inputs carry (e.g. a
    # 'data' axis when composed with DP).
    want_vma = jax.typeof(x_all).vma | {axis}

    def _varying(x):
        missing = tuple(a for a in want_vma if a not in jax.typeof(x).vma)
        return lax.pcast(x, missing, to="varying") if missing else x

    zero_x = _varying(jnp.zeros((mb, s, x_all.shape[-1]), x_all.dtype))

    def tick(carry, t):
        prev_out, ce_acc, n_acc = carry
        # Activation arriving from the previous stage (stage 0 receives its
        # fresh microbatch embedding instead).
        recv = lax.ppermute(prev_out, axis, perm)
        m_in = jnp.clip(t, 0, m_micro - 1)
        fresh = lax.dynamic_index_in_dim(x_all, m_in, 0, keepdims=False)
        x_in = jnp.where(me == 0, fresh, recv)
        out = stage_fn(local_layers, x_in)
        # Last stage finishes microbatch t-(n-1): unembed + masked CE.
        m_out = jnp.clip(t - (n - 1), 0, m_micro - 1)
        valid = (me == n - 1) & (t - (n - 1) >= 0) & (t - (n - 1) < m_micro)
        h = tfm.rms_norm(out, shared["final_norm"], cfg.norm_eps)
        logits = h.astype(jnp.float32) @ shared["embed"].T.astype(jnp.float32)
        tgt = lax.dynamic_index_in_dim(targets, m_out, 0, keepdims=False)
        ce, cnt = masked_ce(logits, tgt)
        ce_acc = ce_acc + jnp.where(valid, ce, 0.0)
        n_acc = n_acc + jnp.where(valid, cnt, 0)
        return (out, ce_acc, n_acc), None

    ce0 = _varying(jnp.zeros(()))
    n0 = _varying(jnp.zeros((), jnp.int32))
    (_, ce_sum, n_sum), _ = lax.scan(
        tick, (zero_x, ce0, n0), jnp.arange(m_micro + n - 1))
    return ce_sum, n_sum
