"""Pipeline parallelism: microbatch pipelining over a mesh axis.

The reference's parallelism inventory is data-parallel only (SURVEY.md
section 5); this module adds the pipeline axis for models whose layer stack
does not fit one chip.  Design (the JAX SPMD formulation, not a scheduler
thread per stage):

- the transformer's L identical blocks are split into ``n * v`` logical
  chunks (n = axis_size(pipe) devices, v = ``interleave`` virtual stages
  per device); chunk ``j`` lives on device ``j % n``, so each device holds
  v round-robin chunks of L/(n*v) layers — Megatron's interleaved stage
  placement;
- a ``lax.scan`` runs a circular **wave** schedule: microbatches are
  admitted in waves of n, one per tick; a microbatch hops device
  s -> s+1 -> ... -> n-1 -> 0 -> ... around the ring v times (one
  ``lax.ppermute`` per tick), visiting chunks in order.  Within a wave
  each device is busy every tick with exactly one (chunk, microbatch) —
  lockstep-collision-free — and wave w+1 starts the tick device 0 frees
  up, so steady state has zero idle ticks;
- the fill/drain bubble is (n-1)/(v*M + n-1) in chunk-ticks — the v-fold
  bubble reduction of interleaved scheduling, here in a forward-only scan
  (``interleave=1`` degenerates to the classic GPipe schedule);
- ticks outside a device's valid window compute on zeros and are masked
  out of the loss;
- the backward schedule is NOT hand-written: ``jax.grad`` through the scan
  and ppermute yields the reverse pipeline (ppermute's transpose reverses
  the ring), with ``jax.checkpoint`` on the chunk body for activation
  remat;
- pp composes with tensor parallelism: chunk layer weights additionally
  carry the Megatron head/FFN sharding over ``tp_axis`` and the block's two
  psums run inside every chunk (mesh (data, pipe, seq, model)); with
  sequence parallelism (ring attention inside chunks over 'seq'); and with
  uniformly-MoE stacks (moe_every=1 — every layer MoE, so chunk params
  stack homogeneously; per-(chunk, microbatch) aux accumulates through the
  ticks).

Schedule index math (device s, tick t, N = n*v):
  rel = t - s                      # ticks since the wavefront passed s
  w   = rel // N                   # wave index
  k   = (rel mod N) // n           # which of my v chunks is active
  m   = w*n + (rel mod n)          # microbatch index
  active iff rel >= 0 and m < M.  Chunk ``k*n + s`` receives from chunk
  ``k*n + s - 1``, which processed the same microbatch on the previous
  device at tick t-1 — so one ring hop per tick moves every in-flight
  microbatch forward one chunk.  Device 0 at k == 0 injects the fresh
  microbatch embedding instead; device n-1 at k == v-1 finishes
  microbatch m.

Embedding/unembedding weights are replicated to every stage (cheap at these
scales) so first/last-stage special-casing is a mask, not a branch.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from ..models import transformer as tfm
from ..utils.compat import pcast, vma_of

PyTree = Any


def _uniform_moe(cfg: tfm.TransformerConfig) -> bool:
    """True when EVERY layer is an MoE layer (moe_every == 1): the one MoE
    shape whose layer params stack homogeneously into pipeline chunks."""
    return bool(cfg.n_experts) and all(
        cfg.is_moe_layer(i) for i in range(cfg.n_layers))


def split_layer_params(params: PyTree, cfg: tfm.TransformerConfig,
                       n_stages: int, interleave: int = 1):
    """Re-pack per-layer params into device-stacked chunk leaves.

    Returns ``(stage_params, shared)`` where each ``stage_params`` leaf has
    shape (n_stages, interleave, layers_per_chunk, *leaf) — shard its
    leading dim over 'pipe' — and ``shared`` holds embed/final_norm
    (replicated everywhere).  Logical chunk ``j`` (contiguous layers) lands
    at [j % n_stages, j // n_stages] (round-robin interleaved placement).

    MoE models pipeline iff the stack is uniform (``moe_every == 1``, every
    layer MoE): a dense/MoE-alternating stack has heterogeneous layer
    params that cannot stack into one scanned chunk body.
    """
    if cfg.n_experts and not _uniform_moe(cfg):
        raise ValueError(
            "pipeline parallelism requires a homogeneous layer stack: "
            "dense models, or uniformly-MoE models (moe_every=1).  A "
            "dense/MoE-alternating stack (moe_every > 1) cannot stack "
            "into pipeline chunks")
    n_chunks = n_stages * interleave
    if cfg.n_layers % n_chunks:
        raise ValueError(
            f"{cfg.n_layers} layers do not split into {n_stages} stages "
            f"x {interleave} virtual stages")
    per = cfg.n_layers // n_chunks
    layers = [params[f"layer{i}"] for i in range(cfg.n_layers)]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *layers)
    # (L, ...) -> (v, n, per, ...) [chunk j = k*n + s] -> (n, v, per, ...)
    stage_params = jax.tree.map(
        lambda x: jnp.moveaxis(
            x.reshape((interleave, n_stages, per) + x.shape[1:]), 0, 1),
        stacked)
    shared = {"embed": params["embed"], "final_norm": params["final_norm"]}
    return stage_params, shared


def merge_layer_params(stage_params: PyTree, shared: PyTree,
                       cfg: tfm.TransformerConfig) -> PyTree:
    """Inverse of split_layer_params (for checkpoint export/tests)."""
    flat = jax.tree.map(
        lambda x: jnp.moveaxis(x, 0, 1).reshape((-1,) + x.shape[3:]),
        stage_params)
    params = {"embed": shared["embed"], "final_norm": shared["final_norm"]}
    for i in range(cfg.n_layers):
        params[f"layer{i}"] = jax.tree.map(lambda x: x[i], flat)
    return params


def stage_specs(cfg: tfm.TransformerConfig, n_stages: int,
                tp_axis: str | None = None,
                interleave: int = 1) -> PyTree:
    """The spec tree matching split_layer_params' stage output: leading
    device dim over 'pipe'; with ``tp_axis``, each leaf's trailing dims also
    carry the Megatron head/FFN sharding (models/transformer.shard_specs),
    shifted right past the three stacking dims (device, virtual stage,
    layer-in-chunk)."""
    from jax.sharding import PartitionSpec as P

    stages_shape = jax.eval_shape(
        lambda k: split_layer_params(tfm.init(k, cfg), cfg, n_stages,
                                     interleave)[0],
        jax.random.key(0))
    if tp_axis is None:
        return jax.tree.map(lambda _: P("pipe"), stages_shape)
    layer_tp = tfm.shard_specs(cfg, tp_axis=tp_axis)["layer0"]
    return jax.tree.map(lambda spec, _: P("pipe", None, None, *spec),
                        layer_tp, stages_shape)


def _chunk(chunk_layers: PyTree, x: jax.Array,
           cfg: tfm.TransformerConfig, attn_impl: str,
           tp_axis: str | None = None,
           seq_axis: str | None = None,
           seq_layout: str = "contiguous",
           pos: jax.Array | None = None,
           is_moe: bool = False) -> tuple[jax.Array, jax.Array]:
    """Run one chunk's layers_per_chunk blocks (a homogeneous layer scan
    over the shared models/transformer.py:block body); returns (x, summed
    MoE aux).  With ``seq_axis`` the activations are sequence shards and
    each block's attention is the ring over that axis (pp x sp
    composition); ``pos`` is then the shard's absolute token positions.
    ``is_moe`` applies to every layer (uniform stacks only — see
    split_layer_params)."""
    if pos is None:
        pos = jnp.arange(x.shape[1])

    def body(carry, lp):
        x, aux_acc = carry
        x, aux = tfm.block(lp, x, cfg=cfg, is_moe=is_moe, pos=pos,
                           attn_impl=attn_impl, tp_axis=tp_axis,
                           seq_axis=seq_axis, seq_layout=seq_layout)
        return (x, aux_acc + aux), None

    # aux carry starts with x's vma so the scan carry types are stable
    aux0 = jnp.zeros((), jnp.float32)
    missing = tuple(a for a in vma_of(x) if a not in vma_of(aux0))
    if missing:
        aux0 = pcast(aux0, missing, to="varying")
    (x, aux), _ = lax.scan(body, (x, aux0), chunk_layers)
    return x, aux


def num_ticks(m_micro: int, n: int, interleave: int) -> int:
    """Scan length of the wave schedule: the tick after microbatch M-1
    (wave ceil(M/n)-1, in-wave slot (M-1)%n) clears the last chunk of
    device n-1."""
    waves = -(-m_micro // n)
    big_n = n * interleave
    return ((waves - 1) * big_n + (interleave - 1) * n
            + ((m_micro - 1) % n) + n)


def pipeline_loss(
    stage_params: PyTree,
    shared: PyTree,
    tokens: jax.Array,     # (M, mb, S) microbatched token ids
    targets: jax.Array,    # (M, mb, S) next-token targets (IGNORE = pad)
    *,
    cfg: tfm.TransformerConfig,
    axis: str = "pipe",
    dtype: jnp.dtype | None = None,
    attn_impl: str = "flash",
    tp_axis: str | None = None,
    seq_axis: str | None = None,
    seq_layout: str = "contiguous",
    pos: jax.Array | None = None,
    interleave: int = 1,
    remat_block_ticks: int | None = 0,
) -> jax.Array:
    """Mean masked CE over all microbatches, computed through the pipeline.

    Runs inside shard_map with ``stage_params`` leaves carrying this
    device's (1, interleave, layers_per_chunk, ...) slice.  Returns
    ``(ce_sum, n_valid, aux_sum)``: the loss summed over this shard's
    tokens, the valid-token count, and this pipe rank's summed MoE aux
    over its chunks and all microbatches (0.0 for dense stacks) — the
    caller psums ce/n across data/pipe/seq, psums aux over 'pipe' (layers
    are split across ranks) and means it over microbatches and data/seq.

    With ``seq_axis`` (pp x sp), ``tokens``/``targets`` are sequence
    shards: every microbatch's activations stay seq-sharded through the
    pipeline hops, and each chunk's attention is the ring over
    ``seq_axis``.  The ring's collectives run inside the tick, so pipeline
    (pipe-axis ppermute) and ring (seq-axis ppermute) traffic interleave
    tick by tick.  ``pos`` is this seq shard's absolute positions.
    """
    from ..ops.nn import masked_ce

    me = lax.axis_index(axis)
    n = lax.axis_size(axis)
    v = interleave
    big_n = n * v
    local = jax.tree.map(lambda x: x[0], stage_params)  # (v, per, ...)
    m_micro, mb, s = tokens.shape

    # Embed all microbatches (replicated embed; masked-out ticks feed zeros).
    x_all = shared["embed"][tokens]  # (M, mb, S, D)
    if dtype is not None:
        x_all = x_all.astype(dtype)

    chunk_fn = jax.checkpoint(partial(_chunk, cfg=cfg, attn_impl=attn_impl,
                                      tp_axis=tp_axis, seq_axis=seq_axis,
                                      seq_layout=seq_layout, pos=pos,
                                      is_moe=_uniform_moe(cfg)))
    perm = [(i, (i + 1) % n) for i in range(n)]  # ring: chunk k*n+s -> +1

    # Scan carries must be varying over every axis their updates vary over:
    # the pipe axis (stage params) plus whatever the inputs carry (e.g. a
    # 'data' axis when composed with DP).
    want_vma = vma_of(x_all) | {axis}

    def _varying(x):
        missing = tuple(a for a in want_vma if a not in vma_of(x))
        return pcast(x, missing, to="varying") if missing else x

    zero_x = _varying(jnp.zeros((mb, s, x_all.shape[-1]), x_all.dtype))

    def tick(carry, t):
        prev_out, ce_acc, n_acc, aux_acc = carry
        # Activation arriving from the previous device's chunk (one ring
        # hop per tick); device 0's first chunk takes the fresh microbatch
        # embedding instead.
        recv = lax.ppermute(prev_out, axis, perm)
        rel = t - me
        w = rel // big_n                   # wave (floor: negative pre-fill)
        k = (rel % big_n) // n             # active virtual stage (>= 0)
        m = w * n + (rel % n)              # microbatch index
        valid = (rel >= 0) & (m >= 0) & (m < m_micro)
        chunk_layers = jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(x, jnp.clip(k, 0, v - 1), 0,
                                               keepdims=False), local)
        m_in = jnp.clip(m, 0, m_micro - 1)
        fresh = lax.dynamic_index_in_dim(x_all, m_in, 0, keepdims=False)
        x_in = jnp.where((me == 0) & (k == 0), fresh, recv)
        out, aux = chunk_fn(chunk_layers, x_in)
        # every (chunk, microbatch) pair contributes its layers' aux once
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        # Last logical chunk (device n-1, slot v-1) finishes microbatch m:
        # unembed + masked CE.
        finish = (me == n - 1) & (k == v - 1) & valid
        h = tfm.rms_norm(out, shared["final_norm"], cfg.norm_eps)
        logits = h.astype(jnp.float32) @ shared["embed"].T.astype(jnp.float32)
        tgt = lax.dynamic_index_in_dim(targets, m_in, 0, keepdims=False)
        ce, cnt = masked_ce(logits, tgt)
        ce_acc = ce_acc + jnp.where(finish, ce, 0.0)
        n_acc = n_acc + jnp.where(finish, cnt, 0)
        return (out, ce_acc, n_acc, aux_acc), None

    ce0 = _varying(jnp.zeros(()))
    n0 = _varying(jnp.zeros((), jnp.int32))
    aux0 = _varying(jnp.zeros(()))

    # -- 1F1B-grade activation memory: block-remat over the tick scan ------
    # A flat scan of T ticks saves one (mb, S, D) carry per tick for the
    # backward: O(T) = O(M*v) live activations — the O(num_ticks) wall.
    # Nesting the scan (outer over blocks of ``remat_block_ticks`` ticks,
    # inner scan checkpointed) makes the backward keep only the T/block
    # block-boundary carries and rematerialize one block at a time, so peak
    # live activations are O(M*v/n + n) microbatch-sized buffers — for the
    # standard M = O(n) microbatch regime, O(pp * mb), 1F1B's bound.  The
    # price is one extra tick-forward per backward (the usual remat trade;
    # the per-chunk jax.checkpoint above keeps the within-block recompute
    # itself lean).  remat_block_ticks: 0 = auto (one wave, n ticks);
    # None = flat scan (the O(T) layout, kept for A/B memory tests).
    ticks = num_ticks(m_micro, n, v)
    if remat_block_ticks is None:
        (_, ce_sum, n_sum, aux_sum), _ = lax.scan(
            tick, (zero_x, ce0, n0, aux0), jnp.arange(ticks))
        return ce_sum, n_sum, aux_sum
    block = remat_block_ticks or n
    # Padded tail ticks still run a full (masked-out) chunk forward — they
    # are no-ops for the loss, not for compute.  The auto block (n) wastes
    # at most n-1 ticks; an explicit oversized block wastes up to block-1.
    t_pad = -(-ticks // block) * block

    @partial(jax.checkpoint, prevent_cse=False)
    def tick_block(carry, ts):
        carry, _ = lax.scan(tick, carry, ts)
        return carry, None

    (_, ce_sum, n_sum, aux_sum), _ = lax.scan(
        tick_block, (zero_x, ce0, n0, aux0),
        jnp.arange(t_pad).reshape(t_pad // block, block))
    return ce_sum, n_sum, aux_sum
