"""Pipeline parallelism: microbatch pipelining over a mesh axis.

The reference's parallelism inventory is data-parallel only (SURVEY.md
section 5); this module adds the pipeline axis for models whose layer stack
does not fit one chip.  Design (the JAX SPMD formulation, not a scheduler
thread per stage):

- the transformer's L identical blocks are split into ``n * v`` logical
  chunks (n = axis_size(pipe) devices, v = ``interleave`` virtual stages
  per device); chunk ``j`` lives on device ``j % n``, so each device holds
  v round-robin chunks of L/(n*v) layers — Megatron's interleaved stage
  placement;
- a ``lax.scan`` runs a circular **wave** schedule: microbatches are
  admitted in waves of n, one per tick; a microbatch hops device
  s -> s+1 -> ... -> n-1 -> 0 -> ... around the ring v times (one
  ``lax.ppermute`` per tick), visiting chunks in order.  Within a wave
  each device is busy every tick with exactly one (chunk, microbatch) —
  lockstep-collision-free — and wave w+1 starts the tick device 0 frees
  up, so steady state has zero idle ticks;
- the fill/drain bubble is (n-1)/(v*M + n-1) in chunk-ticks — the v-fold
  bubble reduction of interleaved scheduling, here in a forward-only scan
  (``interleave=1`` degenerates to the classic GPipe schedule);
- ticks outside a device's valid window compute on zeros and are masked
  out of the loss;
- the backward schedule is NOT hand-written: ``jax.grad`` through the scan
  and ppermute yields the reverse pipeline (ppermute's transpose reverses
  the ring), with ``jax.checkpoint`` on the chunk body for activation
  remat;
- pp composes with tensor parallelism: chunk layer weights additionally
  carry the Megatron head/FFN sharding over ``tp_axis`` and the block's two
  psums run inside every chunk (mesh (data, pipe, seq, model)); with
  sequence parallelism (ring attention inside chunks over 'seq'); and with
  uniformly-MoE stacks (moe_every=1 — every layer MoE, so chunk params
  stack homogeneously; per-(chunk, microbatch) aux accumulates through the
  ticks).

Schedule index math (device s, tick t, N = n*v):
  rel = t - s                      # ticks since the wavefront passed s
  w   = rel // N                   # wave index
  k   = (rel mod N) // n           # which of my v chunks is active
  m   = w*n + (rel mod n)          # microbatch index
  active iff rel >= 0 and m < M.  Chunk ``k*n + s`` receives from chunk
  ``k*n + s - 1``, which processed the same microbatch on the previous
  device at tick t-1 — so one ring hop per tick moves every in-flight
  microbatch forward one chunk.  Device 0 at k == 0 injects the fresh
  microbatch embedding instead; device n-1 at k == v-1 finishes
  microbatch m.

Embedding/unembedding weights are replicated to every stage (cheap at these
scales) so first/last-stage special-casing is a mask, not a branch.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from ..models import transformer as tfm
from ..utils.compat import opt_barrier, pcast, vma_of

PyTree = Any


def _uniform_moe(cfg: tfm.TransformerConfig) -> bool:
    """True when EVERY layer is an MoE layer (moe_every == 1): the one MoE
    shape whose layer params stack homogeneously into pipeline chunks."""
    return bool(cfg.n_experts) and all(
        cfg.is_moe_layer(i) for i in range(cfg.n_layers))


def split_layer_params(params: PyTree, cfg: tfm.TransformerConfig,
                       n_stages: int, interleave: int = 1):
    """Re-pack per-layer params into device-stacked chunk leaves.

    Returns ``(stage_params, shared)`` where each ``stage_params`` leaf has
    shape (n_stages, interleave, layers_per_chunk, *leaf) — shard its
    leading dim over 'pipe' — and ``shared`` holds embed/final_norm
    (replicated everywhere).  Logical chunk ``j`` (contiguous layers) lands
    at [j % n_stages, j // n_stages] (round-robin interleaved placement).

    MoE models pipeline iff the stack is uniform (``moe_every == 1``, every
    layer MoE): a dense/MoE-alternating stack has heterogeneous layer
    params that cannot stack into one scanned chunk body.
    """
    if cfg.n_experts and not _uniform_moe(cfg):
        raise ValueError(
            "pipeline parallelism requires a homogeneous layer stack: "
            "dense models, or uniformly-MoE models (moe_every=1).  A "
            "dense/MoE-alternating stack (moe_every > 1) cannot stack "
            "into pipeline chunks")
    n_chunks = n_stages * interleave
    if cfg.n_layers % n_chunks:
        raise ValueError(
            f"{cfg.n_layers} layers do not split into {n_stages} stages "
            f"x {interleave} virtual stages")
    per = cfg.n_layers // n_chunks
    layers = [params[f"layer{i}"] for i in range(cfg.n_layers)]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *layers)
    # (L, ...) -> (v, n, per, ...) [chunk j = k*n + s] -> (n, v, per, ...)
    stage_params = jax.tree.map(
        lambda x: jnp.moveaxis(
            x.reshape((interleave, n_stages, per) + x.shape[1:]), 0, 1),
        stacked)
    shared = {"embed": params["embed"], "final_norm": params["final_norm"]}
    return stage_params, shared


def merge_layer_params(stage_params: PyTree, shared: PyTree,
                       cfg: tfm.TransformerConfig) -> PyTree:
    """Inverse of split_layer_params (for checkpoint export/tests)."""
    flat = jax.tree.map(
        lambda x: jnp.moveaxis(x, 0, 1).reshape((-1,) + x.shape[3:]),
        stage_params)
    params = {"embed": shared["embed"], "final_norm": shared["final_norm"]}
    for i in range(cfg.n_layers):
        params[f"layer{i}"] = jax.tree.map(lambda x: x[i], flat)
    return params


def stage_specs(cfg: tfm.TransformerConfig, n_stages: int,
                tp_axis: str | None = None,
                interleave: int = 1) -> PyTree:
    """The spec tree matching split_layer_params' stage output: leading
    device dim over 'pipe'; with ``tp_axis``, each leaf's trailing dims also
    carry the Megatron head/FFN sharding (models/transformer.shard_specs),
    shifted right past the three stacking dims (device, virtual stage,
    layer-in-chunk)."""
    from jax.sharding import PartitionSpec as P

    stages_shape = jax.eval_shape(
        lambda k: split_layer_params(tfm.init(k, cfg), cfg, n_stages,
                                     interleave)[0],
        jax.random.key(0))
    if tp_axis is None:
        return jax.tree.map(lambda _: P("pipe"), stages_shape)
    layer_tp = tfm.shard_specs(cfg, tp_axis=tp_axis)["layer0"]
    return jax.tree.map(lambda spec, _: P("pipe", None, None, *spec),
                        layer_tp, stages_shape)


def _chunk(chunk_layers: PyTree, x: jax.Array,
           cfg: tfm.TransformerConfig, attn_impl: str,
           tp_axis: str | None = None,
           seq_axis: str | None = None,
           seq_layout: str = "contiguous",
           pos: jax.Array | None = None,
           is_moe: bool = False) -> tuple[jax.Array, jax.Array]:
    """Run one chunk's layers_per_chunk blocks (a homogeneous layer scan
    over the shared models/transformer.py:block body); returns (x, summed
    MoE aux).  With ``seq_axis`` the activations are sequence shards and
    each block's attention is the ring over that axis (pp x sp
    composition); ``pos`` is then the shard's absolute token positions.
    ``is_moe`` applies to every layer (uniform stacks only — see
    split_layer_params)."""
    if pos is None:
        pos = jnp.arange(x.shape[1])

    def body(carry, lp):
        # Fusion barrier at the body boundary (both passes — compat's
        # opt_barrier also barriers the cotangent): a rolled scan body is
        # a fusion unit by construction (the while-loop boundary), but XLA
        # UNROLLS trip-count-1 scans and then fuses the body with its
        # neighbours, perturbing f32 reduction vectorization sub-ulp — a
        # 1-layer pipeline chunk would train measurably ≠ the same layer
        # inside a longer chunk (found by the round-10 bitwise pins: every
        # per>=2 split exact, every per=1 split off by ~1e-10).  The
        # explicit barrier pins the body's compilation boundary at every
        # trip count; rolled splits (>= 2 layers per chunk) are bitwise ==
        # monolithic, and 1-layer chunks keep a residual ~1e-10 drift from
        # the reverse-scan residual layouts the barrier cannot reach —
        # the bitwise pins run per >= 2, the per=1 corner pins allclose.
        x, aux_acc = opt_barrier(carry)
        x, aux = tfm.block(lp, x, cfg=cfg, is_moe=is_moe, pos=pos,
                           attn_impl=attn_impl, tp_axis=tp_axis,
                           seq_axis=seq_axis, seq_layout=seq_layout)
        return opt_barrier((x, aux_acc + aux)), None

    # aux carry starts with x's vma so the scan carry types are stable
    aux0 = jnp.zeros((), jnp.float32)
    missing = tuple(a for a in vma_of(x) if a not in vma_of(aux0))
    if missing:
        aux0 = pcast(aux0, missing, to="varying")
    (x, aux), _ = lax.scan(body, (x, aux0), chunk_layers)
    return x, aux


def num_ticks(m_micro: int, n: int, interleave: int) -> int:
    """Scan length of the wave schedule: the tick after microbatch M-1
    (wave ceil(M/n)-1, in-wave slot (M-1)%n) clears the last chunk of
    device n-1."""
    waves = -(-m_micro // n)
    big_n = n * interleave
    return ((waves - 1) * big_n + (interleave - 1) * n
            + ((m_micro - 1) % n) + n)


# ---------------------------------------------------------------------------
# Interleaved-1F1B over the 'pp' mesh axis (round 10).
#
# The wave schedule above is the forward-only SPMD formulation (one scanned
# tick body, backward synthesized by autodiff).  The 1F1B machinery below is
# its training-schedule sibling for lm.py's ``pp_size``: the transformer's
# layer GROUPS (models/transformer.sync_group_index — the same boundary
# schedule that places the streaming ZeRO-3 gathers and DCN sync points)
# are partitioned into ``pp_size * interleave`` contiguous chunks, chunk j
# living on physical stage j % pp_size (Megatron's round-robin interleaved
# placement), and the train step EMITS each (chunk, microbatch) forward/
# backward unit in the order of an explicit one-forward-one-backward
# timetable, with the stage-boundary activation handoffs expressed as
# ppermute transfers over the 'pp' axis.  The timetable is data (a list of
# clocks), so the schedule the program was emitted in is directly
# measurable — utils/debug.py ``assert_pipeline_schedule`` checks 1F1B
# well-formedness and the fill/drain bubble against the analytic
# (pp-1)/(pp-1+M) bound, the same way the round-8/9 inspector pins
# collective interleaving.
#
# Unlike the wave schedule, the 1F1B step's backward is NOT synthesized by
# autodiff-through-the-scan: lm.py emits one explicit ``jax.vjp`` per
# (chunk, microbatch) backward unit in timetable order, with every
# cross-device reduction written out by hand.  That makes the schedule a
# first-class program property (the thing the inspector measures) — and,
# operationally, the whole path runs bit-correct even on legacy runtimes
# whose shard_map lacks automatic cotangent psums (utils/compat.py), which
# autodiff-era multi-axis LM paths do not.
# ---------------------------------------------------------------------------


def one_f_one_b_schedule(n_micro: int, n_stages: int,
                         interleave: int = 1) -> list[dict]:
    """The interleaved-1F1B timetable: a list of clocks, each a dict
    ``{stage: (kind, chunk, microbatch)}`` with kind "F" or "B".

    Generated by a work-conserving greedy simulation of the classic
    policy — every stage runs, each clock, its earliest-microbatch READY
    backward if one exists (a backward is ready once its own forward and
    the downstream chunk's backward finished in an EARLIER clock), else
    its earliest ready forward.  For interleave=1 this reproduces the
    textbook 1F1B schedule exactly (warmup forwards, steady-state strict
    F/B alternation, backward drain) and meets the analytic bubble bound
    (pp-1)/(pp-1+M); with interleave > 1 the virtual chunks round-robin
    through the same policy.  Per chunk, backwards execute in ascending
    microbatch order — the property that makes the 1F1B reordering a
    pure reassociation of the grad-accumulation sum (lm.py's bitwise
    claim)."""
    if n_micro < 1:
        raise ValueError(f"need >= 1 microbatch, got {n_micro}")
    n_chunks = n_stages * interleave
    done_f: dict[tuple[int, int], int] = {}   # (chunk, micro) -> clock
    done_b: dict[tuple[int, int], int] = {}
    next_f = [0] * n_chunks
    next_b = [0] * n_chunks
    clocks: list[dict] = []
    total = 2 * n_micro * n_chunks
    while len(done_f) + len(done_b) < total:
        clock: dict[int, tuple] = {}
        for s in range(n_stages):
            op = None
            cand_b = []
            for k in range(interleave):
                c = k * n_stages + s
                m = next_b[c]
                if (m < n_micro and (c, m) in done_f
                        and (c == n_chunks - 1 or (c + 1, m) in done_b)):
                    cand_b.append((m, -c))
            if cand_b:
                m, neg_c = min(cand_b)
                op = ("B", -neg_c, m)
            else:
                cand_f = []
                for k in range(interleave):
                    c = k * n_stages + s
                    m = next_f[c]
                    if m < n_micro and (c == 0 or (c - 1, m) in done_f):
                        cand_f.append((m, c))
                if cand_f:
                    m, c = min(cand_f)
                    op = ("F", c, m)
            if op is not None:
                clock[s] = op
        if not clock:  # pragma: no cover - a policy bug, not a data case
            raise AssertionError(
                f"1F1B schedule deadlocked at clock {len(clocks)} "
                f"(M={n_micro}, stages={n_stages}, v={interleave})")
        t = len(clocks)
        for s, (kind, c, m) in clock.items():
            if kind == "F":
                done_f[(c, m)] = t
                next_f[c] = m + 1
            else:
                done_b[(c, m)] = t
                next_b[c] = m + 1
        clocks.append(clock)
    return clocks


def bubble_fraction(clocks: list[dict], n_stages: int) -> float:
    """Measured bubble of a timetable: the fraction of (stage, clock)
    slots with no scheduled unit.  For the textbook 1F1B timetable this
    equals the analytic fill/drain bound exactly — see
    ``analytic_bubble_bound`` (the ONE definition of that bound — the
    schedule inspector imports it)."""
    busy = sum(len(c) for c in clocks)
    slots = n_stages * len(clocks)
    return 1.0 - busy / slots if slots else 0.0


def analytic_bubble_bound(n_stages: int, n_micro: int,
                          interleave: int = 1) -> float:
    """The interleaved-1F1B fill/drain bubble bound in chunk-clock units:
    ``(pp-1) / (pp-1 + M*v)`` — the classic (pp-1)/(pp-1+M) at
    interleave 1, shrinking v-fold with virtual stages (each of the M*v
    chunk-passes per stage is 1/v the work, but the fill/drain ramp stays
    pp-1 chunk-clocks)."""
    denom = n_stages - 1 + n_micro * interleave
    return (n_stages - 1) / denom if denom else 0.0


def schedule_tables(clocks: list[dict], n_stages: int, n_micro: int,
                    interleave: int = 1) -> dict:
    """Compile a 1F1B timetable into the dense per-(clock, stage) arrays
    the SPMD train step indexes with ``axis_index('pp')`` — the bridge
    from the timetable-as-data to the uniform per-clock program every
    rank traces.

    Returns int32/bool numpy arrays of shape (T, n_stages):

    - ``f_valid/f_k/f_m``: this stage runs a forward unit this clock, on
      its local virtual-stage slot ``f_k`` (chunk ``f_k*n + s``) and
      microbatch ``f_m``;
    - ``b_valid/b_k/b_m``: same for backward units;
    - ``fr_valid/fr_k/fr_m``: the stage RECEIVES a forward activation
      this clock (the upstream neighbour ran F on the preceding chunk),
      to stash for local slot ``fr_k``'s microbatch ``fr_m``;
    - ``br_valid/br_k/br_m``: same for backward cotangents arriving from
      the downstream neighbour.

    Invalid slots carry index 0 (the step masks them out).
    """
    import numpy as np

    n_chunks = n_stages * interleave
    t_total = len(clocks)
    z = lambda: np.zeros((t_total, n_stages), np.int32)  # noqa: E731
    f = {k: z() for k in ("f_valid", "f_k", "f_m", "b_valid", "b_k", "b_m",
                          "fr_valid", "fr_k", "fr_m",
                          "br_valid", "br_k", "br_m")}
    for t, clock in enumerate(clocks):
        for s, (kind, c, m) in clock.items():
            k = c // n_stages
            if kind == "F":
                f["f_valid"][t, s] = 1
                f["f_k"][t, s], f["f_m"][t, s] = k, m
                if c < n_chunks - 1:
                    # chunk c+1 lives on stage (s+1) % n: it receives this
                    # unit's output over the forward ring hop this clock
                    rs = (s + 1) % n_stages
                    f["fr_valid"][t, rs] = 1
                    f["fr_k"][t, rs] = (c + 1) // n_stages
                    f["fr_m"][t, rs] = m
            else:
                f["b_valid"][t, s] = 1
                f["b_k"][t, s], f["b_m"][t, s] = k, m
                if c > 0:
                    # chunk c-1's stage receives this unit's input
                    # cotangent over the reverse ring hop this clock
                    rs = (s - 1) % n_stages
                    f["br_valid"][t, rs] = 1
                    f["br_k"][t, rs] = (c - 1) // n_stages
                    f["br_m"][t, rs] = m
    return f


def stash_plan(clocks: list[dict], n_stages: int, n_micro: int,
               interleave: int = 1) -> tuple[int, int]:
    """Activation/cotangent stash depths for the 1F1B step, computed FROM
    the timetable and statically verified collision-free.

    The step keeps two rolling buffers per local chunk slot, indexed by
    ``microbatch % depth``: ``x_stash`` (chunk inputs received over the
    forward hop, read at the chunk's F clock and again at its B clock for
    the recompute-vjp) and ``cot_stash`` (output cotangents received over
    the reverse hop, read at the B clock).  A slot written at the end of
    clock ``t_w`` is live through its final read at clock ``t_r``; the
    plan asserts no later write lands on the slot before ``t_r`` — the
    bounded-stash property that gives 1F1B its O(pp * microbatch)
    activation memory (vs the flat wave scan's O(num_ticks)).

    Returns ``(x_depth, cot_depth)``.
    """
    n_chunks = n_stages * interleave
    done_f: dict = {}
    done_b: dict = {}
    for t, clock in enumerate(clocks):
        for s, (kind, c, m) in clock.items():
            (done_f if kind == "F" else done_b)[(c, m)] = t

    def min_depth(spans_by_chunk: dict) -> int:
        depth = 1
        for spans in spans_by_chunk.values():
            while True:
                by_slot: dict = {}
                for m, (t_w, t_r) in spans.items():
                    by_slot.setdefault(m % depth, []).append((t_w, t_r))
                ok = True
                for entries in by_slot.values():
                    entries.sort()
                    for (w1, r1), (w2, _) in zip(entries, entries[1:]):
                        if w2 < r1:  # overwritten while still live
                            ok = False
                if ok:
                    break
                depth += 1
        return depth

    x_spans: dict = {c: {} for c in range(1, n_chunks)}
    cot_spans: dict = {c: {} for c in range(n_chunks - 1)}
    for m in range(n_micro):
        for c in range(1, n_chunks):
            # written when the upstream F runs, last read at this B
            x_spans[c][m] = (done_f[(c - 1, m)], done_b[(c, m)])
        for c in range(n_chunks - 1):
            # written when the downstream B runs, read at this B
            cot_spans[c][m] = (done_b[(c + 1, m)], done_b[(c, m)])
    return (max(1, min_depth(x_spans)), max(1, min_depth(cot_spans)))


def pipeline_loss(
    stage_params: PyTree,
    shared: PyTree,
    tokens: jax.Array,     # (M, mb, S) microbatched token ids
    targets: jax.Array,    # (M, mb, S) next-token targets (IGNORE = pad)
    *,
    cfg: tfm.TransformerConfig,
    axis: str = "pipe",
    dtype: jnp.dtype | None = None,
    attn_impl: str = "flash",
    tp_axis: str | None = None,
    seq_axis: str | None = None,
    seq_layout: str = "contiguous",
    pos: jax.Array | None = None,
    interleave: int = 1,
    remat_block_ticks: int | None = 0,
    loss_impl: str = "dense",
    loss_chunk: int | None = None,
) -> jax.Array:
    """Mean masked CE over all microbatches, computed through the pipeline.

    Runs inside shard_map with ``stage_params`` leaves carrying this
    device's (1, interleave, layers_per_chunk, ...) slice.  Returns
    ``(ce_sum, n_valid, aux_sum)``: the loss summed over this shard's
    tokens, the valid-token count, and this pipe rank's summed MoE aux
    over its chunks and all microbatches (0.0 for dense stacks) — the
    caller psums ce/n across data/pipe/seq, psums aux over 'pipe' (layers
    are split across ranks) and means it over microbatches and data/seq.

    With ``seq_axis`` (pp x sp), ``tokens``/``targets`` are sequence
    shards: every microbatch's activations stay seq-sharded through the
    pipeline hops, and each chunk's attention is the ring over
    ``seq_axis``.  The ring's collectives run inside the tick, so pipeline
    (pipe-axis ppermute) and ring (seq-axis ppermute) traffic interleave
    tick by tick.  ``pos`` is this seq shard's absolute positions.

    ``loss_impl``/``loss_chunk`` route the finishing tick's unembed
    through the unified head-loss seam (ops/losses.py head_loss):
    "dense" traces the historical logits matmul + masked_ce bit-for-bit,
    "chunked" streams the head over vocab chunks (full vocab per rank —
    the wave head does not vocab-shard over tp).
    """
    from ..ops.losses import head_loss

    me = lax.axis_index(axis)
    n = lax.axis_size(axis)
    v = interleave
    big_n = n * v
    local = jax.tree.map(lambda x: x[0], stage_params)  # (v, per, ...)
    m_micro, mb, s = tokens.shape

    # Embed all microbatches (replicated embed; masked-out ticks feed zeros).
    x_all = shared["embed"][tokens]  # (M, mb, S, D)
    if dtype is not None:
        x_all = x_all.astype(dtype)

    chunk_fn = jax.checkpoint(partial(_chunk, cfg=cfg, attn_impl=attn_impl,
                                      tp_axis=tp_axis, seq_axis=seq_axis,
                                      seq_layout=seq_layout, pos=pos,
                                      is_moe=_uniform_moe(cfg)))
    perm = [(i, (i + 1) % n) for i in range(n)]  # ring: chunk k*n+s -> +1

    # Scan carries must be varying over every axis their updates vary over:
    # the pipe axis (stage params) plus whatever the inputs carry (e.g. a
    # 'data' axis when composed with DP).
    want_vma = vma_of(x_all) | {axis}

    def _varying(x):
        missing = tuple(a for a in want_vma if a not in vma_of(x))
        return pcast(x, missing, to="varying") if missing else x

    zero_x = _varying(jnp.zeros((mb, s, x_all.shape[-1]), x_all.dtype))

    def tick(carry, t):
        prev_out, ce_acc, n_acc, aux_acc = carry
        # Activation arriving from the previous device's chunk (one ring
        # hop per tick); device 0's first chunk takes the fresh microbatch
        # embedding instead.
        recv = lax.ppermute(prev_out, axis, perm)
        rel = t - me
        w = rel // big_n                   # wave (floor: negative pre-fill)
        k = (rel % big_n) // n             # active virtual stage (>= 0)
        m = w * n + (rel % n)              # microbatch index
        valid = (rel >= 0) & (m >= 0) & (m < m_micro)
        chunk_layers = jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(x, jnp.clip(k, 0, v - 1), 0,
                                               keepdims=False), local)
        m_in = jnp.clip(m, 0, m_micro - 1)
        fresh = lax.dynamic_index_in_dim(x_all, m_in, 0, keepdims=False)
        x_in = jnp.where((me == 0) & (k == 0), fresh, recv)
        out, aux = chunk_fn(chunk_layers, x_in)
        # every (chunk, microbatch) pair contributes its layers' aux once
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        # Last logical chunk (device n-1, slot v-1) finishes microbatch m:
        # unembed + masked CE.
        finish = (me == n - 1) & (k == v - 1) & valid
        h = tfm.rms_norm(out, shared["final_norm"], cfg.norm_eps)
        tgt = lax.dynamic_index_in_dim(targets, m_in, 0, keepdims=False)
        ce, cnt = head_loss(h, shared["embed"], tgt,
                            loss_impl=loss_impl, loss_chunk=loss_chunk)
        ce_acc = ce_acc + jnp.where(finish, ce, 0.0)
        n_acc = n_acc + jnp.where(finish, cnt, 0)
        return (out, ce_acc, n_acc, aux_acc), None

    ce0 = _varying(jnp.zeros(()))
    n0 = _varying(jnp.zeros((), jnp.int32))
    aux0 = _varying(jnp.zeros(()))

    # -- 1F1B-grade activation memory: block-remat over the tick scan ------
    # A flat scan of T ticks saves one (mb, S, D) carry per tick for the
    # backward: O(T) = O(M*v) live activations — the O(num_ticks) wall.
    # Nesting the scan (outer over blocks of ``remat_block_ticks`` ticks,
    # inner scan checkpointed) makes the backward keep only the T/block
    # block-boundary carries and rematerialize one block at a time, so peak
    # live activations are O(M*v/n + n) microbatch-sized buffers — for the
    # standard M = O(n) microbatch regime, O(pp * mb), 1F1B's bound.  The
    # price is one extra tick-forward per backward (the usual remat trade;
    # the per-chunk jax.checkpoint above keeps the within-block recompute
    # itself lean).  remat_block_ticks: 0 = auto (one wave, n ticks);
    # None = flat scan (the O(T) layout, kept for A/B memory tests).
    ticks = num_ticks(m_micro, n, v)
    if remat_block_ticks is None:
        (_, ce_sum, n_sum, aux_sum), _ = lax.scan(
            tick, (zero_x, ce0, n0, aux0), jnp.arange(ticks))
        return ce_sum, n_sum, aux_sum
    block = remat_block_ticks or n
    # Padded tail ticks still run a full (masked-out) chunk forward — they
    # are no-ops for the loss, not for compute.  The auto block (n) wastes
    # at most n-1 ticks; an explicit oversized block wastes up to block-1.
    t_pad = -(-ticks // block) * block

    @partial(jax.checkpoint, prevent_cse=False)
    def tick_block(carry, ts):
        carry, _ = lax.scan(tick, carry, ts)
        return carry, None

    (_, ce_sum, n_sum, aux_sum), _ = lax.scan(
        tick_block, (zero_x, ce0, n0, aux0),
        jnp.arange(t_pad).reshape(t_pad // block, block))
    return ce_sum, n_sum, aux_sum
