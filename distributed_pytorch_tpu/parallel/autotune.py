"""Topology-aware gradient-sync autotuner (round 11).

PRs 4-10 built every sync mechanism the dp x fsdp x tp x pp lattice
needs — reverse-topo bucket plans, in-backward sync points, two-level
(ici, dcn) streaming, int8-on-the-DCN-hop with error feedback — but
every knob was hand-picked: fixed 25 MB buckets, one global strategy
string, compression only where a human wired it.  DynamiQ (compressed
multi-hop all-reduce) and "The Big Send-off" (PAPERS.md) both show the
right algorithm/compression choice is a function of the LINK, not of
the model; this module closes the loop:

1. **Calibration** (``calibrate``): per mesh axis, time a small ladder
   of real collectives — ``psum``, reduce-scatter + all-gather, and a
   ppermute ring — at 3-4 payload sizes, then least-squares fit an
   alpha-beta cost model per link (``LinkModel``: launch latency
   ``alpha_s`` + inverse bandwidth ``beta_s_per_byte``), using each
   algorithm's analytic launch/wire factors so all observations
   constrain one (alpha, beta) pair.  Round 16 adds one quantize/
   dequantize round-trip to the same pass (``quant_s_per_byte``): the
   compute a compressed hop spends to earn its wire saving, so the
   chooser stops recommending compression on hosts where quantize
   compute eats the win (the round-11 CPU 0.71x mischoice).  Profiles cache to a versioned
   repo-local JSON (like the XLA compile cache; ``save_profile`` /
   ``load_profile``; a version mismatch invalidates silently), and
   deterministic synthetic profiles (``synthetic_profile``) are
   injectable for CPU tests.

2. **Plan choosing** (``choose_train_plan`` / ``choose_lm_plan``):
   given the grad-tree byte census (the same ``make_bucket_plan``
   packing the strategies execute) and a fitted profile, pick the
   bucket size, the ring-vs-tree-vs-two-level algorithm, and per-hop
   compression (none / int8+EF / int4+EF) by minimizing predicted
   step-sync
   time, emitting an explainable ``SyncPlan`` (predicted ms + operand
   bytes per axis, printable table).  The chooser is a pure function
   of (census, profile, config flags) — deterministic given a fixed
   profile (test-pinned).

3. **Resolution** (``resolve_train_auto`` / ``resolve_lm_auto``):
   ``TrainConfig(strategy="auto")`` / ``LMTrainConfig(sync_plan=
   "auto")`` resolve to the NAMED strategies/knobs the framework
   already ships, so the chosen plan routes through the existing
   (bitwise-pinned) paths unchanged: ``strategy="auto"`` under a
   forced profile trains bitwise-identically to the named strategy it
   resolves to.

Cost model (documented so the numbers are auditable; O = operand bytes
per device, n = axis size, a/b = the link's alpha/beta):

- ``psum`` (all-reduce, modeled bandwidth-optimal): a + 2*O*(n-1)/n*b
- ``psum_scatter`` (reduce-scatter):                a +   O*(n-1)/n*b
- ``all_gather`` of an O-byte shard:                a + O*(n-1)*b
- ``ppermute`` of an O-byte payload:                a + O*b

Wire accounting (``AxisPlan.predicted_bytes``) is OPERAND-PAYLOAD,
scan-trip-weighted — deliberately the same accounting as the schedule
inspector's ``bytes_executed`` (utils/debug.py), so predictions are
cross-checkable against measurements (``debug.assert_plan_bytes_match``,
scripts/bench_strategies.py's predicted-vs-measured table).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from . import strategies as strat
from ..utils import telemetry

# 2 since round 16: the cost model gained the quantize-compute term, so
# a version-1 profile (no quant_s_per_byte) would cost compression the
# optimistic old way — the exact mischoice this round fixes.  The cache
# version bump forces recalibration instead of silently steering.
# 3 since round 17: the memory chooser (choose_lm_memory_plan) prices
# remat/chunked-CE rungs with the device's calibrated
# recompute-seconds-per-byte; a version-2 profile has no such term and
# would cost rematerialization as free.
# 4 since round 20: profiles carry the routed-plan era's fields — the
# concurrent-calibration record (``concurrent`` block in ``measured``
# plus ``concurrent_delta_pct``) and the 3-level preset vocabulary
# ('wan' joins 'dcn' as a link role) — and the route chooser
# (choose_sync_plan) prices hop-graphs from the same per-axis fits.  A
# version-3 profile predates the busy-MXU calibration option and the
# wan role; the version gate recalibrates instead of silently steering
# (regression-tested in tests/test_routing.py).
# 5 since round 21: calibration times an all-to-all ladder rung per
# axis (the expert-dispatch collective, wire factor (n-1)/n) and the
# MoE dispatch chooser (choose_moe_plan) prices dispatch bit-widths
# from the same per-axis fits.  A version-4 profile's alpha-beta fit
# never saw an all-to-all observation; the version gate recalibrates
# instead of silently steering (regression-tested in tests/test_a2a.py).
PROFILE_VERSION = 5

# Bucket-size candidates (MB).  25 first: the torch-DDP default wins
# ties (strict-improvement argmin), so the chooser only moves off it
# when the profile actually says so.
BUCKET_LADDER_MB = (25.0, 4.0, 100.0)

# int8 ring per-hop payload factor: chunk int8 bytes + one f32 scale per
# 256-element row = chunk * (1 + 4/(4*256)) relative to chunk elements.
_RING_BLOCK = 256
_INT8_ROW_OVERHEAD = 1.0 + 1.0 / 64.0  # (1 int8 + 4/256 scale bytes)/elem
# int4 (round 16): two nibbles per int8 lane halve the chunk payload;
# the per-row f32 scale rides at full width either way.
_INT4_ROW_OVERHEAD = 0.5 + 1.0 / 64.0  # (0.5 packed + 4/256 scale)/elem

# Quantize-COMPUTE f32 passes per chunk element per ring hop: every hop
# dequantizes the incoming chunk and requantizes the outgoing one (2
# full f32 passes); the int4 rung adds the nibble pack/unpack pair on
# top.  Charged at the link's calibrated ``quant_s_per_byte`` — this is
# the term whose absence produced the round-11 CPU mischoice (predicted
# win, measured 0.71x: the wire saving was real, the quantize compute
# that paid for it was not in the model).
_QUANT_PASSES = {"int8": 2.0, "int4": 4.0}

# The two-level gather-back runs all_gather_invariant where available;
# legacy runtimes fall back to an embed + full-width psum over the fast
# axis (strategies.two_level_psum) — the predictor must account bytes
# for the program THIS runtime actually emits.
_GATHER_FALLBACK = strat._all_gather_inv is None


# ---------------------------------------------------------------------------
# profiles


@dataclass(frozen=True)
class LinkModel:
    """Alpha-beta-quant cost model of one mesh-axis link: a collective
    costs ``launches * alpha_s + wire_bytes * beta_s_per_byte +
    quant_bytes * quant_s_per_byte`` seconds, where ``quant_bytes`` is
    the f32 traffic a compressed hop pushes through quantize/dequantize
    (and, at int4, nibble pack/unpack) on the way to the wire.  The
    quant term (round 16) is calibrated from the same pass as alpha/
    beta; it defaults to 0.0 only for hand-built profile dicts — cached
    profiles without it are version-1 and recalibrate (PROFILE_VERSION
    bump)."""

    alpha_s: float
    beta_s_per_byte: float
    quant_s_per_byte: float = 0.0


@dataclass
class TopologyProfile:
    """Fitted per-axis link models for one mesh topology.

    ``axes`` preserves mesh order (outer first); ``measured`` carries the
    raw calibration observations (axis -> algo -> payload-bytes -> s) for
    auditability; ``source`` records provenance ("calibrated",
    "synthetic:<preset>", "cache:<path>").

    ``recompute_s_per_byte`` (round 17, version 3) is the DEVICE's cost
    of re-producing one activation byte under rematerialization —
    calibrated from a jitted transformer-shaped forward in the same pass
    as alpha/beta/quant, and charged by the memory chooser against the
    bytes ``utils.memacct.predict_recompute_bytes`` says a remat/chunked
    rung re-runs.  Like ``quant_s_per_byte`` it defaults to 0.0 only for
    hand-built dicts; cached profiles without it are stale and
    recalibrate (version gate).

    ``concurrent_delta_pct`` (round 20, version 4) records how much the
    quantize rate degraded when calibration ran against a background
    matmul stream (``calibrate(concurrent=True)`` — link fits that
    reflect a busy MXU instead of an idle device); ``None`` means the
    profile was calibrated idle.  Hand-built dicts default it; cached
    profiles without the field are version-3 and recalibrate."""

    version: int
    device_kind: str
    axes: dict[str, int]
    links: dict[str, LinkModel]
    source: str = "calibrated"
    measured: dict = field(default_factory=dict)
    recompute_s_per_byte: float = 0.0
    concurrent_delta_pct: float | None = None

    def key(self) -> str:
        """Cache-file key: device kind + topology (axis names x sizes)."""
        topo = "-".join(f"{a}{s}" for a, s in self.axes.items())
        kind = "".join(c if c.isalnum() else "_" for c in self.device_kind)
        return f"{kind}_{topo}"

    def to_json(self) -> dict:
        return {"version": self.version, "device_kind": self.device_kind,
                "axes": dict(self.axes),
                "links": {a: {"alpha_s": l.alpha_s,
                              "beta_s_per_byte": l.beta_s_per_byte,
                              "quant_s_per_byte": l.quant_s_per_byte}
                          for a, l in self.links.items()},
                "source": self.source, "measured": self.measured,
                "recompute_s_per_byte": self.recompute_s_per_byte,
                "concurrent_delta_pct": self.concurrent_delta_pct}

    @classmethod
    def from_json(cls, d: dict) -> "TopologyProfile":
        return cls(version=int(d["version"]),
                   device_kind=d["device_kind"],
                   axes={a: int(s) for a, s in d["axes"].items()},
                   links={a: LinkModel(float(l["alpha_s"]),
                                       float(l["beta_s_per_byte"]),
                                       # pre-round-16 profiles have no
                                       # quant term: load, cost it free
                                       float(l.get("quant_s_per_byte",
                                                   0.0)))
                          for a, l in d["links"].items()},
                   source=d.get("source", "cache"),
                   measured=d.get("measured", {}),
                   recompute_s_per_byte=float(
                       d.get("recompute_s_per_byte", 0.0)),
                   # pre-round-20 profiles never calibrated busy: None
                   concurrent_delta_pct=d.get("concurrent_delta_pct"))


# Deterministic synthetic profiles for CPU tests and the dryrun: each
# preset maps the requested axes onto fixed (alpha, beta) pairs by ROLE
# ('dcn' = the cross-slice slow hop; every other axis is a fast intra-
# slice link).  The numbers are chosen so each preset has one clearly
# optimal plan (test-pinned in tests/test_autotune.py):
#
# - uniform:           equal medium links, launch-latency-dominated ->
#                      the flat fused psum (fewest launches) wins.
# - fast_ici_slow_dcn: ~400x bandwidth gap -> two-level + int8 on the
#                      scarce hop (the DynamiQ design point).  int8, NOT
#                      int4: at 0.5 GB/s the int4 rung's halved wire
#                      (saves ~2 ns/elem) no longer pays for its doubled
#                      quantize passes (~1.6 ns/elem extra at the preset
#                      quant rate) plus the 16x-coarser rounding — the
#                      quant term keeps the ladder honest.
# - inverted:          the INNER link is the bottleneck -> two-level
#                      buys nothing (its reduce-scatter/gather ride the
#                      slow link either way); flat psum wins on launches.
# - slow:              one slow flat link -> the int8+EF ring (true
#                      per-hop wire compression) wins.
# - fast:              one fast flat link -> plain fused psum wins.
# - wan_dcn:           a WAN-grade cross-site hop (~0.05 GB/s, round
#                      16): wire is 10x scarcer than fast_ici_slow_dcn,
#                      so halving it dominates the extra quantize
#                      passes -> two-level + int4+EF on the slow hop.
# - quant_bound:       same 0.5 GB/s DCN hop but a quantize throughput
#                      of ~0.5 GB/s (a host-bound mesh, e.g. the CPU
#                      mesh of BASELINE round 11 that measured 0.71x on
#                      a predicted win): quantize compute eats the wire
#                      saving -> the chooser DECLINES compression.
_QUANT = 2e-10  # ~5 GB/s quantize/dequantize throughput (accelerator)
# ~5 GB/s of re-produced activation bytes: the synthetic presets' stand-
# in for the calibrated recompute rate (same order as _QUANT — both are
# device compute, not wire)
_RECOMPUTE_SYNTH = 2e-10
_FAST = LinkModel(alpha_s=1e-6, beta_s_per_byte=5e-12,     # ~200 GB/s
                  quant_s_per_byte=_QUANT)
_SLOW = LinkModel(alpha_s=1e-5, beta_s_per_byte=2e-9,      # ~0.5 GB/s
                  quant_s_per_byte=_QUANT)
_WAN = LinkModel(alpha_s=1e-5, beta_s_per_byte=2e-8,       # ~0.05 GB/s
                 quant_s_per_byte=_QUANT)
_SLOW_QUANT_BOUND = LinkModel(alpha_s=1e-5, beta_s_per_byte=2e-9,
                              quant_s_per_byte=2e-9)  # ~0.5 GB/s quant
_MEDIUM_HIGH_ALPHA = LinkModel(alpha_s=2e-4, beta_s_per_byte=1e-11,
                               quant_s_per_byte=_QUANT)
SYNTHETIC_PRESETS = {
    "uniform": lambda axis: _MEDIUM_HIGH_ALPHA,
    "fast_ici_slow_dcn": lambda axis: _SLOW if axis == "dcn" else _FAST,
    "inverted": lambda axis: _FAST if axis == "dcn" else _SLOW,
    "slow": lambda axis: LinkModel(alpha_s=2e-6, beta_s_per_byte=2e-9,
                                   quant_s_per_byte=_QUANT),
    "fast": lambda axis: _MEDIUM_HIGH_ALPHA,
    "wan_dcn": lambda axis: _WAN if axis == "dcn" else _FAST,
    "quant_bound": lambda axis: (_SLOW_QUANT_BOUND if axis == "dcn"
                                 else _FAST),
    # round 20: the ≥3-level mesh the route chooser searches — fast ICI
    # within a slice, a datacenter-grade DCN tier across slices, and a
    # WAN-grade cross-site tier above that.  The optimal plan is a
    # NESTED 3-hop route (ici:rs → dcn:rs → wan:ring[int4+ef] → dcn:ag
    # → ici:ag): the wan exchange rides a payload already divided by
    # BOTH faster axes, and at 0.05 GB/s halving its wire dominates the
    # extra quantize passes (test-pinned in tests/test_routing.py).
    "ici_dcn_wan": lambda axis: (_WAN if axis == "wan"
                                 else _SLOW if axis == "dcn" else _FAST),
}


def synthetic_profile(preset: str, axes: dict[str, int]) -> TopologyProfile:
    """A deterministic profile for ``axes`` from a named preset — the CPU
    tests' injection point (no device timing anywhere)."""
    try:
        link_of = SYNTHETIC_PRESETS[preset]
    except KeyError:
        raise ValueError(
            f"unknown synthetic profile {preset!r}; presets: "
            f"{sorted(SYNTHETIC_PRESETS)}") from None
    return TopologyProfile(
        version=PROFILE_VERSION, device_kind="synthetic",
        axes=dict(axes), links={a: link_of(a) for a in axes},
        source=f"synthetic:{preset}",
        recompute_s_per_byte=_RECOMPUTE_SYNTH)


# ---------------------------------------------------------------------------
# profile cache (repo-local, versioned — the XLA-compile-cache shape)


def profile_cache_dir() -> str:
    env = os.environ.get("JAX_GRAFT_AUTOTUNE_CACHE")
    if env:
        return env
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, ".autotune_cache")


def save_profile(profile: TopologyProfile,
                 cache_dir: str | None = None) -> str:
    d = cache_dir or profile_cache_dir()
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"profile_{profile.key()}.json")
    with open(path, "w") as f:
        json.dump(profile.to_json(), f, indent=1, sort_keys=True)
    return path


def load_profile(device_kind: str, axes: dict[str, int],
                 cache_dir: str | None = None) -> TopologyProfile | None:
    """Cached profile for this (device kind, topology), or None on a miss
    OR a version/topology mismatch — a stale profile must trigger
    recalibration, never silently steer the chooser."""
    key = TopologyProfile(PROFILE_VERSION, device_kind, dict(axes), {}).key()
    path = os.path.join(cache_dir or profile_cache_dir(),
                        f"profile_{key}.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError):
        return None
    if int(d.get("version", -1)) != PROFILE_VERSION:
        return None
    p = TopologyProfile.from_json(d)
    if p.axes != dict(axes):
        return None
    p.source = f"cache:{path}"
    return p


# ---------------------------------------------------------------------------
# calibration


def _algo_factors(algo: str, n: int) -> tuple[float, float]:
    """(launches, wire-bytes-per-payload-byte) of one calibration
    collective over an n-way axis — the analytic factors the fit divides
    out so every (algo, size) observation constrains ONE (alpha, beta)."""
    if algo == "psum":
        return 1.0, 2.0 * (n - 1) / n
    if algo == "rs_ag":  # psum_scatter + all_gather
        return 2.0, 2.0 * (n - 1) / n
    if algo == "ring":   # n-1 chained full-payload ppermute hops
        return float(n - 1), float(n - 1)
    if algo == "a2a":    # all-to-all: each device keeps its own 1/n block
        return 1.0, float(n - 1) / n
    raise ValueError(f"unknown calibration algorithm {algo!r}")


def fit_alpha_beta(observations: list[tuple[float, float, float]]
                   ) -> LinkModel:
    """Least-squares fit of ``t = alpha*L + beta*W`` over observations
    ``(launches L, wire_bytes W, seconds t)``; both coefficients clamped
    non-negative (a negative latency/bandwidth fit is noise)."""
    A = np.asarray([[l, w] for l, w, _ in observations], np.float64)
    t = np.asarray([s for _, _, s in observations], np.float64)
    coef, *_ = np.linalg.lstsq(A, t, rcond=None)
    alpha = float(max(coef[0], 1e-12))
    beta = float(max(coef[1], 1e-15))
    return LinkModel(alpha_s=alpha, beta_s_per_byte=beta)


def _time_axis_collective(mesh, axis: str, payload_bytes: int, algo: str,
                          *, inner: int = 4, reps: int = 2) -> float:
    """Measured seconds per execution of one ``algo`` collective over
    ``axis`` at ``payload_bytes`` (f32 payload), best-of-``reps`` of an
    ``inner``-deep data-chained loop (the bench.py chained-window
    discipline: the chain defeats CSE, one fetch ends the window)."""
    import time

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map

    n = dict(zip(mesh.axis_names, mesh.devices.shape))[axis]
    elems = max(payload_bytes // 4, _RING_BLOCK)
    elems += (-elems) % n  # rs_ag needs an n-divisible payload
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(x):
        if algo == "psum":
            return lax.psum(x, axis) * (1.0 / n)
        if algo == "rs_ag":
            s = lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
            return lax.all_gather(s, axis, axis=0, tiled=True) * (1.0 / n)
        if algo == "a2a":  # the expert-dispatch permutation (round 21)
            y = lax.all_to_all(x.reshape(n, elems // n), axis,
                               split_axis=0, concat_axis=0, tiled=False)
            return y.reshape(elems)
        acc = x
        for _ in range(n - 1):  # ring: chained full-payload hops
            acc = lax.ppermute(acc, axis, perm)
        return acc

    def chained(x):
        for _ in range(inner):
            x = body(x)
            x = lax.optimization_barrier(x)
        return x

    fn = jax.jit(shard_map(
        chained, mesh=mesh,
        in_specs=(P(),), out_specs=P(),
        # the ring assembles a ppermute result: replicated by
        # construction (value-preserving permutation of identical
        # payloads), not provably — calibration is measurement-only
        check_vma=False))
    x = jnp.full((elems,), 1.0 / inner, jnp.float32)
    np.asarray(fn(x))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(x)
        out.block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return best / inner


def _time_quantize(payload_bytes: int = 4 << 20, *,
                   reps: int = 3) -> float:
    """Seconds per f32 byte of ONE quantize-or-dequantize pass on the
    default device: time a jitted per-row symmetric int8 round-trip
    (the ring hops' exact compute shape) over a ``payload_bytes``
    buffer, best-of-``reps``, and divide by the two passes' f32 bytes.
    This is the round-16 calibration of ``LinkModel.quant_s_per_byte``
    — measured on the same pass as alpha/beta so the chooser can weigh
    wire saved against quantize compute spent on THIS host."""
    import time

    import jax
    import jax.numpy as jnp

    elems = max(payload_bytes // 4, _RING_BLOCK)
    elems += (-elems) % _RING_BLOCK

    @jax.jit
    def roundtrip(x):
        rows = x.reshape(-1, _RING_BLOCK)
        scale = jnp.maximum(
            jnp.max(jnp.abs(rows), axis=1, keepdims=True), 1e-30) / 127.0
        q = jnp.clip(jnp.round(rows / scale), -127, 127).astype(jnp.int8)
        return (q.astype(jnp.float32) * scale).reshape(x.shape)

    x = jnp.linspace(-1.0, 1.0, elems, dtype=jnp.float32)
    np.asarray(roundtrip(x))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        roundtrip(x).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return best / (2.0 * elems * 4.0)


def _time_recompute(*, rows: int = 2048, width: int = 512,
                    reps: int = 3) -> float:
    """Seconds per activation byte RE-produced by a rematerialized
    forward on the default device: time a jitted transformer-flavored
    chain (matmul -> silu-gate -> matmul, the block's recompute shape)
    and divide by the intermediate bytes it materializes.  The round-17
    calibration of ``TopologyProfile.recompute_s_per_byte`` — the
    ``_time_quantize`` precedent, aimed at memory instead of wire: the
    memory chooser weighs activation bytes saved against THIS host's
    cost of re-running the forward that re-creates them."""
    import time

    import jax
    import jax.numpy as jnp

    @jax.jit
    def fwd(x, w1, w2):
        g = x @ w1                  # rows x (4*width)
        a = jax.nn.silu(g) * g      # two more rows x (4*width)
        return a @ w2               # rows x width

    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, (rows, width), jnp.float32)
    w1 = jax.random.normal(jax.random.fold_in(k, 1),
                           (width, 4 * width), jnp.float32) * 0.02
    w2 = jax.random.normal(jax.random.fold_in(k, 2),
                           (4 * width, width), jnp.float32) * 0.02
    produced = (3 * rows * 4 * width + rows * width) * 4  # f32 bytes
    np.asarray(fwd(x, w1, w2))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fwd(x, w1, w2).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return best / produced


class _BackgroundMatmul:
    """A host thread that keeps dispatching a jitted matmul chain on the
    default device while calibration times its ladders — the round-20
    busy-MXU stream.  Context manager: enter starts the stream, exit
    joins it.  Dispatch is async (one ``block_until_ready`` per chain of
    8), so the device queue stays occupied without the host thread
    monopolizing the GIL."""

    def __init__(self, dim: int = 512):
        import threading

        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, args=(dim,),
                                        daemon=True)

    def _run(self, dim: int) -> None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def chain(x):
            for _ in range(8):
                x = x @ x * (1.0 / dim)
            return x

        x = jnp.full((dim, dim), 1.0 / dim, jnp.float32)
        x = chain(x)
        x.block_until_ready()  # compile outside the timed window
        while not self._stop.is_set():
            x = chain(x)
            x.block_until_ready()

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join()
        return False


def calibrate(mesh, *, payload_bytes=(256 << 10, 1 << 20, 4 << 20),
              algos=("psum", "rs_ag", "ring", "a2a"),
              inner: int = 4, reps: int = 2,
              concurrent: bool = False) -> TopologyProfile:
    """Fit a ``TopologyProfile`` by timing real collectives per axis of
    ``mesh`` (the calibration pass), plus one quantize/dequantize
    round-trip for the compute half of the compressed-hop cost (shared
    across axes — it runs on the device, not the link).  Axes of size 1
    get a zero-cost link (nothing ever crosses them).  The ladder's
    fourth rung (round 21) is the all-to-all — the expert-dispatch
    permutation, wire factor ``(n-1)/n`` — so the same (alpha, beta)
    fit also prices MoE dispatch (``choose_moe_plan``).

    ``concurrent=True`` (round 20) runs the quantize ladder and the
    per-axis collective ladders against a background matmul stream
    (``_BackgroundMatmul``), so the fits reflect a BUSY device — the
    regime the sync actually runs in (collectives compete with backward
    compute for the same cores/MXU).  The idle quantize rate is always
    measured first; the busy-vs-idle delta lands in
    ``concurrent_delta_pct`` and ``measured['concurrent']`` (recorded in
    BASELINE round 20)."""
    import contextlib
    import time

    import jax

    t0 = time.perf_counter()
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    quant_idle = _time_quantize()
    recompute = _time_recompute()
    stream = _BackgroundMatmul() if concurrent else contextlib.nullcontext()
    concurrent_delta = None
    with stream:
        quant = _time_quantize() if concurrent else quant_idle
        if concurrent:
            concurrent_delta = (quant / quant_idle - 1.0) * 100.0
        links: dict[str, LinkModel] = {}
        measured: dict[str, dict] = {"quantize_s_per_byte": quant,
                                     "recompute_s_per_byte": recompute}
        if concurrent:
            measured["concurrent"] = {
                "quantize_s_per_byte_idle": quant_idle,
                "quantize_s_per_byte_busy": quant,
                "delta_pct": concurrent_delta}
        for axis, n in sizes.items():
            if n < 2:
                links[axis] = LinkModel(alpha_s=0.0, beta_s_per_byte=0.0)
                continue
            obs: list[tuple[float, float, float]] = []
            raw: dict[str, dict] = {}
            for algo in algos:
                raw[algo] = {}
                for b in payload_bytes:
                    t = _time_axis_collective(mesh, axis, b, algo,
                                              inner=inner, reps=reps)
                    launches, wire_per_byte = _algo_factors(algo, n)
                    obs.append((launches, wire_per_byte * b, t))
                    raw[algo][str(b)] = t
            links[axis] = dataclasses.replace(fit_alpha_beta(obs),
                                              quant_s_per_byte=quant)
            measured[axis] = raw
    tel = telemetry.active()
    if tel is not None:
        # calibration on the unified timeline (round 13): when, how
        # long, and which links it fitted
        tel.span_at("autotune_calibrate", t0, time.perf_counter() - t0,
                    phase="autotune", axes=sizes,
                    links={a: {"alpha_s": l.alpha_s,
                               "beta_s_per_byte": l.beta_s_per_byte,
                               "quant_s_per_byte": l.quant_s_per_byte}
                           for a, l in links.items()})
    return TopologyProfile(
        version=PROFILE_VERSION,
        device_kind=getattr(jax.devices()[0], "device_kind", "cpu"),
        axes=sizes, links=links,
        source="calibrated:concurrent" if concurrent else "calibrated",
        measured=measured, recompute_s_per_byte=recompute,
        concurrent_delta_pct=concurrent_delta)


def get_profile(spec, axes: dict[str, int], *, cache_dir: str | None = None,
                calibrate_kwargs: dict | None = None) -> TopologyProfile:
    """Resolve a profile for ``axes`` from ``spec``:

    - a ``TopologyProfile``: used as-is (axes must match — a forced
      profile for the wrong topology would silently mis-steer);
    - a synthetic preset name (``SYNTHETIC_PRESETS``);
    - a path to a profile JSON (version/axes-checked, loudly);
    - ``None``: the cached profile for this (device kind, topology), or
      a fresh calibration over a throwaway mesh, saved back to the cache.
    """
    if isinstance(spec, TopologyProfile):
        if spec.axes != dict(axes):
            raise ValueError(
                f"injected profile is for topology {spec.axes}, the config "
                f"needs {dict(axes)} — refusing to choose from the wrong "
                f"links")
        return spec
    if isinstance(spec, str):
        if spec in SYNTHETIC_PRESETS:
            return synthetic_profile(spec, axes)
        if os.path.exists(spec):
            with open(spec) as f:
                d = json.load(f)
            if int(d.get("version", -1)) != PROFILE_VERSION:
                raise ValueError(
                    f"profile {spec} has version {d.get('version')}, this "
                    f"build needs {PROFILE_VERSION} — recalibrate")
            p = TopologyProfile.from_json(d)
            if p.axes != dict(axes):
                raise ValueError(
                    f"profile {spec} is for topology {p.axes}, the config "
                    f"needs {dict(axes)}")
            return p
        raise ValueError(
            f"autotune profile {spec!r} is neither a synthetic preset "
            f"({sorted(SYNTHETIC_PRESETS)}) nor an existing profile file")
    import jax

    kind = getattr(jax.devices()[0], "device_kind", "cpu")
    cached = load_profile(kind, axes, cache_dir)
    if cached is not None:
        return cached
    from .mesh import make_mesh

    n = int(np.prod(list(axes.values())))
    mesh = make_mesh(n, axis_names=tuple(axes),
                     axis_shape=tuple(axes.values()))
    prof = calibrate(mesh, **(calibrate_kwargs or {}))
    save_profile(prof, cache_dir)
    return prof


# ---------------------------------------------------------------------------
# grad census


# the ONE shapes-only stand-in for bucket planning (defined next to
# make_bucket_plan; lm.py's EF-residual sizing shares it)
_SizedLeaf = strat.SizedLeaf


@dataclass(frozen=True)
class GradCensus:
    """Byte census of a gradient pytree: per-leaf (element count, dtype)
    in flatten order — everything the bucket planner and the cost model
    need, nothing device-resident."""

    leaves: tuple[_SizedLeaf, ...]

    @property
    def total_bytes(self) -> int:
        return sum(l.size * l.dtype.itemsize for l in self.leaves)

    @property
    def n_leaves(self) -> int:
        return len(self.leaves)

    def bucket_plan(self, bucket_bytes: int) -> list[int]:
        """Per-bucket byte sizes under the REAL reverse-topo packing
        (the one plan every strategy shares)."""
        plan = strat.make_bucket_plan(list(self.leaves), bucket_bytes)
        return [sum(self.leaves[i].size * self.leaves[i].dtype.itemsize
                    for i in b) for b in plan]


def grad_census(tree) -> GradCensus:
    """Census of ``tree`` (arrays OR ShapeDtypeStructs, e.g. from
    ``jax.eval_shape`` — no device work)."""
    import jax

    leaves = jax.tree.leaves(tree)
    return GradCensus(tuple(
        _SizedLeaf(int(np.prod(l.shape, dtype=np.int64) or 1),
                   np.dtype(l.dtype)) for l in leaves))


# ---------------------------------------------------------------------------
# the cost model


@dataclass(frozen=True)
class AxisPlan:
    """One mesh axis' share of a candidate plan: the algorithm label,
    launch count, predicted operand-payload bytes per step (the
    inspector-comparable number), and predicted milliseconds."""

    axis: str
    algorithm: str
    launches: int
    predicted_bytes: int
    predicted_ms: float


@dataclass(frozen=True)
class SyncPlan:
    """The chooser's output: a resolved named strategy + knobs, with the
    prediction that justified it.  ``predicted_ms`` is the EXPOSED
    per-step sync time (wire hidden under backward compute is
    discounted when ``overlap``); ``per_axis`` carries the raw totals.

    ``sync_every`` (round 18) is the chosen local-SGD window: the slow
    hop runs once per ``sync_every`` steps, so ``predicted_ms`` is the
    AMORTIZED per-step figure (dcn term divided by the window) when the
    interval is > 1; ``per_axis`` stays per-EXCHANGE so the dcn row
    remains comparable to the inspector's boundary-step bytes.

    ``route`` (round 20) is the declarative hop-graph this plan
    executes, in ``parallel/routing`` grammar (e.g. ``ici:rs →
    dcn:ring[int8+ef] → ici:ag``) — attached to every 2-level plan the
    legacy choosers emit and to the routed plans ``choose_sync_plan``
    searches; ``per_hop`` carries one cost row per hop (AxisPlan with
    the hop label in ``axis``) for plans priced by the route model."""

    strategy: str
    bucket_mb: float
    dcn_compress: str | None
    dcn_size: int
    overlap: bool
    predicted_ms: float
    per_axis: tuple[AxisPlan, ...]
    profile_source: str
    census_bytes: int
    sync_every: int = 1
    route: str = ""
    per_hop: tuple = ()
    # Round 22 (the WAN/DiLoCo dimension): ``outer_opt`` is the chooser's
    # boundary-update recommendation (None = plain mean; "nesterov" when
    # a ≥3-tier route widened an interval — wide windows want outer
    # momentum, the measured convergence-band claim);
    # ``interval_by_hop`` records the per-tier sync interval assignment
    # as sorted (axis, H) pairs — ``sync_every`` stays the BASE (minimum)
    # interval, what the trainer's window cadence compiles to, and the
    # slower tiers' wider H map to per-slice windows.
    outer_opt: str | None = None
    interval_by_hop: tuple = ()

    def axis(self, name: str) -> AxisPlan | None:
        for ap in self.per_axis:
            if ap.axis == name:
                return ap
        return None

    def summary(self) -> dict:
        """Compact JSON-able form (the bench's train_autotune_plan)."""
        out = {"strategy": self.strategy, "bucket_mb": self.bucket_mb,
               "dcn_compress": self.dcn_compress,
               "dcn_size": self.dcn_size, "overlap": self.overlap,
               "sync_every": self.sync_every,
               "predicted_ms": round(self.predicted_ms, 4),
               "profile": self.profile_source,
               "bytes_by_axis": {ap.axis: ap.predicted_bytes
                                 for ap in self.per_axis}}
        if self.route:
            out["route"] = self.route
        if self.per_hop:
            out["bytes_by_hop"] = {hp.axis: hp.predicted_bytes
                                   for hp in self.per_hop}
        if self.outer_opt is not None:
            out["outer_opt"] = self.outer_opt
        if self.interval_by_hop:
            out["interval_by_hop"] = dict(self.interval_by_hop)
        return out

    def table(self) -> str:
        """Printable explanation: one row per axis + the decision line."""
        lines = [f"SyncPlan: strategy={self.strategy} "
                 f"bucket={self.bucket_mb:g}MB "
                 f"dcn_compress={self.dcn_compress or 'none'} "
                 f"overlap={self.overlap} "
                 f"sync_every={self.sync_every} "
                 f"predicted {self.predicted_ms:.3f} ms/step "
                 f"(grads {self.census_bytes / 1e6:.2f} MB, "
                 f"profile {self.profile_source})",
                 "| axis | algorithm | launches | MB/step | ms |",
                 "|---|---|---|---|---|"]
        for ap in self.per_axis:
            lines.append(
                f"| {ap.axis} | {ap.algorithm} | {ap.launches} | "
                f"{ap.predicted_bytes / 1e6:.2f} | "
                f"{ap.predicted_ms:.3f} |")
        if self.route:
            lines.append(f"route: {self.route}")
        if self.interval_by_hop:
            lines.append("intervals: " + ", ".join(
                f"{a}:H={h}" for a, h in self.interval_by_hop)
                + (f" (outer_opt={self.outer_opt})"
                   if self.outer_opt else ""))
        for hp in self.per_hop:
            lines.append(
                f"|   hop {hp.axis} | {hp.algorithm} | {hp.launches} | "
                f"{hp.predicted_bytes / 1e6:.2f} | "
                f"{hp.predicted_ms:.3f} |")
        return "\n".join(lines)


def _ring_chunk_elems(elems: int, n: int) -> int:
    """The int8 ring's block-aligned per-device chunk (strategies.
    QuantizedRing._chunk) for an ``elems``-element flat vector."""
    return -(-elems // (n * _RING_BLOCK)) * _RING_BLOCK


def _quant_ring_bytes(elems: int, n: int, compress: str = "int8"
                      ) -> tuple[int, int, int]:
    """(executed ppermute operand bytes, launches, quantize-compute f32
    bytes) of one ``QuantizedRing._ring_sum`` over an n-way axis: the
    reduce-scatter and all-gather scans each run n-1 trips of one
    quantized-chunk ppermute (int8 lanes, or nibble-packed int4 at half
    width) plus one f32 row-scale ppermute.  The third number is the
    f32 traffic through quantize/dequantize (+ pack/unpack at int4)
    those hops cost in COMPUTE — charged at the link's
    ``quant_s_per_byte``."""
    if n < 2:
        return 0, 0, 0
    chunk = _ring_chunk_elems(elems, n)
    overhead = (_INT4_ROW_OVERHEAD if compress == "int4"
                else _INT8_ROW_OVERHEAD)
    hops = 2 * (n - 1)
    return (hops * int(chunk * overhead), hops,
            int(hops * chunk * 4 * _QUANT_PASSES[compress]))


def _two_level_axis_costs(bucket_elems: list[int], n_ici: int, n_dcn: int,
                          compress: str | None) -> dict[str, tuple]:
    """Per-axis (operand bytes, launches, wire bytes, quantize-compute
    bytes) of the two-level reduction over the given f32 bucket element
    counts: reduce-scatter over the fast axis, shard exchange over the
    slow one (stock psum or the int8/int4 ring), gather back
    (all_gather_invariant, or the legacy embed + full-width psum
    fallback)."""
    ici_bytes = ici_wire = dcn_bytes = dcn_wire = dcn_quant = 0
    ici_launch = dcn_launch = 0
    for e in bucket_elems:
        padded = e + (-e) % max(n_ici, 1)
        shard = padded // max(n_ici, 1)
        if n_ici > 1:
            # psum_scatter operand: the padded full vector
            ici_bytes += padded * 4
            ici_wire += padded * 4 * (n_ici - 1) // n_ici
            ici_launch += 1
            if _GATHER_FALLBACK:
                ici_bytes += padded * 4      # full-width psum fallback
                ici_wire += 2 * padded * 4 * (n_ici - 1) // n_ici
            else:
                ici_bytes += shard * 4       # all_gather of the shard
                ici_wire += shard * 4 * (n_ici - 1)
            ici_launch += 1
        if n_dcn > 1:
            if compress in ("int8", "int4"):
                b, l, q = _quant_ring_bytes(shard, n_dcn, compress)
                dcn_bytes += b
                dcn_wire += b
                dcn_launch += l
                dcn_quant += q
            else:
                dcn_bytes += shard * 4
                dcn_wire += 2 * shard * 4 * (n_dcn - 1) // n_dcn
                dcn_launch += 1
    return {"ici": (ici_bytes, ici_launch, ici_wire, 0),
            "dcn": (dcn_bytes, dcn_launch, dcn_wire, dcn_quant)}


def predict_named(name: str, census: GradCensus, profile: TopologyProfile,
                  *, bucket_mb: float = strat.BUCKET_CAP_MB,
                  dcn_compress: str | None = None,
                  overlap: bool = False) -> dict | None:
    """Predicted cost of running ``name`` (a registry strategy, or
    'none') for this census on this profile: ``{"ms_total", "ms_exposed",
    "per_axis": [AxisPlan, ...]}``; None for strategies the model does
    not cover.  ``ms_exposed`` discounts wire time hidden under backward
    compute when ``overlap`` (all but one bucket's wire hides — the
    exposed tail + every launch), and is what the chooser minimizes;
    ``ms_total`` is the undiscounted sum (what a post-backward step
    serializes — scripts/bench_strategies.py's predicted_ms column)."""
    bucket_bytes = int(bucket_mb * 1024 * 1024)
    B = census.total_bytes
    nl = census.n_leaves
    axes = list(profile.axes.items())
    links = profile.links

    def axis_plan(axis, algo, launches, op_bytes, wire, n, qbytes=0):
        link = links[axis]
        ms = (launches * link.alpha_s + wire * link.beta_s_per_byte
              + qbytes * link.quant_s_per_byte) * 1e3
        return AxisPlan(axis=axis, algorithm=algo, launches=int(launches),
                        predicted_bytes=int(op_bytes), predicted_ms=ms)

    per_axis: list[AxisPlan] = []
    n_buckets = 1
    can_overlap = name in ("ddp", "bucketed", "quantized",
                           "quantized_ring", "quantized_ring_ef",
                           "hierarchical")

    if name == "none":
        per_axis = []
    elif name in ("ddp", "bucketed", "all_reduce", "quantized",
                  "gather_scatter_symmetric", "gather_scatter",
                  "quantized_ring", "quantized_ring_ef"):
        # flat strategies: one emitted axis ('data'); on a factored
        # profile the payload crosses EVERY link at full width, so the
        # time sums the per-link costs while the operand bytes stay one
        # row (the emitted program has one axis).
        if name == "ddp":
            algo, op_bytes, launches, wire_f = "flat fused psum", B, 1, 2.0
        elif name == "bucketed":
            sizes = census.bucket_plan(bucket_bytes)
            n_buckets = len(sizes)
            algo, op_bytes, launches, wire_f = ("flat bucketed psum", B,
                                                n_buckets, 2.0)
        elif name == "all_reduce":
            algo, op_bytes, launches, wire_f = ("per-leaf sequential psum",
                                                B, nl, 2.0)
        elif name == "quantized":
            # pmax (scalar) + int32 psum per leaf: full-width wire
            algo, op_bytes, launches, wire_f = ("per-leaf int32 psum", B,
                                                2 * nl, 2.0)
        elif name == "gather_scatter_symmetric":
            # all_gather(leaf) + psum(leaf) per leaf
            algo, op_bytes, launches, wire_f = ("all_gather + masked psum",
                                                2 * B, 2 * nl, 3.0)
        elif name == "gather_scatter":
            n_tot = int(np.prod([s for _, s in axes]))
            algo = "rank-0 gather/scatter (ppermute)"
            op_bytes = 2 * (n_tot - 1) * B
            launches = 2 * (n_tot - 1) * nl
            wire_f = 2.0 * (n_tot - 1)
        else:  # the int8 rings
            sizes = census.bucket_plan(bucket_bytes)
            n_buckets = len(sizes)
            n_tot = int(np.prod([s for _, s in axes]))
            op_bytes = launches = qb = 0
            for b in sizes:
                bb, ll, qq = _quant_ring_bytes(b // 4, n_tot)
                op_bytes += bb
                launches += ll
                qb += qq
            algo = "int8 ring reduce-scatter/all-gather"
            wire_f = None  # wire == operand bytes for ppermute payloads
        # time: cross every link of the profile at the strategy's width
        ms = 0.0
        for axis, n in axes:
            if n < 2:
                continue
            link = links[axis]
            if wire_f is None:
                wire = op_bytes
            elif name == "gather_scatter":
                wire = 2.0 * (np.prod([s for _, s in axes]) - 1) * B
            else:
                wire = wire_f / 2.0 * 2.0 * B * (n - 1) / n
            ms += (launches * link.alpha_s
                   + wire * link.beta_s_per_byte) * 1e3
        if name in ("quantized_ring", "quantized_ring_ef"):
            # quantize COMPUTE happens once per hop on the device, not
            # per link crossed — charge it once, at the rate of the
            # slowest active quantizer
            ms += qb * max((links[a].quant_s_per_byte
                            for a, s in axes if s > 1), default=0.0) * 1e3
        emitted = "data" if len(axes) > 1 or axes[0][0] == "data" \
            else axes[0][0]
        per_axis = [AxisPlan(axis=emitted, algorithm=algo,
                             launches=int(launches),
                             predicted_bytes=int(op_bytes),
                             predicted_ms=ms)]
    elif name == "hierarchical":
        # the two-level reduction: slow hop is the 'dcn' axis, the fast
        # hop is whatever inner axis the profile carries ('ici' on the
        # VGG factored mesh, 'data' on the LM multislice mesh)
        sizes = {a: s for a, s in axes}
        fast = next((a for a, _ in axes if a != "dcn"), "ici")
        n_dcn, n_fast = sizes.get("dcn", 1), sizes.get(fast, 1)
        if overlap or dcn_compress in ("int8", "int4"):
            bucket_elems = [b // 4 for b in census.bucket_plan(bucket_bytes)]
        else:
            # the post-backward plain path flattens the WHOLE tree once
            bucket_elems = [B // 4]
        n_buckets = len(bucket_elems)
        costs = _two_level_axis_costs(bucket_elems, n_fast, n_dcn,
                                      dcn_compress)
        for axis, row in (("dcn", costs["dcn"]), (fast, costs["ici"])):
            ob, la, wi, qb = row
            algo = (f"{dcn_compress} ring exchange" if axis == "dcn"
                    and dcn_compress in ("int8", "int4") else
                    "shard-sized psum" if axis == "dcn" else
                    "reduce-scatter + gather")
            per_axis.append(axis_plan(axis, algo, la, ob, wi,
                                      sizes.get(axis, 1), qbytes=qb))
    else:
        return None

    ms_total = sum(ap.predicted_ms for ap in per_axis)
    launch_ms = sum(ap.launches * links.get(
        ap.axis, links[axes[0][0]]).alpha_s for ap in per_axis) * 1e3 \
        if per_axis else 0.0
    if len(axes) > 1 and per_axis and per_axis[0].axis == "data":
        # flat-on-factored: the launch term crossed every link above
        launch_ms = sum(per_axis[0].launches * links[a].alpha_s
                        for a, s in axes if s > 1) * 1e3
    wire_ms = ms_total - launch_ms
    if overlap and can_overlap and n_buckets > 0:
        # all but the last bucket's wire hides under backward compute
        ms_exposed = launch_ms + wire_ms / n_buckets
    else:
        ms_exposed = ms_total
    return {"ms_total": ms_total, "ms_exposed": ms_exposed,
            "per_axis": per_axis, "n_buckets": n_buckets}


# ---------------------------------------------------------------------------
# the route model (round 20): price hop-graphs, not strategy names


def _axis_parts(axis: str, sizes: dict) -> list[tuple[str, int]]:
    """Constituent (link, size) pairs of a hop axis.  Route enumeration
    writes joint axes as 'a+b' (a flat collective over a factored mesh
    crosses every constituent link); single axes pass through."""
    return [(a, int(sizes.get(a, 1))) for a in axis.split("+")]


def price_route(route, census: GradCensus, profile: TopologyProfile, *,
                bucket_mb: float = strat.BUCKET_CAP_MB,
                overlap: bool = False,
                intervals: dict[str, int] | None = None) -> dict:
    """Predicted cost of executing ``route`` (a ``routing.HopPlan``) for
    this census on this profile — the hop-graph generalization of
    ``predict_named``: each hop is priced with its axis' LinkModel
    alpha-beta fit plus the quantize-compute term of ring hops, payloads
    divided by every enclosing reduce-scatter.  Returns ``{"ms_total",
    "ms_exposed", "per_axis", "per_hop", "n_buckets"}`` where
    ``per_hop`` has one AxisPlan per hop (labelled ``axis:algo`` in
    route grammar) and ``per_axis`` aggregates hop rows per mesh axis —
    the inspector-comparable accounting ``plan_bytes_vs_schedule``
    cross-checks.

    ``intervals`` (round 22) prices PER-HOP local-SGD windows: a hop on
    axis ``a`` with ``intervals[a] = H`` runs once per H optimizer
    steps, so its bytes/launch-ms/wire-ms/quantize-ms rows are divided
    by H — the returned figures become amortized per-OPTIMIZER-STEP
    costs (the predicted WAN bytes/optimizer-step table the round-22
    bench pins).  Launch counts stay per-exchange.  Default None is the
    round-20 per-step accounting, untouched."""
    links = profile.links
    sizes = profile.axes
    bucket_bytes = int(bucket_mb * 1024 * 1024)
    if route.compressed or overlap:
        bucket_elems = [b // 4 for b in census.bucket_plan(bucket_bytes)]
    else:
        # the post-backward plain path flattens the whole tree once
        bucket_elems = [census.total_bytes // 4]
    n_buckets = len(bucket_elems)
    # per hop: [op_bytes, launches, launch_ms, wire_ms, quant_ms]
    acc = [[0, 0, 0.0, 0.0, 0.0] for _ in route.hops]
    for e0 in bucket_elems:
        e = e0
        stack: list[tuple[int, int]] = []
        for hi, hop in enumerate(route.hops):
            parts = _axis_parts(hop.axis, sizes)
            n = int(np.prod([ni for _, ni in parts]))
            active = [(a, ni) for a, ni in parts if ni > 1]
            if hop.kind == "a2a":
                raise ValueError(
                    "a2a hops are activation collectives priced by "
                    "choose_moe_plan (capacity census), not by the "
                    "gradient-bucket pricer")
            if hop.kind == "rs":
                padded = e + (-e) % max(n, 1)
                if n > 1 and hop.algorithm == "scatter" and active:
                    acc[hi][0] += padded * 4
                    acc[hi][1] += 1
                    acc[hi][2] += sum(links[a].alpha_s
                                      for a, _ in active) * 1e3
                    acc[hi][3] += sum(
                        padded * 4 * (ni - 1) / ni
                        * links[a].beta_s_per_byte
                        for a, ni in active) * 1e3
                # 'slice' is free: the value is already replicated
                stack.append((padded, n))
                e = padded // max(n, 1)
            elif hop.kind == "exchange":
                if not active:
                    continue  # degraded tier: nothing crosses
                if hop.bits == "f32":
                    acc[hi][0] += e * 4
                    acc[hi][1] += 1
                    acc[hi][2] += sum(links[a].alpha_s
                                      for a, _ in active) * 1e3
                    acc[hi][3] += sum(
                        2 * e * 4 * (ni - 1) / ni
                        * links[a].beta_s_per_byte
                        for a, ni in active) * 1e3
                else:
                    b, l, q = _quant_ring_bytes(e, n, hop.bits)
                    acc[hi][0] += b
                    acc[hi][1] += l
                    acc[hi][2] += l * sum(links[a].alpha_s
                                          for a, _ in active) * 1e3
                    # ppermute payloads cross every constituent link
                    acc[hi][3] += b * sum(links[a].beta_s_per_byte
                                          for a, _ in active) * 1e3
                    acc[hi][4] += q * max(links[a].quant_s_per_byte
                                          for a, _ in active) * 1e3
            else:  # 'ag'
                padded, n2 = stack.pop()
                if n2 > 1 and active:
                    acc[hi][1] += 1
                    acc[hi][2] += sum(links[a].alpha_s
                                      for a, _ in active) * 1e3
                    if _GATHER_FALLBACK:
                        acc[hi][0] += padded * 4
                        acc[hi][3] += sum(
                            2 * padded * 4 * (ni - 1) / ni
                            * links[a].beta_s_per_byte
                            for a, ni in active) * 1e3
                    else:
                        acc[hi][0] += e * 4
                        acc[hi][3] += sum(
                            e * 4 * (ni - 1)
                            * links[a].beta_s_per_byte
                            for a, ni in active) * 1e3
                e = padded
    if intervals:
        # amortize each hop's per-exchange cost over its window: H
        # optimizer steps share one exchange on this tier (launch
        # counts stay per-exchange — they describe the boundary
        # program, not the per-step average)
        for hi, hop in enumerate(route.hops):
            h = intervals.get(hop.axis, 1)
            if h > 1:
                ob, la, lm, wm, qm = acc[hi]
                acc[hi] = [ob / h, la, lm / h, wm / h, qm / h]
    per_hop: list[AxisPlan] = []
    by_axis: dict[str, list[float]] = {}
    for hop, (ob, la, lm, wm, qm) in zip(route.hops, acc):
        ms = lm + wm + qm
        per_hop.append(AxisPlan(
            axis=hop.describe(), algorithm=f"{hop.kind}/{hop.algorithm}",
            launches=int(la), predicted_bytes=int(ob), predicted_ms=ms))
        row = by_axis.setdefault(hop.axis, [0, 0, 0.0, []])
        row[0] += int(ob)
        row[1] += int(la)
        row[2] += ms
        row[3].append(hop.describe().split(":", 1)[1])
    per_axis = [AxisPlan(axis=a, algorithm="+".join(r[3]),
                         launches=int(r[1]), predicted_bytes=int(r[0]),
                         predicted_ms=r[2])
                for a, r in by_axis.items()]
    ms_total = sum(hp.predicted_ms for hp in per_hop)
    launch_ms = sum(a[2] for a in acc)
    if overlap and n_buckets > 0:
        # all but the last bucket's wire hides under backward compute
        ms_exposed = launch_ms + (ms_total - launch_ms) / n_buckets
    else:
        ms_exposed = ms_total
    return {"ms_total": ms_total, "ms_exposed": ms_exposed,
            "per_axis": per_axis, "per_hop": per_hop,
            "n_buckets": n_buckets}


def _route_label(name: str, compress: str | None,
                 profile: TopologyProfile) -> str:
    """The route-grammar description of a NAMED strategy choice — how
    the legacy choosers' outputs read as hop-graphs (the 2-level plans
    are literally executed through ``parallel/routing`` now)."""
    axes = list(profile.axes)
    flat = "+".join(axes) if len(axes) > 1 else (axes[0] if axes
                                                 else "data")
    x = f"ring[{compress}+ef]" if compress else "psum"
    if name == "hierarchical":
        fast = next((a for a in axes if a != "dcn"), "ici")
        if "dcn" in profile.axes:
            return f"{fast}:rs → dcn:{x} → {fast}:ag"
        return f"{fast}:rs → {fast}:ag"
    if name.startswith("two_level"):
        return f"data:rs → dcn:{x} → data:ag"
    if name in ("ddp", "bucketed", "flat_autodiff_psum"):
        return f"{flat}:psum"
    if name in ("quantized_ring", "quantized_ring_ef"):
        return f"{flat}:ring[int8+ef]"
    return ""


def choose_sync_plan(census: GradCensus, profile: TopologyProfile, *,
                     ladder: tuple = BUCKET_LADDER_MB,
                     overlap: bool = False,
                     max_sync_every: int = 1,
                     steps_per_loop: int | None = None) -> SyncPlan:
    """The route chooser (round 20): enumerate every hop-graph over the
    profile's axes (``routing.enumerate_routes`` — flat, every 2-level
    split, and the nested/sequential 3-level shapes on ≥3-level meshes,
    each at every slow-hop precision), price each with
    ``price_route`` at every ladder bucket size, and return the
    cheapest as an explainable routed ``SyncPlan`` (``route`` +
    ``per_hop`` populated).  Axes are ordered fastest→slowest by fitted
    inverse bandwidth, so 'nested' always reduces over the cheap links
    first.  Candidate order breaks exact ties toward the simpler route
    (enumeration emits flat, then 2-level, then 3-level).  Local-SGD
    amortization (``max_sync_every``) widens the window against the
    SLOWEST tier's hop cost — the 3-level generalization of round 18's
    dcn rule.  Deterministic given a profile (test-pinned on
    ``uniform``/``wan_dcn``/``ici_dcn_wan``)."""
    from . import routing

    fast_first = tuple(sorted(
        profile.axes,
        key=lambda a: (profile.links[a].beta_s_per_byte,
                       profile.links[a].alpha_s, a)))
    slowest = fast_first[-1]
    best: SyncPlan | None = None
    for route in routing.enumerate_routes(fast_first):
        for mb in ladder:
            pred = price_route(route, census, profile, bucket_mb=mb,
                               overlap=overlap)
            ring_bits = [h.bits for h in route.hops
                         if h.kind == "exchange" and h.bits != "f32"]
            plan = SyncPlan(
                strategy="routed", bucket_mb=mb,
                dcn_compress=ring_bits[-1] if ring_bits else None,
                dcn_size=profile.axes.get("dcn", 1), overlap=overlap,
                predicted_ms=pred["ms_exposed"],
                per_axis=tuple(pred["per_axis"]),
                profile_source=profile.source,
                census_bytes=census.total_bytes,
                route=route.describe(), per_hop=tuple(pred["per_hop"]))
            if max_sync_every > 1 and len(fast_first) >= 3:
                # round 22: ≥3-tier meshes price the interval PER HOP
                # (dcn H × wan H), with the outer-opt recommendation
                plan = _route_intervals(
                    plan, route, census, profile, max_sync_every,
                    overlap=overlap, fast_first=fast_first,
                    align=steps_per_loop)
            else:
                plan = _interval_for(plan, max_sync_every,
                                     align=steps_per_loop,
                                     slow_axis=slowest)
            if best is None or plan.predicted_ms < best.predicted_ms - 1e-12:
                best = plan
    assert best is not None
    _emit_plan(best, side="routed")
    return best


# ---------------------------------------------------------------------------
# the MoE dispatch chooser (round 21)


def _a2a_row_bytes(d: int, bits: str) -> float:
    """Wire bytes one d-element f32 token row occupies on the expert
    all-to-all at ``bits`` — the routed executor's exact format: f32 is
    full-width; int8/int4 ship the quantized lanes (nibble pairs at
    int4) plus the row's f32 scale bitcast onto the same row."""
    if bits == "f32":
        return 4.0 * d
    if bits == "int8":
        return d + 4.0
    if bits == "int4":
        return d / 2.0 + 4.0
    raise ValueError(f"unknown dispatch bits {bits!r}")


@dataclass(frozen=True)
class MoePlan:
    """The MoE dispatch chooser's explainable output: which wire width
    the expert all-to-alls run at, why (every candidate priced in
    ``per_bits``), and the predicted wire bytes the accounting
    inspectors (``plan_bytes_vs_schedule(by_hop=True)``) hold the
    compiled program to.  ``sync_every`` exists for inspector API parity
    with :class:`SyncPlan` (dispatch runs every step)."""

    dispatch_bits: str
    axis: str
    predicted_ms: float
    per_bits: tuple = ()         # one priced AxisPlan row per candidate
    per_hop: tuple = ()          # the chosen row(s), inspector-comparable
    per_axis: tuple = ()         # alias of per_hop (axis-level view)
    profile_source: str = ""
    dispatch_bytes: int = 0      # per-step wire bytes at the chosen width
    sync_every: int = 1
    route: str = ""              # 'expert:a2a@<bits>'

    def summary(self) -> dict:
        return {
            "dispatch_bits": self.dispatch_bits, "axis": self.axis,
            "predicted_ms": round(self.predicted_ms, 4),
            "dispatch_bytes": self.dispatch_bytes, "route": self.route,
            "profile_source": self.profile_source,
            "bytes_by_bits": {p.axis: p.predicted_bytes
                              for p in self.per_bits},
            "ms_by_bits": {p.axis: round(p.predicted_ms, 4)
                           for p in self.per_bits},
        }

    def table(self) -> str:
        rows = ["| dispatch | wire bytes/step | predicted ms |",
                "|---|---|---|"]
        for p in self.per_bits:
            pick = (" ←" if p.axis.rsplit("@", 1)[1] == self.dispatch_bits
                    else "")
            rows.append(f"| {p.axis} | {p.predicted_bytes} | "
                        f"{p.predicted_ms:.4f}{pick} |")
        return "\n".join(rows)


def choose_moe_plan(profile: TopologyProfile, *, axis: str, tokens: int,
                    d_model: int, n_experts: int,
                    capacity_factor: float = 2.0, top_k: int = 1,
                    bits_options: tuple = ("f32", "int8"),
                    a2a_per_step: int = 4) -> MoePlan:
    """Price the expert dispatch/combine all-to-alls over ``profile``'s
    ``axis`` link at every candidate wire width and return the cheapest
    as an explainable :class:`MoePlan` (round 21).

    The census is the MoE layer's own capacity arithmetic: each step
    moves the full ``(E, C, D)`` buffer — ``E * C`` rows of
    ``_a2a_row_bytes(d_model, bits)`` with ``C = min(max(1, ceil(T *
    top_k * capacity_factor / E)), T)`` — once per all-to-all, and a
    train step issues ``a2a_per_step`` of them (dispatch + combine
    forward, their transposes backward: 4 per MoE layer; pass 2 to
    price a forward-only program, or scale by the MoE layer count).
    Cost per width follows the calibrated alpha-beta-quant fit:
    ``launches * alpha + wire_bytes * (n-1)/n * beta`` plus — for
    compressed widths — the quantize/dequantize passes over the f32
    payload at the link's ``quant_s_per_byte``, priced at the actual
    width via ``_QUANT_PASSES`` (the round-11 lesson: the wire saving
    is only real if the compute that buys it is in the model).  f32
    wins exact ties (strict-improvement argmin, candidate order) —
    the chooser declines compression on quantize-bound links
    (``quant_bound`` preset) and fast uniform meshes, and takes int8 on
    slow/WAN expert links (matrix pinned in tests/test_a2a.py).  int4
    stays OUT of the default ladder — its routed-token flip rate has
    not cleared the 0.02 gate at small d_model — pass
    ``bits_options=("f32", "int8", "int4")`` to let the pricer consider
    it."""
    import math

    if axis not in profile.axes:
        raise ValueError(
            f"profile has no {axis!r} axis (axes: "
            f"{sorted(profile.axes)}) — calibrate the mesh the experts "
            f"actually shard over")
    n = int(profile.axes[axis])
    link = profile.links[axis]
    cap = min(max(1, math.ceil(tokens * top_k * capacity_factor
                               / n_experts)), tokens)
    rows = n_experts * cap
    wire_factor = (n - 1) / n if n > 1 else 0.0
    per_bits: list[AxisPlan] = []
    for bits in bits_options:
        payload = rows * _a2a_row_bytes(d_model, bits)
        launch_ms = link.alpha_s * 1e3 * a2a_per_step
        wire_ms = (payload * wire_factor * link.beta_s_per_byte
                   * 1e3 * a2a_per_step)
        quant_ms = 0.0
        if bits != "f32":
            quant_ms = (rows * d_model * 4.0 * _QUANT_PASSES[bits]
                        * link.quant_s_per_byte * 1e3 * a2a_per_step)
        per_bits.append(AxisPlan(
            axis=f"{axis}:a2a@{bits}", algorithm="a2a",
            launches=a2a_per_step,
            predicted_bytes=int(payload * a2a_per_step),
            predicted_ms=launch_ms + wire_ms + quant_ms))
    best = per_bits[0]
    for cand in per_bits[1:]:
        if cand.predicted_ms < best.predicted_ms - 1e-12:
            best = cand
    bits = best.axis.rsplit("@", 1)[1]
    # per_hop speaks the PROFILE's (mesh) axis name so the inspector can
    # match the compiled program's collectives; ``route`` speaks the
    # declarative grammar ('expert' tier) like every HopPlan.
    plan = MoePlan(
        dispatch_bits=bits, axis=axis, predicted_ms=best.predicted_ms,
        per_bits=tuple(per_bits), per_hop=(best,), per_axis=(best,),
        profile_source=profile.source, dispatch_bytes=best.predicted_bytes,
        route=f"expert:a2a@{bits}")
    _emit_plan(plan, side="moe")
    return plan


# ---------------------------------------------------------------------------
# the chooser


def _mk_plan(name, pred, *, bucket_mb, dcn_compress, dcn_size, overlap,
             profile, census) -> SyncPlan:
    return SyncPlan(
        strategy=name, bucket_mb=bucket_mb, dcn_compress=dcn_compress,
        dcn_size=dcn_size, overlap=overlap,
        predicted_ms=pred["ms_exposed"],
        per_axis=tuple(pred["per_axis"]),
        profile_source=profile.source, census_bytes=census.total_bytes,
        route=_route_label(name, dcn_compress, profile))


def _route_intervals(plan: SyncPlan, route, census: GradCensus,
                     profile: TopologyProfile, max_sync_every: int, *,
                     overlap: bool, fast_first: tuple,
                     align: int | None = None) -> SyncPlan:
    """Per-TIER interval assignment for ≥3-level routes (round 22, the
    WAN generalization of ``_interval_for``): walking tiers
    fastest→slowest, each slow tier's window H doubles (powers of 2,
    monotone — a slower tier never syncs more often than a faster one)
    while its amortized per-step cost still dominates everything that
    runs more often, then the route re-prices with
    ``price_route(intervals=...)`` so the candidate competes on the
    amortized figure.  The plan's ``sync_every`` becomes the BASE
    (minimum assigned) interval — the trainer's compiled boundary
    cadence — with the wider tiers recorded in ``interval_by_hop`` (the
    per-slice-window recommendation), and ``outer_opt`` set to
    "nesterov": a widened window wants the DiLoCo outer step (the
    measured wider-window-at-matched-quality band,
    tests/test_diloco.py).  ``per_axis`` stays per-exchange, like
    ``_interval_for``."""
    if max_sync_every <= 1:
        return plan
    axis_ms = {ap.axis: ap.predicted_ms for ap in plan.per_axis}
    intervals: dict[str, int] = {}
    h_floor = 1
    for i, a in enumerate(fast_first):
        if i == 0 or axis_ms.get(a, 0.0) <= 0.0:
            continue
        faster = sum(axis_ms[b] / intervals.get(b, 1)
                     for b in fast_first[:i] if b in axis_ms)
        h = h_floor
        while (2 * h <= max_sync_every
               and (align is None or align % (2 * h) == 0)
               and axis_ms[a] / h > faster):
            h *= 2
        if h > 1:
            intervals[a] = h
            h_floor = h
    if not intervals:
        return plan
    pred = price_route(route, census, profile, bucket_mb=plan.bucket_mb,
                       overlap=overlap, intervals=intervals)
    return dataclasses.replace(
        plan, sync_every=min(intervals.values()),
        predicted_ms=pred["ms_exposed"],
        per_hop=tuple(pred["per_hop"]),
        interval_by_hop=tuple(sorted(intervals.items())),
        outer_opt="nesterov")


def _interval_for(plan: SyncPlan, max_sync_every: int,
                  *, align: int | None = None,
                  slow_axis: str = "dcn") -> SyncPlan:
    """Attach the local-SGD interval dimension (round 18) to a candidate
    plan: widen the window H (powers of 2, up to ``max_sync_every``)
    while the slow hop's AMORTIZED cost still dominates the per-step
    fast-hop cost — once dcn/H drops at or below the ici term, further
    widening shrinks an already-subdominant term while the staleness
    risk keeps growing, so the admission rule stops there.  Plans
    without a dcn row (flat strategies, single-slice meshes) never
    widen: local-SGD windows only attach to the two-level family
    (``strategies.require_sync_window``).  ``align`` (the VGG trainer's
    ``steps_per_loop``) constrains H to divide it, so every compiled
    dispatch ends on a window boundary.  ``predicted_ms`` becomes the
    amortized per-step figure; the per-axis rows stay per-exchange."""
    if max_sync_every <= 1:
        return plan
    dcn = plan.axis(slow_axis)
    if dcn is None or dcn.predicted_ms <= 0.0:
        return plan
    ici_ms = sum(ap.predicted_ms for ap in plan.per_axis
                 if ap.axis != slow_axis)
    h = 1
    while (2 * h <= max_sync_every
           and (align is None or align % (2 * h) == 0)
           and dcn.predicted_ms / h > ici_ms):
        h *= 2
    if h == 1:
        return plan
    # the raw dcn row now bills once per H steps; the exposed figure
    # keeps whatever overlap discount the base prediction already took,
    # minus the amortized share of the slow hop
    amortized = max(plan.predicted_ms
                    - dcn.predicted_ms * (1.0 - 1.0 / h), 0.0)
    return dataclasses.replace(plan, sync_every=h, predicted_ms=amortized)


def choose_train_plan(census: GradCensus, profile: TopologyProfile, *,
                      dcn_size: int = 1, overlap: bool = False,
                      max_sync_every: int = 1,
                      steps_per_loop: int | None = None,
                      ladder: tuple = BUCKET_LADDER_MB) -> SyncPlan:
    """Pick the VGG trainer's sync plan: flat fused psum (``ddp``) vs
    bucketed psum vs the int8+EF ring on flat topologies; flat psum vs
    two-level (``hierarchical``) with an optional int8 or int4 DCN hop
    on factored ones — each at every ``ladder`` bucket size — by
    minimum predicted exposed sync time.  Pure function of its arguments
    (deterministic given a profile; candidate order breaks exact ties
    toward the simpler plan).  A caller with a pinned bucket size
    passes a one-rung ladder so the recorded prediction describes the
    config that will actually run.

    ``max_sync_every`` (round 18, default 1 so relaxation stays opt-in)
    lets the two-level candidates amortize their slow hop over a
    local-SGD window (``_interval_for``): candidates compete on the
    AMORTIZED per-step figure, so a windowed hierarchical plan can beat
    the flat psum a per-step comparison would have picked."""
    factored = dcn_size > 1 and "dcn" in profile.axes
    default_mb = float(ladder[0])
    candidates: list[tuple[str, str | None, float]] = []
    if factored:
        candidates.append(("ddp", None, default_mb))
        for mb in ladder:
            candidates.append(("hierarchical", None, mb))
            candidates.append(("hierarchical", "int8", mb))
            candidates.append(("hierarchical", "int4", mb))
        if overlap:
            for mb in ladder:
                candidates.append(("bucketed", None, mb))
    else:
        candidates.append(("ddp", None, default_mb))
        for mb in ladder:
            candidates.append(("bucketed", None, mb))
            candidates.append(("quantized_ring_ef", None, mb))
    best: SyncPlan | None = None
    for name, compress, mb in candidates:
        pred = predict_named(name, census, profile, bucket_mb=mb,
                             dcn_compress=compress, overlap=overlap)
        if pred is None:
            continue
        plan = _mk_plan(name, pred, bucket_mb=mb, dcn_compress=compress,
                        dcn_size=dcn_size if name == "hierarchical" else 1,
                        overlap=overlap, profile=profile, census=census)
        if name == "hierarchical":
            plan = _interval_for(plan, max_sync_every,
                                 align=steps_per_loop)
        if best is None or plan.predicted_ms < best.predicted_ms - 1e-12:
            best = plan
    assert best is not None
    return best


def choose_lm_plan(census: GradCensus, profile: TopologyProfile, *,
                   dcn_size: int = 1, overlap: bool = False,
                   grad_accum: int = 1, allow_compress: bool = True,
                   max_sync_every: int = 1,
                   ladder: tuple = BUCKET_LADDER_MB) -> SyncPlan:
    """Pick the LM trainer's sync knobs.  The LM data-axis algorithm is
    structurally fixed (autodiff cotangent psums on flat meshes, the
    explicit two-level reduction when ``dcn_size > 1``); what the
    profile decides is the slow-hop compression (none vs int8+EF vs
    int4+EF — ``allow_compress=False`` removes the compressed
    candidates for configs whose step has no sync-state channel, e.g.
    the pipeline paths) and the streaming bucket size.  Deterministic
    given a profile.

    Stated approximation: leaves are costed as if they all ride the
    grouped two-level path; under fsdp the shard-sized leaves skip the
    ici reduce-scatter/gather and ring the shard directly over dcn —
    same dcn magnitude, slightly overstated ici bytes (the per-axis
    BYTE cross-check in debug.assert_plan_bytes_match is scoped to the
    VGG programs, where the prediction is exact).

    ``max_sync_every`` (round 18) admits local-SGD windows on the
    two-level candidates (``_interval_for`` — default 1, opt-in), so a
    WAN-grade dcn hop can amortize over H local steps instead of being
    paid per step."""
    if dcn_size <= 1 or "dcn" not in profile.axes:
        pred = predict_named("ddp", census, profile, overlap=overlap)
        plan = _mk_plan("flat_autodiff_psum", pred,
                        bucket_mb=float(ladder[0]),
                        dcn_compress=None, dcn_size=1, overlap=overlap,
                        profile=profile, census=census)
        return plan
    best: SyncPlan | None = None
    for compress in ((None, "int8", "int4") if allow_compress else (None,)):
        for mb in ladder:
            pred = predict_named("hierarchical", census, profile,
                                 bucket_mb=mb, dcn_compress=compress,
                                 overlap=overlap and grad_accum == 1)
            plan = _mk_plan(
                "two_level" if compress is None
                else f"two_level_{compress}",
                pred, bucket_mb=mb, dcn_compress=compress,
                dcn_size=dcn_size, overlap=overlap,
                profile=profile, census=census)
            plan = _interval_for(plan, max_sync_every)
            if best is None or plan.predicted_ms < best.predicted_ms - 1e-12:
                best = plan
    assert best is not None
    return best


# ---------------------------------------------------------------------------
# the memory chooser (round 17): activation bytes vs recompute seconds


# Rung order = preference under exact price ties: no knob before either
# knob, the streamed head before block remat (it spends one logits
# recompute for a V-sized saving), selective before full (it keeps the
# flash kernel's work).
MEMORY_RUNGS = (
    ("none", "dense"),
    ("none", "chunked"),
    ("selective", "dense"),
    ("selective", "chunked"),
    ("full", "dense"),
    ("full", "chunked"),
)


@dataclass(frozen=True)
class MemoryPlan:
    """The memory chooser's output: which (remat, loss_impl) rung and
    microbatch to run, with the prediction that justified it.
    ``predicted_bytes`` is the accountant's per-microbatch activation
    footprint (utils.memacct — census-verified); ``recompute_ms`` is the
    per-step compute the rung spends re-producing activations, at the
    profile's calibrated rate; ``considered`` carries every rung
    evaluated at the chosen microbatch (auditability, printable via
    ``table()``)."""

    remat: str
    loss_impl: str
    microbatch: int
    n_micro: int
    predicted_bytes: int
    budget_bytes: int
    recompute_ms: float
    profile_source: str
    considered: tuple = ()

    def summary(self) -> dict:
        """Compact JSON-able form (the bench's lm_memory_plan shape)."""
        return {"remat": self.remat, "loss_impl": self.loss_impl,
                "microbatch": self.microbatch, "n_micro": self.n_micro,
                "predicted_bytes": self.predicted_bytes,
                "budget_bytes": self.budget_bytes,
                "recompute_ms": round(self.recompute_ms, 4),
                "profile": self.profile_source}

    def table(self) -> str:
        """Printable explanation: the decision line + one row per rung
        evaluated at the chosen microbatch."""
        lines = [f"MemoryPlan: remat={self.remat} "
                 f"loss_impl={self.loss_impl} "
                 f"microbatch={self.microbatch} (x{self.n_micro}) "
                 f"predicted {self.predicted_bytes / 1e6:.2f} MB of "
                 f"{self.budget_bytes / 1e6:.2f} MB budget, "
                 f"recompute {self.recompute_ms:.3f} ms/step "
                 f"(profile {self.profile_source})",
                 "| remat | loss_impl | MB | recompute ms | fits |",
                 "|---|---|---|---|---|"]
        for remat, li, act, ms, fits in self.considered:
            lines.append(f"| {remat} | {li} | {act / 1e6:.2f} | "
                         f"{ms:.3f} | {'yes' if fits else 'no'} |")
        return "\n".join(lines)


def choose_lm_memory_plan(model, profile: TopologyProfile, *,
                          batch: int, seq: int,
                          memory_budget_bytes: int,
                          dtype_bytes: int = 4,
                          tp: int = 1, sp: int = 1) -> MemoryPlan:
    """Pick the LM trainer's activation-memory knobs: the largest
    microbatch (descending divisors of ``batch``) at which ANY
    (remat, loss_impl) rung's predicted activation footprint
    (``utils.memacct.predict_activation_bytes``) fits
    ``memory_budget_bytes``, then the cheapest fitting rung by
    recompute price — ``predict_recompute_bytes`` charged at the
    profile's calibrated ``recompute_s_per_byte`` (the
    ``quant_s_per_byte`` precedent: both sides of the trade in
    seconds).  Microbatch outranks rung because splitting the batch
    serializes accumulation steps — re-running a forward is cheaper
    than running the whole step twice.  Pure function of its arguments
    (deterministic given a profile; rung order breaks exact ties toward
    the simpler knob).  Refuses loudly when even the smallest
    microbatch at the thriftiest rung overflows the budget."""
    if memory_budget_bytes <= 0:
        raise ValueError(
            f"memory_budget_bytes must be positive, got "
            f"{memory_budget_bytes}")
    from ..utils import memacct

    rate = profile.recompute_s_per_byte
    floor_bytes = None
    for m in sorted((m for m in range(1, batch + 1) if batch % m == 0),
                    reverse=True):
        n_micro = batch // m
        rows = []
        for remat, li in MEMORY_RUNGS:
            act = memacct.predict_activation_bytes(
                model, batch=m, seq=seq, remat=remat, loss_impl=li,
                dtype_bytes=dtype_bytes, tp=tp, sp=sp)
            rec = memacct.predict_recompute_bytes(
                model, batch=m, seq=seq, remat=remat, loss_impl=li,
                dtype_bytes=dtype_bytes, tp=tp, sp=sp)
            ms = rec * n_micro * rate * 1e3
            rows.append((remat, li, act, ms, act <= memory_budget_bytes))
        floor_bytes = min(r[2] for r in rows) if floor_bytes is None \
            else min(floor_bytes, min(r[2] for r in rows))
        fitting = [(r[3], i, r) for i, r in enumerate(rows) if r[4]]
        if not fitting:
            continue
        _, _, (remat, li, act, ms, _) = min(fitting)
        plan = MemoryPlan(
            remat=remat, loss_impl=li, microbatch=m, n_micro=n_micro,
            predicted_bytes=act, budget_bytes=memory_budget_bytes,
            recompute_ms=ms, profile_source=profile.source,
            considered=tuple(rows))
        tel = telemetry.active()
        if tel is not None:
            tel.event("memory_plan", phase="autotune", side="lm",
                      **plan.summary())
        return plan
    raise ValueError(
        f"no (remat, loss_impl, microbatch) configuration fits "
        f"memory_budget_bytes={memory_budget_bytes}: even microbatch=1 "
        f"under remat='full' + loss_impl='chunked' needs "
        f"{floor_bytes} predicted activation bytes "
        f"(model d={model.d_model} L={model.n_layers} "
        f"V={model.vocab_size}, seq={seq}) — raise the budget, shorten "
        f"the sequence, or shard the model further")


# ---------------------------------------------------------------------------
# config resolution (the ``strategy="auto"`` / ``sync_plan="auto"`` entry)


def train_topology_axes(dcn_size: int, n_devices: int) -> dict[str, int]:
    """The link topology a TrainConfig describes: ``dcn_size > 1`` (and
    divisible) factors the fleet into Mesh(('dcn', 'ici')); otherwise
    one flat 'data' link."""
    if dcn_size > 1 and n_devices % dcn_size == 0 and n_devices > dcn_size:
        return {"dcn": dcn_size, "ici": n_devices // dcn_size}
    return {"data": n_devices}


def resolve_train_auto(cfg, *, num_devices: int | None = None):
    """Resolve ``TrainConfig(strategy="auto")``: calibrate-or-load the
    profile (``cfg.autotune_profile`` injects one), census the model's
    grad tree, choose, and return ``(resolved_cfg, SyncPlan)`` — the
    resolved config names an existing strategy plus its knobs, so the
    Trainer routes through the bitwise-pinned named paths unchanged."""
    import jax

    from ..models import vgg

    if cfg.dcn_compress is not None:
        raise ValueError(
            "strategy='auto' resolves dcn_compress itself; an explicit "
            "dcn_compress alongside auto is ambiguous — set one, not "
            "both (a named strategy honors the explicit knob)")
    if cfg.sync_every != 1:
        raise ValueError(
            "strategy='auto' resolves sync_every itself (within "
            "max_sync_every); an explicit sync_every alongside auto is "
            "ambiguous — pin the strategy to pin the window")
    if cfg.outer_opt is not None:
        raise ValueError(
            "strategy='auto' resolves the boundary update itself; an "
            "explicit outer_opt alongside auto is ambiguous — pin the "
            "strategy to pin the outer optimizer")
    n = num_devices if num_devices is not None else len(jax.devices())
    if n < 2:
        plan = SyncPlan(strategy="none", bucket_mb=float(strat.BUCKET_CAP_MB),
                        dcn_compress=None, dcn_size=1, overlap=False,
                        predicted_ms=0.0, per_axis=(),
                        profile_source="single-device", census_bytes=0)
        _emit_plan(plan, side="train")
        return dataclasses.replace(cfg, strategy="none", overlap=False,
                                   dcn_compress=None), plan
    census = grad_census(jax.eval_shape(
        lambda k: vgg.init(k, cfg.model)[0], jax.random.key(0)))
    axes = train_topology_axes(cfg.dcn_size, n)
    profile = get_profile(cfg.autotune_profile, axes)
    # an explicitly pinned bucket size constrains the ladder, so the
    # recorded prediction describes the config that actually runs
    ladder = (BUCKET_LADDER_MB if cfg.overlap_bucket_mb is None
              else (float(cfg.overlap_bucket_mb),))
    # local-SGD windows only run on the non-overlapped window builder
    # (require_sync_window): with overlap on, the interval stays 1
    plan = choose_train_plan(census, profile,
                             dcn_size=axes.get("dcn", 1),
                             overlap=cfg.overlap,
                             max_sync_every=(1 if cfg.overlap
                                             else cfg.max_sync_every),
                             steps_per_loop=cfg.steps_per_loop,
                             ladder=ladder)
    resolved = dataclasses.replace(
        cfg, strategy=plan.strategy,
        dcn_size=plan.dcn_size if plan.strategy == "hierarchical"
        else cfg.dcn_size,
        dcn_compress=plan.dcn_compress,
        sync_every=plan.sync_every,
        outer_opt=plan.outer_opt,
        overlap_bucket_mb=(cfg.overlap_bucket_mb
                           if cfg.overlap_bucket_mb is not None
                           else plan.bucket_mb))
    _emit_plan(plan, side="train")
    return resolved, plan


def _emit_plan(plan: "SyncPlan", *, side: str) -> None:
    """The chosen SyncPlan on the unified timeline (round 13): the
    explainable decision — strategy/bucket/compression + predicted ms —
    as one 'autotune' event, so a run's telemetry records WHY its sync
    path looks the way it does."""
    tel = telemetry.active()
    if tel is not None:
        tel.event("sync_plan", phase="autotune", side=side,
                  **plan.summary())


def lm_topology_axes(cfg) -> dict[str, int]:
    """The LM config's data-sync links: the factored (dcn, data) pair on
    multislice configs, one flat 'data' link otherwise.  (tp/sp/ep axes
    carry activation traffic the sync chooser does not own.)"""
    if cfg.dcn_size > 1:
        return {"dcn": cfg.dcn_size, "data": cfg.dp // cfg.dcn_size}
    return {"data": max(cfg.dp, 1)}


def resolve_lm_auto(cfg):
    """Resolve ``LMTrainConfig(sync_plan="auto")`` into explicit
    ``dcn_compress`` / ``bucket_mb`` knobs (the LM side's tunables);
    returns ``(resolved_cfg, SyncPlan)``."""
    import jax

    from ..models import transformer as tfm

    if cfg.dcn_compress is not None:
        raise ValueError(
            "sync_plan='auto' resolves dcn_compress itself; an explicit "
            "dcn_compress alongside auto is ambiguous — set one, not "
            "both (drop sync_plan to pin the knob by hand)")
    if cfg.sync_every != 1:
        raise ValueError(
            "sync_plan='auto' resolves sync_every itself (within "
            "max_sync_every); an explicit sync_every alongside auto is "
            "ambiguous — drop sync_plan to pin the window by hand")
    if cfg.outer_opt is not None:
        raise ValueError(
            "sync_plan='auto' resolves the boundary update itself; an "
            "explicit outer_opt alongside auto is ambiguous — drop "
            "sync_plan to pin the outer optimizer by hand")
    census = grad_census(jax.eval_shape(
        lambda k: tfm.init(k, cfg.model), jax.random.key(0)))
    axes = lm_topology_axes(cfg)
    profile = get_profile(cfg.autotune_profile, axes)
    # windows require the windowed step family: no pipeline, no grad
    # accumulation (require_sync_window) — gate the interval dimension
    # rather than choose a plan the trainer would then refuse
    windowable = (cfg.pp_size == 0 and cfg.pp == 1
                  and cfg.grad_accum == 1 and cfg.dcn_size > 1)
    plan = choose_lm_plan(
        census, profile, dcn_size=cfg.dcn_size, overlap=cfg.overlap,
        grad_accum=cfg.grad_accum,
        # the pipeline steps have no sync-state channel (validate_lm_cfg
        # rejects dcn_compress there): keep int8 out of the candidates
        # instead of choosing a plan the trainer would then refuse
        allow_compress=cfg.pp_size == 0 and cfg.pp == 1,
        max_sync_every=cfg.max_sync_every if windowable else 1,
        ladder=(BUCKET_LADDER_MB if cfg.bucket_mb is None
                else (float(cfg.bucket_mb),)))
    resolved = dataclasses.replace(
        cfg, sync_plan=None, dcn_compress=plan.dcn_compress,
        sync_every=plan.sync_every,
        outer_opt=plan.outer_opt,
        bucket_mb=cfg.bucket_mb if cfg.bucket_mb is not None
        else plan.bucket_mb)
    _emit_plan(plan, side="lm")
    return resolved, plan


def resolve_lm_route(cfg):
    """Resolve ``LMTrainConfig(sync_route=...)`` — the hand-pinned
    routed surface (round 21, the round-20 follow-up) — into the
    explicit knobs the LM sync machinery executes; returns
    ``(resolved_cfg, HopPlan)``.

    The same resolve-to-named-knobs mechanism as ``sync_plan='auto'``:
    parse the route (``routing.parse_route``), refuse what the trainer
    cannot run (``strategies.require_lm_route`` — wrong shapes for this
    topology, pp, combining with auto or an explicit dcn_compress),
    and translate the dcn hop's wire format into ``dcn_compress``.
    Round 20 already rebuilt ``_two_level_sync`` on
    ``routing.execute``, so the accepted routes ARE the programs the
    explicit knobs compile — a routed config trains BITWISE-identically
    to the config it names (parser + equivalence pinned in
    tests/test_a2a.py)."""
    from . import routing
    from .strategies import require_lm_route

    plan = routing.parse_route(cfg.sync_route)
    require_lm_route(plan, dcn=cfg.dcn_size > 1,
                     pp=cfg.pp > 1 or cfg.pp_size > 0,
                     dcn_compress=cfg.dcn_compress,
                     sync_plan=cfg.sync_plan)
    ring_bits = [h.bits for h in plan.hops
                 if h.kind == "exchange" and h.bits != "f32"]
    resolved = dataclasses.replace(
        cfg, sync_route=None,
        dcn_compress=ring_bits[0] if ring_bits else None)
    tel = telemetry.active()
    if tel is not None:
        tel.event("sync_plan", phase="autotune", side="lm_route",
                  route=plan.describe(),
                  dcn_compress=ring_bits[0] if ring_bits else None)
    return resolved, plan
