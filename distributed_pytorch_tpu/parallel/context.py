"""Ring attention: sequence/context parallelism over a mesh axis.

The reference has no sequence dimension at all (SURVEY.md section 5: its
parallelism inventory is data-parallel only); this module is the long-context
capability the TPU framework adds.  Sequences are sharded over a named mesh
axis ``seq``; each device holds a chunk of Q/K/V.  Attention over the full
sequence is computed in ``n = axis_size(seq)`` ring steps:

  step t: attend my Q chunk against the K/V chunk that started on device
  ``(my - t) mod n``, then pass my current K/V chunk to the next neighbor
  with ``lax.ppermute`` (XLA lowers this to ICI neighbor exchange, which
  overlaps with the attention compute of the current step).

Partial results are merged with the online-softmax rule — each step yields a
normalized chunk output plus its row logsumexp; two partials combine by
logaddexp-weighted averaging.  The whole thing is plain differentiable JAX
(``ppermute``'s transpose is ``ppermute``), so one ``jax.grad`` produces the
backward ring automatically.

Two sequence layouts:

- ``contiguous``: device r holds global positions [r*S_loc, (r+1)*S_loc).
  Simple, but causally imbalanced: ring steps whose source chunk is later
  are fully masked, yet run in SPMD lockstep — about half the attention
  FLOPs are wasted.
- ``zigzag`` (default for causal): the sequence is cut into 2n chunks and
  device r holds chunks [r, 2n-1-r] concatenated.  Every device then has
  exactly the same causal work at every ring step — the diagonal step is
  one local causal attention, and each of the n-1 ring steps is exactly two
  half-chunk full attentions (either both q-halves against the early k-half,
  or the late q-half against both k-halves) — no masked-out compute at all.
  Callers lay out tokens with :func:`zigzag_permutation` and positions with
  :func:`zigzag_positions`.

Per-chunk attention uses either the XLA reference (``impl='reference'``) or
the Pallas flash kernel (``impl='flash'``, ops/attention.py) — the flash
path returns its logsumexp as a differentiable output, so the merge (and its
backward, which sends a cotangent into lse) works identically for both.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.attention import NEG_INF, attention_reference, flash_attention
# load the runtime-compat shims (axis_size/pcast polyfills on
# legacy jax) before anything in this module traces
from ..utils import compat as _compat  # noqa: F401

Array = jax.Array


# ---------------------------------------------------------------------------
# Zigzag layout helpers (host-side; used by the data path and tests)
# ---------------------------------------------------------------------------

def zigzag_permutation(n: int, s: int) -> np.ndarray:
    """Index permutation laying a length-``s`` sequence out for an n-way
    zigzag ring: position j of the permuted sequence holds original position
    ``perm[j]``.  Shard the permuted sequence contiguously (P over the seq
    axis) and device r ends up with chunks [r, 2n-1-r].  ``s`` must divide
    into 2n equal chunks."""
    if s % (2 * n):
        raise ValueError(f"sequence length {s} not divisible into {2 * n} "
                         f"zigzag chunks")
    c = s // (2 * n)
    idx = []
    for r in range(n):
        idx.append(np.arange(r * c, (r + 1) * c))
        idx.append(np.arange((2 * n - 1 - r) * c, (2 * n - r) * c))
    return np.concatenate(idx)


def inverse_zigzag_permutation(n: int, s: int) -> np.ndarray:
    """Inverse of :func:`zigzag_permutation` (restores original order)."""
    perm = zigzag_permutation(n, s)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(s)
    return inv


def zigzag_positions(me: Array | int, n: int, s_local: int) -> Array:
    """Global positions of this device's zigzag chunk pair, (s_local,).

    Device ``me`` holds chunk ``me`` then chunk ``2n-1-me``, each of length
    s_local/2 — this is what rotary embeddings must see as absolute
    positions."""
    c = s_local // 2
    lo = me * c + jnp.arange(c)
    hi = (2 * n - 1 - me) * c + jnp.arange(c)
    return jnp.concatenate([lo, hi])


# ---------------------------------------------------------------------------
# Online-softmax merge
# ---------------------------------------------------------------------------

def _merge(o1: Array, lse1: Array, o2: Array, lse2: Array):
    """Combine two normalized partial attentions (online-softmax merge).

    ``o_i`` are (B, H, S, D) outputs normalized within their own key chunk;
    ``lse_i`` are their (B, H, S) logsumexps.  Fully-masked partials carry
    lse ~= NEG_INF and vanish smoothly (finite large-negative, no NaNs).
    """
    lse = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse)[..., None]
    w2 = jnp.exp(lse2 - lse)[..., None]
    return o1 * w1 + o2 * w2, lse


def _attn(q: Array, k: Array, v: Array, *, causal: bool, sm_scale: float,
          impl: str):
    """One chunk-pair attention returning (o_f32, lse) for the merge."""
    if impl == "flash":
        o, lse = flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                                 with_lse=True)
    else:
        o, lse = attention_reference(q, k, v, causal=causal,
                                     sm_scale=sm_scale, with_lse=True)
    return o.astype(jnp.float32), lse


# ---------------------------------------------------------------------------
# Ring attention
# ---------------------------------------------------------------------------

def ring_attention(
    q: Array, k: Array, v: Array, axis: str, *,
    causal: bool = True, sm_scale: float | None = None,
    impl: str = "reference", layout: str = "contiguous",
) -> Array:
    """Attention over a sequence sharded across mesh axis ``axis``.

    Args are this device's chunks, (B, H, S_local, D).  Equivalent (tested)
    to full attention over the concatenated sequence, with chunks laid out
    per ``layout`` ('contiguous' in axis-index order, or 'zigzag' — see
    module docstring; non-causal attention is key-order invariant, so
    layout only matters for ``causal``).  Peak score memory per device is
    O(S_local^2) per ring step with the reference impl, O(block^2) with
    flash — the blockwise-attention memory saving that makes million-token
    sequences feasible.
    """
    if impl not in ("reference", "flash"):
        raise ValueError(f"impl must be 'reference' or 'flash', got {impl!r}")
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"layout must be 'contiguous' or 'zigzag', "
                         f"got {layout!r}")
    n = lax.axis_size(axis)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if n == 1:
        o, _ = _attn(q, k, v, causal=causal, sm_scale=sm_scale, impl=impl)
        return o.astype(q.dtype)
    if causal and layout == "zigzag":
        return _ring_zigzag(q, k, v, axis, n=n, sm_scale=sm_scale, impl=impl)
    return _ring_contiguous(q, k, v, axis, n=n, causal=causal,
                            sm_scale=sm_scale, impl=impl)


def _ring_contiguous(q, k, v, axis, *, n, causal, sm_scale, impl):
    me = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]  # pass k/v to the right

    # Diagonal (t = 0): my own chunk — causal triangle (or full).
    acc, lse_acc = _attn(q, k, v, causal=causal, sm_scale=sm_scale, impl=impl)

    def step(carry, t):
        k_t, v_t, acc, lse_acc = carry
        # Rotate first: after t rotations the chunk in hand started on
        # device src = (me - t) mod n.
        k_t = lax.ppermute(k_t, axis, perm)
        v_t = lax.ppermute(v_t, axis, perm)
        src = (me - t) % n
        o_t, lse_t = _attn(q, k_t, v_t, causal=False, sm_scale=sm_scale,
                           impl=impl)
        if causal:
            # Chunks are contiguous in axis order: src < me -> fully
            # visible; src > me -> fully masked (lockstep no-op step).
            live = src < me
            o_t = jnp.where(live, o_t, 0.0)
            lse_t = jnp.where(live, lse_t, NEG_INF)
        acc, lse_acc = _merge(acc, lse_acc, o_t, lse_t)
        return (k_t, v_t, acc, lse_acc), None

    (_, _, acc, _), _ = lax.scan(step, (k, v, acc, lse_acc),
                                 jnp.arange(1, n))
    return acc.astype(q.dtype)


def _ring_zigzag(q, k, v, axis, *, n, sm_scale, impl):
    """Causal ring over the zigzag layout: balanced, no masked compute.

    My chunks: lo = global chunk ``me``, hi = global chunk ``2n-1-me``
    (so lo < hi always, and every other device's lo is < my hi).  At ring
    step t the K/V in hand came from src = (me-t) mod n, with chunk halves
    c_lo = src and c_hi = 2n-1-src.  Exactly two of the four (q, k) half
    pairs are causally active:

      src < me:  (q_lo, c_lo) full and (q_hi, c_lo) full
      src > me:  (q_hi, c_lo) full and (q_hi, c_hi) full

    — equal work on every device at every step, computed as two half-chunk
    full attentions with `where`-selected operands (static shapes, SPMD).
    """
    me = lax.axis_index(axis)
    sq = q.shape[2]
    if sq % 2:
        raise ValueError(f"zigzag layout needs an even local sequence "
                         f"length, got {sq}")
    c = sq // 2
    q_lo, q_hi = q[:, :, :c], q[:, :, c:]
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Diagonal step: causal attention over my own [lo; hi] pair — correct
    # because all of lo precedes all of hi globally and each half is
    # internally ordered.
    o0, lse0 = _attn(q, k, v, causal=True, sm_scale=sm_scale, impl=impl)
    acc_lo, lse_lo = o0[:, :, :c], lse0[:, :, :c]
    acc_hi, lse_hi = o0[:, :, c:], lse0[:, :, c:]

    def step(carry, t):
        k_t, v_t, acc_lo, lse_lo, acc_hi, lse_hi = carry
        k_t = lax.ppermute(k_t, axis, perm)
        v_t = lax.ppermute(v_t, axis, perm)
        src = (me - t) % n
        early = src < me
        k_c_lo, k_c_hi = k_t[:, :, :c], k_t[:, :, c:]
        v_c_lo, v_c_hi = v_t[:, :, :c], v_t[:, :, c:]
        # Pair 1: (q_lo if early else q_hi) x c_lo, always fully visible.
        q1 = jnp.where(early, q_lo, q_hi)
        o1, lse1 = _attn(q1, k_c_lo, v_c_lo, causal=False,
                         sm_scale=sm_scale, impl=impl)
        # Pair 2: q_hi x (c_lo if early else c_hi), always fully visible.
        k2 = jnp.where(early, k_c_lo, k_c_hi)
        v2 = jnp.where(early, v_c_lo, v_c_hi)
        o2, lse2 = _attn(q_hi, k2, v2, causal=False, sm_scale=sm_scale,
                         impl=impl)
        # Route the two partials to the right q-half accumulators.
        om, lsem = _merge(o1, lse1, o2, lse2)   # both pairs were q_hi
        p_lo_o = jnp.where(early, o1, 0.0)
        p_lo_lse = jnp.where(early, lse1, NEG_INF)
        p_hi_o = jnp.where(early, o2, om)
        p_hi_lse = jnp.where(early, lse2, lsem)
        acc_lo, lse_lo = _merge(acc_lo, lse_lo, p_lo_o, p_lo_lse)
        acc_hi, lse_hi = _merge(acc_hi, lse_hi, p_hi_o, p_hi_lse)
        return (k_t, v_t, acc_lo, lse_lo, acc_hi, lse_hi), None

    (_, _, acc_lo, _, acc_hi, _), _ = lax.scan(
        step, (k, v, acc_lo, lse_lo, acc_hi, lse_hi), jnp.arange(1, n))
    return jnp.concatenate([acc_lo, acc_hi], axis=2).astype(q.dtype)
