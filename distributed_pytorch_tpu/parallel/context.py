"""Ring attention: sequence/context parallelism over a mesh axis.

The reference has no sequence dimension at all (SURVEY.md section 5: its
parallelism inventory is data-parallel only); this module is the long-context
capability the TPU framework adds.  Sequences are sharded over a named mesh
axis ``seq``; each device holds one contiguous chunk of Q/K/V.  Attention
over the full sequence is computed in ``n = axis_size(seq)`` ring steps:

  step t: attend my Q chunk against the K/V chunk that started on device
  ``(my - t) mod n``, then pass my current K/V chunk to the next neighbor
  with ``lax.ppermute`` (XLA lowers this to ICI neighbor exchange, which
  overlaps with the attention compute of the current step).

Partial results are merged with the online-softmax rule — each step yields a
normalized chunk output plus its row logsumexp; two partials combine by
logaddexp-weighted averaging.  The whole thing is plain differentiable JAX
(``ppermute``'s transpose is ``ppermute``), so one ``jax.grad`` produces the
backward ring automatically.

Causality across chunks: with contiguous ("segment") layout, chunk r is
entirely before chunk m for r < m, so step t attends fully when the source
chunk is earlier, causally on the diagonal (t == 0), and not at all when the
source is later.  The not-at-all steps still run (SPMD lockstep) and are
masked out — the classic ring-attention load imbalance; a striped layout is
the known fix and a future optimization.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import NEG_INF, attention_reference

Array = jax.Array


def _merge(o1: Array, lse1: Array, o2: Array, lse2: Array):
    """Combine two normalized partial attentions (online-softmax merge).

    ``o_i`` are (B, H, S, D) outputs normalized within their own key chunk;
    ``lse_i`` are their (B, H, S) logsumexps.  Fully-masked partials carry
    lse ~= NEG_INF and vanish smoothly (finite large-negative, no NaNs).
    """
    lse = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse)[..., None]
    w2 = jnp.exp(lse2 - lse)[..., None]
    return o1 * w1 + o2 * w2, lse


def ring_attention(
    q: Array, k: Array, v: Array, axis: str, *,
    causal: bool = True, sm_scale: float | None = None,
) -> Array:
    """Attention over a sequence sharded across mesh axis ``axis``.

    Args are this device's chunks, (B, H, S_local, D).  Equivalent (tested)
    to full attention over the concatenated sequence with chunks laid out
    contiguously in axis-index order.  Peak score memory per device is
    O(S_local^2) per ring step — the blockwise-attention memory saving that
    makes million-token sequences feasible.
    """
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    sq = q.shape[2]

    perm = [(i, (i + 1) % n) for i in range(n)]  # pass k/v to the right

    def step(carry, t):
        k_t, v_t, acc, lse_acc = carry
        src = (me - t) % n  # the chunk now in hand started on device src
        # Additive bias selecting the causal relation of (my chunk, src):
        #   src == me (t == 0): causal triangle;  src < me: full;  else: none.
        tri = jnp.where(
            jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 0)
            >= jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 1),
            0.0, NEG_INF)
        if causal:
            bias = jnp.where(
                src == me, tri,
                jnp.where(src < me, 0.0, NEG_INF))
        else:
            bias = jnp.zeros((sq, sq))
        o_t, lse_t = attention_reference(
            q, k_t, v_t, sm_scale=sm_scale, with_lse=True,
            bias=bias[None, None])
        acc, lse_acc = _merge(acc, lse_acc, o_t.astype(jnp.float32), lse_t)
        # Rotate K/V around the ring (skipped after the last step's compute
        # would be wasted; one extra hop keeps the scan body uniform).
        k_t = lax.ppermute(k_t, axis, perm)
        v_t = lax.ppermute(v_t, axis, perm)
        return (k_t, v_t, acc, lse_acc), None

    # Accumulator inits derive from q (0*q) so they inherit q's full set of
    # varying mesh axes — a fresh constant would be axis-invariant and the
    # scan carry type check would reject the merge with varying partials.
    acc0 = q.astype(jnp.float32) * 0.0
    lse0 = jnp.sum(acc0, axis=-1) + NEG_INF
    (_, _, acc, _), _ = lax.scan(step, (k, v, acc0, lse0), jnp.arange(n))
    return acc.astype(q.dtype)
