"""Gradient-synchronization strategies as grad-pytree transforms.

The reference implements each strategy as a distinct copy-pasted script whose
only real delta is ~15 lines between ``loss.backward()`` and
``optimizer.step()`` (SURVEY.md section 0).  Here each strategy is a pure
function ``grads -> synced_grads`` executed *inside* the compiled, shard_mapped
train step, over the named mesh axis:

- ``none``       — identity; the single-process baseline (reference main.py).
- ``all_reduce`` — per-tensor mean via psum, kept sequential with explicit
                   optimization barriers (reference main_all_reduce.py:45-48:
                   34 sequential blocking all_reduces per step).
- ``gather_scatter`` — per-tensor ppermute-to-rank-0 -> mean -> ppermute-out,
                   sequential (reference main_gather.py:42-59: two network
                   crossings per tensor, ALL traffic through rank 0).  This is
                   the deliberately-naive parameter-server baseline, slow for
                   the reference's reason (device 0 is the bandwidth hotspot).
- ``gather_scatter_symmetric`` — same semantics via all_gather + masked psum:
                   no rank-0 hotspot; the ICI-friendly re-expression.
- ``ddp``        — one whole-pytree pmean; XLA's latency-hiding scheduler
                   provides the bucketing/overlap that torch DDP implements in
                   C++ autograd hooks (reference main_ddp.py:137).
- ``bucketed``   — explicit DDP-style gradient bucketing: leaves flattened and
                   packed into ~25 MB buckets, one psum per bucket (torch
                   DDP's default bucket_cap_mb=25), making the overlap
                   measurable and XLA's fusion explicit.

Why barriers: torch dispatches 34 *eager* collectives; XLA would otherwise
fuse them into one — dissolving exactly the contrast these baselines exist to
measure (SURVEY.md section 7.3 "preserving naivety on purpose").  Each leaf's
collective is data-chained to the previous leaf's result with
``lax.optimization_barrier`` so the schedule stays sequential.
"""

from __future__ import annotations

from typing import Any, Callable, Protocol

import jax
import jax.numpy as jnp
from jax import lax
# load the runtime-compat shims (axis_size/pcast polyfills on
# legacy jax) before anything in this module traces
from ..utils import compat as _compat  # noqa: F401

try:  # provable varying->invariant gather (jax 0.9: not yet re-exported)
    from jax._src.lax.parallel import all_gather_invariant as _all_gather_inv
except ImportError:  # pragma: no cover - future jax: use the public name
    _all_gather_inv = getattr(lax, "all_gather_invariant", None)

PyTree = Any

BUCKET_CAP_MB = 25  # torch DDP default bucket size


class Strategy(Protocol):
    name: str
    needs_mesh: bool

    def __call__(self, grads: PyTree, axis: str) -> PyTree: ...


def _chain(leaf: jax.Array, token: jax.Array) -> jax.Array:
    """Tie ``leaf`` to ``token`` so its collective cannot be reordered/fused
    with the previous one (emulates the reference's sequential eager
    dispatch)."""
    leaf, _ = lax.optimization_barrier((leaf, token))
    return leaf


class NoSync:
    """Single-process baseline — no communication (reference main.py)."""

    name = "none"
    needs_mesh = False

    def __call__(self, grads: PyTree, axis: str | None = None) -> PyTree:
        return grads


class AllReduce:
    """Per-tensor sequential all-reduce-mean (reference main_all_reduce.py:45-48).

    ``psum / N`` is numerically the reference's sum-then-divide; sequencing
    is forced per tensor to preserve the 34-collectives-per-step structure.
    """

    name = "all_reduce"
    needs_mesh = True

    def __init__(self, sequential: bool = True):
        self.sequential = sequential

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        n = lax.axis_size(axis)
        leaves, treedef = jax.tree.flatten(grads)
        out = []
        token = jnp.zeros((), jnp.float32)
        for g in leaves:
            if self.sequential:
                g = _chain(g, token)
            g = lax.psum(g, axis) / n
            if self.sequential:
                token = g.ravel()[0].astype(jnp.float32)
            out.append(g)
        return jax.tree.unflatten(treedef, out)


class GatherScatter:
    """Per-tensor gather -> rank-0 mean -> scatter with ALL traffic routed
    through device 0 (reference main_gather.py:42-59).

    Wire-faithful to the reference's parameter-server baseline: for each
    tensor, every rank's gradient crosses to rank 0 (n-1 ``ppermute`` sends,
    all landing on device 0 — the gather, main_gather.py:49), rank 0 means
    them (main_gather.py:53-55), then rank 0 sends the mean back out to each
    rank (n-1 more ``ppermute`` sends, all departing device 0 — the scatter,
    main_gather.py:59).  Two crossings per tensor through rank 0, per-tensor
    sequential: device 0's links are the bandwidth hotspot, so this strategy
    is slow for exactly the reference's reason.  (For the symmetric
    ICI-friendly formulation that dissolves the hotspot, see
    ``gather_scatter_symmetric``.)

    vma note: each rank's result arrives via ``ppermute`` from rank 0 —
    bitwise identical everywhere by construction, but assembled from
    device-varying values the vma checker cannot prove invariant, hence
    ``vma_opaque`` (the trainer compiles this strategy's step with
    ``check_vma=False``, replaces the lost static proof with a one-time
    dynamic replication check after the first step, and tests pin the
    numerics against the exact mean).
    """

    name = "gather_scatter"
    needs_mesh = True
    vma_opaque = True  # replication holds by construction, not by proof

    def __init__(self, sequential: bool = True):
        self.sequential = sequential

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        n = lax.axis_size(axis)
        idx = lax.axis_index(axis)
        leaves, treedef = jax.tree.flatten(grads)
        out = []
        token = jnp.zeros((), jnp.float32)
        for g in leaves:
            if self.sequential:
                g = _chain(g, token)
            if n == 1:
                out.append(g)
                continue
            # gather (main_gather.py:49): rank r's grad crosses to rank 0.
            # The adds chain the hops, mirroring the synchronous dist.gather;
            # on ranks != 0 each recv is zeros and acc is unused garbage.
            acc = g
            for r in range(1, n):
                acc = acc + lax.ppermute(g, axis, [(r, 0)])
            # rank-0 mean (main_gather.py:53-55): stack-then-mean == sum/n
            mean = acc / n
            # scatter (main_gather.py:59): rank 0 sends the mean to each
            # rank; rank r receives exactly one nonzero payload.
            result = jnp.where(idx == 0, mean, jnp.zeros_like(mean))
            for r in range(1, n):
                result = result + lax.ppermute(mean, axis, [(0, r)])
            if self.sequential:
                token = result.ravel()[0].astype(jnp.float32)
            out.append(result)
        return jax.tree.unflatten(treedef, out)


class GatherScatterSymmetric:
    """The same gather -> rank-0 mean -> broadcast semantics expressed with
    symmetric collectives (``all_gather`` + masked ``psum``): numerically
    identical to ``gather_scatter`` but with no rank-0 hotspot — the
    ICI-friendly form XLA can schedule, kept as the contrast point showing
    what re-expressing the parameter-server pattern buys on a torus."""

    name = "gather_scatter_symmetric"
    needs_mesh = True

    def __init__(self, sequential: bool = True):
        self.sequential = sequential

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        idx = lax.axis_index(axis)
        leaves, treedef = jax.tree.flatten(grads)
        out = []
        token = jnp.zeros((), jnp.float32)
        for g in leaves:
            if self.sequential:
                g = _chain(g, token)
            # collective 1: gather all replicas' grads (main_gather.py:49)
            gathered = lax.all_gather(g, axis)
            # rank-0 mean (main_gather.py:53-55); other ranks contribute zeros
            mean0 = jnp.where(idx == 0, 1.0, 0.0).astype(g.dtype) * jnp.mean(
                gathered, axis=0)
            # collective 2: broadcast rank 0's mean (scatter, main_gather.py:59)
            g = lax.psum(mean0, axis)
            if self.sequential:
                token = g.ravel()[0].astype(jnp.float32)
            out.append(g)
        return jax.tree.unflatten(treedef, out)


class DDP:
    """Whole-pytree fused pmean — the idiomatic TPU path (reference
    main_ddp.py:137's DistributedDataParallel, minus the C++ machinery: XLA
    sees all 34 reductions at once and schedules/overlaps them itself)."""

    name = "ddp"
    needs_mesh = True

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        return jax.tree.map(lambda g: lax.pmean(g, axis), grads)


class Bucketed:
    """Explicit DDP-style bucketing: pack leaves into ~bucket_mb buckets,
    one psum per bucket (torch DDP's Reducer with bucket_cap_mb=25,
    reference main_ddp.py:137's underlying engine)."""

    name = "bucketed"
    needs_mesh = True

    def __init__(self, bucket_mb: int = BUCKET_CAP_MB):
        self.bucket_bytes = bucket_mb * 1024 * 1024

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        n = lax.axis_size(axis)
        leaves, treedef = jax.tree.flatten(grads)
        # Pack in reverse so late-backward (output-side) grads share the
        # first-reduced bucket, like torch DDP's reversed bucket order.
        buckets: list[list[int]] = [[]]
        size = 0
        for i in reversed(range(len(leaves))):
            nbytes = leaves[i].size * leaves[i].dtype.itemsize
            if size + nbytes > self.bucket_bytes and buckets[-1]:
                buckets.append([])
                size = 0
            buckets[-1].append(i)
            size += nbytes
        out: list[jax.Array | None] = [None] * len(leaves)
        for bucket in buckets:
            flat = jnp.concatenate([leaves[i].ravel() for i in bucket])
            flat = lax.psum(flat, axis) / n
            offset = 0
            for i in bucket:
                g = leaves[i]
                out[i] = flat[offset : offset + g.size].reshape(g.shape)
                offset += g.size
        return jax.tree.unflatten(treedef, out)


class QuantizedAllReduce:
    """Int8-quantized gradient all-reduce (the EQuARX/DynamiQ family of
    compressed collectives, e.g. arxiv.org/abs/2506.17615): per-tensor
    symmetric int8 quantization against a cross-replica-shared scale
    (pmax of |g|), integer psum, dequantize, mean.

    Scope note (honest accounting): with XLA's stock collectives the psum
    operand is int32, so the bytes on the wire match an fp32 all-reduce —
    this strategy demonstrates the *numerics* of quantized sync (shared
    scale makes the integer sum exact; only quantization loses precision,
    <1% relative error per tensor) and reserves the API slot.  For true
    wire compression see ``quantized_ring`` below, which moves int8 bytes
    on every hop.
    """

    name = "quantized"
    needs_mesh = True

    def __init__(self, bits: int = 8):
        self.levels = 2 ** (bits - 1) - 1  # 127 for int8

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        n = lax.axis_size(axis)

        def sync(g):
            g32 = g.astype(jnp.float32)
            absmax = lax.pmax(jnp.max(jnp.abs(g32)), axis)
            scale = jnp.maximum(absmax / self.levels, 1e-30)
            q = jnp.clip(jnp.round(g32 / scale), -self.levels,
                         self.levels).astype(jnp.int8)
            summed = lax.psum(q.astype(jnp.int32), axis)
            return (summed.astype(jnp.float32) * scale / n).astype(g.dtype)

        return jax.tree.map(sync, grads)


class QuantizedRing:
    """Int8 ring all-reduce with TRUE wire compression: a ring
    reduce-scatter followed by a ring all-gather built from ``ppermute``
    hops whose payloads are the int8 tensors themselves (plus one f32
    scale per ``block`` values, ~1.6% overhead).  Unlike ``quantized``
    (which feeds XLA's all_reduce int32, so full-width bytes move), every
    inter-chip transfer here is the quantized byte stream — the DynamiQ/
    EQuARX compressed-collective design point, expressed with JAX
    collectives instead of a custom RDMA kernel.

    Numerics: each reduce-scatter hop requantizes its partial sum, so
    quantization noise accumulates O(sqrt(n)) over the ring (the price of
    per-hop compression; block-wise scales keep the relative error ~1e-2
    at int8).  The all-gather forwards each reduced chunk's int8 payload
    verbatim — no further loss.

    vma note: every device dequantizes identical payloads, so the result
    is bitwise replicated by construction — but it is assembled from
    ``ppermute`` (varying) values, which the vma type system cannot prove
    invariant and there is no sanctioned downcast.  The trainer therefore
    runs this strategy with ``check_vma=False`` (see ``vma_opaque``).
    """

    name = "quantized_ring"
    needs_mesh = True
    vma_opaque = True  # replication holds by construction, not by proof

    def __init__(self, bits: int = 8, block: int = 256):
        self.levels = 2 ** (bits - 1) - 1
        self.block = block

    def _quant(self, x: jax.Array):
        xb = x.reshape(-1, self.block)
        scale = jnp.maximum(
            jnp.max(jnp.abs(xb), axis=1, keepdims=True) / self.levels,
            1e-30)
        q = jnp.clip(jnp.round(xb / scale), -self.levels,
                     self.levels).astype(jnp.int8)
        return q, scale.astype(jnp.float32)

    def _dequant(self, q: jax.Array, scale: jax.Array) -> jax.Array:
        return (q.astype(jnp.float32) * scale).ravel()

    def _ring_sum(self, flat: jax.Array, axis: str, n,
                  residual: jax.Array | None = None):
        """The int8 ring: reduce-scatter then all-gather, int8 + per-block
        f32 scales on every hop.  Returns ``(summed[:total], err_rows)``
        where ``summed`` is the (approximate) cross-device SUM of ``flat``
        and ``err_rows`` is the (n, chunk) array of quantization errors
        THIS device dropped (always computed; the plain strategy discards
        it and XLA dead-code-eliminates the bookkeeping).  With
        ``residual`` (error feedback), last step's dropped errors are
        added to this step's chunk contributions first."""
        total = flat.size
        me = lax.axis_index(axis)
        chunk = -(-total // (n * self.block)) * self.block
        parts = jnp.pad(flat, (0, n * chunk - total)).reshape(n, chunk)
        if residual is not None:
            parts = parts + residual.reshape(n, chunk)
        perm = [(i, (i + 1) % n) for i in range(n)]

        # -- ring reduce-scatter (int8 + scales per hop) -------------------
        # After t hops my accumulator holds the partial sum of chunk
        # (me - t) mod n over devices {me-t, ..., me}.
        acc = lax.dynamic_index_in_dim(parts, me, 0, keepdims=False)
        err_rows = jnp.zeros((n, chunk), jnp.float32)

        def rs_step(carry, t):
            acc, err_rows = carry
            q, s = self._quant(acc)
            # chunk (me - t) mod n leaves this device quantized; record the
            # dropped error (EF uses it; otherwise DCE'd)
            err_rows = lax.dynamic_update_index_in_dim(
                err_rows, acc - self._dequant(q, s), jnp.mod(me - t, n), 0)
            q = lax.ppermute(q, axis, perm)
            s = lax.ppermute(s, axis, perm)
            idx = jnp.mod(me - t - 1, n)
            nxt = self._dequant(q, s) + lax.dynamic_index_in_dim(
                parts, idx, 0, keepdims=False)
            return (nxt, err_rows), None

        (acc, err_rows), _ = lax.scan(rs_step, (acc, err_rows),
                                      jnp.arange(n - 1))
        # acc == full sum of chunk (me + 1) mod n

        # -- ring all-gather (int8 payloads forwarded verbatim) ------------
        qf, sf = self._quant(acc)
        own = jnp.mod(me + 1, n)
        # the broadcast copy everyone (including us) uses is dequantized
        err_rows = lax.dynamic_update_index_in_dim(
            err_rows, acc - self._dequant(qf, sf), own, 0)
        q_all = lax.dynamic_update_index_in_dim(
            jnp.zeros((n,) + qf.shape, jnp.int8), qf, own, 0)
        s_all = lax.dynamic_update_index_in_dim(
            jnp.zeros((n,) + sf.shape, jnp.float32), sf, own, 0)

        def ag_step(carry, t):
            q_all, s_all, cur_q, cur_s = carry
            cur_q = lax.ppermute(cur_q, axis, perm)
            cur_s = lax.ppermute(cur_s, axis, perm)
            # payload received at hop t originated at device me-(t+1),
            # i.e. holds reduced chunk (me - t) mod n
            src = jnp.mod(me - t, n)
            q_all = lax.dynamic_update_index_in_dim(q_all, cur_q, src, 0)
            s_all = lax.dynamic_update_index_in_dim(s_all, cur_s, src, 0)
            return (q_all, s_all, cur_q, cur_s), None

        (q_all, s_all, _, _), _ = lax.scan(
            ag_step, (q_all, s_all, qf, sf), jnp.arange(n - 1))
        summed = (q_all.astype(jnp.float32) * s_all).reshape(-1)[:total]
        return summed, err_rows

    def _unflatten(self, mean: jax.Array, leaves, treedef) -> PyTree:
        out, offset = [], 0
        for g in leaves:
            out.append(mean[offset:offset + g.size]
                       .reshape(g.shape).astype(g.dtype))
            offset += g.size
        return jax.tree.unflatten(treedef, out)

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        n = lax.axis_size(axis)
        leaves, treedef = jax.tree.flatten(grads)
        flat = jnp.concatenate([g.ravel().astype(jnp.float32)
                                for g in leaves])
        if n == 1:
            mean = flat
        else:
            mean, _ = self._ring_sum(flat, axis, n)
        return self._unflatten(mean / n, leaves, treedef)


class QuantizedRingEF(QuantizedRing):
    """``quantized_ring`` + error feedback (EF-SGD / EF21 family): every
    quantization error the ring DROPS is recorded locally and fed back
    into the next step's contribution, so compressed sync converges like
    exact sync instead of degrading O(sqrt(n)) with ring size.

    Exact bookkeeping, not an approximation: in the reduce-scatter, device
    d at hop t quantizes its partial sum of chunk (d-t) mod n — the
    residual ``acc - dequant(Q(acc))`` is precisely what the global sum
    loses at that hop, and d is the only device that knows it.  The final
    all-gather quantization of chunk (d+1) mod n drops one more residual.
    Each device therefore records exactly one residual per chunk row per
    step; adding the carried residuals to next step's (sum-space) chunk
    contributions restores them.  Invariant (pinned by tests):

        n * synced_mean + psum(residuals) == exact gradient sum   (to f32)

    i.e. nothing is ever lost — only delayed one step.

    State: one f32 vector per device (the padded flat gradient size),
    carried through the train step's scan like BN state (leading device
    axis, sharded over the data axis).  Dropping the state on restart is
    safe (residuals re-accumulate within a step).
    """

    name = "quantized_ring_ef"
    stateful = True  # __call__ takes and returns the residual carry

    def init_state(self, params: PyTree, n_axis: int) -> jax.Array:
        """Per-device zero residual for a gradient pytree shaped like
        ``params`` over an ``n_axis``-way ring (local, unstacked view)."""
        total = sum(leaf.size for leaf in jax.tree.leaves(params))
        chunk = -(-total // (n_axis * self.block)) * self.block
        return jnp.zeros((n_axis * chunk,), jnp.float32)

    def __call__(self, grads: PyTree, axis: str,
                 residual: jax.Array) -> tuple[PyTree, jax.Array]:
        n = lax.axis_size(axis)
        leaves, treedef = jax.tree.flatten(grads)
        flat = jnp.concatenate([g.ravel().astype(jnp.float32)
                                for g in leaves])
        if n == 1:
            mean, new_res = flat, jnp.zeros_like(residual)
        else:
            mean, err_rows = self._ring_sum(flat, axis, n, residual=residual)
            new_res = err_rows.ravel()
        return self._unflatten(mean / n, leaves, treedef), new_res


class Hierarchical:
    """Two-level (within-slice ICI, cross-slice DCN) gradient mean for
    multi-slice data parallelism.

    The reference's real topology is N nodes over TCP (start_ddp.sh:1 — a
    flat Gloo ring).  At TPU-pod scale the data axis factors into two links
    with ~100x different bandwidth: ICI within a slice and DCN across
    slices.  A flat psum over the combined axis runs the slow ring over
    DCN with the FULL gradient payload; the right algorithm is the
    standard two-level reduction (the scaling-book multi-slice recipe):

      1. ``psum_scatter`` over ``'ici'`` — each chip ends with a 1/ici
         shard of its slice's summed gradient (bandwidth-optimal within
         the slice);
      2. ``psum`` over ``'dcn'`` — slices exchange only the 1/ici shard,
         so cross-slice traffic drops by the ici degree;
      3. all-gather over ``'ici'`` — the full mean returns on the fast
         link.

    Total DCN bytes per step: |grads|/ici vs |grads| for the flat psum.
    The result is the exact global mean, so numerics match ``ddp``
    (pinned by tests/test_strategies.py vs ddp on a 2x4 virtual mesh).

    The gather-back uses ``all_gather_invariant`` so the result is
    *provably* replicated (vma-invariant) over both axes — this strategy
    needs no ``check_vma=False`` escape hatch.  On a jax without it, the
    fallback embeds each shard at its offset and psums over ``'ici'``
    (same result, provable, 2x the ICI bytes of the gather).

    Runs over ``Mesh(('dcn', 'ici'))`` — the trainer builds it from
    ``TrainConfig.dcn_size`` (number of slices).  With a single flat axis
    (or axis size 1 on either level) it degrades gracefully to the exact
    flat mean.
    """

    name = "hierarchical"
    needs_mesh = True
    axes = ("dcn", "ici")  # outer = cross-slice (slow), inner = within-slice

    def __call__(self, grads: PyTree, axis) -> PyTree:
        if isinstance(axis, str):
            dcn, ici = None, axis
        else:
            dcn, ici = axis
        n = lax.axis_size(ici) * (lax.axis_size(dcn) if dcn else 1)
        # the mean division happens on the f32 sum INSIDE two_level_psum
        # (before the cast back to leaf dtype): low-precision leaves must
        # not see the undivided sum, which can overflow their range
        return two_level_psum(grads, dcn, ici, scale=1.0 / n)


def two_level_psum(grads: PyTree, dcn: str | None, ici: str,
                   scale: float | None = None) -> PyTree:
    """The two-level reduction underlying ``Hierarchical`` (steps 1-3 of
    its docstring): reduce-scatter over ``ici``, a SHARD-SIZED ``psum``
    over ``dcn`` (the only cross-slice traffic — |grads|/ici bytes),
    ``all_gather_invariant`` back over ``ici``.  ``scale`` (e.g. 1/n for
    a mean) applies to the f32 sum before the cast back to each leaf's
    dtype.  Output is provably replicated over both axes.  Shared with
    the LM trainer's factored-mesh gradient sync (lm.py dcn_size),
    whose jaxpr test pins the shard-sized DCN payload."""
    n_ici = lax.axis_size(ici)
    leaves, treedef = jax.tree.flatten(grads)
    flat = jnp.concatenate(
        [g.ravel().astype(jnp.float32) for g in leaves])
    total = flat.size
    padded = jnp.pad(flat, (0, (-total) % n_ici))
    # 1. reduce-scatter within the slice (fast link, 1x payload)
    shard = lax.psum_scatter(padded, ici, scatter_dimension=0, tiled=True)
    # 2. cross-slice all-reduce of the shard (slow link, payload/ici)
    if dcn is not None:
        shard = lax.psum(shard, dcn)
    # 3. gather the sum back within the slice (fast link)
    if _all_gather_inv is not None:
        full = _all_gather_inv(shard, ici, axis=0, tiled=True)
    else:
        me = lax.axis_index(ici)
        chunk = padded.size // n_ici
        buf = jnp.zeros_like(padded)
        buf = lax.dynamic_update_slice(buf, shard, (me * chunk,))
        full = lax.psum(buf, ici)
    summed = full[:total]
    if scale is not None:
        summed = summed * scale

    out, offset = [], 0
    for g in leaves:
        out.append(summed[offset:offset + g.size]
                   .reshape(g.shape).astype(g.dtype))
        offset += g.size
    return jax.tree.unflatten(treedef, out)


_REGISTRY: dict[str, Callable[[], Strategy]] = {
    "none": NoSync,
    "all_reduce": AllReduce,
    "gather_scatter": GatherScatter,
    "gather_scatter_symmetric": GatherScatterSymmetric,
    "ddp": DDP,
    "bucketed": Bucketed,
    "quantized": QuantizedAllReduce,
    "quantized_ring": QuantizedRing,
    "quantized_ring_ef": QuantizedRingEF,
    "hierarchical": Hierarchical,
}


def get(name: str) -> Strategy:
    """Look up a strategy by name (the pluggable axis the reference's five
    copy-pasted scripts should have had)."""
    try:
        return _REGISTRY[name]()
    except KeyError:
        raise ValueError(
            f"unknown strategy {name!r}; available: {sorted(_REGISTRY)}"
        ) from None


def available() -> list[str]:
    return sorted(_REGISTRY)
