"""Gradient-synchronization strategies as grad-pytree transforms.

The reference implements each strategy as a distinct copy-pasted script whose
only real delta is ~15 lines between ``loss.backward()`` and
``optimizer.step()`` (SURVEY.md section 0).  Here each strategy is a pure
function ``grads -> synced_grads`` executed *inside* the compiled, shard_mapped
train step, over the named mesh axis:

- ``none``       — identity; the single-process baseline (reference main.py).
- ``all_reduce`` — per-tensor mean via psum, kept sequential with explicit
                   optimization barriers (reference main_all_reduce.py:45-48:
                   34 sequential blocking all_reduces per step).
- ``gather_scatter`` — per-tensor all_gather -> mean at rank 0 -> broadcast,
                   sequential (reference main_gather.py:42-59: two network
                   crossings per tensor, all traffic through rank 0).  This is
                   the deliberately-naive parameter-server baseline.
- ``ddp``        — one whole-pytree pmean; XLA's latency-hiding scheduler
                   provides the bucketing/overlap that torch DDP implements in
                   C++ autograd hooks (reference main_ddp.py:137).
- ``bucketed``   — explicit DDP-style gradient bucketing: leaves flattened and
                   packed into ~25 MB buckets, one psum per bucket (torch
                   DDP's default bucket_cap_mb=25), making the overlap
                   measurable and XLA's fusion explicit.

Why barriers: torch dispatches 34 *eager* collectives; XLA would otherwise
fuse them into one — dissolving exactly the contrast these baselines exist to
measure (SURVEY.md section 7.3 "preserving naivety on purpose").  Each leaf's
collective is data-chained to the previous leaf's result with
``lax.optimization_barrier`` so the schedule stays sequential.
"""

from __future__ import annotations

from typing import Any, Callable, Protocol

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any

BUCKET_CAP_MB = 25  # torch DDP default bucket size


class Strategy(Protocol):
    name: str
    needs_mesh: bool

    def __call__(self, grads: PyTree, axis: str) -> PyTree: ...


def _chain(leaf: jax.Array, token: jax.Array) -> jax.Array:
    """Tie ``leaf`` to ``token`` so its collective cannot be reordered/fused
    with the previous one (emulates the reference's sequential eager
    dispatch)."""
    leaf, _ = lax.optimization_barrier((leaf, token))
    return leaf


class NoSync:
    """Single-process baseline — no communication (reference main.py)."""

    name = "none"
    needs_mesh = False

    def __call__(self, grads: PyTree, axis: str | None = None) -> PyTree:
        return grads


class AllReduce:
    """Per-tensor sequential all-reduce-mean (reference main_all_reduce.py:45-48).

    ``psum / N`` is numerically the reference's sum-then-divide; sequencing
    is forced per tensor to preserve the 34-collectives-per-step structure.
    """

    name = "all_reduce"
    needs_mesh = True

    def __init__(self, sequential: bool = True):
        self.sequential = sequential

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        n = lax.axis_size(axis)
        leaves, treedef = jax.tree.flatten(grads)
        out = []
        token = jnp.zeros((), jnp.float32)
        for g in leaves:
            if self.sequential:
                g = _chain(g, token)
            g = lax.psum(g, axis) / n
            if self.sequential:
                token = g.ravel()[0].astype(jnp.float32)
            out.append(g)
        return jax.tree.unflatten(treedef, out)


class GatherScatter:
    """Per-tensor gather -> rank-0 mean -> scatter (reference main_gather.py:42-59).

    Faithfully two collectives per tensor through rank 0: an ``all_gather``
    (superset of the reference's gather-to-0) followed by a broadcast of
    rank 0's mean, implemented as a masked psum so only rank 0's value
    survives.  Kept sequential per tensor — this strategy's role is to be the
    slow parameter-server baseline in the benchmark.
    """

    name = "gather_scatter"
    needs_mesh = True

    def __init__(self, sequential: bool = True):
        self.sequential = sequential

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        idx = lax.axis_index(axis)
        leaves, treedef = jax.tree.flatten(grads)
        out = []
        token = jnp.zeros((), jnp.float32)
        for g in leaves:
            if self.sequential:
                g = _chain(g, token)
            # collective 1: gather all replicas' grads (main_gather.py:49)
            gathered = lax.all_gather(g, axis)
            # rank-0 mean (main_gather.py:53-55); other ranks contribute zeros
            mean0 = jnp.where(idx == 0, 1.0, 0.0).astype(g.dtype) * jnp.mean(
                gathered, axis=0)
            # collective 2: broadcast rank 0's mean (scatter, main_gather.py:59)
            g = lax.psum(mean0, axis)
            if self.sequential:
                token = g.ravel()[0].astype(jnp.float32)
            out.append(g)
        return jax.tree.unflatten(treedef, out)


class DDP:
    """Whole-pytree fused pmean — the idiomatic TPU path (reference
    main_ddp.py:137's DistributedDataParallel, minus the C++ machinery: XLA
    sees all 34 reductions at once and schedules/overlaps them itself)."""

    name = "ddp"
    needs_mesh = True

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        return jax.tree.map(lambda g: lax.pmean(g, axis), grads)


class Bucketed:
    """Explicit DDP-style bucketing: pack leaves into ~bucket_mb buckets,
    one psum per bucket (torch DDP's Reducer with bucket_cap_mb=25,
    reference main_ddp.py:137's underlying engine)."""

    name = "bucketed"
    needs_mesh = True

    def __init__(self, bucket_mb: int = BUCKET_CAP_MB):
        self.bucket_bytes = bucket_mb * 1024 * 1024

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        n = lax.axis_size(axis)
        leaves, treedef = jax.tree.flatten(grads)
        # Pack in reverse so late-backward (output-side) grads share the
        # first-reduced bucket, like torch DDP's reversed bucket order.
        buckets: list[list[int]] = [[]]
        size = 0
        for i in reversed(range(len(leaves))):
            nbytes = leaves[i].size * leaves[i].dtype.itemsize
            if size + nbytes > self.bucket_bytes and buckets[-1]:
                buckets.append([])
                size = 0
            buckets[-1].append(i)
            size += nbytes
        out: list[jax.Array | None] = [None] * len(leaves)
        for bucket in buckets:
            flat = jnp.concatenate([leaves[i].ravel() for i in bucket])
            flat = lax.psum(flat, axis) / n
            offset = 0
            for i in bucket:
                g = leaves[i]
                out[i] = flat[offset : offset + g.size].reshape(g.shape)
                offset += g.size
        return jax.tree.unflatten(treedef, out)


class QuantizedAllReduce:
    """Int8-quantized gradient all-reduce (the EQuARX/DynamiQ family of
    compressed collectives, e.g. arxiv.org/abs/2506.17615): per-tensor
    symmetric int8 quantization against a cross-replica-shared scale
    (pmax of |g|), integer psum, dequantize, mean.

    Scope note (honest accounting): with XLA's stock collectives the psum
    operand is int32, so the bytes on the wire match an fp32 all-reduce —
    this strategy demonstrates the *numerics* of quantized sync (shared
    scale makes the integer sum exact; only quantization loses precision,
    <1% relative error per tensor) and reserves the API slot.  Actually
    shrinking the transfer needs int8 on the wire with per-hop
    accumulation/requantization — a custom Pallas RDMA ring collective
    (future work); an int8 ``all_gather`` would shrink the payload too but
    its output is vma-varying, which the training step's invariant-carry
    contract cannot absorb without an extra invariant collective.
    """

    name = "quantized"
    needs_mesh = True

    def __init__(self, bits: int = 8):
        self.levels = 2 ** (bits - 1) - 1  # 127 for int8

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        n = lax.axis_size(axis)

        def sync(g):
            g32 = g.astype(jnp.float32)
            absmax = lax.pmax(jnp.max(jnp.abs(g32)), axis)
            scale = jnp.maximum(absmax / self.levels, 1e-30)
            q = jnp.clip(jnp.round(g32 / scale), -self.levels,
                         self.levels).astype(jnp.int8)
            summed = lax.psum(q.astype(jnp.int32), axis)
            return (summed.astype(jnp.float32) * scale / n).astype(g.dtype)

        return jax.tree.map(sync, grads)


_REGISTRY: dict[str, Callable[[], Strategy]] = {
    "none": NoSync,
    "all_reduce": AllReduce,
    "gather_scatter": GatherScatter,
    "ddp": DDP,
    "bucketed": Bucketed,
    "quantized": QuantizedAllReduce,
}


def get(name: str) -> Strategy:
    """Look up a strategy by name (the pluggable axis the reference's five
    copy-pasted scripts should have had)."""
    try:
        return _REGISTRY[name]()
    except KeyError:
        raise ValueError(
            f"unknown strategy {name!r}; available: {sorted(_REGISTRY)}"
        ) from None


def available() -> list[str]:
    return sorted(_REGISTRY)
