"""Gradient-synchronization strategies as grad-pytree transforms.

The reference implements each strategy as a distinct copy-pasted script whose
only real delta is ~15 lines between ``loss.backward()`` and
``optimizer.step()`` (SURVEY.md section 0).  Here each strategy is a pure
function ``grads -> synced_grads`` executed *inside* the compiled, shard_mapped
train step, over the named mesh axis:

- ``none``       — identity; the single-process baseline (reference main.py).
- ``all_reduce`` — per-tensor mean via psum, kept sequential with explicit
                   optimization barriers (reference main_all_reduce.py:45-48:
                   34 sequential blocking all_reduces per step).
- ``gather_scatter`` — per-tensor ppermute-to-rank-0 -> mean -> ppermute-out,
                   sequential (reference main_gather.py:42-59: two network
                   crossings per tensor, ALL traffic through rank 0).  This is
                   the deliberately-naive parameter-server baseline, slow for
                   the reference's reason (device 0 is the bandwidth hotspot).
- ``gather_scatter_symmetric`` — same semantics via all_gather + masked psum:
                   no rank-0 hotspot; the ICI-friendly re-expression.
- ``ddp``        — one whole-pytree pmean; XLA's latency-hiding scheduler
                   provides the bucketing/overlap that torch DDP implements in
                   C++ autograd hooks (reference main_ddp.py:137).
- ``bucketed``   — explicit DDP-style gradient bucketing: leaves flattened and
                   packed into ~25 MB buckets, one psum per bucket (torch
                   DDP's default bucket_cap_mb=25), making the overlap
                   measurable and XLA's fusion explicit.

Why barriers: torch dispatches 34 *eager* collectives; XLA would otherwise
fuse them into one — dissolving exactly the contrast these baselines exist to
measure (SURVEY.md section 7.3 "preserving naivety on purpose").  Each leaf's
collective is data-chained to the previous leaf's result with
``lax.optimization_barrier`` so the schedule stays sequential.
"""

from __future__ import annotations

from typing import Any, Callable, Protocol

import jax
import jax.numpy as jnp
from jax import lax
# load the runtime-compat shims (axis_size/pcast polyfills on
# legacy jax) before anything in this module traces
from ..utils import compat as _compat  # noqa: F401

try:  # provable varying->invariant gather (jax 0.9: not yet re-exported)
    from jax._src.lax.parallel import all_gather_invariant as _all_gather_inv
except ImportError:  # pragma: no cover - future jax: use the public name
    _all_gather_inv = getattr(lax, "all_gather_invariant", None)

PyTree = Any

BUCKET_CAP_MB = 25  # torch DDP default bucket size


class Strategy(Protocol):
    """A stateless gradient-sync strategy: a pure grad-pytree transform.

    Calling convention (the train step's contract, train.py scan body):
    ``synced = strategy(grads, axis)`` with ``axis`` the mesh axis name the
    collective runs over (a TUPLE of names for factored-axis strategies —
    see ``Hierarchical.axes``), or None outside a mesh ('none' only).

    Optional attributes the trainer consults:

    - ``vma_opaque``: result is replicated by construction but not provably
      so (ppermute-assembled) — the step compiles with ``check_vma=False``
      and re-verifies replication dynamically after each fresh compile.
    - ``axes``: factored mesh axis names this strategy needs.
    - ``supports_overlap`` + ``sync_bucket``: the strategy can run as
      in-backward bucket collectives (``OverlapSync``; train.py
      ``overlap=True``).
    """

    name: str
    needs_mesh: bool

    def __call__(self, grads: PyTree, axis: str) -> PyTree: ...


class StatefulStrategy(Protocol):
    """A gradient-sync strategy carrying per-device state between steps
    (error-feedback residuals).  The train step calls it as

        ``synced, new_state = strategy(grads, axis, sync_state)``

    (train.py scan body), threading ``sync_state`` through the K-step scan
    carry next to BN state; ``init_state(params, n_axis)`` builds the
    per-device zero state (the Trainer stacks it with a leading device
    axis).  Stateless strategies thread a zero-size dummy through the same
    carry slot and are called with the two-argument form above — the
    ``stateful`` attribute (True here, absent/False on ``Strategy``) is
    what selects the calling convention.
    """

    name: str
    needs_mesh: bool
    stateful: bool

    def init_state(self, params: PyTree, n_axis: int) -> jax.Array: ...

    def __call__(self, grads: PyTree, axis: str,
                 sync_state: jax.Array) -> tuple[PyTree, jax.Array]: ...


class SizedLeaf:
    """The two attributes ``make_bucket_plan`` reads (``size`` and
    ``dtype.itemsize``), without a device array — the shared stand-in
    for planning buckets from shapes alone (the autotuner's census,
    lm.py's EF-residual sizing).  Lives here, next to the planner whose
    contract it mirrors, so a change to the planner's leaf requirements
    has ONE stand-in to update."""

    __slots__ = ("size", "dtype")

    def __init__(self, size: int, dtype):
        import numpy as np
        self.size = int(size)
        self.dtype = np.dtype(dtype)


def make_bucket_plan(leaves: list, bucket_bytes: int) -> list[list[int]]:
    """Pack leaf indices into ~``bucket_bytes`` buckets in REVERSE flatten
    order (torch DDP's Reducer packing, reference main_ddp.py:137's engine:
    late-backward/output-side grads fill the first-reduced bucket), the one
    packing shared by ``Bucketed``, the int8 ring strategies, and the
    in-backward overlap markers (``OverlapSync``) — so overlap=True and the
    post-backward path always agree on bucket membership.

    Indices within each bucket are returned ASCENDING (tree order): packing
    order decides membership only, concatenation layout stays the flatten
    order — which keeps the single-bucket case (trees under the cap)
    byte-identical to the historical whole-tree flattening.
    """
    buckets: list[list[int]] = [[]]
    size = 0
    for i in reversed(range(len(leaves))):
        nbytes = leaves[i].size * leaves[i].dtype.itemsize
        if size + nbytes > bucket_bytes and buckets[-1]:
            buckets.append([])
            size = 0
        buckets[-1].append(i)
        size += nbytes
    return [sorted(b) for b in buckets]


def _chain(leaf: jax.Array, token: jax.Array) -> jax.Array:
    """Tie ``leaf`` to ``token`` so its collective cannot be reordered/fused
    with the previous one (emulates the reference's sequential eager
    dispatch)."""
    leaf, _ = lax.optimization_barrier((leaf, token))
    return leaf


class NoSync:
    """Single-process baseline — no communication (reference main.py)."""

    name = "none"
    needs_mesh = False

    def __call__(self, grads: PyTree, axis: str | None = None) -> PyTree:
        return grads


class AllReduce:
    """Per-tensor sequential all-reduce-mean (reference main_all_reduce.py:45-48).

    ``psum / N`` is numerically the reference's sum-then-divide; sequencing
    is forced per tensor to preserve the 34-collectives-per-step structure.
    """

    name = "all_reduce"
    needs_mesh = True

    def __init__(self, sequential: bool = True):
        self.sequential = sequential

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        n = lax.axis_size(axis)
        leaves, treedef = jax.tree.flatten(grads)
        out = []
        token = jnp.zeros((), jnp.float32)
        for g in leaves:
            if self.sequential:
                g = _chain(g, token)
            g = lax.psum(g, axis) / n
            if self.sequential:
                token = g.ravel()[0].astype(jnp.float32)
            out.append(g)
        return jax.tree.unflatten(treedef, out)


class GatherScatter:
    """Per-tensor gather -> rank-0 mean -> scatter with ALL traffic routed
    through device 0 (reference main_gather.py:42-59).

    Wire-faithful to the reference's parameter-server baseline: for each
    tensor, every rank's gradient crosses to rank 0 (n-1 ``ppermute`` sends,
    all landing on device 0 — the gather, main_gather.py:49), rank 0 means
    them (main_gather.py:53-55), then rank 0 sends the mean back out to each
    rank (n-1 more ``ppermute`` sends, all departing device 0 — the scatter,
    main_gather.py:59).  Two crossings per tensor through rank 0, per-tensor
    sequential: device 0's links are the bandwidth hotspot, so this strategy
    is slow for exactly the reference's reason.  (For the symmetric
    ICI-friendly formulation that dissolves the hotspot, see
    ``gather_scatter_symmetric``.)

    vma note: each rank's result arrives via ``ppermute`` from rank 0 —
    bitwise identical everywhere by construction, but assembled from
    device-varying values the vma checker cannot prove invariant, hence
    ``vma_opaque`` (the trainer compiles this strategy's step with
    ``check_vma=False``, replaces the lost static proof with a one-time
    dynamic replication check after the first step, and tests pin the
    numerics against the exact mean).
    """

    name = "gather_scatter"
    needs_mesh = True
    vma_opaque = True  # replication holds by construction, not by proof

    def __init__(self, sequential: bool = True):
        self.sequential = sequential

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        n = lax.axis_size(axis)
        idx = lax.axis_index(axis)
        leaves, treedef = jax.tree.flatten(grads)
        out = []
        token = jnp.zeros((), jnp.float32)
        for g in leaves:
            if self.sequential:
                g = _chain(g, token)
            if n == 1:
                out.append(g)
                continue
            # gather (main_gather.py:49): rank r's grad crosses to rank 0.
            # The adds chain the hops, mirroring the synchronous dist.gather;
            # on ranks != 0 each recv is zeros and acc is unused garbage.
            acc = g
            for r in range(1, n):
                acc = acc + lax.ppermute(g, axis, [(r, 0)])
            # rank-0 mean (main_gather.py:53-55): stack-then-mean == sum/n
            mean = acc / n
            # scatter (main_gather.py:59): rank 0 sends the mean to each
            # rank; rank r receives exactly one nonzero payload.
            result = jnp.where(idx == 0, mean, jnp.zeros_like(mean))
            for r in range(1, n):
                result = result + lax.ppermute(mean, axis, [(0, r)])
            if self.sequential:
                token = result.ravel()[0].astype(jnp.float32)
            out.append(result)
        return jax.tree.unflatten(treedef, out)


class GatherScatterSymmetric:
    """The same gather -> rank-0 mean -> broadcast semantics expressed with
    symmetric collectives (``all_gather`` + masked ``psum``): numerically
    identical to ``gather_scatter`` but with no rank-0 hotspot — the
    ICI-friendly form XLA can schedule, kept as the contrast point showing
    what re-expressing the parameter-server pattern buys on a torus."""

    name = "gather_scatter_symmetric"
    needs_mesh = True

    def __init__(self, sequential: bool = True):
        self.sequential = sequential

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        idx = lax.axis_index(axis)
        leaves, treedef = jax.tree.flatten(grads)
        out = []
        token = jnp.zeros((), jnp.float32)
        for g in leaves:
            if self.sequential:
                g = _chain(g, token)
            # collective 1: gather all replicas' grads (main_gather.py:49)
            gathered = lax.all_gather(g, axis)
            # rank-0 mean (main_gather.py:53-55); other ranks contribute zeros
            mean0 = jnp.where(idx == 0, 1.0, 0.0).astype(g.dtype) * jnp.mean(
                gathered, axis=0)
            # collective 2: broadcast rank 0's mean (scatter, main_gather.py:59)
            g = lax.psum(mean0, axis)
            if self.sequential:
                token = g.ravel()[0].astype(jnp.float32)
            out.append(g)
        return jax.tree.unflatten(treedef, out)


class DDP:
    """Whole-pytree fused pmean — the idiomatic TPU path (reference
    main_ddp.py:137's DistributedDataParallel, minus the C++ machinery: XLA
    sees all 34 reductions at once and schedules/overlaps them itself)."""

    name = "ddp"
    needs_mesh = True
    supports_overlap = True
    bucket_bytes = BUCKET_CAP_MB * 1024 * 1024  # overlap marker grouping only

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        return jax.tree.map(lambda g: lax.pmean(g, axis), grads)

    def sync_bucket(self, leaves: list, axis: str) -> list:
        # per-leaf pmean: identical ops to __call__, so overlap=True is
        # bitwise-equal to the post-backward path regardless of bucketing
        return [lax.pmean(g, axis) for g in leaves]


class Bucketed:
    """Explicit DDP-style bucketing: pack leaves into ~bucket_mb buckets,
    one psum per bucket (torch DDP's Reducer with bucket_cap_mb=25,
    reference main_ddp.py:137's underlying engine)."""

    name = "bucketed"
    needs_mesh = True
    supports_overlap = True

    def __init__(self, bucket_mb: float = BUCKET_CAP_MB):
        self.bucket_bytes = int(bucket_mb * 1024 * 1024)

    def sync_bucket(self, leaves: list, axis: str) -> list:
        """One packed psum-mean over these leaves (a single bucket).  The
        psum is elementwise over devices, so the result is independent of
        how leaves are packed into buckets — post-backward and overlap
        bucketing agree bitwise whatever the bucket boundaries."""
        n = lax.axis_size(axis)
        flat = jnp.concatenate([g.ravel() for g in leaves])
        flat = lax.psum(flat, axis) / n
        out, offset = [], 0
        for g in leaves:
            out.append(flat[offset:offset + g.size].reshape(g.shape))
            offset += g.size
        return out

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        leaves, treedef = jax.tree.flatten(grads)
        out: list[jax.Array | None] = [None] * len(leaves)
        for bucket in make_bucket_plan(leaves, self.bucket_bytes):
            synced = self.sync_bucket([leaves[i] for i in bucket], axis)
            for i, s in zip(bucket, synced):
                out[i] = s
        return jax.tree.unflatten(treedef, out)


class QuantizedAllReduce:
    """Int8-quantized gradient all-reduce (the EQuARX/DynamiQ family of
    compressed collectives, e.g. arxiv.org/abs/2506.17615): per-tensor
    symmetric int8 quantization against a cross-replica-shared scale
    (pmax of |g|), integer psum, dequantize, mean.

    Scope note (honest accounting): with XLA's stock collectives the psum
    operand is int32, so the bytes on the wire match an fp32 all-reduce —
    this strategy demonstrates the *numerics* of quantized sync (shared
    scale makes the integer sum exact; only quantization loses precision,
    <1% relative error per tensor) and reserves the API slot.  For true
    wire compression see ``quantized_ring`` below, which moves int8 bytes
    on every hop.
    """

    name = "quantized"
    needs_mesh = True
    supports_overlap = True
    bucket_bytes = BUCKET_CAP_MB * 1024 * 1024  # overlap marker grouping only

    def __init__(self, bits: int = 8):
        self.levels = 2 ** (bits - 1) - 1  # 127 for int8

    def _sync_leaf(self, g: jax.Array, axis: str, n) -> jax.Array:
        g32 = g.astype(jnp.float32)
        absmax = lax.pmax(jnp.max(jnp.abs(g32)), axis)
        scale = jnp.maximum(absmax / self.levels, 1e-30)
        q = jnp.clip(jnp.round(g32 / scale), -self.levels,
                     self.levels).astype(jnp.int8)
        summed = lax.psum(q.astype(jnp.int32), axis)
        return (summed.astype(jnp.float32) * scale / n).astype(g.dtype)

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        n = lax.axis_size(axis)
        return jax.tree.map(lambda g: self._sync_leaf(g, axis, n), grads)

    def sync_bucket(self, leaves: list, axis: str) -> list:
        # per-leaf quantized all-reduce (the scale is per TENSOR, so the
        # bucket grouping cannot change numerics vs the post-backward path)
        n = lax.axis_size(axis)
        return [self._sync_leaf(g, axis, n) for g in leaves]


class QuantizedRing:
    """Int8 ring all-reduce with TRUE wire compression: a ring
    reduce-scatter followed by a ring all-gather built from ``ppermute``
    hops whose payloads are the int8 tensors themselves (plus one f32
    scale per ``block`` values, ~1.6% overhead).  Unlike ``quantized``
    (which feeds XLA's all_reduce int32, so full-width bytes move), every
    inter-chip transfer here is the quantized byte stream — the DynamiQ/
    EQuARX compressed-collective design point, expressed with JAX
    collectives instead of a custom RDMA kernel.

    Numerics: each reduce-scatter hop requantizes its partial sum, so
    quantization noise accumulates O(sqrt(n)) over the ring (the price of
    per-hop compression; block-wise scales keep the relative error ~1e-2
    at int8).  The all-gather forwards each reduced chunk's int8 payload
    verbatim — no further loss.

    vma note: every device dequantizes identical payloads, so the result
    is bitwise replicated by construction — but it is assembled from
    ``ppermute`` (varying) values, which the vma type system cannot prove
    invariant and there is no sanctioned downcast.  The trainer therefore
    runs this strategy with ``check_vma=False`` (see ``vma_opaque``).
    """

    name = "quantized_ring"
    needs_mesh = True
    vma_opaque = True  # replication holds by construction, not by proof
    supports_overlap = True

    def __init__(self, bits: int = 8, block: int = 256,
                 bucket_mb: float = BUCKET_CAP_MB):
        if bits not in (4, 8):
            raise ValueError(f"bits must be 4 or 8, got {bits}")
        self.bits = bits
        self.levels = 2 ** (bits - 1) - 1  # 127 at int8, 7 at int4
        self.block = block
        # One ring per ~bucket_mb bucket (make_bucket_plan, round 8): the
        # per-hop block scales are computed within each bucket's own flat
        # vector, so the ring's numerics depend on bucket LAYOUT — which is
        # why overlap=True and the post-backward path share one plan (and
        # why trees under the cap, every pre-round-8 test tree included,
        # pack to a single bucket bitwise-identical to the old whole-tree
        # flattening).
        self.bucket_bytes = int(bucket_mb * 1024 * 1024)

    def _plan(self, leaves: list) -> list[list[int]]:
        return make_bucket_plan(leaves, self.bucket_bytes)

    def _chunk(self, total: int, n: int) -> int:
        """Per-device ring chunk (block-aligned) for a ``total``-element
        flat vector over an ``n``-way ring."""
        return -(-total // (n * self.block)) * self.block

    def _quant(self, x: jax.Array):
        xb = x.reshape(-1, self.block)
        scale = jnp.maximum(
            jnp.max(jnp.abs(xb), axis=1, keepdims=True) / self.levels,
            1e-30)
        q = jnp.clip(jnp.round(xb / scale), -self.levels,
                     self.levels).astype(jnp.int8)
        return q, scale.astype(jnp.float32)

    def _dequant(self, q: jax.Array, scale: jax.Array) -> jax.Array:
        return (q.astype(jnp.float32) * scale).ravel()

    # -- int4 wire format (bits=4, round 16) ---------------------------
    # Quantized values live in [-7, 7]; two 4-bit two's-complement
    # nibbles ride per int8 lane on every ppermute, so the slow hop
    # moves HALF the int8 payload bytes.  block=256 keeps every chunk
    # even, so the lane pairing never needs padding.

    def _pack(self, q: jax.Array) -> jax.Array:
        """(..., even) int4-valued int8 -> flat int8 of half the size,
        low nibble first."""
        u = q.reshape(-1, 2).astype(jnp.uint8) & jnp.uint8(0xF)
        return (u[:, 0] | (u[:, 1] << 4)).astype(jnp.int8)

    def _unpack(self, packed: jax.Array, shape) -> jax.Array:
        """Inverse of ``_pack``: sign-extend both nibbles back to int8
        and restore ``shape``."""
        u = packed.astype(jnp.uint8)
        lo = ((u & jnp.uint8(0xF)).astype(jnp.int8) ^ 8) - 8
        hi = (((u >> 4) & jnp.uint8(0xF)).astype(jnp.int8) ^ 8) - 8
        return jnp.stack([lo, hi], axis=-1).astype(jnp.int8).reshape(shape)

    def _wire(self, q: jax.Array, axis: str, perm) -> jax.Array:
        """ppermute the quantized payload; at bits=4 the lanes are
        nibble-packed around the hop so the wire carries q.size/2
        bytes (the jaxpr pin in tests/test_lowbit.py measures this)."""
        if self.bits == 8:
            return lax.ppermute(q, axis, perm)
        return self._unpack(lax.ppermute(self._pack(q), axis, perm),
                            q.shape)

    def _ring_sum(self, flat: jax.Array, axis: str, n,
                  residual: jax.Array | None = None):
        """The int8 ring: reduce-scatter then all-gather, int8 + per-block
        f32 scales on every hop.  Returns ``(summed[:total], err_rows)``
        where ``summed`` is the (approximate) cross-device SUM of ``flat``
        and ``err_rows`` is the (n, chunk) array of quantization errors
        THIS device dropped (always computed; the plain strategy discards
        it and XLA dead-code-eliminates the bookkeeping).  With
        ``residual`` (error feedback), last step's dropped errors are
        added to this step's chunk contributions first."""
        total = flat.size
        me = lax.axis_index(axis)
        chunk = -(-total // (n * self.block)) * self.block
        parts = jnp.pad(flat, (0, n * chunk - total)).reshape(n, chunk)
        if residual is not None:
            parts = parts + residual.reshape(n, chunk)
        perm = [(i, (i + 1) % n) for i in range(n)]

        # -- ring reduce-scatter (int8 + scales per hop) -------------------
        # After t hops my accumulator holds the partial sum of chunk
        # (me - t) mod n over devices {me-t, ..., me}.
        acc = lax.dynamic_index_in_dim(parts, me, 0, keepdims=False)
        err_rows = jnp.zeros((n, chunk), jnp.float32)

        def rs_step(carry, t):
            acc, err_rows = carry
            q, s = self._quant(acc)
            # chunk (me - t) mod n leaves this device quantized; record the
            # dropped error (EF uses it; otherwise DCE'd)
            err_rows = lax.dynamic_update_index_in_dim(
                err_rows, acc - self._dequant(q, s), jnp.mod(me - t, n), 0)
            q = self._wire(q, axis, perm)
            s = lax.ppermute(s, axis, perm)
            idx = jnp.mod(me - t - 1, n)
            nxt = self._dequant(q, s) + lax.dynamic_index_in_dim(
                parts, idx, 0, keepdims=False)
            return (nxt, err_rows), None

        (acc, err_rows), _ = lax.scan(rs_step, (acc, err_rows),
                                      jnp.arange(n - 1))
        # acc == full sum of chunk (me + 1) mod n

        # -- ring all-gather (int8 payloads forwarded verbatim) ------------
        qf, sf = self._quant(acc)
        own = jnp.mod(me + 1, n)
        # the broadcast copy everyone (including us) uses is dequantized
        err_rows = lax.dynamic_update_index_in_dim(
            err_rows, acc - self._dequant(qf, sf), own, 0)
        q_all = lax.dynamic_update_index_in_dim(
            jnp.zeros((n,) + qf.shape, jnp.int8), qf, own, 0)
        s_all = lax.dynamic_update_index_in_dim(
            jnp.zeros((n,) + sf.shape, jnp.float32), sf, own, 0)

        def ag_step(carry, t):
            q_all, s_all, cur_q, cur_s = carry
            cur_q = self._wire(cur_q, axis, perm)
            cur_s = lax.ppermute(cur_s, axis, perm)
            # payload received at hop t originated at device me-(t+1),
            # i.e. holds reduced chunk (me - t) mod n
            src = jnp.mod(me - t, n)
            q_all = lax.dynamic_update_index_in_dim(q_all, cur_q, src, 0)
            s_all = lax.dynamic_update_index_in_dim(s_all, cur_s, src, 0)
            return (q_all, s_all, cur_q, cur_s), None

        (q_all, s_all, _, _), _ = lax.scan(
            ag_step, (q_all, s_all, qf, sf), jnp.arange(n - 1))
        summed = (q_all.astype(jnp.float32) * s_all).reshape(-1)[:total]
        return summed, err_rows

    def _split(self, mean: jax.Array, leaves: list) -> list:
        out, offset = [], 0
        for g in leaves:
            out.append(mean[offset:offset + g.size]
                       .reshape(g.shape).astype(g.dtype))
            offset += g.size
        return out

    def sync_bucket(self, leaves: list, axis: str) -> list:
        """One int8 ring over this bucket's flat (tree-order) vector."""
        n = lax.axis_size(axis)
        flat = jnp.concatenate([g.ravel().astype(jnp.float32)
                                for g in leaves])
        if n == 1:
            mean = flat
        else:
            summed, _ = self._ring_sum(flat, axis, n)
            mean = summed / n
        return self._split(mean, leaves)

    def __call__(self, grads: PyTree, axis: str) -> PyTree:
        leaves, treedef = jax.tree.flatten(grads)
        out: list[jax.Array | None] = [None] * len(leaves)
        for bucket in self._plan(leaves):
            synced = self.sync_bucket([leaves[i] for i in bucket], axis)
            for i, s in zip(bucket, synced):
                out[i] = s
        return jax.tree.unflatten(treedef, out)


class QuantizedRingEF(QuantizedRing):
    """``quantized_ring`` + error feedback (EF-SGD / EF21 family): every
    quantization error the ring DROPS is recorded locally and fed back
    into the next step's contribution, so compressed sync converges like
    exact sync instead of degrading O(sqrt(n)) with ring size.

    Exact bookkeeping, not an approximation: in the reduce-scatter, device
    d at hop t quantizes its partial sum of chunk (d-t) mod n — the
    residual ``acc - dequant(Q(acc))`` is precisely what the global sum
    loses at that hop, and d is the only device that knows it.  The final
    all-gather quantization of chunk (d+1) mod n drops one more residual.
    Each device therefore records exactly one residual per chunk row per
    step; adding the carried residuals to next step's (sum-space) chunk
    contributions restores them.  Invariant (pinned by tests):

        n * synced_mean + psum(residuals) == exact gradient sum   (to f32)

    i.e. nothing is ever lost — only delayed one step.

    State: one f32 vector per device — the per-bucket padded residuals
    concatenated in bucket-plan order (a single segment, the padded flat
    gradient size, for trees under the bucket cap) — carried through the
    train step's scan like BN state (leading device axis, sharded over the
    data axis).  Dropping the state on restart is safe (residuals
    re-accumulate within a step).  Under ``overlap=True`` the same layout
    threads through the scan carry with each bucket's segment consumed and
    refilled by that bucket's in-backward marker (``OverlapSync``).
    """

    name = "quantized_ring_ef"
    stateful = True  # __call__ takes and returns the residual carry

    def state_segments(self, leaves: list, n_axis: int) -> list[int]:
        """Per-bucket residual lengths (n_axis * block-aligned chunk), in
        bucket-plan order — the layout contract between ``init_state``,
        ``__call__``, and the overlap markers."""
        return [n_axis * self._chunk(sum(leaves[i].size for i in bucket),
                                     n_axis)
                for bucket in self._plan(leaves)]

    def init_state(self, params: PyTree, n_axis: int) -> jax.Array:
        """Per-device zero residual for a gradient pytree shaped like
        ``params`` over an ``n_axis``-way ring (local, unstacked view)."""
        leaves = jax.tree.leaves(params)
        return jnp.zeros((sum(self.state_segments(leaves, n_axis)),),
                         jnp.float32)

    def sync_bucket(self, leaves: list, axis: str,
                    residual: jax.Array) -> tuple[list, jax.Array]:
        """One error-feedback int8 ring over this bucket; ``residual`` is
        the bucket's state segment, returned updated."""
        n = lax.axis_size(axis)
        flat = jnp.concatenate([g.ravel().astype(jnp.float32)
                                for g in leaves])
        if n == 1:
            mean, new_res = flat, jnp.zeros_like(residual)
        else:
            summed, err_rows = self._ring_sum(flat, axis, n,
                                              residual=residual)
            mean, new_res = summed / n, err_rows.ravel()
        return self._split(mean, leaves), new_res

    def __call__(self, grads: PyTree, axis: str,
                 residual: jax.Array) -> tuple[PyTree, jax.Array]:
        n = lax.axis_size(axis)
        leaves, treedef = jax.tree.flatten(grads)
        out: list[jax.Array | None] = [None] * len(leaves)
        segs = self.state_segments(leaves, n)
        new_parts, offset = [], 0
        for bucket, seg in zip(self._plan(leaves), segs):
            synced, new_r = self.sync_bucket(
                [leaves[i] for i in bucket], axis,
                residual[offset:offset + seg])
            offset += seg
            new_parts.append(new_r)
            for i, s in zip(bucket, synced):
                out[i] = s
        return (jax.tree.unflatten(treedef, out),
                jnp.concatenate(new_parts))


class Hierarchical:
    """Two-level (within-slice ICI, cross-slice DCN) gradient mean for
    multi-slice data parallelism.

    The reference's real topology is N nodes over TCP (start_ddp.sh:1 — a
    flat Gloo ring).  At TPU-pod scale the data axis factors into two links
    with ~100x different bandwidth: ICI within a slice and DCN across
    slices.  A flat psum over the combined axis runs the slow ring over
    DCN with the FULL gradient payload; the right algorithm is the
    standard two-level reduction (the scaling-book multi-slice recipe):

      1. ``psum_scatter`` over ``'ici'`` — each chip ends with a 1/ici
         shard of its slice's summed gradient (bandwidth-optimal within
         the slice);
      2. ``psum`` over ``'dcn'`` — slices exchange only the 1/ici shard,
         so cross-slice traffic drops by the ici degree;
      3. all-gather over ``'ici'`` — the full mean returns on the fast
         link.

    Total DCN bytes per step: |grads|/ici vs |grads| for the flat psum.
    The result is the exact global mean, so numerics match ``ddp``
    (pinned by tests/test_strategies.py vs ddp on a 2x4 virtual mesh).

    The gather-back uses ``all_gather_invariant`` so the result is
    *provably* replicated (vma-invariant) over both axes — this strategy
    needs no ``check_vma=False`` escape hatch.  On a jax without it, the
    fallback embeds each shard at its offset and psums over ``'ici'``
    (same result, provable, 2x the ICI bytes of the gather).

    Runs over ``Mesh(('dcn', 'ici'))`` — the trainer builds it from
    ``TrainConfig.dcn_size`` (number of slices).  With a single flat axis
    (or axis size 1 on either level) it degrades gracefully to the exact
    flat mean.

    ``dcn_compress="int8"`` (round 9, ``TrainConfig.dcn_compress``)
    additionally quantizes ONLY the slow hop: step 2's shard exchange
    runs as an int8 ring over ``'dcn'`` (``QuantizedRing._ring_sum`` —
    int8 payloads + per-256-row f32 scales on every cross-slice
    transfer, the DynamiQ/EQuARX compress-the-scarce-link design point)
    while the ICI reduce-scatter/all-gather stay full-precision.  Every
    bit the wire drops lands in a per-device error-feedback residual
    threaded through the trainer's stateful sync-state channel (the
    ``quantized_ring_ef`` carry), so compressed sync converges like
    exact sync with one step of delay.  Compression makes the strategy
    stateful AND vma-opaque (the ring assembles its result from
    ppermute payloads — replicated by construction, not by proof);
    numerics become bucket-LAYOUT-dependent through the row scales, so
    post-backward and overlap share ONE ``make_bucket_plan`` packing
    exactly like the int8 rings.

    ``dcn_compress="int4"`` (round 16) is the same machinery one rung
    lower: the ring quantizes to [-7, 7] and nibble-packs two values
    per int8 lane around every ppermute, so the scarce hop carries
    ~0.51x the int8 bytes (0.5 + 1/64 scale overhead per element vs
    1 + 1/64).  Error feedback absorbs the coarser rounding the same
    way — the EF invariant and the ddp-curve pins hold bit-for-bit in
    structure, only the per-step quantization noise grows.
    """

    name = "hierarchical"
    needs_mesh = True
    axes = ("dcn", "ici")  # outer = cross-slice (slow), inner = within-slice
    supports_overlap = True

    def __init__(self, dcn_compress: str | None = None, dcn_size: int = 2,
                 bucket_mb: float = BUCKET_CAP_MB):
        self.bucket_bytes = int(bucket_mb * 1024 * 1024)
        self.set_dcn(dcn_compress, dcn_size)

    def set_dcn(self, compress: str | None, dcn_size: int) -> None:
        """Configure the slow-hop compression (the trainers propagate
        ``TrainConfig.dcn_compress``/``dcn_size`` here before building the
        step OR the sync state — the EF residual layout needs dcn_size)."""
        if compress not in (None, "int8", "int4"):
            raise ValueError(f"dcn_compress must be None, 'int8', or "
                             f"'int4', got {compress!r}")
        self.dcn_compress = compress
        self.dcn_size = dcn_size
        # quant/dequant/_ring_sum at the wire's bit width; the _chunk
        # layout is bits-independent, so the EF residual sizing (and
        # every sync-state contract built on it) is stable across rungs
        self._ring = QuantizedRing(bits=4 if compress == "int4" else 8)
        # compression adds the EF residual carry and gives up the static
        # replication proof (ppermute ring on the dcn hop)
        self.stateful = compress is not None
        self.vma_opaque = compress is not None

    @staticmethod
    def _factor(axis) -> tuple[str | None, str]:
        if isinstance(axis, str):
            return None, axis
        dcn, ici = axis
        return dcn, ici

    # -- EF residual layout (dcn_compress only) ---------------------------
    def _shard_len(self, total: int, n_ici: int) -> int:
        """Per-chip ICI shard length of a ``total``-element bucket
        (psum_scatter pads the flat vector to an n_ici multiple)."""
        return -(-total // n_ici)

    def _segments(self, leaves: list, n_dcn: int, n_ici: int) -> list[int]:
        return [n_dcn * self._ring._chunk(
                    self._shard_len(sum(leaves[i].size for i in b), n_ici),
                    n_dcn)
                for b in make_bucket_plan(leaves, self.bucket_bytes)]

    def state_segments(self, leaves: list, n_axis: int) -> list[int]:
        """Per-bucket residual lengths (n_dcn x the dcn-ring chunk of the
        ICI shard), bucket-plan order — the layout contract between
        ``init_state``, ``__call__``, and the overlap markers."""
        n_ici = n_axis // self.dcn_size
        return self._segments(leaves, self.dcn_size, n_ici)

    def init_state(self, params: PyTree, n_axis: int) -> jax.Array:
        if self.dcn_compress is None:
            return jnp.zeros((0,), jnp.float32)
        leaves = jax.tree.leaves(params)
        return jnp.zeros((sum(self.state_segments(leaves, n_axis)),),
                         jnp.float32)

    def _int8_dcn_reduce(self, dcn, n_dcn, residual, out: dict):
        """The compressed slow hop: a ``shard -> summed_shard`` callable
        for ``two_level_psum(dcn_reduce=...)`` that runs the shard
        exchange as a quantized ring over ``dcn`` at the configured bit
        width (int8, or nibble-packed int4) and records the dropped
        quantization error (the EF residual) in ``out``."""
        def reduce(shard):
            if n_dcn == 1:  # degraded topology: nothing crosses, no loss
                out["res"] = jnp.zeros_like(residual)
                return shard
            summed, err_rows = self._ring._ring_sum(
                shard, dcn, n_dcn, residual=residual)
            out["res"] = err_rows.ravel()
            return summed
        return reduce

    def sync_bucket(self, leaves: list, axis, residual: jax.Array | None
                    = None):
        # one two-level (reduce-scatter / shard-sized DCN exchange /
        # gather) reduction per bucket; the plain exchange is elementwise
        # over devices, so post-backward (whole-tree) and overlap
        # (per-bucket) sum the same addends per element either way.  The
        # int8 exchange quantizes against per-row scales of the bucket's
        # OWN shard, so compressed mode shares the bucket plan instead.
        dcn, ici = self._factor(axis)
        n_dcn = lax.axis_size(dcn) if dcn else 1
        n = lax.axis_size(ici) * n_dcn
        # the mean division happens on the f32 sum INSIDE two_level_psum
        # (before the cast back to leaf dtype): low-precision leaves must
        # not see the undivided sum, which can overflow their range
        if self.dcn_compress is None:
            return two_level_psum(leaves, dcn, ici, scale=1.0 / n)
        out: dict = {}
        synced = two_level_psum(
            leaves, dcn, ici, scale=1.0 / n,
            dcn_reduce=self._int8_dcn_reduce(dcn, n_dcn, residual, out))
        return synced, out["res"]

    # -- communication-sparse windows (round 18) ----------------------------
    # Local-SGD on the factored mesh splits the per-step sync in two:
    # ``local_sync`` runs EVERY step (the fast within-slice mean — exactly
    # the per-step path's ICI ops, zero DCN ops) and ``window_exchange``
    # runs only at window boundaries (the slow cross-slice hop over the
    # accumulated update delta, shard-sized like the per-step DCN payload).
    # DCN bytes per step therefore scale ~1/H while ICI bytes are
    # unchanged — the claim tests/test_localsgd.py measures per axis from
    # the schedule inspector.

    def local_sync(self, grads: PyTree, axis) -> PyTree:
        """Within-slice (ICI-only) gradient mean for a LOCAL step of a
        ``sync_every > 1`` window: the per-step reduce-scatter/all-gather
        over ``ici`` with NO cross-slice hop — each slice steps on its own
        slice-mean gradient.  Compression never applies here (it is the
        DCN hop's knob), so this path is stateless and vma-provable
        regardless of ``dcn_compress``."""
        _, ici = self._factor(axis)
        return two_level_psum(grads, None, ici,
                              scale=1.0 / lax.axis_size(ici))

    def window_exchange(self, delta: PyTree, axis,
                        sync_state: jax.Array | None = None):
        """Cross-slice mean of the window's accumulated update ``delta``
        (slice-uniform after H ``local_sync`` steps): each chip takes its
        own static ICI-indexed chunk of the flat delta (free — the value
        is already replicated within the slice, so slicing replaces the
        per-step reduce-scatter), exchanges ONLY that shard over ``dcn``
        (plain psum, or the int8/int4+EF ring under ``dcn_compress`` —
        same chunk length as the per-step exchange, so the EF residual
        layout and ``init_state`` are unchanged), gathers back over
        ``ici``, and divides by the slice count.  Stateful form returns
        ``(mean_delta, new_residual)``.

        Round 20: the window is the routed plan ``ici:slice → [dcn
        exchange] → ici:ag`` (the 'slice' rs algorithm encodes
        "already replicated within the slice") executed per bucket by
        ``parallel/routing.execute`` — same ops, same EF layout."""
        from . import routing
        dcn, ici = self._factor(axis)
        n_dcn = lax.axis_size(dcn) if dcn else 1
        n_ici = lax.axis_size(ici)
        leaves, treedef = jax.tree.flatten(delta)
        out: list[jax.Array | None] = [None] * len(leaves)
        segs = self._segments(leaves, n_dcn, n_ici)
        hops: list = [routing.Hop("rs", ici, algorithm="slice")]
        if dcn is not None:
            hops.append(routing.Hop("exchange", dcn))
        hops.append(routing.Hop("ag", ici))
        plan = routing.HopPlan(tuple(hops))
        new_parts, offset = [], 0
        for bucket, seg in zip(make_bucket_plan(leaves, self.bucket_bytes),
                               segs):
            sub = [leaves[i] for i in bucket]
            overrides = None
            captured: dict = {}
            if self.dcn_compress is not None:
                residual = sync_state[offset:offset + seg]
                offset += seg
                if dcn is not None:
                    overrides = {dcn: self._int8_dcn_reduce(
                        dcn, n_dcn, residual, captured)}
                else:  # degraded topology: nothing crosses, no loss
                    captured["res"] = jnp.zeros_like(residual)
            synced, _ = routing.execute(plan, sub, scale=1.0 / n_dcn,
                                        overrides=overrides)
            if self.dcn_compress is not None:
                new_parts.append(captured["res"])
            for i, s in zip(bucket, synced):
                out[i] = s
        tree = jax.tree.unflatten(treedef, out)
        if self.dcn_compress is None:
            return tree
        return tree, jnp.concatenate(new_parts)

    def _split(self, mean: jax.Array, leaves: list) -> list:
        out, offset = [], 0
        for g in leaves:
            out.append(mean[offset:offset + g.size]
                       .reshape(g.shape).astype(g.dtype))
            offset += g.size
        return out

    def __call__(self, grads: PyTree, axis,
                 sync_state: jax.Array | None = None):
        dcn, ici = self._factor(axis)
        if self.dcn_compress is None:
            n = lax.axis_size(ici) * (lax.axis_size(dcn) if dcn else 1)
            return two_level_psum(grads, dcn, ici, scale=1.0 / n)
        # compressed: one ring-exchanged two-level reduction per plan
        # bucket, residual segments consumed/refilled in plan order
        leaves, treedef = jax.tree.flatten(grads)
        out: list[jax.Array | None] = [None] * len(leaves)
        n_dcn = lax.axis_size(dcn) if dcn else 1
        segs = self._segments(leaves, n_dcn, lax.axis_size(ici))
        new_parts, offset = [], 0
        for bucket, seg in zip(make_bucket_plan(leaves, self.bucket_bytes),
                               segs):
            synced, new_r = self.sync_bucket(
                [leaves[i] for i in bucket], axis,
                sync_state[offset:offset + seg])
            offset += seg
            new_parts.append(new_r)
            for i, s in zip(bucket, synced):
                out[i] = s
        return (jax.tree.unflatten(treedef, out),
                jnp.concatenate(new_parts))


def two_level_psum(grads: PyTree, dcn: str | None, ici: str,
                   scale: float | None = None,
                   dcn_reduce: Callable | None = None) -> PyTree:
    """The two-level reduction underlying ``Hierarchical`` (steps 1-3 of
    its docstring): reduce-scatter over ``ici``, a SHARD-SIZED ``psum``
    over ``dcn`` (the only cross-slice traffic — |grads|/ici bytes, a
    claim scripts/bench_strategies.py now MEASURES per axis from the
    schedule inspector rather than asserts), ``all_gather_invariant``
    back over ``ici``.  ``scale`` (e.g. 1/n for a mean) applies to the
    f32 sum before the cast back to each leaf's dtype.  ``dcn_reduce``
    replaces the stock ``psum`` on the slow hop with a ``shard ->
    summed_shard`` callable — ``Hierarchical(dcn_compress='int8')``
    plugs its quantized ring exchange in here, leaving steps 1 and 3
    untouched.  Output is provably replicated over both axes (with the
    stock hop; a ppermute-based ``dcn_reduce`` forfeits the proof — see
    ``Hierarchical.vma_opaque``).  Shared with the LM trainer's
    factored-mesh gradient sync (lm.py dcn_size), whose jaxpr test pins
    the shard-sized DCN payload.

    Round 20: the hand-built loop is retired — the body is now the
    2-level ``HopPlan`` ``ici:rs → [dcn:psum] → ici:ag`` compiled by
    ``parallel/routing.execute``, which emits the identical op sequence
    (pad → psum_scatter → exchange → all_gather_invariant → slice →
    scale → split); every pre-existing bitwise pin on this function now
    pins the route compiler transitively."""
    from . import routing
    hops: list = [routing.Hop("rs", ici)]
    if dcn is not None:
        hops.append(routing.Hop("exchange", dcn))
    hops.append(routing.Hop("ag", ici))
    overrides = ({dcn: dcn_reduce}
                 if dcn is not None and dcn_reduce is not None else None)
    synced, _ = routing.execute(routing.HopPlan(tuple(hops)), grads,
                                scale=scale, overrides=overrides)
    return synced


# -- DiLoCo outer optimizer over window deltas (round 22) -------------------
#
# The round-18 window boundary applies the plain cross-slice MEAN of the
# accumulated deltas to the anchor.  The DiLoCo recipe (PAPERS.md) keeps
# an OUTER optimizer state on the anchor instead: the mean delta is the
# outer "gradient", and Nesterov/heavy-ball momentum over it lets a much
# wider window (H=8+) track the per-step trajectory — the "wider window
# at matched quality" claim tests/test_diloco.py measures with the
# round-18 convergence-band methodology.

class OuterOptimizer:
    """The window-boundary anchor update ``anchor <- anchor + lr * step``
    where ``step`` is Nesterov (``mu*m' + d``) or heavy-ball (``m'``)
    momentum over the exchanged mean delta (``m' = mu*m + d``).  Momentum
    state is f32, anchor-shaped; arithmetic runs in f32 and casts back to
    each leaf's dtype.

    ``trivial`` (mu == 0 and lr == 1) marks the configuration whose
    update IS the plain mean fold-in: the trainers branch at BUILD time
    and emit the round-18 ``jnp.add`` path with no momentum state at all,
    so zero-momentum outer-opt is bitwise (and jaxpr-census) identical to
    plain mean — the same build-time-branch discipline that keeps
    ``sync_every=1`` out of the windowed builders."""

    KINDS = ("nesterov", "momentum")

    def __init__(self, kind: str, momentum: float = 0.9,
                 lr: float = 1.0):
        if kind not in self.KINDS:
            raise ValueError(f"outer_opt must be one of {self.KINDS} "
                             f"(or None for the plain mean), got {kind!r}")
        self.kind = kind
        self.momentum = float(momentum)
        self.lr = float(lr)

    @property
    def trivial(self) -> bool:
        """True when the update degenerates to ``anchor + d_avg`` exactly
        — callers must then take the plain-mean build path (bitwise)."""
        return self.momentum == 0.0 and self.lr == 1.0

    # -- tree form (the LM trainer's anchor-shaped momentum) ---------------
    def init_state(self, anchor: PyTree) -> PyTree:
        """f32 zero momentum, one leaf per anchor leaf."""
        return jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), anchor)

    def apply(self, anchor: PyTree, d_avg: PyTree,
              m: PyTree) -> tuple[PyTree, PyTree]:
        """One outer step: ``(new_anchor, new_momentum)``.  Static Python
        branch on ``trivial`` so the degenerate config emits exactly the
        round-18 plain-mean ops."""
        if self.trivial:
            return jax.tree.map(jnp.add, anchor, d_avg), m
        mu = self.momentum
        m = jax.tree.map(
            lambda d, mi: mu * mi + d.astype(jnp.float32), d_avg, m)
        if self.kind == "nesterov":
            step = jax.tree.map(
                lambda d, mi: mu * mi + d.astype(jnp.float32), d_avg, m)
        else:
            step = m
        anchor = jax.tree.map(
            lambda a, s: (a.astype(jnp.float32)
                          + self.lr * s).astype(a.dtype), anchor, step)
        return anchor, m

    # -- flat form (the VGG trainer packs momentum into the sync-state
    #    carry, after the EF residual segments) ----------------------------
    @staticmethod
    def state_len(params: PyTree) -> int:
        return sum(int(p.size) for p in jax.tree.leaves(params))

    def init_flat(self, params: PyTree) -> jax.Array:
        return jnp.zeros((self.state_len(params),), jnp.float32)

    def apply_flat(self, anchor: PyTree, d_avg: PyTree,
                   flat_m: jax.Array) -> tuple[PyTree, jax.Array]:
        """``apply`` with the momentum held as ONE flat f32 vector (leaf
        order, ravelled) — the layout that rides train.py's per-device
        sync-state carry next to the EF residual segments."""
        if self.trivial:
            return jax.tree.map(jnp.add, anchor, d_avg), flat_m
        leaves, treedef = jax.tree.flatten(anchor)
        m_leaves, offset = [], 0
        for leaf in leaves:
            m_leaves.append(flat_m[offset:offset + leaf.size]
                            .reshape(leaf.shape))
            offset += leaf.size
        m_tree = jax.tree.unflatten(treedef, m_leaves)
        anchor, m_tree = self.apply(anchor, d_avg, m_tree)
        return anchor, jnp.concatenate(
            [m.ravel() for m in jax.tree.leaves(m_tree)])


# -- backward-overlapped gradient sync (round 8) ---------------------------
#
# The one trick torch DDP plays that the post-backward strategies above do
# not: its Reducer launches each ~25 MB bucket's all-reduce from a C++
# autograd hook the moment the bucket's gradients are produced, hiding the
# collective under the remaining backward compute.  The JAX analogue is a
# custom_vjp identity ("sync point") wrapping each bucket's params at the
# bucket's EARLIEST layer-group boundary in the model's forward pass: the
# transpose visits forward equations in reverse, so the marker's backward
# rule — which runs the bucket's collective on the accumulated cotangents —
# lands in the backward graph immediately after that layer group's backward
# matmuls, with every later bucket's collective already emitted.  XLA's
# latency-hiding scheduler can then run bucket N's collective concurrently
# with layer N-1's backward dot_generals (utils/debug.py op_schedule pins
# the interleaving; train.py overlap=True wires it up).

def sync_boundary(tree: PyTree, sync_fn: Callable[[PyTree], PyTree],
                  group_id: int | str | None = None) -> PyTree:
    """Identity on ``tree`` whose BACKWARD applies ``sync_fn`` to the
    accumulated cotangents at this position in the backward graph — the
    in-backward bucket collective of overlap mode.  ``group_id`` is
    documentation/debugging only (the layer group whose boundary this is).
    """

    @jax.custom_vjp
    def point(t):
        return t

    def fwd(t):
        return t, None

    def bwd(_, ct):
        return (sync_fn(ct),)

    point.defvjp(fwd, bwd)
    return point(tree)


def sync_boundary_stateful(
        tree: PyTree, residual: jax.Array,
        sync_fn: Callable[[PyTree, jax.Array], tuple[PyTree, jax.Array]],
        group_id: int | str | None = None) -> PyTree:
    """``sync_boundary`` for stateful (error-feedback) strategies: the
    residual rides the forward as an inert input and its COTANGENT channel
    carries the updated residual out of the backward — differentiate the
    loss w.r.t. ``(params, sync_state)`` and the sync-state "gradient" IS
    the next step's residual carry (train.py overlap=True threads it back
    into the scan carry).  ``sync_fn(cotangents, residual) -> (synced,
    new_residual)``."""

    @jax.custom_vjp
    def point(t, r):
        return t

    def fwd(t, r):
        return t, r

    def bwd(r, ct):
        synced, new_r = sync_fn(ct, r)
        return synced, new_r

    point.defvjp(fwd, bwd)
    return point(tree, residual)


def _leaf_group(path, group_index: dict) -> int:
    """Map a leaf's tree path to its model layer group via the top-level
    key (models expose ``sync_group_index``)."""
    entry = path[0]
    key = getattr(entry, "key", None)
    if key is None:  # tuple-style paths on older tree_util
        key = str(entry)
    try:
        return group_index[key]
    except KeyError:
        raise ValueError(
            f"param key {key!r} missing from the model's sync_group_index "
            f"map; overlap needs every top-level param entry assigned to a "
            f"forward layer group") from None


class OverlapSync:
    """Per-trace orchestrator for backward-overlapped gradient sync.

    Packs the param tree's leaves into reverse-topological ~bucket_bytes
    buckets (``make_bucket_plan`` — the SAME plan the bucketed/ring
    strategies use post-backward, so overlap=True compares bitwise against
    an equally-bucketed post-backward step), then inserts one sync-point
    marker per bucket at the bucket's earliest layer-group boundary.

    Usage (inside the loss function, fresh per trace):

        ov = OverlapSync(strategy, axis, params, model.sync_group_index(...),
                         sync_state=residual_or_None)
        logits = model.apply(params, ..., boundary=ov.boundary)

    The model calls ``params = boundary(group, params)`` at each layer-group
    boundary in forward order; the returned tree has the due buckets' leaves
    wrapped so their cotangents are synced in-backward.  For stateful
    strategies the residual's updated value comes back as the sync_state
    argument's gradient (see ``sync_boundary_stateful``).
    """

    def __init__(self, strategy, axis, params: PyTree,
                 group_index: dict, *, sync_state: jax.Array | None = None):
        require_overlap_capable(strategy)
        self.strategy, self.axis = strategy, axis
        flat, self.treedef = jax.tree_util.tree_flatten_with_path(params)
        self.leaves = [leaf for _, leaf in flat]
        groups = [_leaf_group(path, group_index) for path, _ in flat]
        self.plan = make_bucket_plan(self.leaves, strategy.bucket_bytes)
        self.stateful = getattr(strategy, "stateful", False)
        if self.stateful:
            if sync_state is None:
                raise ValueError(
                    f"stateful strategy {strategy.name!r} needs sync_state "
                    f"for overlap (the per-device EF residual)")
            # total device count over a possibly-factored axis (the
            # hierarchical strategy runs over the ('dcn', 'ici') tuple)
            n_axis = 1
            for a in ((axis,) if isinstance(axis, str) else tuple(axis)):
                n_axis *= lax.axis_size(a)
            segs = strategy.state_segments(self.leaves, n_axis)
            offs = [0]
            for s in segs:
                offs.append(offs[-1] + s)
            self._res = [sync_state[a:b] for a, b in zip(offs, offs[1:])]
        # bucket b fires at the boundary of its earliest forward group:
        # by then every later group's backward (hence every cotangent the
        # bucket needs) is complete
        self._due: dict[int, list[int]] = {}
        for b, bucket in enumerate(self.plan):
            trigger = min(groups[i] for i in bucket)
            self._due.setdefault(trigger, []).append(b)
        self._marked: set[int] = set()

    def boundary(self, group: int, params: PyTree) -> PyTree:
        """Mark the buckets due at this layer-group boundary; returns the
        params tree with those buckets' leaves replaced by sync-point
        outputs (identity forward, in-backward collective)."""
        due = self._due.get(group)
        if not due:
            return params
        leaves = [leaf for _, leaf in
                  jax.tree_util.tree_flatten_with_path(params)[0]]
        # later boundaries must see earlier markers' outputs: refresh from
        # the incoming tree, then overlay this boundary's markers
        self.leaves = leaves
        for b in due:
            assert b not in self._marked, (b, group)
            self._marked.add(b)
            bucket = self.plan[b]
            sub = tuple(self.leaves[i] for i in bucket)
            if self.stateful:
                def sync_fn(ct, r):
                    synced, new_r = self.strategy.sync_bucket(
                        list(ct), self.axis, r)
                    return tuple(synced), new_r
                marked = sync_boundary_stateful(sub, self._res[b], sync_fn,
                                                group_id=group)
            else:
                def sync_fn(ct):
                    return tuple(self.strategy.sync_bucket(list(ct),
                                                           self.axis))
                marked = sync_boundary(sub, sync_fn, group_id=group)
            for i, m in zip(bucket, marked):
                self.leaves[i] = m
        return jax.tree_util.tree_unflatten(self.treedef, self.leaves)


_REGISTRY: dict[str, Callable[[], Strategy]] = {
    "none": NoSync,
    "all_reduce": AllReduce,
    "gather_scatter": GatherScatter,
    "gather_scatter_symmetric": GatherScatterSymmetric,
    "ddp": DDP,
    "bucketed": Bucketed,
    "quantized": QuantizedAllReduce,
    "quantized_ring": QuantizedRing,
    "quantized_ring_ef": QuantizedRingEF,
    "hierarchical": Hierarchical,
}


def get(name: str) -> Strategy:
    """Look up a strategy by name (the pluggable axis the reference's five
    copy-pasted scripts should have had)."""
    try:
        return _REGISTRY[name]()
    except KeyError:
        if name == "auto":
            # "auto" is not a strategy: it resolves TO one.  The Trainer
            # does that (parallel/autotune.resolve_train_auto) before any
            # registry lookup; reaching here means a caller skipped it.
            raise ValueError(
                "strategy 'auto' must be resolved to a named strategy "
                "first (train.Trainer does this via "
                "parallel/autotune.resolve_train_auto); the registry "
                f"holds only concrete strategies: {sorted(_REGISTRY)}"
            ) from None
        raise ValueError(
            f"unknown strategy {name!r}; available: {sorted(_REGISTRY)}"
        ) from None


def available() -> list[str]:
    return sorted(_REGISTRY)


def overlap_capable() -> list[str]:
    """Strategies usable with ``TrainConfig(overlap=True)`` (they expose
    ``sync_bucket``, the per-bucket collective the in-backward markers
    call).  The sequential-by-design baselines (all_reduce, the
    gather_scatter pair) are deliberately excluded: their point is the
    serialized wire pattern overlap would dissolve (module docstring,
    'preserving naivety on purpose')."""
    return sorted(n for n, c in _REGISTRY.items()
                  if getattr(c, "supports_overlap", False))


# -- overlap capability checks (round 9): the ONE definition site ----------
#
# Both trainers used to hand-roll their overlap refusals (train.py's
# strategy check and lm.py's fsdp/dcn check), which let the two messages —
# and worse, the two CONDITIONS — drift.  They now both call here, next to
# the machinery (OverlapSync) whose capabilities the checks describe.

def require_overlap_capable(strategy) -> None:
    """Raise unless ``strategy`` can run as in-backward bucket collectives
    (``TrainConfig(overlap=True)``); shared by the VGG trainer's config
    validation and ``OverlapSync`` itself, so the refusal and the
    machinery can never disagree."""
    if not getattr(strategy, "supports_overlap", False):
        raise ValueError(
            f"strategy {strategy.name!r} does not support overlap=True; "
            f"overlap-capable strategies: {overlap_capable()} (the "
            f"sequential baselines keep their serialized wire pattern on "
            f"purpose)")


def require_lm_overlap_streamable(*, fsdp: bool, dcn: bool,
                                  pp: bool = False) -> None:
    """The LM trainer's overlap capability check
    (``LMTrainConfig(overlap=True)``): raise unless the config has a
    post-backward cluster the layer-group boundary hook can stream —
    ZeRO-3 weight gathers (``fsdp``) and/or the factored-mesh two-level
    DCN sync points (``dcn`` — dcn_size > 1 AND the sync actually runs
    in-backward: under grad_accum > 1 the one post-accumulation exchange
    sits outside the backward, so the caller passes dcn=False there;
    streamed per layer group since round 9) and/or the interleaved-1F1B
    pipeline (``pp`` — pp_size > 0, round 10: the 1F1B step's per-chunk
    gradient syncs stream right after each chunk's LAST backward unit,
    between the other chunks' remaining backward matmuls, and its ZeRO-3
    gathers move to each chunk's own F/B clocks).  With none of the
    three, the data-axis cotangent psums are already emitted at each
    param's use site by shard_map's transpose — there is nothing to
    stream."""
    if fsdp or dcn or pp:
        return
    raise ValueError(
        "lm overlap=True streams the ZeRO-3 (fsdp) weight gathers and/or "
        "the factored-mesh (dcn_size > 1) two-level sync points through "
        "the layer boundaries; without either there is no post-backward "
        "cluster to dissolve (BASELINE.md rounds 8-9).  Enable fsdp, set "
        "dcn_size > 1, set pp_size > 0, or drop overlap (the VGG "
        "trainer's overlap=True covers the explicit-strategy case)")


def require_lm_route(plan, *, dcn: bool, pp: bool,
                     dcn_compress: str | None,
                     sync_plan: str | None) -> None:
    """The LM trainer's routed-surface capability check
    (``LMTrainConfig(sync_route=...)``, round 21 — the round-20
    follow-up): ONE definition site shared by
    ``autotune.resolve_lm_route``, ``lm_cli``, and the bench pre-checks.
    ``plan`` is a parsed ``routing.HopPlan`` (duck-typed — strategies
    cannot import routing, routing imports us).

    The LM trainer executes exactly the routes its factored-mesh sync
    machinery (``_two_level_sync``) compiles: the flat ``data:psum`` on
    an unfactored mesh, and ``data:rs → dcn:psum → data:ag`` /
    ``data:rs → dcn:ring[int8|int4+ef] → data:ag`` on a factored one —
    anything else must refuse loudly rather than silently run a
    different program than the route names.  pp/pp_size gradient paths
    are hand-emitted (the long-standing dcn_compress refusal), and the
    route carries its own wire format, so combining with an explicit
    ``dcn_compress`` or with ``sync_plan='auto'`` (search vs pin) is
    ambiguous — set one, not both."""
    if sync_plan is not None:
        raise ValueError(
            "sync_route pins the gradient route by hand; "
            "sync_plan='auto' searches for one — ambiguous together, "
            "set one, not both")
    if dcn_compress is not None:
        raise ValueError(
            "sync_route encodes the dcn hop's wire format in the route "
            "itself (e.g. 'dcn:ring[int4+ef]'); an explicit "
            "dcn_compress alongside is ambiguous — drop it")
    if pp:
        raise ValueError(
            "sync_route does not compose with pipeline parallelism "
            "(pp/pp_size): the pipeline's gradient reductions are "
            "hand-emitted per stage, not routed through "
            "_two_level_sync — drop the pipeline or the route")
    hops = list(plan.hops)
    if not dcn:
        if (len(hops) == 1 and hops[0].kind == "exchange"
                and hops[0].axis == "data"
                and hops[0].algorithm == "psum"):
            return
        raise ValueError(
            f"with dcn_size=1 the LM data sync is the flat 'data:psum' "
            f"(per-leaf cotangent psums); got {plan.describe()!r} — "
            f"factor the mesh (dcn_size >= 2) to route a two-level "
            f"plan")
    ok_shape = (len(hops) == 3
                and hops[0].kind == "rs" and hops[0].axis == "data"
                and hops[0].algorithm == "scatter"
                and hops[1].kind == "exchange" and hops[1].axis == "dcn"
                and hops[2].kind == "ag" and hops[2].axis == "data")
    if not ok_shape:
        raise ValueError(
            f"the LM factored-mesh sync executes routes shaped "
            f"'data:rs → dcn:psum → data:ag' or 'data:rs → "
            f"dcn:ring[int8|int4+ef] → data:ag' (what _two_level_sync "
            f"compiles); got {plan.describe()!r}")
    x = hops[1]
    if x.algorithm == "ring" and not x.ef:
        raise ValueError(
            f"the LM dcn ring threads the error-feedback residual "
            f"through the train step's sync-state channel; a "
            f"compressed dcn hop must be ring[int8|int4+ef], got "
            f"{x.describe()!r}")


def require_pp_schedulable(*, n_stages: int, n_micro: int, n_layers: int,
                           interleave: int = 1) -> None:
    """The interleaved-1F1B composition check (``LMTrainConfig(pp_size >
    0)``): ONE definition site — the round-9 ``require_*`` consolidation
    — shared by ``lm.validate_lm_cfg``, ``lm_cli``, and ``bench.py``'s
    pre-bench knob validation, so the refusal conditions cannot drift
    from what ``make_lm_1f1b_train_step`` actually schedules.

    Rejects the incoherent combos loudly: a stage count that does not
    divide the layer stack into ``n_stages * interleave`` homogeneous
    contiguous chunks (the step builder's layer cut needs equal-length
    layer scans), and fewer microbatches than stages (the 1F1B steady state
    needs >= n_stages in-flight microbatches; below that the schedule
    degenerates to fill/drain only and the bubble bound
    (pp-1)/(pp-1+M) is a third or worse)."""
    if n_stages < 1:
        raise ValueError(f"pp_size must be >= 1 here, got {n_stages}")
    n_chunks = n_stages * interleave
    if n_layers % n_chunks:
        raise ValueError(
            f"pp_size={n_stages} x interleave={interleave} does not "
            f"divide the {n_layers}-layer stack into contiguous layer-"
            f"group chunks ({n_layers} % {n_chunks} != 0); pick a stage "
            f"count that cuts on layer-group boundaries")
    if n_micro < n_stages:
        raise ValueError(
            f"microbatches={n_micro} < pp_size={n_stages}: the 1F1B "
            f"steady state keeps pp_size microbatches in flight — with "
            f"fewer the pipeline never leaves fill/drain and the bubble "
            f"fraction (pp-1)/(pp-1+M) >= "
            f"{(n_stages - 1) / (n_stages - 1 + max(n_micro, 1)):.2f}; "
            f"use microbatches >= pp_size (>= 2*pp_size to reach the "
            f"<=1/3 bubble regime)")


def require_sync_window(*, sync_every: int, staleness: int = 0,
                        max_sync_every: int = 1, mesh: bool = True,
                        overlap: bool = False, pp: bool = False,
                        grad_accum: int = 1, dcn_size: int | None = None,
                        steps_per_loop: int | None = None,
                        trainer: str = "train",
                        outer_opt: str | None = None,
                        outer_momentum: float = 0.9,
                        outer_lr: float = 1.0,
                        sync_every_per_slice: tuple | None = None) -> None:
    """The communication-sparse window coherence check
    (``TrainConfig(sync_every=H)`` / ``LMTrainConfig(sync_every=H)``,
    round 18): ONE definition site — the round-9 ``require_*``
    consolidation — shared by both trainers' config validation, both
    CLIs, and bench's pre-bench knob validation, so the refusal
    conditions cannot drift from what the windowed step builders
    actually compile.

    Rejects the incoherent combos loudly: windows need a mesh (the
    meshless single-jit path has no collective to amortize and no
    per-device local state); pipeline stages own their own schedule
    (the 1F1B step has no per-step data exchange a window could skip);
    grad_accum already IS a window over the exchange (composing the two
    double-counts the amortization); the VGG in-backward overlap
    machinery streams the very per-step collective a window removes;
    LM windows relax the DCN hop specifically, so they need a factored
    mesh (dcn_size >= 2) to have a slow axis to relax; and bounded
    staleness must leave the window room to hide under (0 <= S < H,
    S = 0 meaning apply-at-boundary).

    Round 22 (DiLoCo): ``outer_opt`` (None | 'nesterov' | 'momentum')
    is the window-boundary anchor optimizer — it updates at boundaries,
    so it needs a window (sync_every > 1) to have boundaries at all;
    ``outer_momentum`` must sit in [0, 1) and ``outer_lr`` be positive.
    ``sync_every_per_slice`` (LM only) gives each 'dcn' slice its own
    interval: a tuple of dcn_size entries, every entry a multiple of
    the base ``sync_every`` (slices exchange only at base boundaries,
    some skipping), with ``min == sync_every`` (the base IS the
    tightest slice's cadence — anything else would mean boundaries no
    compiled program runs).  Per-slice windows do not compose with
    bounded staleness (the skip mask and the deferred apply would both
    reinterpret the same boundary), and the VGG trainer's windows are
    gang-wide by construction (one flat replica axis — there is no
    per-slice program to skip)."""
    if sync_every < 1:
        raise ValueError(f"sync_every must be >= 1, got {sync_every}")
    if max_sync_every < 1:
        raise ValueError(
            f"max_sync_every must be >= 1, got {max_sync_every}")
    if staleness < 0:
        raise ValueError(f"staleness must be >= 0, got {staleness}")
    if sync_every == 1 and staleness > 0:
        raise ValueError(
            f"staleness={staleness} needs sync_every > 1: with per-step "
            f"sync there are no local steps to hide the exchange under")
    if staleness >= sync_every and staleness > 0:
        raise ValueError(
            f"staleness={staleness} >= sync_every={sync_every}: the "
            f"delayed window exchange must land before the next one "
            f"launches (0 <= S < H; S=0 applies at the boundary step)")
    if outer_opt is not None:
        if outer_opt not in OuterOptimizer.KINDS:
            raise ValueError(
                f"outer_opt must be None, 'nesterov', or 'momentum', "
                f"got {outer_opt!r}")
        if sync_every == 1:
            raise ValueError(
                f"outer_opt={outer_opt!r} needs sync_every > 1: the "
                f"outer step applies at window boundaries — with "
                f"per-step sync there is no window delta to apply it to")
        if not 0.0 <= outer_momentum < 1.0:
            raise ValueError(
                f"outer_momentum must sit in [0, 1), got "
                f"{outer_momentum}")
        if outer_lr <= 0.0:
            raise ValueError(f"outer_lr must be > 0, got {outer_lr}")
    if sync_every_per_slice is not None:
        per = tuple(sync_every_per_slice)
        if trainer != "lm":
            raise ValueError(
                "sync_every_per_slice is an LM-trainer (factored 'dcn' "
                "mesh) feature: the VGG trainer's windows are gang-wide "
                "over one flat replica axis — there is no per-slice "
                "boundary program to skip")
        if sync_every == 1:
            raise ValueError(
                "sync_every_per_slice needs the windowed mode "
                "(sync_every > 1): the base interval is the compiled "
                "boundary cadence the per-slice windows subdivide")
        if staleness > 0:
            raise ValueError(
                f"sync_every_per_slice does not compose with "
                f"staleness={staleness}: the skip mask and the deferred "
                f"apply would both reinterpret the same boundary; pick "
                f"one relaxation")
        if dcn_size is not None and len(per) != dcn_size:
            raise ValueError(
                f"sync_every_per_slice has {len(per)} entries but "
                f"dcn_size={dcn_size}: one interval per slice")
        if any(not isinstance(h, int) or h < 1 for h in per):
            raise ValueError(
                f"sync_every_per_slice entries must be ints >= 1, got "
                f"{per}")
        if any(h % sync_every for h in per):
            raise ValueError(
                f"every sync_every_per_slice entry must be a multiple "
                f"of the base sync_every={sync_every} (slices exchange "
                f"only at base boundaries), got {per}")
        if min(per) != sync_every:
            raise ValueError(
                f"min(sync_every_per_slice)={min(per)} must equal the "
                f"base sync_every={sync_every}: the base is the "
                f"tightest slice's cadence — a larger base would mean "
                f"boundaries no compiled program runs")
    if sync_every == 1:
        return
    if not mesh:
        raise ValueError(
            f"sync_every={sync_every} needs a device mesh: the meshless "
            f"single-jit path has no collective exchange to amortize "
            f"(and no per-device window state); use a mesh-backed "
            f"strategy or sync_every=1")
    if pp:
        raise ValueError(
            f"sync_every={sync_every} is incompatible with pipeline "
            f"parallelism (pp_size > 0): the 1F1B schedule has no "
            f"per-step data exchange a window could skip")
    if grad_accum > 1:
        raise ValueError(
            f"sync_every={sync_every} with grad_accum={grad_accum}: "
            f"grad accumulation already amortizes the exchange over its "
            f"micro-steps — composing the two would double-count the "
            f"window; pick one")
    if trainer == "train" and overlap:
        raise ValueError(
            f"sync_every={sync_every} with overlap=True: the in-backward "
            f"markers stream the per-step collective a window removes; "
            f"run windows post-backward (overlap=False)")
    if trainer == "lm" and dcn_size is not None and dcn_size < 2:
        raise ValueError(
            f"sync_every={sync_every} needs dcn_size >= 2 on the LM "
            f"trainer: windows relax the slow DCN hop specifically — "
            f"with a single slice there is no scarce axis to relax")
    if (trainer == "train" and steps_per_loop is not None
            and steps_per_loop % sync_every):
        raise ValueError(
            f"steps_per_loop={steps_per_loop} is not a multiple of "
            f"sync_every={sync_every}: each compiled dispatch must end "
            f"on a window boundary so params leave the step replicated")
