"""Device mesh construction.

The reference's process topology is 4 Gloo workers, one per node (reference:
start_ddp.sh:1).  The TPU-native equivalent is a named ``jax.sharding.Mesh``
over all addressable devices, with collectives compiled by XLA over ICI
(intra-slice) / DCN (cross-slice).  The reference's parallelism inventory is
data-parallel only (SURVEY.md section 5), so the default mesh has a single
``'data'`` axis — but axis names are parameterised so tensor/pipeline/sequence
axes are future mesh shapes, not rewrites.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(
    num_devices: int | None = None,
    *,
    axis_names: tuple[str, ...] = (DATA_AXIS,),
    axis_shape: tuple[int, ...] | None = None,
    devices: list[jax.Device] | None = None,
) -> Mesh:
    """Build a mesh over ``num_devices`` (default: all) devices.

    Replaces ``init_process_group(world_size=...)`` (reference:
    main_all_reduce.py:96): where Gloo enumerates TCP peers, the mesh
    enumerates chips and names the axes collectives run over.
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    if axis_shape is None:
        axis_shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    dev_array = np.asarray(devices).reshape(axis_shape)
    return Mesh(dev_array, axis_names)


def resize_mesh(mesh: Mesh, num_devices: int,
                devices: list[jax.Device] | None = None) -> Mesh:
    """Rebuild ``mesh`` over ``num_devices`` devices, keeping its axis
    names and every INNER axis extent (elastic resize, round 12): the
    leading axis absorbs the size change — the data/dcn axis is the one
    that shrinks when the gang loses a member and grows back when it
    rejoins.  ``num_devices`` must be divisible by the inner-axes
    product (you cannot shrink a dpxtp mesh below its tp extent)."""
    inner = int(np.prod(mesh.devices.shape[1:])) or 1
    if num_devices % inner:
        raise ValueError(
            f"cannot resize mesh {dict(zip(mesh.axis_names, mesh.devices.shape))} "
            f"to {num_devices} devices: inner axes fix a multiple of "
            f"{inner}")
    return make_mesh(
        num_devices,
        axis_names=tuple(mesh.axis_names),
        axis_shape=(num_devices // inner,) + tuple(mesh.devices.shape[1:]),
        devices=devices,
    )


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a global batch: leading dim split over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, *arrays: jax.Array):
    """Place host-global arrays as batch-sharded global jax.Arrays.

    Single-host equivalent of assembling the global batch from per-rank
    DataLoader shards (reference: DistributedSampler at main_all_reduce.py:112
    gives each process 1/N of the batch; here the global array's leading dim
    is split across the 'data' axis).  For multi-host, use
    ``jax.make_array_from_process_local_data`` via parallel/init.py.
    """
    sharding = data_sharding(mesh)
    out = tuple(jax.device_put(a, sharding) for a in arrays)
    return out if len(out) > 1 else out[0]
