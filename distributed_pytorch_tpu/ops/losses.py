"""Streaming (chunked) vocab cross-entropy and the unified LM head-loss seam.

Every LM trainer used to end the same way: materialize the full
``(B, T, V)`` float32 logits tensor (``h @ embed.T``) and hand it to
:func:`..ops.nn.masked_ce`.  On a real TPU that one tensor dominates peak
activation memory — for LM-base shapes it is larger than every per-layer
residual combined — and it caps the per-device batch size every gradient-sync
strategy amortizes against.

Two exports:

- :func:`head_loss` — the ONE seam all four head-loss sites route through
  (lm.py's train/1F1B/eval builders and parallel/pipeline.py's wave tick;
  the round-13 ``step_metrics`` consolidation pattern).  ``loss_impl="dense"``
  traces the historical op sequence bit-for-bit; ``"chunked"`` streams.
- :func:`masked_ce_chunked` — a custom-vjp loss that scans the head
  projection + an online logsumexp over vocab chunks, so the ``(B, T, V)``
  f32 array never exists.  The largest live loss buffer is ``(B*T, chunk)``.
  The backward recomputes each chunk's logits from the saved hidden states
  and emits the hidden/embedding cotangents directly (softmax minus one-hot,
  chunk by chunk) — flash attention's recompute-from-residuals trick applied
  to the LM head.

Tensor-parallel head: with ``tp_axis``/``tp_size`` set, each rank streams
only its ``V/tp`` vocab rows (sliced from the replicated embedding by
``axis_index``) and the partial logsumexps combine with one ``pmax`` + one
``psum`` over the model axis — the same Megatron seam the dense layers use.
The backward ``psum``s the hidden cotangent and reassembles the full
embedding cotangent with a tiled ``all_gather``, keeping it replicated like
the dense path's.

Masking follows :data:`..ops.nn.IGNORE_INDEX` exactly: ignored positions
contribute zero loss and zero cotangent.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .nn import IGNORE_INDEX, masked_ce

Array = jax.Array


def default_chunk(vocab: int, cap: int = 1024) -> int:
    """Largest divisor of ``vocab`` that is <= ``cap`` (used when
    ``loss_chunk`` is left unset: bounds the streamed logits buffer at
    ``B*T x cap`` without the caller having to know the vocab's factors)."""
    if vocab <= 0:
        raise ValueError(f"vocab must be positive, got {vocab}")
    for c in range(min(cap, vocab), 0, -1):
        if vocab % c == 0:
            return c
    return 1  # unreachable: 1 divides everything


def _flatten(h: Array, targets: Array) -> tuple[Array, Array, int]:
    d = h.shape[-1]
    n = 1
    for s in h.shape[:-1]:
        n *= s
    return h.reshape(n, d), targets.reshape(n), n


def _local_rows(emb: Array, tp_axis: str | None, tp_size: int):
    """This rank's vocab slice of the replicated embedding and its global
    row offset (0 without tensor parallelism)."""
    if tp_axis is None or tp_size <= 1:
        return emb, jnp.zeros((), jnp.int32)
    v_local = emb.shape[0] // tp_size
    v0 = lax.axis_index(tp_axis) * v_local
    return lax.dynamic_slice_in_dim(emb, v0, v_local, 0), v0


def _fwd_core(h, emb, targets, chunk, tp_axis, tp_size):
    """Online-logsumexp forward: returns (ce_sum, lse, mask) with lse the
    GLOBAL per-token logsumexp (already combined across the tp head)."""
    h2, t, n = _flatten(h, targets)
    h2 = h2.astype(jnp.float32)
    mask = t != IGNORE_INDEX
    safe = jnp.where(mask, t, 0)
    emb_l, v0 = _local_rows(emb, tp_axis, tp_size)
    n_chunks = emb_l.shape[0] // chunk

    def body(carry, i):
        m, s, tl = carry
        w = lax.dynamic_slice_in_dim(emb_l, i * chunk, chunk, 0)
        lg = h2 @ w.T.astype(jnp.float32)          # (n, chunk) — the only
        m_new = jnp.maximum(m, jnp.max(lg, axis=-1))  # live logits buffer
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(lg - m_new[:, None]), axis=-1)
        idx = safe - (v0 + i * chunk)
        own = (idx >= 0) & (idx < chunk)
        got = jnp.take_along_axis(
            lg, jnp.clip(idx, 0, chunk - 1)[:, None], axis=-1)[:, 0]
        tl = tl + jnp.where(own, got, 0.0)
        return (m_new, s, tl), None

    init = (jnp.full((n,), -jnp.inf, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, s, tl), _ = lax.scan(body, init, jnp.arange(n_chunks))

    if tp_axis is not None and tp_size > 1:
        # combine the per-rank partial logsumexps and the (owned-by-one-
        # rank) true logit — the Megatron vocab-parallel CE combine
        mg = lax.pmax(m, tp_axis)
        sg = lax.psum(s * jnp.exp(m - mg), tp_axis)
        lse = mg + jnp.log(sg)
        tl = lax.psum(tl, tp_axis)
    else:
        lse = m + jnp.log(s)
    ce = jnp.where(mask, lse - tl, 0.0)
    return jnp.sum(ce), lse, mask


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ce_chunked(h, emb, targets, chunk, tp_axis, tp_size):
    ce_sum, _, _ = _fwd_core(h, emb, targets, chunk, tp_axis, tp_size)
    return ce_sum


def _ce_chunked_fwd(h, emb, targets, chunk, tp_axis, tp_size):
    ce_sum, lse, _ = _fwd_core(h, emb, targets, chunk, tp_axis, tp_size)
    # residuals: the hidden states (original dtype), embedding, integer
    # targets and the (n,)-sized logsumexp — NO logits-sized array
    return ce_sum, (h, emb, targets, lse)


def _ce_chunked_bwd(chunk, tp_axis, tp_size, res, g):
    h, emb, targets, lse = res
    h2, t, _ = _flatten(h, targets)
    h2 = h2.astype(jnp.float32)
    mask = t != IGNORE_INDEX
    safe = jnp.where(mask, t, 0)
    emb_l, v0 = _local_rows(emb, tp_axis, tp_size)
    v_local = emb_l.shape[0]
    n_chunks = v_local // chunk
    cols = jnp.arange(chunk)

    def body(dh, i):
        w = lax.dynamic_slice_in_dim(emb_l, i * chunk, chunk, 0)
        w32 = w.astype(jnp.float32)
        lg = h2 @ w32.T
        p = jnp.exp(lg - lse[:, None])              # softmax slice
        idx = safe - (v0 + i * chunk)
        onehot = (cols[None, :] == idx[:, None]).astype(jnp.float32)
        coeff = g * (p - onehot) * mask[:, None]    # (n, chunk)
        dh = dh + coeff @ w32
        dw = coeff.T @ h2                           # (chunk, d)
        return dh, dw

    dh, dws = lax.scan(body, jnp.zeros_like(h2), jnp.arange(n_chunks))
    demb = dws.reshape(v_local, h.shape[-1])
    if tp_axis is not None and tp_size > 1:
        # each rank holds the partial dh for ITS vocab slice and the full
        # demb for its rows: reduce / reassemble, replicated like dense
        dh = lax.psum(dh, tp_axis)
        demb = lax.all_gather(demb, tp_axis, axis=0, tiled=True)
    dh = dh.reshape(h.shape).astype(h.dtype)
    demb = demb.astype(emb.dtype)
    dtargets = np.zeros(targets.shape, dtype=jax.dtypes.float0)
    return dh, demb, dtargets


_ce_chunked.defvjp(_ce_chunked_fwd, _ce_chunked_bwd)


def masked_ce_chunked(
    h: Array,
    emb: Array,
    targets: Array,
    *,
    chunk: int,
    tp_axis: str | None = None,
    tp_size: int = 1,
) -> tuple[Array, Array]:
    """Streaming masked cross-entropy: ``(sum of CE, #unmasked tokens)``
    over ``logits = h @ emb.T`` WITHOUT materializing the logits.

    ``chunk`` must divide this rank's vocab rows (``V`` plain, ``V/tp``
    with a tp-sharded head).  Matches :func:`..ops.nn.masked_ce` on the
    same logits to ~1e-6 (online vs one-shot logsumexp rounding).
    """
    if tp_size <= 1:
        tp_axis = None
    v_local = emb.shape[0] // (tp_size if tp_axis is not None else 1)
    if chunk <= 0 or v_local % chunk:
        raise ValueError(
            f"loss_chunk {chunk} must be a positive divisor of the "
            f"per-rank vocab rows {v_local} (vocab {emb.shape[0]}"
            + (f" over the {tp_size}-way tp head" if tp_axis else "")
            + ") — the scan needs equal-sized chunks")
    ce_sum = _ce_chunked(h, emb, targets, int(chunk), tp_axis, int(tp_size))
    n = jnp.sum(targets != IGNORE_INDEX)
    return ce_sum, n


def head_loss(
    h: Array,
    emb: Array,
    targets: Array,
    *,
    loss_impl: str = "dense",
    loss_chunk: int | None = None,
    tp_axis: str | None = None,
    tp_size: int = 1,
) -> tuple[Array, Array]:
    """THE head-loss seam: final-norm hidden states + tied embedding ->
    ``(sum of masked CE, #unmasked tokens)``.

    ``loss_impl="dense"`` traces the historical op sequence bit-for-bit
    (``h.astype(f32) @ emb.T.astype(f32)`` then ``masked_ce``);
    ``"chunked"`` streams via :func:`masked_ce_chunked` with ``loss_chunk``
    (default: :func:`default_chunk` of the per-rank vocab rows).
    """
    if loss_impl == "chunked":
        v_local = emb.shape[0] // (tp_size if tp_axis is not None else 1)
        chunk = loss_chunk if loss_chunk else default_chunk(v_local)
        return masked_ce_chunked(h, emb, targets, chunk=chunk,
                                 tp_axis=tp_axis, tp_size=tp_size)
    if loss_impl != "dense":
        raise ValueError(
            f"unknown loss_impl {loss_impl!r}: expected 'dense' or "
            "'chunked'")
    logits = h.astype(jnp.float32) @ emb.T.astype(jnp.float32)
    return masked_ce(logits, targets)
