"""Functional neural-network primitives, TPU-first.

These are the compute building blocks the reference obtains from ``torch.nn``
(reference: ``model.py:11-27`` builds Conv3x3 -> BatchNorm2d -> ReLU(inplace)
blocks and MaxPool2d(2,2)).  Here they are expressed as pure functions over
explicit parameter pytrees so that the whole model is a single XLA program:

- layout is **NHWC** with **HWIO** kernels (the TPU-native convolution layout,
  unlike torch's NCHW/OIHW) so XLA can tile convs straight onto the MXU;
- all functions are pure: BatchNorm returns its updated running statistics
  instead of mutating buffers in place;
- a ``dtype`` argument supports bfloat16 compute with float32 parameters
  (params are cast on entry, results accumulated in float32 where it matters).

Initialisation matches torch defaults (kaiming-uniform with a=sqrt(5) for
conv/linear weights, uniform(+-1/sqrt(fan_in)) for biases, ones/zeros for BN)
so that loss curves are comparable with the reference, though not bitwise
identical (different RNG streams; see SURVEY.md section 7.3).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array
PyTree = Any

# Torch BatchNorm2d defaults (reference model.py:24 uses defaults).
BN_MOMENTUM = 0.1
BN_EPS = 1e-5


# ---------------------------------------------------------------------------
# Initialisers (torch-default-compatible)
# ---------------------------------------------------------------------------

def kaiming_uniform(key: Array, shape: tuple[int, ...], fan_in: int) -> Array:
    """torch.nn.init.kaiming_uniform_(a=sqrt(5)) == uniform(+-sqrt(1/fan_in))."""
    bound = math.sqrt(1.0 / fan_in)
    return jax.random.uniform(key, shape, jnp.float32, -bound, bound)


def fan_in_uniform(key: Array, shape: tuple[int, ...], fan_in: int) -> Array:
    """torch's default bias init: uniform(+-1/sqrt(fan_in))."""
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, jnp.float32, -bound, bound)


# ---------------------------------------------------------------------------
# Conv2d
# ---------------------------------------------------------------------------

def conv2d_init(key: Array, in_ch: int, out_ch: int, ksize: int = 3) -> dict:
    """Parameters for a 2-D convolution; kernel layout HWIO (TPU-native)."""
    kkey, bkey = jax.random.split(key)
    fan_in = in_ch * ksize * ksize
    return {
        "kernel": kaiming_uniform(kkey, (ksize, ksize, in_ch, out_ch), fan_in),
        "bias": fan_in_uniform(bkey, (out_ch,), fan_in),
    }


def conv2d(params: dict, x: Array, *, stride: int = 1, padding: int = 1) -> Array:
    """NHWC conv with HWIO kernel (reference conv: model.py:18-23)."""
    kernel = params["kernel"].astype(x.dtype)
    y = lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(stride, stride),
        padding=[(padding, padding), (padding, padding)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + params["bias"].astype(x.dtype)


# ---------------------------------------------------------------------------
# BatchNorm2d
# ---------------------------------------------------------------------------

def batchnorm_init(num_features: int) -> tuple[dict, dict]:
    """Returns (trainable params, running state) for BatchNorm2d.

    Matches torch defaults: weight=1, bias=0, running_mean=0, running_var=1
    (reference model.py:24).
    """
    params = {
        "scale": jnp.ones((num_features,), jnp.float32),
        "bias": jnp.zeros((num_features,), jnp.float32),
    }
    state = {
        "mean": jnp.zeros((num_features,), jnp.float32),
        "var": jnp.ones((num_features,), jnp.float32),
    }
    return params, state


def _train_stats(state: dict, x: Array,
                 axis_name: str | None) -> tuple[Array, Array, dict]:
    """Train-mode batch statistics + running-buffer update, shared by
    ``batchnorm`` and the fused ``batchnorm_relu`` path so the two can
    never drift: f32 moments; with ``axis_name`` (sync-BN) global
    moments FIRST, then the variance (pmean of local variances would
    understate global variance by the spread of per-replica means);
    torch's convention for the buffers (momentum 0.1, unbiased variance
    stored, biased used for normalization)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(0, 1, 2))
    mean_sq = jnp.mean(jnp.square(x32), axis=(0, 1, 2))
    if axis_name is not None:
        mean = lax.pmean(mean, axis_name)
        mean_sq = lax.pmean(mean_sq, axis_name)
    var = mean_sq - jnp.square(mean)
    n = x32.shape[0] * x32.shape[1] * x32.shape[2]
    if axis_name is not None:
        n = n * lax.psum(jnp.ones((), jnp.float32), axis_name)
    unbiased = var * (n / jnp.maximum(n - 1, 1))
    new_state = {
        "mean": (1 - BN_MOMENTUM) * state["mean"] + BN_MOMENTUM * mean,
        "var": (1 - BN_MOMENTUM) * state["var"] + BN_MOMENTUM * unbiased,
    }
    return mean, var, new_state


def batchnorm(
    params: dict,
    state: dict,
    x: Array,
    *,
    train: bool,
    axis_name: str | None = None,
) -> tuple[Array, dict]:
    """BatchNorm over NHWC input; returns (y, new_state).

    Statistics are computed in float32 regardless of compute dtype.  When
    ``axis_name`` is given (sync-BN mode), batch statistics are additionally
    averaged across that mesh axis with ``lax.pmean``; the reference does NOT
    sync BN across replicas (SURVEY.md section 2.3), so the default is local.
    Running stats use torch's convention: momentum 0.1, *unbiased* variance
    stored in the running buffer, biased variance used for normalisation.
    """
    if train:
        mean, var, new_state = _train_stats(state, x, axis_name)
    else:
        mean, var = state["mean"], state["var"]
        new_state = state
    inv = lax.rsqrt(var + BN_EPS) * params["scale"].astype(jnp.float32)
    if x.dtype == jnp.float32:
        y = (x - mean) * inv + params["bias"].astype(jnp.float32)
        return y, new_state
    # Mixed precision (torch-autocast style): statistics above stay f32 for
    # stability, but the per-element normalization applies in the compute
    # dtype — the f32 round-trip per BN layer costs ~20% of the bf16 VGG
    # step and changes the loss only at bf16 noise level.
    y = ((x - mean.astype(x.dtype)) * inv.astype(x.dtype)
         + params["bias"].astype(x.dtype))
    return y, new_state


# ---------------------------------------------------------------------------
# Pooling / Dense
# ---------------------------------------------------------------------------

def batchnorm_relu(
    params: dict,
    state: dict,
    x: Array,
    *,
    train: bool,
    axis_name: str | None = None,
    fused: bool | None = None,
) -> tuple[Array, dict]:
    """``relu(batchnorm(x))`` with an optionally FUSED Pallas backward.

    Forward-bitwise with ``relu(batchnorm(...))`` in every mode (the
    fused path reproduces the normalization arithmetic operation for
    operation); ``fused=True`` replaces the autodiff backward with the
    closed-form two-kernel Pallas pass (ops/fused_bn.py).  The default
    (``fused=None``) resolves to the PLAIN path: the hand backward was
    built and measured e2e SLOWER than XLA's autodiff on TPU v5e — the
    documented negative result in ops/fused_bn.py — so the fusion stays
    an explicit experiment, not the default.
    """
    from . import fused_bn

    use = fused_bn.supported(x, train, axis_name) if fused is None \
        else fused
    if not train:
        use = False  # eval has no backward to fuse: plain path, no error
    elif use and not fused_bn.applicable(x, train, axis_name):
        # explicit fused=True outside the kernel envelope: a clear error
        # here beats a Mosaic layout failure deep in the backward (and
        # sync-BN silently computing LOCAL stats would be worse still)
        raise ValueError(
            f"fused BN+ReLU does not cover this configuration "
            f"(shape {x.shape}, train={train}, axis_name={axis_name}): "
            f"it requires train mode, local (non-synced) statistics, and "
            f"lane-alignable channels — use fused=False/None")
    if not use:
        y, new_state = batchnorm(params, state, x, train=train,
                                 axis_name=axis_name)
        return relu(y), new_state
    mean, var, new_state = _train_stats(state, x, axis_name)
    rstd = lax.rsqrt(var + BN_EPS)
    # the fused VJP bakes the through-stats gradient into da; stop the
    # outer graph from double-counting via its own reduction backward
    r = fused_bn.bn_relu(x, params["scale"], params["bias"],
                         lax.stop_gradient(mean),
                         lax.stop_gradient(rstd))
    return r, new_state


def max_pool(x: Array, window: int = 2, stride: int = 2) -> Array:
    """MaxPool2d(kernel_size=2, stride=2) over NHWC (reference model.py:16)."""
    neg_inf = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    return lax.reduce_window(
        x,
        neg_inf,
        lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding="VALID",
    )


def dense_init(key: Array, in_features: int, out_features: int) -> dict:
    kkey, bkey = jax.random.split(key)
    return {
        "kernel": kaiming_uniform(kkey, (in_features, out_features), in_features),
        "bias": fan_in_uniform(bkey, (out_features,), in_features),
    }


def dense(params: dict, x: Array) -> Array:
    """Linear layer (reference fc1: model.py:40)."""
    return x @ params["kernel"].astype(x.dtype) + params["bias"].astype(x.dtype)


def relu(x: Array) -> Array:
    return jnp.maximum(x, 0)


# ---------------------------------------------------------------------------
# Loss
# ---------------------------------------------------------------------------

def cross_entropy_per_sample(logits: Array, labels: Array) -> Array:
    """Per-sample cross-entropy, computed in float32 for stability under
    bf16 compute.  Shared by the training loss and the masked eval sum."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return logz - true_logit


def cross_entropy_loss(logits: Array, labels: Array) -> Array:
    """Mean cross-entropy over the batch == torch.nn.CrossEntropyLoss()."""
    return jnp.mean(cross_entropy_per_sample(logits, labels))


def accuracy_count(logits: Array, labels: Array) -> Array:
    """Number of correct argmax predictions (reference main.py:60-62)."""
    return jnp.sum(jnp.argmax(logits, axis=-1) == labels)


IGNORE_INDEX = -1  # target id excluded from LM losses (padding)


def masked_ce(logits: Array, targets: Array) -> tuple[Array, Array]:
    """(sum of next-token CE over non-ignored tokens, count).

    The LM-side sibling of ``cross_entropy_loss``: callers psum the pair
    over their data/sequence axes and divide, so the mean is global no
    matter how the batch/sequence are sharded.
    """
    logits = logits.astype(jnp.float32)
    mask = targets != IGNORE_INDEX
    safe = jnp.where(mask, targets, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    ce = jnp.where(mask, logz - true_logit, 0.0)
    return jnp.sum(ce), jnp.sum(mask)


def step_metrics(grad_sq_sum: Array, params: Any) -> Array:
    """(2,) f32 [grad global-norm, param global-norm] — the round-13
    per-step device-side telemetry scalars, shared by BOTH trainers
    (train.py's in-scan body and lm.py's step finishers).  Computed from
    the SAME gradient sum-of-squares the sentry health flag already
    needs plus one reduction over the (updated) params, and returned
    through the same output channel as the flag — so telemetry on/off
    is never a program property."""
    psq = sum(jnp.sum(jnp.square(p.astype(jnp.float32)))
              for p in jax.tree.leaves(params))
    return jnp.stack([jnp.sqrt(grad_sq_sum.astype(jnp.float32)),
                      jnp.sqrt(psq)])
