"""Attention ops: XLA reference + Pallas TPU flash attention (fwd/bwd).

The reference repo has no attention at all — it is a CNN project (SURVEY.md
section 5: "no attention, no sequence dimension").  This module is the
long-context capability the TPU framework adds: the hot op of every
transformer, built MXU-first:

- ``attention_reference``: plain XLA attention (einsum -> f32 softmax ->
  einsum).  O(S^2) memory — the oracle the kernel is tested against, and the
  building block of the pure-JAX ring attention (parallel/context.py).
- ``flash_attention``: Pallas TPU kernel, online-softmax tiling so the S x S
  score matrix never materializes in HBM; custom VJP with the standard
  recompute backward (dQ kernel + dK/dV kernel).  Default blocks are
  512 (q) x 1024 (k) from v5e sweeps, auto-shrunk to the largest 8-aligned
  divisor of the sequence length; scores/accumulators are f32, inputs may
  be bf16.

Shapes follow the (batch, heads, seq, head_dim) convention.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils import compat

Array = jax.Array

# Defaults from block-size sweeps on v5e (fwd+bwd at S=1024..8192, plus
# the end-to-end LM train step): the largest tile wins or ties everywhere
# measured — grid overhead dominates before VMEM pressure does at these
# shapes (1024x1024 beat 512x1024 by 9-26% fwd+bwd).  Small block_q
# (256) with a large grid is pathological in the dK/dV kernel — avoid.
# Short sequences auto-shrink via _fit_block.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
# Decode reads are skipped at block granularity (dead blocks past ``pos``),
# so the decode kernel wants much finer tiles than training flash attention:
# 256 keeps the skip useful at common cache lengths (512-4k) while the
# per-grid-step overhead stays amortized (measured flat vs 512 at 4k cache).
DEFAULT_DECODE_BLOCK_K = 256
NEG_INF = -1e30  # large-negative instead of -inf: keeps exp()/max() NaN-free


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Reference attention (the correctness oracle)
# ---------------------------------------------------------------------------

def attention_reference(
    q: Array, k: Array, v: Array, *, causal: bool = False,
    sm_scale: float | None = None, with_lse: bool = False,
    bias: Array | None = None,
):
    """Plain XLA attention over (B, H, S, D) tensors.

    Scores and softmax in float32 regardless of input dtype.  With
    ``with_lse`` also returns the row logsumexp (B, H, Sq) — the quantity
    ring attention needs to merge partial results across sequence chunks.
    ``bias`` is an additive score bias broadcastable to (B, H, Sq, Sk)
    (e.g. the NEG_INF cache-validity mask of KV-cache decode, generate.py).
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        qi = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        kj = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(qi + (sk - sq) >= kj, s, NEG_INF)
    lse = jax.nn.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32).astype(q.dtype)
    if with_lse:
        return o, lse
    return o


# ---------------------------------------------------------------------------
# Flash attention: forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, sm_scale: float, causal: bool,
                block_q: int, block_k: int):
    """Grid (BH, num_q, num_k); the k dimension is innermost/sequential, so
    the VMEM scratch (acc/m/l) carries the online-softmax state across k
    blocks of one q block."""
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: the whole k block is masked iff its first key comes after the
    # last query of this q block — skip the compute (the grid still visits).
    # The non-causal predicate is traced-true rather than literal True:
    # pl.when(True) inlines the body, and the Pallas HLO interpreter's vma
    # check then rejects block loads on shard_map-varying inputs (a traced
    # cond keeps CPU interpret tests working; Mosaic folds it on TPU).
    live = (j * block_k <= i * block_q + block_q - 1) if causal else (j >= 0)

    @pl.when(live)
    def _compute():
        q = q_ref[0]  # (block_q, d)
        s = jax.lax.dot_general(
            q, k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (bq, bk)
        if causal:
            qi = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kj = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qi >= kj, s, NEG_INF)
        m_prev = m_ref[:, :1]                          # (bq, 1)
        l_prev = l_ref[:, :1]                          # (bq, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)      # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)                # (bq, 1)
        p = jnp.exp(s - m_new)                         # (bq, bk) f32
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bq, d)
        acc_ref[:] = acc_ref[:] * alpha + pv

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse = m_ref[:, 0] + jnp.log(safe_l[:, 0])      # (bq,)
        # (8, bq) broadcast: the lse buffer keeps 8 sublanes so its block
        # satisfies the TPU (8, 128) tile-divisibility rule.
        lse_ref[0] = jnp.broadcast_to(lse[None, :], lse_ref.shape[1:])


def _vma(*arrays):
    """Union of the inputs' varying mesh axes: pallas_call outputs must
    declare their vma explicitly under shard_map(check_vma=True).  On
    runtimes without vma tracking this is always empty (compat.vma_of)
    and the out_shapes below drop the kwarg."""
    out = frozenset()
    for a in arrays:
        out |= compat.vma_of(a)
    return out


def _fwd(q, k, v, *, sm_scale, causal, block_q, block_k, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // block_q, sk // block_k
    vma = _vma(q, k, v)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            compat.shape_struct((bh, sq, d), q.dtype, vma=vma),
            compat.shape_struct((bh, 8, sq), jnp.float32, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),    # acc
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum l
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# Flash attention: backward kernels (recompute p from q,k + saved lse)
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, sm_scale: float, causal: bool,
                   block_q: int, block_k: int):
    """Grid (BH, num_q, num_k), k innermost: accumulate dQ for one q block.

    ``delta`` is precomputed outside the kernel as rowsum(do*o) - dlse, so
    one kernel serves both the o-only VJP (dlse = 0) and the (o, lse) VJP
    ring attention differentiates through (the lse cotangent folds into ds
    as ds = p * (dp - delta) exactly)."""
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    live = (j * block_k <= i * block_q + block_q - 1) if causal else (j >= 0)

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        s = jax.lax.dot_general(
            q, k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qi = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kj = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qi >= kj, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0][:, None])        # (bq, bk)
        do = do_ref[0].astype(jnp.float32)
        delta = delta_ref[0, 0][:, None]               # (bq, 1)
        dp = jax.lax.dot_general(
            do.astype(v_ref.dtype), v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bq, bk)
        ds = p * (dp - delta) * sm_scale
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, sm_scale: float, causal: bool,
                    block_q: int, block_k: int):
    """Grid (BH, num_k, num_q), q innermost: accumulate dK/dV for one k block."""
    j, i = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = (i * block_q + block_q - 1 >= j * block_k) if causal else (i >= 0)

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        s = jax.lax.dot_general(
            q, k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qi = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kj = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qi >= kj, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0][:, None])        # (bq, bk)
        do = do_ref[0].astype(jnp.float32)
        delta = delta_ref[0, 0][:, None]               # (bq, 1)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bk, d)
        dp = jax.lax.dot_general(
            do.astype(v_ref.dtype), v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bq, bk)
        ds = p * (dp - delta) * sm_scale               # (bq, bk)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bk, d)

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, interpret, residuals, do,
         dlse=None):
    q, k, v, o, lse = residuals
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // block_q, sk // block_k
    vma = _vma(q, k, v, o, do, lse)

    # delta = rowsum(do*o) - dlse, packed (bh, 8, sq) like lse.  Folding the
    # lse cotangent here is exact: d s from lse is dlse*p, so
    # ds = p*(dp - rowsum(do*o)) + dlse*p = p*(dp - delta).
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
        vma = vma | _vma(dlse)
    delta = jnp.broadcast_to(delta[:, None, :], (bh, 8, sq))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=compat.shape_struct((bh, sq, d), q.dtype, vma=vma),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, 8, block_q), lambda b, j, i: (b, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            compat.shape_struct((bh, sk, d), k.dtype, vma=vma),
            compat.shape_struct((bh, sk, d), v.dtype, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public entry point
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    o, _ = _fwd(q, k, v, sm_scale=sm_scale, causal=causal,
                block_q=block_q, block_k=block_k, interpret=interpret)
    return o


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    o, lse = _fwd(q, k, v, sm_scale=sm_scale, causal=causal,
                  block_q=block_q, block_k=block_k, interpret=interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    return _bwd(sm_scale, causal, block_q, block_k, interpret, res, g)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    """(o, lse) variant: lse is a differentiable OUTPUT (its cotangent from
    an online-softmax merge folds into the backward's delta term) — the
    kernel form ring attention needs (parallel/context.py)."""
    o, lse = _fwd(q, k, v, sm_scale=sm_scale, causal=causal,
                  block_q=block_q, block_k=block_k, interpret=interpret)
    return o, lse[:, 0]


def _flash_lse_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    o, lse = _fwd(q, k, v, sm_scale=sm_scale, causal=causal,
                  block_q=block_q, block_k=block_k, interpret=interpret)
    # Selective-remat seam (models/transformer.py remat="selective"): name
    # the kernel's OWN residuals so a save_only_these_names policy can pin
    # exactly (o, lse) — the remat backward then rebuilds q/k/v from the
    # layer input but never re-runs the forward kernel.  Outside a
    # checkpoint policy the tags are identity no-ops.
    o = checkpoint_name(o, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return (o, lse[:, 0]), (q, k, v, o, lse)


def _flash_lse_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    do, dlse = g
    return _bwd(sm_scale, causal, block_q, block_k, interpret, res, do,
                dlse=dlse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


# ---------------------------------------------------------------------------
# Decode attention kernel (single-token query over a KV cache)
# ---------------------------------------------------------------------------

def _decode_kernel_body(pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref,
                        m_ref, l_ref, *, sm_scale: float, block_k: int,
                        hkv: int, g: int, ks_ref=None, vs_ref=None):
    """Grid (B, num_k_blocks), k innermost — ONE batch element per step.

    The query tile is all H = hkv*g heads at once, (H, D); the cache tile is
    (hkv, block_k, D).  A static loop over the hkv kv heads computes each
    group's (g, block_k) scores — the GQA head-repeat folded into row
    assembly, so every cache line is read once, not g times.  The online-
    softmax state update then runs vectorized over all H rows.

    ``pos`` arrives via scalar prefetch; blocks past ``pos`` are dead: their
    compute is skipped with ``pl.when`` and their DMA is skipped by the
    clamped BlockSpec index map (dead blocks map to the last live block, and
    Pallas elides the copy when the block index repeats).  Keeping the whole
    batch element's heads in one grid step keeps the grid coarse — per-step
    overhead, not bandwidth, dominates a fine decode grid.

    INT8 KV (``ks_ref``/``vs_ref`` given): the cache tiles arrive as int8
    with per-row scale tiles (hkv, block_k, 1) on the SAME index maps, so
    the HBM read per step is ~half the bf16 cache's — dequantization
    (int8 row x its scale, cast back to the query dtype so the MXU dots
    stay in the compute dtype) happens HERE, in VMEM, never as a dense
    bf16 materialization on the hot path.
    """
    j = pl.program_id(1)
    nk = pl.num_programs(1)
    # per-sequence position: pos_ref is (B,) — ragged batches decode with
    # exact per-sequence bounds (broadcast a scalar to (B,) for the
    # uniform case)
    pos = pos_ref[pl.program_id(0)]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(j * block_k <= pos)
    def _compute():
        # per-kv-head scores, assembled to (H, block_k) rows
        rows = []
        for t in range(hkv):
            qg = q_ref[0, t * g:(t + 1) * g]           # (g, D)
            kt = k_ref[0, t]                           # (bk, D)
            if ks_ref is not None:
                kt = (kt.astype(jnp.float32)
                      * ks_ref[0, t]).astype(qg.dtype)
            rows.append(jax.lax.dot_general(
                qg, kt, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32))   # (g, bk)
        s = jnp.concatenate(rows, axis=0) * sm_scale   # (H, bk)
        # exact pos+1 read bound: slots beyond pos are invalid (zero-filled
        # future positions of the cache buffer)
        slot = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(slot <= pos, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                         # (H, bk)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
        pv = []
        for t in range(hkv):
            vt = v_ref[0, t]                           # (bk, D)
            if vs_ref is not None:
                vt = (vt.astype(jnp.float32)
                      * vs_ref[0, t]).astype(q_ref.dtype)
            pg = p[t * g:(t + 1) * g].astype(vt.dtype)
            pv.append(jax.lax.dot_general(
                pg, vt, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))   # (g, D)
        acc_ref[:] = acc_ref[:] * alpha + jnp.concatenate(pv, axis=0)

    @pl.when(j == nk - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:]
                    / jnp.maximum(l_ref[:, :1], 1e-30)).astype(o_ref.dtype)


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
                   l_ref, *, sm_scale: float, block_k: int, hkv: int,
                   g: int):
    _decode_kernel_body(pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref,
                        m_ref, l_ref, sm_scale=sm_scale, block_k=block_k,
                        hkv=hkv, g=g)


def _decode_kernel_q(pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                     acc_ref, m_ref, l_ref, *, sm_scale: float,
                     block_k: int, hkv: int, g: int):
    """int8 twin of ``_decode_kernel``: two extra scale-tile operands."""
    _decode_kernel_body(pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref,
                        m_ref, l_ref, sm_scale=sm_scale, block_k=block_k,
                        hkv=hkv, g=g, ks_ref=ks_ref, vs_ref=vs_ref)


def decode_attention(
    q: Array, k_cache: Array, v_cache: Array, pos: Array, *,
    k_scale: Array | None = None,
    v_scale: Array | None = None,
    sm_scale: float | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> Array:
    """Single-token decode attention with exact ``pos+1`` cache-read bounds.

    ``q``: (B, H, 1, D) this step's queries; ``k_cache``/``v_cache``:
    (B, Hkv, S, D) full cache buffers (zero-filled beyond ``pos``); ``pos``:
    scalar int32, or (B,) int32 for RAGGED batches — sequence ``b`` attends
    cache slots ``[0, pos[b]]`` exactly (per-sequence read bounds: a short
    sequence in the batch reads only its own prefix, the continuous-
    batching primitive).  Returns (B, H, 1, D).

    INT8 KV cache: with ``k_scale``/``v_scale`` (B, Hkv, S, 1) float32
    per-row scales, the caches are int8 and each tile dequantizes INSIDE
    the kernel (``_decode_kernel_body``) — the HBM cache read per step is
    ~half the bf16 cache's, with no dense dequantized buffer ever
    materialized.  The scale tiles ride the same clamped index maps, so
    dead blocks' scale DMAs are elided exactly like the cache's.

    TPU-first design (the fix for the segmented-decode workaround the
    round-1 ROADMAP documented): decode at long cache is HBM-bound on cache
    reads, and the compiled XLA path must read (and mask) the whole static
    buffer — or a static per-segment bound.  Here the bound is dynamic and
    exact: dead cache blocks past ``pos`` are never fetched (clamped index
    map + copy elision) nor computed (``pl.when``).  GQA is folded in: the
    grid runs per kv head with the G = H/Hkv sharing queries as rows of one
    MXU tile, so cache lines are read ONCE per kv head, not repeated per
    query head (``jnp.repeat`` in the XLA path materializes G copies).
    """
    b, h, sq, d = q.shape
    if sq != 1:
        raise ValueError(f"decode_attention takes single-token queries, "
                         f"got sq={sq}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale or neither")
    hkv, s = k_cache.shape[1], k_cache.shape[2]
    g = h // hkv
    if h % hkv:
        raise ValueError(f"{h} query heads do not group over {hkv} kv heads")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = _interpret_default()
    block_k = (_fit_block(DEFAULT_DECODE_BLOCK_K, s) if block_k is None
               else block_k)
    if s % block_k:
        raise ValueError(f"cache len {s} must divide block_k {block_k}")
    nk = s // block_k

    # (B, H, D) queries with each kv-head group's g queries contiguous rows
    qf = q.reshape(b, h, d)
    pos_arr = jnp.broadcast_to(jnp.atleast_1d(pos), (b,)).astype(jnp.int32)
    quant = k_scale is not None
    vma = (_vma(q, k_cache, v_cache, k_scale, v_scale) if quant
           else _vma(q, k_cache, v_cache))

    def live_block(bb, j, pos_ref):
        return jnp.minimum(j, pos_ref[bb] // block_k)

    def cache_spec(width):
        return pl.BlockSpec(
            (1, hkv, block_k, width),
            lambda bb, j, pos_ref: (bb, 0, live_block(bb, j, pos_ref), 0))

    in_specs = [pl.BlockSpec((1, h, d), lambda bb, j, pos_ref: (bb, 0, 0)),
                cache_spec(d), cache_spec(d)]
    inputs = [qf, k_cache, v_cache]
    if quant:
        in_specs += [cache_spec(1), cache_spec(1)]
        inputs += [k_scale, v_scale]
    o = pl.pallas_call(
        functools.partial(_decode_kernel_q if quant else _decode_kernel,
                          sm_scale=sm_scale, block_k=block_k, hkv=hkv, g=g),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, nk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, h, d),
                                   lambda bb, j, pos_ref: (bb, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((h, d), jnp.float32),      # acc
                pltpu.VMEM((h, 128), jnp.float32),    # running max m
                pltpu.VMEM((h, 128), jnp.float32),    # running sum l
            ],
        ),
        out_shape=compat.shape_struct((b, h, d), q.dtype, vma=vma),
        interpret=interpret,
    )(pos_arr, *inputs)
    return o.reshape(b, h, 1, d)


def _fit_block(limit: int, s: int) -> int:
    """Largest 8-aligned divisor of ``s`` that is <= ``limit`` (block sizes
    must tile the sequence exactly; 8 is the f32 sublane granule).

    Refuses degenerate tilings: a block below 128 (one MXU lane tile) is
    accepted only when it is the whole sequence — otherwise an awkward
    length like 8*prime would silently run a pathologically tiny grid."""
    for b in range(min(limit, s), 7, -1):
        if s % b == 0 and b % 8 == 0 and (b >= 128 or b == s):
            return b
    raise ValueError(
        f"sequence length {s} has no MXU-friendly divisor <= {limit} "
        f"(need an 8-aligned divisor >= 128, or s itself); pad the "
        f"sequence or pass explicit block sizes")


def flash_attention(
    q: Array, k: Array, v: Array, *,
    causal: bool = False,
    sm_scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    with_lse: bool = False,
) -> Array | tuple[Array, Array]:
    """Tiled attention over (B, H, S, D); differentiable (custom VJP).

    Default block sizes auto-shrink to the largest 8-aligned divisor of each
    sequence length; explicitly passed blocks must divide the lengths
    exactly.  Off-TPU the kernels run in Pallas interpret mode so CPU tests
    exercise the exact same code path.

    With ``with_lse`` also returns the row logsumexp (B, H, S) as a second
    differentiable output — the contract ring attention's online-softmax
    merge needs (the lse cotangent is handled exactly in the backward).
    """
    if q.ndim != 4:
        raise ValueError(f"expected (B, H, S, D) q, got {q.shape}")
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = _fit_block(DEFAULT_BLOCK_Q, sq) if block_q is None else min(
        block_q, sq)
    block_k = _fit_block(DEFAULT_BLOCK_K, sk) if block_k is None else min(
        block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"seq lens ({sq}, {sk}) must divide block sizes "
            f"({block_q}, {block_k})")
    if causal and sq != sk:
        raise ValueError("causal flash attention requires sq == sk")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = _interpret_default()
    qf, kf, vf = (q.reshape(b * h, sq, d), k.reshape(b * h, sk, d),
                  v.reshape(b * h, sk, d))
    if with_lse:
        o, lse = _flash_lse(qf, kf, vf, sm_scale, causal,
                            block_q, block_k, interpret)
        return o.reshape(b, h, sq, d), lse.reshape(b, h, sq)
    o = _flash(qf, kf, vf, sm_scale, causal, block_q, block_k, interpret)
    return o.reshape(b, h, sq, d)


def _decode_kernel_paged(pos_ref, table_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *, sm_scale: float,
                         block_k: int, hkv: int, g: int):
    """Paged twin of ``_decode_kernel``: identical math; the cache tiles
    arrive via the block-table index map instead of a contiguous buffer,
    and ``table_ref`` (the second scalar-prefetch operand) is consumed by
    the BlockSpec index maps only."""
    del table_ref
    _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
                   l_ref, sm_scale=sm_scale, block_k=block_k, hkv=hkv, g=g)


def _decode_kernel_paged_q(pos_ref, table_ref, q_ref, k_ref, v_ref,
                           ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref,
                           *, sm_scale: float, block_k: int, hkv: int,
                           g: int):
    """Paged int8 twin: the per-row scale tiles ride the block table the
    way the page gather already does (same live_page index map)."""
    del table_ref
    _decode_kernel_body(pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref,
                        m_ref, l_ref, sm_scale=sm_scale, block_k=block_k,
                        hkv=hkv, g=g, ks_ref=ks_ref, vs_ref=vs_ref)


def decode_attention_paged(
    q: Array, k_pool: Array, v_pool: Array, table: Array, pos: Array, *,
    k_scale: Array | None = None,
    v_scale: Array | None = None,
    sm_scale: float | None = None,
    interpret: bool | None = None,
) -> Array:
    """Single-token decode attention over a PAGED KV pool.

    The vLLM-style memory layout, TPU-native: instead of one contiguous
    (B, Hkv, S, D) buffer per sequence, K/V live in a shared pool of
    fixed-size pages — ``k_pool``/``v_pool``: (P, Hkv, page, D) — and each
    sequence owns the pages its ``table`` row lists: ``table``
    (B, n_pages) int32, entry j = the pool page holding cache slots
    [j*page, (j+1)*page).  ``pos``: (B,) int32 exact read bounds, as in
    ``decode_attention``.

    The page indirection costs NOTHING on the read path: the same
    scalar-prefetch BlockSpec index maps that clamp dead blocks in the
    dense kernel simply look the live block up in the table —
    ``(table[b, min(j, pos[b]//page)], ...)`` — so each grid step DMAs
    exactly one live page and dead pages' copies are elided (repeated
    index).  Entries past a sequence's allocated pages may be garbage; the
    clamp means they are never dereferenced.  Returns (B, H, 1, D).

    INT8 KV pool: with ``k_scale``/``v_scale`` (P, Hkv, page, 1) float32
    per-row scale POOLS, the caches are int8 and the scale tiles ride the
    identical live_page lookup — a shared (prefix-cached) page carries
    its scales with it, and each tile dequantizes inside the kernel
    (see ``decode_attention``).
    """
    b, h, sq, d = q.shape
    if sq != 1:
        raise ValueError(f"decode_attention_paged takes single-token "
                         f"queries, got sq={sq}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale or neither")
    p_blocks, hkv, page, _ = k_pool.shape
    g = h // hkv
    if h % hkv:
        raise ValueError(f"{h} query heads do not group over {hkv} kv heads")
    if page % 8 or (page < 128 and p_blocks > 1):
        raise ValueError(
            f"page size {page} must be 8-aligned, and >= 128 whenever the "
            f"pool holds more than one page (got {p_blocks} pages; a "
            f"single-page pool tolerates shorter pages since no block-table "
            f"indirection happens)")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = _interpret_default()
    n_pages = table.shape[1]

    qf = q.reshape(b, h, d)
    pos_arr = jnp.broadcast_to(jnp.atleast_1d(pos), (b,)).astype(jnp.int32)
    table = table.astype(jnp.int32)
    quant = k_scale is not None
    vma = (_vma(q, k_pool, v_pool, k_scale, v_scale) if quant
           else _vma(q, k_pool, v_pool))

    def live_page(bb, j, pos_ref, table_ref):
        return table_ref[bb, jnp.minimum(j, pos_ref[bb] // page)]

    def pool_spec(width):
        return pl.BlockSpec(
            (1, hkv, page, width),
            lambda bb, j, pos_ref, table_ref: (
                live_page(bb, j, pos_ref, table_ref), 0, 0, 0))

    in_specs = [pl.BlockSpec((1, h, d),
                             lambda bb, j, pos_ref, table_ref: (bb, 0, 0)),
                pool_spec(d), pool_spec(d)]
    inputs = [qf, k_pool, v_pool]
    if quant:
        in_specs += [pool_spec(1), pool_spec(1)]
        inputs += [k_scale, v_scale]
    o = pl.pallas_call(
        functools.partial(
            _decode_kernel_paged_q if quant else _decode_kernel_paged,
            sm_scale=sm_scale, block_k=page, hkv=hkv, g=g),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, n_pages),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, h, d), lambda bb, j, pos_ref, table_ref: (bb, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((h, d), jnp.float32),      # acc
                pltpu.VMEM((h, 128), jnp.float32),    # running max m
                pltpu.VMEM((h, 128), jnp.float32),    # running sum l
            ],
        ),
        out_shape=compat.shape_struct((b, h, d), q.dtype, vma=vma),
        interpret=interpret,
    )(pos_arr, table, *inputs)
    return o.reshape(b, h, 1, d)
