"""Fused BatchNorm+ReLU backward (Pallas, TPU) — a MEASURED NEGATIVE
RESULT, kept as the reproducible experiment.

The round-3 profiling (ROADMAP.md MFU accounting) hypothesized that
XLA's autodiff of train-mode BN+ReLU wastes HBM passes (~7.6 effective
for the isolated fwd+bwd vs ~5-6 necessary) and that a hand-written
backward was worth ~0.15 ms/step (+2-3 MFU points).  Round 4 built it
and measured the opposite, twice over (BASELINE.md round-4 section):

- the two-kernel Pallas backward below (5 HBM-sized passes, exactly as
  designed) makes the END-TO-END step 1.72x SLOWER (4.09 vs 2.37
  ms/step, batch 256 bf16, same session): the ``custom_vjp`` boundary
  forces residual/cotangent materialization XLA's fuser would have
  elided, and the kernel's f32 elementwise work is VPU-bound (half the
  packed-bf16 vector width XLA uses);
- the SAME closed form handed to XLA as one jnp expression
  (``FUSED_BN_BWD=xla``) is still ~4% slower than plain autodiff —
  XLA's derived backward graph plus fusion already beats the naive
  pass-count model that motivated the kernel.

Conclusion: on TPU v5e, XLA's BN+ReLU backward is not the ~20% soft
target the isolated-pass arithmetic suggested; the remaining MFU gap is
structural (bf16 elementwise traffic + f32 optimizer state), not a
missing kernel.  The default path is therefore the PLAIN XLA one
(``supported`` below returns False for auto-gating); everything here
stays importable and test-pinned (tests/test_fused_bn.py) so the
experiment is re-runnable on future toolchains/chips, where the
balance may shift.

Design of the kernels (what "5 passes" means), for the record — two
Pallas kernels under a ``jax.custom_vjp``:

- the whole BN backward collapses onto two per-channel scalars: with
  ``xhat = (a - mean) * rstd``, ``y = xhat*gamma + beta``,
  ``r = relu(y)``, ``dy = dr * (y > 0)``, the closed form is

      da = gamma * rstd * (dy - (s1 + xhat * s2) / n)
      dbeta = s1 = sum(dy);   dgamma = s2 = sum(dy * xhat)

  so kernel 1 streams (dr, a) once accumulating (s1, s2) per channel
  and kernel 2 streams (dr, a) once more writing ``da`` — 5 HBM-sized
  passes total (2 reads + 2 reads + 1 write), nothing else touches the
  activation-sized arrays;
- the ReLU mask is RECOMPUTED inside the kernel with the forward's
  exact arithmetic (same dtype, same ``inv = rstd*scale`` product and
  cast order as ``ops.nn.batchnorm`` + ``relu``), so no mask is stored
  and fwd/bwd agree bitwise on which elements were clipped;
- the statistics' through-graph gradient is BAKED into ``da`` (the
  closed form above already includes the d(mean)/d(var) chains), so the
  caller must pass ``lax.stop_gradient``-wrapped mean/rstd — otherwise
  XLA would backprop its own reduction graph on top and double-count.

The FORWARD stays plain XLA (it already fuses into the conv epilogue
at ~hardware speed; reproduced here operation-for-operation so the
fused path is forward-bitwise with the unfused one).  Scope: train
mode with local (non-synced) statistics — eval and sync-BN keep the
plain path (reference semantics: SURVEY.md section 2.3 — BN is NOT
cross-replica synced, so the hot path is exactly this one).

No reference analog: the reference inherits BN backward from libtorch
(reference model.py:24 uses nn.BatchNorm2d); this is the TPU-native
equivalent of owning that kernel.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

Array = jax.Array


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _block_rows(m: int, c: int) -> int:
    """Rows per grid step: cap the VMEM tile around 512k elements and
    keep it a divisor of m (shapes here are powers of two)."""
    bm = max(8, min(m, (1 << 19) // c))
    while m % bm:
        bm //= 2
    return max(bm, 1)


def _mask_dy_xhat(dr, a, mean, rstd, gamma, beta):
    """Shared by both kernels: the forward-exact ReLU mask (compute
    dtype, same cast order as ops.nn.batchnorm) and the f32 (dy, xhat)
    the closed-form backward consumes."""
    inv_c = (rstd * gamma).astype(a.dtype)
    y = (a - mean.astype(a.dtype)) * inv_c + beta.astype(a.dtype)
    a32 = a.astype(jnp.float32)
    # compare after an exact f32 upcast: bf16 cmp vectors are unsupported
    # by Mosaic's packed layout, and sign is preserved exactly
    dy = jnp.where(y.astype(jnp.float32) > 0,
                   dr.astype(jnp.float32), 0.0)
    xhat = (a32 - mean) * rstd
    return dy, xhat


def _reduce_kernel(dr_ref, a_ref, mean_ref, rstd_ref, gamma_ref, beta_ref,
                   s1_ref, s2_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)

    dy, xhat = _mask_dy_xhat(dr_ref[...], a_ref[...], mean_ref[...],
                             rstd_ref[...], gamma_ref[...], beta_ref[...])
    s1_ref[...] += jnp.sum(dy, 0, keepdims=True)
    s2_ref[...] += jnp.sum(dy * xhat, 0, keepdims=True)


def _apply_kernel(dr_ref, a_ref, mean_ref, rstd_ref, gamma_ref, beta_ref,
                  s1_ref, s2_ref, da_ref, *, n: float):
    dy, xhat = _mask_dy_xhat(dr_ref[...], a_ref[...], mean_ref[...],
                             rstd_ref[...], gamma_ref[...], beta_ref[...])
    coef = gamma_ref[...] * rstd_ref[...]
    da = coef * (dy - (s1_ref[...] + xhat * s2_ref[...]) * (1.0 / n))
    da_ref[...] = da.astype(da_ref.dtype)


def _bwd_pallas(dr, a, mean, rstd, gamma, beta, *, interpret: bool):
    """(da, dgamma, dbeta) for the flattened (M, C) problem.

    Narrow layers (C < 128, e.g. VGG's 64-channel conv0 — the single
    largest activation) fold ``128 // C`` rows into one 128-wide lane
    row: the channel pattern repeats, so the per-channel vectors tile
    and the two half-lane sums add back together at the end.  Without
    the fold, half of every vector lane would be padding."""
    m, c = a.shape
    n = float(m)
    fold = 128 // c if c < 128 else 1
    if fold > 1:
        m, c = m // fold, c * fold
        dr = dr.reshape(m, c)
        a = a.reshape(m, c)
        mean, rstd, gamma, beta = (jnp.tile(v, fold)
                                   for v in (mean, rstd, gamma, beta))
    bm = _block_rows(m, c)
    nsteps = m // bm
    vec = lambda v: v.reshape(1, c).astype(jnp.float32)
    mean, rstd, gamma, beta = map(vec, (mean, rstd, gamma, beta))
    row = pl.BlockSpec((bm, c), lambda i: (i, 0))
    chan = pl.BlockSpec((1, c), lambda i: (0, 0))

    s1, s2 = pl.pallas_call(
        _reduce_kernel,
        grid=(nsteps,),
        in_specs=[row, row, chan, chan, chan, chan],
        out_specs=[chan, chan],
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32)] * 2,
        interpret=interpret,
    )(dr, a, mean, rstd, gamma, beta)
    # true per-channel totals: under folding each lane column held only
    # its own rows' partial sum — collapse the fold, then re-tile so the
    # apply kernel sees full sums in every folded column
    s1 = s1.reshape(fold, -1).sum(0)
    s2 = s2.reshape(fold, -1).sum(0)

    da = pl.pallas_call(
        partial(_apply_kernel, n=n),
        grid=(nsteps,),
        in_specs=[row, row, chan, chan, chan, chan, chan, chan],
        out_specs=row,
        out_shape=jax.ShapeDtypeStruct((m, c), a.dtype),
        interpret=interpret,
    )(dr, a, mean, rstd, gamma, beta,
      jnp.tile(s1, fold)[None], jnp.tile(s2, fold)[None])
    if fold > 1:
        da = da.reshape(m * fold, c // fold)
    return da, s2, s1


@partial(jax.custom_vjp, nondiff_argnums=(5,))
def bn_relu(a, scale, bias, mean, rstd, interpret=None):
    """relu((a - mean) * rstd * scale + bias) with the fused backward.

    ``mean``/``rstd`` must be the BATCH statistics of ``a`` wrapped in
    ``lax.stop_gradient`` (their gradient chain is baked into ``da``);
    forward arithmetic is operation-identical to
    ``ops.nn.batchnorm`` + ``relu`` in both f32 and mixed precision.
    """
    inv = rstd * scale
    if a.dtype == jnp.float32:
        y = (a - mean) * inv + bias
    else:
        y = ((a - mean.astype(a.dtype)) * inv.astype(a.dtype)
             + bias.astype(a.dtype))
    return jnp.maximum(y, 0)


def _bn_relu_fwd(a, scale, bias, mean, rstd, interpret):
    return bn_relu(a, scale, bias, mean, rstd, interpret), \
        (a, scale, bias, mean, rstd)


def _bwd_impl() -> str:
    """Experiment switch, read at trace time so it can be flipped after
    import: "pallas" (two hand kernels) or "xla" (the same closed form
    as one jnp expression XLA fuses itself); both measured SLOWER than
    plain autodiff e2e — see the module docstring."""
    return os.environ.get("FUSED_BN_BWD", "pallas")


def _bwd_xla(dr, a, mean, rstd, gamma, beta):
    """The identical closed form, left to XLA's fuser: elementwise in the
    compute dtype (mask from the forward-exact arithmetic), reductions
    accumulated in f32."""
    n = a.size // a.shape[-1]
    cd = a.dtype
    inv_c = (rstd * gamma).astype(cd)
    y = (a - mean.astype(cd)) * inv_c + beta.astype(cd)
    dy = jnp.where(y > 0, dr, jnp.zeros((), cd))
    xhat = (a.astype(jnp.float32) - mean) * rstd
    dy32 = dy.astype(jnp.float32)
    axes = tuple(range(a.ndim - 1))
    s1 = jnp.sum(dy32, axes)                 # dbeta
    s2 = jnp.sum(dy32 * xhat, axes)          # dgamma
    coef = gamma * rstd
    da = coef * (dy32 - (s1 + xhat * s2) * (1.0 / n))
    return da.astype(cd), s2, s1


def _bn_relu_bwd(interpret, res, dr):
    a, scale, bias, mean, rstd = res
    c = a.shape[-1]
    if _bwd_impl() == "xla":
        da, dgamma, dbeta = _bwd_xla(dr, a, mean, rstd, scale, bias)
    else:
        da, dgamma, dbeta = _bwd_pallas(
            dr.reshape(-1, c), a.reshape(-1, c), mean, rstd, scale, bias,
            interpret=(_interpret_default() if interpret is None
                       else interpret))
        da = da.reshape(a.shape)
    return (da, dgamma.astype(scale.dtype),
            dbeta.astype(bias.dtype), jnp.zeros_like(mean),
            jnp.zeros_like(rstd))


bn_relu.defvjp(_bn_relu_fwd, _bn_relu_bwd)


def supported(x: Array, train: bool, axis_name) -> bool:
    """Auto-gate for ``batchnorm_relu(fused=None)``: always False — the
    measured e2e result (module docstring) says the plain XLA backward
    wins on current TPUs.  ``applicable`` reports whether the kernel
    COULD run, for explicit ``fused=True`` experiments."""
    return False


def applicable(x: Array, train: bool, axis_name) -> bool:
    """Shape/mode envelope the kernel handles: train mode, local
    (non-synced) statistics, lane-aligned (or lane-foldable) channels."""
    c = x.shape[-1]
    m = x.size // c
    if not (train and axis_name is None and m % 8 == 0):
        return False
    if c % 128 == 0:
        return True
    return 128 % c == 0 and m % (8 * (128 // c)) == 0
