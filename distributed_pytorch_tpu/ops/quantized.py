"""Quantized dense compute: int8 x int8 -> int32 matmul (round 16).

The wire tricks (``dcn_compress``, ``fsdp_gather_dtype``) spend fewer
bits on LINKS; this module spends fewer bits in the MXU itself — the
EQuARX observation that weights (and forward activations) tolerate
lower precision than gradient accumulators, applied to the
transformer's dense projections:

- ``quantize_rowwise`` / ``quantize_colwise``: symmetric int8
  quantization against per-row (activation) / per-column (weight) f32
  scales — one absmax per output row/col of the product, so the
  epilogue dequant is a rank-1 outer product of scales.
- ``int8_matmul_xla``: the reference path — quantize both operands,
  one ``lax.dot_general`` on int8 with ``preferred_element_type=
  jnp.int32`` (exact integer arithmetic), dequantize.  This is also
  the legacy-runtime fallback: every XLA backend lowers int8 dots.
- ``int8_matmul``: the Pallas TPU kernel — (m, n, k)-tiled grid with k
  innermost, int32 VMEM accumulator, per-row x per-col scale dequant
  in the epilogue of the last k step.  Bitwise-identical to the XLA
  path (both run the same exact integer dot over the same quantized
  operands — pinned by tests/test_lowbit.py), so CPU test runs
  exercise the interpreter while TPU runs hit the MXU's native int8
  throughput.
- ``quantized_matmul``: the training entry point ``matmul_dtype=
  "int8"`` routes through (models/transformer.py ``_proj``): int8
  forward, STRAIGHT-THROUGH backward — cotangents flow through the
  plain matmul transpose in the compute dtype, because rounding the
  gradient stream would need the EF machinery the sync paths carry
  and the forward perturbation alone is what the optimizer tracks.

Shapes are plain (m, k) @ (k, n); the transformer reshapes its 3D
einsum weights to 2D around the call.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils import compat

Array = jax.Array

# MXU-native int8 tiles: the (32, 128) minimum int8 tile from the
# Pallas guide, widened to the usual 128-lane squares where the
# operands allow.  _fit ensures every grid dim divides exactly; shapes
# that cannot tile at the minimum fall back to the XLA path.
DEFAULT_BLOCK_M = 128
DEFAULT_BLOCK_N = 128
DEFAULT_BLOCK_K = 128


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def quantize_rowwise(x: Array) -> tuple[Array, Array]:
    """Symmetric int8 per-ROW quantization of a (m, k) activation:
    ``q * scale ~= x`` with ``scale`` (m, 1) f32."""
    x32 = x.astype(jnp.float32)
    scale = jnp.maximum(
        jnp.max(jnp.abs(x32), axis=1, keepdims=True) / 127.0, 1e-30)
    q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
    return q, scale


def quantize_colwise(w: Array) -> tuple[Array, Array]:
    """Symmetric int8 per-COLUMN quantization of a (k, n) weight:
    ``q * scale ~= w`` with ``scale`` (1, n) f32."""
    w32 = w.astype(jnp.float32)
    scale = jnp.maximum(
        jnp.max(jnp.abs(w32), axis=0, keepdims=True) / 127.0, 1e-30)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return q, scale


def int8_matmul_xla(x: Array, w: Array) -> Array:
    """The XLA reference/fallback: quantize -> exact int8 dot ->
    dequant.  Output f32 (the caller casts)."""
    qx, sx = quantize_rowwise(x)
    qw, sw = quantize_colwise(w)
    acc = jax.lax.dot_general(qx, qw, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * (sx * sw)


def _matmul_kernel(qx_ref, qw_ref, sx_ref, sw_ref, o_ref, acc_ref, *,
                   n_k: int):
    """Grid (num_m, num_n, num_k), k innermost/sequential: the int32
    VMEM accumulator carries partial sums across k tiles of one (m, n)
    tile; the LAST k step applies the rank-1 scale dequant and writes
    f32."""
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot_general(
        qx_ref[:], qw_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(kk == n_k - 1)
    def _epilogue():
        # same association as int8_matmul_xla (acc * (sx*sw)) so the
        # two paths stay BITWISE equal, not merely close
        o_ref[:] = acc_ref[:].astype(jnp.float32) * (sx_ref[:] * sw_ref[:])


def _fit(limit: int, dim: int, align: int) -> int | None:
    """Largest block <= limit that divides ``dim`` and is a multiple of
    ``align``; None when no such block exists (caller falls back)."""
    b = min(limit, dim)
    while b >= align:
        if dim % b == 0 and b % align == 0:
            return b
        b -= align
    return None


def int8_matmul(x: Array, w: Array, *,
                block_m: int | None = None, block_n: int | None = None,
                block_k: int | None = None,
                interpret: bool | None = None) -> Array:
    """Pallas int8 matmul of (m, k) @ (k, n): quantize both operands
    (per-row / per-col scales), run the tiled exact integer dot with
    the dequant epilogue, return f32.  Shapes that cannot tile on the
    minimum int8 tile route to ``int8_matmul_xla`` — same quantized
    operands, same exact integer sum, bitwise-equal output."""
    if interpret is None:
        interpret = _interpret_default()
    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    bm = block_m if block_m is not None else _fit(DEFAULT_BLOCK_M, m, 32)
    bn = block_n if block_n is not None else _fit(DEFAULT_BLOCK_N, n, 128)
    bk = block_k if block_k is not None else _fit(DEFAULT_BLOCK_K, k, 128)
    if bm is None or bn is None or bk is None:
        return int8_matmul_xla(x, w)
    qx, sx = quantize_rowwise(x)
    qw, sw = quantize_colwise(w)
    vma = compat.vma_of(x) | compat.vma_of(w)
    kernel = functools.partial(_matmul_kernel, n_k=k // bk)
    return pl.pallas_call(
        kernel,
        grid=(m // bm, n // bn, k // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((bm, 1), lambda i, j, kk: (i, 0)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=compat.shape_struct((m, n), jnp.float32, vma=vma),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.int32)],
        interpret=interpret,
    )(qx, qw, sx, sw)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def quantized_matmul(x: Array, w: Array, use_kernel: bool = True) -> Array:
    """int8-forward / straight-through-backward matmul: forward runs
    the exact int8 product of the quantized operands (Pallas kernel, or
    the XLA int8 dot when ``use_kernel=False``), backward differentiates
    the PLAIN product — ``dx = g @ w.T``, ``dw = x.T @ g`` in the input
    dtype, no rounding on the gradient stream.  Off-TPU the kernel path
    would run Mosaic-interpreted, so the training entry point takes the
    XLA int8 dot there — the two are BITWISE equal (test-pinned), the
    choice is throughput only."""
    out = (int8_matmul(x, w) if use_kernel and not _interpret_default()
           else int8_matmul_xla(x, w))
    return out.astype(x.dtype)


def _qm_fwd(x, w, use_kernel):
    return quantized_matmul(x, w, use_kernel), (x, w)


def _qm_bwd(use_kernel, res, g):
    x, w = res
    dx = jnp.dot(g, w.T.astype(g.dtype)).astype(x.dtype)
    dw = jnp.dot(x.T.astype(g.dtype), g).astype(w.dtype)
    return dx, dw


quantized_matmul.defvjp(_qm_fwd, _qm_bwd)
