"""Mixture-of-Experts layer with expert parallelism (all_to_all dispatch).

The last parallelism axis the framework adds (the reference has none of
this — SURVEY.md section 5): a Switch-style top-1-routed MoE MLP whose
experts are sharded over a mesh axis.  Design:

- **Routing** (per token): softmax router over all E experts, top-1 pick,
  output scaled by the router probability (straight-through gating).
- **Capacity**: each expert accepts at most C = ceil(T * cf / E) tokens per
  routing group; overflow tokens are dropped (their MLP delta is zero —
  the residual stream passes them through), the standard Switch behavior.
- **Dispatch** is einsum against a (T, E, C) one-hot tensor — dense,
  MXU-shaped, fully differentiable (the gradient of a dropped token's
  delta is zero, as it should be).
- **Expert parallelism** (``axis``): each device holds E_local = E/n
  experts and routes its own T tokens; one ``lax.all_to_all`` carries every
  device's per-expert buffers to the expert's owner and a second carries
  results back.  XLA lowers these to ICI all-to-alls.  Since round 21 both
  trips route through ``parallel/routing.execute_a2a`` — the same executor
  the ``expert:a2a@…`` route grammar compiles to — so the wire can be
  rowwise-quantized (``dispatch_bits='int8'/'int4'``, per-token f32 scales
  riding the same exchange; activation compression, gated by the round-16
  flip-rate methodology rather than an EF ledger) and capacity-chunked
  (``a2a_chunks>1``: chunk k's combine all-to-all overlaps chunk k+1's
  expert FFN).  At the defaults (f32, 1 chunk) the emitted program is
  bitwise the pre-round-21 hand-built one.
- **Load-balance aux loss**: the Switch aux ``E * sum_e f_e * p_e`` over
  this device's tokens (f = routed fraction, p = mean router prob).
- **Router z-loss** (``z_coef``): mean squared logsumexp of the router
  logits (ST-MoE), discouraging logit blow-up; added into the returned aux.
- **Expert-choice routing** (``router_mode='experts'``): experts pick their
  top-C tokens instead of tokens picking experts (Zhou et al. 2022) —
  perfectly load-balanced by construction (balance aux is 0), tokens may
  be served by several experts or none.

All shapes are static: capacity and expert counts are trace-time constants,
so the whole layer compiles into one XLA program.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
# load the runtime-compat shims (axis_size/pcast polyfills on
# legacy jax) before anything in this module traces
from ..utils import compat as _compat  # noqa: F401
from ..parallel import routing as _routing

Array = jax.Array
PyTree = Any


def moe_init(key: Array, d_model: int, d_ff: int, n_experts: int) -> PyTree:
    """Router + per-expert SwiGLU stacks.  To expert-shard, split the
    leading expert dim of w_gate/w_up/w_down over the mesh axis (the router
    stays replicated)."""
    ks = jax.random.split(key, 4)

    def dense(k, shape, fan_in):
        return jax.random.normal(k, shape, jnp.float32) / math.sqrt(fan_in)

    return {
        "router": dense(ks[0], (d_model, n_experts), d_model),
        "w_gate": dense(ks[1], (n_experts, d_model, d_ff), d_model),
        "w_up": dense(ks[2], (n_experts, d_model, d_ff), d_model),
        "w_down": dense(ks[3], (n_experts, d_ff, d_model), d_ff),
    }


def moe_apply(
    params: PyTree,
    x: Array,                      # (T, D) this device's tokens
    *,
    n_experts: int,                # GLOBAL expert count E
    capacity_factor: float = 2.0,
    axis: str | None = None,       # expert-parallel mesh axis
    top_k: int = 1,                # 1 = Switch, 2 = classic top-2 MoE
    router_mode: str = "tokens",   # 'tokens' (top-k) | 'experts' (EC)
    z_coef: float = 0.0,           # router z-loss weight (added into aux)
    dispatch_bits: str = "f32",    # a2a wire precision: f32 | int8 | int4
    a2a_chunks: int = 1,           # capacity chunks for combine/FFN overlap
) -> tuple[Array, Array]:
    """Returns (out (T, D), auxiliary loss scalar).

    The aux scalar is the Switch load-balance loss (0 under expert-choice
    routing, which is balanced by construction) plus ``z_coef`` times the
    router z-loss; the caller applies its overall aux weight on top.

    Without ``axis``, ``params`` holds all E experts.  With ``axis``,
    ``params['w_*']`` hold this device's E/n expert shard and tokens are
    exchanged over the axis with all_to_all.

    ``top_k=2`` routes each token to its two best experts with gates
    normalized over the chosen pair (Shazeer-style); choice-2 tokens fill
    expert slots after every choice-1 token (lower drop priority).

    ``router_mode='experts'``: each expert picks its top-C tokens by router
    affinity (C = ceil(T * capacity_factor / E)); a token's output is the
    gate-weighted sum over every expert that picked it.

    ``dispatch_bits``: wire precision of the two expert all-to-alls
    (round 21).  'int8'/'int4' rowwise-quantize each dispatched token row
    with its f32 scale riding the same exchange — the
    ``parallel/routing`` ``expert:a2a@bits`` wire format; the backward
    cotangent is compressed identically.  'f32' is the exact hand-built
    exchange.  Requires ``axis`` — without an expert-parallel axis there
    is no wire to compress.

    ``a2a_chunks``: split the (E, C) capacity buffers into this many
    capacity slices so chunk k's combine all-to-all issues between chunk
    k's and chunk k+1's expert FFN matmuls (async collectives then hide
    the exchange behind compute).  ``1`` is the historical unchunked
    program, bitwise.  Requires ``axis`` for the same reason.

    CAVEAT (expert-choice acausality): the per-expert top-C selection ranks
    over the flattened (B*S) token dim, so in causal LM training a token's
    output depends on the router logits of FUTURE positions (and of other
    sequences in the batch).  This is inherent to expert-choice routing, not
    a bug — but it means EC train/eval loss is not reproducible by any
    autoregressive decode (decode sees only the past, and ``generate``
    approximates EC models with capacity-free token-choice mixing; it warns
    when it does).  Use ``router_mode='tokens'`` when train-vs-decode loss
    parity matters.
    """
    t, d = x.shape
    e = n_experts
    n = lax.axis_size(axis) if axis is not None else 1
    if e % n:
        raise ValueError(f"{e} experts do not shard over {n} devices")
    if router_mode not in ("tokens", "experts"):
        raise ValueError(f"router_mode must be 'tokens' or 'experts', "
                         f"got {router_mode!r}")
    if top_k not in (1, 2):
        raise ValueError(f"top_k must be 1 or 2, got {top_k}")
    if router_mode == "experts" and top_k != 1:
        raise ValueError("expert-choice routing has no top_k (experts pick "
                         "tokens); leave top_k=1")
    if dispatch_bits not in ("f32", "int8", "int4"):
        raise ValueError(f"dispatch_bits must be f32, int8, or int4, "
                         f"got {dispatch_bits!r}")
    if dispatch_bits != "f32" and axis is None:
        raise ValueError(
            f"dispatch_bits={dispatch_bits!r} quantizes the expert "
            f"all_to_all wire; without an expert-parallel axis there is "
            f"no wire to compress (the local einsum path is exact)")
    if a2a_chunks < 1:
        raise ValueError(f"a2a_chunks must be >= 1, got {a2a_chunks}")
    if a2a_chunks > 1 and axis is None:
        raise ValueError(
            f"a2a_chunks={a2a_chunks} pipelines the dispatch/combine "
            f"all_to_alls against the expert FFN; without an "
            f"expert-parallel axis there is no exchange to overlap")
    e_local = e // n
    # min(·, t): expert-choice top_k needs cap <= t; more slots than tokens
    # is meaningless in either mode.
    cap = min(max(1, math.ceil(t * top_k * capacity_factor / e)), t)

    # -- routing (f32 for a stable softmax) --------------------------------
    logits = x.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)              # (T, E)

    # Router z-loss (ST-MoE): mean logsumexp^2 keeps logits small.
    z_loss = jnp.mean(jnp.square(jax.nn.logsumexp(logits, axis=-1)))

    if router_mode == "experts":
        # Experts choose tokens: per expert, top-cap tokens by affinity.
        g, idx = jax.lax.top_k(probs.T, cap)             # (E, C) each
        sel = jax.nn.one_hot(idx, t, dtype=x.dtype)      # (E, C, T)
        dispatch = jnp.einsum("ect->tec", sel)           # (T, E, C)
        combine = jnp.einsum("ect,ec->tec", sel, g.astype(x.dtype))
        aux = z_coef * z_loss                            # balanced by design
    else:
        top_probs, top_idx = jax.lax.top_k(probs, top_k)     # (T, K)
        if top_k == 1:
            gates = top_probs                            # Switch: raw prob
        else:
            gates = top_probs / jnp.sum(top_probs, axis=-1, keepdims=True)
        onehots = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)  # (T, K, E)

        # Load-balance aux over the primary assignment (Switch
        # normalization: a perfectly uniform router gives aux == 1).
        frac = jnp.mean(onehots[:, 0], axis=0)
        mean_prob = jnp.mean(probs, axis=0)
        aux = e * jnp.sum(frac * mean_prob) + z_coef * z_loss

        # -- capacity & dispatch tensor (T, E, C) --------------------------
        # Slot assignment: all choice-1 tokens first (stream order), then
        # choice-2 tokens fill what remains — choice-2 drops first under
        # pressure, the standard top-2 priority.
        flat = onehots.transpose(1, 0, 2).reshape(top_k * t, e)  # (K*T, E)
        pos = (jnp.cumsum(flat, axis=0) * flat).reshape(top_k, t, e)
        keep = (pos > 0) & (pos <= cap)
        slot = (pos - 1).astype(jnp.int32)
        dispatch_k = jax.nn.one_hot(slot, cap, dtype=x.dtype) * keep[
            ..., None].astype(x.dtype)                   # (K, T, E, C)
        dispatch = jnp.sum(dispatch_k, axis=0)           # (T, E, C)
        combine = jnp.einsum("ktec,tk->tec", dispatch_k,
                             gates.astype(x.dtype))

    xin = jnp.einsum("tec,td->ecd", dispatch, x)         # (E, C, D)

    # -- per-expert SwiGLU (batched over the local expert dim) -------------
    def expert_ffn(xe: Array) -> Array:
        g = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe,
                                   params["w_gate"].astype(x.dtype)))
        u = jnp.einsum("ecd,edf->ecf", xe, params["w_up"].astype(x.dtype))
        return jnp.einsum("ecf,efd->ecd", g * u,
                          params["w_down"].astype(x.dtype))

    if axis is None:
        yout = expert_ffn(xin)
    else:
        # Both trips route through the ONE a2a executor (round 21): slot
        # j of the dispatch result = the buffer device j routed to my
        # experts; combine is the exact inverse trip.
        hop = _routing.Hop("a2a", _routing._A2A_AXIS, bits=dispatch_bits)
        chunks = min(a2a_chunks, cap)
        if chunks == 1:
            xin = _routing.execute_a2a(hop, xin, direction="dispatch",
                                       axis=axis)
            yout = expert_ffn(xin)
            yout = _routing.execute_a2a(hop, yout, direction="combine",
                                        axis=axis)
        else:
            # Capacity-chunked overlap: trace order is d0 f0 c0 d1 f1 c1
            # …, so chunk k's combine all-to-all sits strictly between
            # chunk k's and chunk k+1's expert matmuls — the async
            # window XLA hides the exchange in (inspector-pinned by
            # tests/test_a2a.py).
            bounds = [(k * cap) // chunks for k in range(chunks + 1)]
            parts = []
            for k in range(chunks):
                xk = _routing.execute_a2a(
                    hop, xin[:, bounds[k]:bounds[k + 1]],
                    direction="dispatch", axis=axis)
                parts.append(_routing.execute_a2a(
                    hop, expert_ffn(xk), direction="combine", axis=axis))
            yout = jnp.concatenate(parts, axis=1)

    out = jnp.einsum("tec,ecd->td", combine, yout)       # (T, D)
    return out, aux.astype(jnp.float32)
