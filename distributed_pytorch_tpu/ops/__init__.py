from . import nn

__all__ = ["nn"]
