"""LM trainer: multi-axis (data x expert x sequence x tensor) LM training.

The VGG trainer (train.py) reproduces the reference's DP-only world; this
trainer is the framework's scale-out path for transformer LMs, composing the
parallelism axes over one ``Mesh(('data', 'expert', 'seq', 'model'))``
(the 'expert' axis is size 1 unless ``ep > 1``; batches shard over
``(data+expert, seq)``):

- **data**: batch sharded; gradient sync is the automatic cotangent ``psum``
  shard_map inserts for axis-invariant params (the 'ddp' strategy fused into
  autodiff).
- **seq**: activations sharded over the sequence; attention is the ring over
  ICI (parallel/context.py); params are seq-invariant so their cotangents
  psum over 'seq' as well.
- **model**: Megatron tensor parallelism — head/FFN-sharded weights
  (models/transformer.py shard_specs), two activation psums per layer.

Design: the *gradient* step runs inside ``shard_map`` (explicit collectives,
ring attention); the AdamW update runs as plain global ops in the same outer
``jit``, where GSPMD propagates each leaf's sharding — no hand-written specs
for optimizer state.  Loss is masked next-token cross-entropy; ``targets``
are pre-shifted host-side so sequence shards never need neighbor tokens.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .models import transformer as tfm
from .utils import faults
from .utils import compat
from .utils import monitor
from .utils import telemetry
from .utils.compat import shard_map
from .ops.nn import IGNORE_INDEX, masked_ce, step_metrics  # noqa: F401
from .ops import losses
from .parallel import context as ctx
from .parallel.mesh import make_mesh

PyTree = Any

DATA, SEQ, MODEL, PIPE, EXPERT = "data", "seq", "model", "pipe", "expert"
DCN = "dcn"  # outer factor of the data axis on multislice meshes
PP = "pp"    # interleaved-1F1B stage axis (round 10; distinct from the
             # wave scheduler's 'pipe' — see make_lm_1f1b_train_step)
IGNORE = IGNORE_INDEX  # target id excluded from the loss (padding)


@dataclass
class LMTrainConfig:
    model: tfm.TransformerConfig = field(
        default_factory=lambda: tfm.PRESETS["LM-tiny"])
    lr: float = 3e-4
    warmup_steps: int = 0     # linear LR warmup
    decay_steps: int = 0      # cosine decay horizon (0 = constant LR)
    min_lr_ratio: float = 0.1
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    aux_coef: float = 0.01  # MoE load-balance loss weight (Switch default)
    compute_dtype: str | None = "bfloat16"
    seed: int = 1
    # parallel degrees; dp * ep * sp * tp * pp must equal the mesh size
    dp: int = 1
    sp: int = 1
    tp: int = 1
    pp: int = 1          # pipeline stages; composes with dp, sp, and tp
    # Dedicated expert-parallel degree (EP x TP): MoE experts shard over
    # their own 'expert' mesh axis (E/ep experts per rank, each expert's
    # FFN tp-sharded) and the batch additionally splits over it for
    # non-MoE layers (EP ranks own distinct tokens — no duplicated
    # attention).  ep=1 keeps the round-2 experts-over-'model' layout.
    ep: int = 1
    # Multislice factoring of the data axis: dp = dcn_size slices x
    # (dp // dcn_size) chips each.  With dcn_size > 1 the mesh gains an
    # outer 'dcn' axis and the DP gradient sync becomes the EXPLICIT
    # two-level reduction (reduce-scatter over the slice, a SHARD-SIZED
    # psum across slices, all-gather back) — |grads|/ici bytes cross
    # DCN per optimizer step instead of the full payload, as a property
    # of the emitted program (jaxpr-pinned), not an assumption about
    # XLA's collective lowering.  With grad_accum = A the microbatch
    # backwards run entirely local and the accumulated grads sync ONCE
    # (_make_accum_grad_step): one shard-sized DCN exchange per
    # optimizer step, not A.
    dcn_size: int = 1
    # Slow-hop compression for the factored-mesh sync (round 11 — the
    # LM analog of TrainConfig.dcn_compress, closing the round-9
    # "needs a sync-state channel" note): "int8" runs every bucket's
    # cross-slice exchange in ``_two_level_sync`` as an int8 ring
    # (per-256-row f32 scales on each DCN transfer; the ICI
    # reduce-scatter/all-gather stay full-precision), with the dropped
    # quantization error carried as a per-device error-feedback
    # residual THROUGH THE TRAIN STEP: the step signature gains a
    # donated ``sync_state`` arg/result (LMTrainer threads it), the
    # whole-tree sync point becomes a stateful custom-vjp whose
    # residual input's cotangent IS the updated carry, and under
    # ``overlap`` each layer group's streamed point consumes/refills
    # its own residual segment.  EF invariant (test-pinned): delivered
    # shard sum + psum_dcn(residuals) == the exact two-level shard sum
    # — nothing lost, only delayed one step.  "int4" (round 16) is the
    # same machinery one rung lower: [-7, 7] levels, two nibbles packed
    # per int8 lane around every DCN ppermute (~0.51x the int8 wire
    # bytes), identical residual layout and EF invariant.  Requires
    # dcn_size > 1; does not compose with pp/pp_size (their gradient
    # paths are hand-emitted; open item).  Dropping the carry on
    # restart is safe (residuals re-accumulate within a step;
    # checkpoints skip it).
    dcn_compress: str | None = None
    # Streaming bucket size (MB) for the factored-mesh exchange
    # (default: strategies.BUCKET_CAP_MB's ~25 MB): feeds the
    # grad-accumulation path's post-scan sync, the 1F1B path's
    # _pp_grad_sync, and the int8 ring's bucket layout.  None keeps the
    # historical default — the plain paths are bitwise-unchanged.
    bucket_mb: float | None = None
    # "auto" (round 11): resolve dcn_compress/bucket_mb from a
    # calibrated (or injected — ``autotune_profile``) link profile by
    # minimizing predicted step-sync time (parallel/autotune.py).  The
    # resolved plan routes through the explicit knobs above unchanged
    # (auto under a forced profile trains bitwise-identically to the
    # explicit config it resolves to); LMTrainer records it as
    # ``trainer.sync_plan``.
    sync_plan: str | None = None
    # Profile source for sync_plan="auto": None = cached/calibrated, or
    # a synthetic preset name / profile-JSON path / TopologyProfile.
    autotune_profile: Any = None
    # Explicit routed sync surface (round 21, the round-20 follow-up —
    # the CNN trainer's strategy="routed" analogue): a route string in
    # the parallel/routing grammar pinning the gradient sync by hand
    # instead of searching for it ("data:psum" on a flat mesh;
    # "data:rs -> dcn:psum -> data:ag" or
    # "data:rs -> dcn:ring[int8|int4+ef] -> data:ag" on a factored
    # one).  Resolved by autotune.resolve_lm_route into the explicit
    # knobs above (the exact routes `_two_level_sync` already
    # executes), so a routed config trains BITWISE-identically to the
    # explicit config it names; anything the LM machinery cannot run —
    # other shapes, pp/pp_size, combining with sync_plan="auto" or
    # dcn_compress — refuses loudly (strategies.require_lm_route).
    sync_route: str | None = None
    # Interleaved-1F1B pipeline parallelism (round 10): pp_size > 0 routes
    # training through make_lm_1f1b_train_step — layer chunks partitioned
    # over a dedicated 'pp' mesh axis, one explicit forward/backward unit
    # emitted per (chunk, microbatch) in one-forward-one-backward timetable
    # order (parallel/pipeline.py one_f_one_b_schedule), stage-boundary
    # activations/cotangents moving as ppermute transfers over 'pp'.
    # Unlike the wave scheduler (``pp``), the backward is hand-emitted
    # (one jax.vjp per unit) with every gradient reduction explicit, so it
    # composes with fsdp-within-stage, dcn_size, grad_accum and overlap —
    # and the 1F1B reordering is a pure reassociation of the same
    # microbatch grads: pp_size=N trains BITWISE-identically to pp_size=1
    # (test-pinned, params+Adam over multi-step runs).  pp_size=1 is the
    # legal degenerate schedule (single-stage microbatched accumulation,
    # the baseline of those pins); 0 = off.
    pp_size: int = 0
    microbatches: int = 0  # per-step microbatches for pp (default 2*pp)
    # Virtual pipeline stages per device (Megatron interleaved placement):
    # the fill/drain bubble shrinks by this factor (parallel/pipeline.py
    # wave schedule).  Requires n_layers % (pp * interleave) == 0.
    interleave: int = 1
    # Tick-scan remat block for pp (parallel/pipeline.py): 0 = auto (one
    # wave per block — 1F1B-grade O(pp*mb) activation memory), None = flat
    # scan (O(num_ticks) memory; kept for A/B measurement), or an explicit
    # tick count.
    pp_remat_block: int | None = 0
    fsdp: bool = False   # ZeRO-3: shard params+optimizer over 'data' too
    # Quantized ZeRO-3 weight all-gathers (round 16): "int8" runs every
    # fsdp param gather (the post-backward whole-tree path and the
    # streamed per-layer-group boundary path alike — both route through
    # ``_fsdp_gather``) as an int8 exchange with per-row f32 scales:
    # quantize the local shard, all-gather int8 payload + scales over
    # 'data', dequantize at the consumer.  Weights-not-grads, so there
    # is no EF carry — the pin is a convergence-curve follow of the
    # full-precision run plus a jaxpr pin that i8 is on the wire.
    # Requires fsdp=True (there is no gather to quantize otherwise);
    # does not compose with pp_size (the 1F1B stacked gather is a
    # different code path, kept full-precision).  "int4" (round 18,
    # lifting the round-16 refusal) packs two nibbles per wire byte on
    # the same exchange (+/-7 levels against the identical per-row
    # scales) — 8x fewer payload bytes; same full-precision gradient
    # reduce-scatter, same curve-following pin at a looser rtol.
    # None = exact gathers.
    fsdp_gather_dtype: str | None = None
    # Low-bit dense compute (round 16): "int8" routes the transformer's
    # dense projections (attention q/k/v/o and the MLP matmuls) through
    # ops/quantized.py's int8xint8->int32 matmul on the FORWARD pass —
    # per-row activation scales, per-col weight scales, dequant in the
    # epilogue (Pallas kernel on TPU, lax.dot_general-on-int8 XLA
    # fallback elsewhere) — while the backward stays in the configured
    # compute dtype (straight-through estimator).  Flip-rate-measured
    # against bf16 like the int8 KV cache was.  None = stock matmuls.
    matmul_dtype: str | None = None
    # Backward-overlapped sync (rounds 8-9): stream the step's bulk
    # communication through the layer-group boundaries (transformer.apply
    # boundary hook) instead of emitting it all-at-once.  With fsdp
    # (round 8), each group's ZeRO-3 weight gather moves to its boundary
    # — forward all_gathers stream layer by layer and their transposes
    # (the gradient reduce-scatters) land interleaved between the
    # backward matmuls.  With dcn_size > 1 (round 9), the factored-mesh
    # two-level gradient sync streams the same way: the whole-tree
    # _dcn_sync_point becomes one per-layer-group custom-vjp point each,
    # so group N's ICI reduce-scatter -> shard-sized DCN psum ->
    # all-gather is emitted right after group N's backward matmuls and
    # the latency-hiding scheduler can run it under group N-1's backward.
    # Bitwise-identical trajectories either way (same ops, moved; the
    # two-level reduction is elementwise, so regrouping changes no sums).
    # Requires fsdp=True or dcn_size > 1: otherwise the data-axis
    # cotangent psums already sit at each param's use site and there is
    # no post-backward cluster to dissolve.
    overlap: bool = False
    # Gradient accumulation: split each global batch into grad_accum
    # microbatches, scan them accumulating gradients, apply ONE optimizer
    # step.  The CE gradient is EXACT (grads normalize by the full batch's
    # global token count, counted before the scan, so microbatch mask
    # imbalance reweights nothing).  MoE aux is a per-routing-group
    # statistic, and accumulation makes each microbatch its own group —
    # the aux term therefore shifts slightly, exactly as it does for any
    # other change of group size (dp/tp splits included).
    grad_accum: int = 1
    # Head-loss implementation (round 17): "dense" materializes the full
    # (B, T, V) f32 logits and calls masked_ce — the historical graph,
    # bit-for-bit.  "chunked" streams the head projection + an online
    # logsumexp over vocab chunks (ops/losses.py masked_ce_chunked, a
    # custom-vjp whose backward recomputes each chunk's logits and emits
    # the hidden/embedding cotangents directly) so the logits tensor
    # never exists — on real TPUs it is the single largest activation
    # and the cap on per-device batch size.  Under tp > 1 the chunked
    # head additionally shards the vocab over 'model' (per-rank partial
    # logsumexp + one pmax/psum combine).  Matches dense to ~1e-6.
    loss_impl: str = "dense"
    # Vocab rows per streamed chunk for loss_impl="chunked"; must divide
    # the per-rank vocab (V, or V // tp when tp > 1).  None = the largest
    # divisor <= 1024 (ops/losses.py default_chunk).
    loss_chunk: int | None = None
    # Activation rematerialization for the non-pp layer stack (round 17):
    # "full" wraps each transformer block in jax.checkpoint (only the
    # layer-boundary carries stay live through the backward; everything
    # else recomputes), "selective" additionally saves the flash
    # attention (o, lse) pair via checkpoint names so only the
    # projections and MLP recompute — the usual best point on the
    # memory/time curve.  Losses are bitwise-equal to remat="none" (the
    # recompute replays the identical ops).  The sync custom-vjp
    # boundaries (overlap streaming, ZeRO-3 gathers, two-level DCN
    # points) sit OUTSIDE the checkpointed block, so no sync collective
    # is re-emitted — schedule-inspector-pinned.  Does not compose with
    # pp/pp_size: parallel/pipeline.py owns its own per-tick remat
    # (pp_remat_block).  "none" = historical graph.
    remat: str = "none"
    # Communication-sparse windows (round 18, the BAGUA-style system
    # relaxation the ROADMAP carried): run H local optimizer steps
    # between cross-slice exchanges.  Requires the factored multislice
    # mesh (dcn_size >= 2) — the window relaxes the SLOW hop
    # specifically: within a window every step syncs gradients over the
    # intra-slice axes only (data/expert/seq/model — ICI) and each
    # slice advances its own params p = anchor + delta with PER-SLICE
    # Adam state (delta and opt state carry a leading 'dcn' axis); at
    # step kH the accumulated deltas average across 'dcn' through the
    # same bucketed two-level exchange the per-step path uses —
    # composing with dcn_compress (int8/int4 ring + EF residual, now
    # charged once per window) and with overlap/fsdp (local steps
    # stream ICI-only sync points and ZeRO-3 gathers; the boundary
    # exchange is whole-tree).  DCN bytes/step scale ~1/H
    # (schedule-inspector-pinned); sync_every=1 is the existing
    # per-step path, bitwise (build-time branch).  Adam trajectories
    # follow the per-step curve (curve pin), they do not equal it.
    sync_every: int = 1
    # Bounded staleness S (0 <= S < H): launch the window exchange at
    # step kH but apply it at step kH+S, so the DCN round-trip can
    # drain under S steps of local compute instead of stalling the
    # boundary step.  The launch snapshots delta; the apply adds the
    # averaged delta to the anchor and subtracts the snapshot from the
    # live delta (local progress made during the S steps is kept).
    # NOTE: on a single-stream runtime the launch/apply programs still
    # execute in dispatch order — the structure bounds what a
    # multi-stream runtime may overlap; it does not force overlap.
    staleness: int = 0
    # Relaxation ceiling for the interval-aware autotuner
    # (sync_plan="auto" prices intervals H <= max_sync_every) and the
    # RunDoctor straggler actuator (monitor.SyncRelaxHook widens
    # sync_every up to this bound on a step-time SLO breach).  Default
    # 1: relaxation is strictly opt-in.
    max_sync_every: int = 1
    # DiLoCo outer optimizer (round 22): at each window boundary the
    # anchor moves by outer_opt(mean delta) instead of the plain mean —
    # Nesterov/heavy-ball momentum ON THE ANCHOR (f32, host-side per
    # device like the EF residual) recovers convergence lost to wide
    # windows, so H can widen at matched quality (measured band,
    # tests/test_diloco.py).  None (default) is the round-18 plain
    # mean, UNTOUCHED at build time; momentum==0 ∧ lr==1 collapses to
    # the same plain-add branch (OuterOptimizer.trivial) — bitwise.
    outer_opt: str | None = None      # None | "nesterov" | "momentum"
    outer_momentum: float = 0.9
    outer_lr: float = 1.0
    # Per-slice non-uniform windows (round 22): each WAN-attached slice
    # owns its own H_i (a multiple of the base sync_every, which must
    # equal min(H_i)).  At a base boundary only slices with
    # step % H_i == 0 participate: skippers contribute an EXACT zero
    # delta through a (dcn,)-shaped participation mask inside the
    # exchange (EF ledger invariant pinned) and keep accumulating
    # locally; participants' deltas average over ALL n_dcn slices and
    # everyone adopts the anchor move, so params stay replicated.  The
    # per-slice SyncRelaxHook widens a straggling slice's own H without
    # staling healthy slices.  None (default) = uniform windows,
    # bitwise (build-time branch).
    sync_every_per_slice: tuple | None = None
    @property
    def dtype(self) -> jnp.dtype | None:
        """compute_dtype resolved to a jnp dtype (None = float32 params)."""
        return jnp.dtype(self.compute_dtype) if self.compute_dtype else None

    # Ring-attention sequence layout when sp > 1: 'zigzag' (balanced causal
    # ring, ~2x fewer attention FLOPs — parallel/context.py) or 'contiguous'.
    # The step permutes the global token stream in-jit to match; the loss is
    # permutation-invariant, so trajectories equal the contiguous layout.
    seq_layout: str = "zigzag"


def validate_lm_cfg(cfg: LMTrainConfig) -> None:
    """Composition checks for (dp, ep, sp, tp, pp, interleave,
    grad_accum).  Shared by ``make_lm_mesh`` and ``LMTrainer`` so a
    caller-supplied mesh cannot skip them — e.g. ``LMTrainer(cfg(pp=2,
    grad_accum=4), mesh=m)`` must raise exactly like the mesh-built path
    (the pp step builder never reads grad_accum, so silently accepting it
    would drop the setting)."""
    if cfg.interleave < 1:
        raise ValueError(f"interleave must be >= 1, got {cfg.interleave}")
    if cfg.interleave > 1 and cfg.pp == 1 and cfg.pp_size <= 1:
        raise ValueError(
            "interleave (virtual pipeline stages) requires pp > 1 or "
            "pp_size > 1; without a pipeline it would be silently ignored")
    if cfg.grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {cfg.grad_accum}")
    if cfg.grad_accum > 1 and cfg.pp > 1:
        raise ValueError(
            "grad_accum does not compose with pp (the pipeline's "
            "microbatch schedule already bounds activation memory; use "
            "--microbatches)")
    if cfg.pp_size < 0:
        raise ValueError(f"pp_size must be >= 0, got {cfg.pp_size}")
    if cfg.pp_size > 0:
        # the 1F1B path: composition checks live in ONE place
        # (parallel/strategies.py require_pp_schedulable, the round-9
        # require_*-style consolidation) so lm_cli/bench/LMTrainer cannot
        # drift from the step builder's actual capabilities
        from .parallel.pipeline import _uniform_moe
        from .parallel.strategies import require_pp_schedulable
        if cfg.pp > 1:
            raise ValueError(
                "pp (wave scheduler) and pp_size (interleaved-1F1B) are "
                "two schedulers for the same axis — set one, not both")
        if cfg.ep > 1:
            raise ValueError("the dedicated 'expert' axis does not "
                             "compose with pp_size (experts shard over "
                             "'model' inside pipeline stages); use ep=1")
        if cfg.model.n_experts and not _uniform_moe(cfg.model):
            raise ValueError(
                "pp_size supports MoE only for uniform stacks "
                "(moe_every=1); a dense/MoE-alternating stack cannot "
                "stack into homogeneous pipeline chunks")
        # (tp head-divisibility is checked once, below: pp_size keeps
        # cfg.pp == 1, so the detailed non-pp tp branch applies)
        require_pp_schedulable(
            n_stages=cfg.pp_size,
            n_micro=cfg.microbatches or 2 * cfg.pp_size,
            n_layers=cfg.model.n_layers,
            interleave=cfg.interleave)
    if cfg.dcn_size < 1:
        raise ValueError(f"dcn_size must be >= 1, got {cfg.dcn_size}")
    if cfg.dcn_size > 1:
        if cfg.dp % cfg.dcn_size:
            raise ValueError(f"dp={cfg.dp} does not factor into "
                             f"dcn_size={cfg.dcn_size} slices")
        if cfg.pp > 1:
            raise ValueError("dcn_size does not compose with pp (the "
                             "pipeline mesh has no factored data axis)")
    if cfg.sync_plan not in (None, "auto"):
        raise ValueError(
            f"sync_plan must be None or 'auto', got {cfg.sync_plan!r}")
    if cfg.bucket_mb is not None and cfg.bucket_mb <= 0:
        raise ValueError(f"bucket_mb must be > 0, got {cfg.bucket_mb}")
    if cfg.dcn_compress is not None:
        if cfg.dcn_compress not in ("int8", "int4"):
            raise ValueError(
                f"dcn_compress must be None, 'int8', or 'int4', got "
                f"{cfg.dcn_compress!r}")
        if cfg.dcn_size < 2:
            raise ValueError(
                f"dcn_compress={cfg.dcn_compress!r} quantizes the "
                "cross-slice (dcn) hop of the factored-mesh sync; with "
                f"dcn_size={cfg.dcn_size} there is no DCN hop to compress")
        if cfg.pp > 1 or cfg.pp_size > 0:
            raise ValueError(
                "dcn_compress does not compose with pipeline parallelism "
                "(pp/pp_size): the pipeline gradient paths are "
                "hand-emitted without the stateful sync-state channel "
                "(open item); drop the pipeline or the compression")
    if (cfg.sync_every != 1 or cfg.staleness != 0
            or cfg.max_sync_every != 1 or cfg.outer_opt is not None
            or cfg.sync_every_per_slice is not None):
        # the ONE window-coherence check site (round 18,
        # parallel/strategies.py require_* consolidation): interval
        # bounds, staleness-vs-window ordering, and the combos the LM
        # windowed machinery does not cover (pipeline paths,
        # grad_accum's already-amortized exchange, flat meshes)
        from .parallel.strategies import require_sync_window
        require_sync_window(
            sync_every=cfg.sync_every, staleness=cfg.staleness,
            max_sync_every=cfg.max_sync_every, mesh=True,
            overlap=cfg.overlap, pp=cfg.pp > 1 or cfg.pp_size > 0,
            grad_accum=cfg.grad_accum, dcn_size=cfg.dcn_size,
            trainer="lm", outer_opt=cfg.outer_opt,
            outer_momentum=cfg.outer_momentum, outer_lr=cfg.outer_lr,
            sync_every_per_slice=cfg.sync_every_per_slice)
    if cfg.fsdp_gather_dtype is not None:
        if cfg.fsdp_gather_dtype not in ("int8", "int4"):
            raise ValueError(
                f"fsdp_gather_dtype must be None, 'int8' or 'int4', got "
                f"{cfg.fsdp_gather_dtype!r}")
        if not cfg.fsdp:
            raise ValueError(
                "fsdp_gather_dtype quantizes the ZeRO-3 weight "
                "all-gather; with fsdp=False there is no gather to "
                "quantize")
        if cfg.pp_size > 0:
            raise ValueError(
                "fsdp_gather_dtype does not compose with pp_size: the "
                "1F1B stacked per-chunk gather is a separate path kept "
                "full-precision (open item); drop one")
    if cfg.matmul_dtype is not None:
        if cfg.matmul_dtype != "int8":
            raise ValueError(
                f"matmul_dtype must be None or 'int8', got "
                f"{cfg.matmul_dtype!r}")
        if cfg.pp > 1 or cfg.pp_size > 0:
            raise ValueError(
                "matmul_dtype does not compose with pipeline parallelism "
                "(pp/pp_size): the stage runners call the block body "
                "directly without the matmul_dtype plumbing (open item); "
                "drop one")
    if cfg.loss_impl not in ("dense", "chunked"):
        raise ValueError(
            f"loss_impl must be 'dense' or 'chunked', got "
            f"{cfg.loss_impl!r}")
    if (cfg.loss_impl == "chunked" and cfg.tp > 1 and cfg.pp == 1
            and cfg.pp_size == 0 and cfg.model.vocab_size % cfg.tp):
        raise ValueError(
            f"vocab_size {cfg.model.vocab_size} must divide over "
            f"tp={cfg.tp} for the chunked (vocab-sharded) head")
    if cfg.loss_chunk is not None:
        if cfg.loss_impl != "chunked":
            raise ValueError(
                f"loss_chunk={cfg.loss_chunk} only applies to "
                "loss_impl='chunked'; the dense head has no chunk size "
                "(set loss_impl='chunked' or drop loss_chunk)")
        v = cfg.model.vocab_size
        # the streamed head shards the vocab over 'model' only on the
        # non-pp SPMD path; the pipeline heads chunk the full vocab
        v_local = v // cfg.tp if (cfg.tp > 1 and cfg.pp == 1
                                  and cfg.pp_size == 0) else v
        if cfg.loss_chunk <= 0 or v_local % cfg.loss_chunk:
            raise ValueError(
                f"loss_chunk={cfg.loss_chunk} must be a positive divisor "
                f"of the per-rank vocab rows ({v_local}"
                + (f" = {v} // tp={cfg.tp}" if v_local != v else "")
                + ") — the streaming scan needs equal-sized chunks")
    if cfg.remat not in ("none", "full", "selective"):
        raise ValueError(
            f"remat must be 'none', 'full' or 'selective', got "
            f"{cfg.remat!r}")
    if cfg.remat != "none" and (cfg.pp > 1 or cfg.pp_size > 0):
        raise ValueError(
            "remat does not compose with pipeline parallelism "
            "(pp/pp_size): the pipeline schedulers own their own "
            "rematerialization (pp_remat_block wraps each tick block in "
            "jax.checkpoint already); drop one")
    if cfg.fsdp and cfg.dp // max(cfg.dcn_size, 1) == 1:
        # param_specs shards ZeRO-3 leaves over the INNER 'data' axis
        # (slice-local); at inner size 1 there is nothing to shard and
        # the user's fsdp=True would silently buy fully replicated
        # params/optimizer state (ADVICE r5 #3) — refuse instead
        raise ValueError(
            f"fsdp=True with dp={cfg.dp}, dcn_size={cfg.dcn_size} is a "
            f"no-op: the slice-local data axis has size "
            f"dp // dcn_size = 1, so no leaf can shard over it — raise "
            f"dp (or drop fsdp)")
    if cfg.overlap:
        # the ONE capability-check site (parallel/strategies.py, round 9):
        # overlap streams ZeRO-3 gathers and/or — since round 9 — the
        # factored-mesh two-level DCN sync points, per layer group.
        # Under grad_accum > 1 the dcn exchange happens ONCE after the
        # local accumulation scan (never per microbatch), so dcn alone
        # gives overlap nothing to stream there — only fsdp does (its
        # per-microbatch gathers still stream); refuse the silent no-op.
        from .parallel.strategies import require_lm_overlap_streamable
        require_lm_overlap_streamable(
            fsdp=cfg.fsdp,
            dcn=cfg.dcn_size > 1 and (cfg.grad_accum == 1
                                      or cfg.pp_size > 0),
            pp=cfg.pp_size > 0)
    if cfg.ep > 1:
        if cfg.pp > 1:
            raise ValueError("the dedicated 'expert' axis does not compose "
                             "with pp (experts shard over 'model' inside "
                             "pipeline stages); use ep=1 with pp")
        if not cfg.model.n_experts:
            raise ValueError("ep > 1 requires an MoE model (n_experts > 0)")
        if cfg.model.n_experts % cfg.ep:
            raise ValueError(f"{cfg.model.n_experts} experts do not shard "
                             f"over ep={cfg.ep}")
    if (cfg.model.moe_dispatch_bits != "f32"
            or cfg.model.moe_a2a_chunks > 1):
        # The a2a knobs act where the MoE layer crosses a mesh axis (the
        # EP / tensor-axis call sites in models/transformer.block); on a
        # layout with no expert exchange they would silently no-op.
        if not cfg.model.n_experts:
            raise ValueError(
                f"moe_dispatch_bits={cfg.model.moe_dispatch_bits!r}/"
                f"moe_a2a_chunks={cfg.model.moe_a2a_chunks} configure the "
                f"expert all_to_all of an MoE model; this model is dense "
                f"(n_experts=0)")
        if cfg.ep == 1 and cfg.tp == 1:
            raise ValueError(
                f"moe_dispatch_bits={cfg.model.moe_dispatch_bits!r}/"
                f"moe_a2a_chunks={cfg.model.moe_a2a_chunks} shape the "
                f"expert all_to_all wire, but ep=1 and tp=1 route "
                f"experts locally (no exchange to compress or overlap) "
                f"— raise ep or tp, or drop the knobs")
    if cfg.pp > 1:
        from .parallel.pipeline import _uniform_moe
        if cfg.model.n_experts and not _uniform_moe(cfg.model):
            raise ValueError(
                "pp supports MoE only for uniform stacks (moe_every=1, "
                "every layer MoE); a dense/MoE-alternating stack cannot "
                "stack into homogeneous pipeline stages")
        if cfg.tp > 1 and (cfg.model.n_heads % cfg.tp
                           or cfg.model.kv_heads % cfg.tp):
            raise ValueError(f"heads must divide over tp={cfg.tp}")
    elif cfg.tp > 1:
        if cfg.model.n_heads % cfg.tp:
            raise ValueError(f"n_heads {cfg.model.n_heads} must divide over "
                             f"tp={cfg.tp}")
        if cfg.model.kv_heads % cfg.tp:
            raise ValueError(
                f"n_kv_heads {cfg.model.kv_heads} must divide over "
                f"tp={cfg.tp} (replicating kv heads across tensor ranks is "
                f"not supported; lower tp or raise n_kv_heads)")


def make_lm_mesh(cfg: LMTrainConfig, devices=None) -> Mesh:
    validate_lm_cfg(cfg)
    if cfg.pp_size > 0:
        # 1F1B: a dedicated OUTERMOST 'pp' axis — stages map onto DCN
        # slices on multislice topologies (the stage-boundary ppermutes
        # are the only cross-stage traffic), and the remaining axes keep
        # the exact non-pp layout so param_specs/_two_level_sync apply
        # unchanged within each stage.
        inner = (cfg.dp * cfg.ep * cfg.sp * cfg.tp)
        if cfg.dcn_size > 1:
            return make_mesh(cfg.pp_size * inner,
                             axis_names=(PP, DCN, DATA, EXPERT, SEQ, MODEL),
                             axis_shape=(cfg.pp_size, cfg.dcn_size,
                                         cfg.dp // cfg.dcn_size, cfg.ep,
                                         cfg.sp, cfg.tp),
                             devices=devices)
        return make_mesh(cfg.pp_size * inner,
                         axis_names=(PP, DATA, EXPERT, SEQ, MODEL),
                         axis_shape=(cfg.pp_size, cfg.dp, cfg.ep,
                                     cfg.sp, cfg.tp),
                         devices=devices)
    if cfg.pp > 1:
        # pp composes with dp, sp (ring attention inside each stage's
        # layer chunks) and tp — a 4-axis mesh; unused axes have size 1.
        return make_mesh(cfg.dp * cfg.pp * cfg.sp * cfg.tp,
                         axis_names=(DATA, PIPE, SEQ, MODEL),
                         axis_shape=(cfg.dp, cfg.pp, cfg.sp, cfg.tp),
                         devices=devices)
    # The 'expert' axis is always present (size ep, usually 1 — free):
    # batch shards over (data, expert), expert weights over 'expert'.
    if cfg.dcn_size > 1:
        # multislice: the data axis factors as dcn (outer, cross-slice)
        # x data (inner, within-slice ICI)
        return make_mesh(cfg.dp * cfg.ep * cfg.sp * cfg.tp,
                         axis_names=(DCN, DATA, EXPERT, SEQ, MODEL),
                         axis_shape=(cfg.dcn_size, cfg.dp // cfg.dcn_size,
                                     cfg.ep, cfg.sp, cfg.tp),
                         devices=devices)
    return make_mesh(cfg.dp * cfg.ep * cfg.sp * cfg.tp,
                     axis_names=(DATA, EXPERT, SEQ, MODEL),
                     axis_shape=(cfg.dp, cfg.ep, cfg.sp, cfg.tp),
                     devices=devices)


def param_specs(cfg: LMTrainConfig) -> PyTree:
    """Per-leaf PartitionSpecs for the transformer params.

    Base: the Megatron tensor sharding (models/transformer.py shard_specs),
    with MoE experts on the dedicated 'expert' axis and their FFN width
    tp-sharded (EP x TP; at ep=1 the expert axis is size 1, so experts are
    simply replicated across tp with tp-sharded FFNs).
    With ``fsdp``, each leaf's first data-divisible unsharded dim
    additionally shards over 'data' (ZeRO-3): parameters and optimizer
    state shrink by the data degree per device; the train step all-gathers
    weights for use and autodiff's transpose reduce-scatters the gradients
    back.  On the factored multislice mesh (``dcn_size > 1``) 'data' is
    the SLICE-LOCAL inner axis — ZeRO-3 partitions within each slice
    (all-gathers ride ICI) and the state replicates across 'dcn', so the
    per-step cross-slice exchange stays one shard-sized gradient psum
    (the standard FSDP x multislice layout).
    """
    specs = tfm.shard_specs(cfg.model, tp_axis=MODEL,
                            ep_axis=EXPERT if cfg.ep > 1 else None)
    inner_dp = cfg.dp // cfg.dcn_size  # the mesh's actual 'data' size
    if not cfg.fsdp or inner_dp == 1:
        return specs
    shapes = jax.eval_shape(lambda k: tfm.init(k, cfg.model),
                            jax.random.key(0))

    def add_data(spec: P, shape) -> P:
        parts = list(spec) + [None] * (len(shape.shape) - len(spec))
        for i, (ax, dim) in enumerate(zip(parts, shape.shape)):
            if ax is None and dim % inner_dp == 0:
                parts[i] = DATA
                return P(*parts)
        return spec  # no divisible dim: leaf stays dp-replicated

    return jax.tree.map(add_data, specs, shapes)


def _q8_shard_gather(p: jax.Array, dim: int) -> jax.Array:
    """One fsdp leaf's all-gather, int8 on the wire (round 16,
    ``fsdp_gather_dtype="int8"``): quantize the LOCAL shard against
    per-row f32 scales (row = index along the gathered dim, so scales
    gather along the same axis as the payload), all_gather the int8
    tensor + scales over 'data', dequantize at the consumer — 4x fewer
    gather bytes for f32 params, 2x for bf16, plus one f32 scale per
    row.  Weights-not-grads: the BACKWARD is the PLAIN tiled gather's
    transpose (the ZeRO reduce-scatter of cotangents, full precision),
    a straight-through estimator — rounding the forward weights is a
    small perturbation the optimizer tracks, rounding the gradient
    stream would need the EF machinery the grad paths use."""
    axes = tuple(i for i in range(p.ndim) if i != dim)

    def _quantized(x):
        x32 = x.astype(jnp.float32)
        scale = jnp.maximum(
            jnp.max(jnp.abs(x32), axis=axes, keepdims=True) / 127.0,
            1e-30)
        q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
        qg = jax.lax.all_gather(q, DATA, axis=dim, tiled=True)
        sg = jax.lax.all_gather(scale, DATA, axis=dim, tiled=True)
        return (qg.astype(jnp.float32) * sg).astype(x.dtype)

    @jax.custom_vjp
    def g(x):
        return _quantized(x)

    def fwd(x):
        return _quantized(x), None

    def bwd(_, ct):
        return (jax.lax.psum_scatter(ct, DATA, scatter_dimension=dim,
                                     tiled=True),)

    g.defvjp(fwd, bwd)
    return g(p)


def _q4_shard_gather(p: jax.Array, dim: int) -> jax.Array:
    """One fsdp leaf's all-gather, int4 on the wire (round 18,
    ``fsdp_gather_dtype="int4"`` — lifting the round-16 refusal):
    quantize the LOCAL shard to +/-7 levels against the same per-row
    f32 scales as the int8 rung, then pack two nibbles per wire byte
    along the gathered dim (odd shard lengths pad one element, sliced
    off after the unpack) — 8x fewer gather payload bytes for f32
    params.  The gather runs untiled (leading device axis) so the
    unpack/slice happens per shard before the shards concatenate; the
    BACKWARD is unchanged from the int8 rung — the full-precision ZeRO
    reduce-scatter of cotangents (weights tolerate the 16x-coarser
    forward rounding; the gradient stream is never quantized)."""
    axes = tuple(i for i in range(p.ndim) if i != dim)
    m = p.shape[dim]

    def _quantized(x):
        x32 = x.astype(jnp.float32)
        scale = jnp.maximum(
            jnp.max(jnp.abs(x32), axis=axes, keepdims=True) / 7.0,
            1e-30)
        q = jnp.clip(jnp.round(x32 / scale), -7, 7).astype(jnp.int8)
        if m % 2:
            q = jnp.pad(q, [(0, 1) if i == dim else (0, 0)
                            for i in range(q.ndim)])
        sel = lambda start: tuple(
            slice(start, None, 2) if i == dim else slice(None)
            for i in range(q.ndim))
        packed = ((q[sel(0)] + 8).astype(jnp.uint8)
                  | ((q[sel(1)] + 8).astype(jnp.uint8) << 4))
        pg = jax.lax.all_gather(packed, DATA, axis=0)   # (n, ..packed..)
        sg = jax.lax.all_gather(scale, DATA, axis=0)    # (n, ..1-at-dim..)
        d = dim + 1  # the gather added a leading device axis
        lo = (pg & 0xF).astype(jnp.int8) - 8
        hi = ((pg >> 4) & 0xF).astype(jnp.int8) - 8
        u = jnp.stack([lo, hi], axis=d + 1)
        u = u.reshape(u.shape[:d] + (-1,) + u.shape[d + 2:])
        u = jax.lax.slice_in_dim(u, 0, m, axis=d)
        full = u.astype(jnp.float32) * sg
        # collapse (device, dim) -> the concatenated gathered dim, in
        # shard order — the tiled-gather layout the plain path produces
        full = jnp.moveaxis(full, 0, dim)
        return full.reshape(full.shape[:dim] + (-1,)
                            + full.shape[dim + 2:]).astype(x.dtype)

    @jax.custom_vjp
    def g(x):
        return _quantized(x)

    def fwd(x):
        return _quantized(x), None

    def bwd(_, ct):
        return (jax.lax.psum_scatter(ct, DATA, scatter_dimension=dim,
                                     tiled=True),)

    g.defvjp(fwd, bwd)
    return g(p)


def _fsdp_gather(params: PyTree, specs: PyTree,
                 dtype: str | None = None) -> PyTree:
    """all_gather fsdp-sharded leaves back to full (tp shards stay local).

    Inside shard_map; the transpose of these gathers is the reduce-scatter
    that delivers each device only its shard's gradient — ZeRO's comm
    pattern, synthesized by autodiff.  ``dtype="int8"`` swaps each leaf's
    gather for the quantized exchange (``_q8_shard_gather``;
    ``dtype="int4"`` the nibble-packed ``_q4_shard_gather``); the
    gradient reduce-scatter stays full-precision either way.
    """
    def gather(p, spec):
        for dim, ax in enumerate(spec):
            if ax == DATA:
                if dtype == "int8":
                    return _q8_shard_gather(p, dim)
                if dtype == "int4":
                    return _q4_shard_gather(p, dim)
                return jax.lax.all_gather(p, DATA, axis=dim, tiled=True)
        return p

    return jax.tree.map(gather, params, specs)


def _zigzag_global(cfg: LMTrainConfig, x: jax.Array) -> jax.Array:
    """Permute the GLOBAL sequence axis into the zigzag ring layout,
    inside jit (before shard_map).  Operating on the logical global array
    makes the layout correct for any process topology — multi-host runs
    where the seq axis spans processes included (a host-side permute of
    process-local slices would scramble the layout there).  XLA compiles
    the cross-shard gather; tokens are int32, so the exchange is tiny
    next to one layer's activations.  Identity unless sp > 1 and the
    layout is zigzag."""
    if cfg.sp <= 1 or cfg.seq_layout != "zigzag":
        return x
    perm = ctx.zigzag_permutation(cfg.sp, x.shape[1])  # trace-time constant
    return x[:, perm]


def _shard_positions(cfg: LMTrainConfig, s_local: int) -> jax.Array:
    """This seq-shard's absolute token positions (inside shard_map).

    Contiguous: [me*s_local, (me+1)*s_local).  Zigzag: the shard holds
    global chunks [me, 2*sp-1-me] (parallel/context.py zigzag layout).
    """
    me = jax.lax.axis_index(SEQ)
    if cfg.sp > 1 and cfg.seq_layout == "zigzag":
        return ctx.zigzag_positions(me, cfg.sp, s_local)
    return me * s_local + jnp.arange(s_local)


def pp_stage_specs(cfg: LMTrainConfig) -> PyTree:
    """Stage-stacked param specs for the pp layout — the single derivation
    of the pipe (+ optional Megatron) sharding, shared by the trainer's
    param placement and the train step's shard_map specs."""
    from .parallel import pipeline as pp
    return pp.stage_specs(cfg.model, cfg.pp,
                          tp_axis=MODEL if cfg.tp > 1 else None,
                          interleave=cfg.interleave)


def make_schedule(cfg: LMTrainConfig):
    """Constant LR, or linear warmup + cosine decay to min_lr_ratio*lr."""
    if cfg.decay_steps <= 0 and cfg.warmup_steps <= 0:
        return cfg.lr
    if cfg.decay_steps <= 0:
        return optax.linear_schedule(0.0, cfg.lr, cfg.warmup_steps)
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=cfg.lr,
        warmup_steps=cfg.warmup_steps,
        decay_steps=cfg.decay_steps,
        end_value=cfg.lr * cfg.min_lr_ratio)


def make_optimizer(cfg: LMTrainConfig) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        optax.adamw(make_schedule(cfg), b1=cfg.b1, b2=cfg.b2,
                    weight_decay=cfg.weight_decay),
    )


def _batch_axes(cfg: LMTrainConfig) -> tuple[str, ...]:
    """Axes the batch (and hence the loss reduction) shards over on the
    non-pp mesh: the factored multislice data axis adds 'dcn' outermost."""
    return ((DCN, DATA, EXPERT) if cfg.dcn_size > 1
            else (DATA, EXPERT))


def _lm_batch_spec(cfg: LMTrainConfig) -> P:
    return P(_batch_axes(cfg), SEQ)


def _spec_axes(spec) -> set:
    out = set()
    for part in spec:
        if part is None:
            continue
        out |= set(part) if isinstance(part, tuple) else {part}
    return out


def _dcn_sync_point(params: PyTree, specs: PyTree) -> PyTree:
    """Identity whose BACKWARD owns the cotangent sync of ``params`` on
    the factored multislice mesh: the data-axis reduction runs as the
    explicit two-level algorithm — reduce-scatter('data') ->
    SHARD-SIZED psum('dcn') -> all_gather_invariant('data') — instead
    of shard_map's automatic flat psum, and each leaf's remaining
    invariant axes (expert/seq, and 'model' for tp-replicated leaves,
    read off its PartitionSpec) get their flat intra-slice psums.  The
    cotangent returns fully vma-invariant, so shard_map inserts nothing
    more: the shard-sized DCN payload is a property of the program,
    pinned by tests/test_lm.py::test_dcn_payload_is_shard_sized_lm.

    Placement is the caller's: the post-backward path wraps the WHOLE
    tree once (all dcn traffic after the backward drains); overlap=True
    wraps each layer group at its boundary (``_stream_group_boundary``),
    so the groups' sync points stream through the backward."""
    @jax.custom_vjp
    def point(p):
        return p

    def fwd(p):
        return p, None

    def bwd(_, g):
        return (_two_level_sync(g, specs),)

    point.defvjp(fwd, bwd)
    return point(params)


def _local_sync_point(params: PyTree, specs: PyTree, n_dcn: int) -> PyTree:
    """``_dcn_sync_point``'s window-local sibling (round 18): identity
    whose backward syncs cotangents over every mesh axis EXCEPT 'dcn' —
    per-leaf psums over the leaf's invariant intra-slice axes (the
    ``_fsdp_gather`` transpose already reduce-scattered fsdp leaves over
    'data'), scaled by ``n_dcn`` so each slice's local step sees its
    slice-mean gradient at the full-batch rate (equal per-slice token
    counts make the scaled slice mean an unbiased estimate of the
    global mean).  The cotangent returns dcn-VARYING by construction:
    inside a sync window no gradient byte crosses DCN — the property
    the schedule inspector pins."""
    scale = jnp.float32(n_dcn)

    @jax.custom_vjp
    def point(p):
        return p

    def fwd(p):
        return p, None

    def bwd(_, g):
        leaves, td = jax.tree.flatten(g)
        out = []
        for gl, sp in zip(leaves, jax.tree.leaves(specs)):
            axes = _spec_axes(sp)
            rest = tuple(a for a in (DATA, EXPERT, SEQ, MODEL)
                         if a not in axes)
            gl = jax.lax.psum(gl, rest) if rest else gl
            out.append(gl * scale.astype(gl.dtype))
        return (jax.tree.unflatten(td, out),)

    point.defvjp(fwd, bwd)
    return point(params)


def _sync_bucket_bytes(cfg: LMTrainConfig) -> int:
    """The factored-mesh streaming bucket size in bytes —
    ``cfg.bucket_mb`` (the round-11 tunable the autotuner sets) or the
    historical strategies.BUCKET_CAP_MB default."""
    from .parallel.strategies import BUCKET_CAP_MB
    mb = cfg.bucket_mb if cfg.bucket_mb is not None else BUCKET_CAP_MB
    return int(mb * 1024 * 1024)


def _sync_partition(g_leaves: list, s_leaves: list,
                    bucket_bytes: int | None) -> list[tuple[str, list[int]]]:
    """The ONE ordered partition of the grad tree the factored-mesh sync
    walks: fsdp ('data'-sharded) leaves first, then the remaining leaves
    grouped by their sharded-axes set (first-appearance order), each run
    split into ~bucket_bytes buckets (``strategies.make_bucket_plan``).
    Returns ``[(kind, [leaf_index, ...]), ...]`` with kind 'fsdp' or
    'two_level'.  Deterministic given shapes/specs — the layout contract
    between ``_two_level_sync``'s execution and the EF-residual sizing
    (``lm_sync_state_len``), which must never disagree."""
    from .parallel.strategies import make_bucket_plan

    groups: dict = {}
    fsdp_items: list[int] = []
    for i, sp in enumerate(s_leaves):
        axes = _spec_axes(sp)
        if DATA in axes:
            fsdp_items.append(i)
        else:
            groups.setdefault(frozenset(axes), []).append(i)

    def buckets(idxs: list[int]) -> list[list[int]]:
        if not idxs:
            return []
        if bucket_bytes is None or len(idxs) <= 1:
            return [idxs]
        plan = make_bucket_plan([g_leaves[i] for i in idxs], bucket_bytes)
        return [[idxs[j] for j in b] for b in plan]

    return ([("fsdp", b) for b in buckets(fsdp_items)]
            + [("two_level", b) for items in groups.values()
               for b in buckets(items)])


def _bucket_residual_len(kind: str, total_elems: int, n_dcn: int,
                         n_ici: int) -> int:
    """EF-residual length of one bucket's int8 DCN exchange: n_dcn x the
    block-aligned ring chunk of the payload that actually crosses DCN —
    the full (already shard-sized) flat vector for fsdp buckets, the ICI
    shard (``two_level_psum`` pads to an n_ici multiple) otherwise."""
    from .parallel.strategies import QuantizedRing
    base = total_elems if kind == "fsdp" else -(-total_elems // n_ici)
    return n_dcn * QuantizedRing()._chunk(base, n_dcn)


def _residual_total_len(g_leaves: list, s_leaves: list, n_dcn: int,
                        n_ici: int, bucket_bytes: int | None) -> int:
    """Total EF-residual length for one sync of ``g_leaves`` — segments
    in ``_sync_partition`` order (the consumption order of
    ``_two_level_sync``)."""
    total = 0
    for kind, idxs in _sync_partition(g_leaves, s_leaves, bucket_bytes):
        elems = sum(int(g_leaves[i].size) for i in idxs)
        total += _bucket_residual_len(kind, elems, n_dcn, n_ici)
    return total


def _two_level_sync(g: PyTree, specs: PyTree,
                    bucket_bytes: int | None = None,
                    dcn_compress: str | None = None,
                    residual: jax.Array | None = None):
    """The factored-mesh gradient sync itself (shared by the custom-VJP
    points, the grad-accumulation path, and the 1F1B path): per-leaf
    flat psums over each leaf's remaining invariant axes, then the
    grouped two-level (data, dcn) reduction over the ``_sync_partition``
    buckets.  Leaves are grouped by their sharded axes:
    ``two_level_psum`` flattens a group into ONE vector, so mixing
    (say) tp-sharded leaves — whose values legitimately vary over
    'model' — with replicated ones would poison the latter's vma.

    ``bucket_bytes`` (round 9, the grad-accumulation path) splits each
    group into ~bucket-sized pipelines (``strategies.make_bucket_plan``)
    instead of one monolithic flat vector per group: bucket N's ICI
    reduce-scatter can run under bucket N-1's DCN psum.  The plain
    reduction is elementwise, so the split changes no sums — numerics
    are bitwise bucket-independent (test-pinned).

    FSDP leaves ('data' in the spec) skip the two-level reduction
    entirely: the ``_fsdp_gather`` transpose already reduce-scattered
    their cotangent over 'data', so what arrives here IS the
    slice-local ZeRO-3 shard — the cross-slice exchange is one
    shard-sized ``psum('dcn')`` per bucket, the same DCN payload as the
    replicated-state path.

    ``dcn_compress="int8"`` (round 11) replaces every bucket's DCN
    exchange with ``QuantizedRing._ring_sum`` — int8 payloads + per-row
    f32 scales on each cross-slice transfer, the ICI steps untouched —
    consuming/refilling ``residual`` segments in partition order and
    returning ``(synced, new_residual)``.  ``"int4"`` (round 16) is the
    same exchange one rung lower: nibble-packed payloads, half the DCN
    bytes, identical residual layout (``_chunk`` is bits-independent).
    Numerics become bucket-LAYOUT-dependent through the row scales (the
    layout is the partition above, shared with the residual sizing).

    Round 20: every bucket body is a routed ``HopPlan`` compiled by
    ``parallel/routing.execute`` — fsdp buckets run the single-hop
    ``dcn:psum`` (leaf mode, one multi-operand psum) or
    ``dcn:ring[bits+ef]`` route, two_level buckets the ``data:rs →
    dcn:… → data:ag`` route via ``two_level_psum`` (itself routed).
    The op sequences are identical; the pre-existing loss/census/EF
    pins on this function now pin the route compiler."""
    from .parallel import routing
    from .parallel.strategies import QuantizedRing, two_level_psum

    g_leaves, td = jax.tree.flatten(g)
    s_leaves = jax.tree.leaves(specs)
    synced_in: list = []
    for gl, sp in zip(g_leaves, s_leaves):
        axes = _spec_axes(sp)
        rest = tuple(a for a in (EXPERT, SEQ, MODEL)
                     if a not in axes)
        synced_in.append(jax.lax.psum(gl, rest) if rest else gl)
    part = _sync_partition(g_leaves, s_leaves, bucket_bytes)
    out: list = [None] * len(g_leaves)
    if dcn_compress is None:
        for kind, idxs in part:
            vals = [synced_in[i] for i in idxs]
            if kind == "fsdp":
                # one psum primitive per bucket, per-leaf payloads (no
                # concat: leaves keep their own vma; each is already
                # data-shard-sized)
                synced, _ = routing.execute(
                    routing.HopPlan((routing.Hop("exchange", DCN),)),
                    vals, concat=False)
            else:
                synced = two_level_psum(vals, DCN, DATA)
            for i, s in zip(idxs, synced):
                out[i] = s
        return jax.tree.unflatten(td, out)
    # quantized DCN hop (int8 round 11, int4 round 16): ring-exchange
    # each bucket at the configured bit width, EF residual segments
    # consumed and refilled in partition order
    ring = QuantizedRing(bits=4 if dcn_compress == "int4" else 8)
    n_dcn = jax.lax.axis_size(DCN)
    n_ici = jax.lax.axis_size(DATA)
    offset = 0
    new_parts: list = []
    for kind, idxs in part:
        vals = [synced_in[i] for i in idxs]
        elems = sum(int(g_leaves[i].size) for i in idxs)
        seg = _bucket_residual_len(kind, elems, n_dcn, n_ici)
        res = residual[offset:offset + seg]
        offset += seg
        if kind == "fsdp":
            # the bucket is already shard-sized: ring the concatenated
            # flat vector across slices directly (the single-hop
            # dcn:ring[bits+ef] route)
            synced, new_r = routing.execute(
                routing.HopPlan((routing.Hop(
                    "exchange", DCN, algorithm="ring",
                    bits=dcn_compress, ef=True),)),
                vals, residuals=[res])
            new_parts.extend(new_r)
        else:
            captured: dict = {}

            def dcn_reduce(shard, res=res, captured=captured):
                summed, err_rows = ring._ring_sum(shard, DCN, n_dcn,
                                                  residual=res)
                captured["res"] = err_rows.ravel()
                return summed

            synced = two_level_psum(vals, DCN, DATA, dcn_reduce=dcn_reduce)
            new_parts.append(captured["res"])
        for i, s in zip(idxs, synced):
            out[i] = s
    new_residual = (jnp.concatenate(new_parts) if new_parts
                    else jnp.zeros((0,), jnp.float32))
    return jax.tree.unflatten(td, out), new_residual


def _dcn_sync_point_stateful(params: PyTree, residual: jax.Array,
                             specs: PyTree,
                             bucket_bytes: int | None,
                             dcn_compress: str = "int8") -> PyTree:
    """``_dcn_sync_point`` with the quantized (int8 or int4) DCN hop:
    the EF residual rides the forward as an inert second input and its
    COTANGENT channel carries the updated residual out of the backward
    (the strategies.sync_boundary_stateful trick) — differentiate the
    loss w.r.t. ``(params, sync_state)`` and the sync-state "gradient"
    IS the next step's carry."""
    @jax.custom_vjp
    def point(p, r):
        return p

    def fwd(p, r):
        return p, r

    def bwd(r, g):
        synced, new_r = _two_level_sync(g, specs, bucket_bytes=bucket_bytes,
                                        dcn_compress=dcn_compress,
                                        residual=r)
        return synced, new_r

    point.defvjp(fwd, bwd)
    return point(params, residual)


def _local_sized_leaves(shapes: PyTree, specs: PyTree,
                        axis_sizes: dict[str, int]) -> list:
    """Per-leaf LOCAL (per-device shard) sizes of a param subtree in
    flatten order — the shapes the grad cotangents have at the sync
    point inside shard_map (fsdp leaves arrive data-shard-sized, tp
    leaves model-shard-sized).  Leaves are ``strategies.SizedLeaf``
    stand-ins — the ONE shapes-only contract ``make_bucket_plan``
    reads."""
    from .parallel.strategies import SizedLeaf
    out: list[SizedLeaf] = []
    for sh, sp in zip(jax.tree.leaves(shapes), jax.tree.leaves(specs)):
        dims = list(sh.shape)
        for d, ax in enumerate(sp):
            if ax is None:
                continue
            for name in (ax if isinstance(ax, tuple) else (ax,)):
                dims[d] //= axis_sizes[name]
        out.append(SizedLeaf(int(np.prod(dims, dtype=np.int64) or 1),
                             sh.dtype))
    return out


def lm_sync_state_len(cfg: LMTrainConfig, mesh: Mesh) -> int:
    """Total per-device EF-residual length for ``dcn_compress="int8"``
    — the layout contract between LMTrainer's ``sync_state`` init and
    the step's consumption order: the whole-tree partition for the
    post-backward and grad-accumulation paths, or the per-layer-group
    partitions in forward (group-index) order under streaming
    ``overlap`` (exactly the walk ``_stream_group_boundary`` makes).
    Under sync windows (``sync_every > 1``) the quantized exchange
    happens ONLY at the whole-tree window boundary — local steps stream
    ICI-only points with no residual — so the layout is the whole-tree
    partition even when ``overlap`` is on."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    n_dcn, n_ici = sizes[DCN], sizes[DATA]
    bucket_bytes = _sync_bucket_bytes(cfg)
    specs = param_specs(cfg)
    shapes = jax.eval_shape(lambda k: tfm.init(k, cfg.model),
                            jax.random.key(0))
    streamed = (cfg.overlap and cfg.grad_accum == 1
                and cfg.sync_every == 1)
    if not streamed:
        return _residual_total_len(
            _local_sized_leaves(shapes, specs, sizes),
            jax.tree.leaves(specs), n_dcn, n_ici, bucket_bytes)
    total = 0
    for key, _ in sorted(tfm.sync_group_index(cfg.model).items(),
                         key=lambda kv: kv[1]):
        total += _residual_total_len(
            _local_sized_leaves(shapes[key], specs[key], sizes),
            jax.tree.leaves(specs[key]), n_dcn, n_ici, bucket_bytes)
    return total


def _stream_group_boundary(cfg: LMTrainConfig, specs, *, dcn_sync: bool,
                           residual: jax.Array | None = None,
                           local_n_dcn: int | None = None):
    """The streaming (``cfg.overlap``) layer-group hook: at each group's
    boundary in ``transformer.apply``, wrap the group's params in the
    two-level DCN sync point (``dcn_sync``, round 9) and/or gather its
    ZeRO-3 shards (``cfg.fsdp``, round 8) — instead of doing either
    all-at-once on the whole tree.  The ops are IDENTICAL to the
    whole-tree path (the two-level reduction is elementwise, the gathers
    are the same per-leaf all_gathers) — only their position moves, so
    trajectories are bitwise-identical; in the backward, each group's
    gradient reduce-scatter (the gather's transpose) runs first and the
    sync point's shard-sized ``psum('dcn')`` immediately after, right
    where that group's backward matmuls finish — the per-layer-group
    streaming the latency-hiding scheduler needs (utils/debug.py
    op_schedule pins the dcn-axis interleaving)."""
    # one source of truth for the boundary numbering: the model's own
    # group schedule (transformer.sync_group_index), inverted to
    # group-index -> top-level param key
    keys = {v: k for k, v in tfm.sync_group_index(cfg.model).items()}
    bucket_bytes = _sync_bucket_bytes(cfg)
    # int8 streaming (round 11): each group's stateful point consumes
    # its own residual slice; offsets advance in boundary (= group,
    # = forward) order, the same walk lm_sync_state_len sizes — the
    # closure counter is fresh per trace (the boundary is rebuilt
    # inside each loss trace).
    state = {"off": 0}

    def boundary(group: int, params):
        k = keys.get(group)
        if k is None:
            return params
        p = dict(params)
        sub = p[k]
        # forward order: sync point THEN gather, so the backward runs the
        # gather's reduce-scatter first and the point's psum('dcn') on
        # the already-scattered shard — the whole-tree op sequence
        if local_n_dcn is not None:
            # window-local streaming (round 18): the group's sync point
            # stays at its boundary but reduces intra-slice only — the
            # latency-hiding interleave without the DCN hop
            sub = _local_sync_point(sub, specs[k], local_n_dcn)
        elif dcn_sync:
            if residual is not None:
                n_dcn = jax.lax.axis_size(DCN)
                n_ici = jax.lax.axis_size(DATA)
                seg = _residual_total_len(
                    jax.tree.leaves(sub), jax.tree.leaves(specs[k]),
                    n_dcn, n_ici, bucket_bytes)
                a = state["off"]
                state["off"] = a + seg
                sub = _dcn_sync_point_stateful(sub, residual[a:a + seg],
                                               specs[k], bucket_bytes,
                                               cfg.dcn_compress)
            else:
                sub = _dcn_sync_point(sub, specs[k])
        if cfg.fsdp:
            sub = _fsdp_gather(sub, specs[k], cfg.fsdp_gather_dtype)
        p[k] = sub
        return p

    return boundary


def _build_local_loss(cfg: LMTrainConfig, specs, *, dcn_sync: bool,
                      local_window: bool = False):
    """The per-shard loss shared by every grad path.  ``dcn_sync``
    injects the custom-VJP two-level sync point on params (the a=1
    factored-mesh path); the accumulation path passes False and syncs
    ONCE after its local scan instead.  ``local_window`` (round 18, the
    sync_every > 1 local steps) injects the ICI-only sync point
    (``_local_sync_point``) instead — same streaming positions under
    ``overlap``, no DCN traffic, cotangents dcn-varying; the window
    boundary exchange handles the cross-slice hop (and the EF residual,
    when compressed) in its own program.

    With ``cfg.dcn_compress`` AND ``dcn_sync`` the returned loss is the
    STATEFUL variant ``(params, residual, tokens, targets, n_total,
    aux_w)``: the sync points become their int8-ring stateful forms and
    differentiating w.r.t. ``residual`` yields the updated EF carry
    (round 11)."""
    dtype = cfg.dtype
    # tp psums always run (free over a size-1 'model' axis) — they also carry
    # the vma bookkeeping that makes the loss provably replicated.  The ring
    # only replaces local flash attention when the seq axis is actually cut.
    tp_axis = MODEL
    seq_axis = SEQ if cfg.sp > 1 else None
    reduce_axes = _batch_axes(cfg) + (SEQ,)
    stateful = (cfg.dcn_compress is not None and dcn_sync
                and not local_window)
    bucket_bytes = _sync_bucket_bytes(cfg)

    def local_loss(params, tokens, targets, n_total, aux_w, residual=None):
        boundary = None
        if cfg.overlap and (dcn_sync or cfg.fsdp or local_window):
            # streaming (rounds 8-9): per-layer-group sync points and/or
            # ZeRO-3 gathers at the boundaries instead of whole-tree
            boundary = _stream_group_boundary(
                cfg, specs, dcn_sync=dcn_sync and not local_window,
                residual=residual,
                local_n_dcn=cfg.dcn_size if local_window else None)
        else:
            if local_window:
                params = _local_sync_point(params, specs, cfg.dcn_size)
            elif dcn_sync:
                if residual is not None:
                    # stateful whole-tree point: the quantized-ring
                    # exchange with the EF residual channel (round 11;
                    # int4 rung round 16)
                    params = _dcn_sync_point_stateful(
                        params, residual, specs, bucket_bytes,
                        cfg.dcn_compress)
                else:
                    # route the data-axis cotangent sync through the
                    # explicit two-level reduction (shard-sized DCN
                    # payload), as one whole-tree point — the
                    # post-backward contrast shape
                    params = _dcn_sync_point(params, specs)
            if cfg.fsdp:
                params = _fsdp_gather(params, specs,
                                      cfg.fsdp_gather_dtype)
        pos = _shard_positions(cfg, tokens.shape[1])
        # the unified head-loss seam (round 17, ops/losses.py): apply
        # hands the final-norm hidden states + the boundary-transformed
        # tied embedding (under streaming ZeRO-3 the GATHERED copy) to
        # head_loss, which routes dense (historical ops, bit-for-bit) or
        # chunked (streamed logits; vocab tp-sharded when tp > 1)
        head = partial(losses.head_loss, targets=targets,
                       loss_impl=cfg.loss_impl, loss_chunk=cfg.loss_chunk,
                       tp_axis=tp_axis if cfg.tp > 1 else None,
                       tp_size=cfg.tp)
        (ce_sum, _), aux = tfm.apply(
            params, tokens, cfg=cfg.model, dtype=dtype,
            seq_axis=seq_axis, seq_layout=cfg.seq_layout,
            tp_axis=tp_axis, pos=pos,
            ep_axis=EXPERT if cfg.ep > 1 else None,
            return_aux=True, boundary=boundary,
            matmul_dtype=cfg.matmul_dtype, remat=cfg.remat,
            head_fn=head)
        # Global mean over every shard's tokens; the batch shards over
        # (data, expert), so 'expert' reduces like a data axis ('model'
        # shards compute identical values, no reduction needed there).
        # ``n_total`` is the caller-counted GLOBAL valid-token count of the
        # step's full batch — under gradient accumulation each microbatch
        # contributes ce_sum_i/n_total with aux_w = coef/A, so the SUM of
        # microbatch grads is exactly the unaccumulated step's gradient.
        ce_sum = jax.lax.psum(ce_sum, reduce_axes)
        aux = jax.lax.pmean(aux, reduce_axes)  # pmean'd over MODEL
        return ce_sum / jnp.maximum(n_total, 1) + aux_w * aux

    if stateful:
        def local_loss_st(params, residual, tokens, targets, n_total,
                          aux_w):
            return local_loss(params, tokens, targets, n_total, aux_w,
                              residual=residual)
        return local_loss_st
    return local_loss


def _make_grad_step(cfg: LMTrainConfig, mesh: Mesh):
    """The ONE shard_mapped loss-and-grad builder shared by the single-step
    and K-step-scan train paths (their loss semantics must never drift).

    With ``cfg.dcn_compress`` (round 11) the returned fn is stateful:
    ``(params, sync_state, tokens, targets, n_total, aux_w) -> (loss,
    grads, new_sync_state)``, the per-device EF residual carried as a
    ``(n_devices, L)`` array sharded one row per device."""
    specs = param_specs(cfg)
    local_loss = _build_local_loss(cfg, specs,
                                   dcn_sync=cfg.dcn_size > 1)
    bspec = _lm_batch_spec(cfg)
    if cfg.dcn_compress is None or cfg.dcn_size <= 1:
        return shard_map(
            jax.value_and_grad(local_loss),
            mesh=mesh,
            in_specs=(specs, bspec, bspec, P(), P()),
            out_specs=(P(), specs),
            # check_vma stays ON: the automatic psum of cotangents for
            # axis-invariant params (the fused DP/SP gradient sync)
            # depends on it.
        )
    rspec = P(tuple(mesh.axis_names))
    vg = jax.value_and_grad(local_loss, argnums=(0, 1))

    def stateful(params, res, tokens, targets, n_total, aux_w):
        loss, (grads, new_r) = vg(params, res[0], tokens, targets,
                                  n_total, aux_w)
        return loss, grads, new_r[None]

    return shard_map(
        stateful, mesh=mesh,
        in_specs=(specs, rspec, bspec, bspec, P(), P()),
        out_specs=(P(), specs, rspec),
        # the int8 ring assembles its result from ppermute payloads —
        # replicated by construction, not provably (the vma_opaque trade
        # train.py makes for the same strategy); every param's data-axis
        # sync is EXPLICIT through the stateful point, so nothing here
        # relies on the automatic cotangent psums check_vma enables.
        check_vma=False)


def _make_accum_grad_step(cfg: LMTrainConfig, mesh: Mesh):
    """Gradient accumulation with ONE cross-device exchange per
    optimizer step, for the factored multislice mesh: the A microbatch
    backwards run with NO cross-slice traffic inside one shard_map (the
    per-microbatch collectives are intra-slice only: the loss's scalar
    psums, plus the ZeRO-3 weight gathers / gradient reduce-scatters
    when fsdp is on), local grads accumulate through a lax.scan, and
    the accumulated tree syncs once — per-leaf intra psums + the
    grouped two-level (data, dcn) reduction (shard-sized psum('dcn')
    for fsdp leaves), emitted per ~25 MB bucket (round 9) so the
    exchange pipelines instead of moving as one monolithic per-group
    vector.  The naive alternative (scanning the synced grad_step)
    pays A sequential shard-sized DCN round-trips per step.

    ``(params, micro_tokens (A, B, S), micro_targets, n_total, aux_w)
    -> (summed loss, synced grads)``; numerics match the scanned path
    to f32 reassociation noise (sum-then-sync == sync-then-sum)."""
    specs = param_specs(cfg)
    local_loss = _build_local_loss(cfg, specs, dcn_sync=False)
    grad_fn = jax.value_and_grad(local_loss)
    bucket_bytes = _sync_bucket_bytes(cfg)

    def local_grads(params, micro_t, micro_y, n_total, aux_w):
        def body(carry, batch):
            loss_acc, g_acc = carry
            tk, tg = batch
            loss_i, g_i = grad_fn(params, tk, tg, n_total, aux_w)
            return (loss_acc + loss_i,
                    jax.tree.map(jnp.add, g_acc, g_i)), None

        zeros = jax.tree.map(jnp.zeros_like, params)
        (loss, g), _ = jax.lax.scan(
            body, (jnp.float32(0), zeros), (micro_t, micro_y))
        return loss, g

    bspec = _lm_batch_spec(cfg)
    mspec = P(None, *bspec)  # leading scan axis unsharded
    if cfg.dcn_compress is None:
        def local_accum(params, micro_t, micro_y, n_total, aux_w):
            loss, g = local_grads(params, micro_t, micro_y, n_total, aux_w)
            # the ONE post-accumulation sync, streamed per ~bucket_mb
            # bucket (round 9) instead of as a monolithic per-group
            # tree: bucket N's ICI reduce-scatter runs under bucket
            # N-1's DCN psum
            return loss, _two_level_sync(g, specs,
                                         bucket_bytes=bucket_bytes)

        return shard_map(
            local_accum, mesh=mesh,
            in_specs=(specs, mspec, mspec, P(), P()),
            out_specs=(P(), specs))

    # quantized DCN hop (round 11; int4 rung round 16): the one
    # post-accumulation exchange rides the ring with the EF residual
    # threaded through directly (no custom-vjp needed — the sync runs
    # OUTSIDE the microbatch autodiff)
    rspec = P(tuple(mesh.axis_names))

    def local_accum_st(params, res, micro_t, micro_y, n_total, aux_w):
        loss, g = local_grads(params, micro_t, micro_y, n_total, aux_w)
        synced, new_r = _two_level_sync(g, specs, bucket_bytes=bucket_bytes,
                                        dcn_compress=cfg.dcn_compress,
                                        residual=res[0])
        return loss, synced, new_r[None]

    return shard_map(
        local_accum_st, mesh=mesh,
        in_specs=(specs, rspec, mspec, mspec, P(), P()),
        out_specs=(P(), specs, rspec),
        # vma_opaque: the ring's ppermute-assembled result (see
        # _make_grad_step's compressed branch)
        check_vma=False)


# the ONE implementation of the round-13 [grad-norm, param-norm]
# telemetry vector lives next to the loss primitives (ops/nn.py
# step_metrics) — train.py's in-scan body uses the same function
_step_metrics = step_metrics


def _make_window_grad_step(cfg: LMTrainConfig, mesh: Mesh):
    """The window-LOCAL loss-and-grad program (round 18,
    ``sync_every > 1``): each 'dcn' slice forwards at its own params
    ``p = anchor + delta[slice]`` and its gradient syncs over the
    intra-slice axes only (``_local_sync_point`` — ICI traffic, scaled
    x n_dcn), so the returned grads are dcn-VARYING and come back
    STACKED over a leading 'dcn' axis (one slice's slice-mean estimate
    per row).  ``(anchor, delta, tokens, targets, n_total, aux_w) ->
    (loss, grads)`` with loss still the global scalar (each slice's
    tokens scored under its own slice params — scalar psums only)."""
    specs = param_specs(cfg)
    local_loss = _build_local_loss(cfg, specs, dcn_sync=False,
                                   local_window=True)
    bspec = _lm_batch_spec(cfg)
    dspec = jax.tree.map(lambda s: P(DCN, *s), specs)

    def _vary_dcn(a):
        if DCN in compat.vma_of(a):
            return a
        return compat.pcast(a, (DCN,), to="varying")

    def local(anchor, delta, tokens, targets, n_total, aux_w):
        # anchor is dcn-invariant, the delta block dcn-varying: cast the
        # anchor varying so the sum is well-typed under check_vma
        p = jax.tree.map(lambda a, d: _vary_dcn(a) + d[0], anchor, delta)
        loss, g = jax.value_and_grad(local_loss)(
            p, tokens, targets, n_total, aux_w)
        return loss, jax.tree.map(lambda x: x[None], g)

    return shard_map(
        local, mesh=mesh,
        in_specs=(specs, dspec, bspec, bspec, P(), P()),
        out_specs=(P(), dspec))


def _lm_window_wire_bytes(cfg: LMTrainConfig, mesh: Mesh) -> int:
    """Predicted per-device DCN payload bytes of ONE window-boundary
    delta exchange (f32, pre-quantization) — the whole-tree
    ``_sync_partition`` walk the boundary program makes: fsdp buckets
    are already data-shard-sized, two-level buckets cross DCN as their
    ICI shard.  Feeds the per-window ``window_wire_bytes`` telemetry
    gauge (utils/telemetry.emit_sync_windows)."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    n_data = sizes[DATA]
    specs = param_specs(cfg)
    shapes = jax.eval_shape(lambda k: tfm.init(k, cfg.model),
                            jax.random.key(0))
    leaves = _local_sized_leaves(shapes, specs, sizes)
    total = 0
    for kind, idxs in _sync_partition(leaves, jax.tree.leaves(specs),
                                      _sync_bucket_bytes(cfg)):
        elems = sum(int(leaves[i].size) for i in idxs)
        total += 4 * (elems if kind == "fsdp" else -(-elems // n_data))
    return total


def _lm_outer(cfg: LMTrainConfig):
    """The configured DiLoCo outer optimizer, or None for the plain-mean
    boundary — also None when trivial (momentum==0 ∧ lr==1), the
    build-time collapse that keeps zero-momentum bitwise ≡ round 18."""
    from .parallel.strategies import OuterOptimizer
    if cfg.sync_every > 1 and cfg.outer_opt is not None:
        outer = OuterOptimizer(cfg.outer_opt, cfg.outer_momentum,
                               cfg.outer_lr)
        if not outer.trivial:
            return outer
    return None


def make_lm_window_steps(cfg: LMTrainConfig, mesh: Mesh):
    """The communication-sparse program family (round 18,
    ``sync_every = H > 1`` on the factored multislice mesh):

    - ``local``: one optimizer step with NO cross-slice traffic —
      ``(anchor, delta, opt_state, tokens, targets[, step_no,
      fault_arm]) -> (delta, opt_state, loss, ok, met)``.  ``delta``
      (the accumulated optax updates since the last exchange) and the
      optimizer state carry a leading 'dcn' axis: each slice advances
      its own Adam trajectory at ``p = anchor + delta[slice]``
      (``jax.vmap`` over the slice axis; the anchor — the live
      ``LMTrainer.params`` — is read-only here).  ``ok``/``met`` cover
      ALL slices (gsq sums the stacked grads; the param-norm runs over
      the stacked tree, ~sqrt(n_dcn) x the per-slice figure).
    - ``exchange`` (staleness 0): average the deltas across 'dcn'
      through the SAME bucketed two-level reduction the per-step path
      uses (``_two_level_sync`` — dcn_compress rides it with the EF
      residual, now charged once per window), fold the mean into the
      anchor, zero the delta.  Each leaf prescales by
      1/(n_dcn * n_rest [* n_data]) so the redundant intra-slice psums
      cancel exactly and what lands is the plain mean over slices.
    - ``launch``/``apply`` (staleness S > 0): ``launch`` runs the same
      exchange but leaves anchor and delta untouched, returning the
      averaged delta and a SNAPSHOT of the launched delta; ``apply``
      (dispatched S steps later) folds the average into the anchor and
      subtracts the snapshot from the live delta — local progress made
      during the S steps is kept, and the DCN round-trip has S local
      steps to drain under.

    Round 22 grows two build-time variants on the boundary programs
    (the legacy plain-mean/uniform branches stay byte-identical):

    - ``cfg.outer_opt``: ``exchange``/``apply`` take (and return) the
      DiLoCo outer-momentum tree ``m`` and move the anchor by
      ``outer_opt(mean delta)`` instead of the plain add.
    - ``cfg.sync_every_per_slice``: ``exchange`` takes a host-computed
      (n_dcn,) f32 participation MASK — slices with mask==0 contribute
      an exact zero delta (masked before prescale, inside the
      shard_map, so the EF residual ledger stays exact) and keep their
      accumulated delta; the mean still divides by all n_dcn slices
      and every slice adopts the anchor move, so params stay
      replicated.  Argument order: ``[anchor, delta]``
      ``+ [sync_state] if dcn_compress + [m] if outer + [mask] if
      per-slice``; returns mirror the inputs minus the mask."""
    tx = make_optimizer(cfg)
    grad_step = _make_window_grad_step(cfg, mesh)
    specs = param_specs(cfg)
    dspec = jax.tree.map(lambda s: P(DCN, *s), specs)
    bucket_bytes = _sync_bucket_bytes(cfg)
    n_dcn = cfg.dcn_size
    n_data = cfg.dp // cfg.dcn_size
    coef = jnp.float32(cfg.aux_coef)
    compress = cfg.dcn_compress is not None
    rspec = P(tuple(mesh.axis_names))
    rest_sizes = {EXPERT: cfg.ep, SEQ: cfg.sp, MODEL: cfg.tp}

    def _prescale(dl, sp):
        axes = _spec_axes(sp)
        n_rest = int(np.prod([rest_sizes[a]
                              for a in (EXPERT, SEQ, MODEL)
                              if a not in axes], dtype=np.int64))
        denom = n_dcn * n_rest * (1 if DATA in axes else n_data)
        return dl * jnp.asarray(1.0 / denom, dl.dtype)

    def _vary_all(x):
        missing = tuple(a for a in mesh.axis_names
                        if a not in compat.vma_of(x))
        return compat.pcast(x, missing, to="varying") if missing else x

    outer = _lm_outer(cfg)
    use_outer = outer is not None
    per_slice = cfg.sync_every_per_slice is not None

    def _ex_core(delta, residual, mask=None):
        d = jax.tree.map(lambda x: x[0], delta)
        if mask is not None:
            # per-slice windows (round 22): zero a skipping slice's
            # contribution BEFORE prescale, inside the shard_map — the
            # downstream int8/int4 ring quantizes the masked value, so
            # the EF residual ledger stays exact (invariant-pinned)
            my = mask[jax.lax.axis_index(DCN)]
            d = jax.tree.map(lambda x: x * my.astype(x.dtype), d)
        d = jax.tree.map(_prescale, d, specs)
        d = jax.tree.map(_vary_all, d)
        if compress:
            d_avg, new_r = _two_level_sync(
                d, specs, bucket_bytes=bucket_bytes,
                dcn_compress=cfg.dcn_compress, residual=residual[0])
            return d_avg, new_r[None]
        return _two_level_sync(d, specs, bucket_bytes=bucket_bytes)

    ex_core_m = None
    if compress:
        if per_slice:
            ex_core_m = shard_map(
                _ex_core, mesh=mesh, in_specs=(dspec, rspec, P()),
                out_specs=(specs, rspec), check_vma=False)
        ex_core = shard_map(
            _ex_core, mesh=mesh, in_specs=(dspec, rspec),
            out_specs=(specs, rspec),
            # the ring's ppermute-assembled result (see _make_grad_step)
            check_vma=False)
    else:
        if per_slice:
            ex_core_m = shard_map(
                lambda delta, mask: _ex_core(delta, None, mask),
                mesh=mesh, in_specs=(dspec, P()), out_specs=specs,
                # the varying-index mask gather defeats the static
                # replication proof the same way the ring assembly does
                check_vma=False)
        ex_core = shard_map(
            lambda delta: _ex_core(delta, None), mesh=mesh,
            in_specs=(dspec,), out_specs=specs)

    def _mask_reset(delta, mask):
        # participants (mask==1) restart their window from zero;
        # skippers keep the accumulated delta — a jnp.where select, so
        # the kept values are bitwise untouched
        def reset(x):
            mb = mask.reshape((n_dcn,) + (1,) * (x.ndim - 1))
            return jnp.where(mb != 0, jnp.zeros_like(x), x)
        return jax.tree.map(reset, delta)

    @partial(jax.jit, donate_argnums=compat.donate(1, 2))
    def local_step(anchor, delta, opt_state, tokens, targets, step_no=0,
                   fault_arm=0.0):
        tokens = _zigzag_global(cfg, tokens)
        targets = _zigzag_global(cfg, targets)
        n_total = jnp.sum(targets != IGNORE).astype(jnp.float32)
        loss, grads = grad_step(anchor, delta, tokens, targets, n_total,
                                coef)
        grads = faults.tap_grads(grads, step_no, fault_arm)
        loss = faults.tap_loss(loss, step_no, fault_arm)
        gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                  for g in jax.tree.leaves(grads))
        ok = (jnp.isfinite(loss) & jnp.isfinite(gsq)).astype(jnp.float32)
        p = jax.tree.map(lambda a, d: a[None] + d, anchor, delta)
        updates, opt_state = jax.vmap(tx.update)(grads, opt_state, p)
        delta = jax.tree.map(jnp.add, delta, updates)
        met = _step_metrics(
            gsq, jax.tree.map(lambda a, d: a[None] + d, anchor, delta))
        return delta, opt_state, loss, ok, met

    if compress:
        if use_outer and per_slice:
            @partial(jax.jit, donate_argnums=compat.donate(0, 1, 2, 3))
            def exchange(anchor, delta, sync_state, m, mask):
                d_avg, sync_state = ex_core_m(delta, sync_state, mask)
                anchor, m = outer.apply(anchor, d_avg, m)
                return anchor, _mask_reset(delta, mask), sync_state, m
        elif use_outer:
            @partial(jax.jit, donate_argnums=compat.donate(0, 1, 2, 3))
            def exchange(anchor, delta, sync_state, m):
                d_avg, sync_state = ex_core(delta, sync_state)
                anchor, m = outer.apply(anchor, d_avg, m)
                return (anchor, jax.tree.map(jnp.zeros_like, delta),
                        sync_state, m)
        elif per_slice:
            @partial(jax.jit, donate_argnums=compat.donate(0, 1, 2))
            def exchange(anchor, delta, sync_state, mask):
                d_avg, sync_state = ex_core_m(delta, sync_state, mask)
                anchor = jax.tree.map(jnp.add, anchor, d_avg)
                return anchor, _mask_reset(delta, mask), sync_state
        else:
            @partial(jax.jit, donate_argnums=compat.donate(0, 1, 2))
            def exchange(anchor, delta, sync_state):
                d_avg, sync_state = ex_core(delta, sync_state)
                anchor = jax.tree.map(jnp.add, anchor, d_avg)
                return (anchor, jax.tree.map(jnp.zeros_like, delta),
                        sync_state)

        @partial(jax.jit, donate_argnums=compat.donate(1))
        def launch(delta, sync_state):
            d_avg, sync_state = ex_core(delta, sync_state)
            # delta passes through UNDONATED: the output is the
            # snapshot copy `apply` subtracts S steps later (the live
            # delta keeps evolving — and gets donated — in between)
            return d_avg, delta, sync_state
    else:
        if use_outer and per_slice:
            @partial(jax.jit, donate_argnums=compat.donate(0, 1, 2))
            def exchange(anchor, delta, m, mask):
                d_avg = ex_core_m(delta, mask)
                anchor, m = outer.apply(anchor, d_avg, m)
                return anchor, _mask_reset(delta, mask), m
        elif use_outer:
            @partial(jax.jit, donate_argnums=compat.donate(0, 1, 2))
            def exchange(anchor, delta, m):
                d_avg = ex_core(delta)
                anchor, m = outer.apply(anchor, d_avg, m)
                return anchor, jax.tree.map(jnp.zeros_like, delta), m
        elif per_slice:
            @partial(jax.jit, donate_argnums=compat.donate(0, 1))
            def exchange(anchor, delta, mask):
                d_avg = ex_core_m(delta, mask)
                anchor = jax.tree.map(jnp.add, anchor, d_avg)
                return anchor, _mask_reset(delta, mask)
        else:
            @partial(jax.jit, donate_argnums=compat.donate(0, 1))
            def exchange(anchor, delta):
                d_avg = ex_core(delta)
                anchor = jax.tree.map(jnp.add, anchor, d_avg)
                return anchor, jax.tree.map(jnp.zeros_like, delta)

        @jax.jit
        def launch(delta):
            return ex_core(delta), delta

    if use_outer:
        # staleness-deferred apply with the outer step: the momentum
        # update happens where the mean delta actually lands
        @partial(jax.jit, donate_argnums=compat.donate(0, 1, 2, 3, 4))
        def apply_pending(anchor, delta, d_avg, snap, m):
            anchor, m = outer.apply(anchor, d_avg, m)
            delta = jax.tree.map(jnp.subtract, delta, snap)
            return anchor, delta, m
    else:
        @partial(jax.jit, donate_argnums=compat.donate(0, 1, 2, 3))
        def apply_pending(anchor, delta, d_avg, snap):
            anchor = jax.tree.map(jnp.add, anchor, d_avg)
            delta = jax.tree.map(jnp.subtract, delta, snap)
            return anchor, delta

    return local_step, exchange, launch, apply_pending


def make_lm_train_step(cfg: LMTrainConfig, mesh: Mesh):
    """Compiled step: (params, opt_state, tokens, targets[, step_no]) ->
    (params, opt_state, loss, ok, met).  tokens/targets are
    (global_batch, global_seq) int32, sharded (data+expert, seq).
    ``ok`` is the per-step health flag (1.0 = loss and synced grads
    finite — one sum-of-squares pass, the training sentry's in-scan
    detection signal); ``met`` is the (2,) [grad-norm, param-norm]
    telemetry vector (``_step_metrics``); ``step_no`` (default 0) only
    matters to the chaos-harness taps, which trace to nothing without
    an installed FaultPlan.
    With ``cfg.grad_accum = A > 1``
    the batch is split into A microbatches scanned with gradient
    accumulation and ONE optimizer update — peak activation memory drops
    by ~A at the cost of A sequential forward/backward passes.  The CE
    gradient is EXACT (grads normalize by the full batch's token count, so
    microbatch mask imbalance reweights nothing); the MoE aux term is a
    per-routing-group statistic and shifts with the group split, as with
    any dp/tp regrouping."""
    tx = make_optimizer(cfg)
    grad_step = _make_grad_step(cfg, mesh)
    a = cfg.grad_accum
    if a < 1:
        raise ValueError(f"grad_accum must be >= 1, got {a}")
    # factored multislice mesh: accumulate LOCAL grads and sync once
    # (one shard-sized DCN exchange per optimizer step, not A)
    accum_step = (_make_accum_grad_step(cfg, mesh)
                  if a > 1 and cfg.dcn_size > 1 else None)
    coef = jnp.float32(cfg.aux_coef)
    compress = cfg.dcn_compress is not None and cfg.dcn_size > 1

    def _micro_split(tokens, targets):
        b = tokens.shape[0]
        if b % (a * cfg.dp * cfg.ep):
            raise ValueError(
                f"global batch {b} not divisible into grad_accum={a} "
                f"microbatches of dp*ep={cfg.dp * cfg.ep}-divisible "
                f"size")
        mb = b // a
        # INTERLEAVED split (microbatch j = rows j, j+a, j+2a, ...):
        # every device's contiguous (data, expert) block contributes
        # equally to every microbatch, so the scan's shard_map slices
        # are resharding-free (a contiguous split would all-to-all the
        # batch every iteration)
        return (tokens.reshape(mb, a, -1).swapaxes(0, 1),
                targets.reshape(mb, a, -1).swapaxes(0, 1))

    def _finish(params, opt_state, loss, grads, step_no, fault_arm):
        # chaos taps (trace-time no-ops unplanned) + sentry health flag
        grads = faults.tap_grads(grads, step_no, fault_arm)
        loss = faults.tap_loss(loss, step_no, fault_arm)
        gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                  for g in jax.tree.leaves(grads))
        ok = (jnp.isfinite(loss) & jnp.isfinite(gsq)).astype(jnp.float32)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # round-13 telemetry scalars riding the health-flag channel:
        # grad global-norm (gsq already computed for `ok`) + post-update
        # param global-norm — always emitted, so telemetry on/off never
        # changes the compiled program
        met = _step_metrics(gsq, params)
        return params, opt_state, loss, ok, met

    if compress:
        # stateful signature (round 11): the per-device EF residual is a
        # donated carry next to params/opt-state
        @partial(jax.jit, donate_argnums=compat.donate(0, 1, 2))
        def step_st(params, opt_state, sync_state, tokens, targets,
                    step_no=0, fault_arm=0.0):
            tokens = _zigzag_global(cfg, tokens)
            targets = _zigzag_global(cfg, targets)
            n_total = jnp.sum(targets != IGNORE).astype(jnp.float32)
            if a == 1:
                loss, grads, sync_state = grad_step(
                    params, sync_state, tokens, targets, n_total, coef)
            else:
                micro_t, micro_y = _micro_split(tokens, targets)
                loss, grads, sync_state = accum_step(
                    params, sync_state, micro_t, micro_y, n_total,
                    coef / a)
            params, opt_state, loss, ok, met = _finish(
                params, opt_state, loss, grads, step_no, fault_arm)
            return params, opt_state, sync_state, loss, ok, met

        return step_st

    @partial(jax.jit, donate_argnums=compat.donate(0, 1))
    def step(params, opt_state, tokens, targets, step_no=0,
             fault_arm=0.0):
        tokens = _zigzag_global(cfg, tokens)
        targets = _zigzag_global(cfg, targets)
        n_total = jnp.sum(targets != IGNORE).astype(jnp.float32)
        if a == 1:
            loss, grads = grad_step(params, tokens, targets, n_total, coef)
        else:
            micro_t, micro_y = _micro_split(tokens, targets)

            if accum_step is not None:
                loss, grads = accum_step(params, micro_t, micro_y,
                                         n_total, coef / a)
            else:
                def body(carry, batch):
                    loss_acc, grads_acc = carry
                    loss_i, g_i = grad_step(params, *batch, n_total,
                                            coef / a)
                    return (loss_acc + loss_i,
                            jax.tree.map(jnp.add, grads_acc, g_i)), None

                zeros = jax.tree.map(jnp.zeros_like, params)
                (loss, grads), _ = jax.lax.scan(
                    body, (jnp.float32(0), zeros), (micro_t, micro_y))
        params, opt_state, loss, ok, met = _finish(
            params, opt_state, loss, grads, step_no, fault_arm)
        return params, opt_state, loss, ok, met

    return step


def make_lm_pp_train_step(cfg: LMTrainConfig, mesh: Mesh):
    """Pipeline-parallel step over Mesh((data, pipe, seq, model)):
    tokens/targets arrive (global_batch, S) sharded (data, seq); each
    data-rank cuts its local batch into microbatches and drives the wave
    schedule (parallel/pipeline.py).  With sp > 1 each stage's layer chunks
    run ring attention over the 'seq' axis — long-context pipeline
    training (pp x sp), composing further with tp."""
    from .parallel import pipeline as pp

    tx = make_optimizer(cfg)
    dtype = cfg.dtype
    n_micro = cfg.microbatches or 2 * cfg.pp

    tp_axis = MODEL if cfg.tp > 1 else None
    seq_axis = SEQ if cfg.sp > 1 else None

    def local_loss(stage_params, shared, tokens, targets):
        b_local = tokens.shape[0]
        if b_local % n_micro:
            raise ValueError(
                f"local batch {b_local} not divisible into {n_micro} "
                f"microbatches")
        mb = b_local // n_micro
        tokens = tokens.reshape(n_micro, mb, -1)
        targets = targets.reshape(n_micro, mb, -1)
        pos = _shard_positions(cfg, tokens.shape[-1])
        ce_sum, n, aux = pp.pipeline_loss(
            stage_params, shared, tokens, targets,
            cfg=cfg.model, axis=PIPE, dtype=dtype,
            tp_axis=tp_axis, seq_axis=seq_axis,
            seq_layout=cfg.seq_layout, pos=pos,
            interleave=cfg.interleave,
            remat_block_ticks=cfg.pp_remat_block,
            loss_impl=cfg.loss_impl, loss_chunk=cfg.loss_chunk)
        ce_sum = jax.lax.psum(ce_sum, (DATA, PIPE, SEQ))
        n = jax.lax.psum(n, (DATA, PIPE, SEQ))
        # aux: layers are SPLIT across 'pipe' (sum) and each rank's
        # accumulator spans all microbatches (mean); data/seq shards each
        # computed their own routing (mean) — mirrors the dense path's
        # sum-over-layers + pmean-over-(data, seq).
        aux = jax.lax.psum(aux, PIPE) / n_micro
        aux = jax.lax.pmean(aux, (DATA, SEQ))
        return ce_sum / jnp.maximum(n, 1) + cfg.aux_coef * aux

    stage_specs = pp_stage_specs(cfg)
    shared_specs = {"embed": P(), "final_norm": P()}

    grad_step = shard_map(
        jax.value_and_grad(local_loss, argnums=(0, 1)),
        mesh=mesh,
        in_specs=(stage_specs, shared_specs, P(DATA, SEQ), P(DATA, SEQ)),
        out_specs=(P(), (stage_specs, shared_specs)),
    )

    @partial(jax.jit, donate_argnums=compat.donate(0, 1))
    def step(params, opt_state, tokens, targets, step_no=0,
             fault_arm=0.0):
        tokens = _zigzag_global(cfg, tokens)
        targets = _zigzag_global(cfg, targets)
        loss, grads = grad_step(params["stages"], params["shared"],
                                tokens, targets)
        grads = {"stages": grads[0], "shared": grads[1]}
        grads = faults.tap_grads(grads, step_no, fault_arm)
        loss = faults.tap_loss(loss, step_no, fault_arm)
        gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                  for g in jax.tree.leaves(grads))
        ok = (jnp.isfinite(loss) & jnp.isfinite(gsq)).astype(jnp.float32)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        met = _step_metrics(gsq, params)
        return params, opt_state, loss, ok, met

    return step


def _stack_layers(params: PyTree, n_layers: int) -> PyTree:
    """Per-layer param subtrees -> one (L, ...)-stacked tree (pure data
    movement, inside the step; the trainer keeps the DENSE layout)."""
    layers = [params[f"layer{i}"] for i in range(n_layers)]
    return jax.tree.map(lambda *ls: jnp.stack(ls), *layers)


def _gather_stacked(stacked: PyTree, layer_spec: PyTree) -> PyTree:
    """all_gather the fsdp ('data') dim of stacked layer leaves — the
    per-layer spec's sharded dim shifted right past the layer-stack axis."""
    def gather(p, spec):
        for dim, ax in enumerate(spec):
            if ax == DATA:
                return jax.lax.all_gather(p, DATA, axis=dim + 1, tiled=True)
        return p

    return jax.tree.map(gather, stacked, layer_spec)


def _pp_grad_sync(g: PyTree, specs: PyTree, cfg: LMTrainConfig) -> PyTree:
    """The EXPLICIT gradient sync of the 1F1B path (its backward is
    hand-emitted, so nothing is synthesized by shard_map's transpose):
    fsdp leaves reduce-scatter over 'data' (the transpose of their
    forward all_gather, written out), then either the factored-mesh
    two-level (data, dcn) reduction (``_two_level_sync``, streamed per
    ~25 MB bucket) or — on flat meshes — one flat psum per leaf over
    every axis the leaf is invariant to.  Identical per-element sums to
    the autodiff-era sync; emission point is the caller's (whole-tree
    post-backward, or per-chunk under overlap)."""
    def scatter(leaf, spec):
        for dim, ax in enumerate(spec):
            if ax == DATA:
                return jax.lax.psum_scatter(leaf, DATA,
                                            scatter_dimension=dim,
                                            tiled=True)
        return leaf

    g = jax.tree.map(scatter, g, specs)
    if cfg.dcn_size > 1:
        return _two_level_sync(g, specs,
                               bucket_bytes=_sync_bucket_bytes(cfg))

    def flat(leaf, spec):
        axes = _spec_axes(spec)
        rest = tuple(a for a in (DATA, EXPERT, SEQ, MODEL)
                     if a not in axes)
        return jax.lax.psum(leaf, rest) if rest else leaf

    return jax.tree.map(flat, g, specs)


def make_lm_1f1b_train_step(cfg: LMTrainConfig, mesh: Mesh):
    """Interleaved-1F1B pipeline train step (round 10): same signature as
    ``make_lm_train_step``, params in the DENSE per-layer layout.

    The transformer's layer groups are partitioned into ``pp_size *
    interleave`` contiguous chunks — cut on the same layer-group
    boundaries the streaming ZeRO-3 gathers and DCN sync points use —
    chunk j on stage j % pp_size of a dedicated 'pp' mesh axis.  The step EMITS one forward or backward unit per
    (chunk, microbatch) in the order of the one-forward-one-backward
    timetable (``one_f_one_b_schedule``; M = microbatches * grad_accum),
    with stage-boundary activations and cotangents moving as ``ppermute``
    transfers over 'pp' and bounded rolling stashes carrying in-flight
    state (``stash_plan`` — O(pp) deep, the 1F1B activation bound).

    The backward is explicit — one ``jax.vjp`` per unit, seeded with the
    stashed output cotangent (+ the CE seed on the last chunk) — and so
    is every gradient reduction (``_pp_grad_sync``).  Consequences, both
    test-pinned:

    - the 1F1B reordering is a pure reassociation of the same microbatch
      grads (per chunk, backwards run in ascending microbatch order;
      the tied embedding's lookup- and head-path cotangents accumulate
      in SEPARATE accumulators summed once at the end, so the
      association is pp_size-independent): pp_size=N trains
      bitwise-identically to pp_size=1;
    - no collective is synthesized by autodiff, so the path runs
      bit-correct even on legacy runtimes without vma cotangent psums
      (utils/compat.py) — unlike the wave scheduler.

    ``overlap=True`` unrolls the clock loop and streams: each chunk's
    ZeRO-3 gathers are emitted at its F/B clocks and its gradient sync
    (psum('pp') + ``_pp_grad_sync``) right after its LAST backward unit —
    interleaved with the other chunks' remaining backward matmuls.
    ``overlap=False`` scans one uniform clock body (compile-cheap) with
    the whole-tree gather up-front and the whole-tree sync post-backward.
    Bitwise-identical either way at pp_size >= 2 (same elementwise sums,
    moved — test-pinned); at pp_size=1 the unrolled clocks constant-fold
    their schedule-table masks where the scanned body keeps them dynamic,
    and the refused fusions reassociate f32 reductions sub-ulp (~1e-13
    grads — the pp1+overlap corner pins allclose, not bitwise).

    Cost model (SPMD, be honest about it): every rank traces ONE uniform
    clock body that executes one forward unit AND one backward unit per
    clock, masking the unscheduled one — the timetable gives each stage
    at most one unit per clock, so the emitted program runs ~2x the
    scheduled FLOPs in steady state and fill/drain clocks burn full
    masked units.  This is the price of a single-program formulation:
    the per-(stage, clock) kind is ``axis_index('pp')``-dependent, and
    SPMD control flow cannot skip per-rank (a varying-predicate cond
    executes both sides), while masking is exactly what makes the step
    one program, bitwise-provable on a CPU mesh, and legacy-runtime
    safe.  The bubble fraction the inspector reports therefore measures
    the TIMETABLE (the thing a per-stage-program MPMD runtime would
    execute), not this step's executed idle time; the bench A/B
    (bench.py bench_train_pp) compares pp_size=N against pp_size=1
    through this same builder, so both legs pay the same masking tax
    and the ratio isolates the schedule.  Real-hardware deployment at
    HBM-limit scale wants per-stage programs — BASELINE.md round-10
    records this as the standing limitation.

    Bitwise caveat (both schedulers' pins respect it): chunks must hold
    >= 2 layers.  XLA unrolls a trip-count-1 layer scan and re-fuses it
    with its neighbours sub-ulp differently (see the opt_barrier note in
    parallel/pipeline.py _chunk); 1-layer chunks train correctly but
    match pp_size=1 only to reassociation noise.  sp > 1 (ring
    attention) composes the same way: losses bitwise-equal, grads to
    reassociation noise only — the ring's own in-scan ppermute/matmul
    residuals re-fuse with the chunk body beyond what the barrier pins.
    """
    from .parallel import pipeline as pp_mod

    model = cfg.model
    n = cfg.pp_size
    v = cfg.interleave
    n_chunks = n * v
    m_micro = (cfg.microbatches or 2 * n) * cfg.grad_accum
    per = model.n_layers // n_chunks
    clocks = pp_mod.one_f_one_b_schedule(m_micro, n, v)
    tabs = pp_mod.schedule_tables(clocks, n, m_micro, v)
    x_depth, c_depth = pp_mod.stash_plan(clocks, n, m_micro, v)
    t_total = len(clocks)
    # last backward clock per chunk: where overlap streams its sync
    last_b = {}
    for t, clock in enumerate(clocks):
        for s, (kind, c, m) in clock.items():
            if kind == "B":
                last_b[c] = t
    finishing_at: dict[int, list[int]] = {}
    for c, t in last_b.items():
        finishing_at.setdefault(t, []).append(c)

    specs = param_specs(cfg)
    lspec = specs["layer0"]
    shared_specs = {"embed": specs["embed"],
                    "final_norm": specs["final_norm"]}
    fsdp = cfg.fsdp
    dtype = cfg.dtype
    tp_axis = MODEL
    seq_axis = SEQ if cfg.sp > 1 else None
    is_moe = bool(model.n_experts)
    batch_axes = _batch_axes(cfg)
    # aux cotangent seed: d(aux_coef * pmean(aux_sum/M, batch+seq))/d unit
    r_mean = int(np.prod([mesh.shape[a] for a in batch_axes + (SEQ,)]))
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    rev_perm = [(i, (i - 1) % n) for i in range(n)]

    tx = make_optimizer(cfg)

    def local_grad(params, micro_t, micro_y, n_total, aux_w):
        me = jax.lax.axis_index(PP)
        mb_loc, s_loc = micro_t.shape[1], micro_t.shape[2]
        d = model.d_model
        cdtype = dtype or jnp.float32
        pos = _shard_positions(cfg, s_loc)

        shared = {"embed": params["embed"],
                  "final_norm": params["final_norm"]}
        if fsdp:
            # the two shared leaves gather once (they are consumed at
            # both ends of every schedule, not per chunk)
            shared = _fsdp_gather(shared, shared_specs)
        emb, fnorm = shared["embed"], shared["final_norm"]
        stacked = _stack_layers(params, model.n_layers)
        if fsdp and not cfg.overlap:
            stacked = _gather_stacked(stacked, lspec)

        def slice_chunk(chunk):
            sl = jax.tree.map(
                lambda x: jax.lax.dynamic_slice_in_dim(x, chunk * per,
                                                       per, axis=0),
                stacked)
            if fsdp and cfg.overlap:
                # streamed ZeRO-3: gather THIS unit's chunk at its clock
                sl = _gather_stacked(sl, lspec)
            return sl

        ce_seed = 1.0 / jnp.maximum(n_total, 1)
        aux_seed = aux_w / jnp.float32(r_mean)

        def unit(chunk_layers, emb_in, emb_out, fn_, x_in, toks, tgts,
                 is_first, is_last):
            """The uniform (chunk, microbatch) body every rank traces:
            embed-or-receive, the chunk's layer scan, and the (masked)
            unembed head — first/last-chunk special-casing as masks, so
            F and B units stay one traced program under SPMD.  The tied
            embedding enters as TWO arguments so its lookup-path and
            head-path cotangents come back separately (the
            pp_size-independent accumulation the bitwise pin needs)."""
            xe = emb_in[toks]
            if dtype is not None:
                xe = xe.astype(dtype)
            x0 = jnp.where(is_first, xe, x_in)
            y, aux = pp_mod._chunk(
                chunk_layers, x0, cfg=model, attn_impl="flash",
                tp_axis=tp_axis, seq_axis=seq_axis,
                seq_layout=cfg.seq_layout, pos=pos, is_moe=is_moe)
            h = tfm.rms_norm(y, fn_, model.norm_eps)
            # the unified head-loss seam (ops/losses.py): dense traces the
            # historical logits matmul + masked_ce bit-for-bit; the 1F1B
            # head keeps the full vocab per rank (no tp vocab sharding)
            ce, _ = losses.head_loss(h, emb_out, tgts,
                                     loss_impl=cfg.loss_impl,
                                     loss_chunk=cfg.loss_chunk)
            return y, jnp.where(is_last, ce, 0.0), aux

        def at2(buf, i, j):
            row = jax.lax.dynamic_index_in_dim(buf, i, 0, keepdims=False)
            return jax.lax.dynamic_index_in_dim(row, j, 0, keepdims=False)

        def put2(buf, i, j, val, valid):
            cur = at2(buf, i, j)
            val = jnp.where(valid, val, cur)
            row = jax.lax.dynamic_index_in_dim(buf, i, 0, keepdims=False)
            row = jax.lax.dynamic_update_index_in_dim(row, val, j, 0)
            return jax.lax.dynamic_update_index_in_dim(buf, row, i, 0)

        def clock_body(carry, row):
            x_st, c_st, acc_l, acc_ei, acc_eo, acc_fn, ce_acc, aux_acc = \
                carry
            r = {k: row[k][me] for k in row}
            # -- forward unit (masked when this stage has none) ----------
            f_chunk = r["f_k"] * n + me
            toks_f = jax.lax.dynamic_index_in_dim(micro_t, r["f_m"], 0,
                                                  keepdims=False)
            tgts_f = jax.lax.dynamic_index_in_dim(micro_y, r["f_m"], 0,
                                                  keepdims=False)
            x_in_f = at2(x_st, r["f_k"], r["f_m"] % x_depth)
            y_f, ce_f, aux_f = unit(
                slice_chunk(f_chunk), emb, emb, fnorm, x_in_f,
                toks_f, tgts_f, f_chunk == 0, f_chunk == n_chunks - 1)
            fv = r["f_valid"].astype(jnp.float32)
            ce_acc = ce_acc + ce_f * fv
            aux_acc = aux_acc + aux_f * fv
            # -- backward unit: explicit vjp, timetable-seeded -----------
            b_chunk = r["b_k"] * n + me
            toks_b = jax.lax.dynamic_index_in_dim(micro_t, r["b_m"], 0,
                                                  keepdims=False)
            tgts_b = jax.lax.dynamic_index_in_dim(micro_y, r["b_m"], 0,
                                                  keepdims=False)
            x_in_b = at2(x_st, r["b_k"], r["b_m"] % x_depth)
            b_first = b_chunk == 0
            b_last = b_chunk == n_chunks - 1
            cot_y = at2(c_st, r["b_k"], r["b_m"] % c_depth)
            cot_y = jnp.where(b_last, jnp.zeros_like(cot_y), cot_y)
            _, vjp_fn = jax.vjp(
                lambda cl, ei, eo, fn_, xi: unit(
                    cl, ei, eo, fn_, xi, toks_b, tgts_b, b_first, b_last),
                slice_chunk(b_chunk), emb, emb, fnorm, x_in_b)
            g_cl, g_ei, g_eo, g_fn, g_xi = vjp_fn(
                (cot_y, ce_seed, aux_seed))
            bv = r["b_valid"] != 0
            off = b_chunk * per
            acc_l = jax.tree.map(
                lambda a, g: jax.lax.dynamic_update_slice_in_dim(
                    a, jax.lax.dynamic_slice_in_dim(a, off, per, axis=0)
                    + jnp.where(bv, g, jnp.zeros_like(g)), off, axis=0),
                acc_l, g_cl)
            acc_ei = acc_ei + jnp.where(bv, g_ei, jnp.zeros_like(g_ei))
            acc_eo = acc_eo + jnp.where(bv, g_eo, jnp.zeros_like(g_eo))
            acc_fn = acc_fn + jnp.where(bv, g_fn, jnp.zeros_like(g_fn))
            # -- stage-boundary ring hops (the 'pp'-axis transfers) ------
            recv_f = jax.lax.ppermute(y_f, PP, fwd_perm)
            recv_b = jax.lax.ppermute(g_xi, PP, rev_perm)
            x_st = put2(x_st, r["fr_k"], r["fr_m"] % x_depth, recv_f,
                        r["fr_valid"] != 0)
            c_st = put2(c_st, r["br_k"], r["br_m"] % c_depth, recv_b,
                        r["br_valid"] != 0)
            return (x_st, c_st, acc_l, acc_ei, acc_eo, acc_fn, ce_acc,
                    aux_acc)

        # full-size layer-grad accumulator: each rank fills only its own
        # chunks' slots; psum('pp') assembles the rest (zeros elsewhere,
        # so the merge adds exact zeros — bitwise-neutral).  Under
        # fsdp+overlap the stacked closure holds SHARDS (chunks gather at
        # their clocks), so the full accumulator shape is computed, not
        # gathered.
        if fsdp and cfg.overlap:
            n_data = mesh.shape[DATA]

            def full_zeros(x, spec):
                shape = list(x.shape)
                for dim, ax in enumerate(spec):
                    if ax == DATA:
                        shape[dim + 1] *= n_data
                        break
                return jnp.zeros(shape, x.dtype)

            acc_l0 = jax.tree.map(full_zeros, stacked, lspec)
        else:
            acc_l0 = jax.tree.map(jnp.zeros_like, stacked)
        # carries/accumulators mix with pp-varying (and batch-varying)
        # values inside the clock loop: pre-cast them varying so the
        # scan carry vma is stable (no-op on legacy runtimes)
        want_vma = compat.vma_of(
            jnp.zeros((), jnp.float32)) | {PP} | compat.vma_of(micro_t)

        def _varying(x):
            missing = tuple(a for a in want_vma
                            if a not in compat.vma_of(x))
            return compat.pcast(x, missing, to="varying") if missing else x

        ce_seed = _varying(ce_seed)
        aux_seed = _varying(aux_seed)
        carry = jax.tree.map(_varying, (
            jnp.zeros((v, x_depth, mb_loc, s_loc, d), cdtype),
            jnp.zeros((v, c_depth, mb_loc, s_loc, d), cdtype),
            acc_l0,
            jnp.zeros_like(emb), jnp.zeros_like(emb),
            jnp.zeros_like(fnorm),
            jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32),
        ))

        if not cfg.overlap:
            xs = {k: jnp.asarray(a) for k, a in tabs.items()}
            carry, _ = jax.lax.scan(
                lambda c, row: (clock_body(c, row), None), carry, xs)
            x_st, c_st, acc_l, acc_ei, acc_eo, acc_fn, ce_acc, aux_acc = \
                carry
            acc_l = jax.tree.map(lambda g: jax.lax.psum(g, PP), acc_l)
            g_layers = {
                f"layer{i}": jax.tree.map(lambda x, i=i: x[i], acc_l)
                for i in range(model.n_layers)}
        else:
            synced: dict[int, PyTree] = {}
            for t in range(t_total):
                row = {k: jnp.asarray(a[t]) for k, a in tabs.items()}
                # same clock-boundary fusion barrier the scanned path gets
                # from its while-loop body (parallel/pipeline.py _chunk
                # documents the failure mode): without it XLA fuses
                # ACROSS unrolled clocks and overlap drifts sub-ulp off
                # the scanned schedule when the 'pp' collectives compile
                # away (pp_size=1 — the degenerate-schedule pin)
                carry = jax.lax.optimization_barrier(carry)
                carry = clock_body(carry, row)
                (x_st, c_st, acc_l, acc_ei, acc_eo, acc_fn, ce_acc,
                 aux_acc) = carry
                for c in finishing_at.get(t, ()):
                    # stream chunk c's sync right after its last backward
                    sl = jax.tree.map(lambda x: x[c * per:(c + 1) * per],
                                      acc_l)
                    sl = jax.tree.map(lambda g: jax.lax.psum(g, PP), sl)
                    sub = {f"layer{c * per + i}":
                           jax.tree.map(lambda x, i=i: x[i], sl)
                           for i in range(per)}
                    sub_specs = {k: lspec for k in sub}
                    synced.update(_pp_grad_sync(sub, sub_specs, cfg))
            g_layers = synced
        # tied embedding: lookup- and head-path accumulators merge ONCE,
        # after their 'pp' psums — a pp_size-independent association
        g_emb = jax.lax.psum(acc_ei, PP) + jax.lax.psum(acc_eo, PP)
        g_fn = jax.lax.psum(acc_fn, PP)
        g_shared = _pp_grad_sync({"embed": g_emb, "final_norm": g_fn},
                                 shared_specs, cfg)
        if not cfg.overlap:
            g_layers = _pp_grad_sync(
                g_layers, {k: lspec for k in g_layers}, cfg)
        grads = dict(g_layers)
        grads["embed"] = g_shared["embed"]
        grads["final_norm"] = g_shared["final_norm"]
        ce_tot = jax.lax.psum(ce_acc, batch_axes + (SEQ, PP))
        # aux_w arrives as coef/M (the per-unit weight, same convention
        # as the grad_accum path), so the reported aux term is
        # coef * mean-over-units — matching make_lm_train_step's loss
        # and the aux_seed the backward units were seeded with
        aux_tot = jax.lax.psum(aux_acc, (PP,))
        aux_tot = jax.lax.pmean(aux_tot, batch_axes + (SEQ,))
        loss = ce_tot / jnp.maximum(n_total, 1) + aux_w * aux_tot
        return loss, grads

    bspec = _lm_batch_spec(cfg)
    mspec = P(None, *bspec)
    grad_step = shard_map(
        local_grad, mesh=mesh,
        in_specs=(specs, mspec, mspec, P(), P()),
        out_specs=(P(), specs))

    coef = jnp.float32(cfg.aux_coef)

    @partial(jax.jit, donate_argnums=compat.donate(0, 1))
    def step(params, opt_state, tokens, targets, step_no=0,
             fault_arm=0.0):
        tokens = _zigzag_global(cfg, tokens)
        targets = _zigzag_global(cfg, targets)
        n_total = jnp.sum(targets != IGNORE).astype(jnp.float32)
        b = tokens.shape[0]
        if b % (m_micro * cfg.dp * cfg.ep):
            raise ValueError(
                f"global batch {b} not divisible into {m_micro} "
                f"microbatches (pp_size={n} x microbatches="
                f"{cfg.microbatches or 2 * n} x grad_accum="
                f"{cfg.grad_accum}) of dp*ep={cfg.dp * cfg.ep}-divisible "
                f"size")
        mb = b // m_micro
        # INTERLEAVED split, exactly the grad_accum path's (microbatch j
        # = rows j, j+M, j+2M, ...): resharding-free, and the microbatch
        # contents match the pp_size=1 baseline row for row
        micro_t = tokens.reshape(mb, m_micro, -1).swapaxes(0, 1)
        micro_y = targets.reshape(mb, m_micro, -1).swapaxes(0, 1)
        loss, grads = grad_step(params, micro_t, micro_y, n_total,
                                coef / m_micro)
        grads = faults.tap_grads(grads, step_no, fault_arm)
        loss = faults.tap_loss(loss, step_no, fault_arm)
        gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                  for g in jax.tree.leaves(grads))
        ok = (jnp.isfinite(loss) & jnp.isfinite(gsq)).astype(jnp.float32)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        met = _step_metrics(gsq, params)
        return params, opt_state, loss, ok, met

    # surface the timetable for the schedule inspector / bench: the
    # emitted order IS this data (utils/debug.assert_pipeline_schedule)
    step.pp_clocks = clocks
    step.pp_meta = {"n_stages": n, "n_micro": m_micro, "interleave": v,
                    "x_depth": x_depth, "cot_depth": c_depth}
    return step


def make_lm_eval_step(cfg: LMTrainConfig, mesh: Mesh):
    """Forward-only masked-CE: (params, tokens, targets) -> (ce_sum, count),
    globally reduced.  Works for the (data, seq, model) mesh; the pp layout
    evaluates through pipeline_loss the same way."""
    dtype = cfg.dtype
    specs = param_specs(cfg)

    def local_eval(params, tokens, targets):
        if cfg.fsdp:
            # same gather dtype as training: eval sees the weights the
            # train forward saw (quantized when fsdp_gather_dtype is on)
            params = _fsdp_gather(params, specs, cfg.fsdp_gather_dtype)
        pos = _shard_positions(cfg, tokens.shape[1])
        # same head-loss seam as training (ops/losses.py head_loss):
        # dense is the historical graph bit-for-bit; no remat — there is
        # no backward to hold activations for
        head = partial(losses.head_loss, targets=targets,
                       loss_impl=cfg.loss_impl, loss_chunk=cfg.loss_chunk,
                       tp_axis=MODEL if cfg.tp > 1 else None,
                       tp_size=cfg.tp)
        ce, n = tfm.apply(params, tokens, cfg=cfg.model, dtype=dtype,
                          seq_axis=SEQ if cfg.sp > 1 else None,
                          seq_layout=cfg.seq_layout, tp_axis=MODEL,
                          ep_axis=EXPERT if cfg.ep > 1 else None, pos=pos,
                          matmul_dtype=cfg.matmul_dtype, head_fn=head)
        axes = _batch_axes(cfg) + (SEQ,)
        return (jax.lax.psum(ce, axes), jax.lax.psum(n, axes))

    bspec = _lm_batch_spec(cfg)
    sharded_eval = shard_map(
        local_eval, mesh=mesh,
        in_specs=(specs, bspec, bspec),
        out_specs=(P(), P()))

    @jax.jit
    def eval_step(params, tokens, targets):
        return sharded_eval(params, _zigzag_global(cfg, tokens),
                            _zigzag_global(cfg, targets))

    return eval_step


def make_lm_multi_step(cfg: LMTrainConfig, mesh: Mesh):
    """Compiled K-step training loop for the (data, expert, seq, model)
    layout: ``(params, opt_state, tokens, targets) -> (params, opt_state,
    losses, oks, mets)`` with tokens/targets carrying a leading scan axis
    of length K — ONE dispatch executes K optimizer steps (``oks``:
    per-step health flags, ``mets``: (K, 2) per-step [grad-norm,
    param-norm], as in ``make_lm_train_step``).  Shares
    ``_make_grad_step`` with the single-step path, so loss semantics
    cannot drift; see LMTrainer.train_steps for when the scan actually
    helps (measured)."""
    if cfg.dcn_compress is not None:
        raise ValueError("make_lm_multi_step does not thread the "
                         "stateful sync-state (EF residual) carry; with "
                         "dcn_compress use make_lm_train_step")
    tx = make_optimizer(cfg)
    grad_step = _make_grad_step(cfg, mesh)

    @partial(jax.jit, donate_argnums=compat.donate(0, 1))
    def steps(params, opt_state, tokens, targets):
        tokens = jax.vmap(partial(_zigzag_global, cfg))(tokens)
        targets = jax.vmap(partial(_zigzag_global, cfg))(targets)

        def body(carry, batch):
            params, opt_state = carry
            tk, tg = batch
            n_total = jnp.sum(tg != IGNORE).astype(jnp.float32)
            loss, grads = grad_step(params, tk, tg, n_total,
                                    jnp.float32(cfg.aux_coef))
            gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                      for g in jax.tree.leaves(grads))
            ok = (jnp.isfinite(loss) & jnp.isfinite(gsq)).astype(
                jnp.float32)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            met = _step_metrics(gsq, params)
            return (params, opt_state), (loss, ok, met)

        (params, opt_state), (losses, oks, mets) = jax.lax.scan(
            body, (params, opt_state), (tokens, targets))
        return params, opt_state, losses, oks, mets

    return steps


def make_lm_pp_eval_step(cfg: LMTrainConfig, mesh: Mesh):
    """Forward-only masked-CE through the pipeline (no grad, no merge):
    (params, tokens, targets) -> (ce_sum, count), globally reduced.

    The reference evaluates after every training epoch
    (/root/reference/main.py:51-66, called at main.py:108); a pp-trained
    model must run that eval loop without leaving the pipeline layout, so
    this drives the same wave schedule as the pp train step, skipping
    autodiff (and its remat blocks — ``remat_block_ticks=None`` keeps the
    cheap flat scan, since there is no backward to hold activations for).
    """
    from .parallel import pipeline as pp

    dtype = cfg.dtype
    n_micro = cfg.microbatches or 2 * cfg.pp
    tp_axis = MODEL if cfg.tp > 1 else None
    seq_axis = SEQ if cfg.sp > 1 else None

    def local_eval(stage_params, shared, tokens, targets):
        b_local = tokens.shape[0]
        if b_local % n_micro:
            raise ValueError(
                f"eval batch (local {b_local}) not divisible into "
                f"{n_micro} microbatches")
        mb = b_local // n_micro
        tokens = tokens.reshape(n_micro, mb, -1)
        targets = targets.reshape(n_micro, mb, -1)
        pos = _shard_positions(cfg, tokens.shape[-1])
        ce_sum, n, _aux = pp.pipeline_loss(
            stage_params, shared, tokens, targets,
            cfg=cfg.model, axis=PIPE, dtype=dtype,
            tp_axis=tp_axis, seq_axis=seq_axis,
            seq_layout=cfg.seq_layout, pos=pos,
            interleave=cfg.interleave,
            remat_block_ticks=None,
            loss_impl=cfg.loss_impl, loss_chunk=cfg.loss_chunk)
        return (jax.lax.psum(ce_sum, (DATA, PIPE, SEQ)),
                jax.lax.psum(n, (DATA, PIPE, SEQ)))

    stage_specs = pp_stage_specs(cfg)
    shared_specs = {"embed": P(), "final_norm": P()}
    sharded_eval = shard_map(
        local_eval, mesh=mesh,
        in_specs=(stage_specs, shared_specs, P(DATA, SEQ), P(DATA, SEQ)),
        out_specs=(P(), P()))

    @jax.jit
    def eval_step(params, tokens, targets):
        return sharded_eval(params["stages"], params["shared"],
                            _zigzag_global(cfg, tokens),
                            _zigzag_global(cfg, targets))

    return eval_step


class LMTrainer:
    """Owns (params, opt_state) laid out over the (data, seq, model) mesh —
    the (data, pipe, seq, model) mesh when cfg.pp > 1 (the wave
    scheduler's stage-stacked layout) — or the ('pp', data, ...) mesh
    when cfg.pp_size > 0 (interleaved-1F1B; params keep the DENSE
    per-layer layout, pp-replicated, so checkpoints/eval/param_specs are
    layout-identical to the non-pp trainer)."""

    def __init__(self, cfg: LMTrainConfig, mesh: Mesh | None = None):
        # sync_plan="auto" (round 11): resolve FIRST into explicit
        # dcn_compress/bucket_mb knobs (parallel/autotune.py), so
        # everything below runs the exact explicit-config path — auto
        # under a forced profile is bitwise-identical to the config it
        # resolves to (test-pinned).  The explainable plan is kept on
        # the trainer.
        self.sync_plan = None
        # sync_route (round 21): the hand-pinned routed surface resolves
        # through the SAME mechanism — parse, refuse what the LM sync
        # machinery cannot execute, translate the dcn hop's wire format
        # into dcn_compress — so a routed config trains
        # bitwise-identically to the explicit config it names.
        self.sync_route_plan = None
        if cfg.sync_route is not None:
            from .parallel import autotune
            cfg, self.sync_route_plan = autotune.resolve_lm_route(cfg)
        if cfg.sync_plan == "auto":
            from .parallel import autotune
            cfg, self.sync_plan = autotune.resolve_lm_auto(cfg)
        self.cfg = cfg
        # validate even with a caller-supplied mesh: an invalid axis
        # composition (e.g. pp x grad_accum) must raise, not be silently
        # ignored by whichever step builder does not read the setting
        validate_lm_cfg(cfg)
        self.mesh = mesh if mesh is not None else make_lm_mesh(cfg)
        want = (cfg.dp * cfg.ep * cfg.sp * cfg.tp * cfg.pp
                * max(cfg.pp_size, 1))
        assert self.mesh.devices.size == want, (
            f"mesh has {self.mesh.devices.size} devices, config wants {want}")
        # batch sharding: (data, expert) jointly split the batch on the
        # non-pp mesh; the pp mesh has no expert axis (ep=1 enforced).
        # The 1F1B mesh keeps the non-pp batch spec — every stage holds
        # the full (data, expert)-sharded batch, pp-replicated (stages
        # consume different microbatch slices of it per clock).
        self._batch_spec = (P(DATA, SEQ) if cfg.pp > 1
                            else _lm_batch_spec(cfg))

        if cfg.fsdp and cfg.pp > 1:
            raise ValueError("fsdp composes with the (data, seq, model) "
                             "mesh, not with pp")
        params = tfm.init(jax.random.key(cfg.seed), cfg.model)
        tx = make_optimizer(cfg)
        if cfg.pp_size > 0:
            # interleaved-1F1B: dense layout over the 'pp' mesh —
            # param_specs carry no 'pp' entry, so every leaf replicates
            # across stages (each stage reads only its own chunks'
            # slices inside the step; ZeRO-3 shards still apply within
            # the stage via the 'data' axis)
            specs = param_specs(cfg)
            params = jax.tree.map(
                lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
                params, specs)
            self._install_step_fns(self._build_step_fn(cfg, self.mesh))
        elif cfg.pp > 1:
            from .parallel import pipeline as pp
            stages, shared = pp.split_layer_params(
                params, cfg.model, cfg.pp, interleave=cfg.interleave)
            stage_specs = pp_stage_specs(cfg)
            params = {
                "stages": jax.tree.map(
                    lambda x, s: jax.device_put(
                        x, NamedSharding(self.mesh, s)),
                    stages, stage_specs),
                "shared": jax.device_put(
                    shared, NamedSharding(self.mesh, P())),
            }
            self._install_step_fns(self._build_step_fn(cfg, self.mesh))
        else:
            specs = param_specs(cfg)
            params = jax.tree.map(
                lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
                params, specs)
            self._install_step_fns(self._build_step_fn(cfg, self.mesh))
        # zeros_like/elementwise init inherits each param's sharding; leaves
        # with no param ancestry (Adam's step count) come out single-device —
        # normalize them to replicated-on-mesh so every training-state leaf
        # lives on the same device set (mixing committed single-device and
        # mesh-wide args in one jit is an error).
        rep = NamedSharding(self.mesh, P())
        self.opt_state = jax.tree.map(
            lambda leaf: (jax.device_put(leaf, rep)
                          if isinstance(leaf, jax.Array)
                          and len(leaf.sharding.device_set) == 1
                          and self.mesh.devices.size > 1 else leaf),
            jax.jit(tx.init)(params))
        self.params = params
        # int8 DCN compression (round 11): the per-device EF residual
        # carried through the stateful step — one row per device,
        # sharded over the full mesh.  NOT checkpointed: dropping it on
        # restart is safe (residuals re-accumulate within one step).
        self.sync_state = None
        if cfg.dcn_compress is not None:
            n_dev = self.mesh.devices.size
            self.sync_state = jax.device_put(
                jnp.zeros((n_dev, lm_sync_state_len(cfg, self.mesh)),
                          jnp.float32),
                NamedSharding(self.mesh, P(tuple(self.mesh.axis_names))))
        # communication-sparse windows (round 18): the per-slice window
        # delta + per-slice optimizer state (leading 'dcn' axis) and the
        # staleness bookkeeping; params stay the replicated ANCHOR
        self._delta = None
        self._pending = None
        self._window_t0 = None
        self._window_wire_bytes = None
        # DiLoCo outer optimizer (round 22): the f32 momentum tree on
        # the anchor (None without outer_opt — the plain-mean boundary)
        # and the boundary-step counter the telemetry gauge reads
        self._outer_m = None
        self._outer_steps = 0
        if cfg.sync_every > 1:
            self._init_window_state()
        self._eval_fn = None
        self._multi_fn = None
        self._step = 0
        self._last_cache_size = None  # compile-lane gauge change-detect
        self.last_ok = None     # health flag(s) of the last dispatch
        # [grad gnorm, param gnorm] of the last dispatch (round-13
        # telemetry scalars; (K, 2) from train_steps), fetched lazily
        self.last_metrics = None
        self._ckptr = None
        self._ckptr_key = None
        self.restored_meta: dict = {}

    def _emit_cache_size(self, tel, fn) -> None:
        """Compile-lane gauge: the dispatched function's jit-cache entry
        count, emitted only when it CHANGES (a growing cache mid-run is
        a shape leak — exactly what the gauge exists to surface)."""
        size_of = getattr(fn, "_cache_size", None)
        if size_of is None:
            return
        try:
            n = size_of()
        except Exception:
            return
        if n != self._last_cache_size:
            self._last_cache_size = n
            tel.gauge("step_fn_cache_size", float(n), phase="compile")

    def _build_step_fn(self, cfg, mesh):
        """Build the compiled train step for ``cfg``/``mesh``, timed on
        the compile lane (round 15): one phase-"compile" span per build,
        keyed by layout + clip so a sentry tighten or elastic rebuild
        shows up as a NEW program in the trace.  Telemetry off: the
        span is a no-op and the build is byte-identical."""
        if cfg.pp_size > 0:
            kind, builder = "1f1b", make_lm_1f1b_train_step
        elif cfg.pp > 1:
            kind, builder = "pp", make_lm_pp_train_step
        elif cfg.sync_every > 1:
            # round 18: the communication-sparse program family (local
            # step + boundary exchange + staleness launch/apply) — the
            # build returns a 4-tuple, unpacked by _install_step_fns
            kind, builder = "localsgd", make_lm_window_steps
        else:
            kind, builder = "spmd", make_lm_train_step
        with monitor.compile_span(
                "lm_step_build",
                key=(kind, cfg.grad_clip, tuple(mesh.shape.items())),
                kind=kind):
            return builder(cfg, mesh)

    def _install_step_fns(self, built) -> None:
        """Install a step-builder result: the windowed family arrives as
        a (local, exchange, launch, apply) tuple — ``step_fn`` is the
        window-LOCAL step (the hot path, what the cache-size gauge and
        the schedule inspector see); the boundary programs live beside
        it."""
        if isinstance(built, tuple):
            (self.step_fn, self._exchange_fn, self._launch_fn,
             self._apply_fn) = built
        else:
            self.step_fn = built
            self._exchange_fn = self._launch_fn = self._apply_fn = None

    def _stack_dcn(self, tree_: PyTree) -> PyTree:
        """Broadcast every array leaf one copy per 'dcn' slice (leading
        axis dcn_size, sharded over 'dcn' ahead of the leaf's own
        spec) — the per-slice optimizer-state layout of the windowed
        local steps."""
        mesh, n = self.mesh, self.cfg.dcn_size

        def f(x):
            if not isinstance(x, jax.Array):
                return x
            spec = (x.sharding.spec
                    if isinstance(x.sharding, NamedSharding) else P())
            return jax.device_put(
                jnp.broadcast_to(x[None], (n,) + x.shape),
                NamedSharding(mesh, P(DCN, *spec)))

        return jax.tree.map(f, tree_)

    def _init_window_state(self) -> None:
        """Round 18 (``sync_every > 1``): stack the optimizer state one
        copy per 'dcn' slice and zero the per-slice window delta.  The
        live ``params`` stay the replicated anchor — the last exchanged
        point, what checkpoints save and ``evaluate`` reads (mid-window
        local progress lives in the delta until the next boundary)."""
        cfg, mesh = self.cfg, self.mesh
        self.opt_state = self._stack_dcn(self.opt_state)
        specs = param_specs(cfg)
        self._delta = jax.tree.map(
            lambda p, s: jax.device_put(
                jnp.zeros((cfg.dcn_size,) + p.shape, p.dtype),
                NamedSharding(mesh, P(DCN, *s))),
            self.params, specs)
        self._pending = None
        self._window_t0 = None
        self._window_wire_bytes = _lm_window_wire_bytes(cfg, mesh)
        self._outer_m = None
        if _lm_outer(cfg) is not None:
            # f32 momentum shadows the anchor leaf-for-leaf (same
            # shardings — it moves with the anchor, never the wire)
            self._outer_m = jax.tree.map(
                lambda p: jax.device_put(
                    jnp.zeros(p.shape, jnp.float32), p.sharding),
                self.params)

    def tighten_grad_clip(self, factor: float = 0.5) -> float:
        """Multiply the gradient-clip norm by ``factor`` and rebuild the
        compiled step — the training sentry's mid-ladder escalation
        (utils/sentry.py: skip window -> tighten clip -> abort).  The
        optimizer chain's clip transform is stateless, so the live
        opt_state carries over unchanged; the recompile is a fault-path
        cost, not a hot-path one.  Returns the new clip norm."""
        self.cfg.grad_clip *= factor
        self._install_step_fns(self._build_step_fn(self.cfg, self.mesh))
        self._multi_fn = None
        return self.cfg.grad_clip

    # -- elastic resize (round 12) ----------------------------------------
    def rebuild(self, mesh: Mesh | None = None, **overrides) -> None:
        """Re-create the compiled step at a NEW parallel degree, carrying
        the live training state across — the in-process half of the
        elastic gang (parallel/elastic.py).  ``overrides`` are
        ``LMTrainConfig`` field replacements (typically ``dp=...`` and
        ``fsdp=...`` after the fleet shrank or grew); the mesh rebuilds
        from the new config unless supplied.  Params and optimizer state
        are resharded onto the new layout (host-fetched owned copies,
        then placed by the new ``param_specs`` — restoring a checkpoint
        through ``load_resharded`` afterwards is the elastic resume
        path, see ``reshard_from_checkpoint``); the sync-state carry
        re-initializes (safe to drop); compiled step/eval functions are
        discarded; the step counter survives.

        Pipeline meshes refuse: pp/pp_size stage placement is baked into
        the hand-emitted step, so a pipelined gang resizes by relaunch,
        not rebuild (the lm_cli --elastic refusal mirrors this).
        Single-controller only — a multi-process gang resizes via the
        elastic agent's drain + re-rendezvous."""
        if jax.process_count() > 1:
            raise ValueError(
                "in-process rebuild is single-controller; multi-process "
                "gangs resize via the elastic agent's drain + "
                "re-rendezvous (launch.py --elastic)")
        import dataclasses
        cfg = (dataclasses.replace(self.cfg, **overrides) if overrides
               else self.cfg)
        if cfg.pp > 1 or cfg.pp_size > 0:
            raise ValueError(
                "cannot resize a pipeline (pp/pp_size) config for now: "
                "stage placement is baked into the hand-emitted step — "
                "relaunch at the new size instead")
        validate_lm_cfg(cfg)
        new_mesh = mesh if mesh is not None else make_lm_mesh(cfg)
        want = cfg.dp * cfg.ep * cfg.sp * cfg.tp
        if new_mesh.devices.size != want:
            raise ValueError(
                f"resized mesh has {new_mesh.devices.size} devices, "
                f"config wants {want}")
        from .utils.checkpoint import _fetch  # owned copies (donation)

        params_host = jax.tree.map(_fetch, self.params)
        opt_host = jax.tree.map(
            lambda x: _fetch(x) if isinstance(x, jax.Array) else x,
            self.opt_state)
        if self.cfg.sync_every > 1:
            # windowed -> any: the per-slice optimizer state collapses
            # to slice 0 (the rebuild drops un-exchanged window deltas
            # and per-slice Adam divergence — up to H-1 local steps of
            # progress, the same carry-drop contract as sync_state; the
            # SLO actuator widens/narrows at window boundaries where
            # the delta is zero anyway)
            opt_host = jax.tree.map(
                lambda x: x[0] if hasattr(x, "ndim") and x.ndim else x,
                opt_host)
        self.cfg = cfg
        self.mesh = new_mesh
        self._batch_spec = _lm_batch_spec(cfg)
        specs = param_specs(cfg)
        self.params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(new_mesh, s)),
            params_host, specs)
        # target opt-state shardings come from re-initializing on the
        # resharded params (exactly the __init__ recipe, including the
        # single-device -> replicated normalization); the live VALUES
        # then re-place onto those shardings leaf by leaf
        tx = make_optimizer(cfg)
        rep = NamedSharding(new_mesh, P())
        target = jax.tree.map(
            lambda leaf: (jax.device_put(leaf, rep)
                          if isinstance(leaf, jax.Array)
                          and len(leaf.sharding.device_set) == 1
                          and new_mesh.devices.size > 1 else leaf),
            jax.jit(tx.init)(self.params))
        self.opt_state = jax.tree.map(
            lambda old, tgt: (jax.device_put(np.asarray(old), tgt.sharding)
                              if isinstance(tgt, jax.Array) else old),
            opt_host, target)
        self._install_step_fns(self._build_step_fn(cfg, new_mesh))
        self.sync_state = None
        if cfg.dcn_compress is not None:
            n_dev = new_mesh.devices.size
            self.sync_state = jax.device_put(
                jnp.zeros((n_dev, lm_sync_state_len(cfg, new_mesh)),
                          jnp.float32),
                NamedSharding(new_mesh, P(tuple(new_mesh.axis_names))))
        self._delta = None
        self._pending = None
        self._window_t0 = None
        self._window_wire_bytes = None
        self._outer_m = None  # fresh momentum after a resize (carry-drop
        # contract, same as sync_state); _init_window_state re-zeros it
        if cfg.sync_every > 1:
            self._init_window_state()
        self._eval_fn = None
        self._multi_fn = None
        self.last_ok = None
        self.last_metrics = None
        # a cached checkpointer keeps working (directory-keyed), but the
        # next restore must re-template against the new shardings — which
        # maybe_restore does by passing the live (resharded) trees

    def evaluate(self, batches) -> dict[str, float]:
        """Held-out loss/perplexity over an iterable of (tokens, targets).

        pp > 1 evaluates through the pipeline forward (the wave schedule,
        no grad) — the train→eval loop of the reference (main.py:108)
        never leaves the pipeline layout."""
        if self._eval_fn is None:
            self._eval_fn = (make_lm_pp_eval_step(self.cfg, self.mesh)
                             if self.cfg.pp > 1
                             else make_lm_eval_step(self.cfg, self.mesh))
        shd = NamedSharding(self.mesh, self._batch_spec)
        total, count = 0.0, 0
        for tokens, targets in batches:
            if jax.process_count() > 1:
                tokens = jax.make_array_from_process_local_data(shd, tokens)
                targets = jax.make_array_from_process_local_data(shd, targets)
            ce, n = self._eval_fn(self.params, tokens, targets)
            total += float(ce)
            count += int(n)
        loss = total / max(count, 1)
        return {"loss": loss, "ppl": float(np.exp(min(loss, 30.0))),
                "tokens": count}


    # -- checkpointing ----------------------------------------------------
    def _checkpointer(self, directory: str, sharded: bool = False):
        """One cached checkpointer per (directory, format): the whole-tree
        async writer's background handle must survive across save calls
        (writes never interleave; the interpreter flushes the last one at
        exit)."""
        from .utils.checkpoint import PyTreeCheckpointer, ShardedCheckpointer
        key = (directory, sharded)
        if self._ckptr_key != key:
            self._ckptr = (ShardedCheckpointer(directory) if sharded
                           else PyTreeCheckpointer(directory,
                                                   async_write=True))
            self._ckptr_key = key
        return self._ckptr

    def flush_checkpoints(self) -> None:
        """Block until any in-flight background checkpoint write has been
        published (call before reading the directory or exiting a driver
        that must observe the file)."""
        if self._ckptr is not None:
            self._ckptr.wait()

    def save_checkpoint(self, directory: str,
                        extra_meta: dict | None = None,
                        sharded: bool = False) -> None:
        """Snapshot params/opt-state/step (utils/checkpoint.py); all
        processes must call (whole-tree fetches are collectives).  Default
        format: one whole-tree npz, fetched synchronously with the
        serialization/IO overlapping the next train steps (async_write).
        ``sharded=True`` writes per-process shard files instead (no
        allgather, no full-tree host copy — utils ShardedCheckpointer).
        ``extra_meta`` rides along in the JSON meta — the CLI records the
        data-loader position here."""
        self._checkpointer(directory, sharded).save(
            {"params": self.params, "opt": self.opt_state}, self._step,
            meta=dict(extra_meta or {},
                      dp=self.cfg.dp, sp=self.cfg.sp, tp=self.cfg.tp,
                      pp=self.cfg.pp, interleave=self.cfg.interleave))

    def maybe_restore(self, directory: str) -> int:
        """Restore the latest checkpoint if present; returns the step to
        resume from (0 = fresh).  The format (whole-tree npz vs per-shard
        directory) is auto-detected, so resume works regardless of which
        saver wrote it.  The full checkpoint meta (including any
        ``extra_meta`` recorded at save) lands in ``self.restored_meta``.

        Per-shard checkpoints restore through ``load_resharded`` (round
        12): a layout that matches the save still moves only its own
        shard's bytes, and a DIFFERENT topology (the elastic-resize case
        — the gang shrank or grew since the save) is mapped saved-shard
        -> new-mesh per leaf without any host materializing a full
        array.  Values are bitwise-identical either way (test-pinned)."""
        from .utils.checkpoint import PyTreeCheckpointer, ShardedCheckpointer
        sh_list = ShardedCheckpointer(directory).list()
        npz_list = PyTreeCheckpointer(directory).list()
        if not sh_list and not npz_list:
            return 0
        # Mixed directories: resume from whichever format holds the NEWEST
        # step (a run that switched formats must not resurrect stale state).
        sharded = bool(sh_list) and (
            not npz_list or sh_list[-1][0] >= npz_list[-1][0])
        ckptr = self._checkpointer(directory, sharded)
        load = ckptr.load_resharded if sharded else ckptr.restore
        got = load({"params": self.params, "opt": self.opt_state})
        if got is None:
            return 0
        trees, meta = got
        self.params, self.opt_state = trees["params"], trees["opt"]
        self._step = meta["step"]
        self.restored_meta = meta
        return self._step

    def train_step(self, tokens: np.ndarray, targets: np.ndarray):
        if self.cfg.sync_every > 1:
            return self._train_step_windowed(tokens, targets)
        faults.maybe_delay(self._step)  # chaos: straggler (no-op unplanned)
        shd = NamedSharding(self.mesh, self._batch_spec)
        if jax.process_count() > 1:
            tokens = jax.make_array_from_process_local_data(shd, tokens)
            targets = jax.make_array_from_process_local_data(shd, targets)
        else:
            tokens = jax.device_put(tokens, shd)
            targets = jax.device_put(targets, shd)
        # (step_no, fault_arm) feed only the chaos taps — passed solely
        # when a plan is installed, so the clean path's compiled
        # signature (and any cached executable) is byte-identical to
        # pre-sentry builds; arm_window gives step-keyed faults their
        # one-shot semantics across sentry rollbacks
        extra = ((jnp.int32(self._step),
                  jnp.float32(faults.arm_window(self._step)))
                 if faults.step_plan() is not None else ())
        t0 = time.perf_counter()
        if self.sync_state is not None:
            # stateful (dcn_compress) signature: the EF residual is a
            # donated carry next to params/opt-state (round 11)
            (self.params, self.opt_state, self.sync_state, loss,
             self.last_ok, self.last_metrics) = self.step_fn(
                self.params, self.opt_state, self.sync_state, tokens,
                targets, *extra)
        else:
            (self.params, self.opt_state, loss, self.last_ok,
             self.last_metrics) = self.step_fn(
                self.params, self.opt_state, tokens, targets, *extra)
        self._step += 1
        faults.maybe_crash(self._step)  # chaos: injected process death
        tel = telemetry.active()
        if tel is not None:
            telemetry.emit_train_steps(
                tel, t0, self._step - 1, 1, loss, self.last_ok,
                self.last_metrics, span_name="lm_train_step")
            self._emit_cache_size(tel, self.step_fn)
        return loss

    def _train_step_windowed(self, tokens, targets):
        """One local step of the sync_every > 1 schedule, plus whatever
        window bookkeeping the step count makes due: the boundary
        exchange at multiples of H (or its launch when staleness > 0)
        and the deferred apply at kH + S.  Params hold the ANCHOR (last
        exchanged, replica-identical); ``self._delta`` carries the
        dcn-stacked local drift the optimizer accumulates between
        exchanges."""
        faults.maybe_delay(self._step)
        shd = NamedSharding(self.mesh, self._batch_spec)
        if jax.process_count() > 1:
            tokens = jax.make_array_from_process_local_data(shd, tokens)
            targets = jax.make_array_from_process_local_data(shd, targets)
        else:
            tokens = jax.device_put(tokens, shd)
            targets = jax.device_put(targets, shd)
        extra = ((jnp.int32(self._step),
                  jnp.float32(faults.arm_window(self._step)))
                 if faults.step_plan() is not None else ())
        h, s = self.cfg.sync_every, self.cfg.staleness
        t0 = time.perf_counter()
        if self._step % h == 0:
            self._window_t0 = t0
        (self._delta, self.opt_state, loss, self.last_ok,
         self.last_metrics) = self.step_fn(
            self.params, self._delta, self.opt_state, tokens, targets,
            *extra)
        self._step += 1
        boundary = self._step % h == 0
        if boundary:
            if s == 0:
                # round-22 boundary arg packing: [anchor, delta]
                # + [sync_state] if compressed + [m] if outer
                # + [mask] if per-slice (mask is never returned)
                per = self.cfg.sync_every_per_slice
                args = [self.params, self._delta]
                if self.sync_state is not None:
                    args.append(self.sync_state)
                if self._outer_m is not None:
                    args.append(self._outer_m)
                if per is not None:
                    args.append(jnp.asarray(
                        [1.0 if self._step % hi == 0 else 0.0
                         for hi in per], jnp.float32))
                out = list(self._exchange_fn(*args))
                self.params, self._delta = out[0], out[1]
                i = 2
                if self.sync_state is not None:
                    self.sync_state = out[i]
                    i += 1
                if self._outer_m is not None:
                    self._outer_m = out[i]
                    self._outer_steps += 1
            else:
                # staleness-hidden: enqueue the exchange now; the mean
                # delta lands at step kH + S while local compute runs
                if self.sync_state is not None:
                    d_avg, snap, self.sync_state = self._launch_fn(
                        self._delta, self.sync_state)
                else:
                    d_avg, snap = self._launch_fn(self._delta)
                self._pending = (d_avg, snap)
        elif self._pending is not None and self._step % h == s:
            d_avg, snap = self._pending
            self._pending = None
            if self._outer_m is not None:
                self.params, self._delta, self._outer_m = self._apply_fn(
                    self.params, self._delta, d_avg, snap, self._outer_m)
                self._outer_steps += 1
            else:
                self.params, self._delta = self._apply_fn(
                    self.params, self._delta, d_avg, snap)
        faults.maybe_crash(self._step)
        tel = telemetry.active()
        if tel is not None:
            telemetry.emit_train_steps(
                tel, t0, self._step - 1, 1, loss, self.last_ok,
                self.last_metrics, span_name="lm_train_step")
            if boundary and self._window_t0 is not None:
                telemetry.emit_sync_windows(
                    tel, self._window_t0, self._step - h, h, h,
                    wire_bytes=self._window_wire_bytes, phase="train")
                if (self.cfg.sync_every_per_slice is not None
                        or self._outer_m is not None):
                    telemetry.emit_window_plan(
                        tel, step=self._step - 1,
                        sync_every_per_slice=(
                            self.cfg.sync_every_per_slice),
                        outer_steps=(self._outer_steps
                                     if self._outer_m is not None
                                     else None), phase="train")
            self._emit_cache_size(tel, self.step_fn)
        return loss

    def train_steps(self, tokens: np.ndarray, targets: np.ndarray):
        """Run ``K = tokens.shape[0]`` steps over stacked (K, B, S) batches
        as one compiled ``lax.scan`` dispatch; returns the K per-step
        losses.  Identical trajectory to K ``train_step`` calls.

        When it helps (measured, BASELINE.md): per-step jax dispatch is
        ASYNC, so at ~30 ms/step the host already hides its enqueue cost
        and this scan is ~16% SLOWER (carry double-buffering of
        params/Adam state) — use ``train_step`` there.  The scan wins
        when steps are short relative to host work per dispatch (tiny
        models; multi-host ``make_array_from_process_local_data``
        assembly per step; a host that also runs data loading).  Not
        available with pp > 1 (its step carries pipeline-stacked
        params)."""
        if self.cfg.pp > 1 or self.cfg.pp_size > 0:
            raise ValueError("train_steps (K-step scan) supports the "
                             "(data, expert, seq, model) layout; with pp "
                             "or pp_size use train_step")
        if self.cfg.grad_accum > 1:
            raise ValueError("train_steps does not implement gradient "
                             "accumulation; use train_step with "
                             "grad_accum, or stack more steps instead")
        if self.cfg.dcn_compress is not None:
            raise ValueError("train_steps does not thread the stateful "
                             "sync-state (EF residual) carry; with "
                             "dcn_compress use train_step")
        if self.cfg.sync_every > 1:
            raise ValueError("train_steps does not thread the window "
                             "delta / per-slice optimizer carries; with "
                             "sync_every > 1 use train_step")
        if self._multi_fn is None:
            with monitor.compile_span(
                    "lm_multi_build",
                    key=("multi", self.cfg.grad_clip,
                         tuple(self.mesh.shape.items()))):
                self._multi_fn = make_lm_multi_step(self.cfg, self.mesh)
        shd = NamedSharding(self.mesh, P(None, *self._batch_spec))
        if jax.process_count() > 1:
            tokens = jax.make_array_from_process_local_data(shd, tokens)
            targets = jax.make_array_from_process_local_data(shd, targets)
        else:
            tokens = jax.device_put(tokens, shd)
            targets = jax.device_put(targets, shd)
        t0 = time.perf_counter()
        (self.params, self.opt_state, losses, self.last_ok,
         self.last_metrics) = self._multi_fn(
            self.params, self.opt_state, tokens, targets)
        self._step += tokens.shape[0]
        faults.maybe_crash(self._step, tokens.shape[0])
        tel = telemetry.active()
        if tel is not None:
            telemetry.emit_train_steps(
                tel, t0, self._step - tokens.shape[0], tokens.shape[0],
                losses, self.last_ok, self.last_metrics,
                span_name="lm_train_steps")
            self._emit_cache_size(tel, self._multi_fn)
        return losses
