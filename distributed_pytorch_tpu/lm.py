"""LM trainer: 3-D-parallel (data x sequence x tensor) language-model training.

The VGG trainer (train.py) reproduces the reference's DP-only world; this
trainer is the framework's scale-out path for transformer LMs, composing the
three parallelism axes over one ``Mesh(('data', 'seq', 'model'))``:

- **data**: batch sharded; gradient sync is the automatic cotangent ``psum``
  shard_map inserts for axis-invariant params (the 'ddp' strategy fused into
  autodiff).
- **seq**: activations sharded over the sequence; attention is the ring over
  ICI (parallel/context.py); params are seq-invariant so their cotangents
  psum over 'seq' as well.
- **model**: Megatron tensor parallelism — head/FFN-sharded weights
  (models/transformer.py shard_specs), two activation psums per layer.

Design: the *gradient* step runs inside ``shard_map`` (explicit collectives,
ring attention); the AdamW update runs as plain global ops in the same outer
``jit``, where GSPMD propagates each leaf's sharding — no hand-written specs
for optimizer state.  Loss is masked next-token cross-entropy; ``targets``
are pre-shifted host-side so sequence shards never need neighbor tokens.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .models import transformer as tfm
from .parallel.mesh import make_mesh

PyTree = Any

DATA, SEQ, MODEL = "data", "seq", "model"
IGNORE = -1  # target id excluded from the loss (padding)


@dataclass
class LMTrainConfig:
    model: tfm.TransformerConfig = field(
        default_factory=lambda: tfm.PRESETS["LM-tiny"])
    lr: float = 3e-4
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    compute_dtype: str | None = "bfloat16"
    seed: int = 1
    # parallel degrees; dp * sp * tp must equal the mesh size
    dp: int = 1
    sp: int = 1
    tp: int = 1


def make_lm_mesh(cfg: LMTrainConfig, devices=None) -> Mesh:
    return make_mesh(cfg.dp * cfg.sp * cfg.tp,
                     axis_names=(DATA, SEQ, MODEL),
                     axis_shape=(cfg.dp, cfg.sp, cfg.tp),
                     devices=devices)


def make_optimizer(cfg: LMTrainConfig) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        optax.adamw(cfg.lr, b1=cfg.b1, b2=cfg.b2,
                    weight_decay=cfg.weight_decay),
    )


def masked_ce(logits: jax.Array, targets: jax.Array):
    """(sum of CE over non-ignored tokens, count) — caller reduces/divides."""
    logits = logits.astype(jnp.float32)
    mask = targets != IGNORE
    safe = jnp.where(mask, targets, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    ce = jnp.where(mask, logz - true_logit, 0.0)
    return jnp.sum(ce), jnp.sum(mask)


def make_lm_train_step(cfg: LMTrainConfig, mesh: Mesh):
    """Compiled step: (params, opt_state, tokens, targets) ->
    (params, opt_state, loss).  tokens/targets are (global_batch, global_seq)
    int32, sharded (data, seq)."""
    tx = make_optimizer(cfg)
    dtype = jnp.dtype(cfg.compute_dtype) if cfg.compute_dtype else None
    # tp psums always run (free over a size-1 'model' axis) — they also carry
    # the vma bookkeeping that makes the loss provably replicated.  The ring
    # only replaces local flash attention when the seq axis is actually cut.
    tp_axis = MODEL
    seq_axis = SEQ if cfg.sp > 1 else None
    specs = tfm.shard_specs(cfg.model, tp_axis=MODEL)

    def local_loss(params, tokens, targets):
        s_local = tokens.shape[1]
        pos0 = jax.lax.axis_index(SEQ) * s_local
        logits = tfm.apply(params, tokens, cfg=cfg.model, dtype=dtype,
                           seq_axis=seq_axis, tp_axis=tp_axis, pos0=pos0)
        ce_sum, n = masked_ce(logits, targets)
        # Global mean over every shard's tokens (loss is axis-invariant;
        # 'model' shards compute identical values, no reduction needed there).
        ce_sum = jax.lax.psum(ce_sum, (DATA, SEQ))
        n = jax.lax.psum(n, (DATA, SEQ))
        return ce_sum / jnp.maximum(n, 1)

    grad_step = shard_map(
        jax.value_and_grad(local_loss),
        mesh=mesh,
        in_specs=(specs, P(DATA, SEQ), P(DATA, SEQ)),
        out_specs=(P(), specs),
        # check_vma stays ON: the automatic psum of cotangents for
        # axis-invariant params (the fused DP/SP gradient sync) depends on it.
    )

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens, targets):
        loss, grads = grad_step(params, tokens, targets)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


class LMTrainer:
    """Owns (params, opt_state) laid out over the (data, seq, model) mesh."""

    def __init__(self, cfg: LMTrainConfig, mesh: Mesh | None = None):
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_lm_mesh(cfg)
        assert self.mesh.devices.size == cfg.dp * cfg.sp * cfg.tp, (
            f"mesh has {self.mesh.devices.size} devices, config wants "
            f"dp*sp*tp = {cfg.dp * cfg.sp * cfg.tp}")

        params = tfm.init(jax.random.key(cfg.seed), cfg.model)
        specs = tfm.shard_specs(cfg.model, tp_axis=MODEL)
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            params, specs)
        tx = make_optimizer(cfg)
        # zeros_like/elementwise init inherits each param's sharding
        self.opt_state = jax.jit(tx.init)(params)
        self.params = params
        self.step_fn = make_lm_train_step(cfg, self.mesh)
        self._step = 0

    def train_step(self, tokens: np.ndarray, targets: np.ndarray):
        shd = NamedSharding(self.mesh, P(DATA, SEQ))
        if jax.process_count() > 1:
            tokens = jax.make_array_from_process_local_data(shd, tokens)
            targets = jax.make_array_from_process_local_data(shd, targets)
        else:
            tokens = jax.device_put(tokens, shd)
            targets = jax.device_put(targets, shd)
        self.params, self.opt_state, loss = self.step_fn(
            self.params, self.opt_state, tokens, targets)
        self._step += 1
        return loss
