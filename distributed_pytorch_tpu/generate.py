"""Autoregressive decoding with a KV cache for the transformer LM.

Inference counterpart of lm.py: one compiled ``lax.scan`` drives prefill and
sampling (no per-token dispatch), with per-layer K/V caches updated in place
via ``dynamic_update_slice`` — static shapes throughout, so the whole decode
is a single XLA program.

Supports greedy (temperature=0) and temperature/top-k sampling.  MoE layers
decode with a dense-evaluation trick (every expert runs on the B decode
tokens, the router's one-hot selects) — exact w.r.t. training semantics
minus capacity drops, and cheap at decode batch sizes.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from .models import transformer as tfm
from .ops.attention import NEG_INF, attention_reference

PyTree = Any


def init_cache(cfg: tfm.TransformerConfig, batch: int, max_len: int,
               dtype=jnp.float32) -> PyTree:
    """Zeroed per-layer K/V buffers, (B, kv_heads, max_len, head_dim) —
    GQA models cache only the kv heads."""
    shape = (batch, cfg.kv_heads, max_len, cfg.head_dim)
    return {
        f"layer{i}": {"k": jnp.zeros(shape, dtype),
                      "v": jnp.zeros(shape, dtype)}
        for i in range(cfg.n_layers)
    }


def _moe_dense(lp: PyTree, h: jax.Array, cfg: tfm.TransformerConfig):
    """Capacity-free MoE for decode: run all experts, top-k one-hot combine
    (matches training routing — Switch gates for top_k=1, pair-normalized
    gates for top_k=2)."""
    b, s, d = h.shape
    hf = h.reshape(b * s, d)
    probs = jax.nn.softmax(
        hf.astype(jnp.float32) @ lp["moe"]["router"].astype(jnp.float32), -1)
    k = cfg.moe_top_k
    top_probs, top_idx = jax.lax.top_k(probs, k)
    if k > 1:
        top_probs = top_probs / jnp.sum(top_probs, -1, keepdims=True)
    weights = jnp.einsum(
        "tk,tke->te", top_probs,
        jax.nn.one_hot(top_idx, cfg.n_experts, dtype=jnp.float32))
    g = jax.nn.silu(jnp.einsum("td,edf->tef", hf,
                               lp["moe"]["w_gate"].astype(hf.dtype)))
    u = jnp.einsum("td,edf->tef", hf, lp["moe"]["w_up"].astype(hf.dtype))
    y = jnp.einsum("tef,efd->ted", g * u,
                   lp["moe"]["w_down"].astype(hf.dtype))
    out = jnp.einsum("te,ted->td", weights.astype(hf.dtype), y)
    return out.reshape(b, s, d)


def decode_step(params: PyTree, cache: PyTree, token: jax.Array,
                pos: jax.Array, *, cfg: tfm.TransformerConfig,
                dtype=None):
    """Process one token per sequence: (B,) ids at position ``pos`` ->
    ((B, vocab) logits, updated cache)."""
    x = params["embed"][token][:, None, :]  # (B, 1, D)
    if dtype is not None:
        x = x.astype(dtype)
    max_len = next(iter(cache.values()))["k"].shape[2]
    # bias masking cache slots beyond the current position
    slot = jax.lax.broadcasted_iota(jnp.int32, (1, max_len), 1)
    bias = jnp.where(slot <= pos, 0.0, NEG_INF)[None, None]  # (1,1,1,L)

    for i in range(cfg.n_layers):
        lp = params[f"layer{i}"]
        c = cache[f"layer{i}"]
        h = tfm.rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("bsd,dhk->bhsk", h, lp["wq"].astype(h.dtype))
        k = jnp.einsum("bsd,dhk->bhsk", h, lp["wk"].astype(h.dtype))
        v = jnp.einsum("bsd,dhk->bhsk", h, lp["wv"].astype(h.dtype))
        posv = pos[None] if pos.ndim == 0 else pos
        q = tfm.rotary(q, posv, cfg.rope_theta)
        k = tfm.rotary(k, posv, cfg.rope_theta)
        ck = lax.dynamic_update_slice(
            c["k"], k.astype(c["k"].dtype), (0, 0, pos, 0))
        cv = lax.dynamic_update_slice(
            c["v"], v.astype(c["v"].dtype), (0, 0, pos, 0))
        cache[f"layer{i}"] = {"k": ck, "v": cv}
        ka, va = ck.astype(q.dtype), cv.astype(q.dtype)
        if cfg.kv_heads != cfg.n_heads:
            rep = cfg.n_heads // cfg.kv_heads
            ka = jnp.repeat(ka, rep, axis=1)
            va = jnp.repeat(va, rep, axis=1)
        o = attention_reference(q, ka, va, bias=bias)
        x = x + jnp.einsum("bhsk,hkd->bsd", o, lp["wo"].astype(o.dtype))
        h = tfm.rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        if cfg.is_moe_layer(i):
            x = x + _moe_dense(lp, h, cfg)
        else:
            gate = jax.nn.silu(h @ lp["w_gate"].astype(h.dtype))
            up = h @ lp["w_up"].astype(h.dtype)
            x = x + (gate * up) @ lp["w_down"].astype(h.dtype)

    x = tfm.rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x[:, 0].astype(jnp.float32)
              @ params["embed"].T.astype(jnp.float32))
    return logits, cache


def _sample(key, logits, temperature: float, top_k: int | None):
    if temperature == 0.0:
        return jnp.argmax(logits, -1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        kth = jnp.sort(logits, -1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    return jax.random.categorical(key, logits).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg", "max_new", "temperature", "top_k"))
def generate(
    params: PyTree,
    prompt: jax.Array,       # (B, S0) int32
    key: jax.Array,
    *,
    cfg: tfm.TransformerConfig,
    max_new: int,
    temperature: float = 1.0,
    top_k: int | None = None,
) -> jax.Array:
    """Sample ``max_new`` tokens after ``prompt``; returns (B, S0+max_new).

    One jitted program: a prefill scan feeds the prompt through the cache,
    then a sampling scan emits tokens (each step's sample feeds the next).
    """
    b, s0 = prompt.shape
    cache = init_cache(cfg, b, s0 + max_new)

    step = partial(decode_step, cfg=cfg)

    # Prefill: ONE batched causal forward over the whole prompt (matmul-bound
    # MXU work), seeding each layer's cache from the block's rotary-embedded
    # K/V — not a per-token scan of tiny (B, 1, D) ops.
    x = params["embed"][prompt]
    pos = jnp.arange(s0)
    for i in range(cfg.n_layers):
        x, _, (k, v) = tfm.block(
            params[f"layer{i}"], x, cfg=cfg, is_moe=cfg.is_moe_layer(i),
            pos=pos, attn_impl="reference", return_kv=True)
        c = cache[f"layer{i}"]
        cache[f"layer{i}"] = {
            "k": lax.dynamic_update_slice(c["k"], k.astype(c["k"].dtype),
                                          (0, 0, 0, 0)),
            "v": lax.dynamic_update_slice(c["v"], v.astype(c["v"].dtype),
                                          (0, 0, 0, 0)),
        }
    x = tfm.rms_norm(x, params["final_norm"], cfg.norm_eps)
    last_logits = (x[:, -1].astype(jnp.float32)
                   @ params["embed"].T.astype(jnp.float32))

    def sample_step(carry, t):
        cache, logits, key = carry
        key, sub = jax.random.split(key)
        tok = _sample(sub, logits, temperature, top_k)
        logits, cache = step(params, cache, tok, s0 + t)
        return (cache, logits, key), tok

    (_, _, _), tokens = lax.scan(
        sample_step, (cache, last_logits, key), jnp.arange(max_new))
    return jnp.concatenate([prompt, tokens.T], axis=1)
